"""Benchmark harness: column-iters/sec/chip on the flagship config.

The north-star metric (BASELINE.json): a "column-iter" is one t-step update
of all n*L level vectors of one image; we measure the jitted, scan-fused
forward at the ImageNet-224 / L=6 / d=512 config (BASELINE config 4) in
bfloat16 on one chip, with the Pallas fused grouped-MLP kernel on the hot
path (the TPU production configuration).

The reference publishes NO numbers (BASELINE.json "published": {}), so the
baseline this project establishes is the >=70% MFU target from the driver
metadata: vs_baseline reports measured-MFU / 0.70.

Timing methodology (the tunneled chip adds a large FIXED dispatch cost that
is not device throughput):
  * K whole forwards run inside a single compiled fori_loop; the loop carry
    (a tiny data-dependent scalar added to the next input — NOT a
    multiply-by-zero that the compiler could fold away) serializes
    iterations so no dedup/overlap/hoisting can fake speedups;
  * sync by fetching the device-side-reduced scalar (block_until_ready
    returns early on tunneled platforms);
  * per-forward time = (t_chain - t_rtt) / K with ONE long chain (seconds
    of device work) and t_rtt measured by fetching a trivial jitted scalar
    — see glom_tpu/utils/timing.py for why the earlier two-chain slope was
    rejected (it over-credited past the physical matmul-bound floor);
  * min over repeats: jitter and throttling only ever slow things down.

Prints TWO JSON lines — the forward-only line first, then the full
train-step line (fwd+bwd+adam, from bench_train.py) LAST, because the
BASELINE >=70% MFU bar is a *training* target and the driver records the
tail line:
  {"metric": "... bf16 fwd ...", "value": N, ...}
  {"metric": "train_step ...", "value": N, "unit": ..., "vs_baseline": N}
"""

import jax
import jax.numpy as jnp

from glom_tpu.models.core import glom_forward, init_glom
from glom_tpu.telemetry.sinks import emit
from glom_tpu.utils.config import GlomConfig
from glom_tpu.utils.metrics import detect_chip, mfu
from glom_tpu.utils.timing import best_fetch_time, measure_rtt


def main():
    chip = detect_chip()
    on_tpu = chip != "cpu"
    if on_tpu:
        cfg = GlomConfig(dim=512, levels=6, image_size=224, patch_size=14)
        batch, iters, repeats = 8, 12, 6
        # ~7 ms/forward: k=192 gives ~1.4 s of device work per call, so the
        # ~100 ms tunnel RTT (measured and subtracted) is ~7% of the total
        # and its jitter bounds the error at ~2%.
        k_chain = 192
    else:  # CPU fallback so the harness stays runnable anywhere
        cfg = GlomConfig(dim=128, levels=4, image_size=32, patch_size=4)
        batch, iters, repeats = 4, 8, 2
        k_chain = 3
        emit(
            {
                "note": "TPU backend unavailable; measuring the labelled "
                "cpu-fallback config instead of recording a dead zero"
            },
            kind="note",
        )

    params = init_glom(jax.random.PRNGKey(0), cfg)
    img = jax.random.normal(
        jax.random.PRNGKey(1), (batch, 3, cfg.image_size, cfg.image_size), jnp.float32
    )

    def make_chain(k):
        def multi(p, x):
            def body(_, acc):
                # acc is a genuinely data-dependent ~1e-6-scale scalar: it
                # serializes iterations without perturbing the numerics, and
                # the compiler cannot fold it away (unlike `acc * 0.0`).
                out = glom_forward(
                    p, x + acc, cfg, iters=iters,
                    compute_dtype=jnp.bfloat16, use_pallas=on_tpu,
                )
                return jnp.sum(out).astype(jnp.float32) * 1e-9
            return jax.lax.fori_loop(0, k, body, jnp.float32(0.0))
        return jax.jit(multi)

    t_rtt = measure_rtt(img, repeats=repeats)
    t_chain = best_fetch_time(make_chain(k_chain), params, img, repeats=repeats)
    per_forward = (t_chain - t_rtt) / k_chain
    if per_forward <= 0:
        raise RuntimeError(
            f"degenerate timing: t_chain={t_chain:.4f}s t_rtt={t_rtt:.4f}s"
        )

    column_iters_per_sec = batch * iters / per_forward
    measured_mfu = mfu(cfg, column_iters_per_sec, chip=chip)
    emit(
        {
            "metric": (
                f"column_iters_per_sec_per_chip (ImageNet-224, L=6, d=512, "
                f"bf16 fwd, pallas, {chip})"
                if on_tpu
                else "column_iters_per_sec_per_chip (cpu-fallback cfg)"
            ),
            "value": round(column_iters_per_sec, 2),
            "unit": "column-iters/s/chip",
            "vs_baseline": round(measured_mfu / 0.70, 4),
        }
    )


if __name__ == "__main__":
    # Never record a dead zero for a measurable host. Round 4's
    # BENCH_r04.json recorded rc=1 with a raw traceback tail; round 5's
    # fail-fast guard then recorded value 0.0 — a parseable line, but an
    # empty bench trajectory that downstream tooling ingested as a real
    # zero. bench_bootstrap (telemetry/sinks.py) probes through the
    # watchdog (throwaway subprocess — a wedged plugin hangs in-process),
    # downgrades to the labelled CPU fallback when the default platform is
    # down, and on total failure emits ONE schema-v2 "error" record
    # (value null + the outage timeline) that the compare gate treats as
    # MISSING, not zero.
    import argparse

    from glom_tpu.telemetry.sinks import bench_bootstrap, emit as _emit

    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--trace-dir", default=None, metavar="DIR",
        help="capture an XProf trace of the measured chains into DIR "
        "(whole-measurement window; the chained fori_loop has no per-step "
        "boundary to cut at)",
    )
    args = ap.parse_args()
    if not bench_bootstrap("train_step column_iters_per_sec_per_chip"):
        raise SystemExit(0)

    def _run():
        main()
        # The train-step metric is the one BASELINE.md names (>=70% MFU is
        # a TRAINING bar); print it last so the driver's tail-parse
        # records it.
        from bench_train import bench_train_step

        bench_train_step()

    if args.trace_dir:
        from glom_tpu.tracing.capture import trace

        with trace(args.trace_dir):
            _run()
        _emit(
            {"note": "xla-trace captured", "trace_dir": args.trace_dir},
            kind="note",
        )
    else:
        _run()
