"""Benchmark harness: column-iters/sec/chip on the flagship config.

The north-star metric (BASELINE.json): a "column-iter" is one t-step update
of all n*L level vectors of one image; we measure the jitted, scan-fused
forward at the ImageNet-224 / L=6 / d=512 config (BASELINE config 4) in
bfloat16 on one chip, with the Pallas fused grouped-MLP kernel on the hot
path (the TPU production configuration).

The reference publishes NO numbers (BASELINE.json "published": {}), so the
baseline this project establishes is the >=70% MFU target from the driver
metadata: vs_baseline reports measured-MFU / 0.70.

Timing methodology (the tunneled chip adds a large FIXED dispatch cost that
is not device throughput):
  * K whole forwards run inside a single compiled fori_loop; the loop carry
    (a scalar folded into the next input) serializes iterations so no
    dedup/overlap can fake speedups;
  * sync by fetching the device-side-reduced scalar (block_until_ready
    returns early on tunneled platforms);
  * per-forward time is the SLOPE between a short and a long chain:
    (t_long - t_short) / (k_long - k_short). The fixed host-dispatch
    overhead (~100 ms through the tunnel, ~1/3 of a short run's wall time)
    cancels exactly; what remains is steady-state device throughput;
  * min over repeats: jitter and throttling only ever slow things down.

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
"""

import json
import time

import jax
import jax.numpy as jnp

from glom_tpu.models.core import glom_forward, init_glom
from glom_tpu.utils.config import GlomConfig
from glom_tpu.utils.metrics import detect_chip, mfu


def main():
    chip = detect_chip()
    on_tpu = chip != "cpu"
    if on_tpu:
        cfg = GlomConfig(dim=512, levels=6, image_size=224, patch_size=14)
        batch, iters, repeats = 8, 12, 6
        # Chains sized so even the SHORT one carries ~2x the ~100 ms tunnel
        # RTT of device work — an RTT-dominated short chain makes the slope
        # hostage to dispatch jitter (observed 20% spread at k_short=8).
        k_short, k_long = 32, 96
    else:  # CPU fallback so the harness stays runnable anywhere
        cfg = GlomConfig(dim=128, levels=4, image_size=32, patch_size=4)
        batch, iters, repeats = 4, 8, 2
        k_short, k_long = 1, 3

    params = init_glom(jax.random.PRNGKey(0), cfg)
    img = jax.random.normal(
        jax.random.PRNGKey(1), (batch, 3, cfg.image_size, cfg.image_size), jnp.float32
    )

    def make_chain(k):
        def multi(p, x):
            def body(_, acc):
                out = glom_forward(
                    p, x + acc * 0.0, cfg, iters=iters,
                    compute_dtype=jnp.bfloat16, use_pallas=on_tpu,
                )
                return jnp.sum(out).astype(jnp.float32) * 1e-9
            return jax.lax.fori_loop(0, k, body, jnp.float32(0.0))
        return jax.jit(multi)

    def best_time(fn):
        warm = float(fn(params, img))  # compile + warm
        if not jnp.isfinite(warm):
            raise RuntimeError(f"non-finite benchmark output: {warm}")
        times = []
        for _ in range(repeats):
            t0 = time.perf_counter()
            out = float(fn(params, img))
            times.append(time.perf_counter() - t0)
            if not jnp.isfinite(out):
                raise RuntimeError(f"non-finite benchmark output: {out}")
        return min(times)

    t_short = best_time(make_chain(k_short))
    t_long = best_time(make_chain(k_long))
    per_forward = (t_long - t_short) / (k_long - k_short)
    if per_forward <= 0:
        raise RuntimeError(
            f"degenerate slope timing: t_short={t_short:.4f}s t_long={t_long:.4f}s"
        )

    column_iters_per_sec = batch * iters / per_forward
    measured_mfu = mfu(cfg, column_iters_per_sec, chip=chip)
    print(
        json.dumps(
            {
                "metric": (
                    f"column_iters_per_sec_per_chip (ImageNet-224, L=6, d=512, "
                    f"bf16 fwd, pallas, {chip})"
                    if on_tpu
                    else "column_iters_per_sec_per_chip (cpu fallback cfg)"
                ),
                "value": round(column_iters_per_sec, 2),
                "unit": "column-iters/s/chip",
                "vs_baseline": round(measured_mfu / 0.70, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
