"""Benchmark harness: column-iters/sec/chip on the flagship config.

The north-star metric (BASELINE.json): a "column-iter" is one t-step update
of all n*L level vectors of one image; we measure the jitted, scan-fused
forward at the ImageNet-224 / L=6 / d=512 config (BASELINE config 4) in
bfloat16 on one chip.

The reference publishes NO numbers (BASELINE.json "published": {}), so the
baseline this project establishes is the >=70% MFU target from the driver
metadata: vs_baseline reports measured-MFU / 0.70.

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
"""

import json
import time

import jax
import jax.numpy as jnp

from glom_tpu.models.core import glom_forward, init_glom
from glom_tpu.utils.config import GlomConfig
from glom_tpu.utils.metrics import flops_per_column_iter, mfu


def main():
    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    if on_tpu:
        cfg = GlomConfig(dim=512, levels=6, image_size=224, patch_size=14)
        batch, iters, repeats, chain = 16, 12, 4, 8
        chip = "v5e"
    else:  # CPU fallback so the harness stays runnable anywhere
        cfg = GlomConfig(dim=128, levels=4, image_size=32, patch_size=4)
        batch, iters, repeats, chain = 4, 8, 2, 2
        chip = "cpu"

    params = init_glom(jax.random.PRNGKey(0), cfg)
    img = jax.random.normal(jax.random.PRNGKey(1), (batch, 3, cfg.image_size, cfg.image_size), jnp.float32)

    # Timing methodology for a noisy, tunneled device:
    #   * ONE dispatch per measurement — K whole forwards run inside a
    #     single compiled fori_loop, so per-call dispatch overhead and host
    #     round-trip are amortized over K*T column updates;
    #   * the loop carry (a scalar folded into the next input) serializes
    #     iterations, preventing any dedup/overlap from faking speedups;
    #   * sync by fetching the device-side-reduced scalar (block_until_ready
    #     can return before execution completes on tunneled platforms);
    #   * min over repeats: jitter and throttling only ever slow things down.
    def multi(p, x):
        def body(_, acc):
            out = glom_forward(
                p, x + acc * 0.0, cfg, iters=iters, compute_dtype=jnp.bfloat16
            )
            return jnp.sum(out).astype(jnp.float32) * 1e-9
        return jax.lax.fori_loop(0, chain, body, jnp.float32(0.0))

    bench_fn = jax.jit(multi)
    warm = float(bench_fn(params, img))  # compile + warm
    if not jnp.isfinite(warm):
        raise RuntimeError(f"non-finite benchmark output: {warm}")

    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = float(bench_fn(params, img))
        times.append(time.perf_counter() - t0)
        if not jnp.isfinite(out):
            raise RuntimeError(f"non-finite benchmark output: {out}")
    dt = min(times)

    column_iters_per_sec = batch * chain * iters / dt
    measured_mfu = mfu(cfg, column_iters_per_sec, chip=chip)
    print(
        json.dumps(
            {
                "metric": "column_iters_per_sec_per_chip (ImageNet-224, L=6, d=512, bf16 fwd)"
                if on_tpu
                else "column_iters_per_sec_per_chip (cpu fallback cfg)",
                "value": round(column_iters_per_sec, 2),
                "unit": "column-iters/s/chip",
                "vs_baseline": round(measured_mfu / 0.70, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
