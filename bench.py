"""Benchmark harness: column-iters/sec/chip on the flagship config.

The north-star metric (BASELINE.json): a "column-iter" is one t-step update
of all n*L level vectors of one image; we measure the jitted, scan-fused
forward at the ImageNet-224 / L=6 / d=512 config (BASELINE config 4) in
bfloat16 on one chip.

The reference publishes NO numbers (BASELINE.json "published": {}), so the
baseline this project establishes is the >=70% MFU target from the driver
metadata: vs_baseline reports measured-MFU / 0.70.

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
"""

import json
import time

import jax
import jax.numpy as jnp

from glom_tpu.models.core import glom_forward, init_glom
from glom_tpu.utils.config import GlomConfig
from glom_tpu.utils.metrics import flops_per_column_iter, mfu


def main():
    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    if on_tpu:
        cfg = GlomConfig(dim=512, levels=6, image_size=224, patch_size=14)
        batch, iters, repeats, chain = 16, 12, 3, 4
        chip = "v5e"
    else:  # CPU fallback so the harness stays runnable anywhere
        cfg = GlomConfig(dim=128, levels=4, image_size=32, patch_size=4)
        batch, iters, repeats, chain = 4, 8, 2, 2
        chip = "cpu"

    params = init_glom(jax.random.PRNGKey(0), cfg)
    img = jax.random.normal(jax.random.PRNGKey(1), (batch, 3, cfg.image_size, cfg.image_size), jnp.float32)

    # Forward returning a device-side scalar: timing syncs by fetching ONE
    # float. (block_until_ready is unreliable on tunneled platforms — it can
    # return before execution completes; a host fetch cannot.)
    fwd = jax.jit(
        lambda p, x: jnp.sum(
            glom_forward(p, x, cfg, iters=iters, compute_dtype=jnp.bfloat16)
        )
    )
    float(fwd(params, img))  # compile + warm

    # Round-trip latency floor: time fetching an already-computed scalar.
    tiny = jax.jit(lambda x: jnp.sum(x))(img)
    t0 = time.perf_counter()
    for _ in range(3):
        float(tiny)
    rtt = (time.perf_counter() - t0) / 3

    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        outs = [fwd(params, img) for _ in range(chain)]  # async dispatch
        acc = sum(float(o) for o in outs)  # fetches overlap later computes
        assert jnp.isfinite(acc)
        times.append((time.perf_counter() - t0 - rtt) / chain)
    dt = max(min(times), 1e-9)

    column_iters_per_sec = batch * iters / dt
    measured_mfu = mfu(cfg, column_iters_per_sec, chip=chip)
    print(
        json.dumps(
            {
                "metric": "column_iters_per_sec_per_chip (ImageNet-224, L=6, d=512, bf16 fwd)"
                if on_tpu
                else "column_iters_per_sec_per_chip (cpu fallback cfg)",
                "value": round(column_iters_per_sec, 2),
                "unit": "column-iters/s/chip",
                "vs_baseline": round(measured_mfu / 0.70, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
