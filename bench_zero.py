"""ZeRO A/B bench: step time of the sharded weight update vs the replicated
baseline on a data-parallel mesh, plus the quantized-reduce arm.

Four arms, one JSON line each (the queue's pricing rows):
  zero0        — replicated optimizer state, monolithic grad allreduce
  zero1        — reduce-scatter grads -> owned-shard update -> param all-gather
  zero2_accum  — stage 2 with grad_accum=2 (the sharded-accumulator case; it
                 differs from stage 1 only under accumulation)
  zero1_quant  — stage 1 with the EQuARX-style int8 block-scaled reduce
                 emulation (prices the quant/dequant compute and stamps the
                 in-graph quantization-error probe; the wire saving itself
                 needs the real XLA collective hook). Stage 1, not 0: the
                 manual path's quantization hook lives on the explicit
                 reduce-scatter — stage 0's transpose-psum has no hook and
                 resolves the flag off (loudly), so a stage-0 quant arm
                 would measure nothing.

Every arm runs telemetry_level="scalars", so each row carries the MEASURED
collective wire bytes of the schedule it ran next to the modeled ones, and
the measured-vs-modeled drift (telemetry/counters.py).

Every line carries the static observability record the trainers stamp
(zero_stage, per-replica live bytes, per-step comm-volume model), so the
memory/comm claims in docs/PARALLELISM.md are re-derived on every run.

Topology: dp = all visible devices when >= 2 (on TPU this is the arm that
prices the A/B for real — the queue entry exists for the day the tunnel
exposes a slice, today it exposes ONE chip); otherwise a virtual 8-device
CPU mesh, labelled "(cpu-fallback)" — real collectives, meaningless absolute
times, but the RATIO and the analytics are load-bearing and CI asserts them.

Timing: whole Python-loop steps with a terminal block_until_ready, min over
repeats. Both arms pay identical per-step dispatch, so the A/B ratio is
honest even through the tunnel's fixed RTT (unlike the absolute numbers,
which bench.py's chained-loop methodology owns).
"""

import os
import time


def _bootstrap_platform() -> None:
    """Pick the platform BEFORE any in-process backend init: probe via the
    telemetry watchdog's throwaway subprocess (a wedged TPU plugin hangs
    init — round-4/5 axon outage), register it globally so every arm's
    record stamps the backend state, and when fewer than 2 devices answer,
    force a virtual 8-device CPU mesh so the A/B always has replicas to
    shard across."""
    from glom_tpu.telemetry.watchdog import BackendWatchdog, set_global_watchdog
    from glom_tpu.utils.metrics import apply_env_platform

    wd = BackendWatchdog(probe_timeout=120.0)
    set_global_watchdog(wd)
    wd.probe_once()
    n = wd.record()["backend_devices"]
    if n is None or n < 2:
        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = " ".join(
            f
            for f in os.environ.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f
        )
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count=8".strip()
        )
        # Re-probe the forced-CPU platform: the watchdog stays globally
        # registered, and a stale 'down' from the wedged-TPU probe would
        # stamp every live cpu-fallback pricing row as backend-down.
        wd.probe_once()
    apply_env_platform()


def _time_steps(trainer, batch, k: int, repeats: int) -> float:
    import jax

    trainer.step_fast(batch)  # compile + first-touch
    jax.block_until_ready(trainer.state)
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(k):
            trainer.step_fast(batch)
        jax.block_until_ready(trainer.state)
        best = min(best, (time.perf_counter() - t0) / k)
    return best


def main() -> None:
    _bootstrap_platform()
    import dataclasses

    import jax

    from glom_tpu.data import gaussian_dataset
    from glom_tpu.parallel import DistributedTrainer
    from glom_tpu.telemetry.sinks import emit
    from glom_tpu.utils.config import GlomConfig, MeshConfig, TrainConfig
    from glom_tpu.utils.metrics import detect_chip, mfu

    chip = detect_chip()
    on_tpu = chip != "cpu"
    dp = len(jax.devices())
    if on_tpu:
        # Flagship BASELINE config 4 at its declared dp topology.
        # telemetry_level="scalars" on every arm: the records must carry
        # the MEASURED collective bytes + model drift (the uniform in-graph
        # cost rides all four arms identically, so the A/B ratio is clean).
        cfg = GlomConfig(dim=512, levels=6, image_size=224, patch_size=14)
        per_replica_batch, k, repeats = 4, 8, 3
        base = TrainConfig(
            batch_size=per_replica_batch * dp,
            learning_rate=1e-3,
            compute_dtype="bfloat16",
            use_pallas=True,  # manual shard_map path: explicit psum_scatter
            telemetry_level="scalars",
        )
    else:
        cfg = GlomConfig(dim=64, levels=4, image_size=16, patch_size=4)
        per_replica_batch, k, repeats = 2, 4, 2
        base = TrainConfig(
            batch_size=per_replica_batch * dp, learning_rate=1e-3,
            use_pallas=True, telemetry_level="scalars",
        )
        emit(
            {
                "note": "TPU slice unavailable; ZeRO A/B on the virtual "
                f"{dp}-device CPU mesh (cpu-fallback) — ratios and "
                "live-bytes/comm analytics are the signal, not "
                "absolute times"
            },
            kind="note",
        )

    arms = [
        ("zero0", dict(zero_stage=0)),
        ("zero1", dict(zero_stage=1)),
        ("zero2_accum", dict(zero_stage=2, grad_accum=2)),
        ("zero1_quant", dict(zero_stage=1, quantized_reduce=True)),
    ]
    times = {}
    for name, overrides in arms:
        tcfg = dataclasses.replace(base, **overrides)
        trainer = DistributedTrainer(cfg, tcfg, MeshConfig(data=dp))
        batch = next(gaussian_dataset(tcfg.batch_size, cfg.image_size, seed=0))
        per_step = _time_steps(trainer, batch, k, repeats)
        times[name] = per_step
        iters = cfg.default_iters
        col_per_sec = tcfg.batch_size * iters / per_step / dp
        label = f"dp={dp}, {chip}" if on_tpu else f"dp={dp}, cpu-fallback"
        emit(
            {
                "metric": f"zero_ab {name} train_step "
                f"column_iters_per_sec_per_chip ({label})",
                "value": round(col_per_sec, 2),
                "unit": "column-iters/s/chip",
                "step_time_s": round(per_step, 5),
                "vs_zero0": round(times["zero0"] / per_step, 4),
                "mfu": round(
                    mfu(cfg, col_per_sec, chip=chip, backward=True), 4
                ),
                **trainer._static_record,
            }
        )


if __name__ == "__main__":
    main()
