"""TPU-side validation: the checks the CPU test suite must skip.

The pytest suite (tests/) runs on a forced-CPU virtual mesh, where bf16
dots don't exist and Pallas runs in interpret mode — so bf16 kernel
parity and real-Mosaic compilation are asserted here, on hardware, and
the outcome is committed as `results/tpu_validation.jsonl` (VERDICT r1
weak #7: "a TPU-run record of the bf16 test isn't in the repo").

Run: `python tpu_validate.py` on a TPU host. Exits nonzero on any
failure; appends one JSON record per check plus a summary line.
"""

import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


RESULTS = []


class _Skipped(Exception):
    """Raise inside a check to record it as passed-but-skipped (e.g. a
    multi-device check on a 1-chip environment)."""


def check(name):
    def deco(fn):
        def run():
            t0 = time.time()
            try:
                fn()
                rec = {"check": name, "ok": True}
            except _Skipped as e:
                rec = {"check": name, "ok": True, "skipped": True,
                       "reason": str(e)}
            except Exception as e:  # noqa: BLE001 - record and continue
                rec = {"check": name, "ok": False, "error": f"{type(e).__name__}: {e}"[:300]}
            rec["seconds"] = round(time.time() - t0, 1)
            RESULTS.append(rec)
            print(json.dumps(rec), flush=True)
        return run
    return deco


def _bf16_tree(t):
    return jax.tree_util.tree_map(lambda x: x.astype(jnp.bfloat16), t)


@check("grouped_ffw_bf16_forward_parity")
def check_ffw_fwd():
    from glom_tpu.kernels import fused_grouped_ffw
    from glom_tpu.ops.ffw import grouped_ffw, init_grouped_ffw

    params = _bf16_tree(init_grouped_ffw(jax.random.PRNGKey(0), 6, 512, mult=4))
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 256, 6, 512), jnp.bfloat16)
    got = fused_grouped_ffw(params, x)
    want = grouped_ffw(params, x)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32), rtol=5e-2, atol=5e-2
    )


@check("grouped_ffw_bf16_grad_parity_multitile")
def check_ffw_grad():
    from glom_tpu.kernels import fused_grouped_ffw
    from glom_tpu.ops.ffw import grouped_ffw, init_grouped_ffw

    params = _bf16_tree(init_grouped_ffw(jax.random.PRNGKey(0), 4, 128, mult=4))
    x = jax.random.normal(jax.random.PRNGKey(3), (4, 256, 4, 128), jnp.bfloat16)

    def lf(p, x_):
        return jnp.mean(fused_grouped_ffw(p, x_).astype(jnp.float32) ** 2)

    def lx(p, x_):
        return jnp.mean(grouped_ffw(p, x_).astype(jnp.float32) ** 2)

    g1 = jax.jit(jax.grad(lf, argnums=(0, 1)))(params, x)
    g2 = jax.jit(jax.grad(lx, argnums=(0, 1)))(params, x)
    for a, b in zip(jax.tree_util.tree_leaves(g1), jax.tree_util.tree_leaves(g2)):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32), rtol=0.1, atol=2e-3
        )


def _consensus_case(side, radius, dtype, rtol, atol, grad, bwd_impl="blockwise"):
    """grad checks default to FORCING the blockwise kernels — under 'auto'
    the measured-crossover dispatch would route these shapes to the dense
    VJP and the Pallas backward would go unvalidated on hardware."""
    from glom_tpu.kernels.consensus_update import _fused, _xla_reference

    L, B, d = 6, 2, 512
    n = side * side
    ks = jax.random.split(jax.random.PRNGKey(side + int(radius)), 3)
    levels = jax.random.normal(ks[0], (L, B, n, d), dtype)
    bu = jax.random.normal(ks[1], (L, B, n, d), dtype)
    td = jax.random.normal(ks[2], (L - 1, B, n, d), dtype)

    if grad:
        def lf(lv, b_, t_):
            return jnp.mean(
                _fused(lv, b_, t_, side, radius, False, False, bwd_impl)
                .astype(jnp.float32) ** 2
            )

        def lr(lv, b_, t_):
            return jnp.mean(
                _xla_reference(lv, b_, t_, side=side, radius=radius, attend_self=False).astype(jnp.float32) ** 2
            )

        got = jax.jit(jax.grad(lf, argnums=(0, 1, 2)))(levels, bu, td)
        want = jax.jit(jax.grad(lr, argnums=(0, 1, 2)))(levels, bu, td)
        for a, b in zip(got, want):
            np.testing.assert_allclose(
                np.asarray(a, np.float32), np.asarray(b, np.float32), rtol=rtol, atol=atol
            )
    else:
        got = jax.jit(lambda *a: _fused(*a, side, radius, False, False))(levels, bu, td)
        want = _xla_reference(levels, bu, td, side=side, radius=radius, attend_self=False)
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(want, np.float32), rtol=rtol, atol=atol
        )


@check("grouped_ffw_bf16_add_fold_parity")
def check_ffw_add_fold():
    """The folded positional addend (add=) must equal the explicit
    x + tile(add) composition — forward AND all grads including da (the
    pos-emb cotangent reduced in-kernel across the whole grid)."""
    from glom_tpu.kernels import fused_grouped_ffw_lm
    from glom_tpu.ops.ffw import init_grouped_ffw

    G, b, n, d = 5, 4, 256, 512
    M = b * n
    params = _bf16_tree(init_grouped_ffw(jax.random.PRNGKey(0), G, d, mult=4))
    x = jax.random.normal(jax.random.PRNGKey(1), (G, M, d), jnp.bfloat16)
    a = jax.random.normal(jax.random.PRNGKey(2), (n, d), jnp.bfloat16)

    def loss_fold(p, x_, a_):
        out = fused_grouped_ffw_lm(p, x_, add=a_)
        return jnp.mean(out.astype(jnp.float32) ** 2)

    def loss_explicit(p, x_, a_):
        xa = x_ + jnp.tile(a_, (M // n, 1))[None]
        out = fused_grouped_ffw_lm(p, xa)
        return jnp.mean(out.astype(jnp.float32) ** 2)

    v1, g1 = jax.jit(jax.value_and_grad(loss_fold, argnums=(0, 1, 2)))(
        params, x, a
    )
    v2, g2 = jax.jit(jax.value_and_grad(loss_explicit, argnums=(0, 1, 2)))(
        params, x, a
    )
    np.testing.assert_allclose(float(v1), float(v2), rtol=2e-3)
    for t1, t2 in zip(
        jax.tree_util.tree_leaves(g1), jax.tree_util.tree_leaves(g2)
    ):
        np.testing.assert_allclose(
            np.asarray(t1, np.float32), np.asarray(t2, np.float32),
            rtol=5e-2, atol=5e-2,
        )


@check("consensus_bf16_forward_parity_n256")
def check_cons_fwd_256():
    _consensus_case(16, 0.0, jnp.bfloat16, 5e-2, 5e-2, grad=False)


@check("consensus_bf16_forward_parity_n1024_radius7")
def check_cons_fwd_1024():
    _consensus_case(32, 7.0, jnp.bfloat16, 5e-2, 5e-2, grad=False)


@check("consensus_f32_grad_parity_n256")
def check_cons_grad_f32():
    _consensus_case(16, 0.0, jnp.float32, 2e-3, 2e-5, grad=True)


@check("consensus_bf16_grad_parity_n1024")
def check_cons_grad_bf16():
    _consensus_case(32, 0.0, jnp.bfloat16, 0.1, 2e-2, grad=True)


@check("consensus_bf16_grad_parity_n1024_radius7")
def check_cons_grad_bf16_r7():
    _consensus_case(32, 7.0, jnp.bfloat16, 0.1, 2e-2, grad=True)


@check("consensus_bf16_grad_dispatch_auto_n1024")
def check_cons_grad_auto():
    """The 'auto' dispatch side (dense VJP at this shape) on hardware."""
    _consensus_case(32, 0.0, jnp.bfloat16, 0.1, 2e-2, grad=True, bwd_impl="auto")


@check("fused_loop_bf16_grad_parity")
def check_fused_loop_grads():
    """The hand-rolled whole-loop VJP (kernels/fused_loop.py) vs the
    XLA-composed reference loop, in bf16 on real Mosaic: forward and every
    cotangent (FFW weights, pos_emb, tokens, levels0)."""
    from functools import partial

    from glom_tpu.kernels.fused_loop import fused_glom_loop, loop_supported
    from glom_tpu.models.core import contribution_divisor, update_step
    from glom_tpu.ops.consensus import build_local_mask, consensus_attention
    from glom_tpu.ops.ffw import init_grouped_ffw

    L, B, n, d, side, iters = 6, 8, 256, 512, 16, 3
    assert loop_supported(L, B, n, d, 4 * d, 2, iters, n)
    k = jax.random.split(jax.random.PRNGKey(0), 5)
    bu = _bf16_tree(init_grouped_ffw(k[0], L, d, 4))
    td = _bf16_tree(init_grouped_ffw(k[1], L - 1, d, 4))
    pos = jax.random.normal(k[2], (n, d), jnp.bfloat16)
    tokens = jax.random.normal(k[3], (B, n, d), jnp.bfloat16)
    lv0 = jax.random.normal(k[4], (L, B, n, d), jnp.bfloat16)

    def loss_loop(*a):
        return jnp.mean(
            fused_glom_loop(*a, iters, side, 0.0, False, False).astype(
                jnp.float32
            )
            ** 2
        )

    def loss_ref(bu_p, td_p, pos_, tokens_, lv0_):
        class P:
            bottom_up, top_down, pos_emb = bu_p, td_p, pos_

        cons = partial(
            consensus_attention,
            attend_self=False,
            local_mask=build_local_mask(side, 0.0),
        )
        levels = jnp.transpose(lv0_, (1, 2, 0, 3))
        bottom = tokens_[:, :, None, :]
        div = contribution_divisor(L)
        for _ in range(iters):
            levels = update_step(
                P, levels, bottom, pos_[None, :, None, :], div,
                consensus_fn=cons,
            )
        return jnp.mean(jnp.transpose(levels, (2, 0, 1, 3)).astype(jnp.float32) ** 2)

    args = (bu, td, pos, tokens, lv0)
    g1 = jax.jit(jax.grad(loss_loop, argnums=tuple(range(5))))(*args)
    g2 = jax.jit(jax.grad(loss_ref, argnums=tuple(range(5))))(*args)
    for a, b in zip(jax.tree_util.tree_leaves(g1), jax.tree_util.tree_leaves(g2)):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=0.1, atol=3e-3,
        )


def _fused_loop_args(key=0):
    from glom_tpu.ops.ffw import init_grouped_ffw

    L, B, n, d = 6, 8, 256, 512
    k = jax.random.split(jax.random.PRNGKey(key), 5)
    return (
        _bf16_tree(init_grouped_ffw(k[0], L, d, 4)),
        _bf16_tree(init_grouped_ffw(k[1], L - 1, d, 4)),
        jax.random.normal(k[2], (n, d), jnp.bfloat16),
        jax.random.normal(k[3], (B, n, d), jnp.bfloat16),
        jax.random.normal(k[4], (L, B, n, d), jnp.bfloat16),
    )


@check("fused_loop_primal_vs_vjp_forward")
def check_fused_loop_primal_vs_vjp_forward():
    """The no-grad primal (plain [L]-carry body) and the VJP forward (the
    [L+1]-slot body) are SEPARATE computations of the same math, kept
    equal only by tests (the 2% forward-bench split, fused_loop.py) — this
    pins their parity on real Mosaic explicitly, not as a side effect of
    the grad check (round-4 weak #4)."""
    from glom_tpu.kernels.fused_loop import fused_glom_loop

    args = _fused_loop_args()
    primal = jax.jit(
        lambda *a: fused_glom_loop(*a, 3, 16, 0.0, False, False)
    )(*args)

    def via_vjp(*a):
        out, _ = jax.vjp(
            lambda bu, td, pos, tok, lv: fused_glom_loop(
                bu, td, pos, tok, lv, 3, 16, 0.0, False, False
            ),
            *a,
        )
        return out

    vjp_fwd = jax.jit(via_vjp)(*args)
    np.testing.assert_allclose(
        np.asarray(primal, np.float32), np.asarray(vjp_fwd, np.float32),
        rtol=2e-2, atol=2e-3,
    )


@check("fused_loop_remat_grad_parity")
def check_fused_loop_remat_grads():
    """remat=True (recompute-per-iteration backward, BASELINE config 5's
    regime on the fused loop) vs remat=False on real Mosaic: the
    recomputed pre-activations run the same f32-accumulate matmul the
    forward would have saved, so the cotangents must agree tightly."""
    from glom_tpu.kernels.fused_loop import fused_glom_loop, loop_supported

    assert loop_supported(6, 8, 256, 512, 2048, 2, 3, 256, remat=True)
    args = _fused_loop_args(1)

    def loss(remat):
        def f(*a):
            return jnp.mean(
                fused_glom_loop(*a, 3, 16, 0.0, False, False, remat).astype(
                    jnp.float32
                )
                ** 2
            )

        return f

    g0 = jax.jit(jax.grad(loss(False), argnums=tuple(range(5))))(*args)
    g1 = jax.jit(jax.grad(loss(True), argnums=tuple(range(5))))(*args)
    for a, b in zip(
        jax.tree_util.tree_leaves(g0), jax.tree_util.tree_leaves(g1)
    ):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=1e-4, atol=1e-6,
        )


@check("fused_loop_combined_grid_parity")
def check_fused_loop_combined_grid():
    """GLOM_LOOP_GRID=combined on real Mosaic: the 2L-1-group cat grids
    (jnp.where in BlockSpec index maps — first use on hardware) must
    reproduce the split default's loss and cotangents. Measurement A/B
    lives in scratch/ffw_bwd_sched_probe.py; this is the correctness
    gate before any promotion."""
    import os

    from glom_tpu.kernels.fused_loop import fused_glom_loop

    args = _fused_loop_args(2)

    def loss(*a):
        return jnp.mean(
            fused_glom_loop(*a, 3, 16, 0.0, False, False).astype(jnp.float32)
            ** 2
        )

    prior = os.environ.get("GLOM_LOOP_GRID")
    try:
        # BOTH arms pinned explicitly (fresh jits: the knob is read at
        # trace time). Inheriting the env for the baseline would make the
        # check a vacuous combined-vs-combined self-comparison whenever an
        # operator exports GLOM_LOOP_GRID=combined for the whole run.
        os.environ["GLOM_LOOP_GRID"] = "split"
        l_split, g_split = jax.jit(
            jax.value_and_grad(loss, argnums=tuple(range(5)))
        )(*args)
        os.environ["GLOM_LOOP_GRID"] = "combined"
        l_comb, g_comb = jax.jit(
            jax.value_and_grad(loss, argnums=tuple(range(5)))
        )(*args)
    finally:
        # restore, don't pop: an operator-set GLOM_LOOP_GRID must still
        # govern the remaining checks in this run
        if prior is None:
            os.environ.pop("GLOM_LOOP_GRID", None)
        else:
            os.environ["GLOM_LOOP_GRID"] = prior
    np.testing.assert_allclose(
        float(l_split), float(l_comb), rtol=1e-5
    )
    for a, b in zip(
        jax.tree_util.tree_leaves(g_split), jax.tree_util.tree_leaves(g_comb)
    ):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=1e-3, atol=1e-5,
        )


@check("tp_composition_megatron_psum")
def check_tp_composition():
    """TP x Pallas on REAL hardware: the manual-region Megatron psum
    (parallel/manual.py) composed with the fused kernels, vs single-device
    training from identical state/data. CPU-verified since round 3; this
    runs it on silicon automatically in the first environment that shows
    >= 2 devices (round-3 weak #5: the first unverified multi-chip seam).
    On the current 1-chip tunnel it records 'skipped' and passes."""
    if len(jax.devices()) < 2:
        raise _Skipped("1 device visible; TP needs >= 2")
    from glom_tpu.parallel import DistributedTrainer
    from glom_tpu.train.trainer import Trainer
    from glom_tpu.utils.config import GlomConfig, MeshConfig, TrainConfig

    cfg = GlomConfig(dim=256, levels=4, image_size=32, patch_size=4)
    tcfg = TrainConfig(batch_size=8, learning_rate=3e-4,
                       compute_dtype="bfloat16", use_pallas=True)
    single = Trainer(cfg, tcfg)
    dist = DistributedTrainer(
        cfg, tcfg, MeshConfig(data=1, seq=1, model=2), tp_axis="hidden"
    )
    assert dist.use_manual, "TP check fell off the manual fused path"
    img = np.asarray(
        jax.random.normal(jax.random.PRNGKey(1), (8, 3, 32, 32), jnp.float32)
    )
    for i in range(4):
        m1 = single.step(jnp.asarray(img))
        m2 = dist.step(img)
        rel = abs(float(m1["loss"]) - float(m2["loss"])) / max(
            abs(float(m1["loss"])), 1e-9
        )
        assert rel < 5e-2, (i, float(m1["loss"]), float(m2["loss"]))


@check("train_step_bf16_loss_decreases")
def check_train():
    from glom_tpu.train.trainer import create_train_state, make_train_step
    from glom_tpu.utils.config import GlomConfig, TrainConfig

    cfg = GlomConfig(dim=256, levels=4, image_size=64, patch_size=8)
    tcfg = TrainConfig(batch_size=8, learning_rate=3e-4,
                       compute_dtype="bfloat16", use_pallas=True)
    state, optimizer = create_train_state(jax.random.PRNGKey(0), cfg, tcfg)
    step = jax.jit(make_train_step(cfg, tcfg, optimizer))
    img = jax.random.normal(jax.random.PRNGKey(1), (8, 3, 64, 64), jnp.float32)
    losses = []
    for i in range(8):
        state, m = step(state, img, jax.random.fold_in(jax.random.PRNGKey(2), i))
        losses.append(float(m["loss"]))
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses


@check("train_step_bf16_pallas_vs_xla_trajectory")
def check_train_cross_path():
    """The production (Pallas, level-major, save-pre backward) train step
    and the plain-XLA step must produce closely tracking bf16 loss
    trajectories from identical state/data/noise — a whole-step cross-path
    guard the CPU suite cannot run (no real bf16 dots there)."""
    from glom_tpu.train.trainer import create_train_state, make_train_step
    from glom_tpu.utils.config import GlomConfig, TrainConfig

    cfg = GlomConfig(dim=256, levels=4, image_size=64, patch_size=8)
    img = jax.random.normal(jax.random.PRNGKey(1), (8, 3, 64, 64), jnp.float32)

    def run(use_pallas):
        tcfg = TrainConfig(batch_size=8, learning_rate=3e-4,
                           compute_dtype="bfloat16", use_pallas=use_pallas,
                           scan_unroll=use_pallas)
        state, optimizer = create_train_state(jax.random.PRNGKey(0), cfg, tcfg)
        step = jax.jit(make_train_step(cfg, tcfg, optimizer))
        losses = []
        for i in range(6):
            state, m = step(state, img, jax.random.fold_in(jax.random.PRNGKey(2), i))
            losses.append(float(m["loss"]))
        return losses

    lp, lx = run(True), run(False)
    assert all(np.isfinite(lp)) and all(np.isfinite(lx)), (lp, lx)
    worst = max(abs(a - b) / max(abs(b), 1e-9) for a, b in zip(lp, lx))
    assert worst < 5e-2, (worst, lp, lx)


def main():
    dev = jax.devices()[0]
    if dev.platform != "tpu":
        print(json.dumps({"skipped": True, "reason": f"platform={dev.platform}"}))
        return 0
    for fn in (
        check_ffw_fwd, check_ffw_grad, check_ffw_add_fold,
        check_cons_fwd_256, check_cons_fwd_1024,
        check_cons_grad_f32, check_cons_grad_bf16, check_cons_grad_bf16_r7,
        check_cons_grad_auto,
        check_fused_loop_grads,
        check_fused_loop_primal_vs_vjp_forward,
        check_fused_loop_remat_grads,
        check_fused_loop_combined_grid,
        check_tp_composition,
        check_train, check_train_cross_path,
    ):
        fn()
    ok = all(r["ok"] for r in RESULTS)
    summary = {
        "summary": True,
        "device_kind": dev.device_kind,
        "jax": jax.__version__,
        "passed": sum(r["ok"] for r in RESULTS),
        "skipped": sum(bool(r.get("skipped")) for r in RESULTS),
        "total": len(RESULTS),
    }
    print(json.dumps(summary), flush=True)
    out_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)), "results")
    os.makedirs(out_dir, exist_ok=True)
    with open(os.path.join(out_dir, "tpu_validation.jsonl"), "w") as f:
        for rec in RESULTS + [summary]:
            f.write(json.dumps(rec) + "\n")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
