"""Serving benchmark: offered-load sweep -> latency percentiles + throughput.

The training benches measure steady-state step time; serving is judged on
the LATENCY DISTRIBUTION under load — p50 is what the median user feels,
p95/p99 are what the SLO is written against, and throughput is what the
fleet bill is written against. This harness drives the real stack
(InferenceEngine + DynamicBatcher, glom_tpu/serve) end to end:

  1. AOT warmup of every bucket (compile time per bucket on the record —
     the cliff warmup exists to remove);
  2. a closed-loop ceiling measurement: back-to-back full-bucket
     dispatches -> max sustainable requests/sec;
  3. an open-loop offered-load sweep at fractions of that ceiling:
     requests submitted at the offered rate through the batcher, per-
     request latency collected from tickets -> p50/p95/p99 + achieved
     throughput per load point (StepTimeStats percentiles);
  4. with iters="auto": the early-exit iteration histogram — how many
     column updates requests ACTUALLY ran vs the fixed budget;
  5. with --two-tier-ab: the two-tier A/B — heterogeneous synthetic
     traffic (easy requests converge in ~B-3 iterations, hard 100x-scale
     requests near the budget B; --hetero sets the hard fraction) served
     under batch-level exit (quorum 1.0, no continuations) vs two-tier
     exit (quorum + continuation queue), emitting the per-request
     executed-iters histogram SPLIT BY TIER and the mean-executed-iters
     rows the reduction claim is measured by (docs/SERVING.md).

--engines N fans the batcher out over N engine replicas (shared params,
shared admission); --mesh-data/--mesh-seq route every bucket through the
sharded shard_map forward (parallel/serve_mesh.py).

Rows ride sinks.emit / bench_bootstrap like every other bench: UNMEASURED
is an "error" record with value null (never a dead zero), every row stamps
the watchdog backend state, and the output lints with
`python -m glom_tpu.telemetry FILE` and gates with `... compare`
(run_hw_queue.sh serve steps).
"""

from __future__ import annotations

import argparse
import json
import time


def _make_engines(cfg, scfg, n_engines: int):
    import jax

    from glom_tpu.serve.engine import InferenceEngine

    params = None
    if n_engines > 1 or scfg.mesh_data > 1 or scfg.mesh_seq > 1:
        from glom_tpu.models.core import init_glom

        params = init_glom(jax.random.PRNGKey(0), cfg)
    if scfg.mesh_data > 1 or scfg.mesh_seq > 1:
        from glom_tpu.parallel.runtime import make_engine_meshes

        meshes = make_engine_meshes(scfg, n_engines)
    else:
        meshes = [None] * n_engines
    return [
        InferenceEngine(
            cfg, scfg, params=params, mesh=meshes[i], name=f"engine{i}"
        )
        for i in range(n_engines)
    ]


def run_sweep(cfg, scfg, label: str, *, n_requests: int, load_fracs,
              ceiling_repeats: int, n_engines: int = 1) -> None:
    import numpy as np

    from glom_tpu.serve.batcher import DynamicBatcher, ShedError
    from glom_tpu.telemetry.sinks import StepTimeStats, emit

    engines = _make_engines(cfg, scfg, n_engines)
    engine = engines[0]
    for eng in engines:
        for bucket, dt in eng.warmup().items():
            emit(
                {"event": "warmup", "engine": eng.name, "bucket": bucket,
                 "compile_time_s": round(dt, 4), "config": label},
                kind="serve",
            )

    top = max(scfg.buckets)
    rng = np.random.default_rng(0)
    imgs = rng.normal(size=(top, cfg.channels, cfg.image_size, cfg.image_size)
                      ).astype(np.float32)

    # 2. Closed-loop ceiling: back-to-back full buckets, min over repeats
    # (jitter only ever slows things down — bench.py's convention). One
    # engine's ceiling; N engines admit up to N x this.
    per_batch = min(
        engine.infer(imgs, n_valid=top).latency_s
        for _ in range(ceiling_repeats)
    )
    ceiling = top / per_batch * n_engines
    emit(
        {
            "metric": f"serve_throughput_ceiling ({label})",
            "value": round(ceiling, 2),
            "unit": "req/s",
            "bucket": top,
            "engines": n_engines,
            "batch_latency_ms": round(1e3 * per_batch, 3),
        }
    )

    # 3. Open-loop offered-load sweep through the batcher.
    for frac in load_fracs:
        rate = max(ceiling * frac, 1e-6)
        stats = StepTimeStats()
        stats.observe(0.0, is_compile=True)  # no compile phase here
        served = shed = 0
        t0 = time.perf_counter()
        with DynamicBatcher(engines=engines) as batcher:
            tickets = []
            for i in range(n_requests):
                target = t0 + i / rate
                delay = target - time.perf_counter()
                if delay > 0:
                    time.sleep(delay)
                try:
                    tickets.append(batcher.submit(imgs[i % top]))
                except ShedError:
                    shed += 1
            for t in tickets:
                try:
                    _, _, latency_s = t.result(timeout=600.0)
                except Exception:
                    shed += 1
                    continue
                served += 1
                stats.observe(latency_s, is_compile=False)
        wall = time.perf_counter() - t0
        s = stats.summary()
        base = f"load={frac:.2f}x, {label}"
        if served == 0:
            # Every request shed or failed: these rows are UNMEASURED —
            # kind "error", value null — never the 0.0ms/0rps dead zeros
            # the compare gate would read as a massive improvement.
            for name, unit in (
                (f"serve_p50_latency ({base})", "ms"),
                (f"serve_p95_latency ({base})", "ms"),
                (f"serve_p99_latency ({base})", "ms"),
                (f"serve_throughput ({base})", "req/s"),
            ):
                emit(
                    {
                        "metric": name,
                        "value": None,
                        "unit": unit,
                        "error": "no-requests-served",
                        "note": f"UNMEASURED: all {n_requests} requests "
                        f"shed or failed ({shed} shed)",
                    },
                    kind="error",
                )
            emit(dict(batcher.summary_record(), config=base), kind="serve")
            continue
        emit(
            {
                "metric": f"serve_p50_latency ({base})",
                "value": s["step_time_p50_ms"],
                "unit": "ms",
                "offered_rps": round(rate, 2),
                "served": served,
                "shed": shed,
            }
        )
        emit(
            {
                "metric": f"serve_p95_latency ({base})",
                "value": s["step_time_p95_ms"],
                "unit": "ms",
            }
        )
        emit(
            {
                "metric": f"serve_p99_latency ({base})",
                "value": s["step_time_p99_ms"],
                "unit": "ms",
            }
        )
        emit(
            {
                "metric": f"serve_throughput ({base})",
                "value": round(served / wall, 2) if wall > 0 else 0.0,
                "unit": "req/s",
            }
        )
        # The batcher's own evidence: dispatch mix + iteration histogram.
        emit(dict(batcher.summary_record(), config=base), kind="serve")

    # 4. Early-exit accounting (only meaningful on the auto route).
    # Genuinely closed-loop: submit in windows no larger than half the
    # queue and drain each window before the next, so --requests beyond
    # queue_depth cannot overrun the bounded queue; a failed request
    # drops one sample, never the histogram rows the gate expects.
    if engine.iters_key == "auto":
        iters = []
        window = max(1, min(scfg.queue_depth // 2, 32))
        with DynamicBatcher(engines=engines) as batcher:
            for start in range(0, n_requests, window):
                tickets = []
                for i in range(start, min(start + window, n_requests)):
                    try:
                        tickets.append(batcher.submit(imgs[i % top]))
                    except ShedError:
                        continue
                for t in tickets:
                    try:
                        _, iters_run, _ = t.result(timeout=600.0)
                    except Exception:
                        continue
                    iters.append(iters_run)
        budget = engine.auto_budget
        if iters:
            hist: dict = {}
            for it in iters:
                hist[str(it)] = hist.get(str(it), 0) + 1
            emit(
                {
                    "event": "iter_histogram",
                    "config": label,
                    "budget": budget,
                    "histogram": hist,
                    "n": len(iters),
                },
                kind="serve",
            )
            emit(
                {
                    "metric": f"serve_auto_mean_iters ({label})",
                    "value": round(sum(iters) / len(iters), 3),
                    "unit": "iters/request",
                    "budget": budget,
                }
            )
        else:
            emit(
                {
                    "metric": f"serve_auto_mean_iters ({label})",
                    "value": None,
                    "unit": "iters/request",
                    "error": "no-requests-served",
                    "note": "UNMEASURED: early-exit pass served nothing",
                },
                kind="error",
            )
    for eng in engines:
        for rec in eng.stats_records():
            emit(dict(rec, config=label), kind="serve")
        for rec in eng.collective_time_records():
            # Per-collective wall-time evidence (sharded route, timing
            # on): already stamped kind "collective_time" — printed next
            # to the bucket stats so the compare gate sees the wall_ms
            # cost rows (docs/OBSERVABILITY.md, Capacity observatory).
            print(json.dumps(dict(rec, config=label)), flush=True)


def run_two_tier_ab(cfg, scfg, label: str, *, n_requests: int,
                    hard_frac: float, n_engines: int = 1,
                    quorum: float = 0.5, continuations: int = 3) -> dict:
    """Batch-level vs two-tier exit over HETEROGENEOUS traffic: the same
    request stream (easy gaussian images interleaved with hard 100x-scale
    ones — far from the consensus attractor, they converge near the
    budget) served under both exit policies, with the per-request
    executed-iters histogram split by tier. Returns {arm: mean} so CI can
    assert the reduction as a measured number, not a claim."""
    import dataclasses

    import numpy as np

    from glom_tpu.serve.batcher import DynamicBatcher, ShedError
    from glom_tpu.telemetry.sinks import emit

    if scfg.iters != "auto":
        emit(
            {"note": "two-tier A/B skipped: the configured route is not "
             "iters='auto' (no witness, no stragglers)"},
            kind="note",
        )
        return {}
    rng = np.random.default_rng(7)
    shape = (cfg.channels, cfg.image_size, cfg.image_size)
    n_hard = int(round(hard_frac * n_requests))
    hard_idx = (
        set(np.linspace(0, n_requests - 1, n_hard).astype(int).tolist())
        if n_hard
        else set()
    )
    imgs = []
    for i in range(n_requests):
        img = rng.normal(size=shape).astype(np.float32)
        if i in hard_idx:
            img *= 100.0
        imgs.append(img)

    arms = (
        ("batch-level", dataclasses.replace(
            scfg, exit_quorum=1.0, max_continuations=0)),
        ("two-tier", dataclasses.replace(
            scfg, exit_quorum=quorum, max_continuations=continuations)),
    )
    means: dict = {}
    for arm, arm_scfg in arms:
        engines = _make_engines(cfg, arm_scfg, n_engines)
        for eng in engines:
            eng.warmup()
        window = max(2, min(arm_scfg.max_batch, arm_scfg.queue_depth // 2))
        got = 0
        with DynamicBatcher(engines=engines) as batcher:
            for start in range(0, n_requests, window):
                tickets = []
                for i in range(start, min(start + window, n_requests)):
                    try:
                        tickets.append(batcher.submit(imgs[i]))
                    except ShedError:
                        continue
                for t in tickets:
                    try:
                        t.result(timeout=600.0)
                        got += 1
                    except Exception:
                        continue
            summary = batcher.summary_record()
        mean = summary.get("mean_executed_iters")
        emit(
            {
                "event": "iter_histogram_tiered",
                "arm": arm,
                "config": label,
                "budget": engines[0].auto_budget,
                "quorum": arm_scfg.exit_quorum,
                "max_continuations": arm_scfg.max_continuations,
                "hard_frac": hard_frac,
                "histogram_by_tier": summary["iters_histogram_by_tier"],
                "n_continued": summary["n_continued"],
                "n": got,
            },
            kind="serve",
        )
        if mean is None:
            emit(
                {
                    "metric": f"serve_mean_executed_iters ({arm}, {label})",
                    "value": None,
                    "unit": "iters/request",
                    "error": "no-requests-served",
                    "note": f"UNMEASURED: {arm} arm served nothing",
                },
                kind="error",
            )
        else:
            means[arm] = mean
            emit(
                {
                    "metric": f"serve_mean_executed_iters ({arm}, {label})",
                    "value": mean,
                    "unit": "iters/request",
                    "hard_frac": hard_frac,
                    "served": got,
                }
            )
    return means


def run_temporal(cfg, scfg, label: str, *, n_streams: int, n_frames: int,
                 perturb: float, n_engines: int = 1) -> dict:
    """Frame-sequence (streaming) traffic: warm-start vs cold-start A/B.

    S streams, F frames each; every frame is a small perturbation of its
    stream's base image (hard 100x-scale bases — the convergence-depth
    lever from the hetero mode, so a cold start runs near the budget).
    Frames advance in lockstep rounds — frame t of every stream resolves
    before frame t+1 submits, the temporal contract a video frontend
    provides — and the same traffic is served twice:

      * cold — column cache disabled: every frame pays full convergence;
      * warm — cache sized for all S streams: frame t+1 dispatches from
        frame t's converged columns (the engine's warm levels0 route).

    The measured number is mean executed column-iters/request per arm
    (`serve_temporal_mean_iters`); the warm arm's summary additionally
    carries the cache rollup, whose `bytes_peak <= budget_bytes` the CI
    gate asserts. Returns {arm: mean} so CI can assert warm < cold as a
    measured fact."""
    import dataclasses

    import numpy as np

    from glom_tpu.serve.batcher import DynamicBatcher, ShedError
    from glom_tpu.serve.column_cache import column_state_bytes
    from glom_tpu.telemetry.sinks import emit

    if scfg.iters != "auto":
        emit(
            {"note": "temporal A/B skipped: the configured route is not "
             "iters='auto' (a fixed budget saves no iterations warm)"},
            kind="note",
        )
        return {}
    rng = np.random.default_rng(11)
    shape = (cfg.channels, cfg.image_size, cfg.image_size)
    bases = [
        (100.0 * rng.normal(size=shape)).astype(np.float32)
        for _ in range(n_streams)
    ]
    frames = [
        [
            (bases[s] + perturb * rng.normal(size=shape)).astype(np.float32)
            for _ in range(n_frames)
        ]
        for s in range(n_streams)
    ]

    budget_bytes = (n_streams + 1) * column_state_bytes(cfg, scfg)
    arms = (
        ("cold", dataclasses.replace(scfg, column_cache_bytes=0)),
        ("warm", dataclasses.replace(scfg, column_cache_bytes=budget_bytes)),
    )
    means: dict = {}
    for arm, arm_scfg in arms:
        engines = _make_engines(cfg, arm_scfg, n_engines)
        for eng in engines:
            eng.warmup()
        served = 0
        with DynamicBatcher(engines=engines) as batcher:
            for f in range(n_frames):
                tickets = []
                for s in range(n_streams):
                    try:
                        tickets.append(
                            batcher.submit(frames[s][f], session_id=f"s{s}")
                        )
                    except ShedError:
                        continue
                for t in tickets:
                    try:
                        t.result(timeout=600.0)
                        served += 1
                    except Exception:
                        continue
            summary = batcher.summary_record()
        mean = summary.get("mean_executed_iters")
        emit(
            {
                "event": "temporal_summary",
                "arm": arm,
                "config": label,
                "budget": engines[0].auto_budget,
                "n_streams": n_streams,
                "n_frames": n_frames,
                "perturb": perturb,
                "n": served,
                "iters_histogram": summary["iters_histogram"],
                "column_cache": summary.get("column_cache"),
            },
            kind="serve",
        )
        if mean is None:
            emit(
                {
                    "metric": f"serve_temporal_mean_iters ({arm}, {label})",
                    "value": None,
                    "unit": "iters/request",
                    "error": "no-requests-served",
                    "note": f"UNMEASURED: temporal {arm} arm served nothing",
                },
                kind="error",
            )
        else:
            means[arm] = mean
            emit(
                {
                    "metric": f"serve_temporal_mean_iters ({arm}, {label})",
                    "value": mean,
                    "unit": "iters/request",
                    "n_streams": n_streams,
                    "n_frames": n_frames,
                    "served": served,
                }
            )
    if "cold" in means and "warm" in means and means["cold"] > 0:
        emit(
            {
                "metric": f"serve_temporal_iters_saved ({label})",
                "value": round(
                    100.0 * (1.0 - means["warm"] / means["cold"]), 2
                ),
                "unit": "%",
                "cold_mean": means["cold"],
                "warm_mean": means["warm"],
            }
        )
    return means


def run_temporal_delta(cfg, scfg, label: str, *, n_streams: int,
                       n_frames: int, cameras: int, perturb: float,
                       period: int, atol: float) -> dict:
    """Delta-encoded streaming A/B (ISSUE 12, docs/SERVING.md "Delta
    streaming"): whole-state paged warm vs delta-chain + incremental.

    The traffic is O(1)-shaped video: `cameras` streams per SCENE share
    an identical first frame (the cross-stream base-sharing case — N
    cameras, one scene), and after that each camera's frames alternate
    HOLDS (bitwise-identical — most frames at video rate) with a small
    REGION perturbation every `period` frames (one patch of the canvas —
    the moving object). The same traffic is served twice:

      * whole-state — the PR 11 paged warm route: every write-back
        rewrites the session's whole page block, every warm frame runs
        the full-width tiered exit;
      * delta — write-backs store only the pages whose column residual
        exceeds `delta_page_atol` (the stamped tolerance), bases alias
        across cameras, and warm frames ride the INCREMENTAL route
        seeded from the input delta's support (holds pay the min_iters
        floor).

    Measured rows: `serve_delta_mean_iters` per arm (the <2 acceptance),
    `serve_delta_bytes_per_stream` per arm (actual pool pages per live
    stream — the >=3x acceptance), and `serve_delta_parity` (a
    threshold-0/atol-0 probe asserting base+Σdeltas reconstruction is
    BITWISE the whole-state warm dispatch). Returns {arm: mean_iters}."""
    import dataclasses

    import numpy as np

    from glom_tpu.serve.batcher import DynamicBatcher, ShedError
    from glom_tpu.serve.paged_columns import (
        pages_for_tokens,
        resolve_page_tokens,
    )
    from glom_tpu.telemetry.sinks import emit

    if scfg.iters != "auto":
        emit(
            {"note": "delta A/B skipped: the configured route is not "
             "iters='auto' (no exit to seed incrementally)"},
            kind="note",
        )
        return {}
    cameras = cameras if cameras > 0 else n_streams
    # Page granularity: ONE page per patch row of the canvas keeps the
    # delta support sharp (the perturbed patch is exactly one page).
    pt = 1 if cfg.num_patches <= 64 else resolve_page_tokens(cfg, scfg)
    ppr = pages_for_tokens(cfg.num_patches, pt)
    pool_pages = (n_streams + 4) * ppr
    top = max(8, n_streams)
    common = dict(
        buckets=(1, 2, 4, top) if top > 4 else (1, 2, 4),
        max_batch=top, max_delay_ms=2.0,
        page_pool_pages=pool_pages, page_tokens=pt,
        column_cache_bytes=(n_streams + 2) * ppr
        * pt * cfg.levels * cfg.dim
        * (2 if scfg.compute_dtype == "bfloat16" else 4),
        max_continuations=0, mesh_data=1, mesh_seq=1,
    )
    arms = (
        ("whole-state", dataclasses.replace(
            scfg, **common, delta_streaming=False)),
        ("delta", dataclasses.replace(
            scfg, **common, delta_streaming=True,
            delta_page_atol=atol, delta_chain_cap=4,
            delta_incremental=True, delta_base_share=True)),
    )
    rng = np.random.default_rng(17)
    p = cfg.patch_size
    shape = (cfg.channels, cfg.image_size, cfg.image_size)
    n_scenes = -(-n_streams // cameras)
    scene_base = [
        (100.0 * rng.normal(size=shape)).astype(np.float32)
        for _ in range(n_scenes)
    ]
    # Per-camera frame sequences: frame 0 is the scene base VERBATIM
    # (content-identical converged columns -> shared base pages); later
    # frames perturb one patch-sized region every `period` frames and
    # HOLD (bitwise) otherwise.
    frames = []
    for s in range(n_streams):
        seq = [scene_base[s // cameras]]
        for f in range(1, n_frames):
            if (f - 1) % period == 0:
                img = seq[-1].copy()
                img[:, 0:p, 0:p] += (
                    perturb * 100.0 * rng.normal(size=(cfg.channels, p, p))
                ).astype(np.float32)
                seq.append(img)
            else:
                seq.append(seq[-1])
        frames.append(seq)

    means: dict = {}
    bytes_per_stream: dict = {}
    for arm, arm_scfg in arms:
        engines = _make_engines(cfg, arm_scfg, 1)
        engines[0].warmup()
        served = 0
        with DynamicBatcher(engines=engines) as batcher:
            for f in range(n_frames):
                tickets = []
                for s in range(n_streams):
                    try:
                        tickets.append(
                            batcher.submit(frames[s][f], session_id=f"s{s}")
                        )
                    except ShedError:
                        continue
                for t in tickets:
                    try:
                        t.result(timeout=600.0)
                        served += 1
                    except Exception:
                        continue
            summary = batcher.summary_record()
        pool_rec = summary.get("page_pools", {}).get("engine0", {})
        bps = (
            round(pool_rec["bytes_in_use"] / pool_rec["n_sessions"], 1)
            if pool_rec.get("n_sessions")
            else None
        )
        mean = summary.get("mean_executed_iters")
        emit(dict(summary, config=f"{arm}, {label}"), kind="serve")
        emit(
            {
                "event": "delta_summary",
                "arm": arm,
                "config": label,
                "budget": engines[0].auto_budget,
                "n_streams": n_streams,
                "n_frames": n_frames,
                "cameras": cameras,
                "period": period,
                "delta_page_atol": atol if arm == "delta" else None,
                "n": served,
                "n_incremental": summary.get("n_incremental"),
                "column_cache": summary.get("column_cache"),
            },
            kind="serve",
        )
        for metric, value, unit in (
            (f"serve_delta_mean_iters ({arm}, {label})", mean,
             "iters/request"),
            (f"serve_delta_bytes_per_stream ({arm}, {label})", bps,
             "bytes"),
        ):
            if value is None:
                emit(
                    {
                        "metric": metric, "value": None, "unit": unit,
                        "error": "no-requests-served",
                        "note": f"UNMEASURED: delta A/B {arm} arm served "
                        "nothing",
                    },
                    kind="error",
                )
            else:
                emit(
                    {
                        "metric": metric, "value": value, "unit": unit,
                        "served": served,
                        "delta_page_atol": atol if arm == "delta" else None,
                    }
                )
        if mean is not None:
            means[arm] = mean
        if bps is not None:
            bytes_per_stream[arm] = bps

    # Threshold-0 / atol-0 parity probe: base+Σdeltas reconstruction must
    # be BITWISE the whole-state warm dispatch (the exactness contract
    # the test suite locks; CI reads this row as a 1.0-or-fail gate).
    probe_scfg = dataclasses.replace(
        arms[1][1], iters="auto", exit_threshold=0.0, delta_page_atol=0.0,
        max_auto_iters=4,
    )
    eng = _make_engines(cfg, probe_scfg, 1)[0]
    img1 = frames[0][0][None]
    lv1 = np.asarray(eng.infer(img1, n_valid=1).levels)[0]
    eng.pool.write_back_stream("d", lv1, cfg.num_patches)
    eng.pool.write_back("w", lv1, cfg.num_patches)

    def _warm(sid, img):
        prow = np.asarray([eng.pool.lookup(sid)[0]], np.int32)
        return np.asarray(eng.infer(img, n_valid=1, page_rows=prow).levels)[0]

    img2 = img1 + 0.05 * rng.normal(size=img1.shape).astype(np.float32)
    out_d, out_w = _warm("d", img2), _warm("w", img2)
    eng.pool.write_back_stream("d", out_d, cfg.num_patches)
    eng.pool.write_back("w", out_w, cfg.num_patches)
    img3 = img2 + 0.05 * rng.normal(size=img1.shape).astype(np.float32)
    bitwise = bool(
        np.array_equal(out_d, out_w)
        and np.array_equal(_warm("d", img3), _warm("w", img3))
    )
    emit(
        {
            "metric": f"serve_delta_parity ({label})",
            "value": 1.0 if bitwise else 0.0,
            "unit": "bool",
            "note": "threshold-0/atol-0 base+deltas reconstruction vs "
            "whole-state warm dispatch, bitwise",
            "chain_len": eng.pool.delta_chain_len("d"),
        }
    )
    if "whole-state" in bytes_per_stream and "delta" in bytes_per_stream:
        emit(
            {
                "metric": f"serve_delta_bytes_ratio ({label})",
                "value": round(
                    bytes_per_stream["whole-state"]
                    / max(bytes_per_stream["delta"], 1e-9),
                    2,
                ),
                "unit": "x",
                "whole_state": bytes_per_stream["whole-state"],
                "delta": bytes_per_stream["delta"],
            }
        )
    return means


def run_ragged(cfg, scfg, label: str, *, n_streams: int, n_frames: int,
               perturb: float) -> dict:
    """Mixed-resolution sweep: the ragged paged route vs the bucket
    ladder (docs/SERVING.md, "Paged column memory" / "Ragged admission").

    S streams at CYCLING resolutions (full, 3/4, 1/2 of the canvas — the
    new workload class: mixed resolutions/aspect ratios), F frames each,
    hard 100x-scale bases plus a small per-frame perturbation. The same
    traffic is served twice:

      * bucket-ladder — every image PADDED host-side to the full canvas
        and row-padded to a bucket shape; warm frames ride the PR 8
        host-array cache (levels0 re-uploaded per warm dispatch);
      * ragged-paged — native resolutions packed page-aligned onto the
        ragged page ladder; warm frames take pool pages IN-GRAPH (zero
        levels0 upload).

    The measured numbers: `serve_pad_waste` per arm (true useful tokens
    over dispatched token slots — the bucket arm's canvas padding counts
    as waste, because the MXU multiplies it), warm/cold dispatch latency
    per arm, and `serve_levels0_h2d_bytes` per arm (the ragged arm's
    MUST be zero — the CI gate asserts both claims). Returns
    {arm: pad_waste_pct}."""
    import dataclasses

    import numpy as np

    from glom_tpu.serve.batcher import DynamicBatcher, ShedError
    from glom_tpu.serve.column_cache import column_state_bytes
    from glom_tpu.serve.paged_columns import (
        pages_for_tokens,
        resolve_page_tokens,
    )
    from glom_tpu.telemetry.sinks import emit

    if scfg.iters != "auto":
        emit(
            {"note": "ragged sweep skipped: the configured route is not "
             "iters='auto' (warm frames save nothing on a fixed budget)"},
            kind="note",
        )
        return {}
    rng = np.random.default_rng(13)
    p = cfg.patch_size
    side = cfg.image_size
    # Cycling resolutions: full, ~3/4, ~1/2 of the canvas, rounded to
    # patch multiples (all >= one patch).
    sizes = sorted(
        {max(p, (side * f // (4 * p)) * p) for f in (4, 3, 2)}, reverse=True
    )
    stream_size = [sizes[s % len(sizes)] for s in range(n_streams)]
    bases = [
        (100.0 * rng.normal(size=(cfg.channels, hw, hw))).astype(np.float32)
        for hw in stream_size
    ]
    frames = [
        [
            (bases[s] + perturb * rng.normal(size=bases[s].shape)).astype(
                np.float32
            )
            for _ in range(n_frames)
        ]
        for s in range(n_streams)
    ]
    n_tokens = [(hw // p) ** 2 for hw in stream_size]
    useful = sum(n_tokens) * n_frames

    pt = resolve_page_tokens(cfg, scfg)
    ppr = pages_for_tokens(cfg.num_patches, pt)
    cache_bytes = (n_streams + 1) * column_state_bytes(cfg, scfg)
    pool_pages = (n_streams + 2) * ppr
    arms = (
        ("bucket-ladder", dataclasses.replace(
            scfg, ragged=False, page_pool_pages=0, max_continuations=0,
            column_cache_bytes=cache_bytes)),
        ("ragged-paged", dataclasses.replace(
            scfg, ragged=True, page_pool_pages=pool_pages, page_tokens=pt,
            max_continuations=0, column_cache_bytes=cache_bytes)),
    )
    waste: dict = {}
    for arm, arm_scfg in arms:
        engines = _make_engines(cfg, arm_scfg, 1)
        engine = engines[0]
        if arm == "ragged-paged":
            engine.warmup_ragged()
        else:
            engine.warmup()
        served = 0
        with DynamicBatcher(engines=engines) as batcher:
            for f in range(n_frames):
                tickets = []
                for s in range(n_streams):
                    img = frames[s][f]
                    if arm == "bucket-ladder":
                        # The pad tax, literally: embed the small image
                        # into the full canvas (zeros elsewhere) so the
                        # fixed-shape engine can serve it at all.
                        canvas = np.zeros(
                            (cfg.channels, side, side), np.float32
                        )
                        canvas[:, : img.shape[1], : img.shape[2]] = img
                        img = canvas
                    try:
                        tickets.append(
                            batcher.submit(img, session_id=f"s{s}")
                        )
                    except ShedError:
                        continue
                for t in tickets:
                    try:
                        t.result(timeout=600.0)
                        served += 1
                    except Exception:
                        continue
            summary = batcher.summary_record()
            dispatches = list(batcher.dispatches)
        # True token-slot accounting per arm: the bucket arm's slots are
        # bucket x full-resolution patches (canvas padding included);
        # the ragged arm's are its page-aligned totals.
        if arm == "ragged-paged":
            slots = sum(
                d["n_pages"] * pt for d in dispatches if d.get("ragged")
            )
        else:
            slots = sum(d["bucket"] * cfg.num_patches for d in dispatches)
        pct = round(100.0 * (1.0 - useful / slots), 2) if slots else None
        warm_lat = [
            d["latency_ms"] for d in dispatches
            if d.get("n_cache_warm", 0) or d.get("n_page_warm", 0)
        ]
        cold_lat = [
            d["latency_ms"] for d in dispatches
            if not (d.get("n_cache_warm", 0) or d.get("n_page_warm", 0))
        ]
        emit(dict(summary, config=f"{arm}, {label}"), kind="serve")
        if pct is None:
            emit(
                {
                    "metric": f"serve_pad_waste ({arm}, {label})",
                    "value": None,
                    "unit": "percent",
                    "error": "no-requests-served",
                    "note": f"UNMEASURED: ragged sweep {arm} served nothing",
                },
                kind="error",
            )
            continue
        waste[arm] = pct
        emit(
            {
                "metric": f"serve_pad_waste ({arm}, {label})",
                "value": pct,
                "unit": "percent",
                "useful_tokens": useful,
                "slot_tokens": slots,
                "served": served,
            }
        )
        emit(
            {
                "metric": f"serve_levels0_h2d_bytes ({arm}, {label})",
                "value": summary["levels0_h2d_bytes"],
                "unit": "bytes",
                "n_page_warm": summary["n_page_warm"],
            }
        )
        for name, lat in (("warm", warm_lat), ("cold", cold_lat)):
            if lat:
                emit(
                    {
                        "metric": (
                            f"serve_{name}_dispatch_ms ({arm}, {label})"
                        ),
                        "value": round(sum(lat) / len(lat), 3),
                        "unit": "ms",
                        "n_dispatches": len(lat),
                    }
                )
        mean = summary.get("mean_executed_iters")
        if mean is not None:
            emit(
                {
                    "metric": f"serve_ragged_mean_iters ({arm}, {label})",
                    "value": mean,
                    "unit": "iters/request",
                }
            )
    if "bucket-ladder" in waste and "ragged-paged" in waste:
        # Informational (kind "note", not a gated bench row: a LARGER
        # saving is better, which the cost-unit heuristics would read
        # backwards — the per-arm serve_pad_waste rows are what gate).
        emit(
            {
                "note": "ragged pad-waste saving",
                "config": label,
                "saved_pct_points": round(
                    waste["bucket-ladder"] - waste["ragged-paged"], 2
                ),
                "bucket_ladder_pct": waste["bucket-ladder"],
                "ragged_paged_pct": waste["ragged-paged"],
            },
            kind="note",
        )
    return waste


def run_banded_ab(cfg, scfg, label: str, *, n_streams: int, n_frames: int,
                  perturb: float) -> dict:
    """Block-banded consensus vs the windowed gather, and aliased vs
    copy-on-write pool write-backs, over the SAME ragged streamed
    traffic (docs/SERVING.md, "Block-banded ragged consensus" / "Pool
    aliasing").

    Three arms serve identical mixed-resolution frame streams through
    the ragged paged route:

      * windowed     — the per-token W-fold k/v gather, CoW pool writes;
      * banded       — the per-page block-banded route, CoW pool writes;
      * banded-alias — banded attention + in-place pool aliasing.

    The measured numbers: `serve_ragged_peak_window_bytes` per arm (the
    duplicated k/v working set at the largest DISPATCHED signature —
    banded must sit strictly below windowed: the gate's cost row),
    `serve_ragged_max_signature_pages` per arm (the largest signature
    the windowed arm's top-of-ladder byte budget admits — it must
    strictly GROW under banded), `serve_pool_bytes_moved` per arm
    (aliased write-backs must move fewer bytes than CoW),
    `serve_levels0_h2d_bytes` per arm (zero on the pool warm path,
    aliasing or not), and the threshold-0 `serve_banded_parity` row: one
    mixed dispatch through both attentions, compared BITWISE on every
    row's page span — the 1.0-or-fail gate (unused trailing pages sit
    outside the contract; tests/test_banded_alias.py). Returns
    {arm: peak_window_bytes}."""
    import dataclasses

    import numpy as np

    from glom_tpu.serve.batcher import DynamicBatcher, ShedError
    from glom_tpu.serve.column_cache import column_state_bytes
    from glom_tpu.serve.early_exit import ragged_window_bytes
    from glom_tpu.serve.paged_columns import (
        pages_for_tokens,
        resolve_page_tokens,
    )
    from glom_tpu.telemetry.sinks import emit

    if scfg.iters != "auto":
        emit(
            {"note": "banded A/B skipped: the configured route is not "
             "iters='auto' (the ragged warm path needs the auto route)"},
            kind="note",
        )
        return {}
    rng = np.random.default_rng(17)
    p = cfg.patch_size
    side = cfg.image_size
    sizes = sorted(
        {max(p, (side * f // (4 * p)) * p) for f in (4, 3, 2)}, reverse=True
    )
    stream_size = [sizes[s % len(sizes)] for s in range(n_streams)]
    bases = [
        (100.0 * rng.normal(size=(cfg.channels, hw, hw))).astype(np.float32)
        for hw in stream_size
    ]
    frames = [
        [
            (bases[s] + perturb * rng.normal(size=bases[s].shape)).astype(
                np.float32
            )
            for _ in range(n_frames)
        ]
        for s in range(n_streams)
    ]

    pt = resolve_page_tokens(cfg, scfg)
    ppr = pages_for_tokens(cfg.num_patches, pt)
    window = ppr * pt
    itemsize = 2 if scfg.compute_dtype == "bfloat16" else 4
    cache_bytes = (n_streams + 1) * column_state_bytes(cfg, scfg)
    ragged_base = dict(
        ragged=True, page_pool_pages=(n_streams + 2) * ppr, page_tokens=pt,
        max_continuations=0, column_cache_bytes=cache_bytes,
    )
    arms = (
        ("windowed", dataclasses.replace(
            scfg, ragged_attention="windowed", **ragged_base)),
        ("banded", dataclasses.replace(
            scfg, ragged_attention="banded", **ragged_base)),
        ("banded-alias", dataclasses.replace(
            scfg, ragged_attention="banded", pool_aliasing=True,
            **ragged_base)),
    )
    peak: dict = {}
    for arm, arm_scfg in arms:
        attention = "windowed" if arm == "windowed" else "banded"
        engines = _make_engines(cfg, arm_scfg, 1)
        engine = engines[0]
        engine.warmup_ragged()
        top_pages = max(engine.ragged_page_buckets)
        served = 0
        with DynamicBatcher(engines=engines) as batcher:
            for f in range(n_frames):
                tickets = []
                for s in range(n_streams):
                    try:
                        tickets.append(
                            batcher.submit(frames[s][f], session_id=f"s{s}")
                        )
                    except ShedError:
                        continue
                for t in tickets:
                    try:
                        t.result(timeout=600.0)
                        served += 1
                    except Exception:
                        continue
            summary = batcher.summary_record()
            dispatches = list(batcher.dispatches)
        pool_rec = engine.pool.record() if engine.pool is not None else {}
        sig_pages = [d["n_pages"] for d in dispatches if d.get("ragged")]
        emit(dict(summary, config=f"{arm}, {label}"), kind="serve")
        if not sig_pages:
            emit(
                {
                    "metric": (
                        f"serve_ragged_peak_window_bytes ({arm}, {label})"
                    ),
                    "value": None,
                    "unit": "bytes",
                    "error": "no-requests-served",
                    "note": f"UNMEASURED: banded A/B {arm} served nothing",
                },
                kind="error",
            )
            continue
        peak[arm] = ragged_window_bytes(
            max(sig_pages) * pt, window, cfg.levels, cfg.dim, itemsize,
            pt, attention=attention,
        )
        emit(
            {
                "metric": (
                    f"serve_ragged_peak_window_bytes ({arm}, {label})"
                ),
                "value": peak[arm],
                "unit": "bytes",
                "peak_signature_pages": max(sig_pages),
                "window": window,
                "served": served,
            }
        )
        # The admission headroom the smaller working set buys: the
        # largest signature whose duplicated k/v set still fits the
        # WINDOWED route's budget at its top-of-ladder signature. Both
        # routes are linear in the page count, so one page prices the
        # whole ladder.
        budget = ragged_window_bytes(
            top_pages * pt, window, cfg.levels, cfg.dim, itemsize, pt,
            attention="windowed",
        )
        per_page = ragged_window_bytes(
            pt, window, cfg.levels, cfg.dim, itemsize, pt,
            attention=attention,
        )
        emit(
            {
                "metric": (
                    f"serve_ragged_max_signature_pages ({arm}, {label})"
                ),
                "value": budget // per_page,
                "unit": "pages",
                "byte_budget": budget,
                "bytes_per_page": per_page,
            }
        )
        emit(
            {
                "metric": f"serve_levels0_h2d_bytes ({arm}, {label})",
                "value": summary["levels0_h2d_bytes"],
                "unit": "bytes",
                "n_page_warm": summary["n_page_warm"],
            }
        )
        alias = pool_rec.get("alias") or {}
        emit(
            {
                "metric": f"serve_pool_bytes_moved ({arm}, {label})",
                "value": (
                    pool_rec.get("cow_bytes_moved", 0)
                    + alias.get("alias_bytes_moved", 0)
                ),
                "unit": "bytes",
                "cow_bytes_moved": pool_rec.get("cow_bytes_moved", 0),
                "alias_bytes_moved": alias.get("alias_bytes_moved", 0),
                "n_alias_fallbacks": alias.get("n_alias_fallbacks", 0),
                "alias_rate": alias.get("alias_rate"),
                "n_writebacks": pool_rec.get("n_writebacks", 0),
            }
        )

    # Threshold-0 parity probe: ONE mixed dispatch through both
    # attentions (fresh engines, identical default params), bitwise on
    # every row's page span — CI reads this row as a 1.0-or-fail gate.
    ew = _make_engines(
        cfg,
        dataclasses.replace(scfg, ragged_attention="windowed", **ragged_base),
        1,
    )[0]
    eb = _make_engines(
        cfg,
        dataclasses.replace(scfg, ragged_attention="banded", **ragged_base),
        1,
    )[0]
    counts = [cfg.num_patches, max(1, cfg.num_patches // 4)]
    pages = [pages_for_tokens(c, pt) for c in counts]
    T = ew.pick_pages(sum(pages)) * pt
    flat = np.zeros((T, cfg.patch_dim), np.float32)
    starts, off = [], 0
    for c, k in zip(counts, pages):
        starts.append(off * pt)
        flat[off * pt:off * pt + c] = rng.normal(size=(c, cfg.patch_dim))
        off += k
    rw = ew.infer_ragged(flat, counts, iters_override=2)
    rb = eb.infer_ragged(flat, counts, iters_override=2)
    lw, lb = np.asarray(rw.levels), np.asarray(rb.levels)
    bitwise = all(
        np.array_equal(lw[s:s + k * pt], lb[s:s + k * pt])
        for s, k in zip(starts, pages)
    )
    emit(
        {
            "metric": f"serve_banded_parity ({label})",
            "value": 1.0 if bitwise else 0.0,
            "unit": "bool",
            "note": "threshold-0 banded vs windowed mixed dispatch, "
            "bitwise on every row's page span",
            "counts": counts,
        }
    )
    if "windowed" in peak and "banded" in peak:
        # Informational (kind "note"): the per-arm rows are what gate.
        emit(
            {
                "note": "banded working-set saving",
                "config": label,
                "windowed_peak_bytes": peak["windowed"],
                "banded_peak_bytes": peak["banded"],
                "fold": round(
                    peak["windowed"] / max(peak["banded"], 1), 1
                ),
            },
            kind="note",
        )
    return peak


def run_ramp(cfg, scfg, label: str, *, profile: str = "4x100,56x0,12x200",
             max_engines: int = 2) -> dict:
    """The ELASTIC ramp (docs/SERVING.md "Elastic serving"): an
    offered-load ramp (low -> spike -> low) driven through the real
    autoscaler. The fleet starts at ONE engine; the spike must force a
    scale-out (spawn + warmup off the hot path + admission), the
    post-spike calm a scale-in (graceful drain + device release) — and
    every ticket must resolve: the bench ASSERTS tickets-conserved
    (served+shed+failed == requests with failed == 0) and emits the
    fleet-size TIMELINE row the CI elastic gate reads:

      * serve_ramp_n_engines_peak (count; the timeline rides the row);
      * serve_ramp_spawn_ms (ms — the scale-out's off-hot-path price);
      * serve_ramp_p99 (spike | tail, ms) — recovery made a number;
      * serve_ramp_tickets_conserved (1.0 only when conservation held).
    """
    import dataclasses

    from glom_tpu.serve.batcher import DynamicBatcher, ShedError
    from glom_tpu.serve.cli import parse_ramp
    from glom_tpu.serve.elastic import Autoscaler, resolve_policy
    from glom_tpu.serve.engine import InferenceEngine
    from glom_tpu.telemetry.sinks import emit

    import numpy as np

    phases = parse_ramp(profile)
    scfg = dataclasses.replace(
        scfg,
        elastic=True, min_engines=1, max_engines=max_engines,
        elastic_low_water=0.5, elastic_high_water=0.8,
        elastic_dwell_s=0.1, elastic_cooldown_s=0.5,
        elastic_window_s=2.0, elastic_interval_s=0.05,
        elastic_p99_ms=100.0,
    )
    engines = _make_engines(cfg, scfg, 1)
    params = engines[0].params
    for eng in engines:
        eng.warmup()
    rng = np.random.default_rng(7)
    shape = (cfg.channels, cfg.image_size, cfg.image_size)
    seq = [len(engines)]

    def factory():
        i = seq[0]
        eng = InferenceEngine(cfg, scfg, params=params, name=f"engine{i}")
        seq[0] += 1
        return eng

    lat_by_phase: dict = {}
    n_total = sum(n for n, _ in phases)
    with DynamicBatcher(engines=engines) as batcher:
        # The ramp is a FORECASTED run (ISSUE 17 acceptance): every
        # closed window stamps a "forecast" record carrying its
        # predicted-vs-realized error — the evidence PR 18's
        # anticipatory policy will consume.
        from glom_tpu.telemetry.forecast import ForecastEmitter

        batcher.enable_admission_events()
        forecaster = ForecastEmitter(
            lambda r: emit(r, kind="forecast"),
            interval_s=0.25, window_s=2.0, horizon_s=0.5,
        )
        batcher.add_event_tap(forecaster.tap)
        scaler = Autoscaler(
            batcher, factory, policy=resolve_policy(scfg),
            rules={"p99_ms": scfg.elastic_p99_ms},
            interval_s=scfg.elastic_interval_s,
        ).start()
        try:
            tickets = []
            for phase, (n, gap) in enumerate(phases):
                for _ in range(n):
                    if gap and tickets:
                        time.sleep(gap)
                    try:
                        # HARD traffic (100x scale — the convergence-
                        # depth lever): every request runs near the full
                        # budget, so the spike actually queues instead
                        # of evaporating on a fast host.
                        tickets.append(
                            (phase, batcher.submit(
                                (100.0 * rng.normal(size=shape)).astype(
                                    np.float32
                                )
                            ))
                        )
                    except ShedError:
                        tickets.append((phase, None))
            for phase, t in tickets:
                if t is None:
                    continue
                try:
                    _, _, latency_s = t.result(timeout=600.0)
                except Exception:
                    continue
                lat_by_phase.setdefault(phase, []).append(1e3 * latency_s)
            # Settle: wait (bounded) for the post-spike scale-in.
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                if scaler.record()["n_scale_ins"] >= 1:
                    break
                time.sleep(0.05)
        finally:
            scaler.stop()
        forecaster.close()
        summary = batcher.summary_record()
    el = summary.get("elastic") or {}
    conserved = (
        summary["n_served"] + summary["n_shed"] + summary["n_failed"]
        == summary["n_requests"] == n_total
        and summary["n_failed"] == 0
    )
    emit(
        {
            "event": "ramp_summary",
            "config": label,
            "profile": profile,
            "n_requests": n_total,
            "n_served": summary["n_served"],
            "n_shed": summary["n_shed"],
            "n_failed": summary["n_failed"],
            "elastic": el,
        },
        kind="serve",
    )
    emit(
        {
            "metric": f"serve_ramp_n_engines_peak ({label})",
            "value": el.get("n_engines_peak"),
            "unit": "count",
            "n_scale_outs": el.get("n_scale_outs"),
            "n_scale_ins": el.get("n_scale_ins"),
            # THE timeline row: [t_rel_s, n_engines] per fleet change —
            # capacity following load, as data (perfetto renders it as
            # the fleet counter track).
            "timeline": el.get("timeline"),
        }
    )
    if el.get("spawn_ms_mean") is not None:
        emit(
            {
                "metric": f"serve_ramp_spawn_ms ({label})",
                "value": el["spawn_ms_mean"],
                "unit": "ms",
                "spawn_ms_max": el.get("spawn_ms_max"),
            }
        )
    q = lambda xs, f: sorted(xs)[min(len(xs) - 1, int(f * len(xs)))]
    spike = lat_by_phase.get(1, [])
    tail_all = lat_by_phase.get(len(phases) - 1, [])
    # Steady-state half = the CHRONOLOGICALLY later half (tail_all is in
    # submission order): the first tail requests are submitted while the
    # spike backlog still drains, and their latency is the spike's
    # shadow, not the scaled fleet's.
    tail = tail_all[len(tail_all) // 2:]
    for arm, vals in (("spike", spike), ("tail", tail)):
        if vals:
            emit(
                {
                    "metric": f"serve_ramp_p99 ({arm}, {label})",
                    "value": round(q(vals, 0.99), 3),
                    "unit": "ms",
                    "n": len(vals),
                }
            )
    emit(
        {
            "metric": f"serve_ramp_tickets_conserved ({label})",
            "value": 1.0 if conserved else 0.0,
            "unit": "count",
        }
    )
    assert conserved, (
        "ramp tickets NOT conserved: "
        f"{ {k: summary[k] for k in ('n_requests', 'n_served', 'n_shed', 'n_failed')} }"
    )
    return {
        "elastic": el,
        "conserved": conserved,
        "p99_spike": q(spike, 0.99) if spike else None,
        "p99_tail": q(tail, 0.99) if tail else None,
    }


def run_workload(cfg, scfg, label: str, records, *, source: str,
                 time_scale: float = 1.0, workload_out=None,
                 max_engines: int = 2, hard: bool = True) -> dict:
    """Drive a WORKLOAD artifact through the real elastic stack
    (docs/SERVING.md "Record and replay"): re-offer the records with
    faithful inter-arrival pacing, score a live load forecast on every
    window, and assert ticket conservation — the workload observatory's
    end-to-end gate. `records` come from a recorded run
    (--replay FILE) or a scenario generator (--scenario NAME); either
    way the run emits:

      * "forecast" rows (kind forecast) with forecast_abs_err stamped
        on EVERY window — finite once predictions mature;
      * serve_workload_pacing_lag (ms) — how late the replay offered
        vs the artifact's arrival times;
      * serve_workload_forecast_abs_err (rps) — the matured mean;
      * serve_workload_n_engines_peak (count, with the timeline) and
        serve_workload_tickets_conserved — the elastic gate pair;
      * optionally re-records ITS OWN offered traffic to workload_out,
        closing the record -> replay -> record loop.
    """
    import dataclasses

    from glom_tpu.serve import workload as wl
    from glom_tpu.serve.batcher import DynamicBatcher, ShedError
    from glom_tpu.serve.elastic import Autoscaler, resolve_policy
    from glom_tpu.serve.engine import InferenceEngine
    from glom_tpu.telemetry.forecast import ForecastEmitter
    from glom_tpu.telemetry.sinks import emit

    scfg = dataclasses.replace(
        scfg,
        elastic=True, min_engines=1, max_engines=max_engines,
        elastic_low_water=0.5, elastic_high_water=0.8,
        elastic_dwell_s=0.1, elastic_cooldown_s=0.5,
        elastic_window_s=2.0, elastic_interval_s=0.05,
        elastic_p99_ms=100.0,
    )
    engines = _make_engines(cfg, scfg, 1)
    params = engines[0].params
    for eng in engines:
        eng.warmup()
    seq = [len(engines)]

    def factory():
        i = seq[0]
        eng = InferenceEngine(cfg, scfg, params=params, name=f"engine{i}")
        seq[0] += 1
        return eng

    n_total = len(records)
    signatures = []
    with DynamicBatcher(engines=engines) as batcher:
        recorder = wl.WorkloadRecorder().attach(batcher)
        forecaster = ForecastEmitter(
            lambda r: emit(r, kind="forecast"),
            interval_s=0.25, window_s=2.0, horizon_s=0.5,
        )
        batcher.add_event_tap(forecaster.tap)
        scaler = Autoscaler(
            batcher, factory, policy=resolve_policy(scfg),
            rules={"p99_ms": scfg.elastic_p99_ms},
            interval_s=scfg.elastic_interval_s,
        ).start()
        try:
            tickets = []

            def offer(rec, i):
                signatures.append(rec.get("signature"))
                img = wl.synth_input(rec, i)
                if hard:
                    # HARD traffic (the ramp's 100x convergence-depth
                    # lever): the replayed load must queue, not
                    # evaporate, or the autoscaler has nothing to do.
                    img = 100.0 * img
                try:
                    tickets.append(
                        batcher.submit(img, session_id=rec.get("session"))
                    )
                except ShedError:
                    raise  # replay() counts it; traffic drives on

            stats = wl.replay(records, offer, time_scale=time_scale)
            for t in tickets:
                try:
                    t.result(timeout=600.0)
                except Exception:  # noqa: BLE001 — summary counts it
                    pass
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                if scaler.record()["n_scale_ins"] >= 1:
                    break
                time.sleep(0.05)
        finally:
            scaler.stop()
        forecaster.close()
        summary = batcher.summary_record()
        captured = recorder.records()
        if workload_out:
            recorder.write(workload_out, source=f"bench:{source}")
    el = summary.get("elastic") or {}
    conserved = (
        summary["n_served"] + summary["n_shed"] + summary["n_failed"]
        == summary["n_requests"] == n_total
        and summary["n_failed"] == 0
    )
    # The determinism contract (ISSUE 17 acceptance): the replayed
    # stream re-offers the artifact's signature sequence EXACTLY —
    # what the recorder captured must match what the artifact said.
    sig_match = signatures == [r.get("signature") for r in records] and (
        [c.get("signature") for c in captured] == signatures
    )
    emit(
        {
            "event": "workload_summary",
            "config": label,
            "source": source,
            "n_requests": n_total,
            "n_served": summary["n_served"],
            "n_shed": summary["n_shed"],
            "n_failed": summary["n_failed"],
            "signature_sequence_match": sig_match,
            "forecast_windows": forecaster.n_windows,
            "elastic": el,
            **stats,
        },
        kind="serve",
    )
    emit(
        {
            "metric": f"serve_workload_pacing_lag ({source}, {label})",
            "value": stats["pacing_lag_mean_ms"],
            "unit": "ms",
            "pacing_lag_max_ms": stats["pacing_lag_max_ms"],
        }
    )
    mae = forecaster.forecaster._err_sum / forecaster.forecaster._n_scored \
        if forecaster.forecaster._n_scored else None
    if mae is not None:
        emit(
            {
                "metric": (
                    f"serve_workload_forecast_abs_err ({source}, {label})"
                ),
                "value": round(mae, 4),
                "unit": "rps",
                "n_scored": forecaster.forecaster._n_scored,
                "n_windows": forecaster.n_windows,
            }
        )
    lead = forecaster.lead_model.lead_time_ms()
    if lead is not None:
        emit(
            {
                "metric": f"serve_workload_lead_time_ms ({source}, {label})",
                "value": lead,
                "unit": "ms",
                "n_spawns": len(forecaster.lead_model._samples),
            }
        )
    emit(
        {
            "metric": f"serve_workload_n_engines_peak ({source}, {label})",
            "value": el.get("n_engines_peak"),
            "unit": "count",
            "timeline": el.get("timeline"),
        }
    )
    emit(
        {
            "metric": (
                f"serve_workload_tickets_conserved ({source}, {label})"
            ),
            "value": 1.0 if (conserved and sig_match) else 0.0,
            "unit": "count",
        }
    )
    assert conserved, (
        "workload tickets NOT conserved: "
        f"{ {k: summary[k] for k in ('n_requests', 'n_served', 'n_shed', 'n_failed')} }"
    )
    assert sig_match, (
        "replayed signature sequence diverged from the artifact "
        f"(offered {len(signatures)}, recorded {len(captured)}, "
        f"artifact {n_total})"
    )
    return {
        "elastic": el,
        "conserved": conserved,
        "stats": stats,
        "n_forecast_windows": forecaster.n_windows,
    }


def run_elastic_ab(cfg, scfg, label: str, records, *, source: str,
                   time_scale: float = 1.0, out_prefix: str = "elastic_ab",
                   max_engines: int = 2, gate: bool = False) -> dict:
    """Anticipatory-vs-reactive autoscaling A/B over ONE replayed
    workload artifact (docs/SERVING.md "Anticipatory autoscaling"): the
    same records drive two independent fleets —

      * reactive       — the PR 14 baseline (no forecast wired, no
                         warm pool); and
      * anticipatory   — the PR 18 policy (forecast + spawn-lead-time
                         model + one warm-pool spare),

    each writing its decisions, serve events, and forecasts to its OWN
    JSONL file ({out_prefix}_{arm}.jsonl) so the decision chains stay
    per-arm and `python -m glom_tpu.telemetry audit` scores each arm's
    counterfactual regret independently. Emits per-arm
    serve_elastic_ab_p99 / _failed / _regret rows plus the deltas
    (anticipatory minus reactive; negative = anticipation won). With
    gate=True (the flash-crowd CI gate) the run ASSERTS the
    anticipatory arm shed-or-failed no more tickets AND landed a
    strictly lower p99 than the reactive arm.
    """
    import dataclasses

    from glom_tpu.serve import workload as wl
    from glom_tpu.serve.batcher import DynamicBatcher, ShedError
    from glom_tpu.serve.elastic import Autoscaler, resolve_policy
    from glom_tpu.serve.engine import InferenceEngine
    from glom_tpu.serve.events import stamp_serve
    from glom_tpu.telemetry import schema
    from glom_tpu.telemetry.audit import audit_records, load_records
    from glom_tpu.telemetry.forecast import ForecastEmitter
    from glom_tpu.telemetry.sinks import emit
    from glom_tpu.utils.metrics import MetricsWriter

    scfg_base = dataclasses.replace(
        scfg,
        elastic=True, min_engines=1, max_engines=max_engines,
        elastic_low_water=0.5, elastic_high_water=0.8,
        elastic_dwell_s=0.1, elastic_cooldown_s=0.5,
        elastic_window_s=2.0, elastic_interval_s=0.05,
        elastic_p99_ms=100.0,
    )
    n_total = len(records)
    q = lambda xs, f: sorted(xs)[min(len(xs) - 1, int(f * len(xs)))]

    def _arm(arm: str, *, anticipatory: bool, warm_pool: int) -> dict:
        scfg_arm = dataclasses.replace(
            scfg_base,
            elastic_anticipatory=anticipatory,
            warm_pool=warm_pool,
        )
        path = f"{out_prefix}_{arm}.jsonl"
        writer = MetricsWriter(path, echo=False)
        engines = _make_engines(cfg, scfg_arm, 1)
        params = engines[0].params
        for eng in engines:
            eng.warmup()
        seq = [len(engines)]

        def factory():
            i = seq[0]
            eng = InferenceEngine(
                cfg, scfg_arm, params=params, name=f"engine{i}"
            )
            seq[0] += 1
            return eng

        latencies: list = []
        with DynamicBatcher(engines=engines, writer=writer) as batcher:
            batcher.enable_admission_events()
            forecaster = ForecastEmitter(
                lambda r: writer.write(
                    schema.stamp(dict(r), kind="forecast")
                ),
                # A 1 s window matures the fit within the scenario's
                # pre-crowd base phase; the 2 s default never closes
                # enough scored windows before the burst lands.
                interval_s=0.25, window_s=1.0, horizon_s=0.5,
            )
            batcher.add_event_tap(forecaster.tap)
            scaler = Autoscaler(
                batcher, factory, policy=resolve_policy(scfg_arm),
                rules={"p99_ms": scfg_arm.elastic_p99_ms},
                writer=writer,
                interval_s=scfg_arm.elastic_interval_s,
                # The reactive arm IS the PR 14 baseline: no forecast
                # wired even though the emitter runs (its rows score the
                # counterfactual), no spares.
                forecast=forecaster if anticipatory else None,
                warm_pool=warm_pool,
                fleet=arm,
            ).start()
            try:
                tickets = []

                def offer(rec, i):
                    # HARD traffic, same 100x lever as run_workload: the
                    # crowd must queue or neither arm has anything to do.
                    img = 100.0 * wl.synth_input(rec, i)
                    tickets.append(
                        batcher.submit(img, session_id=rec.get("session"))
                    )

                stats = wl.replay(records, offer, time_scale=time_scale)
                for t in tickets:
                    try:
                        _, _, latency_s = t.result(timeout=600.0)
                        latencies.append(1e3 * latency_s)
                    except Exception:  # noqa: BLE001 — summary counts it
                        pass
            finally:
                scaler.stop()
            forecaster.close()
            srec = scaler.record()
            summary = batcher.summary_record()
            writer.write(stamp_serve(dict(summary)))
        writer.close()
        audit = audit_records(load_records(path))
        assert not audit["errors"], (
            f"{arm} arm decision chain failed its own audit: "
            f"{audit['errors'][:3]}"
        )
        failed = summary["n_shed"] + summary["n_failed"]
        return {
            "arm": arm,
            "path": path,
            "p99_ms": round(q(latencies, 0.99), 3) if latencies else None,
            "n_served": summary["n_served"],
            "failed": failed,
            "regret": audit["regret_total"],
            "regret_per_decision": audit["regret_per_decision"],
            "n_decisions": srec["n_decisions"],
            "decisions_late": srec["decisions_late"],
            "spawn_lead_violations": srec["spawn_lead_violations"],
            "n_promotions": srec["n_promotions"],
            "pacing_lag_mean_ms": stats["pacing_lag_mean_ms"],
            "conserved": (
                summary["n_served"] + summary["n_shed"]
                + summary["n_failed"] == summary["n_requests"] == n_total
            ),
        }

    arms = {
        "reactive": _arm("reactive", anticipatory=False, warm_pool=0),
        "anticipatory": _arm("anticipatory", anticipatory=True,
                             warm_pool=1),
    }
    emit(
        {
            "event": "elastic_ab_summary",
            "config": label,
            "source": source,
            "n_requests": n_total,
            "arms": arms,
        },
        kind="serve",
    )
    for arm, r in arms.items():
        if r["p99_ms"] is not None:
            emit(
                {
                    "metric": f"serve_elastic_ab_p99 ({arm}, {source}, "
                              f"{label})",
                    "value": r["p99_ms"],
                    "unit": "ms",
                    "n": r["n_served"],
                }
            )
        emit(
            {
                "metric": f"serve_elastic_ab_failed ({arm}, {source}, "
                          f"{label})",
                "value": r["failed"],
                "unit": "count",
            }
        )
        emit(
            {
                "metric": f"serve_elastic_ab_regret ({arm}, {source}, "
                          f"{label})",
                "value": r["regret"],
                "unit": "count",
                "regret_per_decision": r["regret_per_decision"],
                "n_decisions": r["n_decisions"],
                "decisions_late": r["decisions_late"],
                "spawn_lead_violations": r["spawn_lead_violations"],
                "log": r["path"],
            }
        )
    rx, ax = arms["reactive"], arms["anticipatory"]
    if rx["p99_ms"] is not None and ax["p99_ms"] is not None:
        emit(
            {
                "metric": f"serve_elastic_ab_p99_delta ({source}, {label})",
                "value": round(ax["p99_ms"] - rx["p99_ms"], 3),
                "unit": "ms",
            }
        )
    emit(
        {
            "metric": f"serve_elastic_ab_failed_delta ({source}, {label})",
            "value": ax["failed"] - rx["failed"],
            "unit": "count",
        }
    )
    emit(
        {
            "metric": f"serve_elastic_ab_regret_delta ({source}, {label})",
            "value": round(ax["regret"] - rx["regret"], 6),
            "unit": "count",
        }
    )
    assert rx["conserved"] and ax["conserved"], (
        f"elastic A/B tickets NOT conserved: reactive={rx}, "
        f"anticipatory={ax}"
    )
    if gate:
        assert ax["failed"] <= rx["failed"], (
            "anticipatory arm shed/failed MORE tickets than reactive: "
            f"{ax['failed']} > {rx['failed']}"
        )
        assert (
            ax["p99_ms"] is not None and rx["p99_ms"] is not None
            and ax["p99_ms"] < rx["p99_ms"]
        ), (
            "anticipatory arm did not beat reactive p99: "
            f"{ax['p99_ms']} vs {rx['p99_ms']}"
        )
    return arms


def run_qos_ab(cfg, scfg, label: str, records, *, source: str,
               time_scale: float = 1.0, out_prefix: str = "qos_ab",
               max_engines: int = 2, gate: bool = False) -> dict:
    """Classless-vs-QoS serving A/B over ONE mixed-class flash-crowd
    artifact (docs/SERVING.md "SLO classes"): the same records drive two
    independent elastic fleets —

      * classless — one shared FIFO queue (the PR 18 baseline); every
                    submit still CARRIES its recorded slo_class label,
                    so per-class latency attributes on both sides; and
      * qos       — three declared SLO classes (premium/standard/batch,
                    8/2/1 weights, per-class lanes partitioning the SAME
                    total queue depth) through the deficit-weighted-fair
                    scheduler, class-aware shed, and class-scoped
                    monitor rules,

    each writing its decision chain to its own JSONL ({out_prefix}_
    {arm}.jsonl) and audited STRICTLY (errors AND warnings fail — the
    acceptance bar). Emits per-(arm, class) p99 / served-fraction /
    shed rows plus the premium-p99 delta. Both arms must conserve
    tickets EXACTLY per class. With gate=True the run additionally
    ASSERTS premium p99 strictly below the classless baseline and the
    batch served fraction at or above the starvation floor.
    """
    import dataclasses

    from glom_tpu.serve import workload as wl
    from glom_tpu.serve.batcher import DynamicBatcher
    from glom_tpu.serve.elastic import Autoscaler, resolve_policy
    from glom_tpu.serve.engine import InferenceEngine
    from glom_tpu.serve.events import stamp_serve
    from glom_tpu.serve.qos import class_slo_rules, resolve_slo_classes
    from glom_tpu.telemetry.audit import audit_records, load_records
    from glom_tpu.telemetry.sinks import emit
    from glom_tpu.utils.metrics import MetricsWriter

    scfg_base = dataclasses.replace(
        scfg,
        elastic=True, min_engines=1, max_engines=max_engines,
        elastic_low_water=0.5, elastic_high_water=0.8,
        elastic_dwell_s=0.1, elastic_cooldown_s=0.5,
        elastic_window_s=2.0, elastic_interval_s=0.05,
        elastic_p99_ms=100.0,
    )
    # The QoS arm's lanes PARTITION the classless arm's queue depth —
    # identical total admission capacity, so the A/B isolates the
    # scheduler, not a bigger buffer.
    qd = scfg_base.queue_depth
    floor = 0.1
    qos_classes = (
        f"premium:weight=8,p99_ms={scfg_base.elastic_p99_ms},"
        f"queue_depth={max(1, qd // 2)}",
        f"standard:weight=2,queue_depth={max(1, qd // 4)}",
        f"batch:weight=1,queue_depth={max(1, qd - qd // 2 - qd // 4)}",
    )
    n_total = len(records)
    qtile = lambda xs, f: sorted(xs)[min(len(xs) - 1, int(f * len(xs)))]

    def _arm(arm: str, *, classed: bool) -> dict:
        scfg_arm = (
            dataclasses.replace(
                scfg_base,
                slo_classes=qos_classes,
                slo_starvation_floor=floor,
            )
            if classed else scfg_base
        )
        path = f"{out_prefix}_{arm}.jsonl"
        writer = MetricsWriter(path, echo=False)
        engines = _make_engines(cfg, scfg_arm, 1)
        params = engines[0].params
        for eng in engines:
            eng.warmup()
        seq = [len(engines)]

        def factory():
            i = seq[0]
            eng = InferenceEngine(
                cfg, scfg_arm, params=params, name=f"engine{i}"
            )
            seq[0] += 1
            return eng

        rules = {"p99_ms": scfg_arm.elastic_p99_ms}
        if classed:
            rules.update(class_slo_rules(resolve_slo_classes(scfg_arm)))
        lat_by_class: dict = {}
        with DynamicBatcher(engines=engines, writer=writer) as batcher:
            batcher.enable_admission_events()
            scaler = Autoscaler(
                batcher, factory, policy=resolve_policy(scfg_arm),
                rules=rules,
                writer=writer,
                interval_s=scfg_arm.elastic_interval_s,
                fleet=arm,
            ).start()
            try:
                tickets = []

                def offer(rec, i):
                    # HARD traffic, the same 100x lever as the elastic
                    # A/B: the crowd must queue or the scheduler has
                    # nothing to arbitrate. A ShedError propagates to
                    # replay(), which counts it and drives on — the
                    # batcher already attributed it to the class.
                    img = 100.0 * wl.synth_input(rec, i)
                    cls = rec.get("slo_class")
                    tickets.append(
                        (cls, batcher.submit(
                            img,
                            session_id=rec.get("session"),
                            slo_class=cls,
                        ))
                    )

                wl.replay(records, offer, time_scale=time_scale)
                for cls, t in tickets:
                    try:
                        _, _, latency_s = t.result(timeout=600.0)
                        lat_by_class.setdefault(cls, []).append(
                            1e3 * latency_s
                        )
                    except Exception:  # noqa: BLE001 — summary counts it
                        pass
            finally:
                scaler.stop()
            summary = batcher.summary_record()
            writer.write(stamp_serve(dict(summary)))
        writer.close()
        audit = audit_records(load_records(path))
        # The acceptance bar is `telemetry audit --strict`: structural
        # errors AND warnings (un-actuated decisions) both fail.
        assert not audit["errors"] and not audit["warnings"], (
            f"{arm} arm failed its strict audit: "
            f"{(audit['errors'] + audit['warnings'])[:3]}"
        )
        classes = summary.get("classes") or {}
        for cls, cnt in classes.items():
            # EXACT per-class ticket conservation — every admitted
            # request settles under the class it was admitted with,
            # across sheds, failover, and continuations.
            assert (
                cnt["n_served"] + cnt["n_shed"] + cnt["n_failed"]
                == cnt["n_requests"]
            ), f"{arm} arm class {cls!r} tickets NOT conserved: {cnt}"
        assert (
            sum(c["n_requests"] for c in classes.values())
            == summary["n_requests"] == n_total
        ), (
            f"{arm} arm class rows do not cover the offered load: "
            f"{classes} vs {n_total}"
        )
        return {
            "arm": arm,
            "path": path,
            "p99_ms": {
                cls: round(qtile(ls, 0.99), 3)
                for cls, ls in sorted(lat_by_class.items())
                if ls
            },
            "classes": classes,
            "regret": audit["regret_total"],
            "regret_weighted": audit["regret_weighted"],
            "n_decisions": audit["n_decisions"],
        }

    arms = {
        "classless": _arm("classless", classed=False),
        "qos": _arm("qos", classed=True),
    }
    emit(
        {
            "event": "qos_ab_summary",
            "config": label,
            "source": source,
            "n_requests": n_total,
            "starvation_floor": floor,
            "arms": arms,
        },
        kind="serve",
    )
    for arm, r in arms.items():
        for cls, p99 in r["p99_ms"].items():
            emit(
                {
                    "metric": f"serve_qos_ab_p99 ({cls}, {arm}, "
                              f"{source}, {label})",
                    "value": p99,
                    "unit": "ms",
                }
            )
        for cls, cnt in sorted(r["classes"].items()):
            if cnt.get("served_fraction") is not None:
                emit(
                    {
                        "metric": "serve_qos_ab_served_fraction "
                                  f"({cls}, {arm}, {source}, {label})",
                        "value": cnt["served_fraction"],
                        "unit": "fraction",
                    }
                )
            emit(
                {
                    "metric": f"serve_qos_ab_shed ({cls}, {arm}, "
                              f"{source}, {label})",
                    "value": cnt["n_shed"],
                    "unit": "count",
                }
            )
        emit(
            {
                "metric": f"serve_qos_ab_regret_weighted ({arm}, "
                          f"{source}, {label})",
                "value": r["regret_weighted"],
                "unit": "count",
                "n_decisions": r["n_decisions"],
                "log": r["path"],
            }
        )
    base, qos = arms["classless"], arms["qos"]
    prem_base = base["p99_ms"].get("premium")
    prem_qos = qos["p99_ms"].get("premium")
    if prem_base is not None and prem_qos is not None:
        emit(
            {
                "metric": f"serve_qos_ab_premium_p99_delta ({source}, "
                          f"{label})",
                "value": round(prem_qos - prem_base, 3),
                "unit": "ms",
            }
        )
    if gate:
        assert prem_base is not None and prem_qos is not None, (
            "qos A/B gate needs premium latencies on both arms: "
            f"classless={prem_base}, qos={prem_qos}"
        )
        assert prem_qos < prem_base, (
            "QoS arm did not beat the classless premium p99: "
            f"{prem_qos} vs {prem_base}"
        )
        batch_served = (qos["classes"].get("batch") or {}).get(
            "served_fraction"
        )
        assert batch_served is not None and batch_served >= floor, (
            "QoS arm starved the batch class below its floor: "
            f"served_fraction={batch_served} < {floor}"
        )
    return arms


def run_trace_ab(cfg, scfg, label: str, *, n_requests: int,
                 n_engines: int = 1, repeats: int = 3) -> dict:
    """Request-tracing overhead A/B (docs/OBSERVABILITY.md, Request
    tracing): the same closed-loop traffic served with trace stamping ON
    (ids minted per submit, per-dispatch scope, per-request resolve
    leaves) vs OFF (context keys stamp as null, no resolve leaves), both
    arms writing through a real MetricsWriter so serialization is priced.
    Arms alternate per repeat and each keeps its BEST mean (min-of-noise,
    the bench convention), emitting `serve_trace_mean_latency` per arm
    and `serve_trace_overhead` in percent — the <2% bar run_hw_queue's
    step 9g gates. Returns {arm: mean_ms}."""
    import numpy as np

    from glom_tpu.serve.batcher import DynamicBatcher, ShedError
    from glom_tpu.telemetry.sinks import emit
    from glom_tpu.utils.metrics import MetricsWriter

    rng = np.random.default_rng(3)
    shape = (cfg.channels, cfg.image_size, cfg.image_size)
    imgs = [
        rng.normal(size=shape).astype(np.float32) for _ in range(n_requests)
    ]
    # ONE engine set serves both arms: tracing is purely host-side, and a
    # per-arm engine would hand the A/B a compiled-program / allocator
    # state difference far larger than the stamping cost being measured.
    engines = _make_engines(cfg, scfg, n_engines)
    for eng in engines:
        eng.warmup()
    window = max(1, min(scfg.queue_depth // 2, 16))
    best: dict = {}
    for rep in range(repeats + 1):
        for arm, flag in (("trace-off", False), ("trace-on", True)):
            writer = MetricsWriter(None, echo=False)
            lat = []
            with DynamicBatcher(
                engines=engines, writer=writer, trace=flag
            ) as batcher:
                for start in range(0, n_requests, window):
                    tickets = []
                    for i in range(start, min(start + window, n_requests)):
                        try:
                            tickets.append(batcher.submit(imgs[i]))
                        except ShedError:
                            continue
                    for t in tickets:
                        try:
                            _, _, latency_s = t.result(timeout=600.0)
                        except Exception:
                            continue
                        lat.append(latency_s)
            writer.close()
            if rep == 0:
                continue  # warm-up pass: first-touch noise, not data
            if lat:
                mean_ms = 1e3 * sum(lat) / len(lat)
                if arm not in best or mean_ms < best[arm]:
                    best[arm] = mean_ms
    for arm in ("trace-off", "trace-on"):
        if arm in best:
            emit(
                {
                    "metric": f"serve_trace_mean_latency ({arm}, {label})",
                    "value": round(best[arm], 4),
                    "unit": "ms",
                    "requests": n_requests,
                    "repeats": repeats,
                }
            )
        else:
            emit(
                {
                    "metric": f"serve_trace_mean_latency ({arm}, {label})",
                    "value": None,
                    "unit": "ms",
                    "error": "no-requests-served",
                    "note": f"UNMEASURED: trace A/B {arm} arm served nothing",
                },
                kind="error",
            )
    if "trace-off" in best and "trace-on" in best and best["trace-off"] > 0:
        overhead = 100.0 * (best["trace-on"] - best["trace-off"]) / best[
            "trace-off"
        ]
        emit(
            {
                "metric": f"serve_trace_overhead ({label})",
                "value": round(overhead, 2),
                "unit": "percent",
                "trace_off_ms": round(best["trace-off"], 4),
                "trace_on_ms": round(best["trace-on"], 4),
                "budget_percent": 2.0,
            }
        )
    return best


def run_phase_ab(cfg, scfg, label: str, *, n_requests: int,
                 n_engines: int = 1, repeats: int = 3) -> dict:
    """Latency-decomposition overhead A/B (docs/OBSERVABILITY.md,
    "Capacity observatory"): the same closed-loop traffic served with the
    phase split ON (queue_wait/pack/h2d/device/resolve stamped on every
    dispatch, bit-exact latency_ms sum, per-request phase totals on the
    resolve leaf) vs OFF (keys null, bare engine wall). The split's cost
    is a handful of perf_counter reads plus the engine-side input sync —
    this bench is what keeps the <2% claim measured, not assumed. Same
    shared-engine interleaved-arm methodology as run_trace_ab (a per-arm
    engine would hand the A/B a compiled-program state difference far
    larger than the phase clocks being measured); the split never touches
    the compiled program, so the ENGINE-side half toggles per arm via the
    host-side `engine.phase_split` attribute — the off arm pays neither
    the batcher clocks nor the input sync."""
    import numpy as np

    from glom_tpu.serve.batcher import DynamicBatcher, ShedError
    from glom_tpu.telemetry.sinks import emit
    from glom_tpu.utils.metrics import MetricsWriter

    rng = np.random.default_rng(5)
    shape = (cfg.channels, cfg.image_size, cfg.image_size)
    imgs = [
        rng.normal(size=shape).astype(np.float32) for _ in range(n_requests)
    ]
    engines = _make_engines(cfg, scfg, n_engines)
    for eng in engines:
        eng.warmup()
    window = max(1, min(scfg.queue_depth // 2, 16))
    best: dict = {}
    for rep in range(repeats + 1):
        for arm, flag in (("phase-off", False), ("phase-on", True)):
            writer = MetricsWriter(None, echo=False)
            lat = []
            for eng in engines:
                eng.phase_split = flag  # host-side; no recompile
            with DynamicBatcher(
                engines=engines, writer=writer, phase_split=flag
            ) as batcher:
                for start in range(0, n_requests, window):
                    tickets = []
                    for i in range(start, min(start + window, n_requests)):
                        try:
                            tickets.append(batcher.submit(imgs[i]))
                        except ShedError:
                            continue
                    for t in tickets:
                        try:
                            _, _, latency_s = t.result(timeout=600.0)
                        except Exception:
                            continue
                        lat.append(latency_s)
            writer.close()
            if rep == 0:
                continue  # warm-up pass: first-touch noise, not data
            if lat:
                mean_ms = 1e3 * sum(lat) / len(lat)
                if arm not in best or mean_ms < best[arm]:
                    best[arm] = mean_ms
    for arm in ("phase-off", "phase-on"):
        if arm in best:
            emit(
                {
                    "metric": f"serve_phase_mean_latency ({arm}, {label})",
                    "value": round(best[arm], 4),
                    "unit": "ms",
                    "requests": n_requests,
                    "repeats": repeats,
                }
            )
        else:
            emit(
                {
                    "metric": f"serve_phase_mean_latency ({arm}, {label})",
                    "value": None,
                    "unit": "ms",
                    "error": "no-requests-served",
                    "note": f"UNMEASURED: phase A/B {arm} arm served nothing",
                },
                kind="error",
            )
    if "phase-off" in best and "phase-on" in best and best["phase-off"] > 0:
        overhead = 100.0 * (best["phase-on"] - best["phase-off"]) / best[
            "phase-off"
        ]
        emit(
            {
                "metric": f"serve_phase_overhead ({label})",
                "value": round(overhead, 2),
                "unit": "percent",
                "phase_off_ms": round(best["phase-off"], 4),
                "phase_on_ms": round(best["phase-on"], 4),
                "budget_percent": 2.0,
            }
        )
    return best


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("--requests", type=int, default=None,
                    help="requests per load point (default: 48 TPU, 16 CPU)")
    ap.add_argument("--iters", default=None,
                    help="override the preset route: an int or 'auto'")
    ap.add_argument("--engines", type=int, default=1, metavar="N",
                    help="engine replicas behind one shared batcher")
    ap.add_argument("--mesh-data", type=int, default=None, metavar="D",
                    help="shard each engine's buckets over a D-way 'data' "
                    "axis (parallel/serve_mesh.py)")
    ap.add_argument("--mesh-seq", type=int, default=None, metavar="S",
                    help="shard the patch axis over an S-way 'seq' axis")
    ap.add_argument("--two-tier-ab", action="store_true",
                    help="run the batch-level vs two-tier exit A/B over "
                    "heterogeneous traffic (tiered executed-iters rows)")
    ap.add_argument("--hetero", type=float, default=0.5, metavar="FRAC",
                    help="fraction of HARD (slow-converging) requests in "
                    "the two-tier A/B's synthetic traffic (default 0.5)")
    ap.add_argument("--ragged", action="store_true",
                    help="run the mixed-resolution ragged-vs-bucket sweep "
                    "INSTEAD of the load sweep: the same streamed traffic "
                    "served padded through the bucket ladder vs packed "
                    "through the ragged page ladder, measuring pad-waste "
                    "fraction, warm/cold dispatch latency, and warm-path "
                    "levels0 upload bytes per arm (docs/SERVING.md)")
    ap.add_argument("--banded-ab", action="store_true",
                    help="run the block-banded vs windowed ragged "
                    "consensus A/B INSTEAD of the load sweep: the same "
                    "mixed-resolution streamed traffic under the "
                    "windowed gather, the banded route, and banded + "
                    "in-place pool aliasing — emitting the peak "
                    "duplicated k/v working set per arm, the largest "
                    "admissible ragged signature under the windowed "
                    "byte budget, pool bytes moved per arm, and the "
                    "threshold-0 bitwise parity row (docs/SERVING.md)")
    ap.add_argument("--temporal", action="store_true",
                    help="run the streaming warm-vs-cold A/B INSTEAD of "
                    "the load sweep: frame-sequence traffic per stream "
                    "through the session column cache, measuring mean "
                    "executed iters/request per arm (docs/SERVING.md)")
    ap.add_argument("--streams", type=int, default=4, metavar="S",
                    help="temporal mode: number of concurrent streams")
    ap.add_argument("--frames", type=int, default=4, metavar="F",
                    help="temporal mode: frames per stream")
    ap.add_argument("--perturb", type=float, default=None, metavar="P",
                    help="temporal mode: per-frame perturbation scale "
                    "relative to the stream's base image (default 0.05; "
                    "delta mode perturbs a one-patch REGION and defaults "
                    "to 0.5 — strong enough that the global witness "
                    "re-settles while the support witness exits)")
    ap.add_argument("--delta", action="store_true",
                    help="with --temporal: run the DELTA streaming A/B "
                    "instead of the warm/cold one — whole-state paged "
                    "warm vs delta-chain storage + the incremental "
                    "update path, over O(1)-shaped traffic (shared scene "
                    "bases, bitwise hold frames, a one-patch moving "
                    "region), measuring mean executed iters/frame, "
                    "actual bytes_per_stream per arm, and the "
                    "threshold-0 bitwise reconstruction parity "
                    "(docs/SERVING.md, Delta streaming)")
    ap.add_argument("--cameras", type=int, default=0, metavar="C",
                    help="delta mode: streams per scene sharing an "
                    "identical first frame (0 = all streams, one scene)")
    ap.add_argument("--delta-atol", type=float, default=0.5, metavar="A",
                    help="delta mode: per-page column residual tolerance "
                    "for the delta arm (stamped on every row; the parity "
                    "probe always runs at 0.0). The default sits mid-gap "
                    "between a perturbed page's residual (~4.0 at the "
                    "default traffic) and unperturbed one-iteration "
                    "drift (~0.1)")
    ap.add_argument("--delta-period", type=int, default=4, metavar="K",
                    help="delta mode: a region perturbation every K "
                    "frames, bitwise holds between (default 4)")
    ap.add_argument("--trace-ab", action="store_true",
                    help="run the request-tracing overhead A/B INSTEAD of "
                    "the load sweep: the same closed-loop traffic with "
                    "trace stamping on vs off, emitting the per-arm mean "
                    "latency and serve_trace_overhead in percent — the "
                    "<2% bar (docs/OBSERVABILITY.md, Request tracing)")
    ap.add_argument("--ramp", action="store_true",
                    help="run the ELASTIC ramp INSTEAD of the load sweep: "
                    "an offered-load ramp (low -> spike -> low) through "
                    "the real autoscaler — the spike must scale the "
                    "fleet OUT, the calm back IN, with every ticket "
                    "conserved; emits the n_engines timeline row and "
                    "spawn/p99 costs (docs/SERVING.md, Elastic serving)")
    ap.add_argument("--ramp-profile", default="4x100,56x0,12x200",
                    metavar="N1xG1,...",
                    help="ramp mode: requests x gap_ms per phase")
    ap.add_argument("--replay", default=None, metavar="FILE",
                    help="replay a recorded workload artifact "
                    "(serve/workload.py) through the real elastic stack "
                    "INSTEAD of the load sweep: faithful inter-arrival "
                    "pacing, a scored live forecast on every window, "
                    "ticket conservation asserted")
    ap.add_argument("--scenario", default=None,
                    choices=("diurnal", "flash-crowd", "rolling-outage"),
                    help="generate a workload scenario (pure-stdlib, "
                    "seeded) and drive it like --replay — chaos-grade "
                    "elastic traffic reproducible from a seed alone")
    ap.add_argument("--scenario-duration", type=float, default=6.0,
                    metavar="S", help="scenario length in seconds")
    ap.add_argument("--scenario-seed", type=int, default=0, metavar="K",
                    help="scenario arrival-process seed")
    ap.add_argument("--scenario-crowd-rps", type=float, default=None,
                    metavar="R",
                    help="flash-crowd only: crowd arrival rate during "
                    "the burst (default 50; raise past one engine's "
                    "service rate to force a genuine capacity crunch "
                    "for the --elastic-ab gate)")
    ap.add_argument("--time-scale", type=float, default=1.0, metavar="X",
                    help="replay/scenario: stretch (>1) or compress (<1) "
                    "the inter-arrival gaps")
    ap.add_argument("--elastic-ab", action="store_true",
                    help="with --replay/--scenario: drive the SAME "
                    "records through a reactive (PR 14 baseline) and an "
                    "anticipatory (forecast + warm pool) fleet, each "
                    "logging its decision chain to its own JSONL file, "
                    "and score counterfactual regret per arm "
                    "(docs/SERVING.md 'Anticipatory autoscaling'); "
                    "flash-crowd runs GATE on the p99 + failed-ticket "
                    "deltas")
    ap.add_argument("--elastic-ab-out", default="elastic_ab",
                    metavar="PREFIX",
                    help="per-arm decision-log path prefix "
                    "(PREFIX_reactive.jsonl / PREFIX_anticipatory.jsonl)")
    ap.add_argument("--class-mix", default=None, metavar="SPEC",
                    help="scenario only: deal each arrival an SLO class "
                    "by seeded fraction, e.g. "
                    "'premium=0.2,standard=0.3,batch=0.5' "
                    "(docs/SERVING.md 'SLO classes')")
    ap.add_argument("--qos-ab", action="store_true",
                    help="with --replay/--scenario: drive the SAME "
                    "records through a classless (shared FIFO) and a "
                    "QoS (premium/standard/batch weighted-fair) fleet, "
                    "audit each arm's decision log STRICTLY, and emit "
                    "per-class p99 / served-fraction rows; flash-crowd "
                    "runs GATE on premium p99 beating the classless "
                    "baseline with batch held at the starvation floor")
    ap.add_argument("--qos-ab-out", default="qos_ab",
                    metavar="PREFIX",
                    help="per-arm decision-log path prefix "
                    "(PREFIX_classless.jsonl / PREFIX_qos.jsonl)")
    ap.add_argument("--workload-out", default=None, metavar="FILE",
                    help="replay/scenario: re-record THIS run's offered "
                    "traffic as a workload artifact (closes the "
                    "record -> replay -> record loop)")
    ap.add_argument("--phase-ab", action="store_true",
                    help="run the latency-decomposition overhead A/B: the "
                    "same traffic with the dispatch phase split on vs "
                    "off, emitting serve_phase_overhead in percent — the "
                    "<2%% bar (docs/OBSERVABILITY.md, Capacity "
                    "observatory)")
    args = ap.parse_args(argv)

    from glom_tpu.telemetry.sinks import bench_bootstrap, emit

    if not bench_bootstrap("serve_p95_latency", "ms"):
        return 0

    import dataclasses

    import jax

    from glom_tpu.utils.config import GlomConfig, ServeConfig
    from glom_tpu.utils.metrics import detect_chip
    from glom_tpu.utils.presets import get_preset

    chip = detect_chip()
    on_tpu = chip != "cpu"
    if on_tpu:
        preset = get_preset("imagenet224-dp8")
        cfg, scfg = preset.model, preset.serve
        label = f"ImageNet-224 L6 d512 bf16, {chip}"
        n_requests = args.requests or 48
        load_fracs = (0.25, 0.5, 0.8)
        ceiling_repeats = 5
    else:
        # CPU fallback: the labelled small config — live numbers for the
        # harness/CI, never a dead zero for the trajectory. The budget is
        # raised past the config's 2L default so the two-tier A/B's easy
        # requests have room to converge inside it (~budget-6 at
        # threshold 1e-3; hard 100x requests land near the budget).
        cfg = GlomConfig(dim=64, levels=3, image_size=16, patch_size=4)
        scfg = ServeConfig(
            buckets=(1, 2, 4), max_batch=4, max_delay_ms=2.0,
            iters="auto", exit_threshold=1e-3, max_auto_iters=16,
        )
        label = "cpu-fallback cfg"
        n_requests = args.requests or 16
        load_fracs = (0.5,)
        ceiling_repeats = 2
        emit(
            {"note": "TPU backend unavailable; measuring the labelled "
             "cpu-fallback serve config instead of recording a dead zero"},
            kind="note",
        )
    overrides = {}
    if args.iters is not None:
        overrides["iters"] = (
            "auto" if args.iters == "auto" else int(args.iters)
        )
    if args.mesh_data is not None:
        overrides["mesh_data"] = args.mesh_data
    if args.mesh_seq is not None:
        overrides["mesh_seq"] = args.mesh_seq
    mesh_data = overrides.get("mesh_data", scfg.mesh_data)
    if mesh_data > 1:
        # Buckets must divide by the data axis; drop the ones that don't
        # (a preset ladder with a 1-bucket tail can't shard its rows) and
        # cap the admission ceiling to what remains.
        buckets = tuple(b for b in scfg.buckets if b % mesh_data == 0)
        if not buckets:
            buckets = (mesh_data,)
        overrides["buckets"] = buckets
        overrides["max_batch"] = min(scfg.max_batch, max(buckets))
    if overrides:
        scfg = dataclasses.replace(scfg, **overrides)
    if args.engines > 1:
        label = f"{label}, engines={args.engines}"
    if scfg.mesh_data > 1 or scfg.mesh_seq > 1:
        label = f"{label}, mesh={scfg.mesh_data}x{scfg.mesh_seq}"
    del jax  # imported to fail fast before any measurement if broken
    if args.replay or args.scenario:
        from glom_tpu.serve.workload import generate, load_workload

        if args.replay:
            records = load_workload(args.replay)
            source = args.replay
            # A faithful replay re-offers the artifact's exact shapes —
            # if the artifact was recorded against a different model
            # config (a preset server, say, vs this driver's fallback
            # cfg), rebuild the engine config around the recorded
            # resolution instead of failing every ticket on a shape
            # mismatch. Only unambiguous fixed-resolution artifacts
            # qualify; mixed/ragged traffic keeps the configured cfg.
            shapes = {
                tuple(r["shape"]) for r in records
                if r.get("shape") is not None
                and str(r.get("signature", "")).startswith("bucket:")
            }
            if len(shapes) == 1:
                (c, h, w), = shapes
                if h == w and (c, h) != (cfg.channels, cfg.image_size):
                    patch = next(
                        p for p in (cfg.patch_size, 7, 4, 2, 1)
                        if h % p == 0
                    )
                    cfg = dataclasses.replace(
                        cfg, channels=c, image_size=h, patch_size=patch,
                    )
                    emit(
                        {"note": f"replay artifact carries {c}x{h}x{w} "
                         "requests; rebuilding the engine config to "
                         "match the recorded resolution"},
                        kind="note",
                    )
        else:
            scen_kw = {}
            if args.scenario_crowd_rps is not None:
                if args.scenario != "flash-crowd":
                    ap.error("--scenario-crowd-rps only applies to "
                             "--scenario flash-crowd")
                scen_kw["crowd_rps"] = args.scenario_crowd_rps
            if args.class_mix is not None:
                from glom_tpu.serve.workload import parse_class_mix

                scen_kw["class_mix"] = parse_class_mix(args.class_mix)
            records = generate(
                args.scenario, args.scenario_duration,
                seed=args.scenario_seed,
                shapes=((cfg.channels, cfg.image_size, cfg.image_size),),
                **scen_kw,
            )
            source = f"scenario:{args.scenario}"
        if args.elastic_ab:
            run_elastic_ab(
                cfg, scfg, label, records,
                source=source,
                time_scale=args.time_scale,
                out_prefix=args.elastic_ab_out,
                # The acceptance gate rides the flash-crowd scenario:
                # the crowd is exactly the shape anticipation must beat.
                gate="flash-crowd" in source,
            )
            return 0
        if args.qos_ab:
            if not any(rec.get("slo_class") for rec in records):
                ap.error("--qos-ab needs classed arrivals: record the "
                         "workload with classes or pass --class-mix "
                         "(e.g. 'premium=0.2,standard=0.3,batch=0.5')")
            run_qos_ab(
                cfg, scfg, label, records,
                source=source,
                time_scale=args.time_scale,
                out_prefix=args.qos_ab_out,
                # Same shape as the elastic gate: the flash crowd is
                # exactly the contention QoS must arbitrate.
                gate="flash-crowd" in source,
            )
            return 0
        run_workload(
            cfg, scfg, label, records,
            source=source,
            time_scale=args.time_scale,
            workload_out=args.workload_out,
        )
        return 0
    if args.ramp:
        run_ramp(cfg, scfg, label, profile=args.ramp_profile)
        return 0
    if args.trace_ab:
        run_trace_ab(
            cfg, scfg, label,
            n_requests=n_requests,
            n_engines=args.engines,
        )
        return 0
    if args.phase_ab:
        run_phase_ab(
            cfg, scfg, label,
            n_requests=n_requests,
            n_engines=args.engines,
        )
        return 0
    if args.banded_ab:
        run_banded_ab(
            cfg, scfg, label,
            n_streams=args.streams,
            n_frames=args.frames,
            perturb=args.perturb if args.perturb is not None else 0.05,
        )
        return 0
    if args.ragged:
        run_ragged(
            cfg, scfg, label,
            n_streams=args.streams,
            n_frames=args.frames,
            perturb=args.perturb if args.perturb is not None else 0.05,
        )
        return 0
    if args.temporal and args.delta:
        run_temporal_delta(
            cfg, scfg, label,
            n_streams=args.streams,
            n_frames=args.frames,
            cameras=args.cameras,
            perturb=args.perturb if args.perturb is not None else 0.5,
            period=args.delta_period,
            atol=args.delta_atol,
        )
        return 0
    if args.temporal:
        run_temporal(
            cfg, scfg, label,
            n_streams=args.streams,
            n_frames=args.frames,
            perturb=args.perturb if args.perturb is not None else 0.05,
            n_engines=args.engines,
        )
        return 0
    run_sweep(
        cfg, scfg, label,
        n_requests=n_requests,
        load_fracs=load_fracs,
        ceiling_repeats=ceiling_repeats,
        n_engines=args.engines,
    )
    if args.two_tier_ab:
        run_two_tier_ab(
            cfg, scfg, label,
            n_requests=n_requests,
            hard_frac=args.hetero,
            n_engines=args.engines,
        )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
