"""Long-context benchmark: consensus+update at large patch counts n.

The patch axis n is GLOM's sequence axis (SURVEY.md §2.2): at the flagship
ImageNet-224/14 config n is only 256 and the grouped MLPs dominate, but at
larger images / smaller patches (n = 1024, 4096, ...) the O(n^2) consensus
attention takes over — the regime the blockwise Pallas kernel
(kernels/consensus_update.py) and its block-sparse local-radius skipping
exist for.

Measures one consensus+mean update (the scan body's attention half) at
L=6, d=512, bf16 on one chip, for each implementation:

  * dense   — the XLA composition that materializes the [L, B, n, n]
              similarity (ops/consensus.py semantics via _xla_reference);
  * fused   — the blockwise Pallas kernel, O(n) memory;
  * both again at local radius 7 (BASELINE config 3's window), where the
    fused kernel skips j-tiles entirely outside the radius band while the
    dense path still pays the full n^2.

Timing: same methodology as bench.py (chained fori_loop, scalar-fetch sync,
per-op = (t_chain - t_rtt) / k with an auto-calibrated chain length — see
glom_tpu/utils/timing.py), except the chain length adapts per variant
because op costs here span µs..ms.

Writes one schema-stamped JSON line per measurement to stdout (kind
"bench"; failed rows — OOM, compile errors — are kind "error" with value
null, never a fake number) and appends them to results/longctx_bench.jsonl.
Every row carries the watchdog backend state (bench_bootstrap registers it
before any backend touch).
"""

import argparse
import json

import jax
import jax.numpy as jnp

from glom_tpu.kernels.consensus_update import _xla_reference, fused_consensus_update
from glom_tpu.telemetry.sinks import emit
from glom_tpu.utils.metrics import detect_chip
from glom_tpu.utils.timing import calibrated_chain_time


def bench_variant(name, op, levels, bu, td, side, radius, repeats,
                  flops_mult=1):
    # levels/bu/td ride as jit ARGUMENTS, not closure constants: closed-over
    # arrays embed in the serialized MLIR, and batched long-row shapes
    # (B=8, n=4096 -> 200MB+) break the remote-compile tunnel (HTTP 413).
    def multi(lv, bu_, td_, k):
        def body(_, acc):
            # genuinely data-dependent ~1e-9-scale coupling (an `acc*0`
            # form could be folded, letting the compiler hoist the body)
            out = op(lv + acc.astype(lv.dtype), bu_, td_,
                     side=side, radius=radius)
            # FULL-output reduction: a partial slice would let XLA
            # dead-code-eliminate the unobserved rows/levels of the
            # dense einsums (measured: "847 TF/s" dense at radius 7).
            return jnp.sum(out).astype(jnp.float32) * 1e-9

        return jax.lax.fori_loop(0, k, body, jnp.float32(0.0))

    multi_jit = jax.jit(multi)

    # calibrated_chain_time re-measures RTT right before the measured chain
    # (a per-n RTT taken minutes earlier would drift).
    per_call = calibrated_chain_time(
        lambda k: multi_jit(levels, bu, td, k), levels, repeats=repeats
    )
    L, B, n, d = levels.shape
    # Dense-equivalent attention FLOPs (two n^2 contractions); for radius
    # runs this is the work the dense path still does and the fused kernel
    # skips, so fused radius throughput can exceed "peak" — that's the point.
    tflops_equiv = flops_mult * 4 * B * L * n * n * d / per_call / 1e12
    rec = {"impl": name, "n": n, "radius": radius,
           "ms_per_call": round(per_call * 1e3, 3),
           "dense_equiv_tflops": round(tflops_equiv, 2)}
    if B > 1:
        rec["batch"] = B
    return rec


def main(only_sides=None, batch=1):
    chip = detect_chip()
    on_tpu = chip != "cpu"
    L, B, d = 6, batch, 512
    # side 16 = the flagship n=256 (anchors the dispatch crossover at the
    # config the train bench runs); side 96 -> n=9216, the past-the-old-cap
    # long-context point the streamed backward unlocked (dense grad at this
    # n materializes a ~2GB sim twice — measured if it fits, recorded as
    # oom otherwise).
    sides = (16, 32, 64, 96) if on_tpu else (8,)
    if only_sides is not None:
        if not only_sides:
            raise ValueError("--sides given but empty; pass side values")
        sides = tuple(only_sides)
    dtype = jnp.bfloat16 if on_tpu else jnp.float32
    repeats = 3 if on_tpu else 2

    def dense(lv, bu, td, *, side, radius):
        return _xla_reference(lv, bu, td, side=side, radius=radius, attend_self=False)

    def fused(lv, bu, td, *, side, radius):
        return fused_consensus_update(lv, bu, td, side=side, radius=radius)

    def fused_bw(lv, bu, td, *, side, radius):
        # force the blockwise backward so the kernel is measured even where
        # the auto dispatch would (correctly) route to the dense VJP
        return fused_consensus_update(
            lv, bu, td, side=side, radius=radius, bwd_impl="blockwise"
        )

    def grad_of(op):
        def gop(lv, bu_, td_, *, side, radius):
            def loss(a, b, c):
                out = op(a, b, c, side=side, radius=radius)
                return jnp.mean(out.astype(jnp.float32) ** 2)

            glv, gbu, gtd = jax.grad(loss, argnums=(0, 1, 2))(lv, bu_, td_)
            # same output contract as the fwd ops so bench_variant's full-sum
            # sync covers every gradient element
            return glv + gbu + jnp.concatenate([gtd, gtd[:1]], axis=0)

        return gop

    for side in sides:
        n = side * side
        key = jax.random.PRNGKey(side)
        k1, k2, k3 = jax.random.split(key, 3)
        levels = jax.random.normal(k1, (L, B, n, d), dtype)
        bu = jax.random.normal(k2, (L, B, n, d), dtype)
        td = jax.random.normal(k3, (L - 1, B, n, d), dtype)
        variants = [
            ("dense_xla", dense, 1),
            ("fused_pallas", fused, 1),
            # training direction: value+grad through the op (bwd counted as
            # 2x fwd) — the dense VJP materializes [L, B, n, n] TWICE
            # (fwd + bwd); the blockwise backward keeps O(n) memory
            ("dense_xla_grad", grad_of(dense), 3),
            ("fused_pallas_grad", grad_of(fused_bw), 3),
            ("auto_dispatch_grad", grad_of(fused), 3),
        ]
        for radius in (0.0, 7.0):
            for name, op, mult in variants:
                label = (
                    f"longctx {name} (n={side * side}, radius={radius:g}, "
                    f"B={B}, {chip})"
                )
                try:
                    rec = bench_variant(
                        name, op, levels, bu, td, side, radius, repeats,
                        flops_mult=mult,
                    )
                    rec.update(
                        metric=label, value=rec["ms_per_call"], unit="ms/call"
                    )
                    kind = "bench"
                except Exception as e:  # noqa: BLE001 - record OOM/compile fails
                    # An unmeasurable row is an "error" record with value
                    # null — the compare gate reads it as MISSING, never as
                    # a zero or an infinitely-fast kernel.
                    rec = {"metric": label, "value": None, "unit": "ms/call",
                           "impl": name, "n": side * side, "radius": radius,
                           "error": f"{type(e).__name__}: {e}"[:200]}
                    kind = "error"
                rec["chip"] = chip
                stamped = emit(rec, kind=kind)
                if on_tpu:
                    # append-as-you-go: a tunnel hiccup mid-run must not
                    # lose the completed measurements
                    with open("results/longctx_bench.jsonl", "a") as f:
                        f.write(json.dumps(stamped) + "\n")


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--sides", type=int, nargs="*", default=None,
        help="restrict to these grid sides (rerun specific rows)",
    )
    ap.add_argument(
        "--batch", type=int, default=1,
        help="batch size B (the batched long-row regime record)",
    )
    ap.add_argument(
        "--trace-dir", default=None, metavar="DIR",
        help="capture an XProf trace of the measured chains into DIR",
    )
    args = ap.parse_args()
    from glom_tpu.telemetry.sinks import bench_bootstrap

    if not bench_bootstrap("longctx consensus ms_per_call", "ms/call"):
        raise SystemExit(0)
    if args.trace_dir:
        from glom_tpu.tracing.capture import trace

        with trace(args.trace_dir):
            main(args.sides, batch=args.batch)
        emit({"note": "xla-trace captured", "trace_dir": args.trace_dir},
             kind="note")
    else:
        main(args.sides, batch=args.batch)
