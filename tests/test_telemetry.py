"""Telemetry subsystem tests: schema round-trip, in-graph diagnostics +
NaN/Inf guard, collective counters vs the comm model, watchdog state
machine, sinks, and the scalars-level overhead budget (slow-marked A/B).
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from glom_tpu.telemetry import schema
from glom_tpu.utils.config import GlomConfig, MeshConfig, TrainConfig

CFG = GlomConfig(dim=16, levels=3, image_size=8, patch_size=2)


def small_tcfg(**kw):
    base = dict(batch_size=4, learning_rate=1e-3, iters=2, recon_iter_index=2)
    base.update(kw)
    return TrainConfig(**base)


class TestSchema:
    def test_stamp_and_validate_roundtrip(self):
        """Every kind's minimal record stamps, serializes, parses, and
        validates — the JSONL round-trip contract."""
        minimal = {
            "train_step": {"step": 3, "loss": 0.5},
            "bench": {"metric": "m", "value": 1.0, "unit": "u"},
            "watchdog": {"backend_state": "up", "t": 1.5},
            "anomaly": {"step": 2, "reason": "nonfinite"},
            "summary": {"summary": True},
            "note": {"note": "hello"},
        }
        for kind, rec in minimal.items():
            stamped = schema.stamp(rec, kind=kind)
            assert stamped["schema_version"] == schema.SCHEMA_VERSION
            assert stamped["kind"] == kind
            back = json.loads(json.dumps(stamped))
            assert schema.validate_record(back) == [], (kind, back)

    def test_stamp_is_idempotent(self):
        rec = schema.stamp({"loss": 1.0, "step": 0}, kind="train_step")
        again = schema.stamp(rec, kind="bench")  # must NOT relabel
        assert again["kind"] == "train_step"

    def test_kind_inference_for_legacy_records(self):
        assert schema.infer_kind({"metric": "x", "value": 1.0}) == "bench"
        assert schema.infer_kind({"loss": 0.1, "step": 2}) == "train_step"
        assert schema.infer_kind({"note": "n"}) == "note"
        assert (
            schema.infer_kind({"backend_state": "up", "t": 0.1}) == "watchdog"
        )

    def test_invalid_records_are_rejected(self):
        assert schema.validate_record([1, 2]) != []
        assert schema.validate_record({"kind": "nope", "schema_version": 1}) != []
        # missing required field
        assert (
            schema.validate_record(
                {"kind": "bench", "schema_version": 1, "metric": "m"}
            )
            != []
        )
        # wrong type
        assert (
            schema.validate_record(
                {
                    "kind": "bench",
                    "schema_version": 1,
                    "metric": "m",
                    "value": "fast",
                    "unit": "u",
                }
            )
            != []
        )
        # future version
        bad = schema.stamp({"note": "x"}, kind="note")
        bad["schema_version"] = schema.SCHEMA_VERSION + 1
        assert schema.validate_record(bad) != []
        with pytest.raises(schema.SchemaError):
            schema.assert_valid({"kind": "nope"})

    def test_lint_stream_skips_shell_noise(self):
        lines = [
            "=== [12:00:00] START bench\n",
            json.dumps(schema.stamp({"note": "hi"}, kind="note")) + "\n",
            "Traceback (most recent call last):\n",
            json.dumps(
                schema.stamp(
                    {"metric": "m", "value": 2.0, "unit": "u"}, kind="bench"
                )
            )
            + "\n",
        ]
        assert schema.lint_stream(lines) == []
        # a stamped-but-broken record IS an error
        broken = schema.stamp({"metric": "m", "unit": "u"}, kind="bench")
        assert schema.lint_stream([json.dumps(broken)]) != []
        # unstamped legacy rows: error strictly, skipped with the flag
        legacy = json.dumps({"some": "row"})
        good = json.dumps(schema.stamp({"note": "n"}, kind="note"))
        assert schema.lint_stream([legacy, good]) != []
        assert schema.lint_stream([legacy, good], require_stamp=False) == []
        # a JSON-free log: an error in strict mode (the round-5 empty bench
        # trajectory), tolerated in the queue's mixed-log sweep (probe /
        # tpu_validate logs legitimately contain no JSON)
        shell_only = ["=== START probe\n", "[TpuDevice(id=0)]\n"]
        assert schema.lint_stream(shell_only) != []
        assert (
            schema.lint_stream(
                shell_only, require_stamp=False, require_records=False
            )
            == []
        )

    def test_metrics_writer_stamps_every_record(self, tmp_path):
        from glom_tpu.utils.metrics import MetricsWriter

        path = tmp_path / "m.jsonl"
        w = MetricsWriter(str(path), echo=False)
        w.write({"step": 1, "loss": 0.25})
        w.write({"note": "context"})
        w.close()
        recs = [json.loads(l) for l in path.read_text().splitlines()]
        assert [r["kind"] for r in recs] == ["train_step", "note"]
        for r in recs:
            assert schema.validate_record(r) == [], r


class TestInGraphDiagnostics:
    def test_scalars_level_stamps_taps(self):
        from glom_tpu.train.trainer import Trainer

        tr = Trainer(CFG, small_tcfg(telemetry_level="scalars"))
        img = jnp.asarray(
            np.random.default_rng(0).normal(size=(4, 3, 8, 8)), jnp.float32
        )
        m = tr.step(img)
        for key in ("grad_norm", "update_norm", "param_norm", "nonfinite_step"):
            assert key in m, key
        assert float(m["nonfinite_step"]) == 0
        assert float(m["update_norm"]) > 0
        assert m["telemetry_level"] == "scalars"
        assert m["backend_state"] in schema.WATCHDOG_STATES

    def test_off_level_stays_clean(self):
        from glom_tpu.train.trainer import Trainer

        tr = Trainer(CFG, small_tcfg())
        img = jnp.zeros((4, 3, 8, 8), jnp.float32)
        m = tr.step(img)
        assert "update_norm" not in m and "nonfinite_step" not in m
        assert m["telemetry_level"] == "off"

    def test_full_level_emits_per_level_agreement(self):
        from glom_tpu.telemetry.diagnostics import split_level_agreement
        from glom_tpu.train.trainer import Trainer

        tr = Trainer(CFG, small_tcfg(telemetry_level="full"))
        img = jnp.asarray(
            np.random.default_rng(1).normal(size=(4, 3, 8, 8)), jnp.float32
        )
        m = split_level_agreement(tr.step(img))
        keys = [k for k in m if k.startswith("consensus_agreement_l")]
        assert len(keys) == CFG.levels
        for k in keys:
            assert -1.0 <= float(m[k]) <= 1.0 + 1e-6

    def test_full_level_rides_grad_accum(self):
        from glom_tpu.telemetry.diagnostics import split_level_agreement
        from glom_tpu.train.trainer import Trainer

        tr = Trainer(CFG, small_tcfg(telemetry_level="full", grad_accum=2))
        img = jnp.asarray(
            np.random.default_rng(2).normal(size=(4, 3, 8, 8)), jnp.float32
        )
        m = split_level_agreement(tr.step(img))
        assert f"consensus_agreement_l{CFG.levels - 1}" in m

    def test_level_agreement_math(self):
        from glom_tpu.telemetry.diagnostics import level_agreement

        # All patches identical at level 0 -> agreement 1; orthogonal
        # pattern at level 1 -> agreement far below 1.
        b, n, d = 2, 4, 8
        lv0 = jnp.ones((b, n, d))
        rng = np.random.default_rng(0)
        lv1 = jnp.asarray(rng.normal(size=(b, n, d)), jnp.float32)
        final = jnp.stack([lv0, lv1], axis=2)  # [b, n, L=2, d]
        agree = level_agreement(final)
        assert agree.shape == (2,)
        assert float(agree[0]) == pytest.approx(1.0, abs=1e-5)
        assert float(agree[1]) < 0.9

    def test_unknown_level_raises(self):
        from glom_tpu.train.trainer import Trainer

        with pytest.raises(ValueError, match="telemetry_level"):
            Trainer(CFG, small_tcfg(telemetry_level="verbose"))
        with pytest.raises(ValueError, match="nonfinite_policy"):
            Trainer(
                CFG,
                small_tcfg(telemetry_level="scalars", nonfinite_policy="explode"),
            )


class TestNonfiniteGuard:
    def _nan_batch(self):
        img = np.random.default_rng(0).normal(size=(4, 3, 8, 8)).astype(np.float32)
        img[0, 0, 0, 0] = np.nan
        return jnp.asarray(img)

    def test_skip_policy_drops_update(self):
        """An injected NaN batch must leave params AND optimizer state
        bit-identical (the skip-step), flag the record, and leave the
        trainer healthy for the next clean batch."""
        from glom_tpu.train.trainer import Trainer

        tr = Trainer(
            CFG, small_tcfg(telemetry_level="scalars", nonfinite_policy="skip")
        )
        before = jax.tree_util.tree_map(np.asarray, tr.state.params)
        opt_before = jax.tree_util.tree_map(np.asarray, tr.state.opt_state)
        m = tr.step(self._nan_batch())
        assert float(m["nonfinite_step"]) == 1
        assert float(m["skipped_nonfinite"]) == 1
        for a, b in zip(
            jax.tree_util.tree_leaves(before),
            jax.tree_util.tree_leaves(tr.state.params),
        ):
            np.testing.assert_array_equal(a, np.asarray(b))
        for a, b in zip(
            jax.tree_util.tree_leaves(opt_before),
            jax.tree_util.tree_leaves(tr.state.opt_state),
        ):
            np.testing.assert_array_equal(a, np.asarray(b))
        # step counter still advances; a clean batch then trains finitely
        assert int(tr.state.step) == 1
        clean = jnp.asarray(
            np.random.default_rng(1).normal(size=(4, 3, 8, 8)), jnp.float32
        )
        m2 = tr.step(clean)
        assert np.isfinite(float(m2["loss"]))
        assert float(m2["nonfinite_step"]) == 0

    def test_warn_policy_applies_update(self):
        from glom_tpu.train.trainer import Trainer

        tr = Trainer(
            CFG, small_tcfg(telemetry_level="scalars", nonfinite_policy="warn")
        )
        m = tr.step(self._nan_batch())
        assert float(m["nonfinite_step"]) == 1
        assert "skipped_nonfinite" not in m
        # warn means the poison went through — that's the policy's contract
        leaves = [np.asarray(x) for x in jax.tree_util.tree_leaves(tr.state.params)]
        assert any(not np.isfinite(l).all() for l in leaves)

    def test_fit_loop_emits_structured_anomaly_event(self, tmp_path):
        from glom_tpu.train.trainer import Trainer
        from glom_tpu.utils.metrics import MetricsWriter

        path = tmp_path / "m.jsonl"
        writer = MetricsWriter(str(path), echo=False)
        tr = Trainer(
            CFG,
            small_tcfg(telemetry_level="scalars", nonfinite_policy="skip"),
            metrics_writer=writer,
        )

        def data():
            yield self._nan_batch()
            while True:
                yield jnp.asarray(
                    np.random.default_rng(3).normal(size=(4, 3, 8, 8)),
                    jnp.float32,
                )

        history = tr.fit(data(), num_steps=2, log_every=1)
        writer.close()
        # history stays homogeneous train_step records (consumers index
        # loss/steps_per_sec); the anomaly event goes to the writer
        assert all(r["kind"] == "train_step" for r in history)
        assert history[0]["nonfinite_step"] == 1
        recs = [json.loads(l) for l in path.read_text().splitlines()]
        anomalies = [r for r in recs if r["kind"] == "anomaly"]
        assert len(anomalies) == 1
        assert anomalies[0]["reason"] == "nonfinite_loss_or_grad"
        assert anomalies[0]["policy"] == "skip"
        assert anomalies[0]["count"] == 1
        for r in recs:
            assert schema.validate_record(r) == [], r

    def test_anomaly_between_logging_steps_is_reported(self, tmp_path):
        """A NaN batch landing on a NON-logging step must still surface:
        the per-step flags are kept as device scalars and fetched at the
        log boundary, so the anomaly event names the flagged iteration
        even though that step's record was never written."""
        from glom_tpu.train.trainer import Trainer
        from glom_tpu.utils.metrics import MetricsWriter

        path = tmp_path / "m.jsonl"
        writer = MetricsWriter(str(path), echo=False)
        tr = Trainer(
            CFG,
            small_tcfg(telemetry_level="scalars", nonfinite_policy="skip"),
            metrics_writer=writer,
        )

        def data():
            rng = np.random.default_rng(4)
            i = 0
            while True:
                img = rng.normal(size=(4, 3, 8, 8)).astype(np.float32)
                if i == 1:  # non-logging step under log_every=3
                    img[0, 0, 0, 0] = np.nan
                yield jnp.asarray(img)
                i += 1

        tr.fit(data(), num_steps=3, log_every=3)
        writer.close()
        recs = [json.loads(l) for l in path.read_text().splitlines()]
        anomalies = [r for r in recs if r["kind"] == "anomaly"]
        assert len(anomalies) == 1
        assert anomalies[0]["count"] == 1
        assert anomalies[0]["flagged_iterations"] == [1]

    def test_guard_on_manual_zero_path(self):
        """The in-region guard (manual shard_map ZeRO step): a NaN batch
        on the dp mesh must skip the sharded update too."""
        from glom_tpu.parallel import DistributedTrainer

        cfg = GlomConfig(dim=16, levels=3, image_size=8, patch_size=4)
        tcfg = TrainConfig(
            batch_size=8, learning_rate=1e-3, use_pallas=True, zero_stage=1,
            telemetry_level="scalars",
        )
        tr = DistributedTrainer(cfg, tcfg, MeshConfig(data=8))
        before = jax.tree_util.tree_map(np.asarray, tr.state.params)
        img = np.random.default_rng(0).normal(size=(8, 3, 8, 8)).astype(np.float32)
        img[0, 0, 0, 0] = np.nan
        m = tr.step(img)
        assert float(m["nonfinite_step"]) == 1
        for a, b in zip(
            jax.tree_util.tree_leaves(before),
            jax.tree_util.tree_leaves(tr.state.params),
        ):
            np.testing.assert_array_equal(a, np.asarray(b))


class TestCollectiveCounters:
    def test_recording_context_and_scale(self):
        from glom_tpu.telemetry.counters import (
            CollectiveCounters,
            record_collective,
            recording,
            scaled,
        )

        c = CollectiveCounters()
        record_collective("reduce", 100)  # outside any context: dropped
        with recording(c):
            record_collective("reduce", 100)
            record_collective("gather", 10)
            with scaled(4):
                record_collective("reduce", 5)
        record_collective("gather", 999)
        t = c.totals()
        assert t["comm_measured_reduce_bytes_per_step"] == 120
        assert t["comm_measured_gather_bytes_per_step"] == 10
        assert t["comm_measured_collective_count"] == 3

    def test_manual_zero1_reconciles_with_model(self):
        """Clean dp=8/seq=1 stage-1 schedule: every gradient leaf has a
        dp-divisible axis... except the ones that don't, and the seq psum
        doesn't exist — measured MUST land within a few percent of the
        model, and the drift is stamped on the record."""
        from glom_tpu.parallel import DistributedTrainer

        cfg = GlomConfig(dim=16, levels=3, image_size=8, patch_size=4)
        tcfg = TrainConfig(
            batch_size=8, learning_rate=1e-3, use_pallas=True, zero_stage=1,
            telemetry_level="scalars",
        )
        tr = DistributedTrainer(cfg, tcfg, MeshConfig(data=8))
        r = tr._static_record
        assert r["comm_measured_bytes_per_step"] > 0
        assert abs(r["comm_model_drift"]) < 0.05
        # and the drift definition reconciles the two stamped totals
        assert r["comm_model_drift"] == pytest.approx(
            (r["comm_measured_bytes_per_step"] - r["comm_bytes_per_step"])
            / r["comm_bytes_per_step"],
            abs=1e-5,
        )

    def test_tp_forward_psum_counts_per_scan_execution(self):
        """The TP forward psum (registered by PR 5's glom-lint
        self-host) prices its ring wire bytes PER SCAN EXECUTION: the
        body traces once under counters.scaled(iters), so one counting
        trace must record exactly 2 sites (bu + td ffw outputs) carrying
        iters x ring_allreduce_bytes each. Trace-level contract only —
        the trainer's counting path can't reach mp>1 today (manual x
        zero>=1 degrades to stage 0 on model>1 meshes), which is exactly
        why the multiplicity needs its own lock."""
        from glom_tpu.models.core import init_glom
        from glom_tpu.parallel.manual import make_manual_forward
        from glom_tpu.parallel.mesh import make_mesh
        from glom_tpu.telemetry.counters import (
            CollectiveCounters,
            recording,
            ring_allreduce_bytes,
        )

        cfg = GlomConfig(dim=16, levels=3, image_size=8, patch_size=4)
        mesh = make_mesh(MeshConfig(data=2, model=2), jax.devices()[:4])
        iters, mp, b = 4, 2, 4
        fwd = make_manual_forward(mesh, cfg, iters=iters, use_pallas=True)
        params = jax.eval_shape(
            lambda k: init_glom(k, cfg), jax.random.PRNGKey(0)
        )
        img = jax.ShapeDtypeStruct((b, 3, 8, 8), jnp.float32)
        c = CollectiveCounters()
        with recording(c):
            jax.eval_shape(fwd, params, img)
        # per-shard ffw outputs: bu [L, b_loc*n_loc, d], td [L-1, ...]
        L, d = cfg.levels, cfg.dim
        rows = (b // 2) * cfg.num_patches  # b_loc * n_loc (seq=1)
        bu = jax.ShapeDtypeStruct((L, rows, d), jnp.float32)
        td = jax.ShapeDtypeStruct((L - 1, rows, d), jnp.float32)
        t = c.totals()
        assert c.n_reduce == 2  # two sites, traced once each
        assert t["comm_measured_reduce_bytes_per_step"] == iters * (
            ring_allreduce_bytes(bu, mp) + ring_allreduce_bytes(td, mp)
        )
        assert t["comm_measured_gather_bytes_per_step"] == 0

    def test_stage2_accum_counts_per_microbatch_scatter(self):
        """Stage 2 scatters once PER MICROBATCH inside the scan (one trace,
        accum executions): the measured reduce bytes must scale with
        grad_accum like the model's do."""
        from glom_tpu.parallel import DistributedTrainer

        cfg = GlomConfig(dim=16, levels=3, image_size=8, patch_size=4)
        base = dict(
            batch_size=16, learning_rate=1e-3, use_pallas=True,
            telemetry_level="scalars",
        )
        r1 = DistributedTrainer(
            cfg, TrainConfig(zero_stage=1, **base), MeshConfig(data=8)
        )._static_record
        r2 = DistributedTrainer(
            cfg, TrainConfig(zero_stage=2, grad_accum=2, **base),
            MeshConfig(data=8),
        )._static_record
        assert (
            r2["comm_measured_reduce_bytes_per_step"]
            == pytest.approx(
                2 * r1["comm_measured_reduce_bytes_per_step"], rel=0.05
            )
        )
        # gather (params) is once per step on both
        assert (
            r2["comm_measured_gather_bytes_per_step"]
            == r1["comm_measured_gather_bytes_per_step"]
        )

    def test_gspmd_path_stamps_model_only(self):
        from glom_tpu.parallel import DistributedTrainer

        cfg = GlomConfig(dim=16, levels=3, image_size=8, patch_size=4)
        tcfg = TrainConfig(
            batch_size=8, learning_rate=1e-3, zero_stage=1,
            telemetry_level="scalars",
        )
        tr = DistributedTrainer(cfg, tcfg, MeshConfig(data=8))
        r = tr._static_record
        assert "comm_bytes_per_step" in r
        assert "comm_measured_bytes_per_step" not in r

    def test_quant_probe_stamped_on_quantized_step(self):
        """The manual ZeRO step with quantized_reduce must stamp the
        in-graph quantization-error probe, and its value must respect the
        block-scaling bound's order of magnitude."""
        from glom_tpu.parallel import DistributedTrainer

        cfg = GlomConfig(dim=16, levels=3, image_size=8, patch_size=4)
        tcfg = TrainConfig(
            batch_size=8, learning_rate=1e-3, use_pallas=True, zero_stage=1,
            quantized_reduce=True, telemetry_level="scalars",
        )
        tr = DistributedTrainer(cfg, tcfg, MeshConfig(data=8))
        img = np.random.default_rng(0).normal(size=(8, 3, 8, 8)).astype(np.float32)
        m = tr.step(img)
        assert "quant_rel_err" in m
        assert 0.0 < float(m["quant_rel_err"]) < 0.05

    def test_quant_probe_on_gspmd_step(self):
        from glom_tpu.parallel import DistributedTrainer

        cfg = GlomConfig(dim=16, levels=3, image_size=8, patch_size=4)
        tcfg = TrainConfig(
            batch_size=8, learning_rate=1e-3, quantized_reduce=True,
            telemetry_level="scalars",
        )
        tr = DistributedTrainer(cfg, tcfg, MeshConfig(data=8))
        img = np.random.default_rng(0).normal(size=(8, 3, 8, 8)).astype(np.float32)
        m = tr.step(img)
        assert 0.0 < float(m["quant_rel_err"]) < 0.05


class TestWatchdog:
    def _wd(self, probes, **kw):
        from glom_tpu.telemetry.watchdog import BackendWatchdog

        seq = iter(probes)
        t = [0.0]

        def probe(timeout):
            return next(seq)

        def clock():
            t[0] += 10.0
            return t[0]

        kw.setdefault("clock", clock)
        return BackendWatchdog(probe=probe, **kw)

    def test_transitions_up_down(self):
        wd = self._wd([8, None, 8])
        assert wd.probe_once() == "up"
        assert wd.probe_once() == "down"
        events = wd.timeline()
        assert [e["backend_state"] for e in events] == ["up", "down"]
        for e in events:
            assert schema.validate_record(e) == [], e
        rec = wd.record()
        assert rec["backend_state"] == "down"
        assert rec["backend_transitions"] == 2

    def test_flapping_detected(self):
        """The round-5 signature: down/up/down/up inside the window must
        surface as 'flapping', not plain 'up'."""
        wd = self._wd(
            [8, None, 8, None, 8], flap_window_s=600.0, flap_threshold=3
        )
        states = [wd.probe_once() for _ in range(5)]
        assert states[-1] == "flapping"
        assert "flapping" in [e["backend_state"] for e in wd.timeline()]

    def test_flap_settles_back_to_up(self):
        # After the window drains with steady up probes, state settles.
        wd = self._wd(
            [8, None, 8, None] + [8] * 30,
            flap_window_s=100.0,  # 10 s per probe tick -> drains fast
            flap_threshold=3,
        )
        states = [wd.probe_once() for _ in range(20)]
        assert "flapping" in states
        assert states[-1] == "up"

    def test_writer_receives_stamped_events(self):
        class Sink:
            def __init__(self):
                self.records = []

            def write(self, rec):
                self.records.append(rec)

        sink = Sink()
        wd = self._wd([8, None], writer=sink)
        wd.probe_once()
        wd.probe_once()
        assert len(sink.records) == 2
        for r in sink.records:
            assert r["kind"] == "watchdog"
            assert schema.validate_record(r) == [], r

    def test_probe_exception_never_escapes_thread(self):
        import time as _time

        from glom_tpu.telemetry.watchdog import BackendWatchdog

        def bad_probe(timeout):
            raise RuntimeError("boom")

        wd = BackendWatchdog(probe=bad_probe, interval_s=0.01)
        wd.start()
        _time.sleep(0.1)
        wd.stop()  # must not raise, thread must join

    def test_global_registration_and_backend_record(self):
        from glom_tpu.telemetry.watchdog import (
            backend_record,
            set_global_watchdog,
        )

        wd = self._wd([None])
        wd.probe_once()
        set_global_watchdog(wd)
        try:
            assert backend_record()["backend_state"] == "down"
        finally:
            set_global_watchdog(None)
        # without a global watchdog: in-process backend is live under the
        # test suite (jax already initialized) -> "up"
        assert backend_record()["backend_state"] in ("up", "unknown")


class TestWatchdogHeartbeat:
    """Low-cadence "up"-confirmation events: a silent hang must leave a
    timestamped ring, not a stale buffer (ROADMAP backlog item)."""

    def _wd(self, probes, **kw):
        from glom_tpu.telemetry.watchdog import BackendWatchdog

        seq = iter(probes)
        t = [0.0]

        def probe(timeout):
            return next(seq)

        def clock():
            t[0] += 10.0
            return t[0]

        kw.setdefault("clock", clock)
        return BackendWatchdog(probe=probe, **kw)

    def _sink(self):
        class Sink:
            def __init__(self):
                self.records = []

            def write(self, rec):
                self.records.append(rec)

        return Sink()

    def test_heartbeat_fires_at_cadence_between_transitions(self):
        sink = self._sink()
        # 10s clock ticks, 25s cadence: probes at t=10 (transition), then
        # re-confirmations at 20,30,40,... — heartbeats land every >= 25s
        # after the last stamped event.
        wd = self._wd([8] * 10, writer=sink, heartbeat_s=25.0)
        for _ in range(10):
            wd.probe_once()
        beats = [r for r in sink.records if r.get("event") == "heartbeat"]
        transitions = [
            r for r in sink.records if r.get("event") == "backend_transition"
        ]
        assert len(transitions) == 1  # unknown -> up, once
        assert len(beats) >= 2
        for b in beats:
            assert b["kind"] == "watchdog"
            assert b["backend_state"] == "up"
            assert schema.validate_record(b) == [], b
        # Cadence respected: consecutive stamped events >= heartbeat_s apart.
        times = [r["t"] for r in sink.records]
        assert all(b - a >= 25.0 for a, b in zip(times, times[1:]))

    def test_no_heartbeat_when_disabled(self):
        sink = self._sink()
        wd = self._wd([8] * 10, writer=sink, heartbeat_s=0.0)
        for _ in range(10):
            wd.probe_once()
        assert all(
            r.get("event") != "heartbeat" for r in sink.records
        )

    def test_no_heartbeat_while_down(self):
        """A repeated "down" heartbeat would re-trigger the flight
        recorder's backend-down dump every probe — only UP confirms."""
        sink = self._sink()
        wd = self._wd([8, None, None, None, None], writer=sink,
                      heartbeat_s=15.0)
        for _ in range(5):
            wd.probe_once()
        beats = [r for r in sink.records if r.get("event") == "heartbeat"]
        assert all(b["backend_state"] == "up" for b in beats)
        # While down, the only events are transitions.
        down_events = [
            r for r in sink.records
            if r.get("backend_state") == "down"
        ]
        assert all(
            r.get("event") == "backend_transition" for r in down_events
        )

    def test_heartbeat_feeds_flight_ring_without_writer(self):
        from glom_tpu.tracing.flight import (
            FlightRecorder,
            set_global_flight_recorder,
        )

        fr = FlightRecorder("/tmp/_hb_flight_unused", capacity=16)
        set_global_flight_recorder(fr)
        try:
            wd = self._wd([8] * 6, heartbeat_s=15.0)
            for _ in range(6):
                wd.probe_once()
        finally:
            set_global_flight_recorder(None)
        buffered = list(fr._buf)
        assert any(r.get("event") == "heartbeat" for r in buffered)
        assert not fr.dumps  # up-confirmations never trigger a dump


class TestSinks:
    def test_step_time_stats_splits_compile(self):
        from glom_tpu.telemetry.sinks import StepTimeStats

        s = StepTimeStats()
        s.observe(5.0)  # compile
        for _ in range(10):
            s.observe(0.010)
        s.observe(0.100)  # one straggler
        out = s.summary()
        assert out["compile_time_s"] == 5.0
        assert out["steps_timed"] == 11
        assert out["step_time_p50_ms"] == pytest.approx(10.0, rel=0.2)
        assert out["step_time_max_ms"] == pytest.approx(100.0, rel=0.01)
        assert out["step_time_p95_ms"] <= out["step_time_max_ms"]

    def test_fit_records_carry_histogram_and_schema(self):
        from glom_tpu.train.trainer import Trainer
        from glom_tpu.data import shapes_dataset

        tr = Trainer(CFG, small_tcfg(telemetry_level="scalars"))
        h = tr.fit(shapes_dataset(4, 8, seed=0), num_steps=3, log_every=2)
        for rec in h:
            assert rec["schema_version"] == schema.SCHEMA_VERSION
            assert rec["kind"] == "train_step"
            for key in (
                "compile_time_s",
                "step_time_p50_ms",
                "step_time_p95_ms",
                "step_time_max_ms",
            ):
                assert key in rec, key
            assert schema.validate_record(rec) == [], rec
        # BOTH jit variants' first calls (fast step at i=0, logging step at
        # i=1) are compile — only i=2 is a steady-state sample.
        assert h[-1]["steps_timed"] == 1
        assert h[-1]["compile_time_s"] > 0
        # Span 2: the jit cache is warm and the compile tracker persists
        # across fit() calls (the checkpoint-span pattern) — every step is
        # a steady-state sample and no fake compile is recorded.
        h2 = tr.fit(shapes_dataset(4, 8, seed=1), num_steps=3, log_every=2)
        assert h2[-1]["steps_timed"] == 3
        assert h2[-1]["compile_time_s"] == 0.0  # nothing compiled this span

    def test_emit_stamps_and_prints(self, capsys):
        from glom_tpu.telemetry.sinks import emit

        out = emit({"metric": "m", "value": 1.0, "unit": "u"})
        printed = json.loads(capsys.readouterr().out.strip())
        assert printed == json.loads(json.dumps(out))
        assert printed["schema_version"] == schema.SCHEMA_VERSION
        assert printed["kind"] == "bench"
        assert "backend_state" in printed


@pytest.mark.slow
class TestOverheadBudget:
    def test_scalars_overhead_under_budget(self):
        """CPU smoke A/B: telemetry_level=scalars must stay within the 2%
        per-step budget (generous 10% runtime guard against shared-runner
        noise; the 2% bar itself is enforced on real hardware by the
        hw-queue's telemetry_ab step — this keeps gross regressions out).
        Arms INTERLEAVE per repeat, min per arm — sequential arms on a
        multi-tenant runner confound the A/B with clock drift (measured
        +24% sequential vs +1.3% interleaved for the same pair)."""
        import time

        from glom_tpu.train.trainer import create_train_state, make_train_step

        cfg = GlomConfig(dim=128, levels=4, image_size=32, patch_size=4)
        img = jax.random.normal(
            jax.random.PRNGKey(1), (8, 3, 32, 32), jnp.float32
        )
        rng = jax.random.PRNGKey(2)
        steps, states = {}, {}
        for level in ("off", "scalars"):
            tcfg = TrainConfig(
                batch_size=8, learning_rate=1e-3, telemetry_level=level
            )
            state, opt = create_train_state(jax.random.PRNGKey(0), cfg, tcfg)
            step = jax.jit(
                make_train_step(cfg, tcfg, opt, with_grad_norm=False),
                donate_argnums=(0,),
            )
            state, m = step(state, img, rng)
            jax.block_until_ready(m["loss"])
            steps[level], states[level] = step, state
        times = {"off": float("inf"), "scalars": float("inf")}
        for rep in range(4):
            order = ("off", "scalars") if rep % 2 == 0 else ("scalars", "off")
            for level in order:
                step, state = steps[level], states[level]
                t0 = time.perf_counter()
                for i in range(6):
                    state, m = step(state, img, jax.random.fold_in(rng, i))
                jax.block_until_ready(m["loss"])
                times[level] = min(
                    times[level], (time.perf_counter() - t0) / 6
                )
                states[level] = state
        overhead = times["scalars"] / times["off"] - 1.0
        assert overhead < 0.10, f"telemetry overhead {overhead:.1%}"
