"""ZeRO-style sharded weight update (Xu et al. 2020, arXiv:2004.13336):
parity, memory-model, comm-model, and quantized-reduce tests on the
8-device virtual CPU mesh.

The acceptance bar: dp=8 ZeRO-1 training must match the unsharded baseline
step-for-step (losses AND params), the per-replica optimizer-state bytes
reported by the live-bytes model must drop ~dp x, and every metrics record
must carry zero_stage + the comm-volume counters."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from glom_tpu.data import shapes_dataset
from glom_tpu.parallel import DistributedTrainer
from glom_tpu.train import Trainer
from glom_tpu.utils.config import GlomConfig, MeshConfig, TrainConfig

CFG = GlomConfig(dim=16, levels=4, image_size=8, patch_size=2)  # n=16
COMM_KEYS = (
    "comm_reduce_bytes_per_step",
    "comm_gather_bytes_per_step",
    "comm_bytes_per_step",
)


def _fit_pair(cfg, tcfg_a, tcfg_b, mesh_b, steps=3, **kw_b):
    single = Trainer(cfg, tcfg_a)
    dist = DistributedTrainer(cfg, tcfg_b, mesh_b, **kw_b)
    h1 = single.fit(shapes_dataset(tcfg_a.batch_size, cfg.image_size, seed=3),
                    steps, log_every=1)
    h2 = dist.fit(shapes_dataset(tcfg_b.batch_size, cfg.image_size, seed=3),
                  steps, log_every=1)
    return single, dist, h1, h2


class TestZeroParity:
    def test_dp8_zero1_matches_unsharded_step_for_step(self):
        """The acceptance criterion: dp=8 ZeRO-1 == single device, loss AND
        params, every step, <= 1e-5 rel."""
        tcfg = TrainConfig(batch_size=8, learning_rate=1e-3, noise_std=0.3,
                           seed=5)
        ztcfg = TrainConfig(batch_size=8, learning_rate=1e-3, noise_std=0.3,
                            seed=5, zero_stage=1)
        single, dist, h1, h2 = _fit_pair(
            CFG, tcfg, ztcfg, MeshConfig(data=8), steps=3
        )
        assert dist.zero_stage == 1
        for a, b in zip(h1, h2):
            np.testing.assert_allclose(a["loss"], b["loss"], rtol=1e-5)
        for x, y in zip(
            jax.tree_util.tree_leaves(single.state.params),
            jax.tree_util.tree_leaves(dist.state.params),
        ):
            np.testing.assert_allclose(
                np.asarray(x), np.asarray(y), rtol=1e-5, atol=1e-6
            )
        # Optimizer moments must match too — they took the sharded update.
        for x, y in zip(
            jax.tree_util.tree_leaves(single.state.opt_state),
            jax.tree_util.tree_leaves(dist.state.opt_state),
        ):
            np.testing.assert_allclose(
                np.asarray(x), np.asarray(y), rtol=1e-5, atol=1e-6
            )

    @pytest.mark.slow
    def test_zero_vs_zero0_distributed_parity(self):
        """Stage 1 vs stage 0 on the SAME dp=8 mesh: identical training."""
        mk = lambda stage: TrainConfig(
            batch_size=8, learning_rate=1e-3, noise_std=0.3, seed=7,
            zero_stage=stage,
        )
        d0 = DistributedTrainer(CFG, mk(0), MeshConfig(data=8))
        d1 = DistributedTrainer(CFG, mk(1), MeshConfig(data=8))
        h0 = d0.fit(shapes_dataset(8, CFG.image_size, seed=4), 3, log_every=1)
        h1 = d1.fit(shapes_dataset(8, CFG.image_size, seed=4), 3, log_every=1)
        for a, b in zip(h0, h1):
            np.testing.assert_allclose(a["loss"], b["loss"], rtol=1e-5)
        for x, y in zip(
            jax.tree_util.tree_leaves(d0.state.params),
            jax.tree_util.tree_leaves(d1.state.params),
        ):
            np.testing.assert_allclose(
                np.asarray(x), np.asarray(y), rtol=1e-5, atol=1e-6
            )

    @pytest.mark.slow
    def test_zero2_grad_accum_matches_unsharded(self):
        """Stage 2 (sharded grad accumulator) with grad_accum=2 must still
        be exact: scatter-then-accumulate == accumulate-then-scatter."""
        tcfg = TrainConfig(batch_size=16, learning_rate=1e-3, noise_std=0.3,
                           seed=5, grad_accum=2)
        ztcfg = TrainConfig(batch_size=16, learning_rate=1e-3, noise_std=0.3,
                            seed=5, grad_accum=2, zero_stage=2)
        single, dist, h1, h2 = _fit_pair(
            CFG, tcfg, ztcfg, MeshConfig(data=8), steps=2
        )
        assert dist.zero_stage == 2
        for a, b in zip(h1, h2):
            np.testing.assert_allclose(a["loss"], b["loss"], rtol=1e-5)
        for x, y in zip(
            jax.tree_util.tree_leaves(single.state.params),
            jax.tree_util.tree_leaves(dist.state.params),
        ):
            np.testing.assert_allclose(
                np.asarray(x), np.asarray(y), rtol=1e-5, atol=1e-6
            )

    @pytest.mark.slow
    def test_dp2_tp2_zero1_composition(self):
        """ZeRO x TP: the zero shard axes avoid the 'model'-taken axes.
        Losses match single device; params are compared zero1-vs-zero0 on
        the SAME mesh (TP already reorders the f32 psum contractions, and
        Adam's elementwise normalization amplifies that to O(lr) on
        near-zero gradients — the pre-existing reason the TP parity test
        asserts losses only)."""
        mk = lambda stage: TrainConfig(
            batch_size=4, learning_rate=1e-3, noise_std=0.3, seed=5,
            zero_stage=stage,
        )
        mesh = MeshConfig(data=2, seq=1, model=2)
        single, dist, h1, h2 = _fit_pair(CFG, mk(0), mk(1), mesh, steps=2)
        assert dist.zero_stage == 1
        for a, b in zip(h1, h2):
            np.testing.assert_allclose(a["loss"], b["loss"], rtol=1e-4)
        d0 = DistributedTrainer(CFG, mk(0), mesh)
        h0 = d0.fit(shapes_dataset(4, CFG.image_size, seed=3), 2, log_every=1)
        for a, b in zip(h0, h2):
            np.testing.assert_allclose(a["loss"], b["loss"], rtol=1e-5)
        for x, y in zip(
            jax.tree_util.tree_leaves(d0.state.params),
            jax.tree_util.tree_leaves(dist.state.params),
        ):
            np.testing.assert_allclose(
                np.asarray(x), np.asarray(y), rtol=1e-4, atol=1e-6
            )

    @pytest.mark.slow
    def test_dp4_sp2_zero1_trains(self):
        """ZeRO x SP: grads psum over 'seq' before the 'data' scatter."""
        tcfg = TrainConfig(batch_size=4, learning_rate=1e-3, noise_std=0.3,
                           seed=5)
        ztcfg = TrainConfig(batch_size=4, learning_rate=1e-3, noise_std=0.3,
                            seed=5, zero_stage=1)
        single, dist, h1, h2 = _fit_pair(
            CFG, tcfg, ztcfg, MeshConfig(data=4, seq=2), steps=2,
            sp_strategy="ring",
        )
        for a, b in zip(h1, h2):
            np.testing.assert_allclose(a["loss"], b["loss"], rtol=1e-4)

    def test_manual_path_zero1_matches_unsharded(self):
        """The EXPLICIT psum_scatter/all_gather shard_map variant
        (use_pallas routes manual): dp=8 ZeRO-1 == single device."""
        tcfg = TrainConfig(batch_size=8, learning_rate=1e-3, noise_std=0.3,
                           seed=5, use_pallas=True)
        ztcfg = TrainConfig(batch_size=8, learning_rate=1e-3, noise_std=0.3,
                            seed=5, use_pallas=True, zero_stage=1)
        single, dist, h1, h2 = _fit_pair(
            CFG, tcfg, ztcfg, MeshConfig(data=8), steps=3
        )
        assert dist.use_manual and dist.zero_stage == 1
        for a, b in zip(h1, h2):
            np.testing.assert_allclose(a["loss"], b["loss"], rtol=1e-5)
        for x, y in zip(
            jax.tree_util.tree_leaves(single.state.params),
            jax.tree_util.tree_leaves(dist.state.params),
        ):
            np.testing.assert_allclose(
                np.asarray(x), np.asarray(y), rtol=1e-5, atol=1e-6
            )

    @pytest.mark.slow
    def test_manual_zero2_accum_matches(self):
        """Manual stage 2: per-microbatch scatter inside the region."""
        tcfg = TrainConfig(batch_size=16, learning_rate=1e-3, noise_std=0.3,
                           seed=5, use_pallas=True, grad_accum=2)
        ztcfg = TrainConfig(batch_size=16, learning_rate=1e-3, noise_std=0.3,
                            seed=5, use_pallas=True, grad_accum=2,
                            zero_stage=2)
        single, dist, h1, h2 = _fit_pair(
            CFG, tcfg, ztcfg, MeshConfig(data=8), steps=2
        )
        for a, b in zip(h1, h2):
            np.testing.assert_allclose(a["loss"], b["loss"], rtol=1e-5)
        for x, y in zip(
            jax.tree_util.tree_leaves(single.state.params),
            jax.tree_util.tree_leaves(dist.state.params),
        ):
            np.testing.assert_allclose(
                np.asarray(x), np.asarray(y), rtol=1e-5, atol=1e-6
            )


class TestZeroResolutionAndRecords:
    def test_dp1_resolves_to_stage0(self):
        from glom_tpu.train.trainer import resolve_zero_stage

        tcfg = TrainConfig(zero_stage=1)
        assert resolve_zero_stage(tcfg, 1) == 0
        assert resolve_zero_stage(tcfg, 8) == 1
        with pytest.raises(ValueError, match="zero_stage"):
            resolve_zero_stage(TrainConfig(zero_stage=3), 8)

    def test_records_carry_zero_stage_and_comm(self):
        """Every metrics record — single AND distributed, any stage —
        names zero_stage and the comm-volume counters."""
        tcfg = TrainConfig(batch_size=8, learning_rate=1e-3, noise_std=0.3,
                           seed=5, zero_stage=1)
        single = Trainer(CFG, tcfg)
        h = single.fit(shapes_dataset(8, CFG.image_size, seed=3), 2, log_every=1)
        for m in h:
            assert m["zero_stage"] == 0  # dp=1 resolves to 0
            for k in COMM_KEYS:
                assert m[k] == 0
        dist = DistributedTrainer(CFG, tcfg, MeshConfig(data=8))
        h = dist.fit(shapes_dataset(8, CFG.image_size, seed=3), 2, log_every=1)
        for m in h:
            assert m["zero_stage"] == 1
            assert m["opt_bytes_per_replica"] > 0
            assert m["comm_reduce_bytes_per_step"] > 0
            assert m["comm_gather_bytes_per_step"] > 0

    def test_opt_bytes_drop_8x_at_dp8(self):
        """The acceptance criterion: per-replica optimizer-state bytes at
        zero_stage=1/dp=8 must be ~8x below the replicated layout. CFG's
        leaves are all dp-divisible on some axis except the tiny biases,
        so 'approximately': within 25% of the full 8x."""
        tcfg = lambda s: TrainConfig(batch_size=8, noise_std=0.3, zero_stage=s)
        d0 = DistributedTrainer(CFG, tcfg(0), MeshConfig(data=8))
        d1 = DistributedTrainer(CFG, tcfg(1), MeshConfig(data=8))
        full = d0._static_record["opt_bytes_per_replica"]
        shard = d1._static_record["opt_bytes_per_replica"]
        assert full > 0 and shard > 0
        ratio = full / shard
        assert ratio > 8 * 0.75, f"opt-state only dropped {ratio:.2f}x"
        # params stay replicated in both layouts
        assert (
            d0._static_record["params_bytes_per_replica"]
            == d1._static_record["params_bytes_per_replica"]
        )

    def test_opt_state_actually_sharded_on_device(self):
        """Not just the model: the live opt-state arrays at stage 1 must
        occupy 1/dp the per-device memory of the replicated layout."""
        tcfg = lambda s: TrainConfig(batch_size=8, noise_std=0.3, zero_stage=s)
        d0 = DistributedTrainer(CFG, tcfg(0), MeshConfig(data=8))
        d1 = DistributedTrainer(CFG, tcfg(1), MeshConfig(data=8))

        def dev_bytes(state):
            total = 0
            for leaf in jax.tree_util.tree_leaves(state.opt_state):
                shard = leaf.addressable_shards[0]
                total += int(np.prod(shard.data.shape)) * leaf.dtype.itemsize
            return total

        assert dev_bytes(d1.state) * 4 < dev_bytes(d0.state)

    def test_comm_model_stage_accounting(self):
        from glom_tpu.utils.metrics import comm_volume_model

        G = P = 1000 * 4
        s0 = comm_volume_model(G, P, 8, 0)
        s1 = comm_volume_model(G, P, 8, 1)
        s2 = comm_volume_model(G, P, 8, 2, grad_accum=4)
        # allreduce = 2*(dp-1)/dp*G; rs+ag = (dp-1)/dp*(G+P): equal when
        # G == P — ZeRO's wire bytes are never worse than allreduce.
        assert s0["comm_bytes_per_step"] == s1["comm_bytes_per_step"]
        assert s1["comm_gather_bytes_per_step"] > 0
        # stage 2 pays the scatter once per microbatch
        assert (
            s2["comm_reduce_bytes_per_step"]
            == 4 * s1["comm_reduce_bytes_per_step"]
        )
        # quantized reduce shrinks the grad leg ~4x, not the param gather
        q1 = comm_volume_model(G, P, 8, 1, quantized=True)
        assert q1["comm_gather_bytes_per_step"] == s1["comm_gather_bytes_per_step"]
        assert q1["comm_reduce_bytes_per_step"] < s1["comm_reduce_bytes_per_step"] / 3
        assert comm_volume_model(G, P, 1, 1)["comm_bytes_per_step"] == 0

    def test_zero_shard_axis_selection(self):
        from jax.sharding import PartitionSpec as P

        from glom_tpu.parallel.sharding import zero_shard_axis

        # largest dp-divisible free axis wins
        assert zero_shard_axis((4, 16, 64), P(None, None, None), 8) == 2
        # 'model'-taken axes are never chosen
        assert zero_shard_axis((4, 16, 64), P(None, None, "model"), 8) == 1
        # no divisible axis -> None (leaf stays replicated)
        assert zero_shard_axis((3, 5), P(None, None), 8) is None
        assert zero_shard_axis((16,), P(None), 1) is None


class TestQuantizedReduce:
    def test_round_trip_error_bound(self, rng):
        from glom_tpu.parallel.quantized import (
            INT8_MAX,
            block_dequantize_int8,
            block_quantize_int8,
            quantize_dequantize,
        )

        x = jnp.asarray(rng.normal(size=(37, 129)) * 3.0, jnp.float32)
        q, scales, n_pad = block_quantize_int8(x, block=128)
        assert q.dtype == jnp.int8
        y = block_dequantize_int8(q, scales, n_pad, x.shape, x.dtype)
        # per-element bound: half a quantization step of the block scale
        err = np.abs(np.asarray(x - y))
        bound = np.asarray(scales).reshape(-1)[:, None] / 2 + 1e-7
        flat_err = np.pad(err.reshape(-1), (0, n_pad)).reshape(-1, 128)
        assert (flat_err <= bound).all()
        # zeros round-trip exactly; idempotent qdq
        assert float(jnp.abs(quantize_dequantize(jnp.zeros((64,)))).max()) == 0
        z = quantize_dequantize(x)
        np.testing.assert_allclose(
            np.asarray(quantize_dequantize(z)), np.asarray(z), atol=1e-6
        )
        # scale construction: max-abs / 127 per block
        blocks = np.pad(np.asarray(x).reshape(-1), (0, n_pad)).reshape(-1, 128)
        np.testing.assert_allclose(
            np.asarray(scales).reshape(-1),
            np.abs(blocks).max(axis=1) / INT8_MAX,
            rtol=1e-6,
        )

    @pytest.mark.slow
    def test_quantized_training_runs_and_stays_close(self):
        """quantized_reduce=True trains (finite losses) on both paths and
        stays within the coarse quantization band of the exact run."""
        tcfg = TrainConfig(batch_size=8, learning_rate=1e-3, noise_std=0.3,
                           seed=5, zero_stage=1)
        qtcfg = TrainConfig(batch_size=8, learning_rate=1e-3, noise_std=0.3,
                            seed=5, zero_stage=1, quantized_reduce=True)
        exact = DistributedTrainer(CFG, tcfg, MeshConfig(data=8))
        quant = DistributedTrainer(CFG, qtcfg, MeshConfig(data=8))
        he = exact.fit(shapes_dataset(8, CFG.image_size, seed=3), 3, log_every=1)
        hq = quant.fit(shapes_dataset(8, CFG.image_size, seed=3), 3, log_every=1)
        for a, b in zip(he, hq):
            assert np.isfinite(b["loss"])
            np.testing.assert_allclose(a["loss"], b["loss"], rtol=5e-2)
            assert b["quantized_reduce"]
        # the record must also show the cheaper wire
        assert (
            hq[0]["comm_reduce_bytes_per_step"]
            < he[0]["comm_reduce_bytes_per_step"]
        )

    @pytest.mark.slow
    def test_manual_quantized_zero_trains(self):
        tcfg = TrainConfig(batch_size=8, learning_rate=1e-3, noise_std=0.3,
                           seed=5, use_pallas=True, zero_stage=1,
                           quantized_reduce=True)
        dist = DistributedTrainer(CFG, tcfg, MeshConfig(data=8))
        h = dist.fit(shapes_dataset(8, CFG.image_size, seed=3), 2, log_every=1)
        assert all(np.isfinite(m["loss"]) for m in h)
