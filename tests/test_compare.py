"""Bench-trajectory compare gate tests (telemetry/compare.py) + the v2
schema kinds it rides on. Pure stdlib paths — no jax, no compiles."""

import json

import pytest

from glom_tpu.telemetry import schema
from glom_tpu.telemetry.compare import (
    compare_files,
    compare_records,
    load_bench_records,
    lower_is_better,
    main as compare_main,
)

FIXTURE_BASE = "tests/fixtures/bench_base.jsonl"
FIXTURE_NEW = "tests/fixtures/bench_new.jsonl"


def bench(metric, value, unit="column-iters/s/chip", **kw):
    return json.dumps(
        schema.stamp({"metric": metric, "value": value, "unit": unit, **kw},
                     kind="bench")
    )


def error_row(metric, err="backend-init-unavailable", unit="column-iters/s/chip"):
    return json.dumps(
        schema.stamp({"metric": metric, "value": None, "unit": unit,
                      "error": err}, kind="error")
    )


def run(base_lines, new_lines, threshold=0.05):
    bm, bu = load_bench_records(base_lines)
    nm, nu = load_bench_records(new_lines)
    return compare_records(bm, bu, nm, nu, threshold=threshold)


class TestSchemaV2Kinds:
    def test_span_and_error_kinds_validate(self):
        span = schema.stamp({"name": "host_data_next", "dur_s": 0.5},
                            kind="span")
        err = schema.stamp(
            {"metric": "m", "value": None, "error": "backend-init-unavailable"},
            kind="error",
        )
        assert span["schema_version"] == schema.SCHEMA_VERSION == 11
        assert schema.validate_record(span) == []
        assert schema.validate_record(err) == []
        # missing required fields are rejected
        assert schema.validate_record(
            {"kind": "span", "schema_version": 2, "name": "x"}) != []
        assert schema.validate_record(
            {"kind": "error", "schema_version": 2, "value": None}) != []

    def test_version_1_records_still_validate(self):
        old = {"kind": "bench", "schema_version": 1, "metric": "m",
               "value": 1.0, "unit": "u"}
        assert schema.validate_record(old) == []

    def test_infer_kind_for_new_shapes(self):
        assert schema.infer_kind({"name": "s", "dur_s": 1.0}) == "span"
        assert schema.infer_kind(
            {"metric": "m", "value": None, "error": "down"}) == "error"
        # a MEASURED row with an error context field stays a bench row
        assert schema.infer_kind(
            {"metric": "m", "value": 3.0, "error": "retried-once"}) == "bench"


class TestDirection:
    def test_rates_regress_down_costs_regress_up(self):
        assert not lower_is_better("train_step cips", "column-iters/s/chip")
        assert not lower_is_better("sp_crossover speedup", "x")
        assert lower_is_better("telemetry overhead", "percent")
        assert lower_is_better("longctx fused", "ms/call")
        assert lower_is_better("live_bytes_model_total", "bytes")
        assert lower_is_better("span_overhead thing", "percent")


class TestLoad:
    def test_skips_noise_and_classifies_unmeasured(self):
        lines = [
            "=== shell noise\n",
            bench("m1", 10.0),
            error_row("m2"),
            json.dumps(schema.stamp({"note": "ctx"}, kind="note")),
            # legacy round-5 dead zero: value 0.0 + error field
            json.dumps({"metric": "m3", "value": 0.0, "vs_baseline": 0.0,
                        "error": "backend-init-unavailable"}),
        ]
        measured, unmeasured = load_bench_records(lines)
        assert list(measured) == ["m1"]
        assert set(unmeasured) == {"m2", "m3"}

    def test_repeats_collapse_to_best(self):
        lines = [bench("m", v) for v in (10.0, 12.0, 11.0)]
        results = run(lines, [bench("m", 11.9)])
        (r,) = results
        # best-of-base is 12.0 (higher-better): 11.9 is inside noise
        assert r["base"] == 12.0
        assert r["status"] == "ok"


class TestVerdicts:
    def test_regression_beyond_threshold(self):
        (r,) = run([bench("m", 100.0)], [bench("m", 90.0)])
        assert r["status"] == "regression"
        assert r["rel_change"] == pytest.approx(-0.1)

    def test_within_noise_is_ok(self):
        (r,) = run([bench("m", 100.0)], [bench("m", 96.0)])
        assert r["status"] == "ok"

    def test_improvement(self):
        (r,) = run([bench("m", 100.0)], [bench("m", 120.0)])
        assert r["status"] == "improvement"

    def test_lower_is_better_flips_direction(self):
        (r,) = run(
            [bench("overhead", 1.0, unit="percent")],
            [bench("overhead", 1.5, unit="percent")],
        )
        assert r["status"] == "regression"
        (r,) = run(
            [bench("overhead", 1.5, unit="percent")],
            [bench("overhead", 1.0, unit="percent")],
        )
        assert r["status"] == "improvement"

    def test_unmeasured_is_missing_never_zero(self):
        """THE round-5 fix: an UNMEASURED row must neither read as a 100%
        regression (value->0) nor fail the gate."""
        results = run([bench("m", 100.0)], [error_row("m")])
        (r,) = results
        assert r["status"] == "unmeasured-in-new"
        assert r["error"] == "backend-init-unavailable"
        assert "rel_change" not in r

    def test_legacy_dead_zero_in_new_is_missing(self):
        legacy = json.dumps({"metric": "m", "value": 0.0,
                             "error": "backend-init-unavailable"})
        (r,) = run([bench("m", 100.0)], [legacy])
        assert r["status"] == "unmeasured-in-new"

    def test_recovery_from_unmeasured_base(self):
        (r,) = run([error_row("m")], [bench("m", 50.0)])
        assert r["status"] == "recovered"
        assert r["new"] == 50.0

    def test_recovered_cost_metric_reports_best_repeat(self):
        # lower-is-better recovery: report the benches' best-of-repeats
        # (min), not the worst.
        (r,) = run(
            [error_row("m", unit="ms/call")],
            [bench("m", 15.0, unit="ms/call"), bench("m", 12.0, unit="ms/call")],
        )
        assert r["status"] == "recovered"
        assert r["new"] == 12.0

    def test_new_only_unmeasured_row_is_reported(self):
        # A brand-new bench that failed on its first run must still show
        # up in the report (it would otherwise silently vanish).
        results = run([bench("a", 1.0)], [bench("a", 1.0), error_row("b")])
        by = {r["metric"]: r for r in results}
        assert by["b"]["status"] == "unmeasured-new-only"
        assert by["b"]["error"] == "backend-init-unavailable"

    def test_bootstrap_error_row_matches_measured_label(self, tmp_path, capsys):
        """THE label contract: bench_bootstrap's UNMEASURED row carries
        the bare metric label, so an outage compares as
        'unmeasured-in-new' against the measured baseline — not as a
        vanished metric."""
        from unittest import mock

        from glom_tpu.telemetry import sinks

        wd = mock.Mock()
        wd.probe_once.return_value = "down"
        wd.timeline.return_value = []
        wd.record.return_value = {"backend_state": "down"}
        with mock.patch(
            "glom_tpu.telemetry.watchdog.BackendWatchdog", return_value=wd
        ), mock.patch.dict("os.environ", {}, clear=False):
            try:
                assert sinks.bench_bootstrap("my_metric", "u") is False
            finally:
                from glom_tpu.telemetry.watchdog import set_global_watchdog

                set_global_watchdog(None)
        row = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert row["kind"] == "error" and row["value"] is None
        assert row["metric"] == "my_metric"  # bare — matches measured rows
        (r,) = run([bench("my_metric", 5.0, unit="u")], [json.dumps(row)])
        assert r["status"] == "unmeasured-in-new"

    def test_new_metric_reported(self):
        results = run([bench("a", 1.0)], [bench("a", 1.0), bench("b", 2.0)])
        by = {r["metric"]: r for r in results}
        assert by["b"]["status"] == "new-metric"

    def test_threshold_is_configurable(self):
        (r,) = run([bench("m", 100.0)], [bench("m", 90.0)], threshold=0.2)
        assert r["status"] == "ok"


class TestCli:
    def test_fixture_pair_fails_the_gate(self, capsys):
        """The committed CI fixture pair: one regression, one improvement,
        one UNMEASURED — the gate must exit nonzero (the regression) while
        the unmeasured row stays a warning."""
        rc = compare_main([FIXTURE_BASE, FIXTURE_NEW])
        assert rc == 1
        out = capsys.readouterr()
        summary = json.loads(out.out.strip().splitlines()[-1])
        assert summary["kind"] == "summary"
        assert summary["n_regression"] == 1
        assert summary["n_improvement"] == 1
        assert summary["n_unmeasured_in_new"] == 1
        assert schema.validate_record(summary) == []

    def test_self_compare_passes(self, capsys):
        assert compare_main([FIXTURE_BASE, FIXTURE_BASE]) == 0

    def test_fail_on_missing_flag(self, tmp_path, capsys):
        base = tmp_path / "b.jsonl"
        new = tmp_path / "n.jsonl"
        base.write_text(bench("gone", 5.0) + "\n")
        new.write_text(bench("other", 5.0) + "\n")
        assert compare_main([str(base), str(new)]) == 0
        assert compare_main([str(base), str(new), "--fail-on-missing"]) == 1

    def test_compare_files_roundtrip(self):
        results = compare_files(FIXTURE_BASE, FIXTURE_NEW)
        statuses = {r["status"] for r in results}
        assert "regression" in statuses and "unmeasured-in-new" in statuses


class TestBenchArtifacts:
    def test_artifact_tail_rows_compare(self, tmp_path):
        """BENCH_r0x.json round artifacts (one JSON object, bench rows in
        "tail") ride the same gate; legacy value-0.0 + error dead zeros
        classify UNMEASURED, never zero."""
        import json

        from glom_tpu.telemetry.compare import artifact_lines, compare_files

        base = tmp_path / "BENCH_r01.json"
        new = tmp_path / "BENCH_r02.json"
        row = {"metric": "fwd x", "value": 100.0, "unit": "col/s",
               "kind": "bench", "schema_version": 4}
        dead = {"metric": "train y (UNMEASURED)", "value": 0.0,
                "unit": "col/s", "error": "backend-init-unavailable"}
        base.write_text(json.dumps(
            {"n": 1, "tail": json.dumps(row) + "\n" + json.dumps(dead)}
        ))
        slower = dict(row, value=50.0)
        new.write_text(json.dumps({"n": 2, "tail": json.dumps(slower)}))
        assert len(artifact_lines(str(base))) == 2
        results = compare_files(str(base), str(new), artifacts=True)
        by_metric = {r["metric"]: r for r in results}
        assert by_metric["fwd x"]["status"] == "regression"
        assert by_metric["train y (UNMEASURED)"]["status"] == "unmeasured-both"

    def test_parsed_fallback_when_tail_empty(self, tmp_path):
        import json

        from glom_tpu.telemetry.compare import artifact_lines

        p = tmp_path / "BENCH_r03.json"
        p.write_text(json.dumps(
            {"parsed": {"metric": "m", "value": 1.0, "unit": "x"}}
        ))
        lines = artifact_lines(str(p))
        assert len(lines) == 1 and json.loads(lines[0])["metric"] == "m"


class TestEngineFlatten:
    """PR 10 satellite: serve summaries' per-engine nests flatten into
    synthetic serve_engine.* rows so fan-out regressions confined to one
    engine GATE instead of vanishing (flatten_engine_metrics)."""

    def summary(self, *, config="load=0.5x", dispatches=5, alive=True,
                engines=("engine0", "engine1"), nest_ladder=False):
        eng = {}
        for name in engines:
            st = {"alive": alive, "dispatches": dispatches,
                  "consecutive_failures": 0, "rejoins": 0}
            if nest_ladder:
                st["ladder"] = {"ladder_degrades": 1, "ladder_restores": 1}
                st["retry"] = {"retry_site": f"{name}-dispatch",
                               "n_retries": 2, "n_gave_up": 0}
            eng[name] = st
        return schema.stamp(
            {"event": "summary", "config": config, "n_served": 8,
             "engines": eng},
            kind="serve",
        )

    def lines(self, rec):
        return [json.dumps(rec)]

    def test_flattens_numeric_and_bool_leaves(self):
        from glom_tpu.telemetry.compare import flatten_engine_metrics

        rows = flatten_engine_metrics(self.summary(nest_ladder=True))
        labels = {r["metric"] for r in rows}
        assert "serve_engine.engine0.dispatches (load=0.5x)" in labels
        assert "serve_engine.engine0.alive (load=0.5x)" in labels
        assert "serve_engine.engine0.ladder.ladder_degrades (load=0.5x)" in labels
        assert "serve_engine.engine1.retry.n_retries (load=0.5x)" in labels
        # Strings (retry_site) never flatten; bools flatten as 0/1.
        assert not any("retry_site" in m for m in labels)
        alive = [r for r in rows if r["metric"].endswith(
            "engine0.alive (load=0.5x)")][0]
        assert alive["value"] == 1.0

    def test_non_summary_and_nestless_records_flatten_to_nothing(self):
        from glom_tpu.telemetry.compare import flatten_engine_metrics

        assert flatten_engine_metrics({"event": "dispatch"}) == []
        assert flatten_engine_metrics(
            {"event": "summary", "n_served": 3}) == []

    def test_dead_engine_regression_gates(self):
        """The kill-serve shape: one engine's dispatches drop to zero and
        alive flips 1 -> 0 — both must surface as regressions (counts are
        rates: lower is the regression)."""
        base = self.lines(self.summary(dispatches=5, alive=True))
        new = self.lines(self.summary(dispatches=0, alive=False))
        results = run(base, new)
        by_metric = {r["metric"]: r for r in results}
        assert by_metric[
            "serve_engine.engine0.dispatches (load=0.5x)"
        ]["status"] == "regression"
        assert by_metric[
            "serve_engine.engine0.alive (load=0.5x)"
        ]["status"] == "regression"

    def test_failure_counts_regress_up(self):
        base = self.lines(self.summary(nest_ladder=True))
        new_rec = self.summary(nest_ladder=True)
        new_rec["engines"]["engine0"]["retry"]["n_retries"] = 20
        results = run(base, self.lines(new_rec))
        (row,) = [r for r in results if r["metric"] ==
                  "serve_engine.engine0.retry.n_retries (load=0.5x)"]
        assert row["lower_is_better"] is True
        assert row["status"] == "regression"

    def test_ladder_churn_regresses_up(self):
        """ladder_degrades (and the restores that track it 1:1) are
        failure-ish counts: a run degrading 20x more often must GATE,
        and a calm run (both drop to 0) must read as an improvement,
        not a vanished-rate regression."""
        base = self.lines(self.summary(nest_ladder=True))
        churny = self.summary(nest_ladder=True)
        churny["engines"]["engine0"]["ladder"]["ladder_degrades"] = 20
        churny["engines"]["engine0"]["ladder"]["ladder_restores"] = 20
        by_metric = {r["metric"]: r for r in run(base, self.lines(churny))}
        row = by_metric["serve_engine.engine0.ladder.ladder_degrades (load=0.5x)"]
        assert row["lower_is_better"] is True
        assert row["status"] == "regression"
        calm = self.summary(nest_ladder=True)
        calm["engines"]["engine0"]["ladder"]["ladder_degrades"] = 0
        calm["engines"]["engine0"]["ladder"]["ladder_restores"] = 0
        by_metric = {r["metric"]: r for r in run(base, self.lines(calm))}
        for key in ("ladder_degrades", "ladder_restores"):
            row = by_metric[f"serve_engine.engine0.ladder.{key} (load=0.5x)"]
            assert row["status"] != "regression", row

    def test_missing_engine_on_one_side(self):
        """A replica absent from NEW (a vanished engine) is missing, not
        silently dropped; a brand-new replica reports as new-metric."""
        base = self.lines(self.summary(engines=("engine0", "engine1")))
        new = self.lines(self.summary(engines=("engine0",)))
        results = run(base, new)
        statuses = {r["metric"]: r["status"] for r in results}
        assert statuses[
            "serve_engine.engine1.dispatches (load=0.5x)"
        ] == "missing-in-new"
        # And the mirror direction:
        results = run(new, base)
        statuses = {r["metric"]: r["status"] for r in results}
        assert statuses[
            "serve_engine.engine1.dispatches (load=0.5x)"
        ] == "new-metric"

    def test_nested_vs_flat_summary_shapes_both_ingest(self):
        """A flat single-engine summary (PR 6 shape: ladder/retry fields
        on the record itself) must not crash the adapter or fabricate
        rows; the nested fan-out shape produces them."""
        flat = schema.stamp(
            {"event": "summary", "n_served": 3, "ladder_rung": "full",
             "n_retries": 1,
             "engines": {"engine0": {"alive": True, "dispatches": 3}}},
            kind="serve",
        )
        measured, unmeasured = load_bench_records(self.lines(flat))
        assert set(measured) == {
            "serve_engine.engine0.alive",
            "serve_engine.engine0.dispatches",
        }
        assert unmeasured == {}


class TestBenchArtifactEdgeCases:
    """PR 10 satellite: `telemetry compare --bench-artifact` edge cases —
    missing engines, all-UNMEASURED artifacts, and summary shapes riding
    the driver's BENCH_r0x container."""

    def artifact(self, tmp_path, name, rows):
        p = tmp_path / name
        p.write_text(json.dumps(
            {"tail": "\n".join(json.dumps(r) for r in rows)}
        ))
        return str(p)

    def bench_row(self, metric="m", value=1.0):
        return schema.stamp(
            {"metric": metric, "value": value, "unit": "x"}, kind="bench"
        )

    def unmeasured_row(self, metric="m"):
        return schema.stamp(
            {"metric": metric, "value": None, "unit": "x",
             "error": "backend-init-unavailable"},
            kind="error",
        )

    def test_all_unmeasured_artifact_warns_not_regresses(self, tmp_path):
        base = self.artifact(tmp_path, "b.json",
                             [self.bench_row("m1"), self.bench_row("m2")])
        new = self.artifact(tmp_path, "n.json",
                            [self.unmeasured_row("m1"),
                             self.unmeasured_row("m2")])
        assert compare_main([base, new, "--bench-artifact"]) == 0
        assert compare_main(
            [base, new, "--bench-artifact", "--fail-on-missing"]) == 0
        results = compare_files(base, new, artifacts=True)
        assert {r["status"] for r in results} == {"unmeasured-in-new"}

    def test_unmeasured_on_both_sides(self, tmp_path):
        base = self.artifact(tmp_path, "b.json", [self.unmeasured_row()])
        new = self.artifact(tmp_path, "n.json", [self.unmeasured_row()])
        results = compare_files(base, new, artifacts=True)
        assert [r["status"] for r in results] == ["unmeasured-both"]

    def test_engine_nest_rides_the_artifact_container(self, tmp_path):
        mk = TestEngineFlatten()
        base = self.artifact(
            tmp_path, "b.json", [mk.summary(dispatches=4)])
        new_rec = mk.summary(dispatches=4)
        new_rec["engines"]["engine1"]["dispatches"] = 0
        new = self.artifact(tmp_path, "n.json", [new_rec])
        assert compare_main([base, new, "--bench-artifact"]) == 1
        results = compare_files(base, new, artifacts=True)
        regressed = [r["metric"] for r in results
                     if r["status"] == "regression"]
        assert regressed == [
            "serve_engine.engine1.dispatches (load=0.5x)"
        ]

    def test_missing_engine_in_artifact_gates_with_fail_on_missing(
        self, tmp_path
    ):
        mk = TestEngineFlatten()
        base = self.artifact(tmp_path, "b.json", [mk.summary()])
        new = self.artifact(
            tmp_path, "n.json", [mk.summary(engines=("engine0",))])
        assert compare_main([base, new, "--bench-artifact"]) == 0
        assert compare_main(
            [base, new, "--bench-artifact", "--fail-on-missing"]) == 1


class TestCapacityObservatory:
    """ISSUE 13 classifications: collective_time.* wall_ms and
    serve_latency.* phase rows are COSTS, capacity headroom is a
    BENEFIT, and a timing-off run classifies UNMEASURED — never 0.0."""

    def test_direction_vocabulary(self):
        assert lower_is_better(
            "collective_time.train-zero1.zero_psum_scatter wall_ms", "ms"
        )
        assert lower_is_better("serve_latency.queue_wait_ms (cfg)", "ms")
        assert lower_is_better(
            "serve_capacity.engine0.utilization (cfg)", "fraction"
        )
        # Headroom is capacity LEFT: higher is better, whatever the unit
        # heuristics would otherwise say.
        assert not lower_is_better(
            "capacity.engine0.headroom", "fraction"
        )
        assert not lower_is_better(
            "serve_capacity.engine0.headroom (cfg)", "fraction"
        )
        assert not lower_is_better(
            "serve_capacity.engine0.service_rate_rps (cfg)", "req/s"
        )

    def test_collective_time_records_ingest_as_cost_rows(self):
        rec = json.dumps(schema.stamp(
            {"site": "zero_all_gather", "axis": "data",
             "collective": "all_gather", "path": "train-zero1",
             "mode": "sampled", "wire_bytes": 4096, "wall_ms": 1.5},
            kind="collective_time",
        ))
        measured, unmeasured = load_bench_records([rec])
        (label,) = measured
        assert label == "collective_time.train-zero1.zero_all_gather wall_ms"
        assert measured[label]["values"] == [1.5]
        assert unmeasured == {}

    def test_capacity_records_ingest_as_headroom_rows(self):
        rec = json.dumps(schema.stamp(
            {"engine": "engine0", "headroom": 0.8, "utilization": 0.2},
            kind="capacity",
        ))
        measured, _ = load_bench_records([rec])
        assert measured["capacity.engine0.headroom"]["values"] == [0.8]

    def test_fixture_pair_timing_regression_and_unmeasured(self):
        results = compare_files(
            "tests/fixtures/colltime_base.jsonl",
            "tests/fixtures/colltime_new.jsonl",
        )
        by = {r["metric"]: r for r in results}
        assert by[
            "collective_time.train-zero1.zero_psum_scatter wall_ms"
        ]["status"] == "regression"
        # Timing OFF in the new run: the site is UNMEASURED — missing,
        # never a 0.0 that would read as an infinite speedup.
        gone = by["collective_time.train-zero1.zero_all_gather wall_ms"]
        assert gone["status"] == "unmeasured-in-new"
        assert gone.get("new") is None
        assert by["capacity.engine0.headroom"]["status"] == "regression"
        assert by["serve_latency.queue_wait_ms (fixture)"][
            "status"] == "regression"
        assert by["serve_latency.device_ms (fixture)"]["status"] == "ok"
        assert compare_main([
            "tests/fixtures/colltime_base.jsonl",
            "tests/fixtures/colltime_new.jsonl",
        ]) == 1

    def test_summary_capacity_nest_flattens(self):
        rec = json.dumps(schema.stamp(
            {"event": "summary", "config": "cfg", "n_requests": 4,
             "engines": {"engine0": {"alive": True, "dispatches": 4}},
             "capacity": {"engine0": {"headroom": 0.7,
                                      "utilization": 0.3,
                                      "service_rate_rps": 12.0}},
             "latency_phases": {"queue_wait_ms": 3.0, "device_ms": 20.0}},
            kind="serve",
        ))
        measured, _ = load_bench_records([rec])
        assert measured["serve_capacity.engine0.headroom (cfg)"][
            "values"] == [0.7]
        assert measured["serve_latency.device_ms (cfg)"]["values"] == [
            20.0
        ]

    def test_summary_elastic_nest_flattens_with_cost_directions(self):
        """The elastic nest (ISSUE 15) flattens as serve_elastic.* rows:
        spawn latency ("ms") and migration bytes ("bytes") classify as
        COSTS, invalidated sessions/spawn failures by metric token; the
        timeline list never becomes a row."""
        from glom_tpu.telemetry.compare import lower_is_better

        rec = json.dumps(schema.stamp(
            {"event": "summary", "config": "cfg", "n_requests": 4,
             "engines": {"engine0": {"alive": True, "dispatches": 4}},
             "elastic": {"n_scale_outs": 1, "n_scale_ins": 1,
                         "n_spawn_failures": 0,
                         "n_migrated_sessions": 3,
                         "n_invalidated_sessions": 1,
                         "migrated_bytes": 4096,
                         "spawn_ms_mean": 950.0,
                         "n_engines_peak": 2,
                         "timeline": [[0.0, 1], [2.0, 2]]}},
            kind="serve",
        ))
        measured, _ = load_bench_records([rec])
        assert measured["serve_elastic.spawn_ms_mean (cfg)"]["values"] == [
            950.0
        ]
        assert measured["serve_elastic.spawn_ms_mean (cfg)"]["rec"][
            "unit"] == "ms"
        assert measured["serve_elastic.migrated_bytes (cfg)"]["rec"][
            "unit"] == "bytes"
        assert "serve_elastic.timeline (cfg)" not in measured
        assert lower_is_better("serve_elastic.spawn_ms_mean (cfg)", "ms")
        assert lower_is_better(
            "serve_elastic.migrated_bytes (cfg)", "bytes"
        )
        assert lower_is_better(
            "serve_elastic.n_invalidated_sessions (cfg)", "count"
        )
        assert lower_is_better(
            "serve_elastic.n_spawn_failures (cfg)", "count"
        )
