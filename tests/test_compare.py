"""Bench-trajectory compare gate tests (telemetry/compare.py) + the v2
schema kinds it rides on. Pure stdlib paths — no jax, no compiles."""

import json

import pytest

from glom_tpu.telemetry import schema
from glom_tpu.telemetry.compare import (
    compare_files,
    compare_records,
    load_bench_records,
    lower_is_better,
    main as compare_main,
)

FIXTURE_BASE = "tests/fixtures/bench_base.jsonl"
FIXTURE_NEW = "tests/fixtures/bench_new.jsonl"


def bench(metric, value, unit="column-iters/s/chip", **kw):
    return json.dumps(
        schema.stamp({"metric": metric, "value": value, "unit": unit, **kw},
                     kind="bench")
    )


def error_row(metric, err="backend-init-unavailable", unit="column-iters/s/chip"):
    return json.dumps(
        schema.stamp({"metric": metric, "value": None, "unit": unit,
                      "error": err}, kind="error")
    )


def run(base_lines, new_lines, threshold=0.05):
    bm, bu = load_bench_records(base_lines)
    nm, nu = load_bench_records(new_lines)
    return compare_records(bm, bu, nm, nu, threshold=threshold)


class TestSchemaV2Kinds:
    def test_span_and_error_kinds_validate(self):
        span = schema.stamp({"name": "host_data_next", "dur_s": 0.5},
                            kind="span")
        err = schema.stamp(
            {"metric": "m", "value": None, "error": "backend-init-unavailable"},
            kind="error",
        )
        assert span["schema_version"] == schema.SCHEMA_VERSION == 5
        assert schema.validate_record(span) == []
        assert schema.validate_record(err) == []
        # missing required fields are rejected
        assert schema.validate_record(
            {"kind": "span", "schema_version": 2, "name": "x"}) != []
        assert schema.validate_record(
            {"kind": "error", "schema_version": 2, "value": None}) != []

    def test_version_1_records_still_validate(self):
        old = {"kind": "bench", "schema_version": 1, "metric": "m",
               "value": 1.0, "unit": "u"}
        assert schema.validate_record(old) == []

    def test_infer_kind_for_new_shapes(self):
        assert schema.infer_kind({"name": "s", "dur_s": 1.0}) == "span"
        assert schema.infer_kind(
            {"metric": "m", "value": None, "error": "down"}) == "error"
        # a MEASURED row with an error context field stays a bench row
        assert schema.infer_kind(
            {"metric": "m", "value": 3.0, "error": "retried-once"}) == "bench"


class TestDirection:
    def test_rates_regress_down_costs_regress_up(self):
        assert not lower_is_better("train_step cips", "column-iters/s/chip")
        assert not lower_is_better("sp_crossover speedup", "x")
        assert lower_is_better("telemetry overhead", "percent")
        assert lower_is_better("longctx fused", "ms/call")
        assert lower_is_better("live_bytes_model_total", "bytes")
        assert lower_is_better("span_overhead thing", "percent")


class TestLoad:
    def test_skips_noise_and_classifies_unmeasured(self):
        lines = [
            "=== shell noise\n",
            bench("m1", 10.0),
            error_row("m2"),
            json.dumps(schema.stamp({"note": "ctx"}, kind="note")),
            # legacy round-5 dead zero: value 0.0 + error field
            json.dumps({"metric": "m3", "value": 0.0, "vs_baseline": 0.0,
                        "error": "backend-init-unavailable"}),
        ]
        measured, unmeasured = load_bench_records(lines)
        assert list(measured) == ["m1"]
        assert set(unmeasured) == {"m2", "m3"}

    def test_repeats_collapse_to_best(self):
        lines = [bench("m", v) for v in (10.0, 12.0, 11.0)]
        results = run(lines, [bench("m", 11.9)])
        (r,) = results
        # best-of-base is 12.0 (higher-better): 11.9 is inside noise
        assert r["base"] == 12.0
        assert r["status"] == "ok"


class TestVerdicts:
    def test_regression_beyond_threshold(self):
        (r,) = run([bench("m", 100.0)], [bench("m", 90.0)])
        assert r["status"] == "regression"
        assert r["rel_change"] == pytest.approx(-0.1)

    def test_within_noise_is_ok(self):
        (r,) = run([bench("m", 100.0)], [bench("m", 96.0)])
        assert r["status"] == "ok"

    def test_improvement(self):
        (r,) = run([bench("m", 100.0)], [bench("m", 120.0)])
        assert r["status"] == "improvement"

    def test_lower_is_better_flips_direction(self):
        (r,) = run(
            [bench("overhead", 1.0, unit="percent")],
            [bench("overhead", 1.5, unit="percent")],
        )
        assert r["status"] == "regression"
        (r,) = run(
            [bench("overhead", 1.5, unit="percent")],
            [bench("overhead", 1.0, unit="percent")],
        )
        assert r["status"] == "improvement"

    def test_unmeasured_is_missing_never_zero(self):
        """THE round-5 fix: an UNMEASURED row must neither read as a 100%
        regression (value->0) nor fail the gate."""
        results = run([bench("m", 100.0)], [error_row("m")])
        (r,) = results
        assert r["status"] == "unmeasured-in-new"
        assert r["error"] == "backend-init-unavailable"
        assert "rel_change" not in r

    def test_legacy_dead_zero_in_new_is_missing(self):
        legacy = json.dumps({"metric": "m", "value": 0.0,
                             "error": "backend-init-unavailable"})
        (r,) = run([bench("m", 100.0)], [legacy])
        assert r["status"] == "unmeasured-in-new"

    def test_recovery_from_unmeasured_base(self):
        (r,) = run([error_row("m")], [bench("m", 50.0)])
        assert r["status"] == "recovered"
        assert r["new"] == 50.0

    def test_recovered_cost_metric_reports_best_repeat(self):
        # lower-is-better recovery: report the benches' best-of-repeats
        # (min), not the worst.
        (r,) = run(
            [error_row("m", unit="ms/call")],
            [bench("m", 15.0, unit="ms/call"), bench("m", 12.0, unit="ms/call")],
        )
        assert r["status"] == "recovered"
        assert r["new"] == 12.0

    def test_new_only_unmeasured_row_is_reported(self):
        # A brand-new bench that failed on its first run must still show
        # up in the report (it would otherwise silently vanish).
        results = run([bench("a", 1.0)], [bench("a", 1.0), error_row("b")])
        by = {r["metric"]: r for r in results}
        assert by["b"]["status"] == "unmeasured-new-only"
        assert by["b"]["error"] == "backend-init-unavailable"

    def test_bootstrap_error_row_matches_measured_label(self, tmp_path, capsys):
        """THE label contract: bench_bootstrap's UNMEASURED row carries
        the bare metric label, so an outage compares as
        'unmeasured-in-new' against the measured baseline — not as a
        vanished metric."""
        from unittest import mock

        from glom_tpu.telemetry import sinks

        wd = mock.Mock()
        wd.probe_once.return_value = "down"
        wd.timeline.return_value = []
        wd.record.return_value = {"backend_state": "down"}
        with mock.patch(
            "glom_tpu.telemetry.watchdog.BackendWatchdog", return_value=wd
        ), mock.patch.dict("os.environ", {}, clear=False):
            try:
                assert sinks.bench_bootstrap("my_metric", "u") is False
            finally:
                from glom_tpu.telemetry.watchdog import set_global_watchdog

                set_global_watchdog(None)
        row = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert row["kind"] == "error" and row["value"] is None
        assert row["metric"] == "my_metric"  # bare — matches measured rows
        (r,) = run([bench("my_metric", 5.0, unit="u")], [json.dumps(row)])
        assert r["status"] == "unmeasured-in-new"

    def test_new_metric_reported(self):
        results = run([bench("a", 1.0)], [bench("a", 1.0), bench("b", 2.0)])
        by = {r["metric"]: r for r in results}
        assert by["b"]["status"] == "new-metric"

    def test_threshold_is_configurable(self):
        (r,) = run([bench("m", 100.0)], [bench("m", 90.0)], threshold=0.2)
        assert r["status"] == "ok"


class TestCli:
    def test_fixture_pair_fails_the_gate(self, capsys):
        """The committed CI fixture pair: one regression, one improvement,
        one UNMEASURED — the gate must exit nonzero (the regression) while
        the unmeasured row stays a warning."""
        rc = compare_main([FIXTURE_BASE, FIXTURE_NEW])
        assert rc == 1
        out = capsys.readouterr()
        summary = json.loads(out.out.strip().splitlines()[-1])
        assert summary["kind"] == "summary"
        assert summary["n_regression"] == 1
        assert summary["n_improvement"] == 1
        assert summary["n_unmeasured_in_new"] == 1
        assert schema.validate_record(summary) == []

    def test_self_compare_passes(self, capsys):
        assert compare_main([FIXTURE_BASE, FIXTURE_BASE]) == 0

    def test_fail_on_missing_flag(self, tmp_path, capsys):
        base = tmp_path / "b.jsonl"
        new = tmp_path / "n.jsonl"
        base.write_text(bench("gone", 5.0) + "\n")
        new.write_text(bench("other", 5.0) + "\n")
        assert compare_main([str(base), str(new)]) == 0
        assert compare_main([str(base), str(new), "--fail-on-missing"]) == 1

    def test_compare_files_roundtrip(self):
        results = compare_files(FIXTURE_BASE, FIXTURE_NEW)
        statuses = {r["status"] for r in results}
        assert "regression" in statuses and "unmeasured-in-new" in statuses


class TestBenchArtifacts:
    def test_artifact_tail_rows_compare(self, tmp_path):
        """BENCH_r0x.json round artifacts (one JSON object, bench rows in
        "tail") ride the same gate; legacy value-0.0 + error dead zeros
        classify UNMEASURED, never zero."""
        import json

        from glom_tpu.telemetry.compare import artifact_lines, compare_files

        base = tmp_path / "BENCH_r01.json"
        new = tmp_path / "BENCH_r02.json"
        row = {"metric": "fwd x", "value": 100.0, "unit": "col/s",
               "kind": "bench", "schema_version": 4}
        dead = {"metric": "train y (UNMEASURED)", "value": 0.0,
                "unit": "col/s", "error": "backend-init-unavailable"}
        base.write_text(json.dumps(
            {"n": 1, "tail": json.dumps(row) + "\n" + json.dumps(dead)}
        ))
        slower = dict(row, value=50.0)
        new.write_text(json.dumps({"n": 2, "tail": json.dumps(slower)}))
        assert len(artifact_lines(str(base))) == 2
        results = compare_files(str(base), str(new), artifacts=True)
        by_metric = {r["metric"]: r for r in results}
        assert by_metric["fwd x"]["status"] == "regression"
        assert by_metric["train y (UNMEASURED)"]["status"] == "unmeasured-both"

    def test_parsed_fallback_when_tail_empty(self, tmp_path):
        import json

        from glom_tpu.telemetry.compare import artifact_lines

        p = tmp_path / "BENCH_r03.json"
        p.write_text(json.dumps(
            {"parsed": {"metric": "m", "value": 1.0, "unit": "x"}}
        ))
        lines = artifact_lines(str(p))
        assert len(lines) == 1 and json.loads(lines[0])["metric"] == "m"
