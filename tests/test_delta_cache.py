"""Delta-encoded streaming cache + sparse incremental convergence
(ISSUE 12, docs/SERVING.md "Delta streaming").

The contracts the delta route ships under:

  * base+Σdeltas reconstruction is BITWISE the whole-state block at
    threshold 0 / atol 0 — the effective page map feeds the SAME paged
    warm signature, so a chain-reconstructed dispatch equals the
    whole-state warm dispatch bit for bit;
  * compaction conserves pages (pages_used + pages_free == pages_total
    through base folds, copy-on-write of shared bases, superseded-page
    reclamation) and DEFERS under concurrent pins — an in-flight
    dispatch's snapshotted indices are never freed under it;
  * an empty-delta frame (bitwise-identical input) short-circuits to the
    min_iters floor on the incremental route;
  * a shared base's pages free only at refcount 0;
  * the chain cap triggers exactly AT the cap.
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from glom_tpu.models.core import init_glom
from glom_tpu.serve.batcher import DynamicBatcher
from glom_tpu.serve.column_cache import ColumnCache
from glom_tpu.serve.early_exit import (
    glom_forward_incremental,
    glom_forward_tiered,
)
from glom_tpu.serve.engine import InferenceEngine
from glom_tpu.serve.paged_columns import PagedColumnPool
from glom_tpu.utils.config import GlomConfig, ServeConfig

CFG = GlomConfig(dim=32, levels=3, image_size=16, patch_size=4)  # n=16
DSCFG = ServeConfig(
    buckets=(1, 2, 4), max_batch=4, max_delay_ms=2.0,
    iters="auto", max_auto_iters=8, exit_threshold=1e-3,
    page_pool_pages=64, page_tokens=4,
    delta_streaming=True, delta_page_atol=0.05, delta_chain_cap=3,
    column_cache_bytes=1 << 20, dispatch_retries=0,
)


def _row(rng, scale=100.0):
    return (scale * rng.normal(size=(16, 3, 32))).astype(np.float32)


def _conserved(pool):
    rec = pool.record()
    assert rec["pages_used"] + rec["pages_free"] == rec["pages_total"], rec
    return rec


def _bump_page(row, ordinal, pt=4):
    out = row.copy()
    out[ordinal * pt] += 1.0
    return out


class TestDeltaChain:
    def _pool(self, **over):
        scfg = dataclasses.replace(DSCFG, **over) if over else DSCFG
        return PagedColumnPool(CFG, scfg, name="t")

    def test_chain_cap_triggers_exactly_at_cap(self):
        """cap=3: deltas at DISJOINT ordinals grow the chain 1, 2 — and
        the 3rd (== cap) folds base <- base+Σdeltas, never earlier."""
        pool = self._pool()
        rng = np.random.default_rng(0)
        row = _row(rng)
        assert pool.write_back_stream("s", row, 16)["kind"] == "base"
        for step, ordinal in enumerate((0, 1)):
            row = _bump_page(row, ordinal)
            info = pool.write_back_stream("s", row, 16)
            assert info["kind"] == "delta", info
            assert info["chain_len"] == step + 1
        row = _bump_page(row, 2)
        info = pool.write_back_stream("s", row, 16)
        assert info["kind"] == "compact" and info["chain_len"] == 0, info
        assert pool.n_compactions == 1
        assert np.array_equal(pool.read_block("s"), row)
        _conserved(pool)

    def test_superseded_pages_reclaimed(self):
        """A stream that keeps perturbing the SAME region stays at
        ~constant pages: the older chain entry's page frees the moment a
        newer delta overrides its ordinal."""
        pool = self._pool(delta_chain_cap=16)
        rng = np.random.default_rng(1)
        row = _row(rng)
        pool.write_back_stream("s", row, 16)
        for _ in range(5):
            row = _bump_page(row, 2)
            info = pool.write_back_stream("s", row, 16)
            assert info["kind"] == "delta" and info["chain_len"] == 1, info
        assert pool.n_superseded == 4
        rec = _conserved(pool)
        assert rec["pages_used"] == 4 + 1  # base + ONE live delta page
        assert np.array_equal(pool.read_block("s"), row)

    def test_compaction_conservation_under_concurrent_pins(self):
        """A PINNED session defers compaction (and superseded pruning) —
        its in-flight snapshot's page indices survive — and the deferred
        fold lands on the next unpinned write, pages conserved
        throughout."""
        pool = self._pool()
        rng = np.random.default_rng(2)
        row = _row(rng)
        pool.write_back_stream("s", row, 16)
        assert pool.lookup("s", pin=True) is not None
        for ordinal in (0, 1, 2, 3):
            row = _bump_page(row, ordinal)
            info = pool.write_back_stream("s", row, 16)
            assert info["kind"] == "delta", info
            _conserved(pool)
        # Chain is past the cap, but the pin held every fold back.
        assert info["chain_len"] == 4 and info.get("compact_deferred")
        assert pool.n_compactions == 0 and pool.n_compact_deferred >= 2
        pool.unpin("s")
        row = _bump_page(row, 0)
        info = pool.write_back_stream("s", row, 16)
        assert info["kind"] == "compact", info
        assert np.array_equal(pool.read_block("s"), row)
        _conserved(pool)

    def test_shared_base_frees_only_at_refcount_zero(self):
        pool = self._pool()
        rng = np.random.default_rng(3)
        row = _row(rng)
        assert pool.write_back_stream("a", row, 16, content_hash="h")[
            "kind"
        ] == "base"
        info = pool.write_back_stream("b", row, 16, content_hash="h")
        assert info["kind"] == "share" and info["base_refs"] == 2
        assert pool.base_refs("a") == 2
        used_shared = _conserved(pool)["pages_used"]
        assert used_shared == 4  # ONE base, two sessions
        # Owner evicts first: the base must survive for the aliaser.
        assert pool.free("a") == 0  # no delta pages, base still ref'd
        assert np.array_equal(pool.read_block("b"), row)
        assert _conserved(pool)["pages_used"] == 4
        assert pool.free("b") == 4  # refcount 0: base pages free NOW
        assert _conserved(pool)["pages_used"] == 0

    def test_shared_base_copy_on_write_compaction(self):
        """A sharer that compacts must NOT rewrite the shared pages: it
        copies on write into a fresh private base; the other session's
        content stays bit-for-bit."""
        pool = self._pool()
        rng = np.random.default_rng(4)
        row = _row(rng)
        pool.write_back_stream("a", row, 16, content_hash="h")
        pool.write_back_stream("b", row, 16, content_hash="h")
        mut = row
        for ordinal in (0, 1, 2):
            mut = _bump_page(mut, ordinal)
            info = pool.write_back_stream("a", mut, 16)
        assert info["kind"] == "compact" and info["base_refs"] == 1
        assert pool.base_refs("b") == 1  # the old base is b's alone now
        assert np.array_equal(pool.read_block("a"), mut)
        assert np.array_equal(pool.read_block("b"), row)
        _conserved(pool)

    def test_atol_zero_is_bitwise(self):
        """atol 0.0 stores a page when any BIT differs — including a
        -0.0 vs 0.0 flip float comparison would miss — and an identical
        frame is an EMPTY delta."""
        pool = self._pool(delta_page_atol=0.0)
        rng = np.random.default_rng(5)
        row = _row(rng)
        pool.write_back_stream("s", row, 16)
        info = pool.write_back_stream("s", row.copy(), 16)
        assert info["pages_written"] == 0 and info.get("empty"), info
        flip = row.copy()
        flip[0, 0, 0] = -0.0 if flip[0, 0, 0] == 0.0 else -flip[0, 0, 0]
        info = pool.write_back_stream("s", flip, 16)
        assert info["pages_written"] == 1, info

    def test_whole_state_alloc_rejected_on_delta_session(self):
        pool = self._pool()
        rng = np.random.default_rng(6)
        pool.write_back_stream("s", _row(rng), 16)
        with pytest.raises(ValueError, match="delta-chain"):
            pool.alloc("s", 16)


class TestDeltaCacheResidency:
    def _cache_pool(self, **over):
        scfg = dataclasses.replace(DSCFG, **over) if over else DSCFG
        pool = PagedColumnPool(CFG, scfg, name="e0")
        cache = ColumnCache(
            scfg.column_cache_bytes, pools={"e0": pool}
        )
        assert cache.delta
        return cache, pool

    def test_store_lookup_roundtrip_and_actual_pricing(self):
        cache, pool = self._cache_pool()
        rng = np.random.default_rng(0)
        row = _row(rng)
        assert cache.store("a", row, engine="e0", n_tokens=16,
                           content_hash="h")
        assert cache.store("b", row, engine="e0", n_tokens=16,
                           content_hash="h")
        # Priced on ACTUAL pages: one shared base = 4 pages total, not 8.
        assert cache.bytes_in_use() == 4 * pool.page_bytes
        hit = cache.lookup("a")
        assert hit is not None and hit.n_tokens == 16
        rec = cache.record()
        assert rec["delta"]["n_base_shares"] == 1
        assert rec["delta"]["bytes_per_stream"] == 2 * pool.page_bytes

    def test_eviction_frees_chain_and_refcounted_base(self):
        cache, pool = self._cache_pool()
        rng = np.random.default_rng(1)
        row = _row(rng)
        cache.store("a", row, engine="e0", n_tokens=16, content_hash="h")
        cache.store("b", row, engine="e0", n_tokens=16, content_hash="h")
        cache.store("a", _bump_page(row, 1), engine="e0", n_tokens=16)
        assert cache.invalidate("a")
        # a's delta page freed, the shared base survives for b.
        assert pool.record()["pages_used"] == 4
        assert np.array_equal(pool.read_block("b"), row)
        assert cache.invalidate("b")
        assert pool.record()["pages_used"] == 0
        assert cache.bytes_in_use() == 0

    def test_pool_exhaustion_evicts_lru(self):
        # 12 pages = 3 whole bases; a 4th DISTINCT stream must evict.
        cache, pool = self._cache_pool(page_pool_pages=12)
        rng = np.random.default_rng(2)
        for s in range(4):
            assert cache.store(
                f"s{s}", _row(rng), engine="e0", n_tokens=16
            )
        assert cache.n_evictions >= 1
        assert cache.lookup("s0") is None  # the LRU victim
        _conserved(pool)

    def test_reject_keeps_previous_state_reachable(self):
        """A delta append that fails on a bone-dry pool (nothing
        evictable) must NOT strand the session's existing block: the
        store returns False, but the PREVIOUS frame's state stays
        reachable through the cache — and evictable, so the pages are
        never orphaned outside every eviction walk."""
        cache, pool = self._cache_pool(page_pool_pages=4)  # exactly 1 base
        rng = np.random.default_rng(5)
        row = _row(rng)
        assert cache.store("s", row, engine="e0", n_tokens=16)
        assert not cache.store(
            "s", _bump_page(row, 1), engine="e0", n_tokens=16
        )
        assert cache.n_rejects == 1
        hit = cache.lookup("s")
        assert hit is not None  # the old frame's warmth survives
        assert np.array_equal(pool.read_block("s"), row)
        assert cache.bytes_in_use() == 4 * pool.page_bytes
        assert cache.invalidate("s")  # ... and is still reclaimable
        assert pool.record()["pages_used"] == 0
        assert cache.bytes_in_use() == 0

    def test_input_support_bitwise_pages(self):
        cache, pool = self._cache_pool()
        rng = np.random.default_rng(3)
        patches = rng.normal(size=(16, 48)).astype(np.float32)
        row = _row(rng)
        cache.store("s", row, engine="e0", n_tokens=16, patches=patches)
        # Hold frame: empty support.
        assert not cache.input_support("s", patches.copy(), 4).any()
        # One token in page 2 changes: exactly page 2 is support.
        mut = patches.copy()
        mut[9, 0] += 1.0
        supp = cache.input_support("s", mut, 4)
        assert supp.tolist() == [False, False, True, False]
        # No previous frame: everything is support.
        assert cache.input_support("x", patches, 4).all()


class TestIncrementalForward:
    @pytest.fixture(scope="class")
    def setup(self):
        params = init_glom(jax.random.PRNGKey(0), CFG)
        rng = np.random.default_rng(7)
        img = (100 * rng.normal(size=(2, 3, 16, 16))).astype(np.float32)
        levels = np.asarray(
            glom_forward_tiered(
                params, jnp.asarray(img), CFG, max_iters=8, threshold=1e-3
            ).levels
        )
        return params, img, levels

    def test_empty_delta_short_circuits_to_min_iters_floor(self, setup):
        params, img, levels = setup
        for floor in (1, 2):
            res = glom_forward_incremental(
                params, jnp.asarray(img), CFG,
                max_iters=8, threshold=1e-3, min_iters=floor,
                levels=jnp.asarray(levels),
                support_mask=jnp.zeros((2, 16), bool),
            )
            assert int(res.iters_run) == floor
            assert bool(res.row_converged.all())

    def test_threshold0_is_bitwise_tiered(self, setup):
        """The bitwise contract: threshold 0 disables the support
        seeding entirely — the incremental call IS glom_forward_tiered,
        full width, bit for bit."""
        params, img, levels = setup
        inc = glom_forward_incremental(
            params, jnp.asarray(img), CFG,
            max_iters=6, threshold=0.0, min_iters=1,
            levels=jnp.asarray(levels),
            support_mask=jnp.zeros((2, 16), bool),  # would short-circuit
        )
        full = glom_forward_tiered(
            params, jnp.asarray(img), CFG,
            max_iters=6, threshold=0.0, min_iters=1,
            levels=jnp.asarray(levels),
        )
        assert int(inc.iters_run) == 6 == int(full.iters_run)
        assert np.array_equal(np.asarray(inc.levels), np.asarray(full.levels))

    def test_dirty_rows_iterate_clean_rows_preconverge(self, setup):
        params, img, levels = setup
        supp = np.zeros((2, 16), bool)
        supp[0, :4] = True  # row 0 dirty, row 1 clean
        img2 = img.copy()
        img2[0, :, 0:4, 0:4] += 0.5
        res = glom_forward_incremental(
            params, jnp.asarray(img2), CFG,
            max_iters=8, threshold=1e-3, min_iters=1,
            levels=jnp.asarray(levels),
            support_mask=jnp.asarray(supp),
        )
        conv = np.asarray(res.row_converged)
        assert bool(conv[1])  # pre-converged by empty support
        assert int(res.iters_run) >= 1


@pytest.mark.slow
class TestDeltaReconstructionParity:
    """THE acceptance lock: threshold-0 / atol-0 delta reconstruction is
    BITWISE the whole-state warm dispatch — the same paged signature fed
    an effective base+Σdeltas map vs a whole-state block."""

    def test_threshold0_chain_bitwise_vs_whole_state(self):
        scfg = dataclasses.replace(
            DSCFG, exit_threshold=0.0, delta_page_atol=0.0,
            max_auto_iters=4,
        )
        eng = InferenceEngine(CFG, scfg, key=jax.random.PRNGKey(0))
        rng = np.random.default_rng(0)
        img1 = (100 * rng.normal(size=(1, 3, 16, 16))).astype(np.float32)
        lv1 = np.asarray(eng.infer(img1, n_valid=1).levels)[0]
        assert eng.pool.write_back_stream("delta", lv1, 16) is not None
        assert eng.pool.write_back("whole", lv1, 16)

        def warm(sid, img):
            prow = np.asarray([eng.pool.lookup(sid)[0]], np.int32)
            return np.asarray(
                eng.infer(img, n_valid=1, page_rows=prow).levels
            )[0]

        img2 = img1 + 0.05 * rng.normal(size=img1.shape).astype(np.float32)
        out_d = warm("delta", img2)
        out_w = warm("whole", img2)
        assert np.array_equal(out_d, out_w)
        # Advance one more frame THROUGH the chain (atol 0: every page
        # that moved stores — a real multi-entry reconstruction).
        assert eng.pool.write_back_stream("delta", out_d, 16) is not None
        assert eng.pool.write_back("whole", out_w, 16)
        assert eng.pool.delta_chain_len("delta") >= 1
        img3 = img2 + 0.05 * rng.normal(size=img1.shape).astype(np.float32)
        assert np.array_equal(warm("delta", img3), warm("whole", img3))


@pytest.mark.slow
class TestDeltaBatcherEndToEnd:
    def test_streaming_holds_and_perturbs(self):
        """The full path: session frames through the DynamicBatcher in
        delta mode — holds ride the incremental route at the min_iters
        floor, perturbed frames exit early, identical first frames share
        one base, and the summary nests price actual pages."""
        scfg = dataclasses.replace(DSCFG, delta_page_atol=0.1)
        eng = InferenceEngine(CFG, scfg, key=jax.random.PRNGKey(0))
        eng.warmup()
        rng = np.random.default_rng(0)
        base = (100 * rng.normal(size=(3, 16, 16))).astype(np.float32)
        streams = ("a", "b")  # two cameras, one scene
        with DynamicBatcher(engine=eng) as b:
            frames = {s: base for s in streams}
            iters_by_frame = []
            for f in range(4):
                if f == 2:  # one perturbed frame per stream
                    for s in streams:
                        img = frames[s].copy()
                        img[:, 0:4, 0:4] += (
                            5.0 * rng.normal(size=(3, 4, 4))
                        ).astype(np.float32)
                        frames[s] = img
                tickets = {
                    s: b.submit(frames[s], session_id=s) for s in streams
                }
                iters_by_frame.append(
                    {s: t.result(timeout=120.0)[1] for s, t in tickets.items()}
                )
            summary = b.summary_record()
        # Frame 1 (hold) short-circuits to the floor on EVERY stream.
        assert all(v == scfg.min_iters for v in iters_by_frame[1].values())
        # The perturbed frame iterates, but below the cold width.
        assert all(
            scfg.min_iters <= v < iters_by_frame[0][s]
            for s, v in iters_by_frame[2].items()
        )
        assert summary["n_incremental"] > 0
        cd = summary["column_cache"]["delta"]
        assert cd["n_base_shares"] == 1  # camera b aliased camera a's base
        # HOLD frames skip their write-back entirely (an unchanged input
        # adds nothing worth storing) — only the perturbed frame stores,
        # one sparse delta per stream.
        assert cd["n_delta_writes"] == 2
        assert cd["n_delta_empty"] == 0
        assert cd["delta_page_atol"] == 0.1
        pp = summary["page_pools"]["engine0"]
        assert pp["pages_used"] + pp["pages_free"] == pp["pages_total"]
        # ACTUAL pricing: two streams share one base -> under 2 whole rows.
        assert pp["bytes_in_use"] < 2 * 4 * pp["page_bytes"]


def test_delta_requires_pool():
    with pytest.raises(ValueError, match="page pool"):
        ServeConfig(delta_streaming=True, page_pool_pages=0)


def test_delta_excludes_ragged():
    with pytest.raises(ValueError, match="bucket route"):
        ServeConfig(
            delta_streaming=True, page_pool_pages=8, ragged=True,
        )


def test_page_gather_validated():
    with pytest.raises(ValueError, match="page_gather"):
        ServeConfig(page_gather="sometimes")
