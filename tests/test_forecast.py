"""Forecast evidence (glom_tpu/telemetry/forecast.py, ISSUE 17).

The tier-1 locks:

  * LoadForecaster's trend fit extrapolates a clean linear series and
    SCORES every prediction once the series passes its target —
    forecast_abs_err rides every record, null until matured, never
    absent (the v9 presence contract);
  * degenerate fits pin honestly: insufficient samples, zero time span,
    and the empty window all stamp predicted null + the reason;
  * seasonality joins the fit only after >= 2 observed seasons
    ("season-immature" before that) and then carries the phase
    deviation;
  * SpawnLeadTimeModel scores its prior estimate against each realized
    spawn before absorbing it, and pins to "no-spawn-evidence" when
    empty;
  * ForecastEmitter under a fake clock: windows close on tap activity
    at interval_s cadence, admit events become arrival-rate samples,
    scale_out spawn_ms becomes lead-time records, close() flushes the
    tail — and every emitted record validates at schema v9.

All fake-clock, no jit, no sleeps.
"""

import threading

import pytest

from glom_tpu.telemetry import schema
from glom_tpu.telemetry.forecast import (
    ForecastEmitter,
    LoadForecaster,
    SpawnLeadTimeModel,
)


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def advance(self, dt):
        self.t += dt

    def __call__(self):
        return self.t


# ---------------------------------------------------------------------------
# the load forecaster
# ---------------------------------------------------------------------------


class TestLoadForecaster:
    def test_trend_extrapolates_linear_series(self):
        """A perfectly linear series forecasts its own continuation:
        value = 2t, horizon 2s ahead of t=10 -> 24."""
        f = LoadForecaster("rps", window_s=20.0, horizon_s=2.0)
        for t in range(11):
            f.observe(float(t), 2.0 * t)
        rec = f.forecast(10.0)
        assert rec["kind"] == "forecast" and rec["metric"] == "rps"
        assert rec["predicted"] == pytest.approx(24.0, abs=1e-6)
        assert rec["trend_per_s"] == pytest.approx(2.0, abs=1e-6)
        assert "forecast_abs_err" in rec  # the v9 presence contract
        assert schema.validate_record(rec) == []

    def test_prediction_scores_once_target_passes(self):
        """forecast() queues the prediction; the first observe() past
        t + horizon scores it and the NEXT record carries the error."""
        f = LoadForecaster("rps", window_s=20.0, horizon_s=2.0)
        for t in range(6):
            f.observe(float(t), 10.0)  # flat series
        first = f.forecast(5.0)  # predicts 10.0 at t=7
        assert first["forecast_abs_err"] is None and first["n_scored"] == 0
        f.observe(8.0, 14.0)  # past the target; realized interp != 10
        scored = f.forecast(8.0)
        assert scored["n_scored"] == 1
        # Realized at t=7 interpolates between (5, 10) and (8, 14).
        realized = 10.0 + (14.0 - 10.0) * (7.0 - 5.0) / (8.0 - 5.0)
        assert scored["forecast_abs_err"] == pytest.approx(
            abs(10.0 - realized), abs=1e-3
        )
        assert scored["realized"] == pytest.approx(realized, abs=1e-3)
        assert scored["forecast_mae"] == scored["forecast_abs_err"]
        assert schema.validate_record(scored) == []

    def test_degenerate_insufficient_samples(self):
        f = LoadForecaster("rps")
        f.observe(0.0, 1.0)
        rec = f.forecast(0.0)
        assert rec["predicted"] is None
        assert rec["reason"] == "insufficient-samples"
        assert rec["forecast_abs_err"] is None  # key present, value null
        assert schema.validate_record(rec) == []

    def test_degenerate_zero_time_span(self):
        f = LoadForecaster("rps")
        for _ in range(4):
            f.observe(3.0, 5.0)  # four samples, one instant
        rec = f.forecast(3.0)
        assert rec["predicted"] is None
        assert rec["reason"] == "zero-time-span"
        assert rec["n_samples"] == 4

    def test_empty_window_forecasts_null(self):
        rec = LoadForecaster("rps").forecast(0.0)
        assert rec["predicted"] is None
        assert rec["reason"] == "insufficient-samples"
        assert rec["n_samples"] == 0
        assert schema.validate_record(rec) == []

    def test_window_prunes_old_samples(self):
        f = LoadForecaster("rps", window_s=5.0)
        for t in range(12):
            f.observe(float(t), 1.0)
        assert f.forecast(11.0)["n_samples"] <= 6

    def test_seasonality_needs_two_full_seasons(self):
        """One observed season stamps trend-only + "season-immature";
        two+ seasons carry the phase deviation in the fit."""
        import math

        f = LoadForecaster(
            "rps", window_s=8.0, horizon_s=1.0, season_s=8.0,
            season_buckets=4,
        )
        rate = lambda t: 10.0 + 5.0 * math.sin(2 * math.pi * t / 8.0)
        for i in range(8):  # one season at 1 Hz
            f.observe(i * 1.0, rate(i * 1.0))
        early = f.forecast(7.0)
        assert early["seasonal"] is None
        assert early["reason"] == "season-immature"
        for i in range(8, 25):  # two more seasons
            f.observe(i * 1.0, rate(i * 1.0))
        late = f.forecast(24.0)
        assert late["seasonal"] is not None
        assert "reason" not in late
        assert schema.validate_record(late) == []

    def test_validation(self):
        with pytest.raises(ValueError):
            LoadForecaster("rps", window_s=0)
        with pytest.raises(ValueError):
            LoadForecaster("rps", horizon_s=-1.0)
        with pytest.raises(ValueError):
            LoadForecaster("rps", season_s=0.0)
        with pytest.raises(ValueError):
            LoadForecaster("rps", min_samples=1)
        with pytest.raises(ValueError):
            LoadForecaster("rps", season_buckets=1)


# ---------------------------------------------------------------------------
# the spawn-lead-time model
# ---------------------------------------------------------------------------


class TestSpawnLeadTimeModel:
    def test_no_evidence_pins_null(self):
        m = SpawnLeadTimeModel()
        assert m.lead_time_ms() is None
        rec = m.record()
        assert rec["kind"] == "forecast"
        assert rec["metric"] == "spawn_lead_time"
        assert rec["lead_time_ms"] is None
        assert rec["reason"] == "no-spawn-evidence"
        assert rec["forecast_abs_err"] is None
        assert schema.validate_record(rec) == []

    def test_scores_prior_estimate_then_absorbs(self):
        m = SpawnLeadTimeModel(quantile=0.9)
        m.observe(100.0)  # no prior -> nothing scored
        assert m.record()["n_scored"] == 0
        assert m.lead_time_ms() == 100.0
        m.observe(140.0)  # prior estimate was 100 -> abs err 40
        rec = m.record()
        assert rec["n_scored"] == 1
        assert rec["forecast_abs_err"] == pytest.approx(40.0)
        assert rec["lead_time_ms"] == 140.0  # p90 nearest-rank of {100,140}
        assert rec["horizon_s"] == pytest.approx(0.14)
        assert schema.validate_record(rec) == []

    def test_validation(self):
        with pytest.raises(ValueError):
            SpawnLeadTimeModel(quantile=0.0)
        with pytest.raises(ValueError):
            SpawnLeadTimeModel(quantile=1.5)
        with pytest.raises(ValueError):
            SpawnLeadTimeModel(max_samples=0)


# ---------------------------------------------------------------------------
# the live emitter (fake clock, fake tap stream)
# ---------------------------------------------------------------------------


def _admit(i=0):
    return {"kind": "serve", "event": "admit", "request_id": f"r{i}"}


class TestForecastEmitter:
    def test_window_closes_on_interval_and_emits_rate(self):
        clk = FakeClock()
        out = []
        em = ForecastEmitter(
            out.append, interval_s=0.5, window_s=5.0, horizon_s=1.0,
            clock=clk,
        )
        em.tap(_admit(0))  # opens the window at t=0
        clk.advance(0.25)
        em.tap(_admit(1))
        assert out == []  # interval not yet elapsed
        clk.advance(0.25)
        em.tap(_admit(2))  # t=0.5 closes the window (3 arrivals / 0.5s)
        assert len(out) == 1 and em.n_windows == 1
        rec = out[0]
        assert rec["kind"] == "forecast"
        assert rec["metric"] == "arrival_rate_rps"
        assert rec["observed_rate_rps"] == pytest.approx(6.0)
        assert "forecast_abs_err" in rec
        assert schema.validate_record(rec) == []

    def test_forecast_matures_across_windows(self):
        """Constant-rate traffic over enough windows: predictions mature
        and forecast_abs_err turns numeric (and small)."""
        clk = FakeClock()
        out = []
        em = ForecastEmitter(
            out.append, interval_s=0.5, window_s=5.0, horizon_s=0.5,
            clock=clk,
        )
        rid = 0
        for _ in range(10):  # 10 windows, 2 admits each -> 4 rps
            em.tap(_admit(rid)); rid += 1
            clk.advance(0.25)
            em.tap(_admit(rid)); rid += 1
            clk.advance(0.25)
        scored = [r for r in out if r["forecast_abs_err"] is not None]
        assert scored, "no prediction matured over 10 windows"
        assert scored[-1]["forecast_abs_err"] < 1.0  # ~flat series
        for r in out:
            assert "forecast_abs_err" in r
            assert schema.validate_record(r) == []

    def test_scale_out_feeds_lead_model(self):
        clk = FakeClock()
        out = []
        em = ForecastEmitter(out.append, interval_s=10.0, clock=clk)
        em.tap({"kind": "serve", "event": "scale_out", "spawn_ms": 80.0})
        leads = [r for r in out if r.get("metric") == "spawn_lead_time"]
        assert len(leads) == 1 and leads[0]["lead_time_ms"] == 80.0
        assert em.lead_model.lead_time_ms() == 80.0

    def test_close_flushes_partial_window_and_lead_record(self):
        clk = FakeClock()
        out = []
        em = ForecastEmitter(out.append, interval_s=10.0, clock=clk)
        em.tap(_admit(0))
        clk.advance(1.0)
        em.close()
        kinds = [(r.get("metric"), r.get("observed_rate_rps")) for r in out]
        assert ("arrival_rate_rps", 1.0) in kinds  # the flushed tail
        assert any(m == "spawn_lead_time" for m, _ in kinds)
        for r in out:
            assert schema.validate_record(r) == []

    def test_idle_stream_emits_nothing(self):
        out = []
        em = ForecastEmitter(out.append, interval_s=0.1, clock=FakeClock())
        em.tap({"kind": "serve", "event": "summary"})  # no t0 traffic yet
        assert out == [] or all(
            r.get("metric") != "arrival_rate_rps" or r["n_samples"] == 0
            for r in out
        )

    def test_taps_are_thread_safe(self):
        """Concurrent taps from submit + worker threads never drop an
        arrival or corrupt a window."""
        clk = FakeClock()
        out = []
        lock = threading.Lock()

        def emit(r):
            with lock:
                out.append(r)

        em = ForecastEmitter(emit, interval_s=1e9, clock=clk)
        threads = [
            threading.Thread(
                target=lambda k=k: [em.tap(_admit(k * 50 + j)) for j in range(50)]
            )
            for k in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert em._window_arrivals == 200

    def test_validation(self):
        with pytest.raises(ValueError):
            ForecastEmitter(lambda r: None, interval_s=0.0)

    def test_latest_forecast_accessor_tracks_closed_windows(self):
        """The autoscaler's pull side (ISSUE 18): latest_forecast() is
        None before any window closes, then a COPY of the most recent
        closed-window record — mutating the copy never corrupts the
        emitter's own state."""
        clk = FakeClock()
        out = []
        em = ForecastEmitter(out.append, interval_s=0.5, clock=clk)
        assert em.latest_forecast() is None
        em.tap(_admit(0))
        clk.advance(0.5)
        em.tap(_admit(1))  # closes the first window
        fc = em.latest_forecast()
        assert fc is not None and "forecast_abs_err" in fc
        assert fc["observed_rate_rps"] == out[-1]["observed_rate_rps"]
        fc["predicted"] = 1e9
        assert em.latest_forecast()["predicted"] != 1e9

    def test_spare_spawn_feeds_lead_model(self):
        """Warm-pool pre-spawns are REAL lead evidence: a spare_spawn's
        spawn_ms lands in the lead model exactly like a cold scale_out's
        — the anticipatory signal can arm before any live spawn."""
        clk = FakeClock()
        out = []
        em = ForecastEmitter(out.append, interval_s=10.0, clock=clk)
        em.tap({"kind": "serve", "event": "spare_spawn", "spawn_ms": 120.0})
        assert em.lead_model.lead_time_ms() == 120.0
        leads = [r for r in out if r.get("metric") == "spawn_lead_time"]
        assert len(leads) == 1
        # A promotion is NOT a spawn: promote_ms must never contaminate
        # the cold-spawn lead distribution.
        em.tap({"kind": "serve", "event": "spare_promote",
                "promote_ms": 0.4})
        assert em.lead_model.lead_time_ms() == 120.0
