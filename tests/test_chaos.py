"""End-to-end chaos: the acceptance scenarios from the resilience PR.

  (1) injected kill mid-train -> fit_supervised resumes from the last
      VALID checkpoint, the metrics stream shows a continuous step
      sequence, and a stamped "recovery" event marks the resume;
  (2) injected backend flap during a serve load burst -> every ticket
      reaches a terminal state (served, degraded-served, or shed — never
      hung), the degradation ladder steps down AND back up, and the
      request accounting conserves exactly.

The in-process fit_supervised tests run host-only (fake trainer, orbax
over np pytrees) and stay tier-1; the subprocess SIGKILL ride and the
threaded serve burst are slow-marked — CI's chaos job runs this module
unfiltered, and `python -m glom_tpu.resilience` drives the same kill
scenario against the REAL training CLI.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from glom_tpu.resilience import DegradationLadder, FaultPlan, InjectedFault
from glom_tpu.telemetry import schema


class ListWriter:
    def __init__(self):
        self.records = []

    def write(self, rec):
        self.records.append(rec)


# ---------------------------------------------------------------------------
# fit_supervised: the in-process restart loop (host-only, tier-1)
# ---------------------------------------------------------------------------


class FlakyTrainer:
    """Host-only trainer honoring the fit_supervised protocol, with a
    seeded failure plan: 'training' folds each batch's mean into w, so a
    resumed-and-realigned run must produce bit-identical state to an
    unfaulted one — the restart loop cannot silently skip or repeat a
    batch without this catching it."""

    def __init__(self, plan=None):
        self.state = {
            "w": np.zeros((), np.float64),
            "step": np.zeros((), np.int32),
        }
        self.plan = plan

    def fit(self, data, num_steps, log_every=10):
        hist = []
        for _ in range(num_steps):
            batch = next(data)
            if self.plan is not None and self.plan.fires("train-step"):
                raise InjectedFault("injected trainer crash")
            step = int(np.asarray(self.state["step"]))
            self.state = {
                "w": np.asarray(
                    np.asarray(self.state["w"]) + float(np.mean(batch)),
                    np.float64,
                ),
                "step": np.asarray(step + 1, np.int32),
            }
            hist.append({"step": step, "loss": 1.0})
        return hist


def _data_factory():
    def make():
        return iter(np.full((2,), float(i)) for i in range(1000))

    return make


class TestFitSupervised:
    def test_crash_resumes_from_last_valid_checkpoint(self, tmp_path):
        from glom_tpu.train.supervise import TrainSupervisor, fit_supervised

        w = ListWriter()
        plan = FaultPlan(seed=1)
        plan.register("train-step", at=(5,), fault="trainer-crash")
        history = fit_supervised(
            lambda: FlakyTrainer(plan),
            _data_factory(),
            8,
            checkpoint_dir=str(tmp_path / "ckpt"),
            checkpoint_every=2,
            log_every=1,
            supervisor=TrainSupervisor(
                max_restarts=2, backoff_s=0.0, writer=w
            ),
            metrics_writer=w,
        )
        steps = sorted({h["step"] for h in history})
        assert steps == list(range(8))  # continuous, no gap, no loss
        actions = [
            r["action"] for r in w.records if r.get("kind") == "recovery"
        ]
        assert actions == ["restart", "resume-from-checkpoint"]
        resume = [
            r for r in w.records
            if r.get("action") == "resume-from-checkpoint"
        ][0]
        assert resume["step"] == 4  # last span committed before the crash
        assert schema.validate_record(resume) == []
        # Bit-identical to an unfaulted run: restart + realign is exact.
        clean = FlakyTrainer()
        data = _data_factory()()
        clean.fit(data, 8, log_every=1)
        final = FlakyTrainer(plan=None)
        # reload the supervised run's final committed state
        from glom_tpu.utils.checkpoint import CheckpointManager, abstract_like

        mgr = CheckpointManager(str(tmp_path / "ckpt"))
        step, got = mgr.restore(abstract_state=abstract_like(final.state))
        mgr.close()
        assert step == 8
        np.testing.assert_array_equal(
            np.asarray(got["w"]), np.asarray(clean.state["w"])
        )

    def test_max_to_keep_plumbs_to_retention(self, tmp_path):
        """--checkpoint-keep must reach the supervised loop's manager:
        pod gangs raise retention precisely because it bounds the step
        drift the preemption barrier can bridge — a silently-default 3
        would garbage-collect the very step a barrier commits."""
        from glom_tpu.train.supervise import fit_supervised

        fit_supervised(
            lambda: FlakyTrainer(),
            _data_factory(),
            6,
            checkpoint_dir=str(tmp_path / "ckpt"),
            checkpoint_every=1,
            log_every=1,
            max_to_keep=6,
        )
        kept = sorted(
            int(p.name)
            for p in (tmp_path / "ckpt").iterdir()
            if p.name.isdigit()
        )
        assert kept == [1, 2, 3, 4, 5, 6]  # default 3 keeps only [4, 5, 6]

    def test_budget_exhausted_gives_up_and_reraises(self, tmp_path):
        from glom_tpu.train.supervise import TrainSupervisor, fit_supervised

        w = ListWriter()
        plan = FaultPlan(seed=1)
        plan.register("train-step", rate=1.0, fault="trainer-crash")
        with pytest.raises(InjectedFault):
            fit_supervised(
                lambda: FlakyTrainer(plan),
                _data_factory(),
                4,
                checkpoint_dir=str(tmp_path / "ckpt"),
                checkpoint_every=2,
                supervisor=TrainSupervisor(
                    max_restarts=1, backoff_s=0.0, writer=w
                ),
                metrics_writer=w,
            )
        actions = [
            r["action"] for r in w.records if r.get("kind") == "recovery"
        ]
        assert actions == ["restart", "give-up"]
        for r in w.records:
            assert schema.validate_record(r) == []

    def test_backoff_is_bounded_exponential(self):
        from glom_tpu.train.supervise import TrainSupervisor

        sleeps = []
        sup = TrainSupervisor(
            max_restarts=4, backoff_s=0.5, backoff_factor=2.0,
            backoff_max_s=1.5, sleep=sleeps.append,
        )
        for _ in range(4):
            sup.begin_attempt()
            assert sup.on_failure(InjectedFault("x")) is not None
        assert sleeps == [0.5, 1.0, 1.5, 1.5]  # capped, never unbounded
        sup.begin_attempt()
        assert sup.on_failure(InjectedFault("x")) is None  # budget spent
        assert sup.record()["gave_up"] is True

    def test_already_complete_checkpoint_returns_immediately(self, tmp_path):
        from glom_tpu.train.supervise import fit_supervised

        fit_supervised(
            lambda: FlakyTrainer(),
            _data_factory(),
            4,
            checkpoint_dir=str(tmp_path / "ckpt"),
            checkpoint_every=2,
        )
        # second run over the same dir: nothing left to train
        history = fit_supervised(
            lambda: FlakyTrainer(),
            _data_factory(),
            4,
            checkpoint_dir=str(tmp_path / "ckpt"),
            checkpoint_every=2,
        )
        assert history == []

    def test_torn_newest_checkpoint_resumes_from_previous(self, tmp_path):
        """Compose the torn-checkpoint fault with the restart loop: the
        newest step is corrupted between runs (the mid-write SIGKILL
        signature), and the next fit_supervised resumes one step back and
        still finishes."""
        from glom_tpu.resilience import truncate_newest_checkpoint
        from glom_tpu.train.supervise import fit_supervised

        ckpt = str(tmp_path / "ckpt")
        fit_supervised(
            lambda: FlakyTrainer(), _data_factory(), 4,
            checkpoint_dir=ckpt, checkpoint_every=2,
        )
        truncate_newest_checkpoint(ckpt)
        w = ListWriter()
        history = fit_supervised(
            lambda: FlakyTrainer(), _data_factory(), 6,
            checkpoint_dir=ckpt, checkpoint_every=2, metrics_writer=w,
        )
        assert sorted({h["step"] for h in history}) == [2, 3, 4, 5]
        resume = [
            r for r in w.records
            if r.get("action") == "resume-from-checkpoint"
        ]
        assert resume and resume[0]["step"] == 2  # torn 4 skipped
        skips = [
            r for r in w.records
            if r.get("action") == "skip-torn-checkpoint"
        ]
        assert skips and skips[0]["quarantined"]  # torn step moved aside
        # THE persistence regression (reviewer-reproduced): a skipped
        # torn step must not keep owning Orbax's latest-step slot — the
        # retrained progress must land durably, or every future resume
        # re-trains the same span forever.
        from pathlib import Path

        from glom_tpu.utils.checkpoint import CheckpointManager

        mgr = CheckpointManager(ckpt)
        assert mgr.latest_step() == 6
        assert 4 in mgr.valid_steps()  # the retrained step 4, re-saved
        mgr.close()
        # forensics preserved, hidden from Orbax's step scanner
        assert list((Path(ckpt) / ".quarantine").glob("4_*"))


# ---------------------------------------------------------------------------
# Acceptance (1): SIGKILL mid-train, real trainer, subprocess
# ---------------------------------------------------------------------------

_WORKER = r"""
import sys
import jax
jax.config.update("jax_platforms", "cpu")
from glom_tpu.data import gaussian_dataset
from glom_tpu.train import Trainer, fit_supervised
from glom_tpu.utils.config import GlomConfig, TrainConfig
from glom_tpu.utils.metrics import MetricsWriter

ckpt_dir, metrics_path, steps = sys.argv[1], sys.argv[2], int(sys.argv[3])
cfg = GlomConfig(dim=16, levels=3, image_size=8, patch_size=2)
tcfg = TrainConfig(batch_size=4, learning_rate=1e-3, iters=2, recon_iter_index=1)
writer = MetricsWriter(metrics_path, echo=False)
history = fit_supervised(
    lambda: Trainer(cfg, tcfg, metrics_writer=writer),
    lambda: gaussian_dataset(tcfg.batch_size, cfg.image_size, seed=0),
    steps,
    checkpoint_dir=ckpt_dir,
    checkpoint_every=1,
    log_every=1,
    metrics_writer=writer,
)
writer.close()
print("DONE", len(history), flush=True)
"""


class TestSigkillSupervised:
    @pytest.mark.slow
    def test_sigkill_mid_train_resumes_continuous(self, tmp_path):
        ckpt = str(tmp_path / "ckpt")
        metrics = str(tmp_path / "metrics.jsonl")
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        args = [sys.executable, "-u", "-c", _WORKER, ckpt, metrics, "6"]

        proc = subprocess.Popen(
            args, env=env, cwd=repo,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        watchdog = threading.Timer(300, proc.kill)
        watchdog.start()
        try:
            # SIGKILL the moment >= 2 steps are manifest-committed.
            deadline = time.monotonic() + 240
            import glob

            while time.monotonic() < deadline:
                if len(glob.glob(os.path.join(ckpt, "manifest_*.json"))) >= 2:
                    os.kill(proc.pid, signal.SIGKILL)
                    break
                if proc.poll() is not None:
                    pytest.fail(
                        f"worker exited early rc={proc.returncode}: "
                        f"{proc.stdout.read()[-2000:]}"
                    )
                time.sleep(0.1)
            else:
                pytest.fail("no 2 committed checkpoints before deadline")
            proc.wait(timeout=60)
        finally:
            watchdog.cancel()
            if proc.poll() is None:
                proc.kill()
        assert proc.returncode != 0

        out = subprocess.run(
            args, env=env, cwd=repo, capture_output=True, text=True,
            timeout=300,
        )
        assert out.returncode == 0, out.stdout + out.stderr
        assert "DONE" in out.stdout

        with open(metrics) as fh:
            recs = [r for _, r in schema.iter_json_lines(fh)]
        steps = sorted(
            {
                int(r["step"]) for r in recs
                if r.get("kind") == "train_step"
            }
        )
        assert steps == list(range(6))  # CONTINUOUS across the kill
        resumes = [
            r for r in recs
            if r.get("kind") == "recovery"
            and r.get("action") == "resume-from-checkpoint"
        ]
        assert resumes and resumes[0]["step"] >= 2
        with open(metrics) as fh:
            assert schema.lint_stream(fh) == []


# ---------------------------------------------------------------------------
# Acceptance (2): backend flap during a serve load burst
# ---------------------------------------------------------------------------


class BurstEngine:
    """Engine-shaped stub with adjustable latency (the queue-pressure
    knob) that honors iters_override like the real engine."""

    retry = None

    def __init__(self, latency_s=0.004):
        self.buckets = (1, 2, 4)
        self.latency_s = latency_s

    def pick_bucket(self, n):
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(n)

    def infer(self, imgs, n_valid=None, iters_override=None):
        from glom_tpu.serve.engine import ServeResult

        time.sleep(self.latency_s)
        b = imgs.shape[0]
        return ServeResult(
            levels=np.zeros((b, 4, 3, 8), np.float32),
            iters_run=iters_override if iters_override is not None else 6,
            latency_s=self.latency_s,
            bucket=b,
            compiled=False,
        )


class TestServeFlapBurst:
    @pytest.mark.slow
    def test_flap_under_load_every_ticket_terminal_ladder_round_trips(self):
        from glom_tpu.serve.batcher import DynamicBatcher, ShedError
        from glom_tpu.telemetry.watchdog import (
            BackendWatchdog,
            set_global_watchdog,
        )

        w = ListWriter()
        # Controllable backend: the cell is what the probe sees; the flap
        # schedule below drives down->up->down inside the flap window.
        cell = [1]
        clock = [0.0]
        wd = BackendWatchdog(
            probe=lambda timeout: cell[0],
            flap_window_s=30.0,
            flap_threshold=3,
            heartbeat_s=0,
            clock=lambda: clock[0],
        )
        ladder = DegradationLadder(
            degraded_iters=3, bucket_cap=2,
            high_water=0.5, low_water=0.2, min_dwell_s=0.0, writer=w,
        )
        set_global_watchdog(wd)
        try:
            assert wd.probe_once() == "up"
            batcher = DynamicBatcher(
                BurstEngine(), max_batch=4, max_delay_ms=1.0,
                queue_depth=8, writer=w, ladder=ladder,
            ).start()
            img = np.zeros((3, 8, 8), np.float32)
            tickets, n_shed_seen = [], 0

            def burst(n, pace_s=0.0):
                nonlocal n_shed_seen
                for _ in range(n):
                    try:
                        tickets.append(batcher.submit(img))
                    except ShedError:
                        n_shed_seen += 1
                    if pace_s:
                        time.sleep(pace_s)

            # Phase A — pressure burst: overfill the bounded queue.
            burst(60)
            # Phase B — flap: down -> up -> down -> up inside the window.
            for t, state in ((1.0, 0), (2.0, 1), (3.0, 0), (4.0, 1)):
                clock[0] = t
                cell[0] = state if state else None
                cell[0] = 1 if state else None
                wd.probe_once()
            assert wd.state == "flapping"
            # Flapping backend still SERVES (paced so the queue breathes).
            burst(20, pace_s=0.005)
            # Phase C — settle: age the flap window out, drain, restore.
            # (One probe ages the window, the next settles flapping->up —
            # the state machine's two-beat settle.)
            clock[0] = 120.0
            wd.probe_once()
            assert wd.probe_once() == "up"
            deadline = time.monotonic() + 30.0
            while ladder.rung() != 0 and time.monotonic() < deadline:
                time.sleep(0.05)
            assert ladder.rung() == 0, "ladder never stepped back up"
            batcher.stop(drain=True)

            # Every ticket terminal — served or shed, never hung.
            n_served = n_failed = 0
            for t in tickets:
                try:
                    t.result(timeout=10.0)
                    n_served += 1
                except ShedError:
                    n_failed += 1
            s = batcher.summary_record()
            assert schema.validate_record(s) == []
            # Conservation: every submit attempt accounted for, exactly.
            assert s["n_requests"] == len(tickets) + n_shed_seen
            assert s["n_served"] + s["n_shed"] + s["n_failed"] == s["n_requests"]
            assert s["n_failed"] == 0  # dispatch never failed a batch
            assert s["n_served"] >= 1 and s["n_shed"] >= 1
            # The ladder stepped DOWN and BACK UP, on the record.
            directions = {e["direction"] for e in ladder.timeline()}
            assert directions == {"degrade", "restore"}
            # Degraded service actually happened during the flap.
            assert s["n_degraded"] >= 1
            # Every stamped record in the stream validates.
            for rec in w.records:
                assert schema.validate_record(rec) == [], rec
        finally:
            set_global_watchdog(None)


class TestPreemptPod:
    @pytest.mark.slow  # 2x2 real train subprocesses; CI chaos job runs it
    def test_preempt_pod_commits_one_common_step_and_gang_resumes(
        self, tmp_path
    ):
        """The pod-preemption acceptance: `python -m glom_tpu.resilience
        --scenario preempt-pod` SIGTERMs a strict subset of a 2-process
        pod, then all of it; the two-phase barrier must commit ONE
        common step on both hosts inside the grace deadline, and the
        relaunched gang must resume from exactly that step with
        continuous per-host train_step sequences — proven from the JSONL
        evidence alone (stamped barrier phases, pod commit marker,
        resume events, lint-clean streams)."""
        proc = subprocess.run(
            [
                sys.executable, "-m", "glom_tpu.resilience",
                "--scenario", "preempt-pod",
                "--dir", str(tmp_path),
                "--steps", "8",
                "--hosts", "2",
            ],
            capture_output=True,
            text=True,
            timeout=500,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        driver = [
            json.loads(l)
            for l in proc.stdout.splitlines()
            if l.strip().startswith("{")
        ]
        summary = [r for r in driver if r.get("event") == "chaos-summary"]
        assert summary and summary[0]["ok"] is True, summary
        common = summary[0]["committed_common_step"]
        # Both SIGTERM waves were stamped as faults, subset first.
        waves = [r.get("wave") for r in driver if r.get("kind") == "fault"]
        assert waves == ["subset", "all"], waves
        # The commit marker is the completeness authority.
        from glom_tpu.resilience import read_pod_commit

        marker = read_pod_commit(tmp_path / "coord")
        assert marker and marker["step"] == common
        assert len(marker["proposals"]) == 2
        assert common == min(int(s) for s in marker["proposals"].values())
        # Per-host evidence: ONE common resume step, continuous steps.
        for h in (0, 1):
            recs = [
                json.loads(l)
                for l in (tmp_path / f"metrics_h{h}.jsonl")
                .read_text().splitlines()
                if l.strip().startswith("{")
            ]
            resumes = {r["step"] for r in recs
                       if r.get("action") == "resume-from-checkpoint"}
            assert resumes == {common}, (h, resumes, common)
            steps = sorted({int(r["step"]) for r in recs
                            if r.get("kind") == "train_step"})
            missing = set(range(8)) - set(steps)
            assert missing <= {common - 1}, (h, steps)


class TestKillServe:
    @pytest.mark.slow  # subprocess serve run; CI chaos job runs it
    def test_kill_serve_scenario_validates_failover_evidence(self, tmp_path):
        """The serve-side chaos acceptance: `python -m glom_tpu.resilience
        --scenario kill-serve` permanently fails engine 0 of a 2-engine
        micro-server via the seeded dispatch_fault seam and must prove,
        from the evidence alone, that every queued ticket re-dispatched
        to the sibling (rc 0, stamped faults + engine_failover +
        engine_dead, exact ticket conservation, lint-clean stream)."""
        import subprocess
        import sys

        proc = subprocess.run(
            [
                sys.executable, "-m", "glom_tpu.resilience",
                "--scenario", "kill-serve",
                "--dir", str(tmp_path),
                "--requests", "8",
            ],
            capture_output=True,
            text=True,
            timeout=500,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        driver = [
            json.loads(l)
            for l in proc.stdout.splitlines()
            if l.strip().startswith("{")
        ]
        summary = [r for r in driver if r.get("event") == "chaos-summary"]
        assert summary and summary[0]["ok"] is True
        assert summary[0]["n_failovers"] >= 1
        metrics = tmp_path / "serve_metrics.jsonl"
        recs = [
            json.loads(l)
            for l in metrics.read_text().splitlines()
            if l.strip().startswith("{")
        ]
        s = [r for r in recs if r.get("event") == "summary"][-1]
        assert s["n_served"] == 8 and s["n_failed"] == 0
        assert not s["engines"]["engine0"]["alive"]
