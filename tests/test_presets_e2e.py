"""End-to-end: every BASELINE preset builds a DistributedTrainer on the
8-device virtual mesh under its DECLARED parallelism strategy and completes
one finite training step (VERDICT round-1 next-step #3 — the round-1 gap was
that preset 3 crashed on its own mesh and no test ever ran the presets
distributed).

Model dims/batch are shrunk for CPU speed, but the parts that broke — patch
GRID GEOMETRY (image/patch size, radius), mesh shape, and sp_strategy — are
kept exactly as declared.
"""

import dataclasses

import jax
import numpy as np
import pytest

from glom_tpu.data import gaussian_dataset
from glom_tpu.parallel import DistributedTrainer
from glom_tpu.utils.presets import PRESETS, get_preset


def _tiny(preset, num_devices=8):
    """Shrink compute (dim, levels, batch, iters) while preserving the patch
    grid geometry, mesh, and SP strategy the preset declares."""
    p = preset.scaled_to(num_devices)
    model = dataclasses.replace(p.model, dim=64, levels=min(p.model.levels, 3))
    train = dataclasses.replace(
        p.train,
        batch_size=2 * p.mesh.data,
        iters=2,
        recon_iter_index=1,
        compute_dtype="float32",  # CPU: bf16 is emulated and slow
    )
    return dataclasses.replace(p, model=model, train=train)


@pytest.mark.parametrize("name", sorted(PRESETS))
def test_preset_builds_and_steps_distributed(name):
    p = _tiny(get_preset(name))
    assert p.mesh.num_devices <= len(jax.devices())
    trainer = DistributedTrainer(
        p.model, p.train, p.mesh, sp_strategy=p.sp_strategy
    )
    batch = next(gaussian_dataset(p.train.batch_size, p.model.image_size, seed=0))
    metrics = trainer.step(batch)
    assert np.isfinite(float(metrics["loss"])), (name, metrics)


def test_preset3_resolves_exact_mechanism():
    """Radius 7 on an 8-row grid can never satisfy the one-hop halo
    precondition (4 rows/shard < 7); the preset declares intent ('auto')
    and the selector resolves an EXACT mechanism without crashing
    (round-1 ADVICE medium; round-3 VERDICT #3: intent, not mechanism).
    At n=64 global crossover, that mechanism is ulysses (L=6 % seq=2)."""
    from glom_tpu.parallel.runtime import effective_sp_strategy

    p = get_preset("imagenet64-local")
    assert p.sp_strategy == "auto"
    assert effective_sp_strategy(p.model, p.mesh.seq, p.sp_strategy) == "ulysses"


def test_halo_preset_keeps_halo_at_8_devices():
    """The long-context halo flagship (32x32 grid, radius 7, seq=4 -> 8 rows
    per shard >= 7) must still resolve to halo after scaled_to(8)."""
    from glom_tpu.parallel.runtime import effective_sp_strategy

    p = get_preset("imagenet256-local").scaled_to(8)
    assert p.mesh.num_devices <= 8
    assert effective_sp_strategy(p.model, p.mesh.seq, p.sp_strategy) == "halo"


def test_scaled_to_falls_back_when_halo_breaks():
    """Shrinking the mesh must re-resolve the halo precondition instead of
    shipping a config that raises at trainer construction: side=32 at
    seq=8 gives 4 rows per shard < floor(radius)=7, and L=6 % 8 != 0
    forbids ulysses too, so the exact mechanism is ring."""
    import glom_tpu.utils.presets as presets_mod
    from glom_tpu.parallel.runtime import effective_sp_strategy

    base = get_preset("imagenet256-local")
    broken = dataclasses.replace(
        base, mesh=presets_mod.MeshConfig(data=1, seq=8, model=1)
    ).scaled_to(8)
    assert (
        effective_sp_strategy(broken.model, broken.mesh.seq, broken.sp_strategy)
        == "ring"
    )


class TestHybridMesh:
    """Multi-slice (ICI x DCN) topology: BASELINE config 5's pod layout."""

    def test_construction_and_step(self):
        """A 2-slice mesh over the 8 virtual devices builds and completes a
        finite train step (slice-major data axis; same logical axes)."""
        from glom_tpu.utils.config import GlomConfig, MeshConfig, TrainConfig

        mesh_cfg = MeshConfig(data=4, seq=2, num_slices=2)
        cfg = GlomConfig(dim=32, levels=3, image_size=16, patch_size=4)
        tcfg = TrainConfig(batch_size=8, iters=2, recon_iter_index=1, remat=True)
        trainer = DistributedTrainer(cfg, tcfg, mesh_cfg, sp_strategy="ring")
        assert trainer.mesh.shape == {"data": 4, "seq": 2, "model": 1}
        batch = next(gaussian_dataset(8, 16, seed=0))
        assert np.isfinite(float(trainer.step(batch)["loss"]))

    def test_indivisible_slices_rejected(self):
        from glom_tpu.utils.config import MeshConfig

        with pytest.raises(ValueError, match="num_slices"):
            MeshConfig(data=4, num_slices=3)

    def test_pod_preset_declares_slices_and_scales_down(self):
        pod = get_preset("imagenet224-pod")
        assert pod.mesh.num_slices == 4
        small = pod.scaled_to(8)
        # DATA shrinks first (the elastic axis): (64,2,2) -> (2,2,2) on 8
        # devices, preserving the declared seq x model composition so the
        # scaled-down pod still exercises TP+SP with the fused kernels —
        # and a scaled-down mesh is a single-slice deployment, so the DCN
        # split must collapse (it would otherwise force the hybrid-mesh
        # path on a topology that has no 4-way slice factor).
        assert small.mesh.shape == (2, 2, 2)
        assert small.mesh.num_slices == 1
        # Unchanged size keeps the declared multi-slice layout.
        assert pod.scaled_to(256).mesh.num_slices == 4


def test_halo_fallback_warns_in_make_consensus_fn():
    """Direct runtime users get the same safety net: halo with an impossible
    geometry falls back to ring (with a warning) instead of raising."""
    from glom_tpu.parallel.mesh import make_mesh
    from glom_tpu.parallel.runtime import make_consensus_fn
    from glom_tpu.utils.config import GlomConfig, MeshConfig

    mesh = make_mesh(MeshConfig(data=1, seq=2, model=1), jax.devices()[:2])
    cfg = GlomConfig(
        dim=64, levels=2, image_size=64, patch_size=8, local_consensus_radius=7
    )
    with pytest.warns(UserWarning, match="falling back to ring"):
        fn = make_consensus_fn(mesh, cfg, "halo")
    assert fn is not None
