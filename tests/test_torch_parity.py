"""Cross-framework parity: glom_tpu (jax) vs the independent PyTorch oracle.

The BASELINE.json north star is "match the PyTorch-CUDA reference loss
curve". These tests make that checkable at unit scale: transplant IDENTICAL
initial weights into both frameworks, feed IDENTICAL data and noise, and
require matching forwards and matching per-step Adam training losses
(torch autograd + torch.optim.Adam vs jax.grad + optax.adam).

The committed full-scale curve artifact is produced by parity_torch.py.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import optax  # noqa: E402

from glom_tpu.models.core import glom_forward  # noqa: E402
from glom_tpu.train.objectives import denoise_loss, init_denoise  # noqa: E402
from glom_tpu.utils.config import GlomConfig  # noqa: E402

import oracle_torch  # noqa: E402  (tests/ is on sys.path via conftest rootdir)

CFG = GlomConfig(dim=16, levels=3, image_size=8, patch_size=4)  # n=4 patches


def _setup(seed=0, cfg=CFG):
    params = init_denoise(jax.random.PRNGKey(seed), cfg)
    tparams = oracle_torch.params_from_jax(params)
    rng = np.random.default_rng(seed + 100)
    img = rng.normal(size=(2, 3, cfg.image_size, cfg.image_size)).astype(np.float32)
    return params, tparams, img


@pytest.mark.parametrize(
    "cfg",
    [
        CFG,
        GlomConfig(dim=16, levels=3, image_size=16, patch_size=4,
                   local_consensus_radius=1),
        GlomConfig(dim=16, levels=3, image_size=8, patch_size=4,
                   consensus_self=True),
    ],
    ids=["global", "local-radius", "attend-self"],
)
def test_forward_matches_torch(cfg):
    params, tparams, img = _setup(cfg=cfg)
    out_jax = np.asarray(glom_forward(params.glom, jnp.asarray(img), cfg))
    with torch.no_grad():
        out_torch = oracle_torch.forward(tparams, torch.from_numpy(img), cfg)
    np.testing.assert_allclose(out_jax, out_torch.numpy(), rtol=1e-4, atol=1e-5)


def test_return_all_matches_torch():
    params, tparams, img = _setup()
    out_jax = np.asarray(
        glom_forward(params.glom, jnp.asarray(img), CFG, return_all=True)
    )
    with torch.no_grad():
        out_torch = oracle_torch.forward(
            tparams, torch.from_numpy(img), CFG, return_all=True
        )
    assert out_jax.shape == tuple(out_torch.shape)  # T+1 stacked states
    np.testing.assert_allclose(out_jax, out_torch.numpy(), rtol=1e-4, atol=1e-5)


def test_adam_loss_curve_matches_torch():
    """5 Adam steps, identical weights/data/noise: per-step losses must track
    to float32 tolerance — the north-star loss-curve match at unit scale."""
    steps, lr = 5, 1e-3
    params, tparams, _ = _setup()
    rng = np.random.default_rng(7)
    shape = (2, 3, CFG.image_size, CFG.image_size)
    images = [rng.normal(size=shape).astype(np.float32) for _ in range(steps)]
    noises = [rng.normal(size=shape).astype(np.float32) for _ in range(steps)]

    # torch side
    torch_losses = oracle_torch.train(tparams, images, noises, CFG, lr)

    # jax side: same objective, optax.adam (defaults match torch.optim.Adam)
    opt = optax.adam(lr)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state, img, noise):
        loss, grads = jax.value_and_grad(denoise_loss)(
            params, img, noise, CFG
        )
        updates, opt_state = opt.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    jax_losses = []
    for img, noise in zip(images, noises):
        params, opt_state, loss = step(
            params, opt_state, jnp.asarray(img), jnp.asarray(noise)
        )
        jax_losses.append(float(loss))

    np.testing.assert_allclose(jax_losses, torch_losses, rtol=5e-4)
