"""Aux subsystem tests: chunked consensus, checkpoint/resume, presets, CLI,
metrics/FLOP model."""

import json
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from glom_tpu.ops.consensus import build_local_mask, consensus_attention
from glom_tpu.ops.consensus_chunked import chunked_consensus_attention
from glom_tpu.utils.config import GlomConfig, TrainConfig
from glom_tpu.utils.metrics import flops_per_column_iter, mfu
from glom_tpu.utils.presets import PRESETS, get_preset


class TestChunkedConsensus:
    def test_matches_dense(self, rng):
        x = jnp.asarray(rng.normal(size=(2, 16, 3, 32)), jnp.float32)
        got = chunked_consensus_attention(x, chunk_size=4)
        want = consensus_attention(x)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-6
        )

    def test_matches_dense_with_radius_and_self(self, rng):
        x = jnp.asarray(rng.normal(size=(1, 16, 2, 16)), jnp.float32)
        got = chunked_consensus_attention(
            x, attend_self=True, num_patches_side=4, local_radius=1.5, chunk_size=8
        )
        want = consensus_attention(
            x, attend_self=True, local_mask=build_local_mask(4, 1.5)
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-6
        )

    def test_differentiable(self, rng):
        x = jnp.asarray(rng.normal(size=(1, 8, 2, 8)), jnp.float32)
        g = jax.grad(lambda t: jnp.mean(chunked_consensus_attention(t, chunk_size=4) ** 2))(x)
        assert np.isfinite(np.asarray(g)).all()

    def test_bad_chunk_raises(self, rng):
        x = jnp.zeros((1, 10, 2, 8))
        with pytest.raises(ValueError, match="divisible"):
            chunked_consensus_attention(x, chunk_size=4)


class TestCheckpoint:
    def test_save_restore_roundtrip(self, tmp_path):
        from glom_tpu.train import Trainer
        from glom_tpu.utils.checkpoint import CheckpointManager, abstract_like
        from glom_tpu.data import shapes_dataset

        cfg = GlomConfig(dim=16, levels=3, image_size=8, patch_size=2)
        tcfg = TrainConfig(batch_size=2, learning_rate=1e-3)
        tr = Trainer(cfg, tcfg)
        tr.fit(shapes_dataset(2, 8, seed=0), num_steps=3, log_every=1)

        mgr = CheckpointManager(str(tmp_path / "ckpt"), async_save=False)
        mgr.save(3, tr.state)
        mgr.wait()
        assert mgr.latest_step() == 3

        step, restored = mgr.restore(abstract_state=abstract_like(tr.state))
        assert step == 3
        for a, b in zip(
            jax.tree_util.tree_leaves(tr.state), jax.tree_util.tree_leaves(restored)
        ):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b))
        mgr.close()

    def test_resume_continues_training(self, tmp_path):
        """Failure-recovery semantics: train 3, checkpoint, 'crash', restore,
        and keep training — the restored trainer must produce identical next
        losses to the uninterrupted one."""
        from glom_tpu.train import Trainer
        from glom_tpu.utils.checkpoint import CheckpointManager, abstract_like
        from glom_tpu.data import shapes_dataset

        cfg = GlomConfig(dim=16, levels=3, image_size=8, patch_size=2)
        tcfg = TrainConfig(batch_size=2, learning_rate=1e-3)

        tr = Trainer(cfg, tcfg)
        data = shapes_dataset(2, 8, seed=0)
        tr.fit(data, num_steps=3, log_every=1)
        mgr = CheckpointManager(str(tmp_path / "ckpt"), async_save=False)
        mgr.save(3, tr.state)
        mgr.wait()
        cont = tr.fit(data, num_steps=2, log_every=1)

        tr2 = Trainer(cfg, tcfg)
        _, tr2.state = mgr.restore(abstract_state=abstract_like(tr2.state))
        tr2.rng = tr.rng  # the host rng is part of resume state in the CLI
        mgr.close()
        # NOTE: rng was advanced during the continued run; to compare we
        # restart the comparison from identical rng + state + data stream.
        data2 = shapes_dataset(2, 8, seed=0)
        for _ in range(3):
            next(data2)
        # can't replay tr.rng pre-continuation here, so just check training
        # proceeds finitely from the restored state
        h = tr2.fit(data2, num_steps=2, log_every=1)
        assert all(np.isfinite(m["loss"]) for m in h)


class TestPresets:
    def test_all_five_baseline_configs_exist(self):
        # The five BASELINE.md configs, plus the long-context halo flagship.
        assert set(PRESETS) == {
            "mnist",
            "cifar10",
            "imagenet64-local",
            "imagenet256-local",
            "imagenet224-dp8",
            "imagenet224-pod",
        }

    def test_configs_match_baseline_table(self):
        m = get_preset("mnist").model
        assert (m.dim, m.levels, m.image_size, m.patch_size) == (128, 4, 28, 7)
        c = get_preset("cifar10").model
        assert (c.dim, c.levels, c.image_size, c.patch_size) == (256, 5, 32, 4)
        i64 = get_preset("imagenet64-local").model
        assert (i64.dim, i64.levels, i64.image_size, i64.patch_size) == (512, 6, 64, 8)
        assert i64.local_consensus_radius == 7
        i224 = get_preset("imagenet224-dp8")
        assert i224.mesh.data == 8
        pod = get_preset("imagenet224-pod")
        assert pod.model.levels == 12 and pod.model.dim == 1024
        assert pod.train.remat and pod.mesh.num_devices == 256

    def test_scaled_to_fits(self):
        for name in PRESETS:
            s = get_preset(name).scaled_to(8)
            assert s.mesh.num_devices <= 8

    def test_unknown_raises(self):
        with pytest.raises(KeyError):
            get_preset("nope")


class TestFlopModel:
    def test_flops_positive_and_scales(self):
        small = flops_per_column_iter(GlomConfig(dim=128, levels=4, image_size=28, patch_size=7))
        big = flops_per_column_iter(GlomConfig(dim=512, levels=6, image_size=224, patch_size=14))
        assert 0 < small < big

    def test_mfu_sane(self):
        cfg = GlomConfig(dim=512, levels=6, image_size=224, patch_size=14)
        # 70% of v5e peak, backward off
        rate = 0.7 * 197e12 / flops_per_column_iter(cfg)
        assert abs(mfu(cfg, rate, chip="v5e") - 0.7) < 1e-6


class TestMetricsWriter:
    def test_tensorboard_mirror(self, tmp_path):
        """tensorboard_dir mirrors numeric scalars to clu summaries (bools
        and strings skipped, `step` consumed as the TB step) while the JSONL
        file stays the artifact of record."""
        pytest.importorskip("clu")
        from glom_tpu.utils.metrics import MetricsWriter

        tb = tmp_path / "tb"
        jsonl = tmp_path / "m.jsonl"
        w = MetricsWriter(str(jsonl), echo=False, tensorboard_dir=str(tb))
        w.write({"step": 3, "loss": 0.5, "note": "text", "flag": True})
        w.write({"loss": 0.25})  # no step -> internal counter (4)
        w.close()
        events = list(tb.glob("events.out.tfevents.*"))
        assert events, "no TensorBoard event file written"
        lines = jsonl.read_text().strip().splitlines()
        assert len(lines) == 2 and '"loss": 0.5' in lines[0]


class TestCLI:
    # Shared subprocess bootstrap: virtual 8-device CPU platform (the
    # config.update is required — env vars alone are defeated by this
    # image's sitecustomize TPU pre-registration).
    ENV_SNIPPET = (
        "import os; os.environ['XLA_FLAGS']='--xla_force_host_platform_device_count=8';"
        "import jax; jax.config.update('jax_platforms','cpu');"
        "from glom_tpu.train.cli import main; import sys;"
    )

    @pytest.mark.slow  # full train/ckpt/resume subprocess ride (~40 s);
    # tier-1 keeps the distributed + parity CLI smokes, CI runs this one
    def test_end_to_end_smoke(self, tmp_path):
        """Drive the CLI as a subprocess on CPU: train, checkpoint, resume."""
        env_snippet = self.ENV_SNIPPET
        ckpt = tmp_path / "ck"
        metrics = tmp_path / "m.jsonl"
        r = subprocess.run(
            [
                sys.executable,
                "-c",
                env_snippet
                + f"sys.exit(main(['--preset','mnist','--steps','4','--log-every','2',"
                f"'--batch-size','2','--data','gaussian',"
                f"'--checkpoint-dir','{ckpt}','--checkpoint-every','2',"
                f"'--metrics-file','{metrics}']))",
            ],
            capture_output=True,
            text=True,
            timeout=600,
        )
        assert r.returncode == 0, r.stderr[-2000:]
        lines = [json.loads(l) for l in metrics.read_text().splitlines()]
        # The stream carries span rollups next to the step records since
        # PR 3 — consumers select by kind (the schema contract).
        steps = [m for m in lines if m.get("kind") == "train_step"]
        assert steps and all(np.isfinite(m["loss"]) for m in steps)

        r2 = subprocess.run(
            [
                sys.executable,
                "-c",
                env_snippet
                + f"sys.exit(main(['--preset','mnist','--steps','6','--log-every','2',"
                f"'--batch-size','2','--data','gaussian',"
                f"'--checkpoint-dir','{ckpt}','--resume']))",
            ],
            capture_output=True,
            text=True,
            timeout=600,
        )
        assert r2.returncode == 0, r2.stderr[-2000:]
        assert "resumed from step 4" in r2.stderr

    def test_distributed_smoke(self, tmp_path):
        """--distributed scales the preset mesh to the visible devices and
        trains on the virtual 8-device mesh."""
        env_snippet = self.ENV_SNIPPET
        metrics = tmp_path / "m.jsonl"
        r = subprocess.run(
            [
                sys.executable,
                "-c",
                env_snippet
                + f"sys.exit(main(['--preset','mnist','--steps','3','--log-every','1',"
                f"'--batch-size','8','--data','gaussian','--distributed',"
                f"'--metrics-file','{metrics}']))",
            ],
            capture_output=True,
            text=True,
            timeout=600,
        )
        assert r.returncode == 0, r.stderr[-2000:]
        assert "mesh" in r.stderr  # the mesh banner printed
        lines = [json.loads(l) for l in metrics.read_text().splitlines()]
        steps = [m for m in lines if m.get("kind") == "train_step"]
        assert steps and all(np.isfinite(m["loss"]) for m in steps)

    def test_check_parity_smoke(self):
        """--check-parity runs sharded-vs-single and exits 0 when the loss
        histories agree (the race-detection / sanitizer mode, SURVEY §5)."""
        env_snippet = self.ENV_SNIPPET
        r = subprocess.run(
            [
                sys.executable,
                "-c",
                env_snippet
                + "sys.exit(main(['--preset','mnist','--steps','2','--log-every','1',"
                "'--batch-size','8','--data','gaussian','--check-parity']))",
            ],
            capture_output=True,
            text=True,
            timeout=600,
        )
        assert r.returncode == 0, (r.stdout + r.stderr)[-2000:]
        assert "parity: worst relative loss deviation" in r.stdout


class TestPrefetch:
    def test_yields_all_batches_on_device(self):
        from glom_tpu.data import prefetch_to_device

        batches = [np.full((2, 3, 4, 4), i, np.float32) for i in range(5)]
        out = list(prefetch_to_device(iter(batches), size=2))
        assert len(out) == 5
        for i, b in enumerate(out):
            assert isinstance(b, jax.Array)
            np.testing.assert_array_equal(np.asarray(b), batches[i])

    def test_propagates_source_exception(self):
        from glom_tpu.data import prefetch_to_device

        def bad():
            yield np.zeros((1,), np.float32)
            raise RuntimeError("boom")

        it = prefetch_to_device(bad(), size=2)
        next(it)
        with pytest.raises(RuntimeError, match="boom"):
            next(it)

    def test_sharded_prefetch_trains(self):
        """Distributed fit(prefetch=2): batches staged pre-sharded must
        train identically-finitely on the virtual mesh."""
        from glom_tpu.data import gaussian_dataset
        from glom_tpu.parallel import DistributedTrainer
        from glom_tpu.utils.config import MeshConfig

        cfg = GlomConfig(dim=16, levels=3, image_size=8, patch_size=4)
        tcfg = TrainConfig(batch_size=8, learning_rate=1e-3)
        tr = DistributedTrainer(cfg, tcfg, MeshConfig(data=4, seq=2),
                                sp_strategy="ring")
        h = tr.fit(gaussian_dataset(8, 8, seed=0), num_steps=3,
                   log_every=1, prefetch=2)
        assert h and all(np.isfinite(m["loss"]) for m in h)

    def test_single_device_prefetch_matches_sync(self):
        """fit(prefetch=2) must produce the same losses as the synchronous
        path (prefetch changes staging, not data order or values)."""
        from glom_tpu.data import shapes_dataset
        from glom_tpu.train import Trainer

        cfg = GlomConfig(dim=16, levels=3, image_size=8, patch_size=2)
        tcfg = TrainConfig(batch_size=2, learning_rate=1e-3)
        h1 = Trainer(cfg, tcfg).fit(shapes_dataset(2, 8, seed=3), num_steps=4,
                                    log_every=1)
        h2 = Trainer(cfg, tcfg).fit(shapes_dataset(2, 8, seed=3), num_steps=4,
                                    log_every=1, prefetch=2)
        np.testing.assert_allclose(
            [m["loss"] for m in h1], [m["loss"] for m in h2], rtol=1e-6
        )

    def test_abandoning_iterator_stops_worker(self):
        """fit pulls N batches from an infinite dataset and drops the
        iterator — the worker thread must exit and release its staging
        slots rather than leak (one thread + size+1 device buffers per
        fit call otherwise)."""
        import threading
        import time as _time

        from glom_tpu.data import prefetch_to_device

        def infinite():
            i = 0
            while True:
                yield np.full((1,), i, np.float32)
                i += 1

        before = threading.active_count()
        it = prefetch_to_device(infinite(), size=2)
        for _ in range(3):
            next(it)
        it.close()  # what dropping the iterator does at GC, deterministically
        deadline = _time.time() + 5.0
        while threading.active_count() > before and _time.time() < deadline:
            _time.sleep(0.05)
        assert threading.active_count() <= before, "prefetch worker leaked"

    def test_bad_size_fails_at_call_site(self):
        from glom_tpu.data import prefetch_to_device

        with pytest.raises(ValueError, match="prefetch size"):
            prefetch_to_device(iter([]), size=0)


class TestBackendProbe:
    """probe_device_count (round-5 driver hardening): the wedged-backend
    probe must NEVER raise and never initialize a backend in the calling
    process — every failure mode maps to None so dryrun_multichip falls
    through to the CPU re-exec and bench.py fails fast parseably."""

    def test_parses_devcount(self, monkeypatch):
        import subprocess as sp
        from types import SimpleNamespace

        from glom_tpu.utils import metrics

        monkeypatch.setattr(
            sp, "run",
            lambda *a, **kw: SimpleNamespace(
                returncode=0,
                stdout="Platform warning...\nDEVCOUNT=8\n",
                stderr="",
            ),
        )
        assert metrics.probe_device_count() == 8

    @pytest.mark.slow  # spawns a REAL backend-init subprocess: in the
    # wedged-TPU image it burns the full 45 s timeout on every run, and
    # even healthy CI pays a backend cold-start; the monkeypatched
    # failure-mode tests below keep every code path in tier-1
    def test_live_probe_never_raises(self):
        """Against the REAL image env (where a sitecustomize hook
        pre-registers the TPU plugin): whatever the backend state — cpu
        mesh, healthy TPU, or the wedged-init hang this helper exists
        for — the call must return an int or None, never raise. (In the
        wedged state it burns `timeout` in the subprocess and returns
        None, which is exactly what routes dryrun_multichip to the CPU
        re-exec.)"""
        from glom_tpu.utils.metrics import probe_device_count

        n = probe_device_count(timeout=45.0)
        assert n is None or (isinstance(n, int) and n >= 1)

    def test_hang_maps_to_none(self, monkeypatch):
        import subprocess as sp

        from glom_tpu.utils import metrics

        def fake_run(*a, **kw):
            raise sp.TimeoutExpired(cmd=a[0], timeout=kw.get("timeout"))

        monkeypatch.setattr(sp, "run", fake_run)
        assert metrics.probe_device_count(timeout=0.1) is None

    def test_crash_maps_to_none(self, monkeypatch):
        import subprocess as sp
        from types import SimpleNamespace

        from glom_tpu.utils import metrics

        monkeypatch.setattr(
            sp, "run",
            lambda *a, **kw: SimpleNamespace(returncode=1, stdout="", stderr="boom"),
        )
        assert metrics.probe_device_count() is None

    def test_garbage_output_maps_to_none(self, monkeypatch):
        import subprocess as sp
        from types import SimpleNamespace

        from glom_tpu.utils import metrics

        monkeypatch.setattr(
            sp, "run",
            lambda *a, **kw: SimpleNamespace(
                returncode=0, stdout="some warning\n", stderr=""
            ),
        )
        assert metrics.probe_device_count() is None
