"""Pod-scale aggregation + SLO monitor (glom_tpu/telemetry/aggregate.py):
clock-family reconciliation across hosts, rollups, barrier-chain checks,
the windowed SLO rules, and both CLIs. Pure host-side, no jax."""

import json

import pytest

from glom_tpu.telemetry import schema
from glom_tpu.telemetry.aggregate import (
    BARRIER_CHAIN,
    SLOMonitor,
    aggregate_main,
    check_barrier_chains,
    expand_paths,
    load_host_records,
    merge_timeline,
    parse_slo,
    percentile,
    rollup,
    watch_main,
)

EPOCH = 1.75e9  # a plausible time.time() reading


def dispatch(engine="engine0", bucket=4, latency_ms=5.0, t=None, **extra):
    rec = {"event": "dispatch", "engine": engine, "bucket": bucket,
           "n_valid": 3, "latency_ms": latency_ms, "iters_run": 6,
           "trace_ids": None, **extra}
    if t is not None:
        rec["wall_time_s"] = t
    return schema.stamp(rec, kind="serve")


def resolve(latency_ms=8.0, iters=6, trace_id=None, t=None, **extra):
    rec = {"event": "resolve", "engine": "engine0", "iters_total": iters,
           "dispatch_ms_total": 5.0, "latency_ms": latency_ms,
           "trace_id": trace_id, **extra}
    if t is not None:
        rec["wall_time_s"] = t
    return schema.stamp(rec, kind="serve")


def barrier(phase, host, step=3, rnd="r1", t=None):
    rec = {"phase": phase, "round": rnd, "host": host, "step": step}
    if t is not None:
        rec["wall_time_s"] = t
    return schema.stamp(rec, kind="barrier")


def train_step(step, wall_time, wall_time_s=None):
    rec = {"step": step, "loss": 1.0, "wall_time": wall_time}
    if wall_time_s is not None:
        rec["wall_time_s"] = wall_time_s
    return schema.stamp(rec, kind="train_step")


def write_stream(path, recs):
    with open(path, "w") as fh:
        fh.write("shell noise line\n")
        for r in recs:
            fh.write(json.dumps(r) + "\n")
    return path


class TestPercentile:
    def test_nearest_rank(self):
        vals = [float(i) for i in range(1, 101)]
        assert percentile(vals, 0.5) == 51.0
        assert percentile(vals, 0.99) == 99.0
        assert percentile([], 0.5) == 0.0


class TestExpandPaths:
    def test_dirs_expand_and_stems_label(self, tmp_path):
        (tmp_path / "metrics_h0.jsonl").write_text("")
        (tmp_path / "metrics_h1.jsonl").write_text("")
        (tmp_path / "noise.log").write_text("")
        hosts = expand_paths([str(tmp_path)])
        assert list(hosts) == ["metrics_h0", "metrics_h1"]

    def test_collisions_qualify_with_parent_dir(self, tmp_path):
        a = tmp_path / "a"
        b = tmp_path / "b"
        a.mkdir()
        b.mkdir()
        (a / "metrics.jsonl").write_text("")
        (b / "metrics.jsonl").write_text("")
        hosts = expand_paths([str(a / "metrics.jsonl"),
                              str(b / "metrics.jsonl")])
        assert set(hosts) == {"metrics", "b/metrics"}

    def test_triple_collision_never_drops_a_stream(self, tmp_path):
        """Three runX/pod/metrics_h0.jsonl: the third's parent-qualified
        label collides with the second's — it must qualify deeper (or
        suffix), never silently overwrite a host's stream."""
        files = []
        for run in ("runA", "runB", "runC"):
            d = tmp_path / run / "pod"
            d.mkdir(parents=True)
            f = d / "metrics_h0.jsonl"
            f.write_text("")
            files.append(str(f))
        hosts = expand_paths(files)
        assert len(hosts) == 3
        assert sorted(hosts.values()) == sorted(files)


class TestMergeTimeline:
    def test_two_anchored_hosts_interleave_on_one_axis(self):
        # Each host: run-relative train steps + ONE anchor record carrying
        # both families (the MetricsWriter + barrier shape).
        hosts = {
            "h0": [train_step(0, 1.0, EPOCH + 1.0),
                   train_step(1, 2.0)],
            "h1": [train_step(0, 1.0, EPOCH + 1.5),
                   train_step(1, 2.0)],
        }
        merged = merge_timeline(hosts)
        assert merged["violations"] == []
        order = [(e["host"], e["rec"].get("step")) for e in merged["events"]]
        assert order == [("h0", 0), ("h1", 0), ("h0", 1), ("h1", 1)]
        assert merged["events"][0]["t"] == 0.0

    def test_barrier_chain_interleaves_with_per_host_steps(self):
        """The preempt-pod acceptance shape: per-host steps on relative
        clocks, the barrier chain on epoch clocks, one consistent merged
        order with zero clock-family violations."""
        hosts = {
            "h0": [train_step(0, 1.0, EPOCH + 1.0),
                   barrier("propose", 0, t=EPOCH + 2.0),
                   barrier("commit", 0, t=EPOCH + 2.2),
                   barrier("saved", 0, t=EPOCH + 2.4),
                   barrier("complete", 0, t=EPOCH + 2.8)],
            "h1": [train_step(0, 1.0, EPOCH + 1.1),
                   train_step(1, 2.1),
                   barrier("propose", 1, t=EPOCH + 2.1),
                   barrier("commit", 1, t=EPOCH + 2.3),
                   barrier("saved", 1, t=EPOCH + 2.5),
                   barrier("complete", 1, t=EPOCH + 2.8)],
        }
        merged = merge_timeline(hosts)
        assert merged["violations"] == []
        labels = [
            (e["host"], e["rec"].get("phase") or f"step{e['rec'].get('step')}")
            for e in merged["events"]
        ]
        # h1's relative-clock step 1 (wall_time 2.1 -> epoch+2.2) lands
        # INSIDE the barrier chain — the interleaving the merge exists for.
        assert labels.index(("h1", "step1")) > labels.index(("h0", "propose"))
        assert labels.index(("h1", "step1")) < labels.index(("h1", "complete"))
        phases = [p for _, p in labels if p in BARRIER_CHAIN]
        assert phases == sorted(phases, key=list(
            ["propose", "commit", "saved", "complete"]).index)

    def test_unanchorable_family_mix_is_a_violation(self):
        hosts = {
            "h0": [
                schema.stamp({"note": "rel", "wall_time": 1.0}, kind="note"),
                schema.stamp({"note": "epoch", "wall_time_s": EPOCH},
                             kind="note"),
            ],
        }
        merged = merge_timeline(hosts)
        assert merged["violations"] and "no anchor" in merged["violations"][0]

    def test_relative_only_host_beside_epoch_host_is_flagged(self):
        hosts = {
            "h0": [schema.stamp({"note": "x", "wall_time_s": EPOCH},
                                kind="note")],
            "h1": [schema.stamp({"note": "y", "wall_time": 1.0},
                                kind="note")],
        }
        merged = merge_timeline(hosts)
        assert any("no epoch anchor" in v for v in merged["violations"])

    def test_clockless_records_keep_stream_order(self):
        hosts = {"h0": [schema.stamp({"note": f"n{i}"}, kind="note")
                        for i in range(3)]}
        merged = merge_timeline(hosts)
        assert merged["violations"] == []
        assert [e["rec"]["note"] for e in merged["events"]] == [
            "n0", "n1", "n2"
        ]


class TestRollup:
    def hosts(self):
        return {
            "h0": [dispatch(latency_ms=4.0), dispatch(latency_ms=6.0),
                   resolve(latency_ms=7.0, iters=4),
                   resolve(latency_ms=9.0, iters=8),
                   schema.stamp({"event": "shed", "reason": "queue-full",
                                 "trace_id": None}, kind="serve"),
                   schema.stamp({"event": "engine_failover",
                                 "engine": "engine0", "trace_ids": None},
                                kind="serve"),
                   schema.stamp({"event": "summary",
                                 "column_cache": {"n_hits": 3, "n_misses": 1,
                                                  "n_writes": 4,
                                                  "n_evictions": 0}},
                                kind="serve")],
            "h1": [dispatch(engine="engine1", bucket=2, latency_ms=10.0),
                   resolve(latency_ms=11.0, iters=6)],
        }

    def test_pod_rollup_counts_and_percentiles(self):
        roll = rollup(self.hosts())
        assert roll["n_hosts"] == 2
        assert roll["requests"]["n_resolved"] == 3
        assert roll["requests"]["n_shed"] == 1
        assert roll["requests"]["shed_rate"] == 0.25
        assert roll["latency_ms"]["dispatch"]["n"] == 3
        assert roll["latency_ms"]["request"]["p50"] == 9.0
        assert roll["executed_iters"]["histogram"] == {"4": 1, "8": 1, "6": 1}
        assert roll["executed_iters"]["mean"] == 6.0
        assert roll["per_engine"]["engine0"]["n_failovers"] == 1
        assert roll["per_engine"]["engine1"]["n_dispatches"] == 1
        assert roll["per_bucket"]["2"]["n_dispatches"] == 1
        assert roll["cache"]["hit_rate"] == 0.75
        assert roll["per_host"]["h0"]["n_shed"] == 1

    def test_rollup_without_cache_or_serve_records(self):
        roll = rollup({"h0": [train_step(0, 1.0)]})
        assert roll["cache"] is None
        assert roll["requests"]["shed_rate"] is None

    def test_dispatch_without_latency_still_counts(self):
        """per_engine/per_bucket dispatch counts must not depend on the
        record carrying a numeric latency_ms — only the latency
        histograms do."""
        rec = dispatch()
        del rec["latency_ms"]
        roll = rollup({"h0": [rec]})
        assert roll["per_host"]["h0"]["n_dispatches"] == 1
        assert roll["per_engine"]["engine0"]["n_dispatches"] == 1
        assert roll["per_engine"]["engine0"]["n_valid"] == 3
        assert roll["per_bucket"]["4"]["n_dispatches"] == 1
        assert roll["per_engine"]["engine0"]["latency_ms"]["n"] == 0

    def test_untraced_stream_rolls_up_from_responses(self):
        """trace_requests=False streams carry NO resolve leaves — the
        shed rate and request latency must fall back to the ok
        responses (SLOMonitor's convention), not read one shed as
        shed_rate 1.0 over an empty latency histogram."""
        def response(ok=True, latency_ms=10.0):
            return schema.stamp(
                {"event": "response", "ok": ok, "latency_ms": latency_ms,
                 "trace_id": None},
                kind="serve",
            )
        recs = [response(latency_ms=ms) for ms in (8.0, 10.0, 12.0)]
        recs.append(response(ok=False))
        recs.append(schema.stamp(
            {"event": "shed", "reason": "queue-full", "trace_id": None},
            kind="serve",
        ))
        roll = rollup({"h0": recs})
        assert roll["requests"]["n_resolved"] == 0
        assert roll["requests"]["shed_rate"] == 0.25  # 1 / (3 ok + 1 shed)
        assert roll["latency_ms"]["request"]["n"] == 3
        assert roll["latency_ms"]["request"]["p50"] == 10.0

    def test_traced_stream_does_not_double_count_responses(self):
        """A traced stream carries BOTH leaves per request: successes
        must come from the resolves (max, not sum) and the latency
        histogram from the resolve leaves alone."""
        recs = [resolve(latency_ms=8.0, trace_id="t1"),
                schema.stamp(
                    {"event": "response", "ok": True, "latency_ms": 9.0,
                     "trace_id": "t1"},
                    kind="serve",
                )]
        roll = rollup({"h0": recs})
        assert roll["requests"]["shed_rate"] == 0.0
        assert roll["latency_ms"]["request"]["n"] == 1
        assert roll["latency_ms"]["request"]["p50"] == 8.0


class TestBarrierChains:
    def complete_round(self):
        rounds = {}
        for phase in BARRIER_CHAIN:
            rounds.setdefault("r1", {}).setdefault(phase, []).extend(
                {"host": h, "step": 3} for h in ("h0", "h1")
            )
        return rounds

    def test_complete_chain_is_clean(self):
        assert check_barrier_chains(self.complete_round()) == []

    def test_missing_phase_on_one_host_is_flagged(self):
        rounds = self.complete_round()
        rounds["r1"]["saved"] = [{"host": "h0", "step": 3}]
        problems = check_barrier_chains(rounds)
        assert problems and "saved" in problems[0]

    def test_diverging_commit_steps_are_flagged(self):
        rounds = self.complete_round()
        rounds["r1"]["commit"][1]["step"] = 4
        problems = check_barrier_chains(rounds)
        assert any("DIFFERENT steps" in p for p in problems)

    def test_aborted_rounds_are_not_held_to_the_chain(self):
        rounds = {"r1": {"propose": [{"host": "h0", "step": 3}],
                         "abort": [{"host": "h0", "step": None}]}}
        assert check_barrier_chains(rounds) == []

    def test_committed_round_missing_complete_is_flagged(self):
        """A host dying between commit and complete (no abort stamped)
        is the partial pod checkpoint this check exists to catch — a
        committed round must NOT be skipped just because 'complete'
        never arrived."""
        rounds = self.complete_round()
        del rounds["r1"]["complete"]
        del rounds["r1"]["saved"]
        problems = check_barrier_chains(rounds)
        assert any("saved" in p for p in problems)
        assert any("complete" in p for p in problems)

    def test_uncommitted_open_round_is_not_flagged(self):
        rounds = {"r1": {"propose": [{"host": "h0", "step": 3},
                                     {"host": "h1", "step": 3}]}}
        assert check_barrier_chains(rounds) == []


class TestParseSlo:
    def test_parses_rule_and_threshold(self):
        assert parse_slo("p99_ms=50") == ("p99_ms", 50.0)
        assert parse_slo("shed_rate=0.1") == ("shed_rate", 0.1)

    def test_unknown_rule_and_bad_value_fail_loudly(self):
        with pytest.raises(ValueError, match="p99_ms"):
            parse_slo("p99=50")
        with pytest.raises(ValueError, match="not a number"):
            parse_slo("p99_ms=fast")


class FakeClock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t


class Sink:
    def __init__(self):
        self.records = []

    def write(self, rec):
        self.records.append(rec)


class TestSLOMonitor:
    def test_p99_breach_emits_stamped_record(self):
        sink = Sink()
        mon = SLOMonitor({"p99_ms": 50.0}, writer=sink, clock=FakeClock())
        for ms in (10.0, 20.0, 80.0):
            mon.observe(resolve(latency_ms=ms))
        breaches = mon.evaluate()
        assert len(breaches) == 1
        b = breaches[0]
        assert b["kind"] == "slo_breach" and b["rule"] == "p99_ms"
        assert b["observed"] == 80.0 and b["threshold"] == 50.0
        assert "backend_state" in b  # watchdog-attributable
        assert schema.validate_record(b) == []
        assert sink.records == breaches  # writer-delivered
        assert mon.n_breaches == 1

    def test_within_slo_emits_nothing(self):
        mon = SLOMonitor({"p99_ms": 50.0, "shed_rate": 0.5},
                         clock=FakeClock())
        mon.observe(resolve(latency_ms=10.0))
        assert mon.evaluate() == []

    def test_shed_rate_rule(self):
        mon = SLOMonitor({"shed_rate": 0.4}, clock=FakeClock())
        mon.observe(resolve())
        mon.observe(schema.stamp({"event": "shed", "reason": "queue-full",
                                  "trace_id": None}, kind="serve"))
        (b,) = mon.evaluate()
        assert b["rule"] == "shed_rate" and b["observed"] == 0.5

    def test_trace_id_dedups_resolve_and_response(self):
        mon = SLOMonitor({"mean_ms": 1.0}, clock=FakeClock())
        mon.observe(resolve(latency_ms=10.0, trace_id="t1"))
        mon.observe(schema.stamp(
            {"event": "response", "ok": True, "latency_ms": 12.0,
             "trace_id": "t1"}, kind="serve"))
        assert len(mon._latency) == 1  # counted once per trace

    def test_min_samples_keeps_a_thin_window_silent(self):
        mon = SLOMonitor({"p99_ms": 1.0}, min_samples=3, clock=FakeClock())
        mon.observe(resolve(latency_ms=50.0))
        assert mon.evaluate() == []

    def test_window_prunes_old_samples(self):
        clock = FakeClock()
        mon = SLOMonitor({"p99_ms": 5.0}, window_s=10.0, clock=clock)
        mon.observe(resolve(latency_ms=100.0))
        clock.t += 60.0
        mon.observe(resolve(latency_ms=1.0))
        assert mon.evaluate() == []  # the spike aged out of the window

    def test_idle_stream_stops_breaching_once_the_window_empties(self):
        """evaluate() must prune on its own clock: a live watch over a
        stream that went IDLE after a slow burst never calls observe()
        again, and the stale burst must not keep firing breaches every
        interval forever."""
        clock = FakeClock()
        mon = SLOMonitor({"p99_ms": 5.0}, window_s=10.0, clock=clock)
        mon.observe(resolve(latency_ms=100.0))
        assert len(mon.evaluate()) == 1  # breach while in-window
        clock.t += 1000.0  # traffic stops; only evaluate() keeps running
        assert mon.evaluate() == []
        assert mon.n_breaches == 1

    def test_breaches_feed_the_flight_recorder_storm_trigger(self, tmp_path):
        from glom_tpu.tracing.flight import (
            FlightRecorder,
            set_global_flight_recorder,
        )

        fr = FlightRecorder(str(tmp_path), storm_threshold=2,
                            storm_window_s=60.0)
        set_global_flight_recorder(fr)
        try:
            mon = SLOMonitor({"p99_ms": 1.0}, clock=FakeClock())
            mon.observe(resolve(latency_ms=50.0))
            mon.evaluate()
            mon.evaluate()  # second breach inside the storm window
        finally:
            set_global_flight_recorder(None)
        assert fr.dumps, "an SLO-breach storm must dump the ring"
        dumped = [json.loads(l) for l in open(fr.dumps[0])
                  if l.strip().startswith("{")]
        assert any(r.get("kind") == "slo_breach" for r in dumped)


class TestWatchCli:
    def breach_stream(self, tmp_path):
        recs = [resolve(latency_ms=100.0 + i, trace_id=None)
                for i in range(8)]
        recs.append(schema.stamp({"event": "shed", "reason": "queue-full",
                                  "trace_id": None}, kind="serve"))
        return write_stream(tmp_path / "serve.jsonl", recs)

    def test_once_mode_breach_exits_nonzero_and_stamps(self, tmp_path,
                                                       capsys):
        self.breach_stream(tmp_path)
        rc = watch_main([str(tmp_path), "--slo", "p99_ms=50", "--once"])
        assert rc == 1
        out = capsys.readouterr()
        stamped = [json.loads(l) for l in out.out.splitlines()
                   if l.startswith("{")]
        assert stamped and stamped[0]["kind"] == "slo_breach"
        assert "SLO BREACH" in out.err

    def test_once_mode_within_slo_exits_zero(self, tmp_path):
        self.breach_stream(tmp_path)
        assert watch_main(
            [str(tmp_path), "--slo", "p99_ms=1000", "--once"]) == 0

    def test_once_mode_with_no_records_exits_two(self, tmp_path):
        (tmp_path / "empty.jsonl").write_text("no json\n")
        assert watch_main(
            [str(tmp_path), "--slo", "p99_ms=10", "--once"]) == 2

    def test_bad_rule_exits_two(self, tmp_path):
        self.breach_stream(tmp_path)
        assert watch_main([str(tmp_path), "--slo", "bogus=1", "--once"]) == 2

    def test_live_mode_tails_and_exits_on_deadline(self, tmp_path):
        self.breach_stream(tmp_path)
        rc = watch_main([
            str(tmp_path), "--slo", "p99_ms=50", "--window", "60",
            "--interval", "0.05", "--max-seconds", "0.2",
        ])
        assert rc == 1


class TestAggregateCli:
    def pod(self, tmp_path):
        h0 = [train_step(0, 1.0, EPOCH + 1.0),
              dispatch(latency_ms=3.0, t=EPOCH + 2.0),
              resolve(latency_ms=5.0, t=EPOCH + 2.1),
              barrier("propose", 0, t=EPOCH + 3.0),
              barrier("commit", 0, t=EPOCH + 3.1),
              barrier("saved", 0, t=EPOCH + 3.2),
              barrier("complete", 0, t=EPOCH + 3.3)]
        h1 = [train_step(0, 1.0, EPOCH + 1.1),
              barrier("propose", 1, t=EPOCH + 3.05),
              barrier("commit", 1, t=EPOCH + 3.15),
              barrier("saved", 1, t=EPOCH + 3.25),
              barrier("complete", 1, t=EPOCH + 3.3)]
        write_stream(tmp_path / "metrics_h0.jsonl", h0)
        write_stream(tmp_path / "metrics_h1.jsonl", h1)
        return tmp_path

    def test_pod_rollup_summary_line(self, tmp_path, capsys):
        rc = aggregate_main([str(self.pod(tmp_path)), "--strict"])
        assert rc == 0
        out = capsys.readouterr().out
        summary = json.loads(out.strip().splitlines()[-1])
        assert summary["kind"] == "summary"
        assert summary["n_violations"] == 0
        assert summary["pod_rollup"]["n_hosts"] == 2
        assert set(
            summary["pod_rollup"]["timelines"]["barrier"]["r1"]
        ) == set(BARRIER_CHAIN)

    def test_strict_fails_on_broken_barrier_chain(self, tmp_path, capsys):
        self.pod(tmp_path)
        # host 1's "saved" never happened: a torn pod round must gate.
        lines = (tmp_path / "metrics_h1.jsonl").read_text().splitlines()
        (tmp_path / "metrics_h1.jsonl").write_text(
            "\n".join(l for l in lines if '"saved"' not in l) + "\n"
        )
        assert aggregate_main([str(tmp_path), "--strict"]) == 1
        assert "saved" in capsys.readouterr().err

    def test_out_writes_rollup_file(self, tmp_path, capsys):
        out = tmp_path / "rollup.json"
        assert aggregate_main(
            [str(self.pod(tmp_path)), "--out", str(out)]) == 0
        obj = json.loads(out.read_text())
        assert obj["rollup"]["n_hosts"] == 2

    def test_no_streams_exits_nonzero(self, tmp_path):
        assert aggregate_main([str(tmp_path / "missing")]) == 1

    def test_real_host_record_loader(self, tmp_path):
        self.pod(tmp_path)
        hosts = expand_paths([str(tmp_path)])
        records = load_host_records(hosts)
        assert set(records) == {"metrics_h0", "metrics_h1"}
        assert all(records.values())


class TestReviewRegressions:
    """Pinned fixes from the PR 10 review pass."""

    def test_shed_rate_on_an_untraced_response_only_stream(self):
        """trace_requests=False streams carry responses but no resolve
        leaves: one shed among many successes must NOT read as rate 1.0."""
        mon = SLOMonitor({"shed_rate": 0.4}, clock=FakeClock())
        for i in range(9):
            mon.observe(schema.stamp(
                {"event": "response", "ok": True, "latency_ms": 5.0,
                 "trace_id": None}, kind="serve"))
        mon.observe(schema.stamp({"event": "shed", "reason": "queue-full",
                                  "trace_id": None}, kind="serve"))
        assert mon.evaluate() == []
        assert mon.observed()["shed_rate"] == 0.1

    def test_shed_rate_not_halved_by_resolve_plus_response_pairs(self):
        mon = SLOMonitor({"shed_rate": 0.0}, clock=FakeClock())
        mon.observe(resolve(trace_id="t1"))
        mon.observe(schema.stamp(
            {"event": "response", "ok": True, "latency_ms": 5.0,
             "trace_id": "t1"}, kind="serve"))
        mon.observe(schema.stamp({"event": "shed", "reason": "queue-full",
                                  "trace_id": None}, kind="serve"))
        assert mon.observed()["shed_rate"] == 0.5  # 1 shed / (1 + 1)

    def test_latency_trace_dedup_set_prunes_with_the_window(self):
        clock = FakeClock()
        mon = SLOMonitor({"p99_ms": 1e9}, window_s=10.0, clock=clock)
        for i in range(5):
            mon.observe(resolve(latency_ms=1.0, trace_id=f"t{i}"))
        clock.t += 60.0
        mon.observe(resolve(latency_ms=1.0, trace_id="fresh"))
        assert mon._latency_traces == {"fresh"}

    def test_clockless_record_after_epoch_anchor_stays_adjacent(self):
        """A seq record trailing an epoch-clock one must ride the pod
        axis through the re-zeroing, not strand ~50 years out."""
        hosts = {
            "h0": [
                schema.stamp({"note": "anchor", "wall_time_s": EPOCH},
                             kind="note"),
                schema.stamp({"note": "clockless"}, kind="note"),
                schema.stamp({"note": "later", "wall_time_s": EPOCH + 5.0},
                             kind="note"),
            ],
        }
        merged = merge_timeline(hosts)
        order = [e["rec"]["note"] for e in merged["events"]]
        assert order == ["anchor", "clockless", "later"]
        assert merged["events"][1]["t"] == pytest.approx(1e-3)

    def test_watch_live_tail_does_not_consume_a_torn_line(self, tmp_path):
        """drain() must never advance past a half-flushed record: the
        complete first line is observed, the torn tail is left for the
        writer's next flush (not consumed as garbage)."""
        p = tmp_path / "s.jsonl"
        full = json.dumps(resolve(latency_ms=100.0))
        p.write_text(full + "\n" + full[: len(full) // 2])
        rc = watch_main([
            str(p), "--slo", "p99_ms=50", "--max-seconds", "0.1",
            "--interval", "0.02",
        ])
        assert rc == 1  # the complete line was seen and breached
