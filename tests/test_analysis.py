"""glom-lint (glom_tpu/analysis): every checker catches its seeded
violation with file:line, passes a clean snippet, and the pass self-hosts
clean on the repo with the reviewed baseline.

Pure AST tests — no jax import, no compiles; they stay in tier-1.
"""

import json
from pathlib import Path

import pytest

from glom_tpu.analysis import run
from glom_tpu.analysis import baseline as baseline_mod
from glom_tpu.analysis.__main__ import main

REPO = Path(__file__).resolve().parent.parent
FIXTURES = Path(__file__).resolve().parent / "fixtures"


def lint(tmp_path, source, name="snippet.py", select=None):
    path = tmp_path / name
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(source)
    return run([str(path)], select=select)


def by_checker(findings, checker):
    return [f for f in findings if f.checker == checker]


# ---------------------------------------------------------------------------
# collective-coverage
# ---------------------------------------------------------------------------


class TestCollectiveCoverage:
    def test_unknown_axis_literal_flagged(self, tmp_path):
        src = (
            "from jax import lax\n"
            "def f(x):\n"
            "    return lax.psum(x, 'bogus_axis')\n"
        )
        fs = by_checker(lint(tmp_path, src), "collective-coverage")
        assert len(fs) == 1
        assert fs[0].line == 3
        assert "bogus_axis" in fs[0].message

    def test_declared_axis_constant_clean(self, tmp_path):
        src = (
            "from jax import lax\n"
            "DATA_AXIS = 'data'\n"
            "def f(x):\n"
            "    return lax.psum(x, DATA_AXIS)\n"
        )
        assert by_checker(lint(tmp_path, src), "collective-coverage") == []

    def test_axis_param_threading_clean(self, tmp_path):
        src = (
            "from jax import lax\n"
            "def shard_body(x, axis_name):\n"
            "    return lax.ppermute(x, axis_name, [(0, 1)])\n"
        )
        assert by_checker(lint(tmp_path, src), "collective-coverage") == []

    def test_non_axis_param_flagged(self, tmp_path):
        src = (
            "from jax import lax\n"
            "def f(x, which):\n"
            "    return lax.pmean(x, which)\n"
        )
        fs = by_checker(lint(tmp_path, src), "collective-coverage")
        assert len(fs) == 1 and "which" in fs[0].message

    def test_unregistered_collective_in_wire_module(self, tmp_path):
        src = (
            "from jax import lax\n"
            "def grads(g):\n"
            "    return lax.psum_scatter(g, 'data', scatter_dimension=0)\n"
        )
        fs = by_checker(
            lint(tmp_path, src, name="parallel/manual.py"),
            "collective-coverage",
        )
        assert len(fs) == 1
        assert fs[0].line == 3 and "record_collective" in fs[0].message

    def test_registered_collective_clean(self, tmp_path):
        src = (
            "from jax import lax\n"
            "from glom_tpu.telemetry import counters as tele_counters\n"
            "def grads(g):\n"
            "    tele_counters.record_collective('reduce', 8)\n"
            "    return lax.psum_scatter(g, 'data', scatter_dimension=0)\n"
        )
        assert (
            by_checker(
                lint(tmp_path, src, name="parallel/manual.py"),
                "collective-coverage",
            )
            == []
        )

    def test_registration_not_required_outside_wire_modules(self, tmp_path):
        src = (
            "from jax import lax\n"
            "def f(x):\n"
            "    return lax.psum(x, 'data')\n"
        )
        assert by_checker(lint(tmp_path, src), "collective-coverage") == []


# ---------------------------------------------------------------------------
# trace-purity
# ---------------------------------------------------------------------------


class TestTracePurity:
    def test_host_clock_in_jitted_body(self, tmp_path):
        src = (
            "import time\n"
            "import jax\n"
            "def step(x):\n"
            "    t0 = time.perf_counter()\n"
            "    return x + t0\n"
            "fast = jax.jit(step)\n"
        )
        fs = by_checker(lint(tmp_path, src), "trace-purity")
        assert len(fs) == 1 and fs[0].line == 4
        assert "trace time" in fs[0].message

    def test_print_reachable_through_helper(self, tmp_path):
        src = (
            "import jax\n"
            "def helper(x):\n"
            "    print('loss', x)\n"
            "    return x\n"
            "def step(x):\n"
            "    return helper(x) * 2\n"
            "fast = jax.jit(step)\n"
        )
        fs = by_checker(lint(tmp_path, src), "trace-purity")
        assert len(fs) == 1 and fs[0].line == 3
        assert "jax.debug.print" in fs[0].message

    def test_numpy_on_parameter_in_shard_map_body(self, tmp_path):
        src = (
            "import numpy as np\n"
            "from glom_tpu.utils.compat import shard_map\n"
            "def build(mesh):\n"
            "    def body(params, x):\n"
            "        return np.asarray(x).sum()\n"
            "    return shard_map(body, mesh=mesh, in_specs=(), out_specs=())\n"
        )
        fs = by_checker(lint(tmp_path, src), "trace-purity")
        assert len(fs) == 1 and "numpy cannot consume tracers" in fs[0].message

    def test_metadata_reads_are_pure(self, tmp_path):
        src = (
            "import numpy as np\n"
            "import jax\n"
            "def step(x):\n"
            "    b = x.shape[0]\n"
            "    scale = np.float32(1.0 / b)\n"
            "    dt = np.dtype(x.dtype).itemsize\n"
            "    return x * scale + dt\n"
            "fast = jax.jit(step)\n"
        )
        assert by_checker(lint(tmp_path, src), "trace-purity") == []

    def test_branch_on_tracer_value(self, tmp_path):
        src = (
            "import jax\n"
            "import jax.numpy as jnp\n"
            "def body(c, x):\n"
            "    s = jnp.sum(x)\n"
            "    if s > 0:\n"
            "        return c, x\n"
            "    return c, -x\n"
            "def outer(xs):\n"
            "    return jax.lax.scan(body, 0, xs)\n"
        )
        fs = by_checker(lint(tmp_path, src), "trace-purity")
        assert len(fs) == 1 and fs[0].line == 5
        assert "lax.cond" in fs[0].message

    def test_while_loop_cond_and_config_branch_clean(self, tmp_path):
        src = (
            "import jax.numpy as jnp\n"
            "from jax import lax\n"
            "def run(x0, remat):\n"
            "    def cond(c):\n"
            "        return jnp.max(jnp.abs(c)) > 1e-3\n"
            "    def body(c):\n"
            "        if remat:\n"
            "            return c * 0.5\n"
            "        return c * 0.9\n"
            "    return lax.while_loop(cond, body, x0)\n"
        )
        assert by_checker(lint(tmp_path, src), "trace-purity") == []

    def test_host_code_not_flagged(self, tmp_path):
        src = (
            "import time\n"
            "def bench(step):\n"
            "    t0 = time.perf_counter()\n"
            "    step()\n"
            "    print(time.perf_counter() - t0)\n"
        )
        assert by_checker(lint(tmp_path, src), "trace-purity") == []


# ---------------------------------------------------------------------------
# donation-safety
# ---------------------------------------------------------------------------


class TestDonationSafety:
    def test_use_after_donated_dispatch(self, tmp_path):
        src = (
            "import jax\n"
            "def serve(params, imgs):\n"
            "    fn = jax.jit(lambda p, x: x * 2, donate_argnums=(1,))\n"
            "    out = fn(params, imgs)\n"
            "    return out, imgs.mean()\n"
        )
        fs = by_checker(lint(tmp_path, src), "donation-safety")
        assert len(fs) == 1 and fs[0].line == 5
        assert "imgs" in fs[0].message and "donated" in fs[0].message

    def test_non_donated_position_clean(self, tmp_path):
        src = (
            "import jax\n"
            "def serve(params, imgs):\n"
            "    fn = jax.jit(lambda p, x: x * 2, donate_argnums=(1,))\n"
            "    out = fn(params, imgs)\n"
            "    return out, params\n"
        )
        assert by_checker(lint(tmp_path, src), "donation-safety") == []

    def test_rebind_revives_the_name(self, tmp_path):
        src = (
            "import jax\n"
            "import jax.numpy as jnp\n"
            "def serve(imgs):\n"
            "    fn = jax.jit(lambda x: x * 2, donate_argnums=(0,))\n"
            "    out = fn(imgs)\n"
            "    imgs = jnp.zeros((4,))\n"
            "    return out, imgs\n"
        )
        assert by_checker(lint(tmp_path, src), "donation-safety") == []

    def test_decorated_empty_argnums_means_no_donation(self, tmp_path):
        src = (
            "import jax\n"
            "from functools import partial\n"
            "@partial(jax.jit, donate_argnums=())\n"
            "def fwd(x):\n"
            "    return x * 2\n"
            "def serve(imgs):\n"
            "    out = fwd(imgs)\n"
            "    return out, imgs.mean()\n"
        )
        assert by_checker(lint(tmp_path, src), "donation-safety") == []

    def test_decorated_donating_function_flagged(self, tmp_path):
        src = (
            "import jax\n"
            "from functools import partial\n"
            "@partial(jax.jit, donate_argnums=(0,))\n"
            "def fwd(x):\n"
            "    return x * 2\n"
            "def serve(imgs):\n"
            "    out = fwd(imgs)\n"
            "    return out, imgs.mean()\n"
        )
        fs = by_checker(lint(tmp_path, src), "donation-safety")
        assert len(fs) == 1 and fs[0].line == 8

    def test_lowered_compile_chain_conservative(self, tmp_path):
        src = (
            "import jax\n"
            "def serve(donate, abstract, params, imgs):\n"
            "    fn = jax.jit(lambda p, x: x, donate_argnums=donate)"
            ".lower(abstract, abstract).compile()\n"
            "    out = fn(params, imgs)\n"
            "    return imgs.sum()\n"
        )
        # unresolvable argnums spec -> every positional arg is treated as
        # donated, so the later read of imgs is flagged
        fs = by_checker(lint(tmp_path, src), "donation-safety")
        assert len(fs) == 1 and "imgs" in fs[0].message

    # -- memoized-handle taint (the PR 5 blind spot, closed) ---------------

    def test_memoized_handle_via_provider_method_flagged(self, tmp_path):
        """The engine's real shape: the donating compiled handle is
        stored in self._compiled by one method, fetched through a
        provider method by another, and the donated batch is read after
        the dispatch — invisible to the intra-function pass, caught by
        the class-level taint."""
        src = (
            "import jax\n"
            "class Engine:\n"
            "    def _compile(self, sig, abstract):\n"
            "        lowered = jax.jit(\n"
            "            lambda p, x: x, donate_argnums=(1,)\n"
            "        ).lower(abstract, abstract)\n"
            "        compiled = lowered.compile()\n"
            "        self._compiled[sig] = compiled\n"
            "        return compiled\n"
            "    def infer(self, sig, abstract, params, imgs):\n"
            "        fn = self._compile(sig, abstract)\n"
            "        out = fn(params, imgs)\n"
            "        return out, imgs.mean()\n"
        )
        fs = by_checker(lint(tmp_path, src), "donation-safety")
        assert len(fs) == 1 and fs[0].line == 13
        assert "imgs" in fs[0].message

    def test_memoized_handle_direct_subscript_call_flagged(self, tmp_path):
        src = (
            "import jax\n"
            "class Engine:\n"
            "    def _compile(self, sig):\n"
            "        self._compiled[sig] = jax.jit(\n"
            "            lambda p, x: x, donate_argnums=(1,)\n"
            "        )\n"
            "    def infer(self, sig, params, imgs):\n"
            "        out = self._compiled[sig](params, imgs)\n"
            "        return out, imgs.sum()\n"
        )
        fs = by_checker(lint(tmp_path, src), "donation-safety")
        assert len(fs) == 1 and fs[0].line == 9
        assert "self._compiled" in fs[0].message

    def test_memoized_handle_splat_kwargs_conservative(self, tmp_path):
        """`jax.jit(fn, **jit_kw)` hides the donation inside the dict —
        on the HANDLE path every position is conservatively donated (the
        direct intra-function rule is unchanged: no class, no handle, no
        finding)."""
        src = (
            "import jax\n"
            "class Engine:\n"
            "    def _compile(self, sig, jit_kw):\n"
            "        self._compiled[sig] = jax.jit(lambda x: x, **jit_kw)\n"
            "    def infer(self, sig, imgs):\n"
            "        out = self._compiled[sig](imgs)\n"
            "        return out, imgs.sum()\n"
        )
        fs = by_checker(lint(tmp_path, src), "donation-safety")
        assert len(fs) == 1 and "imgs" in fs[0].message

    def test_memoized_handle_rebind_clears_the_taint(self, tmp_path):
        """Rebinding the handle name to a NON-donating callable clears
        the taint: the plain callable's call sites must not inherit the
        memoized handle's donation spec (review-caught false positive)."""
        src = (
            "import jax\n"
            "class Engine:\n"
            "    def _compile(self, sig):\n"
            "        self._compiled[sig] = jax.jit(\n"
            "            lambda p, x: x, donate_argnums=(1,)\n"
            "        )\n"
            "        return self._compiled[sig]\n"
            "    def infer(self, sig, plain_fn, params, imgs):\n"
            "        fn = self._compile(sig)\n"
            "        fn = plain_fn\n"
            "        out = fn(params, imgs)\n"
            "        return out, imgs.mean()\n"
        )
        assert by_checker(lint(tmp_path, src), "donation-safety") == []

    def test_memoized_handle_non_donated_position_clean(self, tmp_path):
        src = (
            "import jax\n"
            "class Engine:\n"
            "    def _compile(self, sig):\n"
            "        self._compiled[sig] = jax.jit(\n"
            "            lambda p, x: x, donate_argnums=(1,)\n"
            "        )\n"
            "    def infer(self, sig, params, imgs):\n"
            "        out = self._compiled[sig](params, imgs)\n"
            "        return out, params\n"
        )
        assert by_checker(lint(tmp_path, src), "donation-safety") == []

    def test_memoized_handle_fixture_pair(self):
        """The seeded acceptance pair (tests/fixtures/donation_memo.py):
        both leaky dispatch shapes flagged, the host-copy twin clean."""
        from glom_tpu.analysis import run

        fs = by_checker(
            run([str(FIXTURES / "donation_memo.py")]), "donation-safety"
        )
        symbols = {f.symbol for f in fs}
        assert symbols == {
            "LeakyMemoEngine.infer",
            "LeakyMemoEngine.infer_direct",
        }, fs
        assert all("Safe" not in f.symbol for f in fs)

    def test_alias_unpinned_dispatch_flagged(self, tmp_path):
        """A bare pool.buffer() flowing into a donating dispatch is an
        alias-unpinned-dispatch finding (ISSUE 16) — the pool's donated
        write-back can invalidate the buffer mid-dispatch."""
        src = (
            "import jax\n"
            "class Engine:\n"
            "    def _compile(self, sig):\n"
            "        self._compiled[sig] = jax.jit(\n"
            "            lambda p, b: b, donate_argnums=(0,)\n"
            "        )\n"
            "        return self._compiled[sig]\n"
            "    def infer(self, sig, params):\n"
            "        fn = self._compile(sig)\n"
            "        buf = self.pool.buffer()\n"
            "        return fn(params, buf)\n"
        )
        fs = by_checker(lint(tmp_path, src), "donation-safety")
        assert len(fs) == 1
        assert fs[0].key == "alias-unpinned-dispatch"
        assert "acquire_read" in fs[0].message

    def test_alias_pinned_rebind_clean(self, tmp_path):
        """Rebinding the name through acquire_read() before the dispatch
        clears the hazard — the latest binding decides."""
        src = (
            "import jax\n"
            "class Engine:\n"
            "    def _compile(self, sig):\n"
            "        self._compiled[sig] = jax.jit(\n"
            "            lambda p, b: b, donate_argnums=(0,)\n"
            "        )\n"
            "        return self._compiled[sig]\n"
            "    def infer(self, sig, params):\n"
            "        fn = self._compile(sig)\n"
            "        buf = self.pool.buffer()\n"
            "        buf = self.pool.acquire_read()\n"
            "        try:\n"
            "            return fn(params, buf)\n"
            "        finally:\n"
            "            self.pool.release_read()\n"
        )
        assert by_checker(lint(tmp_path, src), "donation-safety") == []

    def test_alias_compile_time_probe_clean(self, tmp_path):
        """A bare buffer() read that never reaches a dispatch (the
        engine's compile-time dtype probe) stays clean."""
        src = (
            "import jax\n"
            "class Engine:\n"
            "    def _compile(self, sig):\n"
            "        dt = self.pool.buffer().dtype\n"
            "        self._compiled[sig] = jax.jit(\n"
            "            lambda p, b: b, donate_argnums=(0,)\n"
            "        )\n"
            "        return self._compiled[sig]\n"
        )
        assert by_checker(lint(tmp_path, src), "donation-safety") == []

    def test_alias_fixture_pair(self):
        """The seeded acceptance pair (tests/fixtures/alias_pool.py):
        both unpinned dispatch shapes flagged, the pinned twin and its
        compile-time probe clean."""
        from glom_tpu.analysis import run

        fs = by_checker(
            run([str(FIXTURES / "alias_pool.py")]), "donation-safety"
        )
        alias = [f for f in fs if f.key == "alias-unpinned-dispatch"]
        symbols = {f.symbol for f in alias}
        assert symbols == {
            "LeakyPoolEngine.infer",
            "LeakyPoolEngine.infer_inline",
        }, fs
        assert all("Safe" not in f.symbol for f in fs)


# ---------------------------------------------------------------------------
# schema-emit
# ---------------------------------------------------------------------------


class TestSchemaEmit:
    def test_unknown_kind_flagged(self, tmp_path):
        src = (
            "from glom_tpu.telemetry.sinks import emit\n"
            "emit({'metric': 'x', 'value': 1.0, 'unit': 'u'}, kind='benhc')\n"
        )
        fs = by_checker(lint(tmp_path, src), "schema-emit")
        assert len(fs) == 1 and "benhc" in fs[0].message

    def test_registered_kind_clean(self, tmp_path):
        src = (
            "from glom_tpu.telemetry.sinks import emit\n"
            "emit({'metric': 'x', 'value': 1.0, 'unit': 'u'}, kind='bench')\n"
            "emit({'event': 'dispatch', 'trace_ids': ids}, kind='serve')\n"
        )
        assert by_checker(lint(tmp_path, src), "schema-emit") == []

    def test_request_scoped_event_without_trace_context_flagged(
        self, tmp_path
    ):
        src = (
            "from glom_tpu.serve.events import emit_serve\n"
            "emit_serve(w, {'event': 'dispatch', 'engine': 'e0'})\n"
        )
        fs = by_checker(lint(tmp_path, src), "schema-emit")
        assert len(fs) == 1 and fs[0].key == "trace-context"
        assert "trace_id" in fs[0].message

    def test_trace_context_rule_accepts_null_and_splat(self, tmp_path):
        src = (
            "from glom_tpu.serve.events import emit_serve\n"
            "emit_serve(w, {'event': 'resolve', 'trace_id': None,\n"
            "               'slo_class': None})\n"
            "emit_serve(w, {'event': 'shed', **fields})\n"
            "emit_serve(w, {'event': 'warmup', 'bucket': 4})\n"
        )
        assert by_checker(lint(tmp_path, src), "schema-emit") == []

    def test_trace_context_rule_skips_non_serve_kinds(self, tmp_path):
        # A "fault" record whose site context happens to name an event
        # from the serve vocabulary is out of scope for the rule.
        src = (
            "from glom_tpu.telemetry.sinks import emit\n"
            "emit({'fault': 'x', 'event': 'dispatch'}, kind='fault')\n"
        )
        assert by_checker(lint(tmp_path, src), "schema-emit") == []

    def test_trace_emit_fixture_pair(self):
        """The seeded acceptance pair (tests/fixtures/trace_emit.py): the
        context-less dispatch emit flagged, the four good shapes clean."""
        from glom_tpu.analysis import run

        fs = by_checker(
            run([str(FIXTURES / "trace_emit.py")]), "schema-emit"
        )
        assert len(fs) == 1, fs
        assert fs[0].key == "trace-context"
        assert fs[0].symbol == "bad_dispatch_emit"
        src_lines = (FIXTURES / "trace_emit.py").read_text().splitlines()
        assert "dispatch" in src_lines[fs[0].line - 1]

    def test_tenant_scoped_event_without_class_flagged(self, tmp_path):
        src = (
            "from glom_tpu.serve.events import emit_serve\n"
            "emit_serve(w, {'event': 'admit', 'request_id': rid})\n"
        )
        fs = by_checker(lint(tmp_path, src), "schema-emit")
        assert len(fs) == 1 and fs[0].key == "class-context"
        assert "slo_class" in fs[0].message

    def test_class_context_rule_accepts_null_and_splat(self, tmp_path):
        src = (
            "from glom_tpu.serve.events import emit_serve\n"
            "emit_serve(w, {'event': 'admit', 'slo_class': None})\n"
            "emit_serve(w, {'event': 'settle', **fields})\n"
            "emit_serve(w, {'event': 'ladder', 'rung': 'shed'})\n"
        )
        assert by_checker(lint(tmp_path, src), "schema-emit") == []

    def test_class_emit_fixture_pair(self):
        """The seeded acceptance pair (tests/fixtures/class_emit.py): the
        class-less admit emit flagged, the three good shapes clean."""
        from glom_tpu.analysis import run

        fs = by_checker(
            run([str(FIXTURES / "class_emit.py")]), "schema-emit"
        )
        assert len(fs) == 1, fs
        assert fs[0].key == "class-context"
        assert fs[0].symbol == "bad_admit_emit"
        src_lines = (FIXTURES / "class_emit.py").read_text().splitlines()
        assert "admit" in src_lines[fs[0].line - 1]

    def test_dead_zero_unmeasured_flagged(self, tmp_path):
        src = (
            "from glom_tpu.telemetry.sinks import emit\n"
            "emit({'metric': 'x', 'value': 0.0, 'unit': 'u',\n"
            "      'error': 'backend-down'}, kind='error')\n"
        )
        fs = by_checker(lint(tmp_path, src), "schema-emit")
        assert len(fs) == 1 and "must be None" in fs[0].message

    def test_null_unmeasured_clean(self, tmp_path):
        src = (
            "from glom_tpu.telemetry.sinks import emit\n"
            "emit({'metric': 'x', 'value': None, 'unit': 'u',\n"
            "      'error': 'backend-down'}, kind='error')\n"
        )
        assert by_checker(lint(tmp_path, src), "schema-emit") == []

    def test_error_kind_requires_error_field(self, tmp_path):
        src = (
            "from glom_tpu.telemetry import schema\n"
            "rec = schema.stamp({'metric': 'x', 'value': None}, kind='error')\n"
        )
        fs = by_checker(lint(tmp_path, src), "schema-emit")
        assert len(fs) == 1 and "no 'error' field" in fs[0].message

    def test_writer_write_with_inline_kind(self, tmp_path):
        src = "writer.write({'kind': 'not_a_kind', 'note': 'x'})\n"
        fs = by_checker(lint(tmp_path, src), "schema-emit")
        assert len(fs) == 1 and "not_a_kind" in fs[0].message


# ---------------------------------------------------------------------------
# lockset
# ---------------------------------------------------------------------------

RACY = '''
import threading

class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
        self._thread = threading.Thread(target=self._run)

    def _run(self):
        with self._lock:
            self.count += 1

    def read(self):
        return self.count
'''

CLEAN = '''
import threading

class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
        self._thread = threading.Thread(target=self._run)

    def _run(self):
        with self._lock:
            self.count += 1

    def read(self):
        with self._lock:
            return self.count
'''


class TestLockset:
    def test_unguarded_read_flagged(self, tmp_path):
        fs = by_checker(lint(tmp_path, RACY), "lockset")
        assert len(fs) == 1 and fs[0].line == 15
        assert "count" in fs[0].message and "read" in fs[0].message

    def test_guarded_everywhere_clean(self, tmp_path):
        assert by_checker(lint(tmp_path, CLEAN), "lockset") == []

    def test_unlocked_shared_write_from_thread(self, tmp_path):
        src = (
            "import threading\n"
            "class W:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self.log = []\n"
            "        self._t = threading.Thread(target=self._run)\n"
            "    def _run(self):\n"
            "        self.log.append(1)\n"
            "    def snapshot(self):\n"
            "        return list(self.log)\n"
        )
        fs = by_checker(lint(tmp_path, src), "lockset")
        assert len(fs) == 1 and "unsynchronized" in fs[0].message

    def test_held_context_inherits_transitively(self, tmp_path):
        """A private method called only from a held method (which is
        itself only called from lexically-held sites) inherits heldness
        through the fixpoint — the watchdog's _record_transition ->
        _write_event chain must not false-positive."""
        src = (
            "import threading\n"
            "class W:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self.count = 0\n"
            "        self._t = threading.Thread(target=self._run)\n"
            "    def _run(self):\n"
            "        with self._lock:\n"
            "            self._bump()\n"
            "    def _bump(self):\n"
            "        self._write()\n"
            "    def _write(self):\n"
            "        self.count += 1\n"
            "    def read(self):\n"
            "        with self._lock:\n"
            "            return self.count\n"
        )
        assert by_checker(lint(tmp_path, src), "lockset") == []

    def test_mutator_call_is_one_finding_not_two(self, tmp_path):
        """self.buf.clear() is ONE access (a write): the walk must not
        also count the inner self.buf read, or the baseline needs
        count=2 for one site."""
        src = (
            "import threading\n"
            "class W:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self.buf = []\n"
            "        self._t = threading.Thread(target=self._run)\n"
            "    def _run(self):\n"
            "        with self._lock:\n"
            "            self.buf.append(1)\n"
            "    def reset(self):\n"
            "        self.buf.clear()\n"
        )
        fs = by_checker(lint(tmp_path, src), "lockset")
        assert len(fs) == 1 and fs[0].line == 11

    def test_config_and_queue_attrs_exempt(self, tmp_path):
        src = (
            "import queue\n"
            "import threading\n"
            "class W:\n"
            "    def __init__(self, depth):\n"
            "        self._lock = threading.Lock()\n"
            "        self.depth = depth\n"
            "        self._q = queue.Queue(maxsize=depth)\n"
            "        self._t = threading.Thread(target=self._run)\n"
            "    def _run(self):\n"
            "        self._q.put(self.depth)\n"
            "    def submit(self):\n"
            "        self._q.put(self.depth)\n"
        )
        assert by_checker(lint(tmp_path, src), "lockset") == []

    def test_regression_fixture_racy_flagged_locked_clean(self):
        """THE acceptance pair: the deliberately-unlocked DynamicBatcher
        queue mutation in the checked-in fixture is flagged at its line;
        the locked twin in the same file is not."""
        findings = by_checker(
            run([str(FIXTURES / "racy_batcher.py")]), "lockset"
        )
        assert findings, "lockset checker missed the seeded race"
        assert all("RacyBatcher" in f.message for f in findings)
        src_lines = (FIXTURES / "racy_batcher.py").read_text().splitlines()
        for f in findings:
            assert "LockedBatcher" not in f.message
        # the finding anchors the unlocked append itself
        assert any(
            "pending.append" in src_lines[f.line - 1] for f in findings
        )


# ---------------------------------------------------------------------------
# framework: pragmas, baseline, CLI, self-hosting
# ---------------------------------------------------------------------------


class TestFramework:
    def test_pragma_suppresses_same_line(self, tmp_path):
        src = (
            "from jax import lax\n"
            "def f(x):\n"
            "    return lax.psum(x, 'bogus')  "
            "# glom-lint: ok[collective-coverage] seeded test axis\n"
        )
        assert by_checker(lint(tmp_path, src), "collective-coverage") == []

    def test_pragma_on_own_line_suppresses_next(self, tmp_path):
        src = (
            "from jax import lax\n"
            "def f(x):\n"
            "    # glom-lint: ok[collective-coverage] seeded test axis\n"
            "    return lax.psum(x, 'bogus')\n"
        )
        assert by_checker(lint(tmp_path, src), "collective-coverage") == []

    def test_pragma_without_reason_is_a_finding(self, tmp_path):
        src = (
            "from jax import lax\n"
            "def f(x):\n"
            "    return lax.psum(x, 'bogus')  "
            "# glom-lint: ok[collective-coverage]\n"
        )
        fs = lint(tmp_path, src)
        assert by_checker(fs, "collective-coverage") == []
        assert len(by_checker(fs, "pragma")) == 1

    def test_pragma_wrong_checker_does_not_suppress(self, tmp_path):
        src = (
            "from jax import lax\n"
            "def f(x):\n"
            "    return lax.psum(x, 'bogus')  "
            "# glom-lint: ok[lockset] wrong checker\n"
        )
        assert len(by_checker(lint(tmp_path, src), "collective-coverage")) == 1

    def test_pragma_in_docstring_is_not_a_suppression(self, tmp_path):
        """The framework documents its own syntax in docstrings; those
        examples must neither suppress nor warn as unused."""
        src = (
            '"""Docs: write  # glom-lint: ok[lockset] reason  inline."""\n'
            "x = 1\n"
        )
        path = tmp_path / "m.py"
        path.write_text(src)
        warnings = []
        assert run([str(path)], warnings=warnings) == []
        assert warnings == []

    def test_unused_pragma_warns(self, tmp_path):
        src = (
            "def f(x):\n"
            "    return x  # glom-lint: ok[lockset] nothing fires here\n"
        )
        path = tmp_path / "m.py"
        path.write_text(src)
        warnings = []
        assert run([str(path)], warnings=warnings) == []
        assert len(warnings) == 1 and "unused pragma" in warnings[0]
        # a USED pragma does not warn
        used = (
            "from jax import lax\n"
            "def f(x):\n"
            "    return lax.psum(x, 'bogus')  "
            "# glom-lint: ok[collective-coverage] seeded\n"
        )
        path.write_text(used)
        warnings = []
        assert run([str(path)], warnings=warnings) == []
        assert warnings == []
        # a partial --select cannot judge unusedness: no warning
        path.write_text(src)
        warnings = []
        run([str(path)], select=["schema-emit"], warnings=warnings)
        assert warnings == []

    def test_select_unknown_checker_raises(self, tmp_path):
        with pytest.raises(ValueError, match="unknown checkers"):
            lint(tmp_path, "x = 1\n", select=["nope"])

    def test_parse_error_is_a_finding(self, tmp_path):
        fs = lint(tmp_path, "def broken(:\n")
        assert len(by_checker(fs, "parse")) == 1

    def test_baseline_roundtrip_and_ratchet(self, tmp_path, capsys):
        bad = tmp_path / "mod.py"
        bad.write_text(
            "from jax import lax\n"
            "def f(x):\n"
            "    return lax.psum(x, 'bogus')\n"
        )
        b = tmp_path / "baseline.json"
        # 1. unbaselined run fails
        assert main([str(bad), "--no-baseline"]) == 1
        # 2. write + annotate the baseline
        assert main([str(bad), "--write-baseline", str(b)]) == 0
        data = json.loads(b.read_text())
        assert len(data["suppressions"]) == 1
        # 3. unreviewed entries refuse to gate
        assert main([str(bad), "--baseline", str(b)]) == 1
        for entry in data["suppressions"].values():
            entry["reviewed"] = "seeded test suppression"
        b.write_text(json.dumps(data))
        # 4. reviewed baseline gates green
        assert main([str(bad), "--baseline", str(b)]) == 0
        # 5. a NEW finding beyond the baselined count fails
        bad.write_text(
            bad.read_text()
            + "def g(x):\n    return lax.pmean(x, 'bogus2')\n"
        )
        assert main([str(bad), "--baseline", str(b)]) == 1
        # 6. fixing everything leaves the stale entry as a warning only
        bad.write_text("def f(x):\n    return x\n")
        assert main([str(bad), "--baseline", str(b)]) == 0
        assert "stale baseline entry" in capsys.readouterr().out

    def test_baseline_fingerprints_are_line_free(self, tmp_path):
        src = (
            "from jax import lax\n"
            "def f(x):\n"
            "    return lax.psum(x, 'bogus')\n"
        )
        shifted = "# a comment pushing everything down\n\n\n" + src
        fp1 = [f.fingerprint for f in lint(tmp_path, src, name="a/m.py")]
        fp2 = [f.fingerprint for f in lint(tmp_path, shifted, name="a/m.py")]
        assert fp1 == fp2

    def test_list_checkers(self, capsys):
        assert main(["--list-checkers"]) == 0
        out = capsys.readouterr().out
        for name in (
            "collective-coverage", "trace-purity", "donation-safety",
            "schema-emit", "lockset",
        ):
            assert name in out

    def test_self_host_repo_is_clean_with_baseline(self, monkeypatch):
        """The acceptance gate: the merged tree + the checked-in reviewed
        baseline (<= 10 suppressions) lints clean."""
        monkeypatch.chdir(REPO)
        findings = run(["glom_tpu"])
        data = baseline_mod.load(str(REPO / "analysis_baseline.json"))
        assert len(data["suppressions"]) <= 10
        assert baseline_mod.unreviewed(data) == []
        new, _stale = baseline_mod.apply(findings, data)
        assert new == [], "\n".join(f.render() for f in new)


class TestLockOrder:
    def test_fixture_pair_flags_only_the_deadlocky_class(self):
        """The seeded acceptance pair (tests/fixtures/lock_order.py):
        DeadlockyCoordinator's AB/BA cycle is flagged at file:line on
        BOTH edges (including the one formed transitively through
        _tally), OrderedCoordinator scans clean."""
        fs = by_checker(
            run([str(FIXTURES / "lock_order.py")]), "lock-order"
        )
        assert len(fs) == 2
        assert all("DeadlockyCoordinator" in f.symbol for f in fs)
        keys = {f.key for f in fs}
        assert keys == {
            "lock-order-_ledger_lock-_stats_lock",
            "lock-order-_stats_lock-_ledger_lock",
        }
        assert all(f.line > 0 for f in fs)

    def test_nested_two_locks_one_order_clean(self, tmp_path):
        src = (
            "import threading\n"
            "class W:\n"
            "    def __init__(self):\n"
            "        self._a = threading.Lock()\n"
            "        self._b = threading.Lock()\n"
            "        self.x = 0\n"
            "    def one(self):\n"
            "        with self._a:\n"
            "            with self._b:\n"
            "                self.x += 1\n"
            "    def two(self):\n"
            "        with self._a:\n"
            "            with self._b:\n"
            "                return self.x\n"
        )
        assert by_checker(lint(tmp_path, src), "lock-order") == []

    def test_reverse_nesting_flagged(self, tmp_path):
        src = (
            "import threading\n"
            "class W:\n"
            "    def __init__(self):\n"
            "        self._a = threading.Lock()\n"
            "        self._b = threading.Lock()\n"
            "        self.x = 0\n"
            "    def one(self):\n"
            "        with self._a:\n"
            "            with self._b:\n"
            "                self.x += 1\n"
            "    def two(self):\n"
            "        with self._b:\n"
            "            with self._a:\n"
            "                return self.x\n"
        )
        fs = by_checker(lint(tmp_path, src), "lock-order")
        assert len(fs) == 2
        assert {f.line for f in fs} == {9, 13}

    def test_sequential_acquisition_is_not_an_order(self, tmp_path):
        """Taking A, releasing it, then taking B imposes no order — only
        NESTED holds build edges."""
        src = (
            "import threading\n"
            "class W:\n"
            "    def __init__(self):\n"
            "        self._a = threading.Lock()\n"
            "        self._b = threading.Lock()\n"
            "        self.x = 0\n"
            "    def one(self):\n"
            "        with self._a:\n"
            "            self.x += 1\n"
            "        with self._b:\n"
            "            self.x += 1\n"
            "    def two(self):\n"
            "        with self._b:\n"
            "            self.x += 1\n"
            "        with self._a:\n"
            "            return self.x\n"
        )
        assert by_checker(lint(tmp_path, src), "lock-order") == []

    def test_transitive_cycle_through_call_flagged(self, tmp_path):
        src = (
            "import threading\n"
            "class W:\n"
            "    def __init__(self):\n"
            "        self._a = threading.Lock()\n"
            "        self._b = threading.Lock()\n"
            "        self.x = 0\n"
            "    def _take_b(self):\n"
            "        with self._b:\n"
            "            self.x += 1\n"
            "    def one(self):\n"
            "        with self._a:\n"
            "            self._take_b()\n"
            "    def two(self):\n"
            "        with self._b:\n"
            "            with self._a:\n"
            "                return self.x\n"
        )
        fs = by_checker(lint(tmp_path, src), "lock-order")
        assert len(fs) == 2

    def test_single_lock_class_has_no_order_contract(self, tmp_path):
        src = (
            "import threading\n"
            "class W:\n"
            "    def __init__(self):\n"
            "        self._a = threading.Lock()\n"
            "        self.x = 0\n"
            "    def one(self):\n"
            "        with self._a:\n"
            "            with self._a:\n"
            "                self.x += 1\n"
        )
        assert by_checker(lint(tmp_path, src), "lock-order") == []

    def test_shipped_batcher_two_lock_pattern_is_acyclic(self):
        """The multi-engine DynamicBatcher's documented order
        (_engine_lock -> _counter_lock) scans clean — the target this
        checker ships alongside."""
        import glom_tpu.serve.batcher as batcher_mod

        fs = by_checker(run([batcher_mod.__file__]), "lock-order")
        assert fs == []


# ---------------------------------------------------------------------------
# signal-safety
# ---------------------------------------------------------------------------


class TestSignalSafety:
    """Code reachable from a signal.signal-registered handler must not
    acquire non-reentrant Locks or call the blocking-IO denylist — the
    PR 6 'sharing the loop's manager deadlocks' lesson, made static."""

    def test_fixture_pair_flags_only_the_deadlocky_class(self):
        fs = by_checker(
            run([str(FIXTURES / "signal_fixture.py")]), "signal-safety"
        )
        assert fs and all("Deadlocky" in f.symbol for f in fs), fs
        keys = {f.key for f in fs}
        assert keys == {
            "handler-lock-self._lock",
            "handler-join-unbounded",
            "handler-blocking-time.sleep",
            "handler-blocking-queue-get",
        }, keys
        assert all(f.line > 0 for f in fs)

    def test_nested_handler_lock_flagged(self, tmp_path):
        """The flight.py registration shape: a NESTED def handed to
        signal.signal, reaching a module-level helper that takes a plain
        Lock."""
        src = (
            "import signal\n"
            "import threading\n"
            "LOCK = threading.Lock()\n"
            "def flush():\n"
            "    with LOCK:\n"
            "        pass\n"
            "def install():\n"
            "    def _handler(signum, frame):\n"
            "        flush()\n"
            "    signal.signal(signal.SIGTERM, _handler)\n"
        )
        fs = by_checker(lint(tmp_path, src), "signal-safety")
        assert len(fs) == 1 and fs[0].line == 5
        assert "LOCK" in fs[0].message

    def test_rlock_and_bounded_join_exempt(self, tmp_path):
        """The shipped mitigations are NOT findings: RLock reacquisition
        succeeds for the paused owner, and a bounded join is the
        grace-window form."""
        src = (
            "import signal\n"
            "import threading\n"
            "LOCK = threading.RLock()\n"
            "def handler(signum, frame):\n"
            "    with LOCK:\n"
            "        w = threading.Thread(target=print)\n"
            "        w.start()\n"
            "        w.join(timeout=5.0)\n"
            "signal.signal(signal.SIGTERM, handler)\n"
        )
        assert by_checker(lint(tmp_path, src), "signal-safety") == []

    def test_unregistered_code_never_flagged(self, tmp_path):
        """The same hazardous shapes OUTSIDE a handler path are some
        other checker's business (lockset), not this one's."""
        src = (
            "import threading\n"
            "LOCK = threading.Lock()\n"
            "def flush():\n"
            "    with LOCK:\n"
            "        pass\n"
        )
        assert by_checker(lint(tmp_path, src), "signal-safety") == []

    def test_thread_target_is_not_handler_context(self, tmp_path):
        """Work moved to a spawned thread is the sanctioned escape hatch
        (the PR 6 fix): the target's body is not handler-reachable."""
        src = (
            "import signal\n"
            "import threading\n"
            "LOCK = threading.Lock()\n"
            "def worker():\n"
            "    with LOCK:\n"
            "        pass\n"
            "def handler(signum, frame):\n"
            "    t = threading.Thread(target=worker)\n"
            "    t.start()\n"
            "    t.join(timeout=3.0)\n"
            "signal.signal(signal.SIGTERM, handler)\n"
        )
        assert by_checker(lint(tmp_path, src), "signal-safety") == []

    def test_shipped_flight_recorder_handler_path_is_clean(self):
        """The self-host acceptance the satellite names: flight.py's
        SIGTERM path (RLock ring + bounded daemon-thread join) and the
        new pod coordinator's handler-side save both scan clean."""
        import glom_tpu.resilience.coordinator as coord_mod
        import glom_tpu.tracing.flight as flight_mod

        fs = by_checker(
            run([flight_mod.__file__, coord_mod.__file__]), "signal-safety"
        )
        assert fs == [], fs


class TestAxisEnvironment:
    def test_seeded_fixture_pair(self):
        """The seeded acceptance pair (tests/fixtures/axis_env.py): the
        leaky body's psum over MODEL_AXIS — vocabulary-legal but absent
        from ITS shard_map's ('data','seq') MeshConfig — is flagged both
        at the direct lax.psum site and through the _psum_wire threaded
        axis; the clean twin (every collective on a declared axis) scans
        clean."""
        fs = by_checker(
            run([str(FIXTURES / "axis_env.py")]), "axis-environment"
        )
        assert len(fs) == 2, fs
        assert all("'model'" in f.message for f in fs)
        src_lines = (FIXTURES / "axis_env.py").read_text().splitlines()
        for f in fs:
            assert "leaky" in f.symbol or "MODEL" in src_lines[f.line - 1]

    def test_mesh_attested_env_flags_foreign_axis(self, tmp_path):
        src = (
            "from jax import lax\n"
            "from glom_tpu.utils.config import MeshConfig\n"
            "from glom_tpu.utils.compat import shard_map\n"
            "DATA_AXIS = 'data'\n"
            "MODEL_AXIS = 'model'\n"
            "def build(make_mesh, P):\n"
            "    mesh = make_mesh(MeshConfig(data=8))\n"
            "    def body(x):\n"
            "        return lax.psum(x, MODEL_AXIS)\n"
            "    return shard_map(body, mesh=mesh,\n"
            "                     in_specs=(P(DATA_AXIS),), out_specs=P())\n"
        )
        fs = by_checker(lint(tmp_path, src), "axis-environment")
        assert len(fs) == 1
        assert "'model'" in fs[0].message

    def test_opaque_param_caller_attestation_pair(self):
        """The seeded pair for the opaque-mesh blind spot
        (tests/fixtures/axis_env_param.py): the module ALSO builds a
        'model'-carrying training mesh, so the module-wide union would
        attest the wrong environment — the checker must follow the
        intra-module CALLER's MeshConfig(data, seq) instead and flag
        the psum over 'model' (direct site + threaded wrapper), plus
        the hop-forwarded leaky body whose MeshConfig is one more
        caller up. The clean twins and the caller-less opaque helper
        (module-union fallback) scan clean."""
        fs = by_checker(
            run([str(FIXTURES / "axis_env_param.py")]), "axis-environment"
        )
        assert len(fs) == 3, fs
        assert all("'model'" in f.message for f in fs)
        assert sum("_serve_shard_leaky" in f.symbol for f in fs) == 2
        assert sum("_hop_leaky" in f.symbol for f in fs) == 1

    def test_caller_attestation_beats_module_union(self, tmp_path):
        """A file that builds BOTH a (data, seq) serve mesh (passed to
        the opaque-param helper) and a model-carrying training mesh:
        the union alone would hide the bug."""
        src = (
            "from jax import lax\n"
            "from glom_tpu.utils.config import MeshConfig\n"
            "from glom_tpu.utils.compat import shard_map\n"
            "DATA_AXIS = 'data'\n"
            "MODEL_AXIS = 'model'\n"
            "def train_mesh(make_mesh):\n"
            "    return make_mesh(MeshConfig(data=2, model=2))\n"
            "def helper(mesh, P):\n"
            "    def body(x):\n"
            "        return lax.psum(x, MODEL_AXIS)\n"
            "    return shard_map(body, mesh=mesh,\n"
            "                     in_specs=(P(DATA_AXIS),), out_specs=P())\n"
            "def build(make_mesh, P):\n"
            "    mesh = make_mesh(MeshConfig(data=8))\n"
            "    return helper(mesh, P)\n"
        )
        fs = by_checker(lint(tmp_path, src), "axis-environment")
        assert len(fs) == 1
        assert "'model'" in fs[0].message

    def test_one_unattested_caller_poisons_attestation(self, tmp_path):
        """Two callers, one of which binds the mesh param opaquely: the
        checker must not guess — it falls back to the module union
        (which carries 'model' here), so nothing flags."""
        src = (
            "from jax import lax\n"
            "from glom_tpu.utils.config import MeshConfig\n"
            "from glom_tpu.utils.compat import shard_map\n"
            "DATA_AXIS = 'data'\n"
            "MODEL_AXIS = 'model'\n"
            "def train_mesh(make_mesh):\n"
            "    return make_mesh(MeshConfig(data=2, model=2))\n"
            "def helper(mesh, P):\n"
            "    def body(x):\n"
            "        return lax.psum(x, MODEL_AXIS)\n"
            "    return shard_map(body, mesh=mesh,\n"
            "                     in_specs=(P(DATA_AXIS),), out_specs=P())\n"
            "def build(make_mesh, P):\n"
            "    mesh = make_mesh(MeshConfig(data=8))\n"
            "    return helper(mesh, P)\n"
            "def build_opaque(mesh, P):\n"
            "    return helper(mesh, P)\n"
        )
        assert by_checker(lint(tmp_path, src), "axis-environment") == []

    def test_opaque_mesh_skips(self, tmp_path):
        """No MeshConfig anywhere (the training shard bodies' shape:
        mesh arrives from config) -> the environment is unattested and
        the checker never guesses."""
        src = (
            "from jax import lax\n"
            "from glom_tpu.utils.compat import shard_map\n"
            "DATA_AXIS = 'data'\n"
            "MODEL_AXIS = 'model'\n"
            "def build(mesh, P):\n"
            "    def body(x):\n"
            "        return lax.psum(x, MODEL_AXIS)\n"
            "    return shard_map(body, mesh=mesh,\n"
            "                     in_specs=(P(DATA_AXIS),), out_specs=P())\n"
        )
        assert by_checker(lint(tmp_path, src), "axis-environment") == []

    def test_module_wide_meshconfig_attests(self, tmp_path):
        """A module that builds meshes SOMEWHERE attests its axis set
        even when a given site's mesh is a parameter — the serve-mesh
        shape (make_serve_mesh builds (data, seq); every shard_map in
        the file inherits that environment)."""
        src = (
            "from jax import lax\n"
            "from glom_tpu.utils.config import MeshConfig\n"
            "from glom_tpu.utils.compat import shard_map\n"
            "DATA_AXIS = 'data'\n"
            "SEQ_AXIS = 'seq'\n"
            "MODEL_AXIS = 'model'\n"
            "def make_my_mesh(make_mesh, scfg):\n"
            "    return make_mesh(MeshConfig(data=scfg.d, seq=scfg.s))\n"
            "def build(mesh, P):\n"
            "    def body(x):\n"
            "        y = lax.psum(x, SEQ_AXIS)\n"
            "        return lax.psum(y, MODEL_AXIS)\n"
            "    return shard_map(body, mesh=mesh,\n"
            "                     in_specs=(P(DATA_AXIS),), out_specs=P())\n"
        )
        fs = by_checker(lint(tmp_path, src), "axis-environment")
        assert len(fs) == 1
        assert "'model'" in fs[0].message

    def test_spec_axes_union_into_env(self, tmp_path):
        """An axis visible only in the specs (via a local spec variable,
        one level of indirection) is part of the environment — spec axes
        never false-positive even when the MeshConfig kwargs are
        narrower than the specs."""
        src = (
            "from jax import lax\n"
            "from glom_tpu.utils.config import MeshConfig\n"
            "from glom_tpu.utils.compat import shard_map\n"
            "DATA_AXIS = 'data'\n"
            "SEQ_AXIS = 'seq'\n"
            "def build(make_mesh, P):\n"
            "    mesh = make_mesh(MeshConfig(data=4))\n"
            "    lv_spec = P(DATA_AXIS, SEQ_AXIS)\n"
            "    def body(x):\n"
            "        return lax.psum(x, SEQ_AXIS)\n"
            "    return shard_map(body, mesh=mesh,\n"
            "                     in_specs=(lv_spec,), out_specs=lv_spec)\n"
        )
        assert by_checker(lint(tmp_path, src), "axis-environment") == []

    def test_serve_mesh_paged_gather_is_clean(self):
        """The site the ISSUE names: parallel/serve_mesh.py's paged
        gather collectives (all_gather over 'data', witness psums over
        'seq'/'data') all live inside the (data, seq) environment."""
        import glom_tpu.parallel.serve_mesh as sm

        assert by_checker(run([sm.__file__]), "axis-environment") == []


class TestHandRolledCollectiveTiming:
    """ISSUE 13: a registered site that hand-rolls its own clock/callback
    harness around a wire-moving collective must route through the ONE
    shared timing wrapper (counters.timed_collective)."""

    def test_fixture_pair(self, tmp_path):
        """The seeded acceptance pair (tests/fixtures/collective_timing
        .py), linted under a registration-scope path: the leaky twin's
        psum is flagged hand-rolled-timing, the wrapper-routed twin is
        clean."""
        src = (FIXTURES / "collective_timing.py").read_text()
        fs = by_checker(
            lint(tmp_path, src, name="parallel/manual.py"),
            "collective-coverage",
        )
        timing = [f for f in fs if "hand-rolled" in f.message]
        assert len(timing) == 1
        src_lines = src.splitlines()
        assert "lax.psum(g, DATA_AXIS)" in src_lines[timing[0].line - 1]
        assert "leaky_timed_reduce" in timing[0].symbol
        assert "timed_collective" in timing[0].message
        # Neither twin trips the registration rule (record_collective and
        # timed_collective both register), and the clean twin trips
        # NOTHING.
        assert not any("not registered" in f.message for f in fs)
        assert not any("clean_timed_reduce" in (f.symbol or "")
                       for f in fs)

    def test_wrapper_lambda_counts_as_registered(self, tmp_path):
        """The wrapper takes the collective as a LAMBDA: the coverage
        rule must walk the enclosing-scope chain, not just the innermost
        scope, or every wrapper-routed site reads unregistered."""
        src = (
            "from jax import lax\n"
            "from glom_tpu.telemetry import counters as tele_counters\n"
            "DATA_AXIS = 'data'\n"
            "def grads(g):\n"
            "    return tele_counters.timed_collective(\n"
            "        's', DATA_AXIS, 'reduce', 8,\n"
            "        lambda x: lax.psum(x, DATA_AXIS), g,\n"
            "        collective='psum',\n"
            "    )\n"
        )
        assert (
            by_checker(
                lint(tmp_path, src, name="parallel/manual.py"),
                "collective-coverage",
            )
            == []
        )

    def test_timing_primitive_without_collective_is_fine(self, tmp_path):
        """A clock in a registration-scope module that never touches a
        collective (a host-side stats helper) is not this rule's
        business — trace-purity owns reachability from traced entries."""
        src = (
            "import time\n"
            "def stats():\n"
            "    return time.perf_counter()\n"
        )
        assert (
            by_checker(
                lint(tmp_path, src, name="parallel/manual.py"),
                "collective-coverage",
            )
            == []
        )

    def test_hand_rolled_clock_next_to_collective_flagged(self, tmp_path):
        src = (
            "import time\n"
            "from jax import lax\n"
            "from glom_tpu.telemetry import counters as tele_counters\n"
            "DATA_AXIS = 'data'\n"
            "def grads(g):\n"
            "    tele_counters.record_collective('reduce', 8)\n"
            "    t0 = time.perf_counter()\n"
            "    out = lax.psum(g, DATA_AXIS)\n"
            "    dt = time.perf_counter() - t0\n"
            "    return out, dt\n"
        )
        fs = by_checker(
            lint(tmp_path, src, name="parallel/manual.py"),
            "collective-coverage",
        )
        assert len(fs) == 1
        assert "hand-rolled" in fs[0].message and fs[0].line == 8


# ---------------------------------------------------------------------------
# whole-program pass: cross-module fixture pairs (ISSUE 20)
# ---------------------------------------------------------------------------


def run_pair(*names, select=None, scratch=None):
    """Lint a seeded cross-module fixture pair as one analyzed set."""
    return run(
        [str(FIXTURES / n) for n in names], select=select, scratch=scratch
    )


class TestCrossModulePairs:
    def test_purity_reaches_through_import(self):
        """tests/fixtures/xmod_purity.py: the jit entry lives in one
        module, the host print one import away — flagged AT the print's
        own file:line in the util module; the pure twin stays green."""
        fs = by_checker(
            run_pair("xmod_purity.py", "xmod_purity_util.py"),
            "trace-purity",
        )
        assert len(fs) == 1, fs
        assert fs[0].path.endswith("xmod_purity_util.py")
        assert fs[0].key == "host-print"
        src_lines = (
            (FIXTURES / "xmod_purity_util.py").read_text().splitlines()
        )
        assert "print(" in src_lines[fs[0].line - 1]

    def test_purity_pair_needs_both_files(self):
        """The same leaky module linted ALONE is silent — the evidence
        is unreachable without the companion, which is exactly the
        blind spot the project graph closes."""
        assert (
            by_checker(run_pair("xmod_purity_util.py"), "trace-purity")
            == []
        )

    def test_donation_handle_flows_through_typed_receiver(self):
        """tests/fixtures/xmod_donation.py: the donating handle lives on
        Engine in the companion module; the typed-receiver dispatches
        here must taint it — direct handle-attr load, provider-method
        return, and the *args splat (previously skipped silently)."""
        fs = by_checker(
            run_pair("xmod_donation.py", "xmod_donation_engine.py"),
            "donation-safety",
        )
        assert all(f.path.endswith("xmod_donation.py") for f in fs)
        by_key = sorted(f.key for f in fs)
        assert by_key == [
            "splat-at-donating-call",
            "use-after-donate-imgs",
            "use-after-donate-imgs",
        ], fs
        leaky = sorted(f.symbol for f in fs)
        assert leaky == ["provider_leaky", "serve_leaky", "splat_leaky"]

    def test_lock_order_cycle_across_classes_and_modules(self):
        """tests/fixtures/xmod_lock_order.py: each class is single-lock
        and locally consistent; the deadlock exists only in the global
        (class, lock) graph. Both halves of the cycle are flagged, each
        in its OWN module, and the recorded edges name both classes."""
        scratch = {}
        fs = by_checker(
            run_pair(
                "xmod_lock_order.py",
                "xmod_lock_order_pool.py",
                scratch=scratch,
            ),
            "lock-order",
        )
        assert len(fs) == 2, fs
        paths = sorted(f.path for f in fs)
        assert paths[0].endswith("xmod_lock_order.py")
        assert paths[1].endswith("xmod_lock_order_pool.py")
        edges = scratch["lock-order:edges"]
        assert ("Cache._lock", "Pool._lock") in edges
        assert ("Pool._lock", "Cache._lock") in edges
        # the clean twins contribute no edges
        assert not any("Quiet" in a or "Quiet" in b for a, b in edges)

    def test_mesh_flow_attested_through_import(self):
        """tests/fixtures/xmod_mesh_flow.py: the builder module owns no
        MeshConfig at all. The serve caller's (data, seq) ctor intent
        attests the leaky/clean sites through the import boundary; the
        annotated-MeshConfig train parameter attests the FULL axis
        tuple, so its 'model' psum is legal."""
        scratch = {}
        fs = by_checker(
            run_pair(
                "xmod_mesh_flow.py",
                "xmod_mesh_flow_runtime.py",
                scratch=scratch,
            ),
            "axis-environment",
        )
        assert len(fs) == 1, fs
        assert fs[0].path.endswith("xmod_mesh_flow.py")
        assert fs[0].key == "axis-env-model"
        assert fs[0].symbol.startswith("build_leaky")
        trail = {
            (row[0].rsplit("/", 1)[-1], row[2], row[3])
            for row in scratch["axis-environment:attested"]
        }
        assert ("xmod_mesh_flow.py", "flow", ("data", "seq")) in trail
        assert (
            "xmod_mesh_flow.py",
            "flow",
            ("data", "model", "seq"),
        ) in trail
        # single-module run: no caller evidence, every site skips
        solo = {}
        assert (
            by_checker(
                run_pair("xmod_mesh_flow.py", scratch=solo),
                "axis-environment",
            )
            == []
        )
        assert all(
            row[2] == "unattested"
            for row in solo["axis-environment:attested"]
        )

    def test_real_repo_project_evidence(self, monkeypatch):
        """Pins this PR's upgrades against the real tree: the attested
        cross-object lock edges include the serve cache->pool order, and
        the training shard_map sites in parallel/manual.py attest the
        full axis tuple through the runtime's MeshConfig — the sites
        that were skipped before the project graph existed."""
        monkeypatch.chdir(REPO)
        scratch = {}
        run(["glom_tpu"], scratch=scratch)
        edges = scratch["lock-order:edges"]
        assert ("ColumnCache._lock", "PagedColumnPool._lock") in edges
        path, line = edges[("ColumnCache._lock", "PagedColumnPool._lock")]
        assert path == "glom_tpu/serve/column_cache.py" and line > 0
        trail = scratch["axis-environment:attested"]
        manual = {
            row[1]: (row[2], row[3])
            for row in trail
            if row[0] == "glom_tpu/parallel/manual.py"
        }
        assert manual, trail
        assert all(
            how == "flow" and axes == ("data", "model", "seq")
            for how, axes in manual.values()
        ), manual


# ---------------------------------------------------------------------------
# analysis cache (--cache): fingerprint reuse + cross-module invalidation
# ---------------------------------------------------------------------------


class TestAnalysisCache:
    UTIL = "def helper(x):\n    print('x', x)\n    return x\n"
    APP = (
        "import jax\n"
        "from util import helper\n"
        "def step(x):\n"
        "    return helper(x)\n"
        "fast = jax.jit(step)\n"
    )
    LONE = "def f(x):\n    return x\n"

    def _tree(self, tmp_path):
        (tmp_path / "util.py").write_text(self.UTIL)
        (tmp_path / "app.py").write_text(self.APP)
        (tmp_path / "lone.py").write_text(self.LONE)
        return [str(tmp_path / n) for n in ("util.py", "app.py", "lone.py")]

    def _cached_run(self, tmp_path, paths):
        from glom_tpu.analysis.cache import AnalysisCache

        cache = AnalysisCache(str(tmp_path / "cache.json"))
        findings = run(paths, cache=cache)
        return cache, findings

    def test_warm_cache_replays_findings(self, tmp_path):
        paths = self._tree(tmp_path)
        cache, cold = self._cached_run(tmp_path, paths)
        assert cache.stats() == "cache: 0/3 files reused (cold)"
        # the cross-module purity finding is part of what gets stored
        assert [f.key for f in cold] == ["host-print"]
        cache, warm = self._cached_run(tmp_path, paths)
        assert cache.stats() == "cache: 3/3 files reused (warm)"
        assert [(f.fingerprint, f.line) for f in warm] == [
            (f.fingerprint, f.line) for f in cold
        ]

    def test_cross_module_invalidation_both_directions(self, tmp_path):
        """An import edge couples the PAIR: editing the callee must
        re-analyze its importers (their findings read its body), and
        editing the importer must re-analyze the callee (project-wide
        checkers place findings in the callee that the importer's entry
        points cause — the fixture's print is exactly that). The
        unrelated module stays reused either way."""
        paths = self._tree(tmp_path)
        self._cached_run(tmp_path, paths)
        (tmp_path / "util.py").write_text(self.UTIL.replace("'x'", "'y'"))
        cache, _ = self._cached_run(tmp_path, paths)
        assert cache.stats() == "cache: 1/3 files reused (mixed)"
        assert [Path(p).name for p in cache.reused_files] == ["lone.py"]
        self._cached_run(tmp_path, paths)  # re-warm
        (tmp_path / "app.py").write_text(
            self.APP + "def extra(y):\n    return y\n"
        )
        cache, findings = self._cached_run(tmp_path, paths)
        assert cache.stats() == "cache: 1/3 files reused (mixed)"
        assert [Path(p).name for p in cache.reused_files] == ["lone.py"]
        assert [f.key for f in findings] == ["host-print"]

    def test_corruption_falls_back_loudly(self, tmp_path, capsys):
        paths = self._tree(tmp_path)
        _, cold = self._cached_run(tmp_path, paths)
        (tmp_path / "cache.json").write_text("{ not json")
        cache, findings = self._cached_run(tmp_path, paths)
        err = capsys.readouterr().err
        assert "unreadable" in err and "FULL pass" in err
        assert cache.stats() == "cache: 0/3 files reused (cold)"
        assert [f.fingerprint for f in findings] == [
            f.fingerprint for f in cold
        ]
        # ... and the rewritten cache warms right back up
        cache, _ = self._cached_run(tmp_path, paths)
        assert cache.stats() == "cache: 3/3 files reused (warm)"

    def test_select_runs_never_cache(self, tmp_path):
        from glom_tpu.analysis.cache import AnalysisCache

        paths = self._tree(tmp_path)
        cache = AnalysisCache(str(tmp_path / "cache.json"))
        run(paths, cache=cache, select=["trace-purity"])
        assert "disabled" in cache.stats()
        assert not (tmp_path / "cache.json").exists()


# ---------------------------------------------------------------------------
# --prune-baseline
# ---------------------------------------------------------------------------


class TestPruneBaseline:
    def _seed(self, tmp_path):
        bad = tmp_path / "mod.py"
        bad.write_text(
            "from jax import lax\n"
            "def f(x):\n"
            "    return lax.psum(x, 'bogus')\n"
            "def g(x):\n"
            "    return lax.pmean(x, 'bogus2')\n"
        )
        b = tmp_path / "baseline.json"
        assert main([str(bad), "--write-baseline", str(b)]) == 0
        data = json.loads(b.read_text())
        assert len(data["suppressions"]) == 2
        for entry in data["suppressions"].values():
            entry["reviewed"] = "seeded test suppression"
        b.write_text(json.dumps(data))
        # fix ONE of the two findings -> one stale entry
        bad.write_text(
            "from jax import lax\n"
            "def f(x):\n"
            "    return lax.psum(x, 'bogus')\n"
        )
        return bad, b

    def test_dry_run_default_reports_without_writing(
        self, tmp_path, capsys
    ):
        bad, b = self._seed(tmp_path)
        before = b.read_text()
        assert main([str(bad), "--baseline", str(b), "--prune-baseline"]) == 0
        out = capsys.readouterr().out
        assert "dry run" in out and "stale:" in out
        assert b.read_text() == before
        assert not Path(str(b) + ".removed.json").exists()

    def test_apply_rewrites_and_stamps_removal_list(self, tmp_path, capsys):
        bad, b = self._seed(tmp_path)
        assert (
            main(
                [
                    str(bad),
                    "--baseline",
                    str(b),
                    "--prune-baseline",
                    "--apply",
                ]
            )
            == 0
        )
        out = capsys.readouterr().out
        assert "pruned 1 entry" in out
        data = json.loads(b.read_text())
        assert len(data["suppressions"]) == 1
        removal = json.loads(Path(str(b) + ".removed.json").read_text())
        assert removal["pruned_at"] and removal["baseline"] == str(b)
        [(fp, entry)] = removal["removed"].items()
        assert "bogus2" in fp or "pmean" in entry["message"]
        assert entry["reviewed"] == "seeded test suppression"
        # the pruned baseline still gates the remaining finding green
        assert main([str(bad), "--baseline", str(b)]) == 0

    def test_nothing_stale_is_a_no_op(self, tmp_path, capsys):
        bad, b = self._seed(tmp_path)
        assert (
            main(
                [str(bad), "--baseline", str(b), "--prune-baseline", "--apply"]
            )
            == 0
        )
        capsys.readouterr()
        assert (
            main(
                [str(bad), "--baseline", str(b), "--prune-baseline", "--apply"]
            )
            == 0
        )
        assert "nothing to prune" in capsys.readouterr().out

    def test_partial_select_refuses_to_prune(self, tmp_path, capsys):
        bad, b = self._seed(tmp_path)
        assert (
            main(
                [
                    str(bad),
                    "--baseline",
                    str(b),
                    "--select",
                    "collective-coverage",
                    "--prune-baseline",
                ]
            )
            == 2
        )
        assert "full run" in capsys.readouterr().err
