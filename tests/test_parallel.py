"""Multi-device tests on the 8-device virtual CPU mesh (SURVEY.md §4d-e):
sharded-vs-replicated parity for every parallelism strategy."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from glom_tpu.data import shapes_dataset
from glom_tpu.models.core import glom_forward, init_glom
from glom_tpu.ops.consensus import build_local_mask, consensus_attention
from glom_tpu.parallel import (
    DistributedTrainer,
    make_halo_consensus,
    make_mesh,
    make_ring_consensus,
    make_ulysses_consensus,
)
from glom_tpu.train import Trainer
from glom_tpu.utils.config import GlomConfig, MeshConfig, TrainConfig


def seq_mesh(seq=8):
    return make_mesh(MeshConfig(data=1, seq=seq, model=1))


@pytest.fixture(scope="module")
def levels_16():
    """[b, n=16, L=4, d=32] random levels on a 4x4 patch grid."""
    rng = np.random.default_rng(7)
    return jnp.asarray(rng.normal(size=(2, 16, 4, 32)), jnp.float32)


class TestRingConsensus:
    @pytest.mark.parametrize("attend_self", [False, True])
    def test_matches_dense(self, levels_16, attend_self):
        mesh = seq_mesh(8)
        ring = make_ring_consensus(mesh, attend_self=attend_self, side=4)
        got = jax.jit(ring)(levels_16)
        want = consensus_attention(levels_16, attend_self=attend_self)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-6
        )

    def test_matches_dense_with_radius(self, levels_16):
        mesh = seq_mesh(8)
        ring = make_ring_consensus(mesh, attend_self=False, side=4, radius=1.5)
        got = jax.jit(ring)(levels_16)
        want = consensus_attention(
            levels_16, attend_self=False, local_mask=build_local_mask(4, 1.5)
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-6
        )

    def test_seq_2_shards(self, levels_16):
        mesh = seq_mesh(2)
        ring = make_ring_consensus(mesh, attend_self=False, side=4)
        got = jax.jit(ring)(levels_16)
        want = consensus_attention(levels_16)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-6
        )


class TestUlyssesConsensus:
    def test_matches_dense(self, levels_16):
        mesh = seq_mesh(4)  # L=4 divisible by 4
        uly = make_ulysses_consensus(mesh, attend_self=False)
        got = jax.jit(uly)(levels_16)
        want = consensus_attention(levels_16)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-6
        )

    def test_matches_dense_with_mask(self, levels_16):
        """Radius parity through the (side, radius) plumbing: the shard
        builds its mask in-graph from iota (no O(n^2) host buffer — round-4
        weak #5) and must match the dense op fed the numpy mask."""
        mesh = seq_mesh(2)
        uly = make_ulysses_consensus(mesh, attend_self=True, side=4, radius=1.0)
        got = jax.jit(uly)(levels_16)
        want = consensus_attention(
            levels_16, attend_self=True, local_mask=build_local_mask(4, 1.0)
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-6
        )

    def test_iota_mask_equals_numpy_mask(self):
        """iota_local_mask is build_local_mask computed on device: identical
        boolean pattern at several (side, radius) incl. fractional radii."""
        from glom_tpu.ops.consensus import iota_local_mask

        for side, radius in [(4, 1.0), (4, 1.5), (8, 0.5), (8, 2.9), (16, 7.0)]:
            want = build_local_mask(side, radius)
            got = np.asarray(iota_local_mask(side * side, side, radius))
            np.testing.assert_array_equal(got, want)
        assert iota_local_mask(16, 4, 0.0) is None

    def test_indivisible_levels_raises(self, levels_16):
        mesh = seq_mesh(8)  # L=4 not divisible by 8
        uly = make_ulysses_consensus(mesh, attend_self=False)
        with pytest.raises(ValueError, match="divisible"):
            jax.jit(uly)(levels_16)

    def test_selector_threshold_matches_measured_table(self):
        """The ulysses_preferred predicate (sim-working-set model) must
        agree with EVERY measured row of the committed crossover table —
        the selector is driven by the table, not a magic constant
        (round-4 missing #4). Rows within 10% of parity are treated as
        ties (the measured run-to-run band)."""
        import json
        from pathlib import Path

        from glom_tpu.parallel.runtime import ulysses_preferred

        table = Path(__file__).parent.parent / "results" / "sp_crossover.jsonl"
        rows = [json.loads(x) for x in table.read_text().splitlines() if x]
        assert rows, "committed crossover table missing"
        checked = 0
        for r in rows:
            speedup = r["ulysses_speedup"]
            if 0.9 <= speedup <= 1.1:
                continue  # parity band: either choice is fine
            assert ulysses_preferred(r["n"]) == (speedup > 1.0), (
                f"selector disagrees with measured row {r}"
            )
            checked += 1
        assert checked >= 4  # the table must actually constrain the model

    def test_selector_boundary_n2048_keeps_ring(self):
        """The exactly-at-budget point n=2048 (n^2*4 = 16MB) is UNMEASURED
        — the committed table brackets the flip between n=1024 and n=4096
        — so the predicate must stay STRICT and keep the prior ring
        behavior there until an sp_crossover row for 2048 lands (ADVICE
        round 5, low: `<=` silently flipped the unmeasured boundary)."""
        from glom_tpu.parallel.runtime import ulysses_preferred

        assert ulysses_preferred(1024)        # measured: Ulysses side
        assert not ulysses_preferred(2048)    # unmeasured boundary: ring
        assert not ulysses_preferred(4096)    # measured: ring side


class TestHaloConsensus:
    def test_matches_dense_local(self):
        """8x8 grid (n=64), 4 shards of 2 rows, radius 1.5 -> 2 halo rows."""
        rng = np.random.default_rng(8)
        x = jnp.asarray(rng.normal(size=(1, 64, 3, 16)), jnp.float32)
        mesh = seq_mesh(4)
        halo = make_halo_consensus(mesh, attend_self=False, side=8, radius=1.5)
        got = jax.jit(halo)(x)
        want = consensus_attention(
            x, attend_self=False, local_mask=build_local_mask(8, 1.5)
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-6
        )

    def test_subrow_radius_matches_dense(self):
        """0 < radius < 1: zero halo rows are needed (adjacent grid rows are
        distance 1 > radius). Regression: the h=0 slice t[:, -0:] used to
        grab the WHOLE neighbor block mislabeled with local indices."""
        rng = np.random.default_rng(9)
        x = jnp.asarray(rng.normal(size=(1, 64, 2, 16)), jnp.float32)
        mesh = seq_mesh(4)
        halo = make_halo_consensus(mesh, attend_self=True, side=8, radius=0.5)
        got = jax.jit(halo)(x)
        want = consensus_attention(
            x, attend_self=True, local_mask=build_local_mask(8, 0.5)
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-6
        )

    def test_radius_too_large_raises(self):
        mesh = seq_mesh(8)
        with pytest.raises(ValueError, match="halo"):
            make_halo_consensus(mesh, attend_self=False, side=8, radius=3.0)

    def test_zero_radius_raises(self):
        mesh = seq_mesh(2)
        with pytest.raises(ValueError, match="radius"):
            make_halo_consensus(mesh, attend_self=False, side=8, radius=0.0)


CFG = GlomConfig(dim=16, levels=4, image_size=8, patch_size=2)  # n=16


class TestShardedForward:
    """glom_forward with an SP consensus_fn == single-device forward."""

    @pytest.mark.parametrize("strategy", ["ring", "ulysses"])
    def test_forward_parity(self, strategy):
        params = init_glom(jax.random.PRNGKey(0), CFG)
        img = jnp.asarray(
            np.random.default_rng(1).normal(size=(2, 3, 8, 8)), jnp.float32
        )
        mesh = seq_mesh(4)
        from glom_tpu.parallel import make_consensus_fn

        fn = make_consensus_fn(mesh, CFG, strategy)
        dense = glom_forward(params, img, CFG, iters=3)
        sharded = jax.jit(
            lambda p, im: glom_forward(p, im, CFG, iters=3, consensus_fn=fn)
        )(params, img)
        np.testing.assert_allclose(
            np.asarray(sharded), np.asarray(dense), rtol=1e-4, atol=1e-5
        )


class TestDistributedTrainer:
    def test_dp_matches_single_device(self):
        """Same seed: 8-way DP training == single-device training (the
        gradient allreduce must average exactly, not approximately)."""
        tcfg = TrainConfig(batch_size=8, learning_rate=1e-3, noise_std=0.3, seed=5)
        single = Trainer(CFG, tcfg)
        dist = DistributedTrainer(CFG, tcfg, MeshConfig(data=8, seq=1, model=1))
        data1 = shapes_dataset(8, CFG.image_size, seed=3)
        data2 = shapes_dataset(8, CFG.image_size, seed=3)
        h1 = single.fit(data1, num_steps=3, log_every=1)
        h2 = dist.fit(data2, num_steps=3, log_every=1)
        for a, b in zip(h1, h2):
            np.testing.assert_allclose(a["loss"], b["loss"], rtol=1e-4)
        p1 = jax.tree_util.tree_leaves(single.state.params)
        p2 = jax.tree_util.tree_leaves(dist.state.params)
        for x, y in zip(p1, p2):
            np.testing.assert_allclose(
                np.asarray(x), np.asarray(y), rtol=1e-4, atol=1e-5
            )

    @pytest.mark.parametrize("tp_axis", ["hidden", "levels"])
    def test_tp_matches_single_device(self, tp_axis):
        tcfg = TrainConfig(batch_size=4, learning_rate=1e-3, noise_std=0.3, seed=5)
        single = Trainer(CFG, tcfg)
        dist = DistributedTrainer(
            CFG, tcfg, MeshConfig(data=1, seq=1, model=2), tp_axis=tp_axis
        )
        data1 = shapes_dataset(4, CFG.image_size, seed=3)
        data2 = shapes_dataset(4, CFG.image_size, seed=3)
        h1 = single.fit(data1, num_steps=2, log_every=1)
        h2 = dist.fit(data2, num_steps=2, log_every=1)
        for a, b in zip(h1, h2):
            np.testing.assert_allclose(a["loss"], b["loss"], rtol=1e-4)

    def test_dp_sp_combined(self):
        """2 data x 4 seq mesh with ring consensus trains and loss is finite."""
        tcfg = TrainConfig(batch_size=4, learning_rate=1e-3, noise_std=0.3, seed=5)
        dist = DistributedTrainer(
            CFG,
            tcfg,
            MeshConfig(data=2, seq=4, model=1),
            sp_strategy="ring",
        )
        data = shapes_dataset(4, CFG.image_size, seed=3)
        h = dist.fit(data, num_steps=3, log_every=1)
        assert all(np.isfinite(m["loss"]) for m in h)

    @pytest.mark.parametrize("strategy", ["ring", "ulysses", "halo"])
    def test_dp_sp_matches_single_device(self, strategy):
        """Every SP strategy must match single-device training THROUGH the
        trainer (not just the forward): ring (exact ppermute rotation),
        ulysses (all-to-all over levels), halo (local-radius neighbor
        exchange — needs a radius config)."""
        cfg = CFG if strategy != "halo" else GlomConfig(
            dim=16, levels=4, image_size=8, patch_size=2,
            local_consensus_radius=1,
        )
        tcfg = TrainConfig(batch_size=4, learning_rate=1e-3, noise_std=0.3, seed=5)
        single = Trainer(cfg, tcfg)
        dist = DistributedTrainer(
            cfg,
            tcfg,
            MeshConfig(data=2, seq=2, model=1),
            sp_strategy=strategy,
        )
        data1 = shapes_dataset(4, cfg.image_size, seed=3)
        data2 = shapes_dataset(4, cfg.image_size, seed=3)
        h1 = single.fit(data1, num_steps=2, log_every=1)
        h2 = dist.fit(data2, num_steps=2, log_every=1)
        for a, b in zip(h1, h2):
            np.testing.assert_allclose(a["loss"], b["loss"], rtol=1e-3)

    def test_bad_batch_divisibility_raises(self):
        tcfg = TrainConfig(batch_size=3)
        with pytest.raises(ValueError, match="divisible"):
            DistributedTrainer(CFG, tcfg, MeshConfig(data=2))


class TestSpAutoSelector:
    """sp_strategy='auto' encodes the measured ring-vs-Ulysses crossover
    (results/sp_crossover.jsonl) + halo's geometric precondition, and the
    effective mechanism is reported in the metrics stream."""

    def test_measured_crossover(self):
        from glom_tpu.parallel.runtime import select_sp_strategy

        # Ulysses wins at small global n (measured 4.2x at n=256/seq=8)
        cfg_small = GlomConfig(dim=64, levels=8, image_size=64, patch_size=4)
        assert cfg_small.num_patches == 256
        assert select_sp_strategy(cfg_small, 8) == "ulysses"
        # ring wins at long rows (Ulysses loses 2.1x at n=4096/seq=4)
        cfg_long = GlomConfig(dim=64, levels=8, image_size=256, patch_size=4)
        assert cfg_long.num_patches == 4096
        assert select_sp_strategy(cfg_long, 4) == "ring"
        # local radius with one-hop coverage -> halo
        cfg_halo = GlomConfig(
            dim=64, levels=8, image_size=128, patch_size=4,
            local_consensus_radius=7,
        )  # side 32, seq 4 -> 8 rows/shard >= 7
        assert select_sp_strategy(cfg_halo, 4) == "halo"
        # same intent, halo impossible (seq 8 -> 4 rows < 7): mechanism
        # falls to the global crossover (n=1024 -> ulysses at L%8==0)
        assert select_sp_strategy(cfg_halo, 8) == "ulysses"
        # indivisible levels forbid ulysses
        cfg_indiv = GlomConfig(dim=64, levels=5, image_size=64, patch_size=4)
        assert select_sp_strategy(cfg_indiv, 8) == "ring"
        assert select_sp_strategy(cfg_small, 1) == "none"

    def test_effective_resolves_fallbacks(self):
        from glom_tpu.parallel.runtime import effective_sp_strategy

        cfg = GlomConfig(
            dim=16, levels=5, image_size=8, patch_size=2,
            local_consensus_radius=3,
        )  # side 4: seq 2 -> 2 rows < 3 -> halo impossible
        assert effective_sp_strategy(cfg, 2, "halo") == "ring"
        assert effective_sp_strategy(cfg, 2, "ulysses") == "ring"  # 5 % 2
        assert effective_sp_strategy(cfg, 2, "ring") == "ring"
        assert effective_sp_strategy(cfg, 1, "ring") == "none"
        with pytest.raises(ValueError, match="unknown SP strategy"):
            effective_sp_strategy(cfg, 2, "mystery")

    def test_auto_trains_and_logs_effective_strategy(self):
        """'auto' through the real trainer: matches single-device training
        and every metrics record names the resolved mechanism (round-3
        weak #6: silent fallbacks never surfaced in the metrics stream)."""
        from glom_tpu.parallel.runtime import effective_sp_strategy

        tcfg = TrainConfig(batch_size=4, learning_rate=1e-3, noise_std=0.3, seed=5)
        expect = effective_sp_strategy(CFG, 2, "auto")
        assert expect in ("ring", "ulysses")
        single = Trainer(CFG, tcfg)
        dist = DistributedTrainer(
            CFG, tcfg, MeshConfig(data=2, seq=2, model=1), sp_strategy="auto"
        )
        assert dist.sp_strategy == expect
        h1 = single.fit(shapes_dataset(4, CFG.image_size, seed=3), 2, log_every=1)
        h2 = dist.fit(shapes_dataset(4, CFG.image_size, seed=3), 2, log_every=1)
        for a, b in zip(h1, h2):
            np.testing.assert_allclose(a["loss"], b["loss"], rtol=1e-3)
            assert b["sp_strategy"] == expect

    def test_manual_path_auto(self):
        """'auto' on the manual fused shard_map path (use_pallas) resolves
        and trains: the selector output feeds _shard_consensus_fn."""
        tcfg = TrainConfig(
            batch_size=4, learning_rate=1e-3, noise_std=0.3, seed=5,
            use_pallas=True,
        )
        dist = DistributedTrainer(
            CFG, tcfg, MeshConfig(data=2, seq=2, model=1), sp_strategy="auto"
        )
        assert dist.use_manual
        h = dist.fit(shapes_dataset(4, CFG.image_size, seed=3), 2, log_every=1)
        assert all(np.isfinite(m["loss"]) for m in h)
        assert all(m["sp_strategy"] == dist.sp_strategy for m in h)
