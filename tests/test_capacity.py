"""Capacity observatory (ISSUE 13, docs/OBSERVABILITY.md): per-collective
wall-time (α-β time model, sampled/full harnesses), the serve latency
decomposition's bit-exact conservation, and headroom accounting + the
watch --slo headroom lower-bound rule.

Host-side fakes wherever possible; the jitted pieces (the sampler's
re-dispatched sub-graphs, the manual-zero1 and serve-mesh acceptance
locks) ride the 8-device virtual CPU mesh the conftest pins.
"""

import math
import time
from pathlib import Path

import numpy as np
import pytest

from glom_tpu.serve.batcher import DynamicBatcher
from glom_tpu.serve.engine import ServeResult
from glom_tpu.telemetry import comm_time, schema, tracectx
from glom_tpu.telemetry.aggregate import SLOMonitor, watch_main
from glom_tpu.telemetry.counters import (
    CollectiveCounters,
    CollectiveTimeLog,
    recording,
    resolve_collective_timing,
    scaled,
    timed_collective,
    timing,
)
from glom_tpu.telemetry.tracectx import PHASE_KEYS
from glom_tpu.utils.config import ServeConfig

FIXTURES = Path(__file__).resolve().parent / "fixtures"


class Sink:
    def __init__(self):
        self.records = []

    def write(self, rec):
        self.records.append(dict(rec))


IMG = np.zeros((3, 8, 8), np.float32)


# ---------------------------------------------------------------------------
# the α-β time model
# ---------------------------------------------------------------------------


class TestTimeModel:
    def test_fit_recovers_alpha_beta(self):
        alpha, beta = 0.5, 2e-6
        pts = [
            {"wire_bytes": x, "wall_ms": alpha + beta * x}
            for x in (1e5, 2e5, 4e5, 8e5)
        ]
        m = comm_time.fit_time_model(pts)
        assert m["alpha_ms"] == pytest.approx(alpha, rel=1e-6)
        assert m["beta_ms_per_byte"] == pytest.approx(beta, rel=1e-6)
        assert m["n_points"] == 4
        for p in pts:
            pred = comm_time.predict_ms(m, p["wire_bytes"])
            assert comm_time.time_model_drift(p["wall_ms"], pred) == (
                pytest.approx(0.0, abs=1e-6)
            )

    def test_degenerate_fits_stay_honest(self):
        # No points at all.
        m0 = comm_time.fit_time_model([])
        assert m0 == {
            "alpha_ms": 0.0, "beta_ms_per_byte": 0.0, "n_points": 0
        }
        # One point / all points at one byte size: alpha = mean, beta 0 —
        # a bandwidth term the data never measured must not be invented.
        m1 = comm_time.fit_time_model(
            [{"wire_bytes": 1024, "wall_ms": 3.0},
             {"wire_bytes": 1024, "wall_ms": 5.0}]
        )
        assert m1["alpha_ms"] == pytest.approx(4.0)
        assert m1["beta_ms_per_byte"] == 0.0

    def test_negative_slope_clamps_to_zero(self):
        # Noise giving smaller payloads LONGER times must not extrapolate
        # to negative predictions.
        m = comm_time.fit_time_model(
            [{"wire_bytes": 100, "wall_ms": 5.0},
             {"wire_bytes": 10000, "wall_ms": 1.0}]
        )
        assert m["beta_ms_per_byte"] == 0.0
        assert m["alpha_ms"] >= 0.0

    def test_drift_conventions_match_comm_model_drift(self):
        assert comm_time.time_model_drift(0.0, 0.0) == 0.0
        assert comm_time.time_model_drift(1.0, 0.0) == 1e9  # inf clamp
        assert comm_time.time_model_drift(3.0, 2.0) == pytest.approx(0.5)

    def test_records_carry_model_row_and_lint(self):
        samples = [
            {"site": "a", "axis": "data", "collective": "psum",
             "wire_bytes": 1000, "wall_ms": 1.0, "calls": 2},
            {"site": "b", "axis": "data", "collective": "all_gather",
             "wire_bytes": 4000, "wall_ms": 2.0},
        ]
        recs = comm_time.collective_time_records(
            samples, path="test", mode="sampled"
        )
        assert [r["site"] for r in recs] == ["a", "b", "comm_time_model"]
        for r in recs:
            assert schema.validate_record(r) == [], r
            assert r["kind"] == "collective_time"
            assert math.isfinite(r["comm_time_model_drift"])
        model = recs[-1]
        assert model["wall_ms"] == pytest.approx(3.0)
        assert {"alpha_ms", "beta_ms_per_byte", "n_points"} <= set(model)
        # bytes/s only where wall time exists.
        assert recs[0]["bytes_per_s"] == pytest.approx(1000 / 1e-3)
        assert comm_time.collective_time_records(
            [], path="test", mode="sampled"
        ) == []


# ---------------------------------------------------------------------------
# the shared timing wrapper + site registry
# ---------------------------------------------------------------------------


class TestTimedCollective:
    def test_registers_site_with_scaled_calls(self):
        c = CollectiveCounters()
        x = np.zeros((4, 8), np.float32)
        with recording(c), scaled(3):
            out = timed_collective(
                "site_a", "data", "reduce", 128,
                lambda v: v + 1, x, collective="psum",
            )
        np.testing.assert_array_equal(out, x + 1)
        # Bytes counted exactly as record_collective would (x scale).
        assert c.reduce_bytes == 128 * 3
        (site,) = c.sites
        assert site["site"] == "site_a" and site["calls"] == 3
        assert site["shape"] == (4, 8) and site["collective"] == "psum"

    def test_retrace_accumulates_calls_not_duplicates(self):
        c = CollectiveCounters()
        x = np.zeros((2,), np.float32)
        with recording(c):
            for _ in range(2):
                timed_collective(
                    "site_a", "data", "reduce", 8,
                    lambda v: v, x, collective="psum",
                )
        (site,) = c.sites
        assert site["calls"] == 2

    def test_resolve_vocabulary_and_degrade(self):
        with pytest.raises(ValueError, match="collective_timing"):
            resolve_collective_timing("bogus")
        assert resolve_collective_timing("off") == "off"
        assert resolve_collective_timing("full") == "full"
        with pytest.warns(UserWarning, match="sampled"):
            assert (
                resolve_collective_timing("full", supports_full=False)
                == "sampled"
            )

    def test_full_mode_brackets_inside_shard_map(self):
        """The full-mode io_callback brackets, traced INSIDE a shard_map:
        every shard's execution contributes one wall-clock sample to the
        log; off-mode traces of the same body contribute none."""
        import jax
        import jax.numpy as jnp
        from jax import lax
        from jax.sharding import PartitionSpec as P

        from glom_tpu.parallel.mesh import make_mesh
        from glom_tpu.utils.compat import shard_map
        from glom_tpu.utils.config import MeshConfig

        mesh = make_mesh(MeshConfig(data=2), jax.devices()[:2])

        def body(x):
            return timed_collective(
                "bracket_psum", "data", "reduce", 64,
                lambda v: lax.psum(v, "data"), x, collective="psum",
            )

        fn = shard_map(
            body, mesh=mesh, in_specs=(P("data"),), out_specs=P(),
            check_vma=False,
        )
        x = jnp.arange(8.0).reshape(2, 4)
        log = CollectiveTimeLog()
        with timing("full", log):
            compiled = jax.jit(fn).lower(x).compile()
        jax.block_until_ready(compiled(x))
        time.sleep(0.05)  # callbacks flush asynchronously
        rows = log.drain()
        assert rows, "full-mode brackets produced no samples"
        (row,) = rows
        assert row["site"] == "bracket_psum" and row["mode"] == "full"
        assert row["calls"] == 2  # one sample per shard
        assert row["wall_ms"] > 0
        # Off mode: same trace, no callbacks, no samples.
        log2 = CollectiveTimeLog()
        with timing("off", log2):
            compiled2 = jax.jit(fn).lower(x).compile()
        jax.block_until_ready(compiled2(x))
        time.sleep(0.05)
        assert log2.drain() == []


class TestSampler:
    def _mesh(self, k=2):
        import jax

        from glom_tpu.parallel.mesh import make_mesh
        from glom_tpu.utils.config import MeshConfig

        return make_mesh(MeshConfig(data=k), jax.devices()[:k])

    def test_sample_times_each_site(self):
        sites = [
            {"site": "s_psum", "axis": "data", "collective": "psum",
             "wire_bytes": 64, "calls": 1, "shape": (4, 4),
             "dtype": "float32", "dim": 0},
            {"site": "s_gather", "axis": "data",
             "collective": "all_gather", "wire_bytes": 64, "calls": 1,
             "shape": (2, 4), "dtype": "float32", "dim": 0},
        ]
        s = comm_time.CollectiveTimeSampler(
            self._mesh(), sites, interval=2, repeats=2
        )
        rows = s.sample()
        assert {r["site"] for r in rows} == {"s_psum", "s_gather"}
        assert all(r["wall_ms"] > 0 for r in rows)

    def test_maybe_sample_rate_limits(self):
        sites = [
            {"site": "s", "axis": "data", "collective": "psum",
             "wire_bytes": 16, "calls": 1, "shape": (2,),
             "dtype": "float32", "dim": 0},
        ]
        s = comm_time.CollectiveTimeSampler(
            self._mesh(), sites, interval=2, repeats=1
        )
        assert s.maybe_sample(path="t") == []
        recs = s.maybe_sample(path="t")
        assert recs and recs[-1]["site"] == "comm_time_model"
        for r in recs:
            assert schema.validate_record(r) == [], r
        assert s.maybe_sample(path="t") == []

    def test_dedupes_byte_identical_shapes(self):
        sites = [
            {"site": "s", "axis": "data", "collective": "psum",
             "wire_bytes": 64, "calls": 2, "shape": (4, 4),
             "dtype": "float32", "dim": 0},
            {"site": "s", "axis": "data", "collective": "psum",
             "wire_bytes": 64, "calls": 3, "shape": (16,),
             "dtype": "float32", "dim": 0},
            {"site": "s", "axis": "data", "collective": "psum",
             "wire_bytes": 0, "calls": 1, "shape": (1,),
             "dtype": "float32", "dim": 0},
        ]
        s = comm_time.CollectiveTimeSampler(self._mesh(), sites)
        # Two byte-identical entries merge (calls sum); the zero-byte
        # site is filtered entirely.
        assert len(s.sites) == 1
        assert s.sites[0]["calls"] == 5


# ---------------------------------------------------------------------------
# serve latency decomposition (host-side fakes)
# ---------------------------------------------------------------------------


class PhaseFakeEngine:
    """FakeEngine returning a fixed engine wall + engine-side phase
    split, so the batcher's derived device_ms is deterministic."""

    def __init__(self, buckets=(1, 2, 4), latency_s=0.01, phases=None):
        self.scfg = ServeConfig(
            buckets=buckets, max_batch=max(buckets), max_delay_ms=5.0,
            queue_depth=8,
        )
        self.latency_s = latency_s
        self.phases = (
            phases if phases is not None
            else {"h2d_ms": 0.5, "resolve_ms": 0.25}
        )
        self.calls = []

    def pick_bucket(self, n):
        for b in self.scfg.buckets:
            if n <= b:
                return b
        raise ValueError(n)

    def infer(self, imgs, n_valid=None):
        b = imgs.shape[0]
        self.calls.append((b, n_valid))
        return ServeResult(
            levels=np.zeros((b, 16, 3, 16), np.float32),
            iters_run=6,
            latency_s=self.latency_s,
            bucket=b,
            compiled=False,
            phases=dict(self.phases),
        )


class TieredPhaseEngine(PhaseFakeEngine):
    """Auto-route fake whose FIRST dispatch leaves one straggler (row 0
    unconverged), so the batcher opens a continuation hop; a permanent
    `fail` exception drives the failover path."""

    def __init__(self, name="engine0", fail=None, **kw):
        super().__init__(**kw)
        self.scfg = ServeConfig(
            buckets=(1, 2, 4), max_batch=4, max_delay_ms=5.0,
            queue_depth=8, iters="auto", max_auto_iters=12,
            max_continuations=2, exit_threshold=1e-3,
        )
        self.name = name
        self.iters_key = "auto"
        self.auto_budget = 12
        self.fail = fail
        self.dispatches = 0

    def cold_levels(self):
        return np.zeros((16, 3, 16), np.float32)

    def infer(self, imgs, n_valid=None, levels0=None, auto_budget=None,
              iters_override=None):
        if self.fail is not None:
            raise self.fail
        b = imgs.shape[0]
        self.dispatches += 1
        conv = np.ones((b,), bool)
        if self.dispatches == 1 and levels0 is None:
            conv[0] = False  # one straggler on the first cold dispatch
        iters = 4 if levels0 is None else 3
        return ServeResult(
            levels=np.zeros((b, 16, 3, 16), np.float32),
            iters_run=iters,
            latency_s=self.latency_s,
            bucket=b,
            compiled=False,
            row_converged=conv,
            row_iters=np.full((b,), iters, np.int32),
            phases=dict(self.phases),
        )


class TestPhaseSplit:
    def test_phases_sum_bit_exactly_to_latency_ms(self):
        eng = PhaseFakeEngine()
        sink = Sink()
        with DynamicBatcher(eng, max_batch=2, max_delay_ms=10.0,
                            writer=sink) as b:
            ts = [b.submit(IMG) for _ in range(2)]
            for t in ts:
                t.result(timeout=10.0)
        (d,) = [r for r in sink.records if r.get("event") == "dispatch"]
        s = 0.0
        for k in PHASE_KEYS:
            assert isinstance(d[k], float), (k, d[k])
            s = s + d[k]
        assert s == d["latency_ms"]  # BIT-exact, not approx
        # The engine split surfaces: h2d as reported, device = engine
        # wall minus the engine-side h2d + resolve.
        assert d["h2d_ms"] == 0.5
        assert d["device_ms"] == pytest.approx(10.0 - 0.5 - 0.25, abs=0.2)
        assert schema.validate_record(d) == []

    def test_phase_split_off_stamps_null_keys(self):
        eng = PhaseFakeEngine()
        sink = Sink()
        with DynamicBatcher(eng, max_batch=1, max_delay_ms=5.0,
                            writer=sink, phase_split=False) as b:
            b.submit(IMG).result(timeout=10.0)
        (d,) = [r for r in sink.records if r.get("event") == "dispatch"]
        for k in PHASE_KEYS:
            assert k in d and d[k] is None
        # latency_ms reverts to the bare engine wall (pre-v7 reading).
        assert d["latency_ms"] == pytest.approx(10.0, abs=0.01)
        (leaf,) = [r for r in sink.records if r.get("event") == "resolve"]
        assert leaf["phase_ms_total"] is None
        check = tracectx.conservation(sink.records, leaf["trace_id"])
        assert check["ok"], check

    def test_engine_without_phases_attributes_wall_to_device(self):
        class Bare(PhaseFakeEngine):
            def infer(self, imgs, n_valid=None):
                r = super().infer(imgs, n_valid=n_valid)
                return r._replace(phases=None)

        sink = Sink()
        with DynamicBatcher(Bare(), max_batch=1, max_delay_ms=5.0,
                            writer=sink) as b:
            b.submit(IMG).result(timeout=10.0)
        (d,) = [r for r in sink.records if r.get("event") == "dispatch"]
        assert d["h2d_ms"] == 0.0
        assert d["device_ms"] == pytest.approx(10.0, abs=0.01)
        s = 0.0
        for k in PHASE_KEYS:
            s = s + d[k]
        assert s == d["latency_ms"]

    def test_conservation_across_continuation_hops(self):
        """The extended parity lock: per-hop phase sums AND cross-hop
        per-phase totals conserve bit-exactly through a straggler
        continuation chain."""
        eng = TieredPhaseEngine()
        sink = Sink()
        with DynamicBatcher(eng, max_batch=2, max_delay_ms=10.0,
                            writer=sink) as b:
            ts = [b.submit(IMG) for _ in range(2)]
            for t in ts:
                t.result(timeout=10.0)
        recs = sink.records
        assert any(r.get("event") == "continuation" for r in recs)
        for t in ts:
            check = tracectx.conservation(recs, t.trace_id)
            assert check["ok"], check
        straggler = [t for t in ts if t.hops][0]
        check = tracectx.conservation(recs, straggler.trace_id)
        assert check["n_hops"] >= 2
        assert set(check["phase_ms_total"]) == set(PHASE_KEYS)

    def test_conservation_across_failover(self):
        bad = TieredPhaseEngine(name="bad", fail=RuntimeError("boom"))
        good = TieredPhaseEngine(name="good")
        sink = Sink()
        with DynamicBatcher(engines=[bad, good], max_batch=4,
                            max_delay_ms=10.0, writer=sink) as b:
            # PACED submissions until "bad" has demonstrably taken (and
            # failed) a batch — an all-at-once burst let one pickup race
            # decide whether the failover path ran at all (the
            # test_serve.py kill-path fix, same flake).
            ts = [b.submit(IMG)]
            deadline = time.monotonic() + 10.0
            while not any(
                r.get("event") == "engine_failover" for r in sink.records
            ):
                assert time.monotonic() < deadline, "bad never dispatched"
                time.sleep(0.02)
                ts.append(b.submit(IMG))
            ts += [b.submit(IMG) for _ in range(2)]
            for t in ts:
                t.result(timeout=10.0)
        recs = sink.records
        assert any(r.get("event") == "engine_failover" for r in recs)
        for t in ts:
            check = tracectx.conservation(recs, t.trace_id)
            assert check["ok"], check
        for r in recs:
            assert schema.validate_record(r) == [], r

    def test_tampered_phase_fails_conservation(self):
        eng = TieredPhaseEngine()
        sink = Sink()
        with DynamicBatcher(eng, max_batch=2, max_delay_ms=10.0,
                            writer=sink) as b:
            ts = [b.submit(IMG) for _ in range(2)]
            for t in ts:
                t.result(timeout=10.0)
        recs = [dict(r) for r in sink.records]
        straggler = [t for t in ts if t.hops][0]
        for r in recs:
            if r.get("event") == "dispatch":
                r["device_ms"] = r["device_ms"] + 0.001
                break
        check = tracectx.conservation(recs, straggler.trace_id)
        assert not check["ok"]
        assert "phase" in check["why"] or "conserve" in check["why"]

    def test_queue_wait_reflects_actual_waiting(self):
        eng = PhaseFakeEngine()
        sink = Sink()
        b = DynamicBatcher(eng, max_batch=1, max_delay_ms=5.0,
                           writer=sink)  # not started yet
        t = b.submit(IMG)
        time.sleep(0.05)  # the request ages in the queue
        b.start()
        t.result(timeout=10.0)
        b.stop()
        (d,) = [r for r in sink.records if r.get("event") == "dispatch"]
        assert d["queue_wait_ms"] >= 40.0


# ---------------------------------------------------------------------------
# headroom accounting
# ---------------------------------------------------------------------------


class StubPool:
    def __init__(self, used, total):
        self._used, self._total = used, total
        self.delta = False
        self.page_tokens = 16

    def record(self):
        return {"pages_total": self._total, "pages_used": self._used,
                "pages_free": self._total - self._used}


class TestCapacityRecords:
    def test_headroom_monotone_under_queue_load(self):
        eng = PhaseFakeEngine()
        b = DynamicBatcher(eng, queue_depth=8)  # NOT started: queue fills
        headrooms = []
        for _ in range(6):
            b.submit(IMG)
            (cap,) = b.capacity_records()
            headrooms.append(cap["headroom"])
            assert schema.validate_record(cap) == []
        assert headrooms == sorted(headrooms, reverse=True)
        assert headrooms[-1] < headrooms[0]
        b.stop(drain=False)

    def test_dead_engine_has_zero_headroom(self):
        eng = PhaseFakeEngine()
        b = DynamicBatcher(eng)
        with b._engine_lock:
            b._engine_state["engine0"]["alive"] = False
        (cap,) = b.capacity_records()
        assert cap["headroom"] == 0.0 and cap["alive"] is False
        b.stop(drain=False)

    def test_pool_fill_caps_headroom(self):
        eng = PhaseFakeEngine()
        eng.pool = StubPool(used=9, total=10)
        eng.name = "engine0"
        b = DynamicBatcher(eng)
        (cap,) = b.capacity_records()
        assert cap["pool_fill"] == pytest.approx(0.9)
        assert cap["utilization"] >= 0.9
        assert cap["headroom"] <= 0.1
        b.stop(drain=False)

    def test_service_rate_from_dispatch_evidence(self):
        eng = PhaseFakeEngine(latency_s=0.01)
        sink = Sink()
        with DynamicBatcher(eng, max_batch=2, max_delay_ms=10.0,
                            writer=sink) as b:
            ts = [b.submit(IMG) for _ in range(4)]
            for t in ts:
                t.result(timeout=10.0)
            (cap,) = b.capacity_records()
        assert cap["service_rate_rps"] is not None
        assert cap["service_rate_rps"] > 0
        assert cap["n_dispatches"] >= 1

    def test_summary_emits_capacity_records_and_nest(self):
        eng = PhaseFakeEngine()
        sink = Sink()
        with DynamicBatcher(eng, max_batch=1, max_delay_ms=5.0,
                            writer=sink) as b:
            b.submit(IMG).result(timeout=10.0)
            summary = b.summary_record()
        caps = [r for r in sink.records if r.get("kind") == "capacity"]
        assert caps and caps[0]["engine"] == "engine0"
        assert "capacity" in summary
        assert summary["capacity"]["engine0"]["headroom"] == (
            caps[0]["headroom"]
        )
        assert "latency_phases" in summary
        assert set(summary["latency_phases"]) == set(PHASE_KEYS)
        assert schema.validate_record(summary) == []


class TestHeadroomSLO:
    def test_headroom_is_a_lower_bound_rule(self):
        mon = SLOMonitor({"headroom": 0.2}, window_s=None)
        for h in (0.9, 0.5, 0.4):
            mon.observe(schema.stamp(
                {"engine": "e0", "headroom": h}, kind="capacity"
            ))
        assert mon.evaluate() == []  # min 0.4 >= 0.2: no breach
        mon.observe(schema.stamp(
            {"engine": "e1", "headroom": 0.05}, kind="capacity"
        ))
        (breach,) = mon.evaluate()
        assert breach["rule"] == "headroom"
        assert breach["observed"] == pytest.approx(0.05)
        assert breach["bound"] == "lower"
        assert schema.validate_record(breach) == []

    def test_min_across_engines_is_the_signal(self):
        # One exhausted engine among idle siblings IS the scale-out
        # signal.
        mon = SLOMonitor({"headroom": 0.2}, window_s=None)
        mon.observe(schema.stamp(
            {"engine": "idle", "headroom": 0.95}, kind="capacity"
        ))
        mon.observe(schema.stamp(
            {"engine": "hot", "headroom": 0.1}, kind="capacity"
        ))
        assert mon.observed()["headroom"] == pytest.approx(0.1)
        assert len(mon.evaluate()) == 1

    def test_upper_bound_rules_unchanged(self):
        mon = SLOMonitor({"p99_ms": 50.0}, window_s=None)
        mon.observe(schema.stamp(
            {"event": "resolve", "latency_ms": 100.0, "iters_total": 4,
             "trace_id": "t1"}, kind="serve",
        ))
        (breach,) = mon.evaluate()
        assert breach["rule"] == "p99_ms" and breach["bound"] == "upper"

    def test_watch_once_exits_nonzero_on_exhausted_stream(self, capsys):
        rc = watch_main(
            [str(FIXTURES / "capacity_exhausted.jsonl"),
             "--slo", "headroom=0.2", "--once"]
        )
        assert rc == 1
        out = capsys.readouterr()
        assert "headroom" in out.out

    def test_watch_once_exits_zero_on_idle_stream(self):
        rc = watch_main(
            [str(FIXTURES / "capacity_idle.jsonl"),
             "--slo", "headroom=0.2", "--once"]
        )
        assert rc == 0


# ---------------------------------------------------------------------------
# the acceptance locks on the CPU mesh (manual zero1 + serve-mesh witness)
# ---------------------------------------------------------------------------


class TestManualZero1Timing:
    def test_sampled_timing_produces_site_records(self):
        """ISSUE 13 acceptance: with timing enabled on the CPU mesh,
        every registered collective site on the manual zero1 path
        produces collective_time records — schema-clean, nonzero wall_ms,
        finite comm_time_model_drift."""
        import jax

        from glom_tpu.parallel.runtime import DistributedTrainer
        from glom_tpu.utils.config import (
            GlomConfig,
            MeshConfig,
            TrainConfig,
        )

        dp = min(8, len(jax.devices()))
        cfg = GlomConfig(dim=16, levels=2, image_size=8, patch_size=4)
        tcfg = TrainConfig(
            batch_size=dp, use_pallas=True, zero_stage=1,
            telemetry_level="scalars", collective_timing="sampled",
            collective_timing_interval=1,
        )
        tr = DistributedTrainer(cfg, tcfg, MeshConfig(data=dp))
        assert tr.collective_timing == "sampled"
        assert tr._static_record["collective_timing"] == "sampled"
        recs = tr.collective_time_records(force=True)
        sites = {r["site"] for r in recs}
        # The zero1 schedule's registered sites (seq=1: no seq psum).
        assert {"zero_psum_scatter", "zero_all_gather",
                "comm_time_model"} <= sites
        for r in recs:
            assert schema.validate_record(r) == [], r
            assert r["wall_ms"] > 0
            assert math.isfinite(r["comm_time_model_drift"])

    def test_full_degrades_to_sampled_loudly_and_off_is_silent(self):
        import jax

        from glom_tpu.parallel.runtime import DistributedTrainer
        from glom_tpu.utils.config import (
            GlomConfig,
            MeshConfig,
            TrainConfig,
        )

        dp = min(8, len(jax.devices()))
        cfg = GlomConfig(dim=16, levels=2, image_size=8, patch_size=4)
        with pytest.warns(UserWarning, match="sampled"):
            tr = DistributedTrainer(
                cfg,
                TrainConfig(
                    batch_size=dp, use_pallas=True, zero_stage=1,
                    telemetry_level="scalars", collective_timing="full",
                ),
                MeshConfig(data=dp),
            )
        assert tr.collective_timing == "sampled"
        tr_off = DistributedTrainer(
            cfg,
            TrainConfig(
                batch_size=dp, use_pallas=True, zero_stage=1,
                telemetry_level="scalars",
            ),
            MeshConfig(data=dp),
        )
        assert tr_off.collective_timing == "off"
        assert tr_off.collective_sampler is None
        assert tr_off.collective_time_records(force=True) == []


class TestServeMeshTiming:
    def _engine(self, mode):
        from glom_tpu.serve.engine import InferenceEngine
        from glom_tpu.utils.config import GlomConfig

        cfg = GlomConfig(dim=16, levels=2, image_size=8, patch_size=4)
        scfg = ServeConfig(
            buckets=(2,), max_batch=2, iters="auto",
            mesh_data=2, collective_timing=mode,
            collective_timing_interval=1,
        )
        return InferenceEngine(cfg, scfg, name=f"mesh-{mode}")

    def test_sampled_witness_sites_produce_records_and_off_is_absent(
        self,
    ):
        """ISSUE 13 acceptance, serve half: the serve-mesh witness path's
        registered sites produce collective_time records under timing;
        off leaves NONE."""
        eng = self._engine("sampled")
        eng.warmup()
        eng.infer(np.zeros((2, 3, 8, 8), np.float32), n_valid=2)
        recs = eng.collective_time_records()
        sites = {r["site"] for r in recs}
        assert {"quorum_valid_psum", "quorum_exit_psum",
                "comm_time_model"} <= sites
        for r in recs:
            assert schema.validate_record(r) == [], r
            assert r["wall_ms"] > 0
            assert math.isfinite(r["comm_time_model_drift"])
            assert r["engine"] == "mesh-sampled"
        off = self._engine("off")
        off.warmup()
        off.infer(np.zeros((2, 3, 8, 8), np.float32), n_valid=2)
        assert off.collective_time_records() == []

    @pytest.mark.slow  # compiles its own engine; CI telemetry job runs it
    def test_full_mode_brackets_every_execution(self):
        eng = self._engine("full")
        eng.warmup()
        eng.infer(np.zeros((2, 3, 8, 8), np.float32), n_valid=2)
        time.sleep(0.05)
        recs = eng.collective_time_records()
        sites = {r["site"] for r in recs}
        assert {"quorum_valid_psum", "quorum_exit_psum"} <= sites
        per_site = [r for r in recs if r["site"] != "comm_time_model"]
        assert all(r["mode"] == "full" for r in per_site)
        assert all(r["wall_ms"] > 0 for r in per_site)
        # The quorum-exit site rides the while_loop: more executions than
        # the one-shot valid-count psum.
        by = {r["site"]: r for r in per_site}
        assert by["quorum_exit_psum"]["calls"] >= (
            by["quorum_valid_psum"]["calls"]
        )
        # Drained: a second read without dispatches is empty.
        assert eng.collective_time_records() == []

    def test_single_device_engine_resolves_off_loudly(self):
        from glom_tpu.serve.engine import InferenceEngine
        from glom_tpu.utils.config import GlomConfig

        cfg = GlomConfig(dim=16, levels=2, image_size=8, patch_size=4)
        with pytest.warns(UserWarning, match="single-device"):
            eng = InferenceEngine(
                cfg,
                ServeConfig(buckets=(1,), max_batch=1,
                            collective_timing="sampled"),
            )
        assert eng.collective_timing == "off"
