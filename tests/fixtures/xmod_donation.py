"""Seeded fixture pair for donation-safety CROSS-MODULE handle flow
(glom_tpu/analysis/donation.py + analysis/project.py).

The blind spot this pair pins: the donating compiled handle lives on
`Engine` in xmod_donation_engine.py, but it is DISPATCHED here, through
a typed receiver (`eng: Engine`). A single-module pass has no idea
`eng._step` donates anything. The whole-program pass must:

  * flag `serve_leaky`'s use of `imgs` after the typed-receiver
    dispatch donated it (handle-attr load across the import boundary);
  * flag `provider_leaky` the same way when the handle arrives via the
    provider METHOD (`eng.compile_step()`);
  * flag `splat_leaky`'s `fn(*args)` dispatch — the donated positions
    are statically unknowable under a splat, which used to be silently
    skipped;
  * leave the clean twins green (donated buffer never read again /
    only the non-donated position reused).

LINT FIXTURE: parsed, never imported (lint both files together).
"""

from xmod_donation_engine import Engine


def serve_leaky(eng: Engine, params, imgs):
    out = eng._step(params, imgs)
    return out, imgs.mean()  # BUG: imgs was donated at position 1


def serve_clean(eng: Engine, params, imgs):
    out = eng._step(params, imgs)
    return out, params  # position 0 is not donated


def provider_leaky(eng: Engine, params, imgs):
    fn = eng.compile_step()
    out = fn(params, imgs)
    return out, imgs.sum()  # BUG: the provider's handle donated imgs


def provider_clean(eng: Engine, params, imgs):
    fn = eng.compile_step()
    return fn(params, imgs)


def splat_leaky(eng: Engine, args):
    fn = eng.compile_step()
    return fn(*args)  # BUG: donated positions unknowable under a splat
