"""Companion caller module for the axis-environment CROSS-MODULE mesh
flow fixture pair (tests/fixtures/xmod_mesh_flow.py).

This module is where every mesh is BUILT; the shard_map sites it feeds
live one import away. `serve` builds a (data, seq)-intent serve mesh —
the leaky builder's only caller. `train` forwards a MeshConfig-ANNOTATED
parameter through the factory, which attests the full axis tuple
(MeshConfig.axis_names is unconditionally all three) for the
train-shaped builder.

LINT FIXTURE: parsed, never imported.
"""

from xmod_mesh_flow import build_clean, build_leaky, build_train


class MeshConfig:
    """Stand-in for glom_tpu.utils.config.MeshConfig: the checker
    matches the NAME for ctor-keyword intent, and the annotation rule
    needs the class defined in an ANALYZED module — this keeps the pair
    self-contained (lint runs over just these two files)."""

    def __init__(self, data=1, seq=1, model=1):
        self.data, self.seq, self.model = data, seq, model


def make_mesh(cfg: MeshConfig):
    return cfg


def serve():
    mesh = make_mesh(MeshConfig(data=2, seq=2))
    return build_leaky(mesh), build_clean(mesh)


def train(mesh_cfg: MeshConfig):
    mesh = make_mesh(mesh_cfg)
    return build_train(mesh)
