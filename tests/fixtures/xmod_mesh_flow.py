"""Seeded fixture pair for axis-environment CROSS-MODULE mesh flow
(glom_tpu/analysis/axisenv.py + analysis/project.py).

The blind spot this pair pins: this module builds NO mesh — every
builder takes it as an opaque parameter, and the MeshConfig evidence
lives in xmod_mesh_flow_runtime.py, one import away. A single-module
pass has an empty module union and must SKIP all three sites; the
whole-program pass follows the cross-module caller:

  * `build_leaky`'s only caller passes a MeshConfig(data, seq) serve
    mesh — its body's psum over MODEL_AXIS is flagged HERE, through
    the import boundary;
  * `build_clean` runs the same flow on a declared axis: green;
  * `build_train`'s mesh traces back to a MeshConfig-ANNOTATED
    parameter (the trainer/runtime shape), which attests the full
    {data, seq, model} tuple — its 'model' psum is legal: green.

LINT FIXTURE: parsed, never imported (lint both files together).
"""

from jax import lax

DATA_AXIS = "data"
SEQ_AXIS = "seq"
MODEL_AXIS = "model"


def shard_map(fn, mesh=None, in_specs=None, out_specs=None):  # noqa: ARG001
    return fn


def P(*axes):  # noqa: ARG001 — spec stand-in, parsed not executed
    return axes


def build_leaky(mesh):
    def body(x):
        # BUG: the only caller ever passes a (data, seq) serve mesh —
        # this axis exists nowhere in this site's environment.
        return lax.psum(x, MODEL_AXIS)

    return shard_map(
        body, mesh=mesh, in_specs=(P(DATA_AXIS),), out_specs=P()
    )


def build_clean(mesh):
    def body(x):
        return lax.psum(x, SEQ_AXIS)

    return shard_map(
        body, mesh=mesh, in_specs=(P(DATA_AXIS),), out_specs=P()
    )


def build_train(mesh):
    def body(x):
        return lax.psum(x, MODEL_AXIS)  # legal: annotated-config mesh

    return shard_map(
        body, mesh=mesh, in_specs=(P(DATA_AXIS),), out_specs=P()
    )
