"""Seeded acceptance pair for donation-safety's memoized-handle taint
(analysis/donation.py): LeakyMemoEngine stores a donating compiled
forward in `self._compiled[sig]`, fetches it through a provider method,
and then READS the batch it donated — the exact cross-method shape that
was a PR 5 blind spot (the intra-function pass never saw the dispatch
because the handle crossed a method boundary through an attribute).
SafeMemoEngine does the same dispatch but holds the source on the HOST
and re-reads only the host copy — the runtime copy-guard discipline
serve/engine.py ships — and must scan clean.

NOT imported by production code; tests/test_analysis.py runs the checker
over this file and asserts the use-after-donation is flagged at
file:line on the leaky class only. On TPU the leaky reads raise
`RuntimeError: Array has been deleted`; on CPU they pass silently, which
is why the static check exists.
"""

import jax
import numpy as np


class LeakyMemoEngine:
    """Donating handle memoized in an attr, dispatched elsewhere, donated
    buffer read after — both the provider-call and the direct-subscript
    dispatch shapes."""

    def __init__(self):
        self._compiled = {}

    def _fwd(self, params, imgs):
        return imgs * 2

    def _compile(self, sig, abstract):
        if sig in self._compiled:
            return self._compiled[sig]
        lowered = jax.jit(self._fwd, donate_argnums=(1,)).lower(
            abstract, abstract
        )
        compiled = lowered.compile()
        self._compiled[sig] = compiled
        return compiled

    def infer(self, sig, abstract, params, imgs):
        fn = self._compile(sig, abstract)
        out = fn(params, imgs)
        return out, imgs.mean()  # BUG: imgs was donated to fn(...)

    def infer_direct(self, sig, params, imgs):
        out = self._compiled[sig](params, imgs)
        return out, imgs.sum()  # BUG: donated through the memo table


class SafeMemoEngine:
    """Same memoized dispatch, host-copy discipline: the donated device
    buffer is born fresh per call and never re-read."""

    def __init__(self):
        self._compiled = {}

    def _fwd(self, params, imgs):
        return imgs * 2

    def _compile(self, sig, abstract):
        if sig in self._compiled:
            return self._compiled[sig]
        lowered = jax.jit(self._fwd, donate_argnums=(1,)).lower(
            abstract, abstract
        )
        compiled = lowered.compile()
        self._compiled[sig] = compiled
        return compiled

    def infer(self, sig, abstract, params, imgs):
        src = np.asarray(imgs)  # host copy outlives the donation
        fn = self._compile(sig, abstract)
        dev = jax.numpy.asarray(src)
        out = fn(params, dev)
        return out, src.mean()  # reads the HOST copy, never the donated buffer
