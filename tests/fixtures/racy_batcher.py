"""Lockset regression fixture: a trimmed DynamicBatcher with ONE guard
removed, next to its correctly-locked twin.

tests/test_analysis.py runs glom-lint's lockset checker over this file
and asserts the deliberately-unlocked queue mutation in RacyBatcher is
flagged (file:line) while LockedBatcher stays clean — the static half of
the acceptance pair; tests/test_races.py is the runtime half (the same
shape of bug demonstrably loses updates under the seeded interleaving
harness). NOT importable production code: it exists to be linted.
"""

import threading


class RacyBatcher:
    """The bug shape: pending/n_shed are mutated by the worker thread AND
    read by callers, but the pending append slipped out of the lock."""

    def __init__(self):
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self.pending = []
        self.n_shed = 0
        self._thread = threading.Thread(target=self._worker, daemon=True)

    def submit(self, req):
        self.pending.append(req)  # BUG: unlocked queue mutation

    def _worker(self):
        while not self._stop.is_set():
            with self._lock:
                if self.pending:
                    self.pending.pop()
            with self._lock:
                self.n_shed += 1

    def stats(self):
        with self._lock:
            return {"n_shed": self.n_shed, "pending": len(self.pending)}


class LockedBatcher:
    """The same class with every shared access behind the lock."""

    def __init__(self):
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self.pending = []
        self.n_shed = 0
        self._thread = threading.Thread(target=self._worker, daemon=True)

    def submit(self, req):
        with self._lock:
            self.pending.append(req)

    def _worker(self):
        while not self._stop.is_set():
            with self._lock:
                if self.pending:
                    self.pending.pop()
                self.n_shed += 1

    def stats(self):
        with self._lock:
            return {"n_shed": self.n_shed, "pending": len(self.pending)}
