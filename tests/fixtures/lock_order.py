"""Seeded acceptance pair for the lock-order checker (analysis/lockset.py
LockOrder): DeadlockyCoordinator takes its two locks in BOTH orders — the
textbook AB/BA deadlock — while OrderedCoordinator does the same work with
one global order and must scan clean. The multi-engine DynamicBatcher
(serve/batcher.py) is the shipped pattern this pair protects: _engine_lock
before _counter_lock, never the reverse.

NOT imported by production code; tests/test_analysis.py runs the checker
over this file and asserts the cycle is flagged at file:line on the racy
class only. The `transfer` / `audit` pair below WILL deadlock under real
threads the moment their critical sections interleave — which is exactly
why the runtime race harness can't be the only guard: a deadlock hangs
the suite instead of failing it.
"""

import threading


class DeadlockyCoordinator:
    """AB in transfer(), BA in audit() — the cycle the checker must flag.
    audit() also reaches the cycle TRANSITIVELY: it calls _tally() while
    holding the stats lock, and _tally() takes the ledger lock."""

    def __init__(self):
        self._ledger_lock = threading.Lock()
        self._stats_lock = threading.Lock()
        self.ledger = {}
        self.stats = {}

    def transfer(self, key, amount):
        with self._ledger_lock:           # ledger -> stats
            self.ledger[key] = self.ledger.get(key, 0) + amount
            with self._stats_lock:
                self.stats["n_transfers"] = (
                    self.stats.get("n_transfers", 0) + 1
                )

    def _tally(self):
        with self._ledger_lock:
            return sum(self.ledger.values())

    def audit(self):
        with self._stats_lock:            # stats -> ledger (via _tally)
            total = self._tally()
            self.stats["audited_total"] = total
            return dict(self.stats)


class OrderedCoordinator:
    """The clean twin: identical behavior, ONE order (ledger -> stats
    everywhere; audit snapshots under ledger first). Must scan clean."""

    def __init__(self):
        self._ledger_lock = threading.Lock()
        self._stats_lock = threading.Lock()
        self.ledger = {}
        self.stats = {}

    def transfer(self, key, amount):
        with self._ledger_lock:           # ledger -> stats
            self.ledger[key] = self.ledger.get(key, 0) + amount
            with self._stats_lock:
                self.stats["n_transfers"] = (
                    self.stats.get("n_transfers", 0) + 1
                )

    def audit(self):
        with self._ledger_lock:           # same order: ledger -> stats
            total = sum(self.ledger.values())
            with self._stats_lock:
                self.stats["audited_total"] = total
                return dict(self.stats)
