"""schema-emit trace-context regression fixture: one serve emit site that
forgot the v6 trace context, next to its correctly-stamped twins.

tests/test_analysis.py runs glom-lint's schema-emit checker over this
file and asserts the bare `dispatch` emit in `bad_dispatch_emit` is
flagged (key "trace-context", file:line) while the three good shapes —
an explicit trace_id (even null: explicitly untraced lints), the batch
`trace_ids` form, and a `**fields` splat that may carry the context —
stay clean. NOT importable production code: it exists to be linted.
"""


def emit_serve(writer, rec, kind="serve"):  # the emitter family's shape
    return rec


def bad_dispatch_emit(writer):
    # BUG: a request-scoped serve event with no trace context key — the
    # records this site writes can never join their request's tree, and
    # the runtime linter rejects every one of them.
    emit_serve(
        writer,
        {"event": "dispatch", "engine": "engine0", "latency_ms": 1.0},
    )


def good_singular_emit(writer, ticket):
    emit_serve(
        writer,
        {
            "event": "resolve",
            "iters_total": 6,
            "trace_id": ticket.trace_id,  # null when untraced — still fine
            "slo_class": ticket.slo_class,  # v11: null when classless
        },
    )


def good_batch_emit(writer, batch):
    emit_serve(
        writer,
        {
            "event": "continuation",
            "n_stragglers": len(batch),
            "trace_ids": [it.trace_id for it in batch],
        },
    )


def good_splat_emit(writer, fields):
    # A **splat may carry the context (the batcher's tfields pattern);
    # the static rule defers to the runtime linter here.
    emit_serve(writer, {"event": "shed", "reason": "queue-full", **fields})


def good_unscoped_emit(writer):
    # Not a request-scoped event: no trace context required.
    emit_serve(writer, {"event": "warmup", "bucket": 4})
