"""Seeded fixture pair for the lock-order CROSS-OBJECT acquisition
graph (glom_tpu/analysis/lockset.py LockOrder + analysis/project.py).

The blind spot this pair pins: each class on its own is single-lock and
perfectly consistent — the deadlock only exists in the CROSS-OBJECT
order. `Cache.lookup` holds Cache._lock and calls into the typed
`Pool`, whose `release` takes Pool._lock and calls back into the cache
(xmod_lock_order_pool.py), taking Cache._lock again:

    Cache._lock -> Pool._lock      (here, lookup)
    Pool._lock  -> Cache._lock     (pool module, release)

A per-class pass sees no pair of locks in either class. The global
(class, lock) graph must close the cycle and flag it with the reverse
edge's file:line. `QuietCache`/`QuietPool` are the clean twins: the
same typed calls, but no lock is ever held across them.

LINT FIXTURE: parsed, never imported (lint both files together).
"""

import threading

from xmod_lock_order_pool import Pool, QuietPool


class Cache:
    def __init__(self, pool: Pool):
        self._lock = threading.Lock()
        self.pool = pool
        self.entries = {}

    def evict(self, key):
        with self._lock:
            self.entries.pop(key, None)

    def lookup(self, key):
        with self._lock:
            # BUG half 1: Cache._lock is held while entering the pool,
            # which acquires Pool._lock (and then calls back into
            # evict — the opposite order).
            self.pool.release(key)
            return self.entries.get(key)


class QuietCache:
    def __init__(self, pool: QuietPool):
        self._lock = threading.Lock()
        self.pool = pool
        self.entries = {}

    def lookup(self, key):
        with self._lock:
            hit = self.entries.get(key)
        if hit is None:
            self.pool.release(key)  # lock released first: no edge
        return hit
