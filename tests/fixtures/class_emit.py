"""schema-emit class-context regression fixture: one serve emit site that
forgot the v11 SLO-class stamp, next to its correctly-stamped twins.

tests/test_analysis.py runs glom-lint's schema-emit checker over this
file and asserts the bare `admit` emit in `bad_admit_emit` is flagged
(key "class-context", file:line) while the three good shapes — an
explicit slo_class (even null: classless lints), a `**detail` splat that
may carry the class, and a non-tenant-scoped event — stay clean. NOT
importable production code: it exists to be linted.
"""


def emit_serve(writer, rec, kind="serve"):  # the emitter family's shape
    return rec


def bad_admit_emit(writer, ticket):
    # BUG: a tenant-scoped serve event with no slo_class key — no
    # per-class rollup, class-scoped SLO rule, or weighted-regret audit
    # can ever attribute the records this site writes, and the runtime
    # linter rejects every one of them at v11.
    emit_serve(
        writer,
        {
            "event": "admit",
            "request_id": ticket.request_id,
            "trace_id": ticket.trace_id,
        },
    )


def good_classed_emit(writer, ticket):
    emit_serve(
        writer,
        {
            "event": "settle",
            "outcome": "served",
            "trace_id": ticket.trace_id,
            "slo_class": ticket.slo_class,  # null when classless — fine
        },
    )


def good_splat_emit(writer, detail):
    # A **splat may carry the class (the batcher's shed-detail pattern);
    # the static rule defers to the runtime linter here.
    emit_serve(writer, {"event": "shed", "reason": "queue-full", **detail})


def good_unscoped_emit(writer):
    # Not a tenant-scoped event: no slo_class required.
    emit_serve(writer, {"event": "ladder", "rung": "capped_iters"})
