"""Companion module for the trace-purity CROSS-MODULE fixture pair
(tests/fixtures/xmod_purity.py).

The helpers here are imported into another module's jit body — the
host side effect in `log_levels` is only a bug BECAUSE of that import,
which is exactly the reachability hop the single-module checker could
not see. This file on its own is clean (nothing here traces anything).

LINT FIXTURE: parsed, never imported.
"""


def log_levels(x):
    """Host print on its argument — harmless at module scope, a silent
    trace-time constant (or a tracer repr) inside a jit body."""
    print("levels", x)
    return x


def scale(x, k):
    """Pure twin: safe to reach from any traced entry."""
    return x * k
