"""Seeded fixture pair for the axis-environment checker
(glom_tpu/analysis/axisenv.py).

`leaky_serve_body` psums over MODEL_AXIS inside a shard_map whose mesh is
('data', 'seq') — a vocabulary-LEGAL axis (the training mesh declares it)
that does not exist in this shard_map's environment: the copy-pasted-
from-training bug the checker exists to catch on CPU. `clean_serve_body`
is the twin with every collective on a declared axis, including one
threaded through the registered-wrapper idiom.

This file is a LINT FIXTURE: it is parsed, never imported (the fake
shard_map below keeps it import-safe anyway).
"""

from glom_tpu.telemetry import counters as tele_counters
from glom_tpu.utils.config import MeshConfig

DATA_AXIS = "data"
SEQ_AXIS = "seq"
MODEL_AXIS = "model"


def shard_map(fn, mesh=None, in_specs=None, out_specs=None):  # noqa: ARG001
    return fn


def P(*axes):  # noqa: ARG001 — spec stand-in, parsed not executed
    return axes


def make_mesh(cfg):
    return cfg


def lax_psum(x, axis):  # pragma: no cover — never called
    del axis
    return x


def _psum_wire(x, axis_name, k):
    """The registered-wrapper idiom the real serve mesh uses."""
    from jax import lax

    tele_counters.record_collective("reduce", 0 * k)
    return lax.psum(x, axis_name)


def build_leaky():
    from jax import lax

    mesh = make_mesh(MeshConfig(data=4, seq=2))
    batch_spec = P(DATA_AXIS)

    def leaky_serve_body(x, y):
        tele_counters.record_collective("reduce", 0)
        part = lax.psum(x, SEQ_AXIS)  # fine: 'seq' is in the mesh
        # BUG: 'model' is a declared axis SOMEWHERE (the training mesh),
        # but not in THIS shard_map's ('data', 'seq') environment.
        tele_counters.record_collective("reduce", 0)
        bad = lax.psum(part, MODEL_AXIS)
        return _psum_wire(bad + y, MODEL_AXIS, 2)  # threaded: also bad

    return shard_map(
        leaky_serve_body,
        mesh=mesh,
        in_specs=(batch_spec, P(DATA_AXIS, SEQ_AXIS)),
        out_specs=P(DATA_AXIS),
    )


def build_clean():
    from jax import lax

    mesh = make_mesh(MeshConfig(data=4, seq=2))
    batch_spec = P(DATA_AXIS)

    def clean_serve_body(x, y):
        tele_counters.record_collective("reduce", 0)
        part = lax.psum(x, SEQ_AXIS)
        total = _psum_wire(part + y, DATA_AXIS, 4)  # threaded: declared
        return total

    return shard_map(
        clean_serve_body,
        mesh=mesh,
        in_specs=(batch_spec, P(DATA_AXIS, SEQ_AXIS)),
        out_specs=P(DATA_AXIS),
    )
