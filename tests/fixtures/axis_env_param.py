"""Seeded fixture pair for the axis-environment checker's OPAQUE-MESH
caller attestation (glom_tpu/analysis/axisenv.py).

The blind spot this pair pins: `_serve_shard_leaky` takes its mesh as an
opaque PARAMETER, and this module ALSO builds a 'model'-carrying
training mesh — so the module-wide MeshConfig union ({data, seq, model})
would attest the wrong environment and miss the bug. The checker must
instead follow the intra-module CALLER (`build_serve_leaky`) to its
`MeshConfig(data=..., seq=...)` and flag the psum over MODEL_AXIS, both
at the direct lax site and through the registered-wrapper threaded axis.
`_serve_shard_clean` is the twin with every collective on a
caller-attested axis. `_opaque_shard` has NO intra-module caller at all
— with the module union in play it attests {data, seq, model} and stays
clean (the fallback contract, unchanged).

This file is a LINT FIXTURE: it is parsed, never imported (the fake
shard_map below keeps it import-safe anyway).
"""

from glom_tpu.telemetry import counters as tele_counters
from glom_tpu.utils.config import MeshConfig

DATA_AXIS = "data"
SEQ_AXIS = "seq"
MODEL_AXIS = "model"


def shard_map(fn, mesh=None, in_specs=None, out_specs=None):  # noqa: ARG001
    return fn


def P(*axes):  # noqa: ARG001 — spec stand-in, parsed not executed
    return axes


def make_mesh(cfg):
    return cfg


def build_train_mesh():
    """The 'model'-carrying training mesh that poisons the module-wide
    union — the reason caller attestation must win over the fallback."""
    return make_mesh(MeshConfig(data=2, seq=2, model=2))


def _psum_wire(x, axis_name, k):
    """The registered-wrapper idiom the real serve mesh uses."""
    from jax import lax

    tele_counters.record_collective("reduce", 0 * k)
    return lax.psum(x, axis_name)


def _serve_shard_leaky(mesh):
    from jax import lax

    def body(x, y):
        part = _psum_wire(x, SEQ_AXIS, 2)  # fine: caller mesh has 'seq'
        # BUG: the module builds a 'model' mesh SOMEWHERE (build_train_
        # mesh), but THIS shard_map's callers only ever pass (data, seq).
        tele_counters.record_collective("reduce", 0)
        bad = lax.psum(part, MODEL_AXIS)
        return _psum_wire(bad + y, MODEL_AXIS, 2)  # threaded: also bad

    return shard_map(
        body,
        mesh=mesh,
        in_specs=(P(DATA_AXIS), P(DATA_AXIS, SEQ_AXIS)),
        out_specs=P(DATA_AXIS),
    )


def build_serve_leaky():
    mesh = make_mesh(MeshConfig(data=4, seq=2))
    return _serve_shard_leaky(mesh)


def _serve_shard_clean(mesh):
    def body(x, y):
        part = _psum_wire(x, SEQ_AXIS, 2)
        return _psum_wire(part + y, DATA_AXIS, 4)

    return shard_map(
        body,
        mesh=mesh,
        in_specs=(P(DATA_AXIS), P(DATA_AXIS, SEQ_AXIS)),
        out_specs=P(DATA_AXIS),
    )


def build_serve_clean():
    mesh = make_mesh(MeshConfig(data=4, seq=2))
    return _serve_shard_clean(mesh)


def _hop_leaky(mesh):
    """Leaky THROUGH a forwarding hop: the only path to a MeshConfig is
    caller -> caller (bounded parameter-to-parameter recursion). Flagged
    only when the checker actually follows the second hop."""
    from jax import lax

    def body(x):
        tele_counters.record_collective("reduce", 0)
        return lax.psum(x, MODEL_AXIS)

    return shard_map(body, mesh=mesh, in_specs=(P(DATA_AXIS),), out_specs=P())


def _forward_mesh(mesh):
    return _hop_leaky(mesh)


def build_serve_forwarded():
    mesh = make_mesh(MeshConfig(data=4, seq=2))
    return _forward_mesh(mesh)


def _opaque_shard(mesh):
    """No intra-module caller: falls back to the module union (which
    includes 'model' via build_train_mesh) — stays clean, the unchanged
    fallback contract."""
    from jax import lax

    def body(x):
        tele_counters.record_collective("reduce", 0)
        return lax.psum(x, MODEL_AXIS)

    return shard_map(body, mesh=mesh, in_specs=(P(DATA_AXIS),), out_specs=P())
