"""Seeded fixture pair for the collective-coverage checker's
hand-rolled-timing rule (glom_tpu/analysis/collectives.py, ISSUE 13).

`leaky_timed_reduce` registers its psum's wire bytes (the PR 2 contract
holds) but brackets the collective with its OWN io_callback clock harness
— exactly the hand-rolled timing the shared wrapper exists to replace:
a private clock discipline the trace-purity audit cannot reason about,
a record shape the schema never sees, and per-shard callback pairs that
drift from counters.CollectiveTimeLog's. `clean_timed_reduce` is the
twin routed through `counters.timed_collective` — the ONE sanctioned
timing route (byte recording + site registry + the full-mode brackets).

This file is a LINT FIXTURE: the test copies its source under a
registration-scope path (parallel/manual.py) and asserts exactly one
hand-rolled-timing finding at the leaky psum. Parsed, never imported
(the stand-ins below keep it import-safe anyway).
"""

import time

from glom_tpu.telemetry import counters as tele_counters

DATA_AXIS = "data"


def io_callback(fn, result_shape, *args):  # pragma: no cover — stand-in
    del result_shape, args
    return fn()


class lax:  # pragma: no cover — stand-in, parsed not executed
    @staticmethod
    def psum(x, axis):
        del axis
        return x


def leaky_timed_reduce(g, k):
    """FLAGGED: record_collective registers the bytes, but the timing is
    a hand-rolled io_callback clock pair around the collective."""
    tele_counters.record_collective(
        "reduce", tele_counters.ring_allreduce_bytes(g, k)
    )
    t0 = io_callback(lambda: time.perf_counter(), None)
    out = lax.psum(g, DATA_AXIS)
    io_callback(lambda: time.perf_counter(), None, t0)
    return out


def clean_timed_reduce(g, k):
    """CLEAN: the shared wrapper owns the bytes, the site registry, and
    (under timing('full', log)) the brackets."""
    return tele_counters.timed_collective(
        "fixture_psum", DATA_AXIS, "reduce",
        tele_counters.ring_allreduce_bytes(g, k),
        lambda x: lax.psum(x, DATA_AXIS), g, collective="psum",
    )
