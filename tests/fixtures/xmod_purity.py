"""Seeded fixture pair for trace-purity CROSS-MODULE reachability
(glom_tpu/analysis/purity.py + analysis/project.py).

The blind spot this pair pins: `step_leaky` is jitted HERE, but the
host `print` it reaches lives in xmod_purity_util.py — a single-module
reachability walk ends at the import boundary and misses it. The
whole-program walk must follow the imported call and flag the print AT
ITS OWN file:line in the util module. `step_clean` reaches only the
pure twin and stays green.

LINT FIXTURE: parsed, never imported (lint both files together:
run([xmod_purity.py, xmod_purity_util.py])).
"""

import jax

from xmod_purity_util import log_levels, scale


def step_leaky(x):
    # BUG (flagged in xmod_purity_util.py, at the print): the imported
    # helper host-prints its argument, which is a tracer here.
    return log_levels(x) * 2


def step_clean(x):
    return scale(x, 2)


fast_leaky = jax.jit(step_leaky)
fast_clean = jax.jit(step_clean)
