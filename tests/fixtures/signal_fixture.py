"""Seeded fixture pair for the signal-safety checker (glom-lint).

DeadlockySignalDumper is the PR 6 hazard, distilled: its SIGTERM handler
path acquires a NON-reentrant threading.Lock (the paused main thread may
hold it — a paused owner never releases), joins its worker with no
timeout, and blocks on a queue get. SafeSignalDumper is the twin built
the way tracing/flight.py actually ships: RLock, bounded join,
non-blocking queue drain. The checker must flag every Deadlocky site at
file:line and stay silent on the twin — pinned by tests/test_analysis.py.

NOT importable production code — exercised as AST text only.
"""

import queue
import signal
import threading
import time


class DeadlockySignalDumper:
    def __init__(self):
        self._lock = threading.Lock()  # non-reentrant: the hazard
        self._q = queue.Queue()
        self._worker = threading.Thread(target=self._drain, daemon=True)

    def install(self):
        signal.signal(signal.SIGTERM, self._on_term)

    def _on_term(self, signum, frame):
        self._flush()
        self._worker.join()  # unbounded: a wedged worker stalls the exit
        time.sleep(1.0)  # unbounded-ish stall inside the grace window

    def _flush(self):
        with self._lock:  # main thread may be paused HOLDING this
            item = self._q.get()  # blocking get: no timeout
            return item

    def _drain(self):
        while True:
            self._q.get()


class SafeSignalDumper:
    def __init__(self):
        self._lock = threading.RLock()  # reentrant: handler-safe
        self._q = queue.Queue()
        self._worker = threading.Thread(target=self._drain, daemon=True)

    def install(self):
        signal.signal(signal.SIGTERM, self._on_term)

    def _on_term(self, signum, frame):
        self._flush()
        self._worker.join(timeout=5.0)  # bounded: the grace-window form

    def _flush(self):
        with self._lock:  # RLock: the paused owner IS this thread
            try:
                return self._q.get_nowait()
            except queue.Empty:
                return None

    def _drain(self):
        while True:
            self._q.get(timeout=1.0)
