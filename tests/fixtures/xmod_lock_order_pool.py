"""Companion module for the lock-order CROSS-OBJECT fixture pair
(tests/fixtures/xmod_lock_order.py).

`Pool.release` acquires Pool._lock and then calls back into the typed
`Cache` (string-annotated through the TYPE_CHECKING shim — the real
serve modules' import-cycle idiom), closing the cross-module,
cross-class cycle Cache._lock -> Pool._lock -> Cache._lock.
`QuietPool` is the clean twin: it takes its own lock and calls nothing.

LINT FIXTURE: parsed, never imported.
"""

import threading
from typing import TYPE_CHECKING

if TYPE_CHECKING:  # import cycle: the cache module imports this one
    from xmod_lock_order import Cache


class Pool:
    def __init__(self, cache: "Cache"):
        self._lock = threading.Lock()
        self.cache = cache
        self.rows = {}

    def release(self, key):
        with self._lock:
            # BUG half 2: Pool._lock is held while re-entering the
            # cache, which acquires Cache._lock (see Cache.lookup for
            # the opposite order).
            self.cache.evict(key)


class QuietPool:
    def __init__(self):
        self._lock = threading.Lock()
        self.rows = {}

    def release(self, key):
        with self._lock:
            self.rows.pop(key, None)
