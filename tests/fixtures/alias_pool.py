"""Seeded acceptance pair for donation-safety's aliased-pool pinning
rule (analysis/donation.py, ISSUE 16): with in-place pool aliasing the
pool's write-back DONATES the shared buffer on its own seam, so any
dispatch still reading it must hold a read pin — `acquire_read()` /
`release_read()` — to force the seam onto the copy-on-write fallback.

LeakyPoolEngine feeds a bare `pool.buffer()` into its donating compiled
dispatch (both the bound-name and the inline-call shapes) — on an
aliasing pool a concurrent write-back invalidates that buffer
mid-dispatch, a race no CPU test reproduces. SafePoolEngine pins through
acquire_read()/release_read() around the same dispatch and must scan
clean; so must its compile-time `pool.buffer().dtype` probe (a read that
never reaches a dispatch — the engine's real warmup shape).

NOT imported by production code; tests/test_analysis.py runs the checker
over this file and asserts the unpinned dispatches are flagged at
file:line on the leaky class only.
"""

import jax


class LeakyPoolEngine:
    """Bare buffer() into a donating dispatch — flagged twice (named and
    inline), the exact hazard pool aliasing's read-pin seam exists for."""

    def __init__(self, pool):
        self.pool = pool
        self._compiled = {}

    def _fwd(self, params, buf, idx):
        return buf[idx] * 2

    def _compile(self, sig, abstract):
        if sig in self._compiled:
            return self._compiled[sig]
        compiled = jax.jit(self._fwd, donate_argnums=(0,)).lower(
            abstract, abstract, abstract
        ).compile()
        self._compiled[sig] = compiled
        return compiled

    def infer(self, sig, abstract, params, idx):
        fn = self._compile(sig, abstract)
        buf = self.pool.buffer()  # BUG: no read pin
        return fn(params, buf, idx)

    def infer_inline(self, sig, abstract, params, idx):
        fn = self._compile(sig, abstract)
        return fn(params, self.pool.buffer(), idx)  # BUG: no read pin


class SafePoolEngine:
    """Same dispatch, pinned reads: acquire_read() holds the pool's CoW
    fallback open for the dispatch's lifetime."""

    def __init__(self, pool):
        self.pool = pool
        self._compiled = {}

    def _fwd(self, params, buf, idx):
        return buf[idx] * 2

    def _compile(self, sig, abstract):
        if sig in self._compiled:
            return self._compiled[sig]
        compiled = jax.jit(self._fwd, donate_argnums=(0,)).lower(
            abstract, abstract, abstract
        ).compile()
        self._compiled[sig] = compiled
        return compiled

    def infer(self, sig, abstract, params, idx):
        fn = self._compile(sig, abstract)
        buf = self.pool.acquire_read()
        try:
            return fn(params, buf, idx)
        finally:
            self.pool.release_read()

    def probe_dtype(self, sig):
        # Compile-time probe: a bare buffer() read that never reaches a
        # dispatch stays clean.
        return self.pool.buffer().dtype
