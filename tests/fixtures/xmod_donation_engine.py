"""Companion provider module for the donation-safety CROSS-MODULE
fixture pair (tests/fixtures/xmod_donation.py).

`Engine` memoizes a donating compiled handle (`self._step`, donates
position 1) and exposes a provider method that returns it — the
serve/engine.py shape. Nothing in THIS module misuses the handle; the
hazard only exists at the consumer, one import away.

LINT FIXTURE: parsed, never imported.
"""

import jax


class Engine:
    def __init__(self):
        self._step = jax.jit(lambda p, x: x * 2, donate_argnums=(1,))

    def compile_step(self):
        """Provider: returns the donating handle (the `_compile` shape —
        the taint must survive the return into a typed caller)."""
        return self._step
