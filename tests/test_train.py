"""Trainer tests: loss decreases, temporal mode semantics, objective math."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from glom_tpu.data import shapes_dataset
from glom_tpu.models.core import glom_forward, init_glom
from glom_tpu.train import (
    Trainer,
    default_recon_index,
    denoise_loss,
    init_denoise,
    reconstruct,
    temporal_rollout,
)
from glom_tpu.utils.config import GlomConfig, TrainConfig

CFG = GlomConfig(dim=16, levels=3, image_size=8, patch_size=2)


def test_default_recon_index_matches_readme():
    """README hardcodes all_levels[7] for L=6 (T=12)."""
    assert default_recon_index(12) == 7


def test_denoise_loss_finite_and_differentiable():
    params = init_denoise(jax.random.PRNGKey(0), CFG)
    img = jnp.asarray(np.random.default_rng(0).normal(size=(2, 3, 8, 8)), jnp.float32)
    noise = jnp.asarray(np.random.default_rng(1).normal(size=(2, 3, 8, 8)), jnp.float32)
    loss, grads = jax.value_and_grad(denoise_loss)(params, img, noise, CFG)
    assert np.isfinite(float(loss))
    leaves = jax.tree_util.tree_leaves(grads)
    assert all(np.isfinite(np.asarray(g)).all() for g in leaves)
    # the recon head must receive gradient
    assert np.abs(np.asarray(grads.to_pixels.w)).max() > 0


def test_truncated_iters_equals_full_stack_selection():
    """Scanning k iters and taking the top level == selecting index k from the
    full return_all stack (the reference recipe's math)."""
    params = init_denoise(jax.random.PRNGKey(0), CFG)
    img = jnp.asarray(np.random.default_rng(2).normal(size=(1, 3, 8, 8)), jnp.float32)
    k = default_recon_index(CFG.default_iters)
    full = glom_forward(params.glom, img, CFG, return_all=True)
    short = glom_forward(params.glom, img, CFG, iters=k)
    np.testing.assert_allclose(
        np.asarray(full[k]), np.asarray(short), rtol=1e-5, atol=1e-6
    )


def test_unrolled_scan_matches_rolled():
    """scan_unroll is a pure scheduling change: loss AND grads must match the
    rolled scan exactly (same ops, same order, straight-line vs while loop)."""
    params = init_denoise(jax.random.PRNGKey(0), CFG)
    img = jnp.asarray(np.random.default_rng(3).normal(size=(2, 3, 8, 8)), jnp.float32)
    noise = jnp.asarray(np.random.default_rng(4).normal(size=(2, 3, 8, 8)), jnp.float32)
    vg = jax.value_and_grad(denoise_loss)
    loss_r, grads_r = vg(params, img, noise, CFG)
    loss_u, grads_u = vg(params, img, noise, CFG, unroll=True)
    np.testing.assert_allclose(float(loss_r), float(loss_u), rtol=1e-6)
    for gr, gu in zip(
        jax.tree_util.tree_leaves(grads_r), jax.tree_util.tree_leaves(grads_u)
    ):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gu), rtol=1e-5, atol=1e-6)


def test_training_loss_decreases():
    """BASELINE config-2 style smoke: a few steps of denoise training on
    structured synthetic images must reduce the loss."""
    tcfg = TrainConfig(batch_size=4, learning_rate=3e-3, noise_std=0.3, seed=0)
    trainer = Trainer(CFG, tcfg)
    data = shapes_dataset(4, CFG.image_size, seed=0)
    history = trainer.fit(data, num_steps=30, log_every=1)
    first = np.mean([h["loss"] for h in history[:3]])
    last = np.mean([h["loss"] for h in history[-3:]])
    assert np.isfinite(last)
    assert last < first, f"loss did not decrease: {first} -> {last}"


def test_grad_accum_matches_full_batch():
    """grad_accum=A must produce the SAME update as the full-batch step:
    the mean-of-microbatch-means equals the full-batch mean exactly, so
    identical seeds/batches give identical parameters after a step."""
    import dataclasses

    from glom_tpu.train.trainer import create_train_state, make_train_step

    tcfg1 = TrainConfig(batch_size=4, learning_rate=1e-3, iters=2,
                        recon_iter_index=2)
    tcfg2 = dataclasses.replace(tcfg1, grad_accum=2)
    img = jnp.asarray(
        np.random.default_rng(0).normal(size=(4, 3, 8, 8)), jnp.float32
    )
    rng = jax.random.PRNGKey(7)

    states = []
    for tcfg in (tcfg1, tcfg2):
        state, opt = create_train_state(jax.random.PRNGKey(0), CFG, tcfg)
        step = jax.jit(make_train_step(CFG, tcfg, opt))
        state, metrics = step(state, img, rng)
        assert np.isfinite(float(metrics["loss"]))
        states.append(state)
    for a, b in zip(
        jax.tree_util.tree_leaves(states[0].params),
        jax.tree_util.tree_leaves(states[1].params),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7
        )


def test_grad_accum_must_divide_batch():
    import dataclasses

    from glom_tpu.train.trainer import create_train_state, make_train_step

    tcfg = dataclasses.replace(
        TrainConfig(batch_size=4, iters=2, recon_iter_index=2), grad_accum=3
    )
    _, opt = create_train_state(jax.random.PRNGKey(0), CFG, tcfg)
    with pytest.raises(ValueError, match="grad_accum"):
        make_train_step(CFG, tcfg, opt)


class TestVjpRouting:
    """resolve_vjp_path / resolve_training_route (round-5): a supported
    batch must never ship the below-baseline scan path when exact
    grad-accum recovers the fused-loop VJP (round-4 batch curve: batch 128
    measured 3489 col-iters/s on the scan path vs 4255 for batch-64
    fused-loop microbatches), and the decision must be visible in the
    trainer's metric records."""

    FLAGSHIP = GlomConfig(dim=512, levels=6, image_size=224, patch_size=14)

    @pytest.fixture
    def on_tpu(self, monkeypatch):
        from glom_tpu.models import core

        monkeypatch.setattr(core, "_on_tpu", lambda: True)

    def test_flagship_batches(self, on_tpu):
        from glom_tpu.models.core import resolve_vjp_path

        kw = dict(use_pallas=True, itemsize=2)
        assert resolve_vjp_path(self.FLAGSHIP, 64, 7, **kw) == "fused_loop"
        assert resolve_vjp_path(self.FLAGSHIP, 96, 7, **kw) == "fused_loop"
        # batch 128's non-remat residual stack exceeds the budget -> scan
        assert resolve_vjp_path(self.FLAGSHIP, 128, 7, **kw).startswith("scan_")
        # remat drops the pre-activation residuals: batch 128 fits directly
        assert (
            resolve_vjp_path(self.FLAGSHIP, 128, 7, remat=True, **kw)
            == "fused_loop"
        )
        # scan_only (the manual shard_map bodies) never reports fused_loop
        assert resolve_vjp_path(
            self.FLAGSHIP, 64, 7, scan_only=True, **kw
        ).startswith("scan_")

    def test_batch128_auto_accum(self, on_tpu):
        from glom_tpu.train.trainer import resolve_training_route

        tcfg = TrainConfig(
            batch_size=128, use_pallas=True, compute_dtype="bfloat16"
        )
        assert resolve_training_route(self.FLAGSHIP, tcfg) == (2, "fused_loop")
        # batch 64 needs no routing
        tcfg64 = TrainConfig(
            batch_size=64, use_pallas=True, compute_dtype="bfloat16"
        )
        assert resolve_training_route(self.FLAGSHIP, tcfg64) == (1, "fused_loop")

    def test_explicit_accum_honored(self, on_tpu):
        import dataclasses

        from glom_tpu.train.trainer import resolve_training_route

        tcfg = dataclasses.replace(
            TrainConfig(batch_size=128, use_pallas=True, compute_dtype="bfloat16"),
            grad_accum=4,
        )
        accum, path = resolve_training_route(self.FLAGSHIP, tcfg)
        assert accum == 4 and path == "fused_loop"

    def test_explicit_accum_one_is_pinned(self, on_tpu):
        """grad_accum=1 EXPLICIT is the supported auto-routing opt-out
        (ADVICE round 5): batch 128 with pinned accum=1 must ship the
        single-pass scan step, NOT the auto-split that None (the default)
        would route to."""
        import dataclasses

        from glom_tpu.train.trainer import resolve_training_route

        auto = TrainConfig(
            batch_size=128, use_pallas=True, compute_dtype="bfloat16"
        )
        assert auto.grad_accum is None  # the default IS the auto sentinel
        assert resolve_training_route(self.FLAGSHIP, auto) == (2, "fused_loop")
        pinned = dataclasses.replace(auto, grad_accum=1)
        accum, path = resolve_training_route(self.FLAGSHIP, pinned)
        assert accum == 1 and path.startswith("scan_")

    def test_scan_only_excludes_fused_loop_and_auto_accum(self, on_tpu):
        """The GSPMD DistributedTrainer build passes scan_only=True
        (ADVICE round 5, medium): the whole-loop Pallas custom_vjp has no
        GSPMD partitioning rule, so the sharded step must neither resolve
        to it nor auto-split the global batch chasing it — even at shapes
        where the single-chip heuristics WOULD fuse."""
        from glom_tpu.train.trainer import (
            create_train_state,
            make_train_step,
            resolve_training_route,
        )

        tcfg = TrainConfig(
            batch_size=128, use_pallas=True, compute_dtype="bfloat16"
        )
        # sanity: without scan_only this shape auto-routes to the loop
        assert resolve_training_route(self.FLAGSHIP, tcfg) == (2, "fused_loop")
        accum, path = resolve_training_route(
            self.FLAGSHIP, tcfg, scan_only=True
        )
        assert accum == 1 and path.startswith("scan_")
        # and the built step fn (no arrays materialized) reports the same
        _, opt = create_train_state(
            jax.random.PRNGKey(0), CFG, TrainConfig(batch_size=4, iters=2,
                                                    recon_iter_index=2)
        )
        step = make_train_step(self.FLAGSHIP, tcfg, opt, scan_only=True)
        assert step.grad_accum == 1 and step.vjp_path.startswith("scan_")

    def test_trainer_metrics_carry_route(self):
        """Off-TPU everything resolves to scan_dense — but the route must
        still be stamped into every step's metrics next to the loss."""
        tcfg = TrainConfig(batch_size=4, iters=2, recon_iter_index=2)
        trainer = Trainer(CFG, tcfg)
        img = jnp.asarray(
            np.random.default_rng(3).normal(size=(4, 3, 8, 8)), jnp.float32
        )
        m = trainer.step(img)
        assert m["vjp_path"] == "scan_dense"
        assert m["grad_accum"] == 1


def test_lr_schedules():
    """Schedule construction + shape: cosine decays toward the floor,
    warmup starts at 0 and peaks at the configured lr; training under a
    schedule still reduces the loss."""
    import dataclasses

    from glom_tpu.train.trainer import make_lr_schedule

    base = TrainConfig(learning_rate=1e-2, schedule_steps=100)
    assert make_lr_schedule(base) == 1e-2  # constant -> plain float

    cos = make_lr_schedule(dataclasses.replace(base, lr_schedule="cosine"))
    assert float(cos(0)) == pytest.approx(1e-2)
    assert float(cos(100)) == pytest.approx(0.0, abs=1e-9)

    warm = make_lr_schedule(
        dataclasses.replace(
            base, lr_schedule="warmup_cosine", warmup_steps=10,
            lr_final_fraction=0.1,
        )
    )
    assert float(warm(0)) == pytest.approx(0.0, abs=1e-6)
    assert float(warm(10)) == pytest.approx(1e-2, rel=1e-3)
    assert float(warm(100)) == pytest.approx(1e-3, rel=1e-2)

    with pytest.raises(ValueError, match="lr_schedule"):
        make_lr_schedule(dataclasses.replace(base, lr_schedule="linear"))

    tcfg = TrainConfig(
        batch_size=4, learning_rate=3e-3, noise_std=0.3,
        lr_schedule="warmup_cosine", warmup_steps=3, schedule_steps=30,
    )
    trainer = Trainer(CFG, tcfg)
    history = trainer.fit(
        shapes_dataset(4, CFG.image_size, seed=0), num_steps=30, log_every=1
    )
    assert history[-1]["loss"] < history[0]["loss"]


def test_reconstruct_shape():
    params = init_denoise(jax.random.PRNGKey(0), CFG)
    img = jnp.zeros((2, 3, 8, 8))
    out = reconstruct(params, img, CFG)
    assert out.shape == img.shape


class TestTemporal:
    def test_rollout_matches_sequential_calls(self):
        """The scanned video loop == the reference's python frame loop."""
        params = init_glom(jax.random.PRNGKey(3), CFG)
        frames = jnp.asarray(
            np.random.default_rng(4).normal(size=(3, 2, 3, 8, 8)), jnp.float32
        )
        rolled = temporal_rollout(params, frames, CFG, iters=2)

        levels = None
        for i in range(3):
            levels = glom_forward(params, frames[i], CFG, iters=2, levels=levels)
            np.testing.assert_allclose(
                np.asarray(rolled[i]), np.asarray(levels), rtol=1e-4, atol=1e-5
            )

    def test_detach_truncates_bptt(self):
        """With detach, frame-2 loss must not produce gradients w.r.t. frame-1
        inputs beyond the carried state — init_levels still gets grads from
        frame 0 (reference calls frame 0 with levels=None)."""
        params = init_glom(jax.random.PRNGKey(3), CFG)
        frames = jnp.asarray(
            np.random.default_rng(5).normal(size=(2, 1, 3, 8, 8)), jnp.float32
        )

        def loss_first_frame_only(p):
            out = temporal_rollout(p, frames, CFG, iters=1)
            return jnp.mean(out[0] ** 2)

        g = jax.grad(loss_first_frame_only)(params)
        assert np.abs(np.asarray(g.init_levels)).max() > 0
