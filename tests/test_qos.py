"""Multi-tenant QoS (glom_tpu/serve/qos.py, ISSUE 19, docs/SERVING.md
"SLO classes").

The tier-1 locks:

  * the starvation floor is a hard arithmetic bound, not a hint: under
    sustained all-class overload a backlogged class's pick share is
    >= slo_starvation_floor, and premium takes the remainder;
  * per-class lanes are per-class BACKPRESSURE — a batch flood sheds
    batch (and only batch) while premium admission stays open;
  * EXACT per-class ticket conservation (served + shed + failed ==
    requests, per class) across failover and two-tier continuations;
  * a classless config is the PR 18 scheduler byte-for-byte: plain
    queue.Queue, no classes/class_scheduler summary nests, the same
    shed message — the bit-parity pin;
  * low-class SLO breaches are NON-BINDING for the elastic policy
    (audit.binding_breaches) and regret is priced per class weight
    (regret_weighted) — both replayed from the stamped evidence alone.
"""

import queue
import types

import numpy as np
import pytest

from glom_tpu.resilience.ladder import (
    BUCKET_CAP,
    CAPPED_ITERS,
    SHED,
    class_rungs,
)
from glom_tpu.serve.batcher import DynamicBatcher, QueueFullError
from glom_tpu.serve.qos import (
    ClassQueues,
    class_slo_rules,
    parse_slo_class,
    resolve_slo_classes,
)
from glom_tpu.telemetry import schema
from glom_tpu.telemetry.aggregate import SLOMonitor, parse_slo, split_slo_rule
from glom_tpu.telemetry.audit import (
    audit_records,
    binding_breaches,
    policy_action,
    rule_class,
)
from glom_tpu.utils.config import ServeConfig

CLASSES = ("premium:weight=8,p99_ms=150", "standard:weight=2",
           "batch:weight=1,shed_rate=0.5")


def _scfg(**kw):
    kw.setdefault("slo_classes", CLASSES)
    kw.setdefault("queue_depth", 8)
    return ServeConfig(buckets=(1, 2, 4), max_batch=4, max_delay_ms=5.0, **kw)


# ---------------------------------------------------------------------------
# spec parsing / validation
# ---------------------------------------------------------------------------


class TestSpecParsing:
    def test_full_spec_roundtrip(self):
        c = parse_slo_class(
            "premium:weight=8,p99_ms=150,shed_rate=0.1,queue_depth=4"
        )
        assert c.name == "premium" and c.weight == 8.0
        assert c.p99_ms == 150.0 and c.shed_rate == 0.1
        assert c.queue_depth == 4

    def test_bare_name_defaults(self):
        c = parse_slo_class("batch")
        assert c.weight == 1.0 and c.p99_ms is None
        assert c.queue_depth is None

    @pytest.mark.parametrize("spec", [
        "", ":weight=1", "p:weight=0", "p:weight=-1", "p:bogus=3",
        "p:weight", "p:p99_ms=0", "p:shed_rate=1.5", "p:queue_depth=0",
        "p:queue_depth=1.5", "p:weight=abc",
    ])
    def test_malformed_specs_are_loud(self, spec):
        with pytest.raises(ValueError):
            parse_slo_class(spec)

    def test_priority_is_descending_weight_declaration_ties(self):
        spec = resolve_slo_classes(_scfg(
            slo_classes=("a:weight=2", "b:weight=8", "c:weight=2")
        ))
        assert spec.names == ("b", "a", "c")  # ties keep declaration order

    def test_default_shed_order_is_reversed_priority(self):
        spec = resolve_slo_classes(_scfg())
        assert spec.names == ("premium", "standard", "batch")
        assert spec.shed_order == ("batch", "standard", "premium")

    def test_explicit_shed_order_must_be_permutation(self):
        spec = resolve_slo_classes(_scfg(
            slo_shed_order=("standard", "batch", "premium")
        ))
        assert spec.shed_order == ("standard", "batch", "premium")
        with pytest.raises(ValueError, match="permutation"):
            resolve_slo_classes(_scfg(slo_shed_order=("batch", "premium")))

    def test_default_class_prefers_standard_then_top(self):
        assert resolve_slo_classes(_scfg()).default_class == "standard"
        spec = resolve_slo_classes(_scfg(slo_classes=("p:weight=8", "b")))
        assert spec.default_class == "p"
        with pytest.raises(ValueError, match="not a declared class"):
            resolve_slo_classes(_scfg(slo_default_class="gold"))

    def test_duplicate_names_rejected(self):
        with pytest.raises(ValueError, match="duplicate"):
            resolve_slo_classes(_scfg(slo_classes=("a", "a:weight=2")))

    def test_floor_must_leave_top_class_capacity(self):
        with pytest.raises(ValueError, match="floor"):
            resolve_slo_classes(_scfg(slo_starvation_floor=0.5))

    def test_resolve_takes_default_and_rejects_undeclared(self):
        spec = resolve_slo_classes(_scfg())
        assert spec.resolve(None) == "standard"
        assert spec.resolve("batch") == "batch"
        with pytest.raises(ValueError, match="not declared"):
            spec.resolve("gold")

    def test_class_slo_rules_vocabulary(self):
        rules = class_slo_rules(resolve_slo_classes(_scfg()))
        assert rules == {"p99_ms[premium]": 150.0, "shed_rate[batch]": 0.5}
        # Every generated rule parses in the monitor's vocabulary.
        for name, thresh in rules.items():
            assert parse_slo(f"{name}={thresh}") == (name, thresh)

    def test_low_classes_is_shed_order_head(self):
        spec = resolve_slo_classes(_scfg())
        assert spec.low_classes() == frozenset({"batch"})
        solo = resolve_slo_classes(_scfg(slo_classes=("only",)))
        assert solo.low_classes() == frozenset()

    def test_classless_config_resolves_none(self):
        assert resolve_slo_classes(_scfg(slo_classes=None)) is None


class TestClassRungs:
    def test_classless_and_solo_keep_pr18_gates(self):
        assert class_rungs(0, 1) == (CAPPED_ITERS, SHED)

    def test_shed_order_positions_select_gates(self):
        # batch (position 0): sheds a rung EARLY, degrades normally.
        assert class_rungs(0, 3) == (CAPPED_ITERS, BUCKET_CAP)
        # standard (middle): the classless semantics.
        assert class_rungs(1, 3) == (CAPPED_ITERS, SHED)
        # premium (last): holds the full route one rung longer.
        assert class_rungs(2, 3) == (BUCKET_CAP, SHED)

    def test_spec_gates_follow_shed_positions(self):
        spec = resolve_slo_classes(_scfg())
        assert spec.shed_rung("batch") < spec.shed_rung("premium")
        assert spec.degrade_rung("premium") > spec.degrade_rung("batch")

    def test_position_bounds_are_loud(self):
        with pytest.raises(ValueError):
            class_rungs(3, 3)


# ---------------------------------------------------------------------------
# the weighted-fair lane
# ---------------------------------------------------------------------------


def _item(cls):
    return types.SimpleNamespace(slo_class=cls)


def _queues(floor=0.1, depth=64, classes=CLASSES):
    spec = resolve_slo_classes(
        _scfg(slo_classes=classes, slo_starvation_floor=floor)
    )
    return ClassQueues(spec, default_depth=depth)


class TestClassQueues:
    def test_strict_priority_when_no_credit_owed(self):
        q = _queues()
        for cls in ("batch", "standard", "premium"):
            q.put_nowait(_item(cls))
        assert [q.get_nowait().slo_class for _ in range(3)] == [
            "premium", "standard", "batch",
        ]

    def test_starvation_floor_is_a_hard_share_bound(self):
        """Sustained premium+batch overload: batch's pick share lands
        within one credit of floor * n_picks — never starved below it,
        never above premium's strict preference."""
        floor, n = 0.1, 400
        q = _queues(floor=floor, depth=2 * n)
        for _ in range(n):
            q.put_nowait(_item("premium"))
            q.put_nowait(_item("batch"))
        picks = [q.get_nowait().slo_class for _ in range(n)]
        batch = picks.count("batch")
        assert batch >= int(floor * n) - 1, picks[:40]
        assert batch <= int(floor * n) + 2, picks[:40]
        rec = q.record()
        assert rec["n_picks"] == n
        assert rec["n_floor_picks"] == batch  # every batch pick was owed
        assert rec["picks"]["premium"] == n - batch
        assert rec["starvation_floor"] == floor

    def test_lowest_class_preempts_first_when_both_owed(self):
        q = _queues(floor=0.25)
        for _ in range(8):
            q.put_nowait(_item("premium"))
            q.put_nowait(_item("standard"))
            q.put_nowait(_item("batch"))
        picks = [q.get_nowait().slo_class for _ in range(8)]
        # Both lower lanes bank 0.25/pick; at pick 5 both are owed —
        # the LOWEST priority class takes the slot first.
        assert "batch" in picks and "standard" in picks
        assert picks.index("batch") < picks.index("standard")

    def test_idle_class_banks_no_credit(self):
        """Credit accrues only while BACKLOGGED: a class that idled
        through premium's burst starts from zero when its traffic
        arrives — no stored-up monopoly."""
        q = _queues(floor=0.2)
        for _ in range(50):
            q.put_nowait(_item("premium"))
        for _ in range(50):
            q.get_nowait()
        q.put_nowait(_item("premium"))
        q.put_nowait(_item("batch"))
        assert q.get_nowait().slo_class == "premium"

    def test_credit_is_capped(self):
        """A long-backlogged class is owed at most _CREDIT_CAP whole
        picks: after 100 bypasses batch takes 2 consecutive slots, not
        10."""
        q = _queues(floor=0.1, depth=256)
        for _ in range(100):
            q.put_nowait(_item("premium"))
        q.put_nowait(_item("batch"))
        burn = []
        for _ in range(40):
            burn.append(q.get_nowait().slo_class)
        # batch was picked exactly when owed — the cap keeps its share
        # near the floor even with maximal banked credit.
        assert 1 <= burn.count("batch") <= 3

    def test_lane_full_sheds_only_that_class(self):
        q = _queues(classes=("p:weight=8,queue_depth=2",
                             "b:weight=1,queue_depth=2"))
        q.put_nowait(_item("b"))
        q.put_nowait(_item("b"))
        with pytest.raises(queue.Full):
            q.put_nowait(_item("b"))
        q.put_nowait(_item("p"))  # premium admission unaffected
        assert q.record()["lane_full"] == {"b": 1}
        assert q.class_fill()["b"] == {"depth": 2, "capacity": 2}

    def test_queue_facade_shapes(self):
        q = _queues(classes=("p:queue_depth=2", "b:queue_depth=3"))
        assert q.maxsize == 5 and q.empty() and q.qsize() == 0
        q.put_nowait(_item("p"))
        assert q.qsize() == 1 and not q.empty()
        with pytest.raises(queue.Empty):
            _queues().get_nowait()
        with pytest.raises(queue.Empty):
            _queues().get(timeout=0.01)

    def test_unknown_class_requeue_routes_to_default(self):
        # A requeue of a pre-reconfiguration item must not strand.
        q = _queues()
        q.put_nowait(_item("gone"))
        assert q.qsize() == 1
        assert q.get_nowait().slo_class == "gone"


# ---------------------------------------------------------------------------
# the batcher under classes (host-side fake engine, no device)
# ---------------------------------------------------------------------------


from glom_tpu.serve.engine import ServeResult  # noqa: E402  (needs jax)

IMG = np.zeros((3, 8, 8), np.float32)


class FakeEngine:
    def __init__(self, scfg, fail=None, name="fake0"):
        self.scfg = scfg
        self.fail = fail
        self.name = name
        self.calls = []

    def pick_bucket(self, n):
        for b in self.scfg.buckets:
            if n <= b:
                return b
        raise ValueError(n)

    def infer(self, imgs, n_valid=None, **kw):
        if self.fail is not None:
            raise self.fail
        b = imgs.shape[0]
        self.calls.append((b, n_valid))
        return ServeResult(
            levels=np.zeros((b, 16, 3, 16), np.float32),
            iters_run=6, latency_s=0.0, bucket=b, compiled=False,
        )


class TieredFakeEngine:
    """First (cold) dispatch leaves the last valid row unconverged; the
    warm continuation converges it — the two-tier conservation probe."""

    def __init__(self, scfg, name="fake0"):
        self.scfg = scfg
        self.iters_key = "auto"
        self.auto_budget = 12
        self.name = name
        self.calls = []

    def pick_bucket(self, n):
        for b in self.scfg.buckets:
            if n <= b:
                return b
        raise ValueError(n)

    def infer(self, imgs, n_valid=None, levels0=None, auto_budget=None,
              **kw):
        b = imgs.shape[0]
        warm = levels0 is not None
        self.calls.append({"bucket": b, "warm": warm})
        conv = np.ones((b,), bool)
        if not warm:
            conv[max(0, n_valid - 1):n_valid] = False
        iters = 4 if not warm else (auto_budget or 8)
        return ServeResult(
            levels=np.zeros((b, 16, 3, 16), np.float32),
            iters_run=iters, latency_s=0.0, bucket=b, compiled=False,
            row_converged=conv, row_iters=np.full((b,), iters, np.int32),
        )


class Sink:
    def __init__(self):
        self.records = []

    def write(self, rec):
        self.records.append(rec)


def _class_counts(summary):
    return {
        cls: cnt for cls, cnt in (summary.get("classes") or {}).items()
    }


def _assert_conserved(summary):
    for cls, cnt in _class_counts(summary).items():
        assert (
            cnt["n_served"] + cnt["n_shed"] + cnt["n_failed"]
            == cnt["n_requests"]
        ), (cls, cnt)


class TestBatcherQoS:
    def test_priority_order_under_backlog(self):
        """10:1 batch:premium backlog admitted before the workers start:
        premium tickets resolve ahead of the batch wave, batch still
        gets its floor share — the scheduler bound end to end."""
        scfg = _scfg(slo_starvation_floor=0.1, queue_depth=64)
        eng = FakeEngine(scfg)
        sink = Sink()
        b = DynamicBatcher(eng, max_batch=1, max_delay_ms=0.0, writer=sink)
        order = []
        tickets = []
        for i in range(40):
            tickets.append(("batch", b.submit(IMG, slo_class="batch")))
        for i in range(4):
            tickets.append(("premium", b.submit(IMG, slo_class="premium")))
        b.start()
        for cls, t in tickets:
            t.result(timeout=10.0)
        summary = b.summary_record()
        b.stop()
        resolves = [r for r in sink.records if r.get("event") == "resolve"]
        order = [r["slo_class"] for r in resolves]
        # All 4 premium rode the head of the drain (the floor may cede
        # a handful of early slots to the backlogged batch lane).
        assert max(order.index(c) for c in order if c == "premium") < 10
        _assert_conserved(summary)
        counts = _class_counts(summary)
        assert counts["premium"]["n_served"] == 4
        assert counts["batch"]["n_served"] == 40
        sched = summary["class_scheduler"]
        assert sched["n_picks"] >= 44
        assert sched["picks"]["premium"] >= 4
        assert summary["n_served"] == 44

    def test_lane_full_sheds_batch_premium_admits(self):
        scfg = _scfg(slo_classes=(
            "premium:weight=8,queue_depth=4", "batch:weight=1,queue_depth=2",
        ))
        eng = FakeEngine(scfg)
        sink = Sink()
        b = DynamicBatcher(eng, writer=sink)  # NOT started: lanes fill
        b.submit(IMG, slo_class="batch")
        b.submit(IMG, slo_class="batch")
        with pytest.raises(QueueFullError) as ei:
            b.submit(IMG, slo_class="batch")
        assert ei.value.detail["class_depth"] == {"premium": 0, "batch": 2}
        b.submit(IMG, slo_class="premium")  # unaffected by batch's flood
        summary = b.summary_record()
        b.stop(drain=False)
        counts = _class_counts(summary)
        assert counts["batch"]["n_shed"] == 1
        assert counts["premium"]["n_shed"] == 0
        shed = [r for r in sink.records if r.get("event") == "shed"]
        assert shed and shed[0]["slo_class"] == "batch"
        assert schema.validate_record(shed[0]) == []

    def test_undeclared_class_rejected_before_counters(self):
        b = DynamicBatcher(FakeEngine(_scfg()))
        with pytest.raises(ValueError, match="not declared"):
            b.submit(IMG, slo_class="gold")
        summary = b.summary_record()
        b.stop(drain=False)
        assert summary["n_requests"] == 0
        assert _class_counts(summary) == {}

    def test_default_class_stamps_unlabelled_submits(self):
        eng = FakeEngine(_scfg())
        sink = Sink()
        with DynamicBatcher(eng, writer=sink) as b:
            b.submit(IMG).result(timeout=10.0)
            summary = b.summary_record()
        assert _class_counts(summary)["standard"]["n_served"] == 1
        resolve = [r for r in sink.records if r.get("event") == "resolve"]
        assert resolve and resolve[0]["slo_class"] == "standard"

    def test_per_class_conservation_across_failover(self):
        scfg = _scfg()
        bad = FakeEngine(scfg, fail=RuntimeError("boom"), name="bad")
        good = FakeEngine(scfg, name="good")
        with DynamicBatcher(
            engines=[bad, good], max_batch=2, max_delay_ms=5.0,
            engine_fail_threshold=1,
        ) as b:
            tickets = [
                b.submit(IMG, slo_class=cls)
                for cls in ("premium", "batch", "premium", "batch")
            ]
            for t in tickets:
                t.result(timeout=10.0)
            summary = b.summary_record()
        _assert_conserved(summary)
        counts = _class_counts(summary)
        assert counts["premium"]["n_served"] == 2
        assert counts["batch"]["n_served"] == 2
        assert counts["premium"]["n_failed"] == 0

    def test_per_class_conservation_across_continuation(self):
        scfg = _scfg(
            iters="auto", max_auto_iters=12, exit_quorum=0.5,
            max_continuations=2, dispatch_retries=0,
        )
        eng = TieredFakeEngine(scfg)
        sink = Sink()
        with DynamicBatcher(eng, max_batch=4, max_delay_ms=10.0,
                            writer=sink) as b:
            tickets = [
                b.submit(IMG, slo_class=cls)
                for cls in ("premium", "premium", "batch")
            ]
            for t in tickets:
                t.result(timeout=10.0)
            summary = b.summary_record()
        assert summary["n_continued"] >= 1  # the straggler rode a warm hop
        _assert_conserved(summary)
        counts = _class_counts(summary)
        assert counts["premium"]["n_served"] == 2
        assert counts["batch"]["n_served"] == 1
        # The continued ticket's terminal kept its admission class.
        resolves = [r for r in sink.records if r.get("event") == "resolve"]
        assert sorted(r["slo_class"] for r in resolves) == [
            "batch", "premium", "premium",
        ]
        for r in resolves:
            assert schema.validate_record(r) == [], r


class TestClasslessBitParity:
    def test_plain_queue_and_no_class_nests(self):
        eng = FakeEngine(_scfg(slo_classes=None))
        with DynamicBatcher(eng) as b:
            assert type(b._q) is queue.Queue  # the PR 18 scheduler
            b.submit(IMG).result(timeout=10.0)
            summary = b.summary_record()
        assert "classes" not in summary
        assert "class_scheduler" not in summary

    def test_classless_shed_message_is_unchanged(self):
        eng = FakeEngine(_scfg(slo_classes=None))
        b = DynamicBatcher(eng, queue_depth=1)
        b.submit(IMG)
        with pytest.raises(QueueFullError) as ei:
            b.submit(IMG)
        b.stop(drain=False)
        assert "class" not in str(ei.value)
        assert "class_depth" not in ei.value.detail
        assert str(ei.value).startswith("request queue at capacity (1)")

    def test_classless_labels_are_pure_observability(self):
        """Labels on a classless config count per class in the summary
        but never reorder the FIFO."""
        eng = FakeEngine(_scfg(slo_classes=None))
        sink = Sink()
        b = DynamicBatcher(eng, max_batch=1, max_delay_ms=0.0, writer=sink)
        b.submit(IMG, slo_class="batch")
        b.submit(IMG, slo_class="premium")
        b.start()
        summary = None
        try:
            while summary is None or summary["n_served"] < 2:
                summary = b.summary_record()
        finally:
            b.stop()
        counts = _class_counts(b.summary_record())
        assert counts["batch"]["n_served"] == 1
        assert counts["premium"]["n_served"] == 1
        assert "class_scheduler" not in b.summary_record()
        resolves = [r for r in sink.records if r.get("event") == "resolve"]
        # FIFO: the batch submit resolved first despite the label.
        assert [r["slo_class"] for r in resolves] == ["batch", "premium"]


# ---------------------------------------------------------------------------
# class-scoped SLO rules + schema v11
# ---------------------------------------------------------------------------


class TestClassScopedRules:
    def test_split_slo_rule(self):
        assert split_slo_rule("p99_ms[premium]") == ("p99_ms", "premium")
        assert split_slo_rule("p99_ms") == ("p99_ms", None)
        for bad in ("p99_ms[", "p99_ms[]", "p99_ms[x"):
            with pytest.raises(ValueError):
                split_slo_rule(bad)

    def test_parse_slo_rejects_fleet_rules_with_scope(self):
        assert parse_slo("p99_ms[premium]=40") == ("p99_ms[premium]", 40.0)
        with pytest.raises(ValueError, match="class scope"):
            parse_slo("headroom[premium]=0.2")

    def test_monitor_windows_one_class_alone(self):
        t = [0.0]
        mon = SLOMonitor(
            {"p99_ms[premium]": 50.0}, window_s=60.0, clock=lambda: t[0],
        )
        for i in range(8):
            mon.observe({
                "kind": "serve", "event": "resolve", "latency_ms": 500.0,
                "slo_class": "batch", "request_id": i,
            })
        assert mon.evaluate() == []  # batch pain never arms premium's rule
        for i in range(8, 16):
            mon.observe({
                "kind": "serve", "event": "resolve", "latency_ms": 80.0,
                "slo_class": "premium", "request_id": i,
            })
        (breach,) = mon.evaluate()
        assert breach["rule"] == "p99_ms[premium]"
        assert breach["slo_class"] == "premium"
        assert schema.validate_record(breach) == []

    def test_shed_reclassifies_settle_failed(self):
        """A shed's settle-"failed" fires first; the richer shed leaf
        must reclassify the SAME request, not double-count it."""
        t = [0.0]
        mon = SLOMonitor(
            {"shed_rate[batch]": 0.4}, window_s=60.0, clock=lambda: t[0],
        )
        mon.observe({"kind": "serve", "event": "settle", "outcome": "served",
                     "slo_class": "batch", "request_id": 1})
        mon.observe({"kind": "serve", "event": "settle", "outcome": "failed",
                     "slo_class": "batch", "request_id": 2})
        mon.observe({"kind": "serve", "event": "shed",
                     "slo_class": "batch", "request_id": 2})
        (breach,) = mon.evaluate()
        # 1 shed / (1 shed + 1 served) = 0.5 — request 2 counted ONCE.
        assert breach["observed"] == pytest.approx(0.5)


class TestSchemaV11:
    def _rec(self, event, **kw):
        return schema.stamp(
            {"event": event, "request_id": 1, "trace_id": None,
             "span_id": None, "parent_span": None, **kw},
            kind="serve",
        )

    @pytest.mark.parametrize("event", ["admit", "shed", "settle", "resolve"])
    def test_tenant_scoped_events_require_the_key(self, event):
        rec = self._rec(event)
        rec.pop("slo_class", None)
        assert any("slo_class" in e for e in schema.validate_record(rec))
        rec["slo_class"] = None  # classless stamps null — fine
        assert schema.validate_record(rec) == []
        rec["slo_class"] = "premium"
        assert schema.validate_record(rec) == []

    def test_workload_records_require_the_key(self):
        rec = schema.stamp(
            {"t": 0.0, "signature": "bucket:3x8x8", "outcome": "offered"},
            kind="workload",
        )
        rec.pop("slo_class", None)
        assert any("slo_class" in e for e in schema.validate_record(rec))
        rec["slo_class"] = None
        assert schema.validate_record(rec) == []

    def test_pre_v11_records_are_grandfathered(self):
        rec = self._rec("admit")
        rec.pop("slo_class", None)
        rec["schema_version"] = 10
        assert schema.validate_record(rec) == []

    def test_untenanted_serve_events_unconstrained(self):
        rec = self._rec("ladder")
        rec.pop("slo_class", None)
        rec["rung"] = "capped_iters"
        assert schema.validate_record(rec) == []


# ---------------------------------------------------------------------------
# elastic binding + class-weighted regret (stamped-evidence semantics)
# ---------------------------------------------------------------------------


def _evidence(**kw):
    ev = {
        "n_engines": 1, "min_engines": 1, "max_engines": 4,
        "breaches": [], "headroom": 0.5, "low_water": 0.2,
        "high_water": 0.7, "dwell_s": 1.0, "below_held_s": None,
        "above_held_s": None, "anticipatory": False,
        "target_utilization": 0.8, "forecast": None,
        "lead_time_ms": None, "lead_quantile": None,
        "fleet_service_rate_rps": None,
    }
    ev.update(kw)
    return ev


class TestBindingBreaches:
    def test_rule_class_parses_hostile_input(self):
        assert rule_class("p99_ms[premium]") == "premium"
        assert rule_class("p99_ms") is None
        assert rule_class("p99_ms[") is None      # malformed: tolerate
        assert rule_class("p99_ms[]") is None
        assert rule_class(17) is None

    def test_no_low_classes_passes_breaches_verbatim(self):
        ev = _evidence(breaches=["p99_ms", "shed_rate[batch]"])
        assert binding_breaches(ev) == ["p99_ms", "shed_rate[batch]"]

    def test_low_class_breach_does_not_force_scale_out(self):
        ev = _evidence(
            breaches=["p99_ms[batch]"], low_classes=["batch"],
        )
        assert policy_action(ev) is None  # batch pain spends no hardware

    def test_premium_breach_still_scales_out(self):
        ev = _evidence(
            breaches=["p99_ms[premium]"], low_classes=["batch"],
        )
        assert binding_breaches(ev) == ["p99_ms[premium]"]
        assert policy_action(ev) == "scale_out"

    def test_unscoped_breach_is_always_binding(self):
        ev = _evidence(breaches=["p99_ms"], low_classes=["batch"])
        assert policy_action(ev) == "scale_out"

    def test_low_class_breach_cannot_veto_scale_in(self):
        quiet = _evidence(n_engines=2, above_held_s=5.0)
        assert policy_action(quiet) == "scale_in"
        batch_pain = _evidence(
            n_engines=2, above_held_s=5.0,
            breaches=["shed_rate[batch]"], low_classes=["batch"],
        )
        assert policy_action(batch_pain) == "scale_in"
        premium_pain = _evidence(
            n_engines=2, above_held_s=5.0,
            breaches=["p99_ms[premium]"], low_classes=["batch"],
        )
        assert policy_action(premium_pain) != "scale_in"


class TestWeightedRegret:
    def _chain(self, failures, *, weights=None, low=("batch",)):
        ev = _evidence(
            breaches=["p99_ms[premium]"], low_classes=list(low),
            lead_time_ms=1000.0,
        )
        if weights is not None:
            ev["class_weights"] = dict(weights)
        recs = [
            {"kind": "decision", "schema_version": 11, "t": 1.0,
             "fleet": "f0", "decision_id": 1, "prev_decision_id": None,
             "action": "scale_out", "evidence": ev},
            {"kind": "serve", "event": "scale_out", "fleet": "f0",
             "decision_id": 1, "t": 1.1, "spawn_ms": 100.0},
        ]
        recs += failures
        return recs

    def test_regret_weighted_prices_failures_by_class(self):
        recs = self._chain(
            [
                {"kind": "serve", "event": "shed", "t": 1.5,
                 "slo_class": "premium"},
                {"kind": "serve", "event": "shed", "t": 1.6,
                 "slo_class": "batch"},
                {"kind": "serve", "event": "shed", "t": 1.7},  # unclassed
            ],
            weights={"premium": 8.0, "standard": 2.0, "batch": 1.0},
        )
        rep = audit_records(recs)
        assert rep["errors"] == []
        assert rep["regret_total"] == 3
        assert rep["regret_weighted"] == pytest.approx(8.0 + 1.0 + 1.0)
        (pd,) = rep["regret_per_decision"]
        assert pd["regret_weighted"] == pytest.approx(10.0)

    def test_breach_rule_scope_classifies_failures(self):
        recs = self._chain(
            [{"kind": "slo_breach", "rule": "p99_ms[premium]", "t": 1.4}],
            weights={"premium": 8.0},
        )
        rep = audit_records(recs)
        assert rep["regret_weighted"] == pytest.approx(8.0)

    def test_without_weights_weighted_equals_count(self):
        recs = self._chain(
            [{"kind": "serve", "event": "shed", "t": 1.5,
              "slo_class": "premium"}],
        )
        rep = audit_records(recs)
        assert rep["regret_total"] == 1
        assert rep["regret_weighted"] == pytest.approx(1.0)

    def test_evidence_conservation_replays_class_stance(self):
        """The stamped bundle is self-contained: the audit replays
        binding_breaches from evidence alone, so a low-class-only
        scale-out FAILS conservation."""
        ev = _evidence(breaches=["p99_ms[batch]"], low_classes=["batch"])
        recs = [
            {"kind": "decision", "schema_version": 11, "t": 1.0,
             "fleet": "f0", "decision_id": 1, "prev_decision_id": None,
             "action": "scale_out", "evidence": ev},
            {"kind": "serve", "event": "scale_out", "fleet": "f0",
             "decision_id": 1, "t": 1.1, "spawn_ms": 10.0},
        ]
        rep = audit_records(recs)
        assert any("replays to" in e for e in rep["errors"])


# ---------------------------------------------------------------------------
# workload class mix + compare rows
# ---------------------------------------------------------------------------


class TestWorkloadClassMix:
    def test_parse_class_mix(self):
        from glom_tpu.serve.workload import parse_class_mix

        assert parse_class_mix("premium=0.2,batch=0.5") == {
            "premium": 0.2, "batch": 0.5,
        }
        assert parse_class_mix(None) is None
        assert parse_class_mix("") is None
        with pytest.raises(ValueError, match="sum"):
            parse_class_mix("a=0.7,b=0.6")
        with pytest.raises(ValueError):
            parse_class_mix("a=1.5")
        with pytest.raises(ValueError):
            parse_class_mix("noequals")

    def test_generate_deals_classes_deterministically(self):
        from glom_tpu.serve.workload import generate

        mix = {"premium": 0.2, "batch": 0.5}
        a = generate("flash-crowd", 4.0, seed=7, class_mix=mix)
        b = generate("flash-crowd", 4.0, seed=7, class_mix=mix)
        assert a == b  # seeded: the mix never breaks determinism
        assert all("slo_class" in r for r in a)
        assert all(schema.validate_record(r) == [] for r in a)
        dealt = [r["slo_class"] for r in a]
        n = len(dealt)
        # Mixed per the fractions (loose: it's a seeded draw), with the
        # 0.3 remainder unclassed (null).
        assert 0.05 * n < dealt.count("premium") < 0.45 * n
        assert 0.30 * n < dealt.count("batch") < 0.70 * n
        assert dealt.count(None) > 0

    def test_classless_scenario_stamps_null(self):
        from glom_tpu.serve.workload import generate

        recs = generate("diurnal", 2.0, seed=3)
        assert recs and all(r["slo_class"] is None for r in recs)

    def test_replay_reoffers_the_recorded_class(self):
        from glom_tpu.serve.workload import generate, replay

        recs = generate("flash-crowd", 3.0, seed=1,
                        class_mix={"premium": 0.5})
        seen = []
        t = [0.0]

        def clock():
            return t[0]

        def sleep(dt):
            t[0] += dt

        replay(recs, lambda rec, i: seen.append(rec.get("slo_class")),
               clock=clock, sleep=sleep)
        assert seen == [r["slo_class"] for r in recs]
        assert "premium" in seen


class TestCompareClassRows:
    SUMMARY = {
        "kind": "serve", "event": "summary", "config": "tiny",
        "engines": {},
        "classes": {
            "premium": {"n_requests": 10, "n_served": 10, "n_shed": 0,
                        "n_failed": 0, "n_degraded": 0,
                        "served_fraction": 1.0},
            "batch": {"n_requests": 10, "n_served": 3, "n_shed": 7,
                      "n_failed": 0, "n_degraded": 2,
                      "served_fraction": 0.3},
        },
        "class_scheduler": {
            "starvation_floor": 0.1, "n_picks": 13, "n_floor_picks": 2,
            "picks": {"premium": 10, "batch": 3},
            "lane_full": {"batch": 7},
        },
    }

    def test_class_nest_flattens_to_gateable_rows(self):
        from glom_tpu.telemetry.compare import flatten_engine_metrics

        rows = {r["metric"]: r for r in flatten_engine_metrics(self.SUMMARY)}
        assert rows["serve_class.batch.n_shed (tiny)"]["value"] == 7.0
        assert rows["serve_class.batch.served_fraction (tiny)"] == {
            "metric": "serve_class.batch.served_fraction (tiny)",
            "value": 0.3, "unit": "fraction", "kind": "bench",
        }
        assert rows["serve_class.batch.lane_full_rejects (tiny)"][
            "value"
        ] == 7.0
        assert "serve_class.premium.n_failed (tiny)" in rows
        # Scheduler pick counters are workload, not quality: never gate.
        assert not any("picks" in m for m in rows)

    def test_directions(self):
        from glom_tpu.telemetry.compare import lower_is_better

        assert lower_is_better("serve_class.premium.n_failed (t)", "count")
        assert lower_is_better("serve_class.premium.n_shed (t)", "count")
        assert lower_is_better("serve_class.premium.n_degraded (t)", "count")
        assert lower_is_better(
            "serve_class.batch.lane_full_rejects (t)", "count"
        )
        assert not lower_is_better(
            "serve_class.batch.served_fraction (t)", "fraction"
        )
