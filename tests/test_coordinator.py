"""Pod coordination (glom_tpu/resilience/coordinator.py): the two-phase
preemption save barrier, its fault injectors (message loss, deadline
overrun), the pod-mode grace save, and gang-supervised recovery through
fit_supervised.

All host-only (threads simulate hosts over a shared tmp dir; np pytrees
through real Orbax managers) — tier-1 fast. The subprocess end-to-end
ride is the chaos `preempt-pod` scenario (tests/test_chaos.py slow +
CI's chaos job).
"""

import threading
import time
from pathlib import Path

import numpy as np
import pytest

from glom_tpu.resilience import (
    BarrierAbort,
    DirectoryTransport,
    FaultPlan,
    InjectedFault,
    PodCoordinator,
    barrier_delay,
    message_loss,
    peer_host_dirs,
    pod_preemption_save,
    read_pod_commit,
)
from glom_tpu.telemetry import schema


class ListWriter:
    def __init__(self):
        self.records = []
        self._lock = threading.Lock()

    def write(self, rec):
        with self._lock:
            self.records.append(rec)

    def all(self):
        with self._lock:
            return list(self.records)


def _run_hosts(n, fn, timeout=30.0):
    """Run fn(host) on n threads; re-raise the first failure."""
    errs = {}

    def wrap(h):
        try:
            fn(h)
        except BaseException as e:  # noqa: BLE001 — surfaced below
            errs[h] = e

    threads = [
        threading.Thread(target=wrap, args=(h,), daemon=True)
        for h in range(n)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout)
        assert not t.is_alive(), "a simulated host hung (the barrier must \
never hang past its deadline)"
    return errs


class TestDirectoryTransport:
    def test_post_and_read_roundtrip(self, tmp_path):
        a = DirectoryTransport(tmp_path, 0, 2)
        b = DirectoryTransport(tmp_path, 1, 2)
        assert a.post("r1", "propose", {"step": 3})
        assert b.post("r1", "propose", {"step": 5})
        msgs = a.read_all("r1", "propose")
        assert msgs == {0: {"host": 0, "step": 3}, 1: {"host": 1, "step": 5}}
        assert a.read_all("r2", "propose") == {}  # rounds are disjoint

    def test_fault_hook_drops_the_message(self, tmp_path):
        plan = FaultPlan(seed=0)
        plan.register("barrier-msg", at=(0,), fault="barrier-message-loss")
        t = DirectoryTransport(tmp_path, 0, 1, fault_hook=message_loss(plan))
        assert not t.post("r1", "propose", {"step": 3})  # dropped
        assert t.read_all("r1", "propose") == {}
        assert t.post("r1", "saved", {"step": 3})  # off-schedule: lands
        assert [e["fault"] for e in plan.events()] == ["barrier-message-loss"]

    def test_bad_host_rejected(self, tmp_path):
        with pytest.raises(ValueError):
            DirectoryTransport(tmp_path, 2, 2)


class TestPreemptionBarrier:
    def _coord(self, tmp_path, h, n, writer=None, hook=None):
        return PodCoordinator(
            DirectoryTransport(tmp_path, h, n, fault_hook=hook),
            writer=writer, poll_s=0.01,
        )

    def test_commits_the_min_proposal_on_every_host(self, tmp_path):
        w = ListWriter()
        proposals = {0: 5, 1: 3, 2: 4}
        results, saves = {}, {}

        def host(h):
            c = self._coord(tmp_path, h, 3, writer=w)
            results[h] = c.preemption_barrier(
                "preempt-g0", proposals[h],
                lambda commit: saves.__setitem__(h, commit),
                deadline_s=10.0,
            )

        assert _run_hosts(3, host) == {}
        assert results == {0: 3, 1: 3, 2: 3}
        assert saves == {0: 3, 1: 3, 2: 3}  # every host landed THE step
        marker = read_pod_commit(tmp_path)
        assert marker["step"] == 3 and marker["n_hosts"] == 3
        assert marker["proposals"] == {"0": 5, "1": 3, "2": 4}
        recs = w.all()
        for r in recs:
            assert schema.validate_record(r) == [], r
        barrier = [r for r in recs if r["kind"] == "barrier"]
        phases = {(r["host"], r["phase"]) for r in barrier}
        for h in range(3):
            assert {(h, "propose"), (h, "commit"), (h, "saved"),
                    (h, "complete")} <= phases
        assert {r["step"] for r in barrier if r["phase"] == "commit"} == {3}

    def test_message_loss_aborts_loudly_on_every_host(self, tmp_path):
        """The fault-injector acceptance: drop host 1's propose — the
        waiting peers (and host 1 itself, short its own message) must
        abort at the deadline with stamped abort events and NO pod
        commit marker."""
        w = ListWriter()
        plan = FaultPlan(seed=0, writer=w)
        plan.register("barrier-msg", at=(0,), fault="barrier-message-loss")
        hook = message_loss(plan)
        errs = {}

        def host(h):
            c = self._coord(
                tmp_path, h, 2, writer=w, hook=hook if h == 1 else None
            )
            try:
                c.preemption_barrier(
                    "preempt-g0", 3, lambda s: None, deadline_s=0.4
                )
            except BarrierAbort as e:
                errs[h] = e

        _run_hosts(2, host)
        assert set(errs) == {0, 1}
        assert read_pod_commit(tmp_path) is None
        recs = w.all()
        faults = [r for r in recs if r.get("kind") == "fault"]
        assert faults and faults[0]["fault"] == "barrier-message-loss"
        aborts = [r for r in recs if r.get("kind") == "barrier"
                  and r["phase"] == "abort"]
        assert {r["host"] for r in aborts} == {0, 1}
        assert all("deadline" in r["reason"] or "abort" in r["reason"]
                   for r in aborts)

    def test_deadline_overrun_aborts_and_writes_no_marker(self, tmp_path):
        """Stall host 1's 'saved' post past the grace deadline: host 0
        aborts waiting, and host 1 — limping in late — must NOT declare
        the aborted round complete."""
        plan = FaultPlan(seed=0)
        plan.register("barrier-delay", at=(1,), fault="deadline-overrun")
        hook = barrier_delay(plan, delay_s=1.0)
        errs = {}

        def host(h):
            c = self._coord(tmp_path, h, 2, hook=hook if h == 1 else None)
            try:
                c.preemption_barrier(
                    "preempt-g0", 3, lambda s: None, deadline_s=0.4
                )
            except BarrierAbort as e:
                errs[h] = e

        _run_hosts(2, host)
        assert set(errs) == {0, 1}, errs
        assert read_pod_commit(tmp_path) is None

    def test_failed_save_aborts_the_whole_round(self, tmp_path):
        errs = {}

        def host(h):
            c = self._coord(tmp_path, h, 2)

            def save_fn(commit):
                if h == 1:
                    raise InjectedFault("disk full")

            try:
                c.preemption_barrier(
                    "preempt-g0", 3, save_fn, deadline_s=5.0
                )
            except BarrierAbort as e:
                errs[h] = e

        _run_hosts(2, host)
        assert set(errs) == {0, 1}
        assert "disk full" in str(errs[1])
        assert read_pod_commit(tmp_path) is None

    def test_sub_deadline_delay_still_commits(self, tmp_path):
        """A slow-but-alive host (delay INSIDE the deadline) is not an
        abort — the round waits and commits."""
        plan = FaultPlan(seed=0)
        plan.register("barrier-delay", at=(0,), fault="slow-host")
        hook = barrier_delay(plan, delay_s=0.1)
        results = {}

        def host(h):
            c = self._coord(tmp_path, h, 2, hook=hook if h == 1 else None)
            results[h] = c.preemption_barrier(
                "preempt-g0", 3 + h, lambda s: None, deadline_s=10.0
            )

        assert _run_hosts(2, host) == {}
        assert results == {0: 3, 1: 3}
        assert read_pod_commit(tmp_path)["step"] == 3

    def test_relaunch_purges_stale_round_messages(self, tmp_path):
        """Round ids derive from the resume step, so a relaunch after an
        aborted (or zero-progress) round REUSES the id. The previous
        lifetime's abort must not poison the new round, and its stale
        propose/saved must not complete one: each host purges its own
        messages at transport construction (= process start)."""
        # Previous lifetime: a round that aborted, leaving every message
        # kind behind under the id the relaunch will reuse.
        old0 = DirectoryTransport(tmp_path, 0, 2)
        old1 = DirectoryTransport(tmp_path, 1, 2)
        old0.post("preempt-g0", "propose", {"step": 9})
        old1.post("preempt-g0", "propose", {"step": 9})
        old1.post("preempt-g0", "saved", {"step": 9})
        old1.post("preempt-g0", "abort", {"reason": "deadline passed"})
        results = {}

        def host(h):
            c = self._coord(tmp_path, h, 2)  # the relaunch: fresh transport
            results[h] = c.preemption_barrier(
                "preempt-g0", 3 + h, lambda s: None, deadline_s=10.0
            )

        assert _run_hosts(2, host) == {}
        assert results == {0: 3, 1: 3}  # min of the NEW proposals, not 9
        assert read_pod_commit(tmp_path)["step"] == 3

    def test_gang_barrier_excuses_a_done_member(self, tmp_path):
        """A member that finished every step exits the gang: it posts
        the persistent done flag, and a surviving member's restart
        barrier must complete without it — waiting would deadlock every
        recovery attempt until the restart budget died."""
        done = PodCoordinator(DirectoryTransport(tmp_path, 1, 2), poll_s=0.01)
        done.signal_gang_done(8)
        survivor = PodCoordinator(
            DirectoryTransport(tmp_path, 0, 2), poll_s=0.01
        )
        survivor.gang_barrier("restart", 2, deadline_s=5.0)  # no abort
        # The survivor's own arrival is never excused: a fresh host 1
        # waiting on an all-done-peers barrier still posts and passes.
        done2 = PodCoordinator(DirectoryTransport(tmp_path, 1, 2), poll_s=0.01)
        with pytest.raises(BarrierAbort):
            # ... but a barrier whose only live member never arrives
            # (host 0 posted nothing for THIS epoch) still aborts.
            done2.gang_barrier("restart", 3, deadline_s=0.3)


class TestPodPreemptionSave:
    STATE = {"w": np.arange(8, dtype=np.float32),
             "step": np.zeros((), np.int32)}

    def _save_steps(self, directory, steps):
        from glom_tpu.utils.checkpoint import CheckpointManager

        mgr = CheckpointManager(str(directory), async_save=False)
        for s in steps:
            assert mgr.save(
                s, {"w": self.STATE["w"] + s, "step": np.asarray(s, np.int32)}
            )
        mgr.close()

    def test_min_host_grace_saves_ahead_host_proves_retention(self, tmp_path):
        """The two host roles: host 0 AT the min grace-saves its live
        state; host 1 past the min proves the committed step is still on
        disk. Both return the SAME committed step on the recovery
        record."""
        coord_dir = tmp_path / "coord"
        dirs = {h: tmp_path / "ckpt" / f"host_{h}" for h in range(2)}
        for d in dirs.values():
            d.mkdir(parents=True)
        # host 1 ran ahead to step 4 but retains step 2 (per-step saves)
        self._save_steps(dirs[1], [1, 2, 3, 4])
        results = {}

        def host(h):
            c = PodCoordinator(
                DirectoryTransport(coord_dir, h, 2), poll_s=0.01
            )
            step = 2 if h == 0 else 4
            state = {"w": self.STATE["w"] + step,
                     "step": np.asarray(step, np.int32)}
            results[h] = pod_preemption_save(
                c, dirs[h], state, step,
                deadline_s=20.0, round_id="preempt-g0",
            )

        assert _run_hosts(2, host) == {}
        for h in range(2):
            assert results[h]["step"] == 2 and results[h]["pod"] is True
        assert results[0]["proposed_step"] == 2
        assert results[1]["proposed_step"] == 4
        # host 0's grace save landed step 2; host 1's retention held
        from glom_tpu.utils.checkpoint import step_valid_in_dir

        assert step_valid_in_dir(dirs[0], 2)
        assert step_valid_in_dir(dirs[1], 2)
        assert read_pod_commit(coord_dir)["step"] == 2

    def test_ahead_host_without_retention_aborts_the_round(self, tmp_path):
        """A host past the min that does not retain the committed step
        cannot satisfy the round: it polls for a bounded slice of the
        grace budget (the step may be an async commit still landing),
        then aborts LOUDLY (and so does the peer) — never a pod
        checkpoint with a hole in it."""
        coord_dir = tmp_path / "coord"
        dirs = {h: tmp_path / "ckpt" / f"host_{h}" for h in range(2)}
        for d in dirs.values():
            d.mkdir(parents=True)
        self._save_steps(dirs[1], [3, 4])  # step 2 NOT retained
        errs = {}

        def host(h):
            c = PodCoordinator(
                DirectoryTransport(coord_dir, h, 2), poll_s=0.01
            )
            step = 2 if h == 0 else 4
            state = {"w": self.STATE["w"], "step": np.asarray(step, np.int32)}
            try:
                pod_preemption_save(
                    c, dirs[h], state, step,
                    deadline_s=3.0, round_id="preempt-g0",
                )
            except BarrierAbort as e:
                errs[h] = e

        _run_hosts(2, host)
        assert set(errs) == {0, 1}
        assert "does not retain" in str(errs[1])
        assert read_pod_commit(coord_dir) is None

    def test_ahead_host_waits_for_in_flight_async_commit(self, tmp_path):
        """SIGTERM races the loop's ASYNC save: the committed step may
        not be on disk YET when the ahead host checks — its commit
        thread is not paused by the signal handler, so the step lands
        while the host watches. The retention check must poll (bounded),
        not abort on the first look — the flake that motivated it left a
        2-host chaos run aborting on a step that committed 200ms later."""
        coord_dir = tmp_path / "coord"
        dirs = {h: tmp_path / "ckpt" / f"host_{h}" for h in range(2)}
        for d in dirs.values():
            d.mkdir(parents=True)
        self._save_steps(dirs[1], [3, 4])  # step 2 not on disk yet
        results = {}

        def host(h):
            c = PodCoordinator(
                DirectoryTransport(coord_dir, h, 2), poll_s=0.01
            )
            step = 2 if h == 0 else 4
            state = {"w": self.STATE["w"] + step,
                     "step": np.asarray(step, np.int32)}
            if h == 1:
                # The "async commit" lands AFTER host 1 first checks:
                # Orbax's commit is the atomic rename of the step dir,
                # so a bare int-named dir is the landing.
                def land():
                    time.sleep(0.4)
                    (dirs[1] / "2").mkdir()

                threading.Thread(target=land, daemon=True).start()
            results[h] = pod_preemption_save(
                c, dirs[h], state, step,
                deadline_s=20.0, round_id="preempt-g0",
            )

        assert _run_hosts(2, host) == {}
        assert results[0]["step"] == results[1]["step"] == 2
        assert read_pod_commit(coord_dir)["step"] == 2


class TestPeerHostDirs:
    def test_convention_and_loud_mismatch(self, tmp_path):
        d = tmp_path / "pod" / "host_1"
        assert peer_host_dirs(d, 1, 3) == [
            str(tmp_path / "pod" / "host_0"),
            str(tmp_path / "pod" / "host_2"),
        ]
        with pytest.raises(ValueError, match="host_0"):
            peer_host_dirs(tmp_path / "pod" / "ckpt", 0, 2)


# ---------------------------------------------------------------------------
# gang-supervised recovery (fit_supervised gang mode, in-process)
# ---------------------------------------------------------------------------


class GangTrainer:
    """Host-only trainer honoring the fit_supervised protocol (the
    FlakyTrainer recipe): 'training' folds each batch's mean into w, so
    a gang-restarted, reconciled, realigned run must be bit-identical to
    an unfaulted one. `crash_gate` (host 0 only) BLOCKS until the peer
    has committed a checkpoint, then raises — the deterministic
    interleaving the gang test needs."""

    def __init__(self, crash_gate=None, pause_gate=None):
        self.state = {
            "w": np.zeros((), np.float64),
            "step": np.zeros((), np.int32),
        }
        self.crash_gate = crash_gate
        self.pause_gate = pause_gate

    def fit(self, data, num_steps, log_every=10):
        hist = []
        for _ in range(num_steps):
            batch = next(data)
            step = int(np.asarray(self.state["step"]))
            if self.crash_gate is not None and self.crash_gate(step):
                raise InjectedFault("injected gang-member crash")
            if self.pause_gate is not None:
                self.pause_gate(step)
            self.state = {
                "w": np.asarray(
                    np.asarray(self.state["w"]) + float(np.mean(batch)),
                    np.float64,
                ),
                "step": np.asarray(step + 1, np.int32),
            }
            hist.append({"step": step, "loss": 1.0})
        return hist


def _data_factory(host):
    def make():
        return iter(
            np.full((2,), float(1000 * host + i)) for i in range(1000)
        )

    return make


class TestGangSupervisedRecovery:
    def test_one_crash_restarts_the_gang_from_the_common_step(self, tmp_path):
        """The gang acceptance: host 0 crashes mid-span AFTER both hosts
        committed step 2 — host 1 (which may have raced ahead and
        committed more) must see the gang stop, fall back, rendezvous at
        the restart barrier, and BOTH hosts must resume from the SAME
        reconciled common step and finish bit-identical to unfaulted
        runs. Newer half-committed steps are quarantined on every
        host."""
        from glom_tpu.train.supervise import TrainSupervisor, fit_supervised

        root = tmp_path
        dirs = {h: root / "ckpt" / f"host_{h}" for h in range(2)}
        coord_dir = root / "coord"
        w = {h: ListWriter() for h in range(2)}
        results, errors = {}, {}

        def crash_gate(step):
            if step < 3:
                return False
            # Block until the PEER committed its step-2 manifest: the
            # crash then happens at a point where a common step EXISTS,
            # making the reconciled resume step deterministic (2).
            deadline = time.monotonic() + 20.0
            while time.monotonic() < deadline:
                if (dirs[1] / "manifest_2.json").is_file():
                    return True
                time.sleep(0.01)
            raise AssertionError("peer never committed step 2")

        def pause_gate(step):
            # Host 1 holds at step 4 until host 0's gang stop is POSTED:
            # host 1 is then deterministically mid-attempt when the stop
            # arrives, and notices it at its next span boundary — no
            # race against host 1 finishing the run first.
            if step != 4:
                return
            stop_file = coord_dir / "rounds" / "gang-e1" / "stop_0.json"
            deadline = time.monotonic() + 20.0
            while time.monotonic() < deadline:
                if stop_file.is_file():
                    return
                time.sleep(0.01)
            raise AssertionError("host 0 never signaled the gang stop")

        def host(h):
            crashed = [False]

            def make_trainer():
                if h == 0 and not crashed[0]:
                    crashed[0] = True
                    return GangTrainer(crash_gate=crash_gate)
                return GangTrainer(
                    pause_gate=pause_gate if h == 1 else None
                )

            coord = PodCoordinator(
                DirectoryTransport(coord_dir, h, 2),
                writer=w[h], poll_s=0.01,
            )
            try:
                results[h] = fit_supervised(
                    make_trainer,
                    _data_factory(h),
                    8,
                    checkpoint_dir=str(dirs[h]),
                    checkpoint_every=2,
                    log_every=1,
                    supervisor=TrainSupervisor(
                        max_restarts=2, backoff_s=0.0, writer=w[h]
                    ),
                    metrics_writer=w[h],
                    gang=coord,
                    pod_peers=peer_host_dirs(dirs[h], h, 2),
                    gang_barrier_deadline_s=20.0,
                )
            except BaseException as e:  # noqa: BLE001 — asserted below
                errors[h] = e

        errs = _run_hosts(2, host, timeout=60.0)
        assert errs == {} and errors == {}, (errs, errors)
        # Both hosts trained every step (continuity across the restart).
        for h in range(2):
            assert sorted({r["step"] for r in results[h]}) == list(range(8))
        # ONE common resume step, stamped identically on both hosts.
        resumes = {
            h: [r for r in w[h].all()
                if r.get("action") == "resume-from-checkpoint"]
            for h in range(2)
        }
        assert resumes[0] and resumes[1]
        assert {r["step"] for r in resumes[0]} == {2}
        assert {r["step"] for r in resumes[1]} == {2}
        # host 0 stamped the gang stop; host 1 restarted on GangRestart.
        stops = [r for r in w[0].all() if r.get("action") == "gang-stop"]
        assert stops and stops[0]["host"] == 0
        restarts = [r for r in w[1].all() if r.get("action") == "restart"]
        assert restarts and "GangRestart" in restarts[0]["exception"]
        # The restart rendezvous is on the record for BOTH hosts.
        for h in range(2):
            arrivals = [r for r in w[h].all() if r.get("kind") == "barrier"
                        and r["phase"] == "arrive"]
            assert any(r["round"] == "restart-e2" for r in arrivals), (
                h, arrivals,
            )
        # Bit-identical to unfaulted runs: reconciliation + realign is
        # exact on every host.
        from glom_tpu.utils.checkpoint import CheckpointManager, abstract_like

        for h in range(2):
            clean = GangTrainer()
            clean.fit(_data_factory(h)(), 8, log_every=1)
            mgr = CheckpointManager(str(dirs[h]))
            step, got = mgr.restore(
                abstract_state=abstract_like(clean.state)
            )
            mgr.close()
            assert step == 8
            np.testing.assert_array_equal(
                np.asarray(got["w"]), np.asarray(clean.state["w"])
            )
        # Every stamped record on both hosts validates.
        for h in range(2):
            for r in w[h].all():
                assert schema.validate_record(r) == [], r
