"""Pallas kernel tests (interpret mode on CPU) vs the XLA/oracle path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from glom_tpu.kernels import fused_grouped_ffw
from glom_tpu.ops.ffw import GroupedFFWParams, grouped_ffw, init_grouped_ffw


@pytest.fixture(scope="module")
def setup():
    G, d = 4, 128
    params = init_grouped_ffw(jax.random.PRNGKey(0), G, d, mult=4)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 256, G, d), jnp.float32)
    return params, x


class TestFusedGroupedFFW:
    def test_forward_matches_xla(self, setup):
        params, x = setup
        got = fused_grouped_ffw(params, x, tile_m=128, interpret=True)
        want = grouped_ffw(params, x)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-5
        )

    def test_grad_matches_xla(self, setup):
        params, x = setup

        def loss_fused(p, x_):
            return jnp.mean(fused_grouped_ffw(p, x_, tile_m=128, interpret=True) ** 2)

        def loss_xla(p, x_):
            return jnp.mean(grouped_ffw(p, x_) ** 2)

        g1 = jax.grad(loss_fused, argnums=(0, 1))(params, x)
        g2 = jax.grad(loss_xla, argnums=(0, 1))(params, x)
        for a, b in zip(jax.tree_util.tree_leaves(g1), jax.tree_util.tree_leaves(g2)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-3, atol=1e-5
            )

    def test_fallback_on_unsupported_shape(self, setup):
        params, _ = setup
        # M=6 not divisible by tile -> must silently fall back, still correct
        x = jax.random.normal(jax.random.PRNGKey(2), (1, 6, 4, 128), jnp.float32)
        got = fused_grouped_ffw(params, x, tile_m=128)
        want = grouped_ffw(params, x)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-6
        )

    def test_bf16(self, setup):
        if jax.devices()[0].platform == "cpu":
            pytest.skip("CPU XLA lacks bf16xbf16->f32 dot; covered on TPU")
        params, x = setup
        pb = jax.tree_util.tree_map(lambda t: t.astype(jnp.bfloat16), params)
        xb = x.astype(jnp.bfloat16)
        got = fused_grouped_ffw(pb, xb, tile_m=128, interpret=True)
        want = grouped_ffw(pb, xb)
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(want, np.float32), rtol=5e-2, atol=5e-2
        )

    def test_auto_tile_small_batch(self, setup):
        """batch=1, n=256 -> M=256 must auto-pick tile 256 and use the kernel
        (not silently fall back)."""
        from glom_tpu.kernels.grouped_mlp import _pick_tile

        assert _pick_tile(256) == 256
        assert _pick_tile(4096) == 512
        assert _pick_tile(6) is None
        params, _ = setup
        x = jax.random.normal(jax.random.PRNGKey(3), (1, 256, 4, 128), jnp.float32)
        got = fused_grouped_ffw(params, x, interpret=True)
        want = grouped_ffw(params, x)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-5
        )

    def test_bwd_accumulates_f32(self, setup):
        """The custom-VJP backward must pin f32 accumulation on every
        contraction regardless of input dtype (checked via the jaxpr, since
        CPU cannot execute bf16 dots)."""
        from glom_tpu.kernels.grouped_mlp import _bwd

        params, _ = setup
        pb = jax.tree_util.tree_map(lambda t: t.astype(jnp.bfloat16), params)
        x = jnp.zeros((2, 128, 4, 128), jnp.bfloat16)
        g = jnp.zeros_like(x)
        jaxpr = jax.make_jaxpr(lambda p, x_, g_: _bwd(128, False, (p, x_), g_))(
            pb, x, g
        )
        dots = [e for e in jaxpr.jaxpr.eqns if e.primitive.name == "dot_general"]
        assert dots, "backward lost its contractions?"
        for e in dots:
            assert e.params["preferred_element_type"] == jnp.float32
