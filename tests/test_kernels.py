"""Pallas kernel tests (interpret mode on CPU) vs the XLA/oracle path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from glom_tpu.kernels import fused_grouped_ffw
from glom_tpu.ops.ffw import grouped_ffw, init_grouped_ffw


@pytest.fixture(scope="module")
def setup():
    G, d = 4, 128
    params = init_grouped_ffw(jax.random.PRNGKey(0), G, d, mult=4)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 256, G, d), jnp.float32)
    return params, x


class TestFusedGroupedFFW:
    def test_forward_matches_xla(self, setup):
        params, x = setup
        got = fused_grouped_ffw(params, x, tile_m=128, interpret=True)
        want = grouped_ffw(params, x)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-5
        )

    def test_grad_matches_xla(self, setup):
        params, x = setup

        def loss_fused(p, x_):
            return jnp.mean(fused_grouped_ffw(p, x_, tile_m=128, interpret=True) ** 2)

        def loss_xla(p, x_):
            return jnp.mean(grouped_ffw(p, x_) ** 2)

        g1 = jax.grad(loss_fused, argnums=(0, 1))(params, x)
        g2 = jax.grad(loss_xla, argnums=(0, 1))(params, x)
        for a, b in zip(jax.tree_util.tree_leaves(g1), jax.tree_util.tree_leaves(g2)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-3, atol=1e-5
            )

    def test_bwd_kernel_bf16_multi_tile(self, setup):
        """The fused backward kernel in bf16: dw/db accumulate in f32 across
        8 row tiles (M=4*256=1024, bwd tile 128), and the tanh-GELU
        derivative matches the bf16 forward's activation to bf16
        resolution."""
        if jax.devices()[0].platform == "cpu":
            pytest.skip("CPU XLA lacks bf16xbf16->f32 dot; covered on TPU")
        params, _ = setup
        G, d = 4, 128
        pb = jax.tree_util.tree_map(lambda t: t.astype(jnp.bfloat16), params)
        xb = jax.random.normal(jax.random.PRNGKey(3), (4, 256, G, d), jnp.bfloat16)

        def loss_fused(p, x_):
            return jnp.mean(
                fused_grouped_ffw(p, x_, tile_m=128, interpret=True).astype(
                    jnp.float32
                )
                ** 2
            )

        def loss_xla(p, x_):
            return jnp.mean(grouped_ffw(p, x_).astype(jnp.float32) ** 2)

        g1 = jax.grad(loss_fused, argnums=(0, 1))(pb, xb)
        g2 = jax.grad(loss_xla, argnums=(0, 1))(pb, xb)
        for a, b in zip(jax.tree_util.tree_leaves(g1), jax.tree_util.tree_leaves(g2)):
            np.testing.assert_allclose(
                np.asarray(a, np.float32),
                np.asarray(b, np.float32),
                rtol=0.1,
                atol=2e-3,  # bf16 grads + tanh-vs-erf GELU derivative
            )

    def test_fallback_on_unsupported_shape(self, setup):
        params, _ = setup
        # M=6 not divisible by tile -> must silently fall back, still correct
        x = jax.random.normal(jax.random.PRNGKey(2), (1, 6, 4, 128), jnp.float32)
        got = fused_grouped_ffw(params, x, tile_m=128)
        want = grouped_ffw(params, x)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-6
        )

    def test_bf16(self, setup):
        if jax.devices()[0].platform == "cpu":
            pytest.skip("CPU XLA lacks bf16xbf16->f32 dot; covered on TPU")
        params, x = setup
        pb = jax.tree_util.tree_map(lambda t: t.astype(jnp.bfloat16), params)
        xb = x.astype(jnp.bfloat16)
        got = fused_grouped_ffw(pb, xb, tile_m=128, interpret=True)
        want = grouped_ffw(pb, xb)
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(want, np.float32), rtol=5e-2, atol=5e-2
        )

    def test_auto_tile_small_batch(self, setup):
        """batch=1, n=256 -> M=256 must auto-pick tile 256 and use the kernel
        (not silently fall back)."""
        from glom_tpu.kernels.grouped_mlp import _pick_tile

        assert _pick_tile(256) == 256
        assert _pick_tile(4096) == 512
        assert _pick_tile(6) is None
        params, _ = setup
        x = jax.random.normal(jax.random.PRNGKey(3), (1, 256, 4, 128), jnp.float32)
        got = fused_grouped_ffw(params, x, interpret=True)
        want = grouped_ffw(params, x)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-5
        )

    def test_bwd_accumulates_f32(self, setup):
        """The custom-VJP backward must pin f32 accumulation on every
        contraction regardless of input dtype (checked via the jaxpr, since
        CPU cannot execute bf16 dots). Walks into pallas_call sub-jaxprs so
        the dots inside the fused backward kernel are covered too."""
        from glom_tpu.kernels.grouped_mlp import _bwd

        def all_dots(jaxpr):
            for e in jaxpr.eqns:
                if e.primitive.name == "dot_general":
                    yield e
                for sub in jax.core.jaxprs_in_params(e.params):
                    yield from all_dots(sub)

        params, _ = setup
        pb = jax.tree_util.tree_map(lambda t: t.astype(jnp.bfloat16), params)
        f = pb.w1.shape[-1]
        # level-major [G, M, d]: M=256 takes the fused kernel (with and
        # without a saved pre-activation), M=192 the XLA fallback
        for shape, with_pre in [
            ((4, 256, 128), False),
            ((4, 256, 128), True),
            ((4, 192, 128), False),
        ]:
            x = jnp.zeros(shape, jnp.bfloat16)
            g = jnp.zeros_like(x)
            pre = jnp.zeros((shape[0], shape[1], f), jnp.bfloat16) if with_pre else None
            jaxpr = jax.make_jaxpr(
                lambda p, x_, g_: _bwd(64, False, (p, x_, pre), g_)
            )(pb, x, g)
            dots = list(all_dots(jaxpr.jaxpr))
            # saved-pre kernel drops the recompute contraction (5 -> 4);
            # exact counts so a silent fall-back to the recompute kernel
            # (or a lost contraction) both fail
            if shape[1] == 256:
                assert len(dots) == (4 if with_pre else 5), len(dots)
            else:  # XLA fallback path
                assert len(dots) >= 5, "backward lost its contractions?"
            for e in dots:
                assert e.params["preferred_element_type"] == jnp.float32

    def test_add_kwarg_fallback_matches_explicit(self, setup):
        """f32 (no fold: bf16-only path) add= must equal the explicit
        x + tile(add) composition — the wrapper's fallback correctness."""
        from glom_tpu.kernels import fused_grouped_ffw_lm

        params, _ = setup
        G, n, d = 4, 8, 128
        M = 2 * n
        x = jax.random.normal(jax.random.PRNGKey(5), (G, M, d), jnp.float32)
        a = jax.random.normal(jax.random.PRNGKey(6), (n, d), jnp.float32)

        def loss_add(p, x_, a_):
            out = fused_grouped_ffw_lm(p, x_, add=a_, interpret=True)
            return jnp.mean(out ** 2)

        def loss_exp(p, x_, a_):
            xa = x_ + jnp.tile(a_, (M // n, 1))[None]
            out = fused_grouped_ffw_lm(p, xa, interpret=True)
            return jnp.mean(out ** 2)

        v1, g1 = jax.value_and_grad(loss_add, argnums=(0, 1, 2))(params, x, a)
        v2, g2 = jax.value_and_grad(loss_exp, argnums=(0, 1, 2))(params, x, a)
        np.testing.assert_allclose(float(v1), float(v2), rtol=1e-6)
        for t1, t2 in zip(
            jax.tree_util.tree_leaves(g1), jax.tree_util.tree_leaves(g2)
        ):
            np.testing.assert_allclose(
                np.asarray(t1), np.asarray(t2), rtol=1e-5, atol=1e-6
            )

    def test_add_fold_kernels_match_explicit(self, setup):
        """The FOLD path itself (f32 under interpret — CI coverage of
        _mlp_kernel_add / _mlp_bwd_kernel_saved_add and the whole-grid da
        accumulation): forward and ALL grads incl. da must equal the
        explicit x + tile(add) composition."""
        from glom_tpu.kernels import fused_grouped_ffw_lm
        from glom_tpu.kernels.grouped_mlp import _pick_tile
        from glom_tpu.ops.ffw import init_grouped_ffw

        G, n, d = 3, 128, 128
        M = 2 * n
        params = init_grouped_ffw(jax.random.PRNGKey(9), G, d, mult=4)
        x = jax.random.normal(jax.random.PRNGKey(10), (G, M, d), jnp.float32)
        a = jax.random.normal(jax.random.PRNGKey(11), (n, d), jnp.float32)
        assert _pick_tile(M, d, 4 * d, 4) % n == 0  # the fold gate holds

        def loss_add(p, x_, a_):
            out = fused_grouped_ffw_lm(p, x_, add=a_, interpret=True)
            return jnp.mean(out ** 2)

        def loss_exp(p, x_, a_):
            xa = x_ + jnp.tile(a_, (M // n, 1))[None]
            out = fused_grouped_ffw_lm(p, xa, interpret=True)
            return jnp.mean(out ** 2)

        v1, g1 = jax.value_and_grad(loss_add, argnums=(0, 1, 2))(params, x, a)
        v2, g2 = jax.value_and_grad(loss_exp, argnums=(0, 1, 2))(params, x, a)
        np.testing.assert_allclose(float(v1), float(v2), rtol=1e-5)
        for t1, t2 in zip(
            jax.tree_util.tree_leaves(g1), jax.tree_util.tree_leaves(g2)
        ):
            np.testing.assert_allclose(
                np.asarray(t1), np.asarray(t2), rtol=2e-4, atol=1e-5
            )

    def test_bwd_xla_fallback_grad(self, setup):
        """M=192 has no 128-divisible bwd tile -> _bwd must take the
        barrier+XLA fallback (with explicit fwd tile 64) and still match the
        reference gradients."""
        params, _ = setup
        x = jax.random.normal(jax.random.PRNGKey(5), (3, 64, 4, 128), jnp.float32)

        def loss_fused(p, x_):
            return jnp.mean(fused_grouped_ffw(p, x_, tile_m=64, interpret=True) ** 2)

        def loss_xla(p, x_):
            return jnp.mean(grouped_ffw(p, x_) ** 2)

        g1 = jax.grad(loss_fused, argnums=(0, 1))(params, x)
        g2 = jax.grad(loss_xla, argnums=(0, 1))(params, x)
        for a, b in zip(jax.tree_util.tree_leaves(g1), jax.tree_util.tree_leaves(g2)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-3, atol=1e-5
            )


class TestFusedConsensusUpdate:
    """Blockwise consensus + 4-way mean kernel vs the dense XLA composition."""

    def _reference(self, levels_lm, bu_lm, td_lm, side, radius, attend_self):
        from glom_tpu.kernels.consensus_update import _xla_reference

        return _xla_reference(
            levels_lm, bu_lm, td_lm,
            side=side, radius=radius, attend_self=attend_self,
        )

    def _rand(self, key, L, B, n, d):
        k1, k2, k3 = jax.random.split(key, 3)
        levels = jax.random.normal(k1, (L, B, n, d), jnp.float32)
        bu = jax.random.normal(k2, (L, B, n, d), jnp.float32)
        td = jax.random.normal(k3, (L - 1, B, n, d), jnp.float32)
        return levels, bu, td

    @pytest.mark.parametrize("radius", [0.0, 2.0, 7.0])
    @pytest.mark.parametrize("attend_self", [False, True])
    def test_matches_dense(self, radius, attend_self):
        from glom_tpu.kernels import fused_consensus_update

        L, B, side, d = 3, 2, 8, 128
        n = side * side
        levels, bu, td = self._rand(jax.random.PRNGKey(0), L, B, n, d)
        got = fused_consensus_update(
            levels, bu, td,
            side=side, radius=radius, attend_self=attend_self, interpret=True,
        )
        want = self._reference(levels, bu, td, side, radius, attend_self)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5
        )

    def test_multirow_tiles_online_softmax(self):
        """n large enough that the j-loop runs multiple online-softmax steps:
        side=24 -> n=576, tile 64 -> 9 j-tiles per row-tile, exercising the
        exp(m - m_new) carry correction, fully-masked-row self-healing, and
        the block-sparsity j-window arithmetic."""
        from glom_tpu.kernels.consensus_update import _fused, _pick_tile

        L, B, side, d = 2, 1, 24, 128
        n = side * side
        assert _pick_tile(n) < n, "tile must split n or this test is vacuous"
        levels, bu, td = self._rand(jax.random.PRNGKey(1), L, B, n, d)
        # radius 3 on side 24: live window is a band; far j-tiles are skipped
        got = _fused(levels, bu, td, side, 3.0, False, True)
        want = self._reference(levels, bu, td, side, 3.0, False)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-3, atol=2e-5
        )

    def test_grad_matches_dense(self):
        from glom_tpu.kernels import fused_consensus_update

        L, B, side, d = 3, 1, 4, 128
        n = side * side
        levels, bu, td = self._rand(jax.random.PRNGKey(2), L, B, n, d)

        def loss_fused(lv, b_, t_):
            out = fused_consensus_update(
                lv, b_, t_, side=side, radius=2.0, interpret=True,
                bwd_impl="blockwise",
            )
            return jnp.mean(out ** 2)

        def loss_ref(lv, b_, t_):
            return jnp.mean(self._reference(lv, b_, t_, side, 2.0, False) ** 2)

        g1 = jax.grad(loss_fused, argnums=(0, 1, 2))(levels, bu, td)
        g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(levels, bu, td)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-3, atol=1e-5
            )

    @pytest.mark.parametrize("radius", [0.0, 3.0])
    def test_grad_multirow_tiles(self, radius):
        """Backward across many i/j tiles (side=24 -> n=576, tile 64): the
        dq kernel's recomputed stats must match what the dkv kernel reads
        back, the block-sparse windows must cover exactly the live band in
        BOTH kernels (i-major and j-major), and ds must vanish on the
        replaced diagonal."""
        from glom_tpu.kernels.consensus_update import _fused, _xla_reference

        L, B, side, d = 2, 1, 24, 128
        n = side * side
        levels, bu, td = self._rand(jax.random.PRNGKey(7), L, B, n, d)

        def loss_fused(lv, b_, t_):
            out = _fused(lv, b_, t_, side, radius, False, True, "blockwise")
            return jnp.mean(out ** 2)

        def loss_ref(lv, b_, t_):
            out = _xla_reference(
                lv, b_, t_, side=side, radius=radius, attend_self=False
            )
            return jnp.mean(out ** 2)

        g1 = jax.grad(loss_fused, argnums=(0, 1, 2))(levels, bu, td)
        g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(levels, bu, td)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-5
            )

    @pytest.mark.parametrize("radius", [0.0, 3.0])
    def test_grad_two_pass_fallback(self, radius, monkeypatch):
        """Rows too long for the one-sweep kernel's resident dq block fall
        back to the two-pass dq/dkv kernels — forced here by disabling the
        one-sweep eligibility so both generations stay covered."""
        from glom_tpu.kernels import consensus_update as cu

        monkeypatch.setattr(cu, "_onesweep_ok", lambda *a: False)
        L, B, side, d = 2, 1, 24, 128
        n = side * side
        levels, bu, td = self._rand(jax.random.PRNGKey(8), L, B, n, d)

        def loss_fused(lv, b_, t_):
            out = cu._fused(lv, b_, t_, side, radius, False, True, "blockwise")
            return jnp.mean(out ** 2)

        def loss_ref(lv, b_, t_):
            out = cu._xla_reference(
                lv, b_, t_, side=side, radius=radius, attend_self=False
            )
            return jnp.mean(out ** 2)

        g1 = jax.grad(loss_fused, argnums=(0, 1, 2))(levels, bu, td)
        g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(levels, bu, td)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-5
            )

    def test_dense_stats_bwd_matches(self):
        """The explicit stats-based dense backward (bwd_impl='dense'
        through the custom_vjp) vs plain autodiff of the XLA reference."""
        from glom_tpu.kernels.consensus_update import _fused, _xla_reference

        L, B, side, d = 3, 2, 4, 128
        n = side * side
        levels, bu, td = self._rand(jax.random.PRNGKey(9), L, B, n, d)

        def loss_fused(lv, b_, t_):
            out = _fused(lv, b_, t_, side, 0.0, False, True, "dense")
            return jnp.mean(out ** 2)

        def loss_ref(lv, b_, t_):
            out = _xla_reference(
                lv, b_, t_, side=side, radius=0.0, attend_self=False
            )
            return jnp.mean(out ** 2)

        g1 = jax.grad(loss_fused, argnums=(0, 1, 2))(levels, bu, td)
        g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(levels, bu, td)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-3, atol=1e-5
            )

    def test_streamed_forward_matches(self, monkeypatch):
        """The large-n streamed forward layout (j as a windowed inner grid
        axis, (m,l,acc) in scratch) must match the resident-row kernel and
        the dense reference — forced here by dropping _FWD_ROW_LIMIT so
        interpret mode exercises it at test size, incl. the saved-stats
        path through the blockwise backward."""
        from glom_tpu.kernels import consensus_update as cu

        L, B, side, d = 2, 1, 24, 128
        n = side * side
        levels, bu, td = self._rand(jax.random.PRNGKey(5), L, B, n, d)
        for radius in (0.0, 3.0):
            want = self._reference(levels, bu, td, side, radius, False)
            monkeypatch.setattr(cu, "_FWD_ROW_LIMIT", 1)
            got = cu._fused(levels, bu, td, side, radius, False, True, "auto")
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), rtol=1e-3, atol=2e-5
            )

            def loss(lv):
                out = cu._fused(lv, bu, td, side, radius, False, True,
                                "blockwise")
                return jnp.mean(out ** 2)

            def loss_ref(lv):
                out = cu._xla_reference(
                    lv, bu, td, side=side, radius=radius, attend_self=False
                )
                return jnp.mean(out ** 2)

            g1 = jax.grad(loss)(levels)
            monkeypatch.undo()
            g2 = jax.grad(loss_ref)(levels)
            np.testing.assert_allclose(
                np.asarray(g1), np.asarray(g2), rtol=2e-3, atol=2e-5
            )

    def test_grad_dense_dispatch_matches_blockwise(self):
        """Both sides of the backward dispatch (dense-recompute VJP vs the
        streamed blockwise kernels) must produce the same gradients; 'auto'
        must agree with whichever side it picks."""
        from glom_tpu.kernels.consensus_update import _fused

        L, B, side, d = 2, 1, 8, 128
        n = side * side
        levels, bu, td = self._rand(jax.random.PRNGKey(11), L, B, n, d)

        def grads(impl):
            def loss(lv, b_, t_):
                out = _fused(lv, b_, t_, side, 0.0, False, True, impl)
                return jnp.mean(out ** 2)

            return jax.grad(loss, argnums=(0, 1, 2))(levels, bu, td)

        g_block, g_dense, g_auto = grads("blockwise"), grads("dense"), grads("auto")
        for a, b, c in zip(g_block, g_dense, g_auto):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-3, atol=1e-5
            )
            np.testing.assert_allclose(
                np.asarray(c), np.asarray(b), rtol=2e-3, atol=1e-5
            )

    def test_bwd_dispatch_predicate(self):
        """The measured-crossover dispatch (results/longctx_bench.jsonl,
        round 4): long global rows go to the ONE-SWEEP blockwise kernel
        (wins from n=4096 up: 5.6 vs 7.2 ms at n=4096 B=1, 27.6 vs 30.5 at
        n=9216); mid rows stay dense; a truly-sparse local band goes
        blockwise; forced sides are honored."""
        from glom_tpu.kernels.consensus_update import _use_blockwise_bwd

        # flagship train (B=64, single-tile row): batched regime ->
        # blockwise (measured faster at the full train step)
        assert _use_blockwise_bwd((6, 64, 256, 512), 16, 0.0, "auto")
        # small-batch inference-style at n=256 -> dense
        assert not _use_blockwise_bwd((6, 2, 256, 512), 16, 0.0, "auto")
        # mid global rows: dense autodiff wins (0.281 vs 0.388 at n=1024)
        assert not _use_blockwise_bwd((6, 8, 1024, 512), 32, 0.0, "auto")
        assert not _use_blockwise_bwd((6, 1, 1024, 512), 32, 0.0, "auto")
        # long global rows (any batch): the one-sweep kernel wins
        assert _use_blockwise_bwd((6, 1, 4096, 512), 64, 0.0, "auto")
        assert _use_blockwise_bwd((6, 8, 4096, 512), 64, 0.0, "auto")
        assert _use_blockwise_bwd((6, 1, 9216, 512), 96, 0.0, "auto")
        # n=4096, radius 7 on side 64: band covers <1/2 the row -> blockwise
        assert _use_blockwise_bwd((6, 1, 4096, 512), 64, 7.0, "auto")
        # n=16384 global (side 128): one-sweep dq block still fits -> blockwise
        assert _use_blockwise_bwd((6, 1, 16384, 512), 128, 0.0, "auto")
        # forced
        assert _use_blockwise_bwd((6, 64, 256, 512), 16, 0.0, "blockwise")
        assert not _use_blockwise_bwd((6, 1, 4096, 512), 64, 7.0, "dense")

    def test_top_level_divisor_and_zero_topdown(self):
        """Top level must ignore td entirely and divide by 3 (reference
        :121-122/:130): poisoning td's clamped top tile must not change out."""
        from glom_tpu.kernels import fused_consensus_update

        L, B, side, d = 3, 1, 4, 128
        n = side * side
        levels, bu, td = self._rand(jax.random.PRNGKey(3), L, B, n, d)
        out1 = fused_consensus_update(
            levels, bu, td, side=side, interpret=True
        )
        td_poison = td.at[-1].set(1e6)
        out2 = fused_consensus_update(
            levels, bu, td_poison, side=side, interpret=True
        )
        # top level identical (never reads td), level L-2 changes
        np.testing.assert_allclose(
            np.asarray(out1[-1]), np.asarray(out2[-1]), rtol=0, atol=0
        )
        assert not np.allclose(np.asarray(out1[-2]), np.asarray(out2[-2]))


class TestFusedForwardParity:
    """The use_pallas=True fused level-major forward must match the
    reference-layout path on every contract point (CPU: kernels fall back to
    XLA, so this locks the LAYOUT/plumbing; kernel math is locked above in
    interpret mode and on TPU)."""

    def _cfg(self, **kw):
        from glom_tpu.utils.config import GlomConfig

        base = dict(dim=128, levels=4, image_size=32, patch_size=8)
        base.update(kw)
        return GlomConfig(**base)

    def _run(self, cfg, **kw):
        from glom_tpu.models.core import glom_forward, init_glom

        params = init_glom(jax.random.PRNGKey(0), cfg)
        img = jax.random.normal(jax.random.PRNGKey(1), (2, 3, cfg.image_size, cfg.image_size))
        ref = glom_forward(params, img, cfg, use_pallas=False, **kw)
        fused = glom_forward(params, img, cfg, use_pallas=True, **kw)
        return ref, fused

    def test_forward(self):
        ref, fused = self._run(self._cfg())
        np.testing.assert_allclose(np.asarray(fused), np.asarray(ref), rtol=2e-4, atol=2e-5)

    def test_return_all_and_radius(self):
        ref, fused = self._run(self._cfg(local_consensus_radius=2), return_all=True, iters=3)
        assert fused.shape == ref.shape  # [T+1, b, n, L, d]
        np.testing.assert_allclose(np.asarray(fused), np.asarray(ref), rtol=2e-4, atol=2e-5)

    def test_levels_carry_in(self):
        from glom_tpu.models.core import glom_forward, init_glom

        cfg = self._cfg()
        params = init_glom(jax.random.PRNGKey(0), cfg)
        img = jax.random.normal(jax.random.PRNGKey(1), (2, 3, 32, 32))
        lv = glom_forward(params, img, cfg, iters=2)
        ref = glom_forward(params, img, cfg, iters=2, levels=lv, use_pallas=False)
        fused = glom_forward(params, img, cfg, iters=2, levels=lv, use_pallas=True)
        np.testing.assert_allclose(np.asarray(fused), np.asarray(ref), rtol=2e-4, atol=2e-5)

    def test_grad_and_remat(self):
        from glom_tpu.models.core import glom_forward, init_glom

        cfg = self._cfg()
        params = init_glom(jax.random.PRNGKey(0), cfg)
        img = jax.random.normal(jax.random.PRNGKey(1), (1, 3, 32, 32))

        def loss(p, up, rm):
            return jnp.mean(glom_forward(p, img, cfg, iters=2, use_pallas=up, remat=rm) ** 2)

        g_ref = jax.grad(loss)(params, False, False)
        g_fused = jax.grad(loss)(params, True, True)
        for a, b in zip(jax.tree_util.tree_leaves(g_ref), jax.tree_util.tree_leaves(g_fused)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-3, atol=1e-5)


class TestFusedLoop:
    """The hand-rolled whole-loop VJP (kernels/fused_loop.py) vs a reference
    loop composed from the XLA ops (models.core.update_step) — forward and
    EVERY cotangent (both FFWs' weights, pos_emb, tokens, levels0)."""

    L, B, n, d, side = 4, 8, 16, 128, 4

    def _inputs(self, dtype=jnp.float32):
        from glom_tpu.ops.ffw import init_grouped_ffw

        k = jax.random.split(jax.random.PRNGKey(0), 5)
        bu = init_grouped_ffw(k[0], self.L, self.d, 4, dtype)
        td = init_grouped_ffw(k[1], self.L - 1, self.d, 4, dtype)
        pos = jax.random.normal(k[2], (self.n, self.d), dtype)
        tokens = jax.random.normal(k[3], (self.B, self.n, self.d), dtype)
        lv0 = jax.random.normal(k[4], (self.L, self.B, self.n, self.d), dtype)
        return bu, td, pos, tokens, lv0

    def _ref_loop(self, bu_p, td_p, pos, tokens, lv0, iters, radius, attend_self):
        from functools import partial

        from glom_tpu.models.core import contribution_divisor, update_step
        from glom_tpu.ops.consensus import build_local_mask, consensus_attention

        class P:  # update_step only touches these three fields
            bottom_up, top_down, pos_emb = bu_p, td_p, pos

        levels = jnp.transpose(lv0, (1, 2, 0, 3))  # [B, n, L, d]
        bottom = tokens[:, :, None, :]
        pos4 = pos[None, :, None, :]
        div = contribution_divisor(self.L)
        cons = partial(
            consensus_attention,
            attend_self=attend_self,
            local_mask=build_local_mask(self.side, radius),
        )
        for _ in range(iters):
            levels = update_step(P, levels, bottom, pos4, div, consensus_fn=cons)
        return jnp.transpose(levels, (2, 0, 1, 3))

    @pytest.mark.parametrize(
        "radius,attend_self", [(0.0, False), (1.5, False), (0.0, True)]
    )
    def test_forward_and_grads(self, radius, attend_self):
        from glom_tpu.kernels.fused_loop import fused_glom_loop, loop_supported

        assert loop_supported(self.L, self.B, self.n, self.d, 4 * self.d, 4, 3, self.n)
        args = self._inputs()
        iters = 3

        def loss_loop(*a):
            out = fused_glom_loop(
                *a, iters, self.side, radius, attend_self, True
            )
            return jnp.mean(out**2), out

        def loss_ref(*a):
            out = self._ref_loop(*a, iters, radius, attend_self)
            return jnp.mean(out**2), out

        (l1, o1), g1 = jax.value_and_grad(loss_loop, argnums=tuple(range(5)), has_aux=True)(*args)
        (l2, o2), g2 = jax.value_and_grad(loss_ref, argnums=tuple(range(5)), has_aux=True)(*args)
        np.testing.assert_allclose(np.asarray(o1), np.asarray(o2), rtol=2e-4, atol=2e-5)
        for a, b in zip(jax.tree_util.tree_leaves(g1), jax.tree_util.tree_leaves(g2)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-5
            )

    def test_single_iteration(self):
        """iters=1 exercises the no-combine backward variant alone."""
        from glom_tpu.kernels.fused_loop import fused_glom_loop

        args = self._inputs()

        def loss_loop(*a):
            return jnp.mean(
                fused_glom_loop(*a, 1, self.side, 0.0, False, True) ** 2
            )

        def loss_ref(*a):
            return jnp.mean(self._ref_loop(*a, 1, 0.0, False) ** 2)

        g1 = jax.grad(loss_loop, argnums=tuple(range(5)))(*args)
        g2 = jax.grad(loss_ref, argnums=tuple(range(5)))(*args)
        for a, b in zip(jax.tree_util.tree_leaves(g1), jax.tree_util.tree_leaves(g2)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-5
            )

    def test_two_levels(self):
        """L=2 exercises the final-combine else branch (no middle slice)."""
        from glom_tpu.kernels.fused_loop import fused_glom_loop
        from glom_tpu.ops.ffw import init_grouped_ffw

        L, B, n, d = 2, 8, 16, 128
        k = jax.random.split(jax.random.PRNGKey(7), 5)
        args = (
            init_grouped_ffw(k[0], L, d, 4),
            init_grouped_ffw(k[1], L - 1, d, 4),
            jax.random.normal(k[2], (n, d)),
            jax.random.normal(k[3], (B, n, d)),
            jax.random.normal(k[4], (L, B, n, d)),
        )
        old_L = type(self).L
        type(self).L = L
        try:
            def loss_loop(*a):
                return jnp.mean(
                    fused_glom_loop(*a, 2, self.side, 0.0, False, True) ** 2
                )

            def loss_ref(*a):
                return jnp.mean(self._ref_loop(*a, 2, 0.0, False) ** 2)

            g1 = jax.grad(loss_loop, argnums=tuple(range(5)))(*args)
            g2 = jax.grad(loss_ref, argnums=tuple(range(5)))(*args)
        finally:
            type(self).L = old_L
        for a, b in zip(jax.tree_util.tree_leaves(g1), jax.tree_util.tree_leaves(g2)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-5
            )

    def test_zero_iters_not_dispatched(self):
        from glom_tpu.kernels.fused_loop import loop_supported

        assert not loop_supported(6, 64, 256, 512, 2048, 2, 0, 256)

    def test_primal_matches_vjp_forward(self):
        """The no-grad primal (plain [L]-carry body) and the vjp forward
        (the [L+1]-slot body) are different computations of the same math —
        both must match the reference."""
        from glom_tpu.kernels.fused_loop import fused_glom_loop

        args = self._inputs()
        primal = fused_glom_loop(*args, 3, self.side, 0.0, False, True)
        ref = self._ref_loop(*args, 3, 0.0, False)
        np.testing.assert_allclose(
            np.asarray(primal), np.asarray(ref), rtol=2e-4, atol=2e-5
        )

    def test_dispatch_gate(self):
        """loop_supported must reject the shapes the kernels cannot tile."""
        from glom_tpu.kernels.fused_loop import loop_supported

        ok = loop_supported(6, 64, 256, 512, 2048, 2, 7, 256)
        assert ok  # the flagship training shape
        assert not loop_supported(6, 64, 1024, 512, 2048, 2, 7, 1024)  # n too big
        assert not loop_supported(6, 1, 6, 512, 2048, 2, 7, 6)  # untileable M
        assert not loop_supported(6, 64, 256, 512, 2048, 2, 7, 128)  # pos mismatch

    # The local-mask radius exercises the identical remat machinery on a
    # different mask — slow-marked for the tier-1 budget; CI runs it.
    @pytest.mark.parametrize(
        "radius", [0.0, pytest.param(1.5, marks=pytest.mark.slow)]
    )
    def test_remat_matches_nonremat(self, radius):
        """remat=True drops the pre-activation residuals and recomputes them
        in the backward via the first-matmul-only kernel — the SAME
        f32-accumulate dot + cast the forward would have saved, so every
        cotangent must match the non-remat VJP bit-exactly."""
        from glom_tpu.kernels.fused_loop import fused_glom_loop

        args = self._inputs()

        def loss(remat):
            def f(*a):
                return jnp.mean(
                    fused_glom_loop(
                        *a, 3, self.side, radius, False, True, remat
                    )
                    ** 2
                )

            return f

        g0 = jax.grad(loss(False), argnums=tuple(range(5)))(*args)
        g1 = jax.grad(loss(True), argnums=tuple(range(5)))(*args)
        for a, b in zip(
            jax.tree_util.tree_leaves(g0), jax.tree_util.tree_leaves(g1)
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # Env-gated special mode, ~25-35s of interpret-mode backward: slow-
    # marked for the tier-1 budget; CI runs it unfiltered and the hw
    # queue's tpu_validate covers the real-chip variant.
    @pytest.mark.slow
    def test_unchained_backward_matches(self, monkeypatch):
        """The unchained backward variant (pod per-TP-rank d=1024-class
        shapes, where in-kernel accumulator chaining exceeds the
        working-set budget) must produce the same cotangents as the
        chained flagship variant — same kernels' math, the cross-iteration
        dw/da accumulation just moves to XLA adds."""
        from glom_tpu.kernels import fused_loop

        args = self._inputs()

        def loss(*a):
            return jnp.mean(
                fused_loop.fused_glom_loop(*a, 3, self.side, 0.0, False, True)
                ** 2
            )

        g_chained = jax.grad(loss, argnums=tuple(range(5)))(*args)
        monkeypatch.setattr(fused_loop, "_chain_ws_ok", lambda *a: False)
        g_unchained = jax.grad(loss, argnums=tuple(range(5)))(*args)
        for a, b in zip(
            jax.tree_util.tree_leaves(g_chained),
            jax.tree_util.tree_leaves(g_unchained),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7
            )

    def test_pod_per_rank_shape_admitted(self):
        """BASELINE config 5's per-TP-rank shape (L=12, d=1024, f/mp=2048,
        batch 16, remat) must ride the fused loop via the unchained
        backward — the regime round 4 left on the scan path."""
        from glom_tpu.kernels.fused_loop import _chain_ws_ok, loop_supported

        assert loop_supported(12, 16, 256, 1024, 2048, 2, 13, 256, remat=True)
        # ...through the unchained variant specifically:
        from glom_tpu.kernels.grouped_mlp import _pick_bwd_tile

        bt = _pick_bwd_tile(16 * 256, 1024, 2048, 2)
        assert bt is not None and not _chain_ws_ok(bt, 1024, 2048, 2, 256)
        # the flagship stays on the (measured-faster) chained variant
        bt_f = _pick_bwd_tile(64 * 256, 512, 2048, 2)
        assert _chain_ws_ok(bt_f, 512, 2048, 2, 256)

    # Same grid-relayout check on the local mask — slow-marked for the
    # tier-1 budget; CI runs it.
    @pytest.mark.parametrize(
        "radius", [0.0, pytest.param(1.5, marks=pytest.mark.slow)]
    )
    def test_combined_grid_matches_split(self, monkeypatch, radius):
        """GLOM_LOOP_GRID=combined (one 2L-1-group pallas_call per phase
        per iteration instead of separate bu/td calls) is a pure grid
        relayout: same per-group math, same accumulation order — loss and
        every cotangent must match the split default to float-exactness,
        in both the saved-pre and remat modes."""
        from glom_tpu.kernels import fused_loop

        args = self._inputs()

        def loss(remat):
            def f(*a):
                return jnp.mean(
                    fused_loop.fused_glom_loop(
                        *a, 3, self.side, radius, False, True, remat
                    )
                    ** 2
                )

            return f

        vg = lambda remat: jax.value_and_grad(
            loss(remat), argnums=tuple(range(5))
        )(*args)
        # pin the baseline: an exported GLOM_LOOP_GRID=combined in the
        # developer's shell must not turn this into a self-comparison
        monkeypatch.setenv("GLOM_LOOP_GRID", "split")
        l_split, g_split = vg(False)
        monkeypatch.setenv("GLOM_LOOP_GRID", "combined")
        l_comb, g_comb = vg(False)
        l_comb_r, g_comb_r = vg(True)
        np.testing.assert_allclose(float(l_split), float(l_comb), rtol=1e-6)
        np.testing.assert_allclose(float(l_split), float(l_comb_r), rtol=1e-6)
        for want in (g_comb, g_comb_r):
            for a, b in zip(
                jax.tree_util.tree_leaves(g_split),
                jax.tree_util.tree_leaves(want),
            ):
                np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7
                )

    def test_remat_admits_bigger_residuals(self):
        """The remat residual stack (carry + stats only) fits shapes the
        full stack cannot: flagship batch 128 x 12 iters is 20.6GB of
        non-remat residuals (> the 10GB budget) but 2.8GB under remat —
        BASELINE config 5's regime rides the fused loop now."""
        from glom_tpu.kernels.fused_loop import loop_supported

        assert not loop_supported(6, 128, 256, 512, 2048, 2, 12, 256)
        assert loop_supported(6, 128, 256, 512, 2048, 2, 12, 256, remat=True)
