"""Pure-NumPy oracle of the GLOM forward contract (SURVEY.md §3.2).

This is an INDEPENDENT implementation — written directly from the behavioral
spec, sharing no code with glom_tpu — used to lock every contract subtlety:

  1. iters default = 2 * levels
  2. pos-emb added ONLY to the top-down net's input, every iteration
  3. k-only L2 normalization in consensus attention, scale d^-1/2
  4. self-mask value -5e-4 (soft replace); local-radius mask -finfo.max (hard)
  5. per-level divisor: 4 everywhere, 3 at the TOP level (zero-padded top-down)
  6. return_all yields T+1 states including the initial one
  7. `levels` may be passed in (temporal carry)
  8. the update is a plain unweighted mean — no gating/norm

All math float64 by default for a tight tolerance against float32 JAX.
"""

from __future__ import annotations

import numpy as np

TOKEN_ATTEND_SELF_VALUE = -5e-4


def np_gelu(x):
    """Exact (erf) GELU."""
    from scipy.special import erf  # scipy available transitively; fallback below

    return 0.5 * x * (1.0 + erf(x / np.sqrt(2.0)))


try:  # pragma: no cover - environment probe
    import scipy.special  # noqa: F401
except ImportError:  # pragma: no cover
    from math import erf as _erf

    def np_gelu(x):  # type: ignore[no-redef]
        return 0.5 * x * (1.0 + np.vectorize(_erf)(x / np.sqrt(2.0)))


def np_l2norm(x, axis=-1, eps=1e-12):
    n = np.linalg.norm(x, axis=axis, keepdims=True)
    return x / np.maximum(n, eps)


def np_grouped_ffw(params, x):
    """x: [..., G, d]; params dict with w1 [G,d,f], b1 [G,f], w2 [G,f,d], b2 [G,d]."""
    h = np.einsum("...gd,gdf->...gf", x, params["w1"]) + params["b1"]
    h = np_gelu(h)
    return np.einsum("...gf,gfd->...gd", h, params["w2"]) + params["b2"]


def np_local_mask(side, radius):
    if radius <= 0:
        return None
    hs, ws = np.meshgrid(np.arange(side), np.arange(side), indexing="ij")
    coords = np.stack([hs, ws], -1).reshape(-1, 2).astype(np.float64)
    dist = np.linalg.norm(coords[:, None] - coords[None, :], axis=-1)
    return dist > radius


def np_consensus(levels, attend_self=False, local_mask=None):
    """levels: [b, n, L, d] -> [b, n, L, d]."""
    b, n, L, d = levels.shape
    q = levels
    k = np_l2norm(levels)
    sim = np.einsum("bild,bjld->blij", q, k) * (d ** -0.5)
    if not attend_self:
        eye = np.eye(n, dtype=bool)
        sim = np.where(eye[None, None], TOKEN_ATTEND_SELF_VALUE, sim)
    if local_mask is not None:
        sim = np.where(local_mask[None, None], -np.finfo(sim.dtype).max, sim)
    sim = sim - sim.max(axis=-1, keepdims=True)
    attn = np.exp(sim)
    attn = attn / attn.sum(axis=-1, keepdims=True)
    return np.einsum("blij,bjld->bild", attn, levels)


def np_patchify(img, p):
    """[b, c, H, W] -> [b, n, p*p*c], channel innermost per patch."""
    b, c, H, W = img.shape
    h, w = H // p, W // p
    x = img.reshape(b, c, h, p, w, p)
    x = x.transpose(0, 2, 4, 3, 5, 1)  # b h w p1 p2 c
    return x.reshape(b, h * w, p * p * c)


def np_unpatchify(x, p, image_size, c=3):
    b, n, _ = x.shape
    h = image_size // p
    x = x.reshape(b, h, h, p, p, c)
    x = x.transpose(0, 5, 1, 3, 2, 4)  # b c h p1 w p2
    return x.reshape(b, c, h * p, h * p)


def np_forward(
    params,
    img,
    *,
    levels_cfg,
    patch_size,
    iters=None,
    levels=None,
    return_all=False,
    attend_self=False,
    local_mask=None,
):
    """Full GLOM forward. params: dict with keys
    token_w [p*p*c, d], token_b [d], pos_emb [n, d], init_levels [L, d],
    bottom_up {w1,b1,w2,b2} (G=L), top_down {...} (G=L-1).
    """
    L = levels_cfg
    T = iters if iters is not None else 2 * L

    tokens = np_patchify(img, patch_size) @ params["token_w"] + params["token_b"]
    b, n, d = tokens.shape
    pos = params["pos_emb"][None, :, None, :]  # [1, n, 1, d]
    bottom = tokens[:, :, None, :]  # [b, n, 1, d]

    if levels is None:
        levels = np.broadcast_to(params["init_levels"][None, None], (b, n, L, d)).copy()

    hiddens = [levels]
    divisor = np.full((L, 1), 4.0)
    divisor[-1] = 3.0  # top level has no top-down contribution

    for _ in range(T):
        with_input = np.concatenate([bottom, levels], axis=2)  # [b, n, L+1, d]
        bu = np_grouped_ffw(params["bottom_up"], with_input[:, :, :-1, :])
        td = np_grouped_ffw(params["top_down"], with_input[:, :, 2:, :] + pos)
        td = np.concatenate([td, np.zeros_like(td[:, :, :1])], axis=2)
        cons = np_consensus(levels, attend_self=attend_self, local_mask=local_mask)
        levels = (levels + bu + td + cons) / divisor
        hiddens.append(levels)

    if return_all:
        return np.stack(hiddens)  # [T+1, b, n, L, d]
    return levels
