"""Decision-chain audit (glom_tpu/telemetry/audit.py, ISSUE 18).

The tier-1 locks:

  * policy_action IS the PR 14 reactive policy on a stamped evidence
    bundle — breach precedence, dwell hysteresis, min/max clamps — and
    the anticipatory extension adds exactly one signal: a positive
    anticipated_deficit arms scale-out and vetoes scale-in;
  * anticipated_deficit's maturity gate: any missing/unmatured input
    (null predicted, null forecast_abs_err, null lead time, no measured
    service rate) pins the deficit to None — reactive semantics;
  * audit_records reconstructs the per-fleet decision chain, demands
    EVIDENCE CONSERVATION (stamped inputs replay to the stamped action
    bit-for-bit), flags unchained actuations, and scores per-decision
    regret from failure evidence inside each cover window;
  * the CLI exits 0 on clean evidence, 1 on errors (and on warnings
    under --strict).

Pure stdlib — no jax, no numpy, no clocks.
"""

import json

import pytest

from glom_tpu.telemetry.audit import (
    anticipated_deficit,
    audit_records,
    main as audit_main,
    policy_action,
)


def _evidence(**kw):
    ev = {
        "n_engines": 1,
        "min_engines": 1,
        "max_engines": 4,
        "breaches": [],
        "headroom": 0.5,
        "low_water": 0.2,
        "high_water": 0.7,
        "dwell_s": 1.0,
        "below_held_s": None,
        "above_held_s": None,
        "anticipatory": False,
        "target_utilization": 0.8,
        "forecast": None,
        "lead_time_ms": None,
        "lead_quantile": None,
        "fleet_service_rate_rps": None,
    }
    ev.update(kw)
    return ev


def _matured(**kw):
    """Fully matured anticipatory evidence: predicted 50 rps against a
    10 rps fleet at 0.8 target — deficit decisively positive."""
    ev = _evidence(
        anticipatory=True,
        forecast={
            "predicted": 50.0,
            "forecast_abs_err": 1.0,
            "horizon_s": 0.5,
            "trend_per_s": 10.0,
            "t": 1.0,
        },
        lead_time_ms=800.0,
        lead_quantile=0.9,
        fleet_service_rate_rps=10.0,
    )
    ev.update(kw)
    return ev


class TestPolicyAction:
    def test_reactive_quiet_fleet_holds(self):
        assert policy_action(_evidence()) is None

    def test_breach_forces_scale_out_and_vetoes_scale_in(self):
        assert policy_action(_evidence(breaches=["p99_ms"])) == "scale_out"
        # At the ceiling the breach still VETOES scale-in (None, not in).
        assert policy_action(
            _evidence(breaches=["p99_ms"], n_engines=4, above_held_s=5.0)
        ) is None

    def test_dwell_gates_watermarks(self):
        assert policy_action(_evidence(below_held_s=0.5)) is None
        assert policy_action(_evidence(below_held_s=1.0)) == "scale_out"
        assert policy_action(
            _evidence(n_engines=2, above_held_s=0.5)
        ) is None
        assert policy_action(
            _evidence(n_engines=2, above_held_s=1.5)
        ) == "scale_in"

    def test_min_max_clamps(self):
        assert policy_action(
            _evidence(n_engines=4, below_held_s=9.0)
        ) is None
        assert policy_action(
            _evidence(n_engines=1, above_held_s=9.0)
        ) is None

    def test_anticipated_deficit_arms_scale_out(self):
        assert policy_action(_matured()) == "scale_out"

    def test_anticipated_deficit_vetoes_scale_in(self):
        assert policy_action(
            _matured(n_engines=4, above_held_s=9.0)
        ) is None

    def test_unmatured_forecast_is_reactive_bit_for_bit(self):
        """The satellite pin: every maturity gate, knocked out one at a
        time, must reproduce the REACTIVE action on otherwise identical
        evidence — an unproven forecast never spends hardware."""
        reactive = _evidence()
        degradations = (
            _matured(forecast=None),
            _matured(forecast={"predicted": None, "forecast_abs_err": 1.0}),
            _matured(forecast={"predicted": 50.0,
                               "forecast_abs_err": None}),
            _matured(lead_time_ms=None),
            _matured(fleet_service_rate_rps=None),
            _matured(fleet_service_rate_rps=0.0),
        )
        for ev in degradations:
            assert anticipated_deficit(ev) is None, ev
            assert policy_action(ev) == policy_action(reactive), ev

    def test_degenerate_pinned_fit_never_scales_out(self):
        """A degenerate fit stamps predicted null (+ reason) — the
        deficit pins None and the quiet fleet holds."""
        ev = _matured(forecast={
            "predicted": None,
            "degenerate": "insufficient-samples",
            "forecast_abs_err": 1.0,
        })
        assert anticipated_deficit(ev) is None
        assert policy_action(ev) is None


class TestAnticipatedDeficit:
    def test_trend_extrapolates_past_horizon(self):
        # lead 800ms, horizon 500ms: 0.3s of extra trend at 10 rps/s.
        ev = _matured()
        assert anticipated_deficit(ev) == pytest.approx(
            50.0 + 10.0 * 0.3 - 10.0 * 0.8
        )

    def test_lead_shorter_than_horizon_keeps_forecast(self):
        ev = _matured(lead_time_ms=100.0)  # 0.1s < horizon 0.5s
        assert anticipated_deficit(ev) == pytest.approx(50.0 - 8.0)

    def test_capacity_surplus_goes_negative(self):
        ev = _matured(
            forecast={"predicted": 2.0, "forecast_abs_err": 0.5,
                      "horizon_s": 0.5, "trend_per_s": 0.0},
            fleet_service_rate_rps=10.0,
        )
        d = anticipated_deficit(ev)
        assert d is not None and d < 0
        assert policy_action(ev) is None


def _decision(did, action, evidence, *, prev=None, fleet="fleet0", t=0.0):
    return {
        "kind": "decision", "schema_version": 10, "t": t, "fleet": fleet,
        "decision_id": did, "prev_decision_id": prev, "action": action,
        "evidence": evidence,
    }


def _serve(event, did, *, fleet="fleet0", t=0.0, **kw):
    rec = {"kind": "serve", "event": event, "fleet": fleet, "t": t}
    if did is not None:
        rec["decision_id"] = did
    rec.update(kw)
    return rec


def _chain():
    """One clean scale-out -> scale-in run."""
    out_ev = _evidence(breaches=["p99_ms"])
    in_ev = _evidence(n_engines=2, above_held_s=5.0)
    return [
        _decision(1, "scale_out", out_ev, t=1.0),
        _serve("scale_out_decision", 1, t=1.0),
        _serve("scale_out", 1, t=1.2, spawn_ms=150.0),
        _serve("admission_open", 1, t=1.2),
        _decision(2, "scale_in", in_ev, prev=1, t=5.0),
        _serve("scale_in_decision", 2, t=5.0),
        _serve("drain_release", 2, t=5.3),
    ]


class TestAuditRecords:
    def test_clean_chain_conserves(self):
        rep = audit_records(_chain())
        assert rep["errors"] == [] and rep["warnings"] == []
        assert rep["n_decisions"] == 2 and rep["n_conserved"] == 2
        assert rep["fleets"] == ["fleet0"]
        # Scaled out WITH a live breach: late by definition.
        assert rep["decisions_late"] == 1
        assert rep["spawn_lead_violations"] == 0

    def test_corrupted_evidence_breaks_conservation(self):
        recs = _chain()
        recs[0] = _decision(1, "scale_out", _evidence(), t=1.0)  # quiet!
        rep = audit_records(recs)
        assert any("replays to" in e for e in rep["errors"])
        assert rep["n_conserved"] == 1

    def test_chain_gap_and_bad_prev_flagged(self):
        recs = [
            _decision(1, "scale_out", _evidence(breaches=["x"]), t=1.0),
            _serve("scale_out", 1, t=1.1, spawn_ms=10.0),
            _decision(3, "scale_out", _evidence(breaches=["x"]),
                      prev=2, t=2.0),
            _serve("scale_out", 3, t=2.1, spawn_ms=10.0),
        ]
        rep = audit_records(recs)
        assert any("chain gap" in e for e in rep["errors"])
        assert any("prev_decision_id" in e for e in rep["errors"])

    def test_unchained_actuation_is_an_error(self):
        rep = audit_records([_serve("scale_out", None, t=1.0)])
        assert any("no decision_id" in e for e in rep["errors"])

    def test_orphan_decision_warns_only(self):
        rep = audit_records(
            [_decision(1, "scale_out", _evidence(breaches=["x"]), t=1.0)]
        )
        assert rep["errors"] == []
        assert any("actuated no serve" in w for w in rep["warnings"])

    def test_wrong_family_chaining_flagged(self):
        recs = [
            _decision(1, "scale_out", _evidence(breaches=["x"]), t=1.0),
            _serve("drain_release", 1, t=1.5),
        ]
        rep = audit_records(recs)
        assert any("not scale_out" not in e and "scale_out" in e
                   for e in rep["errors"])

    def test_fleets_audit_independently(self):
        recs = []
        for fleet in ("reactive", "anticipatory"):
            recs += [
                _decision(1, "scale_out", _evidence(breaches=["x"]),
                          fleet=fleet, t=1.0),
                _serve("scale_out", 1, fleet=fleet, t=1.1, spawn_ms=9.0),
            ]
        rep = audit_records(recs)
        assert rep["errors"] == []
        assert rep["fleets"] == ["anticipatory", "reactive"]
        assert rep["n_decisions"] == 2

    def test_regret_counts_failures_inside_cover_window(self):
        ev = _matured()  # lead 800ms -> cover 0.8s
        recs = [
            _decision(1, "scale_out", ev, t=1.0),
            _serve("scale_out", 1, t=1.1, spawn_ms=100.0),
            _serve("shed", None, t=1.5),            # inside cover
            _serve("shed", None, t=3.0),            # outside
            {"kind": "slo_breach", "t": 1.7},       # inside
            _serve("settle", None, t=1.6, outcome="failed"),  # inside
        ]
        # The unchained sheds are failure evidence, not actuations.
        for r in recs:
            r.pop("decision_id", None) if r.get("event") == "shed" else None
        rep = audit_records(recs)
        assert rep["errors"] == []
        assert rep["regret_total"] == 3
        assert rep["n_failure_signals"] == 4
        (pd,) = rep["regret_per_decision"]
        assert pd["regret"] == 3 and pd["cover_s"] == pytest.approx(0.8)
        assert pd["late"] is False

    def test_spawn_lead_violation_counted(self):
        ev = _matured(lead_time_ms=50.0)
        recs = [
            _decision(1, "scale_out", ev, t=1.0),
            _serve("scale_out", 1, t=1.2, spawn_ms=200.0),
        ]
        rep = audit_records(recs)
        assert rep["spawn_lead_violations"] == 1

    def test_duplicate_decision_id_is_an_error(self):
        recs = [
            _decision(1, "scale_out", _evidence(breaches=["x"]), t=1.0),
            _decision(1, "scale_out", _evidence(breaches=["x"]), t=2.0),
        ]
        rep = audit_records(recs)
        assert any("duplicate" in e for e in rep["errors"])


class TestAuditCLI:
    def _write(self, tmp_path, name, records):
        p = tmp_path / name
        p.write_text("".join(json.dumps(r) + "\n" for r in records))
        return str(p)

    def test_clean_stream_exits_zero(self, tmp_path, capsys):
        path = self._write(tmp_path, "a.jsonl", _chain())
        assert audit_main([path]) == 0
        out = capsys.readouterr().out.strip().splitlines()
        rep = json.loads(out[-1])
        assert rep["ok"] is True and rep["n_decisions"] == 2
        assert rep["kind"] == "summary"

    def test_broken_chain_exits_one(self, tmp_path):
        recs = [_serve("scale_out", None, t=1.0)]
        path = self._write(tmp_path, "b.jsonl", recs)
        assert audit_main([path]) == 1

    def test_strict_fails_warnings(self, tmp_path):
        recs = [_decision(1, "scale_out", _evidence(breaches=["x"]),
                          t=1.0)]
        path = self._write(tmp_path, "c.jsonl", recs)
        assert audit_main([path]) == 0
        assert audit_main([path, "--strict"]) == 1

    def test_baseline_delta_emitted(self, tmp_path, capsys):
        ev = _matured(lead_time_ms=2000.0)
        loud = [
            _decision(1, "scale_out", ev, t=1.0),
            _serve("scale_out", 1, t=1.1, spawn_ms=100.0),
            _serve("shed", None, t=1.5),
        ]
        quiet = _chain()
        a = self._write(tmp_path, "anticipatory.jsonl", quiet)
        b = self._write(tmp_path, "reactive.jsonl", loud)
        assert audit_main([a, "--baseline", b]) == 0
        lines = capsys.readouterr().out.strip().splitlines()
        delta = json.loads(lines[-1])
        assert delta["audit"] == "baseline-delta"
        assert delta["regret_delta"] == 0 - 1
