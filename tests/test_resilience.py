"""Failure detection / recovery (SURVEY.md §5 row 3): fault injection and
sharding-aware checkpoint restore.

The reference has nothing here (single device, torch.save left to the
user). The TPU-native recovery model is checkpoint-based restart: TPU
slices are fixed-shape (no elastic resize), so "recovery" means the
replacement job restores the latest committed Orbax step — possibly into a
DIFFERENT mesh layout — and continues. These tests exercise exactly that:

  * kill-a-worker: a real SIGKILL mid-training of a subprocess that
    checkpoints every step; the committed steps must be restorable and
    training must continue (Orbax's atomic commit protects against the
    torn final step).
  * sharded restore: restore lands directly in NamedShardings on the
    8-device virtual mesh (no host bounce), including into a mesh of a
    different shape than the one that saved.
"""

import os
import signal
import subprocess
import sys
import threading

import jax
import numpy as np
import pytest

from glom_tpu.data import gaussian_dataset
from glom_tpu.parallel import DistributedTrainer
from glom_tpu.utils.checkpoint import CheckpointManager
from glom_tpu.utils.config import GlomConfig, MeshConfig, TrainConfig

CFG = GlomConfig(dim=16, levels=3, image_size=8, patch_size=2)  # n=16
TCFG = TrainConfig(batch_size=8, learning_rate=1e-3, iters=2, recon_iter_index=1)


def _abstract_with_shardings(state, shardings):
    return jax.tree_util.tree_map(
        lambda x, s: jax.ShapeDtypeStruct(np.shape(x), x.dtype, sharding=s),
        state,
        shardings,
    )


class TestShardedRestore:
    def _train_and_save(self, tmp_path, mesh_cfg, steps=3):
        trainer = DistributedTrainer(CFG, TCFG, mesh_cfg)
        data = gaussian_dataset(TCFG.batch_size, CFG.image_size, seed=0)
        for _ in range(steps):
            trainer.step(next(data))
        mgr = CheckpointManager(str(tmp_path / "ckpt"), async_save=False)
        mgr.save(steps, trainer.state)
        mgr.wait()
        return trainer, mgr

    def test_restore_lands_in_mesh_shardings(self, tmp_path):
        """Restore with an abstract state carrying NamedShardings: arrays
        must come back already sharded over the mesh with identical values
        (the path utils/checkpoint.py:8 advertises, untested in round 1)."""
        mesh_cfg = MeshConfig(data=4, seq=2)
        trainer, mgr = self._train_and_save(tmp_path, mesh_cfg)

        fresh = DistributedTrainer(CFG, TCFG, mesh_cfg)
        abstract = _abstract_with_shardings(fresh.state, fresh.state_shardings)
        step, restored = mgr.restore(abstract_state=abstract)
        mgr.close()
        assert step == 3

        for got, want, sh in zip(
            jax.tree_util.tree_leaves(restored),
            jax.tree_util.tree_leaves(trainer.state),
            jax.tree_util.tree_leaves(fresh.state_shardings),
        ):
            assert got.sharding == sh
            np.testing.assert_allclose(np.asarray(got), np.asarray(want))

    def test_restore_into_different_mesh_shape(self, tmp_path):
        """Recovery onto a different slice layout: save from (4 data x 2
        seq), restore into (2 data x 2 seq x 2 model) and keep training."""
        trainer, mgr = self._train_and_save(tmp_path, MeshConfig(data=4, seq=2))
        loss_before = float(
            trainer.step(
                next(gaussian_dataset(TCFG.batch_size, CFG.image_size, seed=9))
            )["loss"]
        )

        other = DistributedTrainer(CFG, TCFG, MeshConfig(data=2, seq=2, model=2))
        abstract = _abstract_with_shardings(other.state, other.state_shardings)
        step, other.state = mgr.restore(abstract_state=abstract)
        mgr.close()
        assert step == 3

        # Same params, same data -> same next loss, despite the new layout.
        loss_after = float(
            other.step(
                next(gaussian_dataset(TCFG.batch_size, CFG.image_size, seed=9))
            )["loss"]
        )
        np.testing.assert_allclose(loss_after, loss_before, rtol=1e-5)

    def _assert_state_equal(self, got, want):
        for x, y in zip(
            jax.tree_util.tree_leaves(got), jax.tree_util.tree_leaves(want)
        ):
            np.testing.assert_allclose(
                np.asarray(x), np.asarray(y), rtol=1e-6, atol=1e-7
            )

    def test_restore_zero1_checkpoint_at_zero0_dp1(self):
        """ZeRO checkpoints are layout-portable: opt-state GLOBAL shapes
        are invariant to zero_stage (only the NamedShardings differ), so a
        checkpoint written at zero_stage=1/dp=8 restores into a
        zero_stage=0/dp=1 layout — and training continues identically."""
        import dataclasses
        import tempfile

        ztcfg = dataclasses.replace(TCFG, zero_stage=1)
        trainer = DistributedTrainer(CFG, ztcfg, MeshConfig(data=8))
        assert trainer.zero_stage == 1
        data = gaussian_dataset(TCFG.batch_size, CFG.image_size, seed=0)
        for _ in range(3):
            trainer.step(next(data))
        with tempfile.TemporaryDirectory() as tmp:
            mgr = CheckpointManager(tmp + "/ckpt", async_save=False)
            mgr.save(3, trainer.state)
            mgr.wait()

            other = DistributedTrainer(CFG, TCFG, MeshConfig(data=1))
            assert other.zero_stage == 0
            abstract = _abstract_with_shardings(other.state, other.state_shardings)
            step, other.state = mgr.restore(abstract_state=abstract)
            mgr.close()
        assert step == 3
        self._assert_state_equal(other.state, trainer.state)
        probe = next(gaussian_dataset(TCFG.batch_size, CFG.image_size, seed=9))
        np.testing.assert_allclose(
            float(other.step(probe)["loss"]),
            float(trainer.step(probe)["loss"]),
            rtol=1e-5,
        )

    @pytest.mark.slow
    def test_restore_zero0_checkpoint_at_zero1_dp8(self):
        """The reverse direction: replicated dp=1 checkpoint restores
        directly into the dp=8 ZeRO-1 sharded layout (Orbax device_puts
        each moment leaf straight into its 1/8 shard, no host bounce)."""
        import dataclasses
        import tempfile

        trainer = DistributedTrainer(CFG, TCFG, MeshConfig(data=1))
        data = gaussian_dataset(TCFG.batch_size, CFG.image_size, seed=0)
        for _ in range(3):
            trainer.step(next(data))
        with tempfile.TemporaryDirectory() as tmp:
            mgr = CheckpointManager(tmp + "/ckpt", async_save=False)
            mgr.save(3, trainer.state)
            mgr.wait()

            ztcfg = dataclasses.replace(TCFG, zero_stage=1)
            other = DistributedTrainer(CFG, ztcfg, MeshConfig(data=8))
            assert other.zero_stage == 1
            abstract = _abstract_with_shardings(other.state, other.state_shardings)
            step, other.state = mgr.restore(abstract_state=abstract)
            mgr.close()
        assert step == 3
        # restored leaves land in the ZeRO shardings
        for got, sh in zip(
            jax.tree_util.tree_leaves(other.state),
            jax.tree_util.tree_leaves(other.state_shardings),
        ):
            assert got.sharding == sh
        self._assert_state_equal(other.state, trainer.state)
        probe = next(gaussian_dataset(TCFG.batch_size, CFG.image_size, seed=9))
        np.testing.assert_allclose(
            float(other.step(probe)["loss"]),
            float(trainer.step(probe)["loss"]),
            rtol=1e-5,
        )


class TestManifestVerifiedCheckpoints:
    """The crash-safe checkpoint layer (PR 6): checksum manifests written
    atomically after Orbax's commit; the read side only hands out steps
    that VERIFY. Host-only states (np pytrees) keep this tier-1 fast."""

    STATE = {
        "w": np.arange(32, dtype=np.float32),
        "step": np.zeros((), np.int32),
    }

    def _save_steps(self, directory, steps, **kw):
        from glom_tpu.utils.checkpoint import CheckpointManager

        mgr = CheckpointManager(str(directory), async_save=False, **kw)
        for s in steps:
            state = {
                "w": self.STATE["w"] + s,
                "step": np.asarray(s, np.int32),
            }
            assert mgr.save(s, state)
        return mgr

    def _abstract(self):
        from glom_tpu.utils.checkpoint import abstract_like

        return abstract_like(self.STATE)

    def test_every_save_lands_an_atomic_manifest(self, tmp_path):
        mgr = self._save_steps(tmp_path, [1, 2, 3])
        assert mgr.valid_steps() == [1, 2, 3]
        for s in (1, 2, 3):
            assert (tmp_path / f"manifest_{s}.json").is_file()
            assert mgr.verify_step(s)
        mgr.close()

    def test_truncated_newest_restores_the_previous_step(self, tmp_path):
        """THE regression test the satellite names: truncate the newest
        checkpoint; latest_step/restore must land on the previous valid
        one instead of crashing."""
        from glom_tpu.resilience import truncate_newest_checkpoint

        mgr = self._save_steps(tmp_path, [1, 2, 3])
        step, _path = truncate_newest_checkpoint(tmp_path)
        assert step == 3
        assert mgr.latest_step() == 2  # not 3, not a crash
        got_step, got = mgr.restore(abstract_state=self._abstract())
        assert got_step == 2
        np.testing.assert_allclose(np.asarray(got["w"]), self.STATE["w"] + 2)
        mgr.close()

    def test_explicit_corrupt_step_raises_loudly(self, tmp_path):
        from glom_tpu.resilience import truncate_newest_checkpoint
        from glom_tpu.utils.checkpoint import CheckpointCorruptError

        mgr = self._save_steps(tmp_path, [1, 2])
        truncate_newest_checkpoint(tmp_path)
        with pytest.raises(CheckpointCorruptError):
            mgr.restore(2, abstract_state=self._abstract())
        mgr.close()

    def test_unmanifested_torn_step_skips_via_restore_fallback(self, tmp_path):
        """A step whose manifest never landed (kill between commit and
        manifest write) is accepted on Orbax's marker — and when its data
        is ALSO torn, the restore walk skips it with a stamped recovery
        event and lands on the previous step."""
        from glom_tpu.resilience import truncate_newest_checkpoint

        records = []

        class W:
            def write(self, rec):
                records.append(rec)

        mgr = self._save_steps(tmp_path, [1, 2], metrics_writer=W())
        (tmp_path / "manifest_2.json").unlink()
        truncate_newest_checkpoint(tmp_path)
        # heavily corrupt: keep truncating every file of step 2
        for p in (tmp_path / "2").rglob("*"):
            if p.is_file():
                with open(p, "r+b") as fh:
                    fh.truncate(1)
        assert 2 in mgr.valid_steps()  # unverifiable, accepted on marker
        got_step, got = mgr.restore(abstract_state=self._abstract())
        assert got_step == 1
        np.testing.assert_allclose(np.asarray(got["w"]), self.STATE["w"] + 1)
        skips = [
            r for r in records
            if r.get("kind") == "recovery"
            and r.get("action") == "skip-torn-checkpoint"
        ]
        assert skips and skips[0]["step"] == 2
        mgr.close()

    def test_async_saves_pay_manifest_debt_at_sync_points(self, tmp_path):
        from glom_tpu.utils.checkpoint import CheckpointManager

        mgr = CheckpointManager(str(tmp_path), async_save=True)
        mgr.save(1, self.STATE)
        mgr.save(2, self.STATE)  # save() settles step 1's manifest
        assert (tmp_path / "manifest_1.json").is_file()
        mgr.wait()  # wait() settles step 2's
        assert (tmp_path / "manifest_2.json").is_file()
        assert mgr.valid_steps() == [1, 2]
        mgr.close()

    def test_injected_write_failure_leaves_previous_steps_valid(self, tmp_path):
        """Checkpoint-write fault injection (resilience/faults.py): the
        wrapped save raises on schedule, prior steps stay restorable."""
        from glom_tpu.resilience import FaultPlan

        mgr = self._save_steps(tmp_path, [1])
        plan = FaultPlan(seed=0)
        plan.register("ckpt-write", at=(0,), fault="ckpt-write-failure")
        faulty_save = plan.wrap(
            mgr.save, "ckpt-write", exc=lambda: OSError("injected ENOSPC")
        )
        with pytest.raises(OSError):
            faulty_save(2, self.STATE)
        assert mgr.latest_step() == 1
        faulty_save(2, self.STATE)  # off-schedule: passes through
        assert mgr.latest_step() == 2
        mgr.close()


class TestPodRestoreReconciliation:
    """The multi-host twin of TestManifestVerifiedCheckpoints (pod mode,
    docs/RESILIENCE.md): restore(None) only hands out steps whose
    per-host manifests are ALL valid, and a half-committed step — torn,
    checksum-failed, or missing on any one host of N — is quarantined on
    EVERY host with the decision stamped. Host-only np pytrees keep this
    tier-1 fast."""

    STATE = {
        "w": np.arange(32, dtype=np.float32),
        "step": np.zeros((), np.int32),
    }

    def _build_pod(self, root, n_hosts=3, steps=(1, 2, 3)):
        from glom_tpu.utils.checkpoint import CheckpointManager

        dirs = [root / "ckpt" / f"host_{i}" for i in range(n_hosts)]
        for d in dirs:
            mgr = CheckpointManager(str(d), async_save=False)
            for s in steps:
                state = {
                    "w": self.STATE["w"] + s,
                    "step": np.asarray(s, np.int32),
                }
                assert mgr.save(s, state)
            mgr.close()
        return dirs

    def _pod_mgr(self, dirs, host=0, writer=None):
        from glom_tpu.utils.checkpoint import CheckpointManager

        peers = [str(d) for i, d in enumerate(dirs) if i != host]
        return CheckpointManager(
            str(dirs[host]), pod_peers=peers, metrics_writer=writer
        )

    def _abstract(self):
        from glom_tpu.utils.checkpoint import abstract_like

        return abstract_like(self.STATE)

    def test_torn_on_one_host_falls_back_and_quarantines_everywhere(
        self, tmp_path
    ):
        """THE satellite case: step 3 torn on exactly one host of 3 —
        the pod restore lands on step 2 and step 3 is quarantined on
        every host, stamped."""
        from glom_tpu.resilience import truncate_newest_checkpoint

        dirs = self._build_pod(tmp_path)
        step, _path = truncate_newest_checkpoint(dirs[1])
        assert step == 3
        records = []

        class W:
            def write(self, rec):
                records.append(rec)

        mgr = self._pod_mgr(dirs, host=0, writer=W())
        assert mgr.latest_step() == 2  # newest COMMON valid step
        got_step, got = mgr.restore(abstract_state=self._abstract())
        mgr.close()
        assert got_step == 2
        np.testing.assert_allclose(np.asarray(got["w"]), self.STATE["w"] + 2)
        q = [r for r in records if r.get("action") == "quarantine-half-step"]
        assert q and q[0]["step"] == 3
        assert q[0]["invalid_hosts"] == [str(dirs[1])]
        for d in dirs:  # quarantined on EVERY host, forensics preserved
            assert not (d / "3").exists(), d
            assert list((d / ".quarantine").glob("3_*")), d
            assert not (d / "manifest_3.json").exists(), d
        from glom_tpu.telemetry import schema

        for r in records:
            assert schema.validate_record(r) == [], r

    def test_step_missing_on_one_host_is_half_committed(self, tmp_path):
        """A step one host never committed (killed before its save — no
        tear, just absence) is equally half-committed: fall back and
        quarantine the other hosts' copies."""
        dirs = self._build_pod(tmp_path, n_hosts=3, steps=(1, 2))
        # hosts 0 and 2 committed step 3; host 1 never did
        for h in (0, 2):
            from glom_tpu.utils.checkpoint import CheckpointManager

            mgr = CheckpointManager(str(dirs[h]), async_save=False)
            assert mgr.save(3, {"w": self.STATE["w"] + 3,
                                "step": np.asarray(3, np.int32)})
            mgr.close()
        mgr = self._pod_mgr(dirs, host=0)
        got_step, _ = mgr.restore(abstract_state=self._abstract())
        mgr.close()
        assert got_step == 2
        assert not (dirs[0] / "3").exists()
        assert not (dirs[2] / "3").exists()

    def test_own_torn_step_also_quarantines_peer_copies(self, tmp_path):
        """The inverse orientation: the RESTORING host's copy is the
        torn one — its skip-torn path must take the peers' pristine
        copies with it (they are halves of the same unusable pod
        step)."""
        from glom_tpu.resilience import truncate_newest_checkpoint

        dirs = self._build_pod(tmp_path)
        truncate_newest_checkpoint(dirs[0])
        records = []

        class W:
            def write(self, rec):
                records.append(rec)

        mgr = self._pod_mgr(dirs, host=0, writer=W())
        got_step, _ = mgr.restore(abstract_state=self._abstract())
        mgr.close()
        assert got_step == 2
        skips = [r for r in records
                 if r.get("action") == "skip-torn-checkpoint"]
        assert skips and skips[0]["step"] == 3
        assert set(skips[0]["peer_quarantined"]) == {
            str(dirs[1]), str(dirs[2])
        }
        for d in dirs:
            assert not (d / "3").exists(), d

    def test_all_hosts_valid_restores_the_newest_step(self, tmp_path):
        dirs = self._build_pod(tmp_path)
        mgr = self._pod_mgr(dirs, host=0)
        got_step, got = mgr.restore(abstract_state=self._abstract())
        mgr.close()
        assert got_step == 3
        np.testing.assert_allclose(np.asarray(got["w"]), self.STATE["w"] + 3)

    def test_failed_quarantine_rename_keeps_the_manifest(
        self, tmp_path, monkeypatch
    ):
        """A quarantine rename that fails with the step dir STILL IN
        PLACE (EACCES/EBUSY on shared storage) must not drop the
        manifest: the manifest is the evidence that marks the torn step
        invalid, and dropping it would flip step_valid_in_dir's
        absent-manifest fallback to "valid" on a known-bad step."""
        from pathlib import Path

        from glom_tpu.resilience import truncate_newest_checkpoint
        from glom_tpu.utils.checkpoint import (
            quarantine_step_in_dir,
            step_valid_in_dir,
        )

        dirs = self._build_pod(tmp_path, n_hosts=1)
        truncate_newest_checkpoint(dirs[0])
        assert not step_valid_in_dir(dirs[0], 3)

        def deny_rename(self, dst):
            raise OSError("EBUSY: device or resource busy")

        monkeypatch.setattr(Path, "rename", deny_rename)
        assert quarantine_step_in_dir(dirs[0], 3) is None
        monkeypatch.undo()
        assert (dirs[0] / "manifest_3.json").is_file()
        assert (dirs[0] / "3").is_dir()
        assert not step_valid_in_dir(dirs[0], 3)  # still judged torn

    def test_single_host_shape_unchanged_without_pod_peers(self, tmp_path):
        """The acceptance guard: no pod_peers means the PR 6 contract
        bit-for-bit — same events, same fields (no peer_quarantined
        key)."""
        from glom_tpu.resilience import truncate_newest_checkpoint
        from glom_tpu.utils.checkpoint import CheckpointManager

        dirs = self._build_pod(tmp_path, n_hosts=1)
        truncate_newest_checkpoint(dirs[0])
        records = []

        class W:
            def write(self, rec):
                records.append(rec)

        mgr = CheckpointManager(str(dirs[0]), metrics_writer=W())
        got_step, _ = mgr.restore(abstract_state=self._abstract())
        mgr.close()
        assert got_step == 2
        skips = [r for r in records
                 if r.get("action") == "skip-torn-checkpoint"]
        assert skips and "peer_quarantined" not in skips[0]
        assert not any(
            r.get("action") == "quarantine-half-step" for r in records
        )


_WORKER = r"""
import sys
import jax
jax.config.update("jax_platforms", "cpu")
from glom_tpu.data import gaussian_dataset
from glom_tpu.parallel import DistributedTrainer
from glom_tpu.utils.checkpoint import CheckpointManager
from glom_tpu.utils.config import GlomConfig, MeshConfig, TrainConfig

ckpt_dir, steps = sys.argv[1], int(sys.argv[2])
cfg = GlomConfig(dim=16, levels=3, image_size=8, patch_size=2)
tcfg = TrainConfig(batch_size=8, learning_rate=1e-3, iters=2, recon_iter_index=1)
trainer = DistributedTrainer(cfg, tcfg, MeshConfig(data=4, seq=2))
mgr = CheckpointManager(ckpt_dir, async_save=False, save_interval_steps=1)

start = 0
latest = mgr.latest_step()
if latest is not None:
    import numpy as np
    abstract = jax.tree_util.tree_map(
        lambda x, s: jax.ShapeDtypeStruct(np.shape(x), x.dtype, sharding=s),
        trainer.state, trainer.state_shardings)
    start, trainer.state = mgr.restore(abstract_state=abstract)
    print(f"RESUMED_FROM {start}", flush=True)

data = gaussian_dataset(tcfg.batch_size, cfg.image_size, seed=0)
for _ in range(start):
    next(data)  # realign the data stream
for i in range(start, steps):
    loss = float(trainer.step(next(data))["loss"])
    assert loss == loss, "NaN loss"
    mgr.save(i + 1, trainer.state)
    mgr.wait()
    print(f"STEP {i + 1} {loss}", flush=True)
mgr.close()
print("DONE", flush=True)
"""


class TestKillAWorker:
    # Two full training subprocesses (~35s): slow-marked for the tier-1
    # budget; CI's zero-parity job runs test_resilience unfiltered.
    @pytest.mark.slow
    def test_sigkill_and_resume(self, tmp_path):
        """Inject a real fault: SIGKILL the training process mid-run, then
        restart it and require it to resume from the last committed step
        and finish. Run on the same 8-virtual-device mesh as the tests."""
        ckpt = str(tmp_path / "ckpt")
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        flags = [
            f
            for f in env.get("XLA_FLAGS", "").split()
            if "host_platform_device_count" not in f
        ]
        env["XLA_FLAGS"] = " ".join(
            flags + ["--xla_force_host_platform_device_count=8"]
        )
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")

        proc = subprocess.Popen(
            [sys.executable, "-u", "-c", _WORKER, ckpt, "6"],
            env=env,
            cwd=repo,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        # Watchdog: the readline loop below blocks if the worker hangs
        # without printing, so enforce the deadline out-of-band.
        watchdog = threading.Timer(300, proc.kill)
        watchdog.start()
        try:
            # Kill as soon as at least 2 steps have committed.
            seen = []
            for line in proc.stdout:
                if line.startswith("STEP"):
                    seen.append(line.split()[1])
                if len(seen) >= 2:
                    os.kill(proc.pid, signal.SIGKILL)
                    break
            else:  # pragma: no cover — stdout closed (hang-kill or crash)
                pytest.fail(f"worker died/hung before 2 checkpointed steps: {seen}")
            proc.wait(timeout=60)
        finally:
            watchdog.cancel()
        assert proc.returncode != 0  # it was killed, not finished

        # Restart: must resume from a committed step >= 2 and run to 6.
        out = subprocess.run(
            [sys.executable, "-u", "-c", _WORKER, ckpt, "6"],
            env=env,
            cwd=repo,
            capture_output=True,
            text=True,
            timeout=300,
        )
        assert out.returncode == 0, out.stdout + out.stderr
        assert "RESUMED_FROM" in out.stdout
        resumed = int(out.stdout.split("RESUMED_FROM ")[1].split()[0])
        assert resumed >= 2
        assert "DONE" in out.stdout
        assert "STEP 6" in out.stdout
