"""File-backed loader tests (data/loaders.py): the real-data input path
the reference's README recipe assumes the user brings (SURVEY.md §2.1 #8).
Synthetic fixture files stand in for real datasets (zero-egress image)."""

import numpy as np
import pytest

from glom_tpu.data import file_dataset, image_folder_dataset, npy_dataset


@pytest.fixture
def npy_file(tmp_path):
    rng = np.random.default_rng(0)
    arr = rng.integers(0, 256, (20, 16, 16, 3), dtype=np.uint8)  # NHWC uint8
    path = tmp_path / "shard0.npy"
    np.save(path, arr)
    return str(path), arr


class TestNpyDataset:
    def test_shapes_dtype_range(self, npy_file):
        path, _ = npy_file
        batch = next(npy_dataset(path, batch_size=4, image_size=16, seed=0))
        assert batch.shape == (4, 3, 16, 16)
        assert batch.dtype == np.float32
        assert batch.min() >= -1.0 and batch.max() <= 1.0

    def test_nchw_float_input(self, tmp_path):
        arr = np.random.default_rng(1).random((8, 3, 8, 8)).astype(np.float32)
        path = tmp_path / "f.npy"
        np.save(path, arr)
        batch = next(npy_dataset(str(path), batch_size=2, image_size=8))
        assert batch.shape == (2, 3, 8, 8)
        # [0,1] floats map to [-1,1]
        assert batch.min() >= -1.0 and batch.max() <= 1.0

    def test_epoch_covers_all_rows_shuffled(self, npy_file):
        path, arr = npy_file
        it = npy_dataset(path, batch_size=4, image_size=16, seed=3,
                         num_batches=5)
        batches = list(it)
        assert len(batches) == 5  # 20 rows / 4 = one epoch
        # every source row appears exactly once per epoch (match by content)
        flat = np.concatenate([b.reshape(4, -1) for b in batches])
        src = (arr.astype(np.float32) / 127.5 - 1.0).transpose(0, 3, 1, 2)
        src = src.reshape(20, -1)
        # sort rows of both and compare as multisets
        np.testing.assert_allclose(
            np.sort(flat, axis=0), np.sort(src, axis=0), rtol=1e-6
        )

    def test_row_sharding_partitions(self, npy_file):
        path, _ = npy_file
        b0 = list(npy_dataset(path, 2, 16, shard_index=0, num_shards=2,
                              num_batches=5))
        b1 = list(npy_dataset(path, 2, 16, shard_index=1, num_shards=2,
                              num_batches=5))
        r0 = {r.tobytes() for b in b0 for r in b}
        r1 = {r.tobytes() for b in b1 for r in b}
        assert r0.isdisjoint(r1)  # hosts see disjoint rows

    def test_directory_of_shards(self, tmp_path):
        rng = np.random.default_rng(2)
        for i in range(3):
            np.save(tmp_path / f"s{i}.npy",
                    rng.integers(0, 256, (6, 8, 8, 3), dtype=np.uint8))
        batches = list(npy_dataset(str(tmp_path), 3, 8, num_batches=6))
        assert len(batches) == 6
        assert all(b.shape == (3, 3, 8, 8) for b in batches)

    def test_size_mismatch_raises(self, npy_file):
        path, _ = npy_file
        with pytest.raises(ValueError, match="config wants"):
            next(npy_dataset(path, 2, image_size=32))


class TestImageFolderDataset:
    def test_loads_resizes_normalizes(self, tmp_path):
        from PIL import Image

        rng = np.random.default_rng(0)
        for i in range(6):
            Image.fromarray(
                rng.integers(0, 256, (24, 20, 3), dtype=np.uint8)
            ).save(tmp_path / f"img{i}.png")
        batch = next(image_folder_dataset(str(tmp_path), 4, 16, seed=0))
        assert batch.shape == (4, 3, 16, 16)
        assert batch.dtype == np.float32
        assert batch.min() >= -1.0 and batch.max() <= 1.0

    def test_empty_dir_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            next(image_folder_dataset(str(tmp_path), 2, 8))

    def test_process_sharding_disjoint(self, tmp_path):
        from PIL import Image

        for i in range(8):
            Image.fromarray(
                np.full((8, 8, 3), i * 30, dtype=np.uint8)
            ).save(tmp_path / f"img{i}.png")
        b0 = next(image_folder_dataset(
            str(tmp_path), 4, 8, shard_index=0, num_shards=2))
        b1 = next(image_folder_dataset(
            str(tmp_path), 4, 8, shard_index=1, num_shards=2))
        v0 = {round(float(img.mean()), 4) for img in b0}
        v1 = {round(float(img.mean()), 4) for img in b1}
        assert v0.isdisjoint(v1)


class TestFileDatasetDispatch:
    def test_dispatch_npy(self, npy_file):
        path, _ = npy_file
        batch = next(file_dataset(path, 2, 16))
        assert batch.shape == (2, 3, 16, 16)

    def test_dispatch_folder(self, tmp_path):
        from PIL import Image

        Image.fromarray(np.zeros((8, 8, 3), np.uint8)).save(tmp_path / "a.png")
        Image.fromarray(np.zeros((8, 8, 3), np.uint8)).save(tmp_path / "b.png")
        batch = next(file_dataset(str(tmp_path), 2, 8))
        assert batch.shape == (2, 3, 8, 8)

    def test_missing_raises(self):
        with pytest.raises(FileNotFoundError):
            file_dataset("/nonexistent/nowhere", 2, 8)


def test_trainer_fits_on_file_data(tmp_path):
    """End-to-end: real-data path through the Trainer — loss finite and
    decreasing-ish on structured (non-noise) images."""
    import jax.numpy as jnp  # noqa: F401  (jax initialized by conftest)
    from glom_tpu.train import Trainer
    from glom_tpu.utils.config import GlomConfig, TrainConfig

    rng = np.random.default_rng(0)
    # structured images: constant-color quadrants (denoisable signal)
    imgs = np.zeros((16, 8, 8, 3), np.uint8)
    for i in range(16):
        imgs[i, :4, :4] = rng.integers(0, 256, 3)
        imgs[i, 4:, 4:] = rng.integers(0, 256, 3)
    np.save(tmp_path / "d.npy", imgs)

    cfg = GlomConfig(dim=16, levels=2, image_size=8, patch_size=4)
    tcfg = TrainConfig(batch_size=4, iters=2, recon_iter_index=2,
                       learning_rate=1e-3)
    tr = Trainer(cfg, tcfg)
    hist = tr.fit(
        npy_dataset(str(tmp_path / "d.npy"), 4, 8, seed=0),
        num_steps=4, log_every=1,
    )
    assert all(np.isfinite(h["loss"]) for h in hist)


def test_write_shapes_dataset_roundtrip_and_trains(tmp_path):
    """The on-disk dataset generator -> file_dataset -> Trainer, end to
    end: the gate for the file-backed real-data training record
    (results/realdata_loss_curve.jsonl is produced by exactly this path
    on TPU via the CLI --data-dir)."""
    from glom_tpu.data import file_dataset, write_shapes_dataset
    from glom_tpu.train import Trainer
    from glom_tpu.utils.config import GlomConfig, TrainConfig

    paths = write_shapes_dataset(str(tmp_path / "png"), 16, 8, seed=3)
    assert len(paths) == 16
    # determinism: regenerating yields byte-identical files
    paths2 = write_shapes_dataset(str(tmp_path / "png2"), 16, 8, seed=3)
    assert (tmp_path / "png" / "shape_000000.png").read_bytes() == (
        tmp_path / "png2" / "shape_000000.png"
    ).read_bytes()

    npy_paths = write_shapes_dataset(
        str(tmp_path / "npy"), 20, 8, seed=3, fmt="npy", shard_size=8
    )
    assert len(npy_paths) == 3  # 8 + 8 + 4

    batch = next(file_dataset(str(tmp_path / "png"), 4, 8, seed=0))
    assert batch.shape == (4, 3, 8, 8)
    assert -1.0 <= batch.min() and batch.max() <= 1.0
    assert batch.std() > 0.05  # structured content, not blank

    cfg = GlomConfig(dim=16, levels=2, image_size=8, patch_size=4)
    tcfg = TrainConfig(batch_size=4, iters=2, recon_iter_index=2,
                       learning_rate=1e-3)
    hist = Trainer(cfg, tcfg).fit(
        file_dataset(str(tmp_path / "png"), 4, 8, seed=0),
        num_steps=4, log_every=1,
    )
    assert all(np.isfinite(h["loss"]) for h in hist)
