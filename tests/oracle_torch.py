"""PyTorch oracle of the GLOM forward + denoise-training contract.

The BASELINE.json north star is "match the PyTorch-CUDA reference loss
curve". The reference itself publishes no curve (BASELINE.md), so this
module IS the PyTorch side of that comparison: an independent torch
implementation written directly from the behavioral spec (SURVEY.md §3.2
for the forward, §3.3 for the denoise recipe), sharing no code with
glom_tpu — torch autograd + torch.optim.Adam against jax.grad + optax.adam.

Functional style over plain tensor dicts (not nn.Modules) so weights
convert 1:1 from glom_tpu's pytrees: the parity tests transplant the SAME
initial weights into both frameworks, feed the SAME data and noise, and
compare per-step losses.

Used by tests/test_torch_parity.py and parity_torch.py (the committed
loss-curve artifact). CPU-only (the torch in this image has no CUDA), which
is fine: the comparison locks the math, not torch's device performance.
"""

from __future__ import annotations

import numpy as np
import torch
import torch.nn.functional as F

TOKEN_ATTEND_SELF_VALUE = -5e-4


# ---------------------------------------------------------------- weights


def params_from_jax(denoise_params, requires_grad: bool = True) -> dict:
    """Flatten a glom_tpu DenoiseParams pytree into a name->torch.Tensor
    dict (float32, leaf tensors)."""
    g = denoise_params.glom
    raw = {
        "token_w": g.token_embed.w, "token_b": g.token_embed.b,
        "pos_emb": g.pos_emb, "init_levels": g.init_levels,
        "bu_w1": g.bottom_up.w1, "bu_b1": g.bottom_up.b1,
        "bu_w2": g.bottom_up.w2, "bu_b2": g.bottom_up.b2,
        "td_w1": g.top_down.w1, "td_b1": g.top_down.b1,
        "td_w2": g.top_down.w2, "td_b2": g.top_down.b2,
        "pix_w": denoise_params.to_pixels.w, "pix_b": denoise_params.to_pixels.b,
    }
    out = {}
    for name, arr in raw.items():
        t = torch.from_numpy(np.asarray(arr, dtype=np.float32).copy())
        t.requires_grad_(requires_grad)
        out[name] = t
    return out


# ---------------------------------------------------------------- ops


def grouped_ffw(x, w1, b1, w2, b2):
    """x: [..., G, d]; per-group d -> f -> d MLP, exact-erf GELU."""
    h = torch.einsum("...gd,gdf->...gf", x, w1) + b1
    h = F.gelu(h)  # default approximate='none' = exact erf, matching jax.nn.gelu(approximate=False)
    return torch.einsum("...gf,gfd->...gd", h, w2) + b2


def local_mask(side: int, radius: float):
    """[n, n] bool: True where patch-grid euclidean distance > radius."""
    if radius <= 0:
        return None
    hs, ws = torch.meshgrid(torch.arange(side), torch.arange(side), indexing="ij")
    coords = torch.stack([hs, ws], -1).reshape(-1, 2).to(torch.float64)
    dist = torch.cdist(coords, coords)
    return dist > radius


def consensus(levels, attend_self=False, mask=None):
    """Same-level cross-column attention. levels: [b, n, L, d].
    k-only L2 norm, d^-1/2 scale, -5e-4 soft self mask, -finfo.max local."""
    b, n, L, d = levels.shape
    k = F.normalize(levels, dim=-1)  # eps 1e-12, same as the jax op
    sim = torch.einsum("bild,bjld->blij", levels, k) * (d ** -0.5)
    if not attend_self:
        eye = torch.eye(n, dtype=torch.bool)
        sim = sim.masked_fill(eye[None, None], TOKEN_ATTEND_SELF_VALUE)
    if mask is not None:
        sim = sim.masked_fill(mask[None, None], -torch.finfo(sim.dtype).max)
    attn = sim.softmax(dim=-1)
    return torch.einsum("blij,bjld->bild", attn, levels)


def patchify(img, p: int):
    """[b, c, H, W] -> [b, n, p*p*c], channel innermost within a patch."""
    b, c, H, W = img.shape
    h, w = H // p, W // p
    x = img.reshape(b, c, h, p, w, p)
    x = x.permute(0, 2, 4, 3, 5, 1)  # b h w p1 p2 c
    return x.reshape(b, h * w, p * p * c)


def unpatchify(x, p: int, image_size: int, c: int = 3):
    b, n, _ = x.shape
    h = image_size // p
    x = x.reshape(b, h, h, p, p, c)
    x = x.permute(0, 5, 1, 3, 2, 4)  # b c h p1 w p2
    return x.reshape(b, c, image_size, image_size)


# ---------------------------------------------------------------- model


def forward(params, img, cfg, iters=None, levels=None, return_all=False):
    """The T-iteration column update (SURVEY.md §3.2). img: [b, c, H, W]."""
    L = cfg.levels
    T = iters if iters is not None else 2 * L
    p = cfg.patch_size
    side = cfg.image_size // p
    n = side * side
    mask = local_mask(side, cfg.local_consensus_radius)

    tokens = patchify(img, p) @ params["token_w"] + params["token_b"]  # [b,n,d]
    b = tokens.shape[0]
    pos = params["pos_emb"].reshape(1, n, 1, -1)
    bottom = tokens[:, :, None]  # [b, n, 1, d]
    if levels is None:
        levels = params["init_levels"].expand(b, n, L, -1)

    divisor = torch.full((L, 1), 4.0)
    divisor[-1] = 3.0

    hiddens = [levels]
    for _ in range(T):
        with_input = torch.cat([bottom, levels], dim=-2)  # [b, n, L+1, d]
        bu = grouped_ffw(with_input[..., :-1, :],
                         params["bu_w1"], params["bu_b1"],
                         params["bu_w2"], params["bu_b2"])
        td = grouped_ffw(with_input[..., 2:, :] + pos,
                         params["td_w1"], params["td_b1"],
                         params["td_w2"], params["td_b2"])
        td = F.pad(td, (0, 0, 0, 1))  # zero top-down for the top level
        cons = consensus(levels, attend_self=cfg.consensus_self, mask=mask)
        levels = (levels + bu + td + cons) / divisor
        hiddens.append(levels)

    if return_all:
        return torch.stack(hiddens)  # [T+1, b, n, L, d]
    return levels


def denoise_loss(params, img, noise, cfg, recon_index=None, iters=None):
    """MSE(clean img, reconstruction from the noised image's top level at
    iteration recon_index) — the README recipe (SURVEY.md §3.3)."""
    T = iters if iters is not None else 2 * cfg.levels
    k = recon_index if recon_index is not None else T // 2 + 1
    final = forward(params, img + noise, cfg, iters=k)  # iters k+1..T are dead
    top = final[:, :, -1]  # [b, n, d]
    recon = unpatchify(top @ params["pix_w"] + params["pix_b"],
                       cfg.patch_size, cfg.image_size, cfg.channels)
    return ((img - recon) ** 2).mean()


def train(params, images, noises, cfg, lr: float):
    """Adam training over pre-generated (image, noise) step pairs; returns
    the per-step losses. Hyperparameters match optax.adam defaults."""
    opt = torch.optim.Adam(params.values(), lr=lr, betas=(0.9, 0.999), eps=1e-8)
    losses = []
    for img, noise in zip(images, noises):
        opt.zero_grad()
        loss = denoise_loss(params, torch.from_numpy(img), torch.from_numpy(noise), cfg)
        loss.backward()
        opt.step()
        losses.append(float(loss.detach()))
    return losses
