"""SLO-driven elastic serving (glom_tpu/serve/elastic.py, ISSUE 15).

The tier-1 locks:

  * POLICY CORE under a fake clock, no engine spawns: min-dwell
    hysteresis (no flapping across the water marks), cooldown, min/max
    clamps, breach-vs-headroom signal precedence, drain-target
    selection;
  * the AUTOSCALER actuator against a real DynamicBatcher with fake
    engines: a spawned replica receives ZERO admitted work before its
    warmup() precompile completes (test-pinned), a failed spawn rolls
    back loudly (stamped spawn_rollback, fleet unchanged), a scale-in
    runs the full drain chain (drain_begin -> drain_flush ->
    drain_migrate -> drain_release, one decision_id) with DRAINED
    distinct from dead (no probation, no capacity record);
  * CAPACITY-RECORD state stamping (ok/draining/probation/dead) and the
    SLO monitor's headroom exclusion of draining/probation engines;
  * session MIGRATION: a drained engine's paged columns are bitwise-
    served from the sibling pool, or invalidated with the stamped
    `drain` reason when the sibling has no page budget;
  * the STATIC path (no autoscaler attached) keeps the summary record
    shape byte-for-byte — no elastic nest, no drain keys.
"""

import time

import numpy as np
import pytest

from glom_tpu.serve.batcher import DynamicBatcher
from glom_tpu.serve.elastic import (
    Autoscaler,
    ElasticPolicy,
    resolve_policy,
)
from glom_tpu.serve.engine import ServeResult
from glom_tpu.telemetry import schema
from glom_tpu.telemetry.aggregate import SLOMonitor
from glom_tpu.utils.config import ServeConfig

IMG = np.zeros((3, 8, 8), np.float32)


class Sink:
    def __init__(self):
        self.records = []

    def write(self, rec):
        self.records.append(rec)

    def events(self, *names):
        return [r for r in self.records if r.get("event") in names]


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def advance(self, dt):
        self.t += dt

    def __call__(self):
        return self.t


class FakeEngine:
    """Engine-shaped probe that records warmup/dispatch ORDER — the
    admission-after-precompile pin reads it."""

    def __init__(self, name="engine0", buckets=(1, 2, 4)):
        self.name = name
        self.scfg = ServeConfig(
            buckets=buckets, max_batch=max(buckets), max_delay_ms=2.0,
            queue_depth=16,
        )
        self.warmed = False
        self.released = False
        self.calls = []
        self.infer_before_warmup = 0

    def warmup(self, *a, **kw):
        self.warmed = True
        return {}

    def release(self):
        self.released = True

    def pick_bucket(self, n):
        for b in self.scfg.buckets:
            if n <= b:
                return b
        raise ValueError(f"n={n} exceeds the largest bucket")

    def infer(self, imgs, n_valid=None, **kw):
        if not self.warmed:
            self.infer_before_warmup += 1
        b = imgs.shape[0]
        self.calls.append((b, n_valid))
        return ServeResult(
            levels=np.zeros((b, 16, 3, 16), np.float32),
            iters_run=4,
            latency_s=0.0,
            bucket=b,
            compiled=False,
        )


def _policy(clock, **kw):
    kw.setdefault("min_engines", 1)
    kw.setdefault("max_engines", 4)
    kw.setdefault("low_water", 0.2)
    kw.setdefault("high_water", 0.7)
    kw.setdefault("dwell_s", 1.0)
    kw.setdefault("cooldown_s", 3.0)
    kw.setdefault("window_s", 10.0)
    return ElasticPolicy(clock=clock, **kw)


class ScriptedPolicy(ElasticPolicy):
    """Actuator-test policy: decide() pops scripted actions."""

    def __init__(self, actions):
        super().__init__(min_engines=1, max_engines=8)
        self._actions = list(actions)

    def decide(self, n_engines):
        if not self._actions:
            return None
        return {"action": self._actions.pop(0), "signal": {"rule": "test"}}


# ---------------------------------------------------------------------------
# the policy core (fake clock, no engines)
# ---------------------------------------------------------------------------


class TestElasticPolicy:
    def test_dwell_gates_scale_out(self):
        """One low sample never acts; the condition must hold
        CONTINUOUSLY for dwell_s."""
        clk = FakeClock()
        p = _policy(clk)
        p.observe_headroom(0.05)
        assert p.decide(1) is None  # below low, but 0s of dwell
        clk.advance(0.5)
        p.observe_headroom(0.05)
        assert p.decide(1) is None  # 0.5s < dwell 1.0
        clk.advance(0.6)
        p.observe_headroom(0.05)
        d = p.decide(1)
        assert d is not None and d["action"] == "scale_out"
        assert d["signal"]["rule"] == "headroom"
        assert d["signal"]["observed"] == 0.05

    def test_hysteresis_no_flapping_across_the_marks(self):
        """A value OSCILLATING around a water mark resets the dwell
        anchor every crossing — it never accumulates enough hold to
        act, in either direction."""
        clk = FakeClock()
        p = _policy(clk)
        for _ in range(40):  # 20s of oscillation >> dwell
            clk.advance(0.5)
            p.observe_headroom(0.1)   # below low
            assert p.decide(2) is None
            clk.advance(0.5)
            p.observe_headroom(0.5)   # back between the marks: reset
            assert p.decide(2) is None

    def test_dwell_gates_scale_in(self):
        clk = FakeClock()
        p = _policy(clk)
        p.observe_headroom(0.9)
        assert p.decide(2) is None
        clk.advance(1.1)
        p.observe_headroom(0.9)
        d = p.decide(2)
        assert d is not None and d["action"] == "scale_in"

    def test_cooldown_blocks_the_next_action(self):
        clk = FakeClock()
        p = _policy(clk)
        p.observe_headroom(0.05)
        clk.advance(1.1)
        p.observe_headroom(0.05)
        assert p.decide(1)["action"] == "scale_out"
        p.acted("scale_out")
        # The condition keeps holding, but the cooldown gates:
        clk.advance(1.5)
        p.observe_headroom(0.05)
        assert p.decide(2) is None  # 1.5s < cooldown 3.0
        clk.advance(2.0)  # cooldown passed; dwell re-accumulates from
        p.observe_headroom(0.05)   # the post-action below-samples
        assert p.decide(2)["action"] == "scale_out"

    def test_min_max_clamps(self):
        clk = FakeClock()
        p = _policy(clk, min_engines=2, max_engines=3)
        p.observe_headroom(0.05)
        clk.advance(1.1)
        p.observe_headroom(0.05)
        assert p.decide(3) is None  # at max: no scale-out
        assert p.decide(2)["action"] == "scale_out"
        p2 = _policy(clk, min_engines=2, max_engines=3)
        p2.observe_headroom(0.9)
        clk.advance(1.1)
        p2.observe_headroom(0.9)
        assert p2.decide(2) is None  # at min: no scale-in
        assert p2.decide(3)["action"] == "scale_in"

    def test_breach_precedence(self):
        """An SLO breach forces scale-out consideration even at
        comfortable headroom, and VETOES scale-in outright."""
        clk = FakeClock()
        p = _policy(clk)
        # Headroom comfortably high AND sustained — scale-in would arm...
        p.observe_headroom(0.9)
        clk.advance(1.1)
        p.observe_headroom(0.9)
        p.note_breach("p99_ms")
        # ...but the breach wins both ways:
        d = p.decide(2)
        assert d is not None and d["action"] == "scale_out"
        assert d["signal"]["rule"] == "p99_ms"
        assert p.decide(8) is None  # clamped at max AND breach vetoes in

    def test_breach_ages_out_of_the_window(self):
        clk = FakeClock()
        p = _policy(clk, window_s=5.0)
        p.note_breach("p99_ms")
        clk.advance(6.0)
        p.observe_headroom(0.9)
        clk.advance(1.1)
        p.observe_headroom(0.9)
        d = p.decide(2)
        assert d is not None and d["action"] == "scale_in"

    def test_acted_resets_dwell_anchors(self):
        clk = FakeClock()
        p = _policy(clk, cooldown_s=0.0)
        p.observe_headroom(0.05)
        clk.advance(1.1)
        p.observe_headroom(0.05)
        assert p.decide(1)["action"] == "scale_out"
        p.acted("scale_out")
        # No cooldown, but the dwell must re-earn its hold from scratch
        # under the new fleet shape:
        assert p.decide(2) is None

    def test_signal_window_embedded(self):
        clk = FakeClock()
        p = _policy(clk)
        for _ in range(3):
            clk.advance(0.6)
            p.observe_headroom(0.1)
        d = p.decide(1)
        sig = d["signal"]
        assert sig["low_water"] == 0.2 and sig["high_water"] == 0.7
        assert sig["dwell_s"] == 1.0 and len(sig["samples"]) == 3
        assert all(t <= 0 for t, _ in sig["samples"])

    def test_drain_target_least_loaded_eligible_only(self):
        caps = [
            {"engine": "e0", "state": "ok", "headroom": 0.4},
            {"engine": "e1", "state": "ok", "headroom": 0.9},
            {"engine": "e2", "state": "draining", "headroom": 1.0},
            {"engine": "e3", "state": "probation", "headroom": 1.0},
            {"engine": "e4", "state": "dead", "headroom": 0.0},
        ]
        assert ElasticPolicy.pick_drain_target(caps) == "e1"
        assert ElasticPolicy.pick_drain_target(caps[2:]) is None

    def test_validation(self):
        with pytest.raises(ValueError):
            ElasticPolicy(min_engines=0)
        with pytest.raises(ValueError):
            ElasticPolicy(min_engines=3, max_engines=2)
        with pytest.raises(ValueError):
            ElasticPolicy(low_water=0.8, high_water=0.5)
        with pytest.raises(ValueError):
            ElasticPolicy(window_s=0)

    def test_resolve_policy_from_config(self):
        scfg = ServeConfig(
            elastic=True, min_engines=2, max_engines=5,
            elastic_low_water=0.1, elastic_high_water=0.8,
            elastic_dwell_s=0.5, elastic_cooldown_s=1.0,
        )
        p = resolve_policy(scfg)
        assert (p.min_engines, p.max_engines) == (2, 5)
        assert (p.low_water, p.high_water) == (0.1, 0.8)

    def test_config_validation(self):
        with pytest.raises(ValueError):
            ServeConfig(min_engines=0)
        with pytest.raises(ValueError):
            ServeConfig(min_engines=3, max_engines=1)
        with pytest.raises(ValueError):
            ServeConfig(elastic_low_water=0.9, elastic_high_water=0.5)
        with pytest.raises(ValueError):
            ServeConfig(elastic_shed_rate=1.5)


# ---------------------------------------------------------------------------
# the autoscaler actuator (real batcher, fake engines, no control thread)
# ---------------------------------------------------------------------------


def _batcher(n=1, writer=None, **kw):
    engines = [FakeEngine(name=f"engine{i}") for i in range(n)]
    for e in engines:
        e.warmup()
    b = DynamicBatcher(engines=engines, writer=writer, **kw)
    return b, engines


class TestAutoscalerScaleOut:
    def test_spawn_warms_before_admission(self):
        """THE admission pin: a freshly spawned engine receives zero
        admitted work before its warmup() precompile completes, and the
        decision -> scale_out -> admission_open chain is stamped in
        order with one decision_id."""
        sink = Sink()
        b, _ = _batcher(1, writer=sink)
        spawned = []

        def factory():
            e = FakeEngine(name="engine1")
            spawned.append(e)
            return e

        with b:
            sc = Autoscaler(
                b, factory, policy=ScriptedPolicy(["scale_out"]),
                writer=sink,
            )
            assert sc.tick() is not None
            assert b.n_active_engines() == 2
            for _ in range(8):
                b.submit(IMG)
            # Serve everything through the two-engine fleet.
            deadline = time.monotonic() + 10.0
            while b.summary_record()["n_served"] < 8:
                assert time.monotonic() < deadline
                time.sleep(0.01)
        (eng,) = spawned
        assert eng.warmed and eng.infer_before_warmup == 0
        chain = sink.events(
            "scale_out_decision", "scale_out", "admission_open"
        )
        assert [r["event"] for r in chain] == [
            "scale_out_decision", "scale_out", "admission_open"
        ]
        assert len({r["decision_id"] for r in chain}) == 1
        out = sink.events("scale_out")[0]
        assert out["engine"] == "engine1" and out["n_engines"] == 2
        assert isinstance(out["spawn_ms"], float)
        assert out["signal"]["rule"] == "test"

    def test_spawn_fault_rolls_back(self):
        """A failed scale-out leaves the fleet UNCHANGED, stamps
        spawn_rollback (+ the injected fault's own ground-truth event),
        and charges the cooldown so a persistent fault cannot hot-spin
        spawns."""
        from glom_tpu.resilience.faults import FaultPlan, spawn_fault

        sink = Sink()
        b, _ = _batcher(1, writer=sink)
        plan = FaultPlan(writer=sink)
        plan.register("engine-spawn", at=(0,), fault="spawn-fault")
        calls = []

        def factory():
            calls.append(1)
            return FakeEngine(name="engine1")

        with b:
            sc = Autoscaler(
                b, factory,
                policy=ScriptedPolicy(["scale_out", "scale_out"]),
                writer=sink, spawn_hook=spawn_fault(plan),
            )
            sc.tick()
            assert b.n_active_engines() == 1 and not calls
            assert sc.n_spawn_failures == 1
            # The cooldown was charged: the scripted policy ignores it
            # here, but the real policy's acted() ran — next tick's
            # spawn attempt (index 1) is past the fault window and lands.
            sc.tick()
            assert b.n_active_engines() == 2 and len(calls) == 1
        rb = sink.events("spawn_rollback")
        assert len(rb) == 1 and "InjectedFault" in rb[0]["exception"]
        faults = [
            r for r in sink.records
            if r.get("kind") == "fault" and r.get("site") == "engine-spawn"
        ]
        assert len(faults) == 1

    def test_factory_failure_also_rolls_back(self):
        sink = Sink()
        b, _ = _batcher(1, writer=sink)

        def factory():
            raise RuntimeError("no devices left")

        with b:
            sc = Autoscaler(
                b, factory, policy=ScriptedPolicy(["scale_out"]),
                writer=sink,
            )
            sc.tick()
            assert b.n_active_engines() == 1
        assert sink.events("spawn_rollback")

    def test_add_engine_duplicate_name_raises(self):
        b, _ = _batcher(1)
        with pytest.raises(ValueError):
            b.add_engine(FakeEngine(name="engine0"))

    def test_max_engines_never_exceeded_by_real_policy(self):
        """Breach-driven scale-out through the REAL policy clamps at
        max_engines: the breach keeps firing, the fleet stops at 2."""
        sink = Sink()
        b, _ = _batcher(1, writer=sink)
        clk = FakeClock()
        pol = _policy(clk, max_engines=2, dwell_s=0.0, cooldown_s=0.0)
        k = [0]

        def factory():
            k[0] += 1
            return FakeEngine(name=f"spawn{k[0]}")

        with b:
            sc = Autoscaler(b, factory, policy=pol, writer=sink)
            for _ in range(5):
                clk.advance(1.0)
                pol.note_breach("p99_ms")  # persistent breach in-window
                sc.tick()
        assert b.n_active_engines() == 2 and k[0] == 1

    def test_tick_feeds_only_eligible_headroom(self):
        """The control tick's headroom sample is the min across 'ok'
        engines only — a draining engine's value never reaches the
        policy."""
        sink = Sink()
        b, _ = _batcher(2, writer=sink)
        seen = []

        class Recording(ElasticPolicy):
            def observe_headroom(self, h):
                seen.append(h)
                super().observe_headroom(h)

        b.begin_drain("engine0")
        sc = Autoscaler(
            b, lambda: FakeEngine(), writer=sink,
            policy=Recording(min_engines=1, max_engines=4),
        )
        sc.tick()
        caps = {c["engine"]: c for c in b.capacity_records()}
        assert seen == [caps["engine1"]["headroom"]]


class TestAutoscalerScaleIn:
    def test_drain_chain_and_release(self):
        """The graceful drain: decision -> drain_begin -> drain_flush ->
        drain_migrate -> drain_release, one decision_id; the drained
        engine is DRAINED (not dead): worker gone, no probation, no
        capacity record, release() called — and every later request is
        served by the survivor with conservation intact."""
        sink = Sink()
        b, engines = _batcher(2, writer=sink, rejoin_threshold=3)
        with b:
            for _ in range(4):
                b.submit(IMG)
            sc = Autoscaler(
                b, lambda: FakeEngine(), writer=sink,
                policy=ScriptedPolicy(["scale_in"]),
            )
            deadline = time.monotonic() + 10.0
            while b.summary_record()["n_served"] < 4:
                assert time.monotonic() < deadline
                time.sleep(0.01)
            assert sc.tick() is not None
            assert b.n_active_engines() == 1
            drained = sink.events("drain_release")[0]["engine"]
            # DRAINED is distinct from dead: no probation thread spun up
            # for the voluntary exit (rejoin_threshold is armed!).
            assert not sink.events("engine_probation")
            for _ in range(6):
                b.submit(IMG)
            deadline = time.monotonic() + 10.0
            while b.summary_record()["n_served"] < 10:
                assert time.monotonic() < deadline
                time.sleep(0.01)
            s = b.summary_record()
            assert s["n_failed"] == 0 and s["n_served"] == 10
            assert s["engines"][drained]["drained"] is True
            # The drained engine emits no capacity record...
            caps = b.capacity_records()
            assert drained not in {c["engine"] for c in caps}
            # ...and the survivor reads "ok".
            assert all(c["state"] == "ok" for c in caps)
            assert s["elastic"]["n_scale_ins"] == 1
            assert s["elastic"]["n_engines"] == 1
        eng = b.engine_by_name(drained)
        assert eng is not None and eng.released
        chain = sink.events(
            "scale_in_decision", "drain_begin", "drain_flush",
            "drain_migrate", "drain_release",
        )
        assert [r["event"] for r in chain] == [
            "scale_in_decision", "drain_begin", "drain_flush",
            "drain_migrate", "drain_release",
        ]
        assert len({r.get("decision_id") for r in chain}) == 1

    def test_drain_refuses_last_live_engine(self):
        b, _ = _batcher(1)
        with b:
            with pytest.raises(ValueError):
                b.drain_engine("engine0")
            # Still serving after the refusal:
            t = b.submit(IMG)
            t.result(timeout=10.0)

    def test_drain_target_follows_least_loaded(self):
        """The scaler drains the max-headroom 'ok' engine (the capacity
        records decide, not engine order)."""
        sink = Sink()
        b, engines = _batcher(2, writer=sink)
        with b:
            # Load engine0's affinity lane is impractical with fakes —
            # instead pin via capacity: both idle => headroom ties at
            # 1.0, tie breaks to the LAST name (deterministic).
            sc = Autoscaler(
                b, lambda: FakeEngine(), writer=sink,
                policy=ScriptedPolicy(["scale_in"]),
            )
            sc.tick()
            assert sink.events("scale_in_decision")[0]["engine"] == "engine1"

    def test_draining_state_stamped_and_excluded_from_admission(self):
        sink = Sink()
        b, _ = _batcher(2, writer=sink)
        b.begin_drain("engine0")
        caps = {c["engine"]: c for c in b.capacity_records()}
        assert caps["engine0"]["state"] == "draining"
        assert caps["engine1"]["state"] == "ok"
        assert b._alive_engines() == ["engine1"]
        assert b.n_active_engines() == 1

    def test_drained_engine_never_enters_probation(self):
        """Review pin: a drain whose in-flight flush outlives the join
        timeout reaches the worker's dead-exit with alive already False
        — the probation path must refuse the voluntary exit (a rejoin
        would re-admit a RELEASED husk)."""
        b, engines = _batcher(2, rejoin_threshold=2)
        with b:
            b.drain_engine("engine0")
        # The husk is drained; even a direct probation attempt refuses.
        b._start_probation(engines[0], "engine0")
        with b._engine_lock:
            st = dict(b._engine_state["engine0"])
        assert not st["probation"] and not st["alive"]
        assert "engine0" in b._drained

    def test_last_admitting_engine_survives_failures_during_drain(self):
        """Review pin: while a sibling DRAINS, the one remaining
        admitting engine IS the single-engine fleet — consecutive
        failures must not mark it dead (the keeps-serving contract)."""
        b, _ = _batcher(2)
        b.begin_drain("engine1")
        for _ in range(5):  # way past engine_fail_threshold
            state = b._note_failure("engine0")
        assert state["alive"], "last admitting engine marked dead while "
        "its sibling drained"
        assert b._alive_engines() == ["engine0"]

    def test_drain_never_started_batcher(self):
        """drain_engine on a never-started batcher still completes (no
        worker to join) — the affinity queue is handed back here."""
        sink = Sink()
        b, _ = _batcher(2, writer=sink)
        stats = b.drain_engine("engine0", timeout=1.0)
        assert stats["flush_ok"] is True
        assert sink.events("drain_begin") and sink.events("drain_flush")


# ---------------------------------------------------------------------------
# capacity-state satellite: the SLO monitor's headroom exclusion
# ---------------------------------------------------------------------------


class TestHeadroomExclusion:
    @staticmethod
    def _cap(engine, headroom, state):
        return schema.stamp(
            {"engine": engine, "headroom": headroom, "state": state},
            kind="capacity",
        )

    def test_draining_and_probation_excluded(self):
        """A draining engine's 0.0 headroom must NOT drag the windowed
        min — it would fire a permanent false breach that re-triggers
        the very autoscaler that caused the drain."""
        m = SLOMonitor({"headroom": 0.5}, window_s=60.0)
        m.observe(self._cap("e0", 0.9, "ok"))
        m.observe(self._cap("e1", 0.0, "draining"))
        m.observe(self._cap("e2", 0.0, "probation"))
        assert m.observed()["headroom"] == 0.9
        assert m.evaluate() == []

    def test_dead_and_ok_still_count(self):
        """A DEAD engine's 0.0 stays a real signal (an involuntary
        death IS lost capacity), as does any ok engine."""
        m = SLOMonitor({"headroom": 0.5}, window_s=60.0)
        m.observe(self._cap("e0", 0.9, "ok"))
        m.observe(self._cap("e1", 0.0, "dead"))
        assert m.observed()["headroom"] == 0.0
        assert len(m.evaluate()) == 1

    def test_stateless_records_still_count(self):
        """Pre-v8 capacity records (no state key) keep the old
        behavior — the exclusion never hides a legacy stream."""
        m = SLOMonitor({"headroom": 0.5}, window_s=60.0)
        m.observe(
            schema.stamp({"engine": "e0", "headroom": 0.1}, kind="capacity")
        )
        assert m.observed()["headroom"] == 0.1


# ---------------------------------------------------------------------------
# device-group resolution for a runtime spawn
# ---------------------------------------------------------------------------


class TestEngineMeshFor:
    def test_next_contiguous_group_and_exhaustion(self):
        """A spawned replica takes the group the static partitioning
        would have given it; an exhausted pool raises loudly (the
        spawn_rollback path)."""
        from glom_tpu.parallel.runtime import engine_mesh_for

        scfg = ServeConfig(buckets=(2, 4), max_batch=4, mesh_data=2)
        m0 = engine_mesh_for(scfg, 0)
        m3 = engine_mesh_for(scfg, 3)  # 8 virtual devices / 2 per group
        assert m0 is not None and m3 is not None
        assert list(m0.devices.flat) != list(m3.devices.flat)
        with pytest.raises(ValueError):
            engine_mesh_for(scfg, 4)
        # Single-device route: no mesh at any index.
        assert engine_mesh_for(
            ServeConfig(buckets=(1, 2), max_batch=2), 7
        ) is None


# ---------------------------------------------------------------------------
# session migration: bitwise to a sibling pool, or stamped invalidation
# ---------------------------------------------------------------------------


class TestSessionMigration:
    @staticmethod
    def _pools(dst_pages=16):
        from glom_tpu.serve.column_cache import ColumnCache
        from glom_tpu.serve.paged_columns import PagedColumnPool
        from glom_tpu.utils.config import GlomConfig

        cfg = GlomConfig(dim=16, levels=3, image_size=8, patch_size=2)
        mk = lambda name, pages: PagedColumnPool(
            cfg,
            ServeConfig(page_pool_pages=pages, page_tokens=4),
            name=name,
        )
        pools = {"A": mk("A", 16), "B": mk("B", dst_pages)}
        cache = ColumnCache(budget_bytes=1 << 24, pools=pools)
        return cfg, pools, cache

    def test_migrate_bitwise_to_sibling_pool(self):
        """A drained engine's session is bitwise-served from the sibling
        pool after migration: the bytes round-trip src -> host -> dst
        with no float op anywhere."""
        cfg, pools, cache = self._pools()
        rng = np.random.default_rng(0)
        state = rng.normal(size=(cfg.num_patches, cfg.levels, cfg.dim))
        state = state.astype(np.float32)
        assert cache.store("s0", state, engine="A", n_tokens=cfg.num_patches)
        out = cache.migrate_engine_sessions("A", "B", reason="drain")
        assert out["n_migrated"] == 1 and out["n_invalidated"] == 0
        assert out["bytes_migrated"] == state.nbytes
        hit = cache.lookup("s0")
        assert hit is not None and hit.engine == "B"
        assert np.array_equal(pools["B"].read_block("s0"), state)
        assert pools["A"].pages_used() == 0  # src pages freed

    def test_no_budget_invalidates_with_drain_reason(self):
        """No page budget on the sibling: the session is INVALIDATED
        with the stamped `drain` reason — never silently dropped."""
        cfg, pools, cache = self._pools(dst_pages=4)
        full = np.ones(
            (cfg.num_patches, cfg.levels, cfg.dim), np.float32
        )
        sink = Sink()
        cache.writer = sink
        # Fill B so the migration target has no room (16 patches / 4
        # page_tokens = 4 pages per session; B holds exactly one).
        assert cache.store("b0", full, engine="B", n_tokens=cfg.num_patches)
        assert cache.store("a0", full * 2, engine="A", n_tokens=cfg.num_patches)
        # Pin B's block so eviction cannot make room either.
        cache.lookup("b0", pin=True)
        out = cache.migrate_engine_sessions("A", "B", reason="drain")
        assert out["n_migrated"] == 0 and out["n_invalidated"] == 1
        assert cache.lookup("a0") is None
        inv = [
            r for r in sink.records
            if r.get("event") == "cache_invalidate"
            and r.get("reason") == "drain"
        ]
        assert inv and pools["A"].pages_used() == 0

    def test_no_destination_invalidates(self):
        cfg, pools, cache = self._pools()
        full = np.ones((cfg.num_patches, cfg.levels, cfg.dim), np.float32)
        assert cache.store("a0", full, engine="A", n_tokens=cfg.num_patches)
        out = cache.migrate_engine_sessions("A", None, reason="drain")
        assert out["n_invalidated"] == 1 and cache.lookup("a0") is None

    def test_host_mode_retags(self):
        """Host-mode entries are engine-agnostic arrays: migration is a
        zero-byte re-tag."""
        from glom_tpu.serve.column_cache import ColumnCache

        cache = ColumnCache(budget_bytes=1 << 20)
        cache.store("s0", np.ones((4, 2, 4), np.float32), engine="A")
        out = cache.migrate_engine_sessions("A", "B", reason="drain")
        assert out == {
            "n_migrated": 1, "n_invalidated": 0, "bytes_migrated": 0
        }
        assert cache.lookup("s0") is not None

    def test_remove_pool_invalidates_leftovers(self):
        cfg, pools, cache = self._pools()
        full = np.ones((cfg.num_patches, cfg.levels, cfg.dim), np.float32)
        assert cache.store("a0", full, engine="A", n_tokens=cfg.num_patches)
        cache.remove_pool("A")
        assert cache.lookup("a0") is None
        assert "A" not in cache.pools

    def test_pool_release_frees_and_drops_buffer(self):
        cfg, pools, cache = self._pools()
        full = np.ones((cfg.num_patches, cfg.levels, cfg.dim), np.float32)
        assert cache.store("a0", full, engine="A", n_tokens=cfg.num_patches)
        cache.remove_pool("A")
        pools["A"].release()
        rec = pools["A"].record()
        assert rec["pages_used"] == 0
        assert pools["A"].buffer() is None


# ---------------------------------------------------------------------------
# static-path contract: no autoscaler => byte-for-byte the PR 13 shape
# ---------------------------------------------------------------------------


class TestStaticPathUnchanged:
    def test_summary_shape_has_no_elastic_keys(self):
        b, _ = _batcher(2)
        with b:
            for _ in range(3):
                b.submit(IMG)
            deadline = time.monotonic() + 10.0
            while b.summary_record()["n_served"] < 3:
                assert time.monotonic() < deadline
                time.sleep(0.01)
        s = b.summary_record()
        assert "elastic" not in s
        for st in s["engines"].values():
            assert "draining" not in st and "drained" not in st
            assert set(st) == {
                "alive", "dispatches", "consecutive_failures",
                "probation", "rejoins",
            }

    def test_capacity_record_state_ok(self):
        b, _ = _batcher(1)
        (c,) = b.capacity_records()
        assert c["state"] == "ok" and c["alive"] is True


# ---------------------------------------------------------------------------
# the decision observatory (ISSUE 18): evidence-stamped decisions
# ---------------------------------------------------------------------------


class EvidencedPolicy(ElasticPolicy):
    """Actuator-test policy whose scripted actions carry a REAL evidence
    bundle that replays to the action through the pure policy function —
    what audit_records demands of every stamped decision."""

    def __init__(self, actions):
        super().__init__(min_engines=1, max_engines=8)
        self._actions = list(actions)

    def decide(self, n_engines):
        if not self._actions:
            return None
        action = self._actions.pop(0)
        ev = self.evidence(n_engines)
        if action == "scale_out":
            ev["breaches"] = ["p99_ms"]
        else:
            ev["above_held_s"] = ev["dwell_s"] + 1.0
        return {
            "action": action,
            "signal": {"rule": "test"},
            "evidence": ev,
        }


class TestAnticipatoryPolicy:
    def _anticipatory(self, clock, **kw):
        kw.setdefault("anticipatory", True)
        kw.setdefault("target_utilization", 0.8)
        kw.setdefault("low_water", 0.2)
        kw.setdefault("high_water", 0.7)
        kw.setdefault("dwell_s", 1.0)
        kw.setdefault("cooldown_s", 0.0)
        return _policy(clock, **kw)

    def _mature(self, p, predicted=50.0):
        p.note_forecast({
            "predicted": predicted, "forecast_abs_err": 1.0,
            "horizon_s": 0.5, "trend_per_s": 0.0, "t": 1.0,
        })
        p.note_lead_time(800.0, 0.9)
        p.note_service_rate(10.0)

    def test_matured_deficit_arms_scale_out(self):
        """Predicted load over capacity scales out with a QUIET headroom
        signal — the act-ahead path — and the decision carries the full
        evidence bundle, deficit stamped, replaying bit-for-bit."""
        from glom_tpu.telemetry.audit import policy_action

        clk = FakeClock()
        p = self._anticipatory(clk)
        self._mature(p)
        p.observe_headroom(0.5)  # between the water marks: quiet
        d = p.decide(1)
        assert d is not None and d["action"] == "scale_out"
        assert d["signal"]["rule"] == "forecast"
        ev = d["evidence"]
        assert ev["anticipated_deficit_rps"] > 0
        assert ev["forecast"]["predicted"] == 50.0
        assert ev["lead_time_ms"] == 800.0 and ev["lead_quantile"] == 0.9
        assert ev["fleet_service_rate_rps"] == 10.0
        assert policy_action(ev) == "scale_out"

    def test_matured_deficit_vetoes_scale_in(self):
        clk = FakeClock()
        p = self._anticipatory(clk)
        self._mature(p)
        p.observe_headroom(0.9)
        clk.advance(2.0)  # above-water dwell satisfied...
        p.observe_headroom(0.9)
        # At the ceiling (scale-out clamped) the predicted pressure
        # still VETOES the scale-in the held-high headroom earned.
        assert p.decide(p.max_engines) is None

    def test_unmatured_forecast_is_reactive_bit_for_bit(self):
        """The satellite pin: an anticipatory policy whose forecast has
        never matured (forecast_abs_err null) decides EXACTLY like the
        PR 14 reactive policy on an identical signal stream."""
        clk_a, clk_r = FakeClock(), FakeClock()
        p_a = self._anticipatory(clk_a)
        p_r = _policy(clk_r, low_water=0.2, high_water=0.7,
                      dwell_s=1.0, cooldown_s=0.0)
        p_a.note_forecast({
            "predicted": 50.0, "forecast_abs_err": None,
            "horizon_s": 0.5, "trend_per_s": 0.0, "t": 1.0,
        })
        p_a.note_lead_time(800.0, 0.9)
        p_a.note_service_rate(10.0)
        script = [
            (0.5, 0.1, 1), (0.6, 0.1, 1), (0.4, 0.5, 1),  # below dwell
            (0.1, 0.5, 1), (0.1, 0.6, 1),                  # held low
            (0.9, 0.5, 2), (0.9, 1.2, 2),                  # held high
        ]
        for h, dt, n in script:
            for clk, p in ((clk_a, p_a), (clk_r, p_r)):
                clk.advance(dt)
                p.observe_headroom(h)
            d_a, d_r = p_a.decide(n), p_r.decide(n)
            assert (d_a is None) == (d_r is None)
            if d_a is not None:
                assert d_a["action"] == d_r["action"]
                # The anticipatory inputs ride the bundle (null deficit)
                # even when the decision came from the reactive rules.
                assert "anticipated_deficit_rps" not in d_a["evidence"]

    def test_degenerate_pinned_fit_never_scales_out(self):
        """A degenerate fit (predicted null + reason) and a pinned lead
        model both gate to reactive: the quiet fleet holds."""
        clk = FakeClock()
        p = self._anticipatory(clk)
        p.note_forecast({
            "predicted": None, "degenerate": "insufficient-samples",
            "forecast_abs_err": 2.0, "horizon_s": 0.5,
            "trend_per_s": 0.0, "t": 1.0,
        })
        p.note_lead_time(800.0, 0.9)
        p.note_service_rate(10.0)
        p.observe_headroom(0.5)
        assert p.decide(1) is None
        # Matured forecast but NO lead evidence: still reactive.
        p2 = self._anticipatory(clk)
        self._mature(p2)
        p2.note_lead_time(None)
        p2.observe_headroom(0.5)
        assert p2.decide(1) is None

    def test_resolve_policy_wires_anticipatory_knobs(self):
        scfg = ServeConfig(
            elastic=True, elastic_anticipatory=True,
            elastic_target_utilization=0.6,
        )
        p = resolve_policy(scfg)
        assert p.anticipatory is True
        assert p.target_utilization == 0.6


class TestDecisionRecords:
    def test_decision_chain_audits_clean(self):
        """The tentpole end-to-end: a scale-out then a scale-in through
        the real actuator stamp schema-v10 decision records (contiguous
        ids, prev link, evidence bundles) whose JSONL ALONE passes
        audit_records — conservation, coverage, chain."""
        from glom_tpu.telemetry.audit import audit_records

        sink = Sink()
        b, _ = _batcher(1, writer=sink)
        with b:
            sc = Autoscaler(
                b, lambda: FakeEngine(name="engine1"), writer=sink,
                policy=EvidencedPolicy(["scale_out", "scale_in"]),
            )
            assert sc.tick() is not None
            assert sc.tick() is not None
            assert b.n_active_engines() == 1
        decisions = [r for r in sink.records if r.get("kind") == "decision"]
        assert [d["decision_id"] for d in decisions] == [1, 2]
        assert [d["prev_decision_id"] for d in decisions] == [None, 1]
        assert [d["action"] for d in decisions] == ["scale_out", "scale_in"]
        assert decisions[0]["fleet"] == "fleet0"
        for d in decisions:
            assert schema.validate_record(d) == []
        rep = audit_records(sink.records)
        assert rep["errors"] == [], rep["errors"]
        assert rep["n_decisions"] == 2 and rep["n_conserved"] == 2
        # The scripted breach makes the scale-out late by definition.
        assert rep["decisions_late"] == 1
        el = sc.record()
        assert el["n_decisions"] == 2 and el["decisions_late"] == 1
        assert el["spawn_lead_violations"] == 0

    def test_every_actuation_carries_the_decision_id(self):
        sink = Sink()
        b, _ = _batcher(1, writer=sink)
        with b:
            sc = Autoscaler(
                b, lambda: FakeEngine(name="engine1"), writer=sink,
                policy=EvidencedPolicy(["scale_out", "scale_in"]),
            )
            sc.tick()
            sc.tick()
        chain = sink.events(
            "scale_out_decision", "scale_out", "admission_open",
            "engine_add", "scale_in_decision", "drain_begin",
            "drain_flush", "drain_migrate", "drain_release",
        )
        assert len(chain) >= 8
        for r in chain:
            assert isinstance(r.get("decision_id"), int), r
        out_ids = {r["decision_id"] for r in chain
                   if r["event"] in ("scale_out", "admission_open")}
        in_ids = {r["decision_id"] for r in chain
                  if r["event"] == "drain_release"}
        assert out_ids == {1} and in_ids == {2}

    def test_decision_records_fan_to_taps(self):
        """Decision records join the batcher's in-process tap stream —
        the same fan-out the forecaster and `telemetry watch` ride."""
        sink = Sink()
        tapped = []
        b, _ = _batcher(1, writer=sink)
        b.add_event_tap(tapped.append)
        with b:
            sc = Autoscaler(
                b, lambda: FakeEngine(name="engine1"), writer=sink,
                policy=EvidencedPolicy(["scale_out"]),
            )
            sc.tick()
        assert any(r.get("kind") == "decision" for r in tapped)

    def test_scripted_policy_without_evidence_still_works(self):
        """Back-compat: a decide() that returns no evidence key (the PR
        14 shape) actuates normally — the decision record just stamps
        evidence null."""
        sink = Sink()
        b, _ = _batcher(1, writer=sink)
        with b:
            sc = Autoscaler(
                b, lambda: FakeEngine(name="engine1"), writer=sink,
                policy=ScriptedPolicy(["scale_out"]),
            )
            assert sc.tick() is not None
            assert b.n_active_engines() == 2
        (d,) = [r for r in sink.records if r.get("kind") == "decision"]
        assert d["evidence"] is None and d["action"] == "scale_out"


# ---------------------------------------------------------------------------
# warm-pool spares (ISSUE 18 satellite)
# ---------------------------------------------------------------------------


class TestWarmPool:
    def test_fill_then_promote_on_scale_out(self):
        """fill_warm_pool pre-spawns + warms the spare OUTSIDE admission
        (spare_spawn stamped, fleet unchanged); the scale-out PROMOTES
        it — add_engine with the owning decision_id, no cold spawn."""
        sink = Sink()
        b, _ = _batcher(1, writer=sink)
        built = []

        def factory():
            e = FakeEngine(name=f"engine{1 + len(built)}")
            built.append(e)
            return e

        with b:
            sc = Autoscaler(
                b, factory, writer=sink, warm_pool=1,
                policy=EvidencedPolicy(["scale_out"]),
            )
            assert sc.fill_warm_pool() == 1
            (spare,) = built
            assert spare.warmed
            assert b.n_active_engines() == 1  # spare NOT admitted
            (ss,) = sink.events("spare_spawn")
            assert ss["engine"] == "engine1" and ss["n_spares"] == 1
            assert isinstance(ss["spawn_ms"], float)
            assert sc.tick() is not None
            assert b.n_active_engines() == 2
            assert len(built) == 1  # no cold spawn: the spare absorbed it
            (pr,) = sink.events("spare_promote")
            assert pr["engine"] == "engine1" and pr["decision_id"] == 1
            adds = sink.events("engine_add")
            assert adds and adds[-1]["decision_id"] == 1
            assert adds[-1]["spare"] is True
            el = sc.record()
            assert el["n_promotions"] == 1 and el["n_spares"] == 0
            assert el["n_scale_outs"] == 0  # promotion, not cold spawn

    def test_scale_in_demotes_back_to_pool(self):
        """A drained engine re-pools (NO release) while the pool is
        below target; the next scale-out re-promotes it under a fresh
        suffixed name (its old name is a retained husk)."""
        sink = Sink()
        b, _ = _batcher(2, writer=sink)
        with b:
            sc = Autoscaler(
                b, lambda: FakeEngine(name="engine9"), writer=sink,
                warm_pool=1,
                policy=EvidencedPolicy(["scale_in", "scale_out"]),
            )
            # Pool intentionally NOT pre-filled: the demotion fills it.
            assert sc.tick() is not None
            assert b.n_active_engines() == 1
            (dr,) = sink.events("drain_release")
            assert dr["demoted"] is True
            (dm,) = sink.events("spare_demote")
            assert dm["engine"] == dr["engine"] and dm["n_spares"] == 1
            demoted = b.engine_by_name(dr["engine"])
            assert demoted is not None and not demoted.released
            assert sc.record()["n_demotions"] == 1
            # Re-promotion: the husk holds the old name, so the spare
            # re-registers under a suffixed one.
            assert sc.tick() is not None
            assert b.n_active_engines() == 2
            (pr,) = sink.events("spare_promote")
            assert pr["engine"] == f"{dr['engine']}~p1"
        rep_errors = __import__(
            "glom_tpu.telemetry.audit", fromlist=["audit_records"]
        ).audit_records(sink.records)["errors"]
        assert rep_errors == [], rep_errors

    def test_spare_is_not_a_husk(self):
        """Husk retention (husk_max=0: retire every husk instantly)
        composes with the warm pool: the demoted spare leaves the
        batcher's engines nest entirely (husk retired) yet stays warm in
        the pool — and a spare never appears in the nest before its
        promotion."""
        import dataclasses as _dc

        sink = Sink()
        engines = [FakeEngine(name=f"engine{i}") for i in range(2)]
        for e in engines:
            e.warmup()
            e.scfg = _dc.replace(e.scfg, husk_max=0)
        b = DynamicBatcher(engines=engines, writer=sink)
        built = []

        def factory():
            # Exhausts after two spares: the fill stops loudly at 2,
            # leaving one pool slot for the demotion to land in.
            if len(built) >= 2:
                raise RuntimeError("device pool exhausted")
            e = FakeEngine(name=f"engine{5 + len(built)}")
            built.append(e)
            return e

        with b:
            sc = Autoscaler(
                b, factory, writer=sink,
                warm_pool=3,
                policy=EvidencedPolicy(["scale_in"]),
            )
            assert sc.fill_warm_pool() == 2
            s = b.summary_record()
            # Spares never enter the engines nest (not husks, not fleet).
            assert set(s["engines"]) == {"engine0", "engine1"}
            assert sc.tick() is not None
            s = b.summary_record()
            drained = sink.events("drain_release")[0]["engine"]
            assert drained not in s["engines"]  # husk retired (max=0)
            assert s["husks_retired"]["n"] == 1
            el = sc.record()
            # ...but the engine itself lives on as a warm spare.
            assert el["n_spares"] == 3 and el["n_demotions"] == 1

    def test_spawn_failure_during_fill_stops_loudly(self):
        sink = Sink()
        b, _ = _batcher(1, writer=sink)

        def factory():
            raise RuntimeError("device pool exhausted")

        with b:
            sc = Autoscaler(
                b, factory, writer=sink, warm_pool=2,
                policy=ScriptedPolicy([]),
            )
            assert sc.fill_warm_pool() == 0
        (rb,) = sink.events("spawn_rollback")
        assert rb["spare"] is True and rb["decision_id"] is None
        assert "device pool exhausted" in rb["exception"]

    def test_warm_pool_validation(self):
        b, _ = _batcher(1)
        with pytest.raises(ValueError, match="warm_pool"):
            Autoscaler(b, lambda: FakeEngine(), warm_pool=-1,
                       policy=ScriptedPolicy([]))
