"""Request-scoped tracing (glom_tpu/telemetry/tracectx.py): id minting,
the thread-local dispatch scope, causal-tree reconstruction, the exact
executed-work conservation check, the schema-v6 trace-context contract,
and the `python -m glom_tpu.telemetry trace` CLI. Pure host-side, no jax
— the CLI must run against a crashed run's dumps."""

import json
import threading

import pytest

from glom_tpu.telemetry import schema, tracectx


def serve(event, **fields):
    return schema.stamp({"event": event, **fields}, kind="serve")


def make_trace(trace_id="t1", *, iters=(4, 8), submit_span="root"):
    """A two-hop straggler trace: dispatch -> continuation -> dispatch ->
    resolve, with exact per-hop accounting."""
    d1, d2 = "d1", "d2"
    recs = [
        serve("dispatch", engine="e0", iters_run=iters[0], latency_ms=1.5,
              span_id=d1, trace_ids=[trace_id], parent_spans=[submit_span]),
        serve("continuation", engine="e0", n_stragglers=1,
              span_id="c1", trace_ids=[trace_id], parent_spans=[d1]),
        serve("dispatch", engine="e1", iters_run=iters[1], latency_ms=2.25,
              span_id=d2, trace_ids=[trace_id], parent_spans=[d1]),
        serve("resolve", request_id=1, engine="e1",
              iters_total=sum(iters), dispatch_ms_total=1.5 + 2.25,
              latency_ms=9.0, trace_id=trace_id, span_id="r1",
              parent_span=d2),
    ]
    return recs


class TestIds:
    def test_ids_are_hex_and_distinct(self):
        ids = {tracectx.new_id() for _ in range(64)}
        assert len(ids) == 64
        for i in ids:
            assert len(i) == 16
            int(i, 16)  # hex

    def test_trace_and_span_share_the_format(self):
        assert len(tracectx.new_trace_id()) == len(tracectx.new_span_id())


class TestDispatchScope:
    def test_scope_fields_visible_inside_only(self):
        assert tracectx.current_fields() == {}
        with tracectx.dispatch_scope("s1", ["t1", "t2"], ["p1", "p2"]):
            got = tracectx.current_fields()
            assert got == {
                "span_id": "s1",
                "trace_ids": ["t1", "t2"],
                "parent_spans": ["p1", "p2"],
            }
        assert tracectx.current_fields() == {}

    def test_scopes_nest_innermost_wins(self):
        with tracectx.dispatch_scope("outer", ["t"]):
            with tracectx.dispatch_scope("inner", ["t"]):
                assert tracectx.current_fields()["span_id"] == "inner"
            assert tracectx.current_fields()["span_id"] == "outer"

    def test_scope_is_thread_local(self):
        seen = {}

        def worker():
            seen["inner"] = tracectx.current_fields()

        with tracectx.dispatch_scope("s1", ["t1"]):
            t = threading.Thread(target=worker)
            t.start()
            t.join()
        assert seen["inner"] == {}  # another thread's scope never leaks

    def test_stamp_serve_merges_scope_fields(self):
        from glom_tpu.serve.events import stamp_serve

        with tracectx.dispatch_scope("s1", ["t1"]):
            rec = stamp_serve({"event": "cache_evict", "bytes": 8})
            assert rec["span_id"] == "s1" and rec["trace_ids"] == ["t1"]
            # A record carrying its OWN identity is never widened.
            own = stamp_serve({"event": "resolve", "trace_id": "mine"})
            assert own["trace_id"] == "mine" and "trace_ids" not in own


class TestTreeReconstruction:
    def test_records_for_singular_and_batch_forms(self):
        recs = [
            serve("resolve", trace_id="a"),
            serve("dispatch", trace_ids=["a", "b"]),
            serve("dispatch", trace_ids=["b"]),
            serve("shed", trace_id=None),  # explicitly untraced
        ]
        assert len(tracectx.records_for(recs, "a")) == 2
        assert len(tracectx.records_for(recs, "b")) == 2

    def test_list_traces_counts_hops_and_resolution(self):
        recs = make_trace("t1") + [serve("dispatch", trace_ids=["open"],
                                         span_id="dx", parent_spans=["rx"],
                                         iters_run=2, latency_ms=1.0)]
        traces = tracectx.list_traces(recs)
        assert traces["t1"]["n_hops"] == 2
        assert traces["t1"]["resolved"] is True
        assert traces["t1"]["iters_total"] == 12
        assert traces["open"]["resolved"] is False

    def test_build_tree_parent_chain(self):
        tree = tracectx.build_tree(make_trace("t1"), "t1")
        root = tree["root"]
        assert root["span_id"] == "root"  # the synthesized submit span
        assert [n["span_id"] for n in root["children"]] == ["d1"]
        d1 = root["children"][0]
        assert sorted(n["span_id"] for n in d1["children"]) == ["c1", "d2"]
        d2 = [n for n in d1["children"] if n["span_id"] == "d2"][0]
        assert [n["span_id"] for n in d2["children"]] == ["r1"]

    def test_records_sharing_a_span_collapse_into_one_node(self):
        recs = [
            serve("dispatch", span_id="d1", trace_ids=["t"],
                  parent_spans=["root"], iters_run=3, latency_ms=1.0),
            schema.stamp({"action": "dispatch-retry", "span_id": "d1",
                          "trace_ids": ["t"]}, kind="recovery"),
        ]
        tree = tracectx.build_tree(recs, "t")
        (node,) = tree["root"]["children"]
        assert len(node["records"]) == 2  # the retry rides the dispatch node

    def test_render_tree_is_printable(self):
        lines = tracectx.render_tree(tracectx.build_tree(make_trace(), "t1"))
        assert lines[0].startswith("trace t1")
        assert any("resolve" in ln for ln in lines)


class TestConservation:
    def test_exact_conservation_passes(self):
        check = tracectx.conservation(make_trace("t1"), "t1")
        assert check["ok"] is True
        assert check["n_hops"] == 2
        assert check["hop_iters"] == 12
        assert check["hop_dispatch_ms"] == 3.75

    def test_missing_hop_fails(self):
        recs = make_trace("t1")[1:]  # drop the first dispatch
        check = tracectx.conservation(recs, "t1")
        assert check["ok"] is False and "conserve" in check["why"]

    def test_wall_span_mismatch_fails(self):
        recs = make_trace("t1")
        recs[-1] = dict(recs[-1], dispatch_ms_total=99.0)
        check = tracectx.conservation(recs, "t1")
        assert check["ok"] is False and "wall spans" in check["why"]

    def test_unresolved_trace_fails_with_why(self):
        recs = make_trace("t1")[:-1]
        check = tracectx.conservation(recs, "t1")
        assert check["ok"] is False and check["resolved"] is False


class TestSchemaV6Contract:
    def test_request_scoped_serve_event_requires_a_trace_key(self):
        rec = serve("dispatch", engine="e0", latency_ms=1.0)
        errs = schema.validate_record(rec)
        assert errs and "trace" in errs[0]

    def test_null_trace_key_is_explicitly_untraced_and_valid(self):
        # v11: request-scoped events also carry slo_class (null =
        # classless), so the minimal valid shed stamps both keys.
        assert schema.validate_record(
            serve("shed", trace_id=None, slo_class=None)) == []
        assert schema.validate_record(
            serve("dispatch", trace_ids=None)) == []

    def test_pre_v6_records_are_grandfathered(self):
        rec = dict(serve("dispatch", engine="e0"), schema_version=5)
        assert schema.validate_record(rec) == []

    def test_non_request_scoped_events_are_exempt(self):
        assert schema.validate_record(serve("warmup", bucket=4)) == []

    def test_slo_breach_kind_validates(self):
        rec = schema.stamp(
            {"rule": "p99_ms", "threshold": 50.0, "observed": 80.0},
            kind="slo_breach",
        )
        assert schema.validate_record(rec) == []
        assert schema.validate_record(
            schema.stamp({"threshold": 1.0}, kind="slo_breach")) != []


class TestCli:
    def write(self, tmp_path, recs, name="trace.jsonl"):
        p = tmp_path / name
        with open(p, "w") as fh:
            fh.write("shell noise to be skipped\n")
            for r in recs:
                fh.write(json.dumps(r) + "\n")
        return p

    def test_list_mode(self, tmp_path, capsys):
        p = self.write(tmp_path, make_trace("aaa"))
        assert tracectx.main([str(p)]) == 0
        out = capsys.readouterr().out
        assert "aaa" in out and "resolved" in out

    def test_tree_mode_conserving_trace_exits_zero(self, tmp_path, capsys):
        p = self.write(tmp_path, make_trace("aaa"))
        assert tracectx.main([str(p), "--trace-id", "aaa"]) == 0
        out = capsys.readouterr().out
        assert "trace aaa" in out
        summary = json.loads(out.strip().splitlines()[-1])
        assert summary["ok"] is True and summary["kind"] == "summary"

    def test_broken_conservation_exits_nonzero(self, tmp_path, capsys):
        recs = make_trace("aaa")[1:]  # a hop's evidence is missing
        p = self.write(tmp_path, recs)
        assert tracectx.main([str(p), "--trace-id", "aaa"]) == 1
        assert "CONSERVATION FAILED" in capsys.readouterr().err

    def test_unknown_trace_exits_nonzero(self, tmp_path, capsys):
        p = self.write(tmp_path, make_trace("aaa"))
        assert tracectx.main([str(p), "--trace-id", "zzz"]) == 1

    def test_no_traces_listing_exits_nonzero(self, tmp_path):
        p = self.write(
            tmp_path, [schema.stamp({"note": "hi"}, kind="note")]
        )
        assert tracectx.main([str(p)]) == 1

    def test_multiple_inputs_merge(self, tmp_path, capsys):
        recs = make_trace("aaa")
        p1 = self.write(tmp_path, recs[:2], "a.jsonl")
        p2 = self.write(tmp_path, recs[2:], "b.jsonl")
        assert tracectx.main([str(p1), str(p2), "--trace-id", "aaa"]) == 0


class TestUntracedMode:
    def test_batcher_with_tracing_off_stamps_null_context(self):
        import sys

        sys.path.insert(0, "tests")
        import numpy as np

        from glom_tpu.serve.batcher import DynamicBatcher

        class Sink:
            def __init__(self):
                self.records = []

            def write(self, rec):
                self.records.append(rec)

        from test_serve import FakeEngine  # type: ignore

        eng = FakeEngine()
        sink = Sink()
        with DynamicBatcher(eng, max_batch=2, max_delay_ms=10.0,
                            writer=sink, trace=False) as b:
            for t in [b.submit(IMG := np.zeros((3, 8, 8), np.float32))
                      for _ in range(2)]:
                t.result(timeout=10.0)
        dispatches = [r for r in sink.records if r.get("event") == "dispatch"]
        assert dispatches and all(
            r["trace_ids"] is None for r in dispatches
        )
        # No resolve leaves when untraced — they exist for the tree.
        assert not [r for r in sink.records if r.get("event") == "resolve"]
        for r in sink.records:
            assert schema.validate_record(r) == [], r
