"""Workload observatory (glom_tpu/serve/workload.py, ISSUE 17).

The tier-1 locks:

  * the RECORDER rides a real DynamicBatcher's admission events and is
    thread-safe under concurrent submits — conservation holds exactly
    over what it captured (offered == served + shed + failed +
    unresolved), sheds keep their reason, and the artifact round-trips
    through write/load lint-clean at schema v9;
  * RECORD -> REPLAY: a captured run re-offered through a second
    batcher conserves tickets exactly and re-offers the SAME
    per-request signature sequence (the determinism pin);
  * replay PACING on a fake clock: inter-arrival gaps reproduce the
    recorded t's exactly (zero lag), time_scale stretches them, and a
    submit raise counts as shed without stopping the drive;
  * the SCENARIO GENERATORS are deterministic per seed, pure-offline
    artifacts (mixed-resolution ragged and delta modes included), and
    lint clean;
  * drained-HUSK RETENTION: a husk_max bound retires the oldest husk
    from the summary's engines nest, folds its counters into
    husks_retired, and stamps engine_husk_retired — conservation still
    reconciles.

Fake engines only — no device, no jit, no wall-clock sleeps in the
pacing assertions.
"""

import json
import threading

import numpy as np
import pytest

from glom_tpu.serve import workload as wl
from glom_tpu.serve.batcher import DynamicBatcher, QueueFullError
from glom_tpu.serve.engine import ServeResult
from glom_tpu.telemetry import schema
from glom_tpu.utils.config import ServeConfig

IMG = np.zeros((3, 8, 8), np.float32)


class Sink:
    def __init__(self):
        self.records = []

    def write(self, rec):
        self.records.append(rec)


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def advance(self, dt):
        self.t += dt

    def __call__(self):
        return self.t

    def sleep(self, dt):
        self.t += dt


class FakeEngine:
    def __init__(self, name="engine0", buckets=(1, 2, 4), **scfg_kw):
        self.name = name
        self.scfg = ServeConfig(
            buckets=buckets, max_batch=max(buckets), max_delay_ms=2.0,
            queue_depth=64, **scfg_kw,
        )
        self.calls = []

    def pick_bucket(self, n):
        for b in self.scfg.buckets:
            if n <= b:
                return b
        raise ValueError(f"n={n} exceeds the largest bucket")

    def infer(self, imgs, n_valid=None, **kw):
        b = imgs.shape[0]
        self.calls.append((b, n_valid))
        return ServeResult(
            levels=np.zeros((b, 16, 3, 16), np.float32),
            iters_run=4,
            latency_s=0.0,
            bucket=b,
            compiled=False,
        )


# ---------------------------------------------------------------------------
# the recorder on a live batcher
# ---------------------------------------------------------------------------


class TestWorkloadRecorder:
    def test_captures_and_conserves_served_requests(self):
        rec = wl.WorkloadRecorder()
        with DynamicBatcher(FakeEngine()) as b:
            rec.attach(b)
            tickets = [b.submit(IMG) for _ in range(6)]
            for t in tickets:
                t.result(timeout=10.0)
        body = rec.records()
        assert len(body) == 6
        assert all(r["outcome"] == "served" for r in body)
        assert all(r["signature"] == "bucket:3x8x8" for r in body)
        # Arrival times are run-relative and monotone.
        ts = [r["t"] for r in body]
        assert ts[0] == 0.0 and ts == sorted(ts)
        s = rec.summary()
        assert s["served"] == 6 and s["n_offered"] == 6
        for r in body:
            assert schema.validate_record(r) == []

    def test_shed_requests_stay_in_the_artifact(self):
        """A shed request was still OFFERED — the artifact keeps it with
        outcome "shed" and the reason, and conservation counts it."""
        rec = wl.WorkloadRecorder()
        b = DynamicBatcher(FakeEngine(), queue_depth=2)  # NOT started
        rec.attach(b)
        b.submit(IMG)
        b.submit(IMG)
        with pytest.raises(QueueFullError):
            b.submit(IMG)
        b.stop(drain=False)
        body = rec.records()
        assert len(body) == 3
        sheds = [r for r in body if r["outcome"] == "shed"]
        assert len(sheds) == 1 and sheds[0]["reason"] == "queue-full"
        s = rec.summary()
        assert s["n_offered"] == 3
        assert (
            s["served"] + s["shed"] + s["failed"] + s["unresolved"] == 3
        )

    def test_thread_safe_under_concurrent_submits(self):
        """Submits racing from many threads: every offer lands exactly
        once, in a consistent order, and conservation holds exactly."""
        rec = wl.WorkloadRecorder()
        n_threads, per_thread = 8, 25
        shed_count = [0]
        with DynamicBatcher(FakeEngine(), queue_depth=512) as b:
            rec.attach(b)
            tickets, tlock = [], threading.Lock()

            def pound(k):
                for j in range(per_thread):
                    try:
                        t = b.submit(IMG, session_id=f"s{k}")
                        with tlock:
                            tickets.append(t)
                    except Exception:
                        with tlock:
                            shed_count[0] += 1

            threads = [
                threading.Thread(target=pound, args=(k,))
                for k in range(n_threads)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            for t in tickets:
                t.result(timeout=30.0)
        total = n_threads * per_thread
        s = rec.summary()
        assert s["n_offered"] == total
        assert s["served"] == len(tickets)
        assert s["shed"] + s["failed"] == shed_count[0]
        assert s["unresolved"] == 0
        body = rec.records()
        assert len(body) == total
        assert [r["seed"] for r in body] == list(range(total))

    def test_artifact_round_trips_and_lints(self, tmp_path):
        rec = wl.WorkloadRecorder()
        with DynamicBatcher(FakeEngine()) as b:
            rec.attach(b)
            for i in range(4):
                b.submit(IMG, session_id=f"s{i % 2}").result(timeout=10.0)
        path = str(tmp_path / "workload.jsonl")
        n = rec.write(path, source="test")
        assert n == 4
        # Every line in the artifact is a valid stamped record: one note
        # header, the workload body, one summary trailer.
        lines = [json.loads(x) for x in open(path)]
        assert [r["kind"] for r in lines] == (
            ["note"] + ["workload"] * 4 + ["summary"]
        )
        for r in lines:
            assert schema.validate_record(r) == []
        loaded = wl.load_workload(path)
        assert [r["session"] for r in loaded] == ["s0", "s1", "s0", "s1"]

    def test_load_workload_loud_on_empty(self, tmp_path):
        path = tmp_path / "empty.jsonl"
        path.write_text(
            json.dumps(schema.stamp({"note": "nothing"}, kind="note"))
            + "\n"
        )
        with pytest.raises(ValueError, match="no workload records"):
            wl.load_workload(str(path))


# ---------------------------------------------------------------------------
# record -> replay round trip
# ---------------------------------------------------------------------------


class TestReplayRoundTrip:
    def test_replay_conserves_tickets_and_signatures(self, tmp_path):
        """THE round-trip pin: record a run, replay the artifact into a
        fresh batcher — ticket conservation is exact and the re-offered
        per-request signature sequence matches the recording."""
        rec1 = wl.WorkloadRecorder()
        with DynamicBatcher(FakeEngine()) as b1:
            rec1.attach(b1)
            for i in range(8):
                b1.submit(IMG, session_id=f"s{i % 3}").result(timeout=10.0)
        path = str(tmp_path / "w.jsonl")
        rec1.write(path, source="roundtrip")
        records = wl.load_workload(path)

        rec2 = wl.WorkloadRecorder()
        with DynamicBatcher(FakeEngine(name="replayed")) as b2:
            rec2.attach(b2)
            tickets = []

            def offer(r, i):
                tickets.append(
                    b2.submit(wl.synth_input(r, i), session_id=r["session"])
                )

            stats = wl.replay(records, offer, time_scale=0.01)
            for t in tickets:
                t.result(timeout=10.0)
        assert stats["n_offered"] == 8 and stats["n_submitted"] == 8
        assert stats["n_shed"] == 0
        summary = b2.summary_record()
        assert summary["n_requests"] == 8 and summary["n_served"] == 8
        assert (
            summary["n_served"] + summary["n_shed"] + summary["n_failed"]
            == summary["n_requests"]
        )
        body2 = rec2.records()
        assert [r["signature"] for r in body2] == [
            r["signature"] for r in records
        ]
        assert [r["session"] for r in body2] == [
            r["session"] for r in records
        ]

    def test_pacing_on_a_fake_clock_is_exact(self):
        """The injectable clock/sleep make pacing deterministic: each
        offer fires exactly at its recorded arrival (zero lag), and
        time_scale stretches the gaps."""
        clk = FakeClock()
        records = [
            schema.stamp(
                {"t": t, "signature": "bucket:1x8x8", "outcome": "offered",
                 "seed": i, "session": None, "shape": [1, 8, 8]},
                kind="workload",
            )
            for i, t in enumerate([0.0, 0.5, 1.25, 2.0])
        ]
        offered_at = []
        stats = wl.replay(
            records, lambda r, i: offered_at.append(clk.t),
            time_scale=2.0, clock=clk, sleep=clk.sleep,
        )
        assert offered_at == [0.0, 1.0, 2.5, 4.0]  # recorded t x 2
        assert stats["pacing_lag_max_ms"] == 0.0
        assert stats["pacing_lag_mean_ms"] == 0.0
        assert stats["duration_s"] == pytest.approx(4.0)

    def test_submit_raise_counts_as_shed_and_drives_on(self):
        clk = FakeClock()
        records = wl.generate("flash-crowd", 2.0, seed=1)

        def offer(r, i):
            if i % 3 == 0:
                raise QueueFullError("queue-full")

        stats = wl.replay(records, offer, clock=clk, sleep=clk.sleep)
        assert stats["n_offered"] == len(records)
        assert stats["n_shed"] == (len(records) + 2) // 3
        assert stats["n_submitted"] + stats["n_shed"] == len(records)

    def test_synth_input_is_deterministic_and_session_coherent(self):
        stateless = schema.stamp(
            {"t": 0.0, "signature": "bucket:3x8x8", "outcome": "offered",
             "seed": 7, "session": None, "shape": [3, 8, 8]},
            kind="workload",
        )
        a, b = wl.synth_input(stateless), wl.synth_input(stateless)
        assert a.shape == (3, 8, 8) and a.dtype == np.float32
        np.testing.assert_array_equal(a, b)
        # Two frames of one session are small perturbations of a shared
        # base (the column cache's temporal-coherence assumption) —
        # closer to each other than two stateless draws are.
        f0 = dict(stateless, session="sess", seed=0)
        f1 = dict(stateless, session="sess", seed=1)
        d_session = float(
            np.abs(wl.synth_input(f0) - wl.synth_input(f1)).mean()
        )
        d_stateless = float(
            np.abs(
                wl.synth_input(stateless)
                - wl.synth_input(dict(stateless, seed=8))
            ).mean()
        )
        assert d_session < 0.25 * d_stateless

    def test_ragged_record_without_shape_is_loud(self):
        rec = {"t": 0.0, "signature": "ragged:4p", "seed": 0}
        with pytest.raises(ValueError, match="replayable shape"):
            wl.synth_input(rec)


# ---------------------------------------------------------------------------
# the scenario generators
# ---------------------------------------------------------------------------


class TestScenarios:
    def test_deterministic_per_seed(self):
        a = wl.generate("diurnal", 5.0, seed=3)
        b = wl.generate("diurnal", 5.0, seed=3)
        c = wl.generate("diurnal", 5.0, seed=4)
        assert a == b
        assert a != c

    def test_all_scenarios_emit_valid_artifacts(self, tmp_path):
        for name in sorted(wl.SCENARIOS):
            recs = wl.generate(name, 4.0, seed=0)
            assert recs, f"{name}: empty scenario"
            for r in recs:
                assert r["kind"] == "workload"
                assert r["outcome"] == "offered"
                assert schema.validate_record(r) == []
            ts = [r["t"] for r in recs]
            assert ts == sorted(ts) and ts[-1] < 4.0
            path = str(tmp_path / f"{name}.jsonl")
            wl.write_workload(path, recs, source=f"scenario:{name}")
            assert len(wl.load_workload(path)) == len(recs)

    def test_flash_crowd_concentrates_arrivals(self):
        recs = wl.generate(
            "flash-crowd", 9.0, seed=0, base_rps=2.0, crowd_rps=60.0,
        )
        mid = [r for r in recs if 3.0 <= r["t"] < 6.0]
        assert len(mid) > len(recs) / 2  # the middle third IS the crowd

    def test_rolling_outage_silences_each_group_once(self):
        recs = wl.generate(
            "rolling-outage", 8.0, seed=0, rps=40.0, streams=2,
            outage_start=2.0, outage_s=4.0,
        )
        # Group 0 dark over [2, 4), group 1 over [4, 6).
        assert not [
            r for r in recs if r["session"] == "s0" and 2.0 <= r["t"] < 4.0
        ]
        assert not [
            r for r in recs if r["session"] == "s1" and 4.0 <= r["t"] < 6.0
        ]
        assert [r for r in recs if r["session"] == "s0" and r["t"] >= 6.0]

    def test_mixed_resolution_ragged_and_delta_signatures(self):
        """The replay coverage the tentpole names: mixed-resolution
        ragged admission and O(1)-shaped delta streams."""
        ragged = wl.generate(
            "diurnal", 4.0, seed=0, mode="ragged",
            shapes=((1, 28, 28), (1, 56, 56)), patch_size=14, page_tokens=4,
        )
        sigs = {r["signature"] for r in ragged}
        assert sigs == {"ragged:1p", "ragged:4p"}  # 4 and 16 tokens
        assert {tuple(r["shape"]) for r in ragged} == {
            (1, 28, 28), (1, 56, 56)
        }
        delta = wl.generate("diurnal", 4.0, seed=0, mode="delta")
        assert {r["signature"] for r in delta} == {"delta:1x28x28"}
        assert all(r["session"] is not None for r in delta)

    def test_ragged_without_page_pricing_is_loud(self):
        with pytest.raises(ValueError, match="page signature"):
            wl.generate("diurnal", 2.0, seed=0, mode="ragged")

    def test_unknown_scenario_is_loud(self):
        with pytest.raises(ValueError, match="unknown scenario"):
            wl.generate("black-friday", 2.0, seed=0)


# ---------------------------------------------------------------------------
# drained-husk retention
# ---------------------------------------------------------------------------


class TestHuskRetention:
    def _fleet(self, sink, **scfg_kw):
        engines = [
            FakeEngine(name=f"engine{i}", **scfg_kw) for i in range(3)
        ]
        return DynamicBatcher(engines=engines, writer=sink), engines

    def test_unbounded_default_retains_every_husk(self):
        sink = Sink()
        b, _ = self._fleet(sink)
        with b:
            b.submit(IMG).result(timeout=10.0)
            b.drain_engine("engine2", timeout=10.0)
        summary = b.summary_record()
        assert len(summary["engines"]) == 3  # husk retained, pre-v9 shape
        assert "husks_retired" not in summary

    def test_husk_max_retires_oldest_and_folds_counters(self):
        sink = Sink()
        b, _ = self._fleet(sink, husk_max=0)
        with b:
            b.submit(IMG).result(timeout=10.0)
            b.drain_engine("engine2", timeout=10.0)
        summary = b.summary_record()
        names = list(summary["engines"])
        assert "engine2" not in names and len(names) == 2
        assert summary["husks_retired"]["n"] == 1
        retired = [
            r for r in sink.records
            if r.get("event") == "engine_husk_retired"
        ]
        assert len(retired) == 1
        assert retired[0]["engine"] == "engine2"
        assert retired[0]["reason"] == "count-bound"
        assert schema.validate_record(retired[0]) == []
        # The surviving fleet still serves.
        b2 = b  # context already exited; counters are final evidence
        assert b2.summary_record()["n_served"] == 1

    def test_age_bound_uses_drain_time(self):
        sink = Sink()
        b, _ = self._fleet(sink, husk_max_age_s=0.0)
        with b:
            b.submit(IMG).result(timeout=10.0)
            b.drain_engine("engine1", timeout=10.0)
        retired = [
            r for r in sink.records
            if r.get("event") == "engine_husk_retired"
        ]
        assert len(retired) == 1 and retired[0]["reason"] == "age-bound"

    def test_config_validation(self):
        with pytest.raises(ValueError):
            ServeConfig(husk_max=-1)
        with pytest.raises(ValueError):
            ServeConfig(husk_max_age_s=-0.5)
