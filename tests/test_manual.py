"""Parity tests for the fully-manual SPMD path (parallel/manual.py): the
whole loss in one shard_map over (data, seq), Pallas kernels per-device.

The contract: for identical params/img/noise, the manual sharded loss and
its gradients equal the single-device dense composition (denoise_loss) to
float tolerance — DP x SP is a physical layout change, not a math change.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from glom_tpu.parallel.manual import (
    make_manual_loss,
    make_manual_train_step,
    manual_supported,
)
from glom_tpu.parallel.mesh import make_mesh
from glom_tpu.train.objectives import denoise_loss, init_denoise
from glom_tpu.train.trainer import Trainer, create_train_state
from glom_tpu.utils.config import GlomConfig, MeshConfig, TrainConfig

CFG = GlomConfig(dim=16, levels=3, image_size=16, patch_size=4)  # n=16, side=4
TCFG = TrainConfig(batch_size=4, iters=4, recon_iter_index=3)


def _data(key=0):
    rng = np.random.default_rng(key)
    img = jnp.asarray(rng.normal(size=(4, 3, 16, 16)), jnp.float32)
    noise = jnp.asarray(rng.normal(size=(4, 3, 16, 16)), jnp.float32)
    return img, noise


def _ref_loss(params, img, noise, cfg=CFG, tcfg=TCFG):
    return denoise_loss(
        params, img, noise, cfg,
        recon_index=tcfg.recon_iter_index, iters=tcfg.iters,
    )


MESHES = [
    ("dp4", MeshConfig(data=4), "none"),
    ("dp2xsp2-ring", MeshConfig(data=2, seq=2), "ring"),
    ("sp4-ring", MeshConfig(seq=4), "ring"),
    ("dp2xtp2", MeshConfig(data=2, model=2), "none"),
    ("dp2xsp2xtp2-ring", MeshConfig(data=2, seq=2, model=2), "ring"),
]


@pytest.mark.parametrize("name,mesh_cfg,sp", MESHES, ids=[m[0] for m in MESHES])
def test_manual_loss_matches_dense(name, mesh_cfg, sp):
    mesh = make_mesh(mesh_cfg, jax.devices()[: mesh_cfg.num_devices])
    params = init_denoise(jax.random.PRNGKey(0), CFG)
    img, noise = _data()
    loss_fn = make_manual_loss(mesh, CFG, TCFG, sp_strategy=sp)
    got = float(jax.jit(loss_fn)(params, img, noise))
    want = float(jax.jit(_ref_loss)(params, img, noise))
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_manual_grads_match_dense():
    """The shard_map transpose must produce the same param gradients as the
    single-device backward (the DP psum + SP collective transposes)."""
    mesh_cfg = MeshConfig(data=2, seq=2)
    mesh = make_mesh(mesh_cfg, jax.devices()[:4])
    params = init_denoise(jax.random.PRNGKey(0), CFG)
    img, noise = _data()
    loss_fn = make_manual_loss(mesh, CFG, TCFG, sp_strategy="ring")
    g_manual = jax.jit(jax.grad(loss_fn))(params, img, noise)
    g_ref = jax.jit(jax.grad(_ref_loss))(params, img, noise)
    flat_m, _ = jax.tree_util.tree_flatten(g_manual)
    flat_r, _ = jax.tree_util.tree_flatten(g_ref)
    for m, r in zip(flat_m, flat_r):
        np.testing.assert_allclose(
            np.asarray(m), np.asarray(r), rtol=2e-4, atol=1e-6
        )


def test_manual_ulysses_matches_dense():
    """Ulysses in the manual region: the all_to_all L-for-n trade must give
    the same loss AND gradients as the dense single-device composition
    (L=4 divisible by seq=2)."""
    cfg = dataclasses.replace(CFG, levels=4)
    mesh = make_mesh(MeshConfig(data=2, seq=2), jax.devices()[:4])
    params = init_denoise(jax.random.PRNGKey(2), cfg)
    img, noise = _data(2)
    loss_fn = make_manual_loss(mesh, cfg, TCFG, sp_strategy="ulysses")
    ref = lambda p, i, n: _ref_loss(p, i, n, cfg=cfg)  # noqa: E731
    got = float(jax.jit(loss_fn)(params, img, noise))
    want = float(jax.jit(ref)(params, img, noise))
    np.testing.assert_allclose(got, want, rtol=1e-5)
    g_manual = jax.jit(jax.grad(loss_fn))(params, img, noise)
    g_ref = jax.jit(jax.grad(ref))(params, img, noise)
    for m, r in zip(
        jax.tree_util.tree_leaves(g_manual), jax.tree_util.tree_leaves(g_ref)
    ):
        np.testing.assert_allclose(
            np.asarray(m), np.asarray(r), rtol=2e-4, atol=1e-6
        )


def test_manual_ulysses_indivisible_falls_back_to_ring():
    """L=3 not divisible by seq=2: warn and use ring (exact anyway)."""
    mesh = make_mesh(MeshConfig(data=2, seq=2), jax.devices()[:4])
    params = init_denoise(jax.random.PRNGKey(0), CFG)
    img, noise = _data()
    with pytest.warns(UserWarning, match="divisible"):
        loss_fn = make_manual_loss(mesh, CFG, TCFG, sp_strategy="ulysses")
    got = float(jax.jit(loss_fn)(params, img, noise))
    want = float(jax.jit(_ref_loss)(params, img, noise))
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_manual_tp_grads_match_dense():
    """Hidden-axis TP in the manual region: the hand-written Megatron psum
    plus the shard_map transpose must reproduce the single-device gradients
    for every leaf — sharded FFW weights (local cotangents), replicated
    embeddings (psum'd partials), and the 1/mp-scaled b2."""
    mesh = make_mesh(MeshConfig(data=2, seq=2, model=2), jax.devices()[:8])
    params = init_denoise(jax.random.PRNGKey(0), CFG)
    img, noise = _data()
    loss_fn = make_manual_loss(mesh, CFG, TCFG, sp_strategy="ring")
    g_manual = jax.jit(jax.grad(loss_fn))(params, img, noise)
    g_ref = jax.jit(jax.grad(_ref_loss))(params, img, noise)
    flat_m, _ = jax.tree_util.tree_flatten(g_manual)
    flat_r, _ = jax.tree_util.tree_flatten(g_ref)
    for m, r in zip(flat_m, flat_r):
        np.testing.assert_allclose(
            np.asarray(m), np.asarray(r), rtol=2e-4, atol=1e-6
        )


def test_manual_halo_with_radius_matches_dense():
    cfg = dataclasses.replace(CFG, local_consensus_radius=1)
    mesh = make_mesh(MeshConfig(seq=2), jax.devices()[:2])
    params = init_denoise(jax.random.PRNGKey(1), cfg)
    img, noise = _data(1)
    loss_fn = make_manual_loss(mesh, cfg, TCFG, sp_strategy="halo")
    got = float(jax.jit(loss_fn)(params, img, noise))
    want = float(
        jax.jit(lambda p, i, n: _ref_loss(p, i, n, cfg=cfg))(params, img, noise)
    )
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_manual_use_pallas_fallback_matches_dense():
    """use_pallas=True on CPU exercises the fused-path code shape (the
    kernels auto-fall-back to their XLA forms) — values must not change."""
    tcfg = dataclasses.replace(TCFG, use_pallas=True)
    mesh = make_mesh(MeshConfig(data=4), jax.devices()[:4])
    params = init_denoise(jax.random.PRNGKey(0), CFG)
    img, noise = _data()
    loss_fn = make_manual_loss(mesh, CFG, tcfg, sp_strategy="none")
    got = float(jax.jit(loss_fn)(params, img, noise))
    want = float(jax.jit(_ref_loss)(params, img, noise))
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_manual_train_step_matches_single_device():
    """One full manual train step (grad + adam) must track the single-device
    Trainer given identical seeds and batch."""
    mesh = make_mesh(MeshConfig(data=2, seq=2), jax.devices()[:4])
    _, optimizer = create_train_state(jax.random.PRNGKey(TCFG.seed), CFG, TCFG)
    step = make_manual_train_step(mesh, CFG, TCFG, optimizer, sp_strategy="ring")

    single = Trainer(CFG, TCFG)
    state, _ = create_train_state(
        jax.random.split(jax.random.PRNGKey(TCFG.seed))[1], CFG, TCFG
    )
    img, _ = _data()
    rng = jax.random.split(jax.random.PRNGKey(TCFG.seed))[1]
    # Same rng path as Trainer.step: split off the step rng.
    step_rng = jax.random.split(rng)[1]
    state2, metrics = jax.jit(step)(state, img, step_rng)
    ref_metrics = single.step(np.asarray(img))
    np.testing.assert_allclose(
        float(metrics["loss"]), float(ref_metrics["loss"]), rtol=1e-5
    )
    assert int(state2.step) == 1


def test_manual_grad_accum_matches_full_batch():
    """Microbatch accumulation through the manual shard_map region: same
    post-step params as the full-batch manual step."""
    mesh = make_mesh(MeshConfig(data=2, seq=2), jax.devices()[:4])
    img, _ = _data()
    rng = jax.random.PRNGKey(7)
    states = []
    for tcfg in (TCFG, dataclasses.replace(TCFG, grad_accum=2)):
        state, opt = create_train_state(jax.random.PRNGKey(0), CFG, tcfg)
        step = jax.jit(
            make_manual_train_step(mesh, CFG, tcfg, opt, sp_strategy="ring")
        )
        state, metrics = step(state, img, rng)
        assert np.isfinite(float(metrics["loss"]))
        states.append(state)
    for a, b in zip(
        jax.tree_util.tree_leaves(states[0].params),
        jax.tree_util.tree_leaves(states[1].params),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7
        )


def test_tp_hidden_uses_manual_path():
    """Hidden-axis TP + use_pallas rides the manual shard_map path (round-2
    VERDICT item 1: the pod preset must reach the fused kernels), and a
    step's loss matches the single-device trainer."""
    from glom_tpu.parallel import DistributedTrainer

    tcfg = dataclasses.replace(TCFG, use_pallas=True, batch_size=4)
    tr = DistributedTrainer(
        CFG, tcfg, MeshConfig(data=2, model=2), sp_strategy="none"
    )
    assert tr.use_manual
    assert tr.tcfg.use_pallas
    img, _ = _data()
    metrics = tr.step(np.asarray(img))

    single = Trainer(CFG, dataclasses.replace(TCFG, batch_size=4))
    ref_metrics = single.step(np.asarray(img))
    np.testing.assert_allclose(
        float(metrics["loss"]), float(ref_metrics["loss"]), rtol=1e-5
    )


def test_tp_levels_fallback_clears_use_pallas():
    """EP-style 'levels' TP has no manual-region body: must fall back to
    GSPMD with the flag CLEARED — otherwise glom_forward would emit Mosaic
    custom calls under TP-sharded weights (unpartitionable; invisible on
    CPU where kernels fall back)."""
    from glom_tpu.parallel import DistributedTrainer

    # levels=4: the EP-style spec shards bottom_up's group axis (G = L) over
    # model=2, so L must divide.
    cfg = dataclasses.replace(CFG, levels=4)
    tcfg = dataclasses.replace(TCFG, use_pallas=True, batch_size=4)
    with pytest.warns(UserWarning, match="levels"):
        tr = DistributedTrainer(
            cfg, tcfg, MeshConfig(data=2, model=2), sp_strategy="none",
            tp_axis="levels",
        )
    assert not tr.use_manual
    assert not tr.tcfg.use_pallas


def test_manual_unknown_strategy_raises():
    mesh = make_mesh(MeshConfig(data=2, seq=2), jax.devices()[:4])
    with pytest.raises(ValueError, match="unknown SP strategy"):
        make_manual_loss(mesh, CFG, TCFG, sp_strategy="ulyses")


def test_manual_supported_predicate():
    m_ok = make_mesh(MeshConfig(data=4), jax.devices()[:4])
    m_tp = make_mesh(MeshConfig(data=2, model=2), jax.devices()[:4])
    assert manual_supported(m_ok)
    assert manual_supported(m_tp)  # hidden-axis TP: manual Megatron psum
    assert manual_supported(m_ok, "levels")  # model=1: nothing to shard
    assert not manual_supported(m_tp, "levels")  # EP-style stays GSPMD


class TestShardFusedLoop:
    """The seq=1/mp=1 manual DP shard body dispatches to the whole-loop
    VJP (round 5) — loss and every gradient must match the scan-path
    manual composition, through the real shard_map (DP transpose psum
    composing with the loop's custom_vjp)."""

    # shard-local batch 8 at a loop_supported shape: d=128, n=16, L=4
    LCFG = GlomConfig(dim=128, levels=4, image_size=16, patch_size=4)
    LTCFG = TrainConfig(
        batch_size=16, iters=2, recon_iter_index=2, use_pallas=True
    )

    def _data(self):
        rng = np.random.default_rng(5)
        img = jnp.asarray(rng.normal(size=(16, 3, 16, 16)), jnp.float32)
        noise = jnp.asarray(rng.normal(size=(16, 3, 16, 16)), jnp.float32)
        return img, noise

    def test_gate_engages_at_shard_shape(self, monkeypatch):
        from glom_tpu.parallel.manual import _use_loop_vjp

        monkeypatch.delenv("GLOM_CONSENSUS_BWD", raising=False)
        assert _use_loop_vjp(
            self.LCFG, 8, 2, False, jnp.dtype(jnp.float32), True
        )
        # sub-batched shards stay on the scan path
        assert not _use_loop_vjp(
            self.LCFG, 2, 2, False, jnp.dtype(jnp.float32), True
        )

    def test_env_override_pins_scan_path(self, monkeypatch):
        """GLOM_CONSENSUS_BWD=dense (the A/B measurement knob) must pin
        the scan path through the shard dispatch too — the gate lives in
        resolve_vjp_path, not re-implemented here."""
        from glom_tpu.parallel.manual import _use_loop_vjp

        monkeypatch.setenv("GLOM_CONSENSUS_BWD", "dense")
        assert not _use_loop_vjp(
            self.LCFG, 8, 2, False, jnp.dtype(jnp.float32), True
        )

    # The heaviest single test in the suite (interpret-mode whole-loop VJP
    # under shard_map, ~60-75s): both variants are slow-marked for the
    # tier-1 budget — CI's unfiltered run and tpu_validate keep the
    # manual fused-loop parity gated on every push / hardware window.
    @pytest.mark.slow
    @pytest.mark.parametrize(
        "remat", [False, pytest.param(True, marks=pytest.mark.slow)]
    )
    def test_dp2_loop_matches_scan(self, remat):
        mesh = make_mesh(MeshConfig(data=2), jax.devices()[:2])
        tcfg = dataclasses.replace(self.LTCFG, remat=remat)
        params = init_denoise(jax.random.PRNGKey(3), self.LCFG)
        img, noise = self._data()
        # interpret=True engages the whole-loop VJP inside the shards
        # (kernels in interpret mode); the default build resolves to the
        # scan path off-TPU — the XLA-composed reference.
        loss_loop = make_manual_loss(mesh, self.LCFG, tcfg, interpret=True)
        loss_scan = make_manual_loss(mesh, self.LCFG, tcfg)
        l1, g1 = jax.value_and_grad(loss_loop)(params, img, noise)
        l2, g2 = jax.value_and_grad(loss_scan)(params, img, noise)
        np.testing.assert_allclose(float(l1), float(l2), rtol=2e-5)
        for a, b in zip(
            jax.tree_util.tree_leaves(g1), jax.tree_util.tree_leaves(g2)
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-5
            )

    def test_distributed_trainer_label_follows_dispatch(self, monkeypatch):
        """DistributedTrainer's vjp_path label must say fused_loop exactly
        when the seq=1/mp=1 shard body would dispatch there (on TPU, at
        the loop-supported shard shape) — the label and the dispatch share
        resolve_vjp_path, so this pins the plumbing between them."""
        from glom_tpu.models import core
        from glom_tpu.parallel import DistributedTrainer

        monkeypatch.setattr(core, "_on_tpu", lambda: True)
        monkeypatch.delenv("GLOM_CONSENSUS_BWD", raising=False)
        tr = DistributedTrainer(
            self.LCFG, self.LTCFG, MeshConfig(data=2), sp_strategy="none"
        )
        assert tr.use_manual
        assert tr.vjp_path == "fused_loop"
        assert tr.grad_accum == 1
        # TP shards never take the loop (scan_only=model>1): label must
        # stay scan-side at the same otherwise-eligible config
        tr_tp = DistributedTrainer(
            self.LCFG, self.LTCFG, MeshConfig(data=2, model=2),
            sp_strategy="none",
        )
        assert tr_tp.vjp_path.startswith("scan_")
