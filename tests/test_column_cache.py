"""Streaming warm-start column cache (glom_tpu/serve/column_cache.py) +
the batcher's session request path and mixed warm/cold buckets
(docs/SERVING.md, "Streaming").

The acceptance locks:
  * cache residency NEVER exceeds the byte budget (LRU eviction, reject
    of over-budget entries), TTL expiry is a miss at lookup, and two
    sessions never share column state;
  * a dispatch failure invalidates the failing engine's entries before
    any requeue — stale/dead-engine state never warm-starts a request;
  * warm-start through the batcher is BITWISE the engine dispatched
    directly from the cached state, and a mixed warm/cold bucket at
    threshold 0 is bitwise the lone-group dispatches it folded together.

Host-side tests drive fake engines (no device); the real-engine parity
locks compile the tiny CFG and are slow-marked per the serve-suite
precedent (CI's serve job runs them unfiltered).
"""

import threading

import numpy as np
import pytest

from glom_tpu.serve.batcher import DynamicBatcher
from glom_tpu.serve.column_cache import (
    ColumnCache,
    column_state_bytes,
    resolve_column_cache,
)
from glom_tpu.serve.engine import ServeResult
from glom_tpu.telemetry import schema
from glom_tpu.utils.config import GlomConfig, ServeConfig

CFG = GlomConfig(dim=16, levels=3, image_size=8, patch_size=2)  # n=16, tiny
IMG = np.zeros((3, 8, 8), np.float32)


class Sink:
    def __init__(self):
        self.records = []

    def write(self, rec):
        self.records.append(rec)


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _state(fill, n=4, L=2, d=4, dtype=np.float32):
    return np.full((n, L, d), fill, dtype)


# ---------------------------------------------------------------------------
# ColumnCache semantics (host-side, fake clock)
# ---------------------------------------------------------------------------


class TestColumnCache:
    def test_miss_then_hit_roundtrip(self):
        c = ColumnCache(budget_bytes=1 << 20)
        assert c.lookup("s0") is None
        assert c.store("s0", _state(1.0), engine="e0")
        got = c.lookup("s0")
        assert got is not None and np.array_equal(got, _state(1.0))
        rec = c.record()
        assert rec["n_hits"] == 1 and rec["n_misses"] == 1
        assert rec["bytes_in_use"] == _state(1.0).nbytes

    def test_session_isolation(self):
        """Two streams never share columns: each key returns exactly what
        IT wrote, and invalidating one leaves the other resident."""
        c = ColumnCache(budget_bytes=1 << 20)
        c.store("a", _state(1.0), engine="e0")
        c.store("b", _state(2.0), engine="e0")
        assert np.array_equal(c.lookup("a"), _state(1.0))
        assert np.array_equal(c.lookup("b"), _state(2.0))
        assert c.invalidate("a")
        assert c.lookup("a") is None
        assert np.array_equal(c.lookup("b"), _state(2.0))

    def test_ttl_expiry_is_a_miss_at_lookup(self):
        clock = FakeClock()
        c = ColumnCache(budget_bytes=1 << 20, ttl_s=10.0, clock=clock)
        c.store("s", _state(1.0), engine="e0")
        clock.t = 9.0
        assert c.lookup("s") is not None  # inside TTL
        clock.t = 20.0
        assert c.lookup("s") is None  # expired: dropped, never served
        rec = c.record()
        assert rec["n_expirations"] == 1
        assert rec["n_sessions"] == 0 and rec["bytes_in_use"] == 0

    def test_lru_eviction_under_budget(self):
        """Budget for exactly two entries: the LEAST-recently-used one
        evicts, a lookup refreshes recency, and bytes_in_use / bytes_peak
        never exceed the budget."""
        entry = _state(0.0).nbytes
        c = ColumnCache(budget_bytes=2 * entry)
        c.store("a", _state(1.0), engine="e0")
        c.store("b", _state(2.0), engine="e0")
        assert np.array_equal(c.lookup("a"), _state(1.0))  # a is now MRU
        c.store("c", _state(3.0), engine="e0")  # evicts b, not a
        assert c.lookup("b") is None
        assert np.array_equal(c.lookup("a"), _state(1.0))
        assert np.array_equal(c.lookup("c"), _state(3.0))
        rec = c.record()
        assert rec["n_evictions"] == 1
        assert rec["bytes_in_use"] <= rec["budget_bytes"]
        assert rec["bytes_peak"] <= rec["budget_bytes"]

    def test_over_budget_entry_rejected_not_overcommitted(self):
        entry = _state(0.0).nbytes
        c = ColumnCache(budget_bytes=entry // 2)
        assert not c.store("s", _state(1.0), engine="e0")
        assert c.lookup("s") is None
        rec = c.record()
        assert rec["n_rejects"] == 1 and rec["bytes_in_use"] == 0

    def test_store_same_key_replaces_without_double_count(self):
        entry = _state(0.0).nbytes
        c = ColumnCache(budget_bytes=2 * entry)
        c.store("s", _state(1.0), engine="e0")
        c.store("s", _state(2.0), engine="e0")
        assert np.array_equal(c.lookup("s"), _state(2.0))
        assert c.record()["bytes_in_use"] == entry

    def test_invalidate_engine_drops_only_its_entries(self):
        c = ColumnCache(budget_bytes=1 << 20)
        c.store("a", _state(1.0), engine="e0")
        c.store("b", _state(2.0), engine="e1")
        assert c.invalidate_engine("e0") == 1
        assert c.lookup("a") is None
        assert np.array_equal(c.lookup("b"), _state(2.0))
        assert c.record()["n_invalidations"] == 1

    def test_events_are_stamped_serve_records(self):
        sink = Sink()
        entry = _state(0.0).nbytes
        clock = FakeClock()
        c = ColumnCache(
            budget_bytes=entry, ttl_s=1.0, writer=sink, clock=clock
        )
        c.store("a", _state(1.0), engine="e0")
        c.store("b", _state(2.0), engine="e0")  # evicts a
        clock.t = 5.0
        c.lookup("b")  # expires b
        c.store("c", _state(3.0), engine="e0")
        c.invalidate_engine("e0")
        events = [r.get("event") for r in sink.records]
        assert "cache_evict" in events
        assert "cache_expire" in events
        assert "cache_invalidate" in events
        for r in sink.records:
            assert r["kind"] == "serve"
            assert schema.validate_record(r) == [], r

    def test_column_state_bytes_prices_the_real_entry(self):
        scfg32 = ServeConfig()
        scfg16 = ServeConfig(compute_dtype="bfloat16")
        n, L, d = CFG.num_patches, CFG.levels, CFG.dim
        assert column_state_bytes(CFG, scfg32) == n * L * d * 4
        assert column_state_bytes(CFG, scfg16) == n * L * d * 2
        real = np.zeros((n, L, d), np.float32)
        assert real.nbytes == column_state_bytes(CFG, scfg32)

    def test_resolve_from_config(self):
        assert resolve_column_cache(ServeConfig()) is None
        c = resolve_column_cache(
            ServeConfig(column_cache_bytes=1 << 16, column_cache_ttl_s=5.0)
        )
        assert c is not None
        assert c.budget_bytes == 1 << 16 and c.ttl_s == 5.0

    def test_config_validation(self):
        with pytest.raises(ValueError, match="column_cache_bytes"):
            ServeConfig(column_cache_bytes=-1)
        with pytest.raises(ValueError, match="column_cache_ttl_s"):
            ServeConfig(column_cache_ttl_s=0.0)
        with pytest.raises(ValueError, match="budget_bytes"):
            ColumnCache(budget_bytes=0)

    def test_thread_safety_conserves_entries(self):
        """Concurrent stores/lookups/invalidations over shared keys: the
        byte count must reconcile exactly with the surviving entries."""
        entry = _state(0.0).nbytes
        c = ColumnCache(budget_bytes=8 * entry)

        def churn(tid):
            for i in range(200):
                c.store(f"s{(tid + i) % 12}", _state(float(i)), engine="e0")
                c.lookup(f"s{i % 12}")
                if i % 17 == 0:
                    c.invalidate(f"s{i % 12}")

        threads = [
            threading.Thread(target=churn, args=(t,)) for t in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        rec = c.record()
        assert rec["bytes_in_use"] == len(c) * entry
        assert rec["bytes_in_use"] <= rec["budget_bytes"]
        assert rec["bytes_peak"] <= rec["budget_bytes"]


# ---------------------------------------------------------------------------
# batcher integration (host-side, fake engine)
# ---------------------------------------------------------------------------


class SessionFakeEngine:
    """Two-tier-shaped engine probe that records each dispatch's levels0
    rows and returns DISTINGUISHABLE per-row states (row i of dispatch k
    resolves to a constant k+1), so the tests can assert exactly which
    cached state warmed which row."""

    def __init__(self, buckets=(1, 2, 4), n_stragglers=0, scfg=None,
                 name="fake0"):
        self.scfg = scfg if scfg is not None else ServeConfig(
            buckets=buckets, max_batch=max(buckets), max_delay_ms=5.0,
            queue_depth=16, iters="auto", max_auto_iters=12,
            exit_quorum=0.5, max_continuations=2, dispatch_retries=0,
        )
        self.iters_key = "auto"
        self.auto_budget = 12
        self.n_stragglers = n_stragglers
        self.fail = None
        self.name = name
        self.calls = []
        self.shape = (4, 2, 4)  # [n, L, d]

    def cold_levels(self):
        return np.zeros(self.shape, np.float32)

    def pick_bucket(self, n):
        for b in self.scfg.buckets:
            if n <= b:
                return b
        raise ValueError(f"n={n} exceeds the largest bucket")

    def infer(self, imgs, n_valid=None, levels0=None, auto_budget=None,
              **kw):
        if self.fail is not None:
            raise self.fail
        b = imgs.shape[0]
        self.calls.append(
            {
                "bucket": b,
                "n_valid": n_valid,
                "levels0": None if levels0 is None else np.array(levels0),
                "auto_budget": auto_budget,
            }
        )
        k = len(self.calls)
        iters = 4
        conv = np.ones((b,), bool)
        if self.n_stragglers and levels0 is None:
            conv[max(0, n_valid - self.n_stragglers):n_valid] = False
        return ServeResult(
            levels=np.full((b, *self.shape), float(k), np.float32),
            iters_run=iters,
            latency_s=0.0,
            bucket=b,
            compiled=False,
            row_converged=conv,
            row_iters=np.full((b,), iters, np.int32),
        )


class TestBatcherSessionPath:
    def _batcher(self, eng, **kw):
        cache = ColumnCache(budget_bytes=1 << 20)
        b = DynamicBatcher(
            eng, max_batch=kw.pop("max_batch", 1),
            max_delay_ms=kw.pop("max_delay_ms", 5.0),
            column_cache=cache, **kw,
        )
        return b, cache

    def test_first_frame_misses_second_warm_starts(self):
        eng = SessionFakeEngine()
        b, cache = self._batcher(eng)
        with b:
            b.submit(IMG, session_id="s0").result(timeout=10.0)
            b.submit(IMG, session_id="s0").result(timeout=10.0)
            summary = b.summary_record()
        assert len(eng.calls) == 2
        assert eng.calls[0]["levels0"] is None  # frame 0: cold (miss)
        lv0 = eng.calls[1]["levels0"]
        assert lv0 is not None  # frame 1: warm from the session cache
        # ... from exactly frame 0's converged state (dispatch 1 -> 1.0).
        assert np.array_equal(lv0[0], np.full(eng.shape, 1.0, np.float32))
        cc = summary["column_cache"]
        assert cc["n_hits"] == 1 and cc["n_misses"] == 1
        assert cc["n_writes"] == 2
        dispatches = [
            d for d in summary["engines"].values()
        ]  # engine state sanity only
        assert dispatches[0]["dispatches"] == 2

    def test_sessionless_requests_never_touch_the_cache(self):
        eng = SessionFakeEngine()
        b, cache = self._batcher(eng)
        with b:
            b.submit(IMG).result(timeout=10.0)
            b.submit(IMG).result(timeout=10.0)
        assert len(cache) == 0
        rec = cache.record()
        assert rec["n_hits"] == rec["n_misses"] == rec["n_writes"] == 0

    def test_two_streams_warm_start_from_their_own_state(self):
        eng = SessionFakeEngine()
        b, cache = self._batcher(eng)
        with b:
            b.submit(IMG, session_id="a").result(timeout=10.0)  # -> 1.0
            b.submit(IMG, session_id="b").result(timeout=10.0)  # -> 2.0
            b.submit(IMG, session_id="a").result(timeout=10.0)
            b.submit(IMG, session_id="b").result(timeout=10.0)
        assert np.array_equal(
            eng.calls[2]["levels0"][0], np.full(eng.shape, 1.0, np.float32)
        )
        assert np.array_equal(
            eng.calls[3]["levels0"][0], np.full(eng.shape, 2.0, np.float32)
        )

    def test_dispatch_failure_invalidates_engine_entries(self):
        """The staleness rule: after a dispatch failure on the engine, its
        cached entries are gone — the next frame is a MISS (cold), never
        a warm start from pre-failure state."""
        eng = SessionFakeEngine()
        b, cache = self._batcher(eng)
        with b:
            b.submit(IMG, session_id="s0").result(timeout=10.0)
            assert len(cache) == 1
            eng.fail = RuntimeError("engine boom")
            t = b.submit(IMG, session_id="s0")
            with pytest.raises(RuntimeError):
                t.result(timeout=10.0)
            assert len(cache) == 0  # invalidated with the failure
            eng.fail = None
            b.submit(IMG, session_id="s0").result(timeout=10.0)
            summary = b.summary_record()
        last = eng.calls[-1]
        assert last["levels0"] is None  # cold restart, not stale warmth
        assert summary["column_cache"]["n_invalidations"] >= 1

    def test_dispatch_records_carry_cache_counters_and_lint(self):
        eng = SessionFakeEngine()
        sink = Sink()
        cache = ColumnCache(budget_bytes=1 << 20, writer=sink)
        with DynamicBatcher(eng, max_batch=1, max_delay_ms=5.0,
                            column_cache=cache, writer=sink) as b:
            b.submit(IMG, session_id="s0").result(timeout=10.0)
            b.submit(IMG, session_id="s0").result(timeout=10.0)
            summary = b.summary_record()
        dispatches = [r for r in sink.records if r.get("event") == "dispatch"]
        assert [d["n_cache_warm"] for d in dispatches] == [0, 1]
        assert [d["n_cache_miss"] for d in dispatches] == [1, 0]
        for r in sink.records + [summary]:
            assert schema.validate_record(r) == [], r


class TestMixedWarmColdBuckets:
    def test_straggler_folds_into_fresh_bucket(self):
        """The padding-cost eraser: a lone straggler's continuation hop
        picks up waiting fresh traffic instead of dispatching alone — one
        mixed dispatch whose levels0 selects per row (warm state for the
        straggler, engine cold init for the fresh row)."""
        eng = SessionFakeEngine(n_stragglers=1)
        sink = Sink()
        b = DynamicBatcher(eng, max_batch=2, max_delay_ms=10.0, writer=sink)
        # Two fresh requests queue BEFORE the worker starts: the first
        # dispatch gathers both, reports one straggler; the straggler's
        # hop folds the third (still-waiting) request into its bucket.
        t1 = b.submit(IMG)
        t2 = b.submit(IMG)
        t3 = b.submit(IMG)
        b.start()
        for t in (t1, t2, t3):
            t.result(timeout=10.0)
        summary = b.summary_record()
        b.stop()
        warm_calls = [c for c in eng.calls if c["levels0"] is not None]
        assert len(warm_calls) == 1
        mixed = warm_calls[0]
        assert mixed["n_valid"] == 2  # straggler + folded fresh row
        # Row 0 carries the straggler's warm state (dispatch 1 -> 1.0),
        # row 1 the engine's cold init — the per-row levels0 select.
        assert np.array_equal(
            mixed["levels0"][0], np.full(eng.shape, 1.0, np.float32)
        )
        assert np.array_equal(mixed["levels0"][1], eng.cold_levels())
        assert summary["n_folded"] == 1
        assert summary["n_served"] == 3 and summary["n_failed"] == 0

    def test_empty_queue_keeps_lone_group_dispatch(self):
        eng = SessionFakeEngine(n_stragglers=1)
        with DynamicBatcher(eng, max_batch=2, max_delay_ms=10.0) as b:
            t1 = b.submit(IMG)
            t2 = b.submit(IMG)
            t1.result(timeout=10.0)
            t2.result(timeout=10.0)
            summary = b.summary_record()
        warm_calls = [c for c in eng.calls if c["levels0"] is not None]
        assert len(warm_calls) == 1 and warm_calls[0]["n_valid"] == 1
        assert summary["n_folded"] == 0

    def test_mixed_dispatch_budget_caps_at_tightest_row(self):
        """A folded fresh row rides the straggler group's REMAINING
        budget (min over rows) and re-enters the continuation queue with
        its own difference — per-request totals never exceed the
        budget."""
        eng = SessionFakeEngine(n_stragglers=1)
        b = DynamicBatcher(eng, max_batch=2, max_delay_ms=10.0)
        t1 = b.submit(IMG)
        t2 = b.submit(IMG)
        t3 = b.submit(IMG)
        b.start()
        outs = [t.result(timeout=10.0) for t in (t1, t2, t3)]
        b.stop()
        warm_calls = [c for c in eng.calls if c["levels0"] is not None]
        # Straggler executed 4 of 12 -> every warm hop runs the remaining
        # budget of its tightest row.
        assert warm_calls[0]["auto_budget"] == 8
        # Every request resolved within the per-request budget.
        assert all(iters <= eng.auto_budget for _, iters, _ in outs)


# ---------------------------------------------------------------------------
# real-engine parity locks (compile-heavy: slow-marked, CI runs them)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def real_engine():
    import jax

    from glom_tpu.serve.engine import InferenceEngine

    scfg = ServeConfig(
        buckets=(1, 2), max_batch=2, max_delay_ms=5.0,
        iters="auto", exit_threshold=1e-3, max_auto_iters=8,
        dispatch_retries=0, column_cache_bytes=1 << 20,
    )
    return InferenceEngine(CFG, scfg, key=jax.random.PRNGKey(3))


@pytest.mark.slow  # compiles warm+cold auto signatures; CI serve job runs it
class TestWarmStartParity:
    def test_batcher_warm_start_bitwise_matches_direct_dispatch(
        self, real_engine
    ):
        """The streaming acceptance lock: frame 2 served through the
        batcher (cache hit -> warm levels0) lands on BITWISE the same
        columns as the engine dispatched directly from the cached state —
        the cache only chooses the init, never perturbs the compute."""
        rng = np.random.default_rng(5)
        frame1 = rng.normal(size=(3, 8, 8)).astype(np.float32)
        frame2 = (frame1 + 0.05 * rng.normal(size=(3, 8, 8))).astype(
            np.float32
        )
        with DynamicBatcher(real_engine, max_batch=1, max_delay_ms=5.0) as b:
            assert b.cache is not None  # resolved from ServeConfig
            lv1, iters1, _ = b.submit(frame1, session_id="s").result(
                timeout=60.0
            )
            cached = np.array(b.cache.lookup("s"))
            assert np.array_equal(cached, np.asarray(lv1))
            lv2, iters2, _ = b.submit(frame2, session_id="s").result(
                timeout=60.0
            )
        direct = real_engine.infer(
            frame2[None], n_valid=1, levels0=cached[None]
        )
        assert np.array_equal(np.asarray(lv2), np.asarray(direct.levels[0]))
        assert iters2 == direct.iters_run
        # And the warm start genuinely saves iterations on a coherent
        # frame — the tentpole's measured win, locked at test scale.
        assert iters2 < iters1

    def test_mixed_bucket_threshold0_bitwise_vs_lone_dispatch(self):
        """Satellite lock: at threshold 0 a mixed warm/cold dispatch is
        bitwise the lone dispatches it folded — the warm row equals the
        lone continuation (same remaining budget), the cold row equals a
        lone cold dispatch capped at the same budget, and total iters
        conserve."""
        import jax

        from glom_tpu.serve.batcher import _Item, Ticket
        from glom_tpu.serve.engine import InferenceEngine

        scfg = ServeConfig(
            buckets=(1, 2), max_batch=2, max_delay_ms=5.0,
            iters="auto", exit_threshold=0.0, max_auto_iters=6,
            max_continuations=2, dispatch_retries=0,
        )
        engine = InferenceEngine(CFG, scfg, key=jax.random.PRNGKey(4))
        rng = np.random.default_rng(6)
        img_w = rng.normal(size=(3, 8, 8)).astype(np.float32)
        img_c = rng.normal(size=(3, 8, 8)).astype(np.float32)
        # The warm row: 3 of 6 iterations already executed.
        first = engine.infer(img_w[None], n_valid=1, auto_budget=3)
        warm_state = np.asarray(first.levels[0])

        item_w = _Item(img_w, Ticket(1))
        item_w.levels = np.array(warm_state)
        item_w.executed = 3
        item_w.hops = 1
        item_w.warm_src = "cont"
        item_c = _Item(img_c, Ticket(2))
        b = DynamicBatcher(engine, max_batch=2, max_delay_ms=5.0)
        b._dispatch(engine, "engine0", [item_w, item_c])

        # Warm row: resolved at the full budget, bitwise the lone
        # continuation of the same state with the same remaining budget.
        lv_w, iters_w, _ = item_w.ticket.result(timeout=60.0)
        lone_w = engine.infer(
            img_w[None], n_valid=1, levels0=warm_state[None], auto_budget=3
        )
        assert np.array_equal(np.asarray(lv_w), np.asarray(lone_w.levels[0]))
        assert iters_w == 6  # 3 executed + 3 remaining: exact conservation
        # Cold row: capped at the straggler's remaining budget (3 of 6),
        # unconverged at threshold 0 -> re-bucketed warm with its OWN
        # remainder; its mid-flight state is bitwise a lone cold dispatch
        # at the same cap (cold init select == the forward's own init).
        assert not item_c.ticket.done()
        group = b._cont_q.get_nowait()
        assert group == [item_c] and item_c.executed == 3
        lone_c = engine.infer(img_c[None], n_valid=1, auto_budget=3)
        assert np.array_equal(item_c.levels, np.asarray(lone_c.levels[0]))
        item_c.ticket._fail(RuntimeError("test cleanup"))
