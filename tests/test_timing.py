"""utils/timing.py coverage (the chain-timing helpers every bench rides)
— previously untested. No profiler backend, no jit compiles of interest:
the chains here are host fakes with deterministic sleeps, so the module
stays cheap in the tier-1 budget while pinning the contracts the benches
depend on (min-over-repeats, non-finite rejection, degenerate-timing
errors, chain-length calibration)."""

import time

import jax.numpy as jnp
import pytest

from glom_tpu.utils import timing
from glom_tpu.utils.timing import (
    best_fetch_time,
    calibrated_chain_time,
    measure_rtt,
)


class TestBestFetchTime:
    def test_returns_min_over_repeats(self):
        durs = iter([0.03, 0.02, 0.01, 0.02])  # first is the warm call

        def fn(x):
            time.sleep(next(durs))
            return jnp.float32(1.0)

        t = best_fetch_time(fn, None, repeats=3)
        assert 0.005 < t < 0.02  # the min of the timed calls, not the mean

    def test_rejects_nonfinite_warm_call(self):
        with pytest.raises(RuntimeError, match="non-finite"):
            best_fetch_time(lambda: jnp.float32(float("nan")), repeats=2)

    def test_rejects_nonfinite_mid_run(self):
        outs = iter([1.0, 1.0, float("inf")])
        with pytest.raises(RuntimeError, match="non-finite"):
            best_fetch_time(lambda: jnp.float32(next(outs)), repeats=2)

    def test_fetch_is_the_sync(self):
        # fn must return something float() can fetch — the host fetch IS
        # the synchronization contract.
        assert best_fetch_time(lambda: jnp.asarray(2.0), repeats=1) >= 0


class TestMeasureRtt:
    def test_small_positive_and_data_dependent(self):
        x = jnp.ones((4, 4), jnp.float32)
        rtt = measure_rtt(x, repeats=2)
        assert 0 < rtt < 5.0


class TestCalibratedChainTime:
    def test_recovers_known_per_op_cost(self):
        per_op = 2e-4

        def chain(k):
            time.sleep(int(k) * per_op)
            return jnp.float32(1.0)

        measured = calibrated_chain_time(
            chain, jnp.ones((2,), jnp.float32),
            repeats=2, calib_k=4, target_s=0.02,
        )
        # Sleep + fetch overhead only ever inflates; bound loosely enough
        # for a loaded CI box while still pinning the order of magnitude.
        assert per_op * 0.5 < measured < per_op * 10

    def test_chain_length_scales_to_target(self):
        calls = []
        per_op = 1e-3

        def chain(k):
            calls.append(int(k))
            time.sleep(int(k) * per_op)
            return jnp.float32(1.0)

        calibrated_chain_time(
            chain, jnp.ones((2,), jnp.float32),
            repeats=2, calib_k=2, target_s=0.05,
        )
        # last chain sized to ~target_s/per_est ops, clamped >= calib_k
        assert calls[-1] > 2
        assert calls[-1] * per_op == pytest.approx(0.05, rel=0.9)

    def test_degenerate_timing_raises(self, monkeypatch):
        # An RTT estimate larger than the whole chain (the broken-tunnel
        # signature) must error loudly, not return a negative per-op.
        monkeypatch.setattr(timing, "measure_rtt", lambda *a, **k: 100.0)
        with pytest.raises(RuntimeError, match="degenerate"):
            calibrated_chain_time(
                lambda k: jnp.float32(1.0), jnp.ones((2,), jnp.float32),
                repeats=1, calib_k=2, target_s=0.01,
            )

    def test_max_k_clamps_runaway_chains(self):
        calls = []

        def chain(k):
            calls.append(int(k))
            return jnp.float32(1.0)  # ~instant: per_est floors at 1e-7

        try:
            calibrated_chain_time(
                chain, jnp.ones((2,), jnp.float32),
                repeats=1, calib_k=2, target_s=10.0, max_k=64,
            )
        except RuntimeError:
            pass  # degenerate is fine — the clamp is what's under test
        assert max(calls) <= 64
