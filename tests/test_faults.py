"""Fault-injection harness + recovery machinery, host-side (tier-1 fast).

The determinism contract comes first: every test here drives the SAME
seeded FaultPlan API the chaos suite uses, and the assertions pin exact
fire patterns, exact watchdog transition chains, and exact ladder rung
sequences — a fault harness that flakes certifies nothing. Heavier
integration (real engine compiles, subprocess kills) lives in
tests/test_chaos.py (slow-marked; CI's chaos job runs it unfiltered).
"""

import numpy as np
import pytest

from glom_tpu.resilience import (
    CAPPED_ITERS,
    NORMAL,
    SHED,
    DegradationLadder,
    FaultPlan,
    InjectedFault,
    RetryPolicy,
    dispatch_fault,
    nan_storm,
    probe_flap,
    truncate_newest_checkpoint,
)
from glom_tpu.telemetry import schema
from glom_tpu.telemetry.watchdog import (
    BackendWatchdog,
    set_global_watchdog,
)


class ListWriter:
    """Minimal writer: records land in .records (the tests' stream)."""

    def __init__(self):
        self.records = []

    def write(self, rec):
        self.records.append(rec)


@pytest.fixture(autouse=True)
def _clean_globals():
    yield
    set_global_watchdog(None)


# ---------------------------------------------------------------------------
# FaultPlan: the seeded decision source
# ---------------------------------------------------------------------------


class TestFaultPlan:
    def test_at_schedule_fires_exactly_there(self):
        w = ListWriter()
        plan = FaultPlan(seed=7, writer=w)
        plan.register("site", at=(1, 3))
        assert [plan.fires("site") for _ in range(5)] == [
            False, True, False, True, False,
        ]
        assert [e["index"] for e in plan.events()] == [1, 3]
        assert plan.record()["sites"]["site"] == {"calls": 5, "fired": 2}
        for rec in w.records:
            assert rec["kind"] == "fault"
            assert schema.validate_record(rec) == []

    def test_rate_schedule_is_seed_deterministic(self):
        def pattern(seed):
            p = FaultPlan(seed=seed)
            p.register("s", rate=0.3)
            return [p.fires("s") for _ in range(64)]

        assert pattern(11) == pattern(11)
        assert pattern(11) != pattern(12)
        assert any(pattern(11))  # a 0.3 rate over 64 calls fires

    def test_sites_are_independent(self):
        p1 = FaultPlan(seed=5)
        p1.register("a", rate=0.5)
        fired_a = [p1.fires("a") for _ in range(32)]
        p2 = FaultPlan(seed=5)
        p2.register("b", rate=0.5)  # extra site must not perturb "a"
        p2.register("a", rate=0.5)
        assert [p2.fires("a") for _ in range(32)] == fired_a

    def test_window_bounds_rate_fires(self):
        p = FaultPlan(seed=0)
        p.register("s", rate=1.0, start=2, stop=4)
        assert [p.fires("s") for _ in range(6)] == [
            False, False, True, True, False, False,
        ]

    def test_unregistered_site_never_fires(self):
        p = FaultPlan(seed=0)
        assert not any(p.fires("nope") for _ in range(10))

    def test_register_validation(self):
        p = FaultPlan()
        with pytest.raises(ValueError):
            p.register("s")  # neither at nor rate
        with pytest.raises(ValueError):
            p.register("s", at=(1,), rate=0.5)  # both
        with pytest.raises(ValueError):
            p.register("s", rate=1.5)

    def test_wrap_raises_scheduled_and_passes_through(self):
        p = FaultPlan(seed=0)
        p.register("ckpt-write", at=(1,), fault="ckpt-write-failure")
        calls = []
        fn = p.wrap(lambda x: calls.append(x) or x, "ckpt-write")
        assert fn(10) == 10
        with pytest.raises(InjectedFault):
            fn(11)
        assert fn(12) == 12
        assert calls == [10, 12]  # the faulted call never reached fn
        [event] = p.events()
        assert event["fault"] == "ckpt-write-failure"

    def test_wrap_custom_exception(self):
        p = FaultPlan(seed=0)
        p.register("io", at=(0,))
        fn = p.wrap(lambda: "ok", "io", exc=lambda: OSError("injected"))
        with pytest.raises(OSError):
            fn()
        assert fn() == "ok"


# ---------------------------------------------------------------------------
# Backend flap: the watchdog's injection seam
# ---------------------------------------------------------------------------


def _flap_watchdog(fault_indices, *, flap_threshold=3, writer=None):
    """Watchdog on a healthy fake probe with a seeded flap schedule
    installed through the production seam (set_probe_fault)."""
    plan = FaultPlan(seed=3, writer=writer)
    plan.register(
        "watchdog-probe", at=fault_indices, fault="backend-flap"
    )
    clock = [0.0]
    wd = BackendWatchdog(
        probe=lambda timeout: 1,
        flap_window_s=1e9,
        flap_threshold=flap_threshold,
        heartbeat_s=0,
        writer=writer,
        clock=lambda: clock[0],
    )
    wd.set_probe_fault(probe_flap(plan))
    return wd, plan, clock


class TestInjectedFlap:
    def test_seeded_schedule_pins_the_transition_chain(self):
        """The satellite contract: the down->up->down flap window is pinned
        by a seeded fault schedule — same seed, same chain, every run."""
        w = ListWriter()
        wd, plan, clock = _flap_watchdog((2, 4), writer=w)
        states = []
        for i in range(6):
            clock[0] = float(i)
            states.append(wd.probe_once())
        # idx: 0 up (unknown->up), 1 up, 2 injected down, 3 up — third
        # transition inside the window => FLAPPING, 4 injected down,
        # 5 up (still flapping).
        assert states == ["up", "up", "down", "flapping", "down", "flapping"]
        tl = wd.timeline()
        for prev, nxt in zip(tl, tl[1:]):
            assert nxt["prev_state"] == prev["backend_state"]
        assert [t["backend_state"] for t in tl] == [
            "up", "down", "flapping", "down", "flapping",
        ]
        # the injected ground truth reconciles: two faults, two downs
        assert [e["index"] for e in plan.events()] == [2, 4]
        for rec in w.records:
            assert schema.validate_record(rec) == []

    def test_flapping_state_never_triggers_backend_down_dump(self, tmp_path):
        """Flapping must NOT fire the flight recorder's backend-down dump:
        only hard "down" transitions dump; the flapping re-entries (and
        the up legs between) add nothing."""
        from glom_tpu.tracing.flight import FlightRecorder

        fr = FlightRecorder(str(tmp_path))
        wd, plan, clock = _flap_watchdog((2, 4, 6), writer=fr)
        for i in range(9):
            clock[0] = float(i)
            wd.probe_once()
        # Exactly one dump per DOWN transition — the flapping events in
        # between never re-trigger (they are "up with history").
        n_down = sum(
            1 for t in wd.timeline() if t["backend_state"] == "down"
        )
        assert n_down == 3
        assert len(fr.dumps) == n_down
        for path in fr.dumps:
            with open(path) as fh:
                lines = fh.read().splitlines()
            assert schema.lint_stream(lines) == []
            first = next(schema.iter_json_lines([lines[0]]))[1]
            assert first["trigger"] == "backend-down"

    def test_batcher_serves_through_flapping_but_sheds_on_down(self):
        """Flapping is degraded service, not an outage: submissions must
        be ACCEPTED while flapping and shed only on hard down."""
        from glom_tpu.serve.batcher import BackendDownError, DynamicBatcher

        wd, plan, clock = _flap_watchdog((2,))
        for i in range(4):
            clock[0] = float(i)
            wd.probe_once()
        assert wd.state == "flapping"
        set_global_watchdog(wd)
        batcher = DynamicBatcher(
            _FakeEngine(), max_batch=2, queue_depth=4
        )
        ticket = batcher.submit(np.zeros((3, 8, 8), np.float32))
        assert not ticket.done() or ticket  # admitted, not shed
        # now force a hard down
        wd.set_probe_fault(lambda n: None)
        clock[0] = 10.0
        assert wd.probe_once() == "down"
        with pytest.raises(BackendDownError) as ei:
            batcher.submit(np.zeros((3, 8, 8), np.float32))
        assert "queue_depth" in ei.value.detail
        batcher.stop(drain=False)


# ---------------------------------------------------------------------------
# RetryPolicy: flapping retries, down fails fast
# ---------------------------------------------------------------------------


class _FakeEngine:
    """Engine-shaped stub (tests/test_races.py's FakeEngine, leaner)."""

    retry = None

    def __init__(self, buckets=(1, 2, 4), latency_s=0.0):
        self.buckets = buckets
        self.latency_s = latency_s
        self.calls = []

    def pick_bucket(self, n):
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(n)

    def infer(self, imgs, n_valid=None, iters_override=None):
        import time as _time

        from glom_tpu.serve.engine import ServeResult

        if self.latency_s:
            _time.sleep(self.latency_s)
        self.calls.append({"n_valid": n_valid, "iters_override": iters_override})
        b = imgs.shape[0]
        return ServeResult(
            levels=np.zeros((b, 4, 3, 8), np.float32),
            iters_run=iters_override if iters_override is not None else 6,
            latency_s=self.latency_s,
            bucket=b,
            compiled=False,
        )


class _StubWatchdog:
    def __init__(self, state):
        self.state = state

    def record(self):
        return {"backend_state": self.state}


class TestRetryPolicy:
    def _policy(self, writer=None, **kw):
        kw.setdefault("backoff_s", 0.0)
        return RetryPolicy(writer=writer, **kw)

    def test_transient_failure_recovers_and_stamps(self):
        w = ListWriter()
        sleeps = []
        policy = RetryPolicy(
            retries=2, backoff_s=0.05, backoff_factor=2.0,
            writer=w, sleep=sleeps.append,
        )
        attempts = [0]

        def attempt():
            attempts[0] += 1
            if attempts[0] < 3:
                raise InjectedFault("transient")
            return "served"

        assert policy.run(attempt, bucket=4) == "served"
        assert attempts[0] == 3
        assert sleeps == [0.05, 0.1]  # exponential
        actions = [r["action"] for r in w.records]
        assert actions == [
            "dispatch-retry", "dispatch-retry", "dispatch-recovered",
        ]
        for rec in w.records:
            assert rec["kind"] == "recovery"
            assert rec["bucket"] == 4
            assert schema.validate_record(rec) == []
        rec = policy.record()
        assert rec["n_retries"] == 2 and rec["n_recovered"] == 1

    def test_nonretryable_raises_immediately(self):
        policy = self._policy()
        calls = [0]

        def attempt():
            calls[0] += 1
            raise ValueError("caller bug")

        with pytest.raises(ValueError):
            policy.run(attempt)
        assert calls[0] == 1
        assert policy.record()["n_retries"] == 0

    def test_down_backend_fails_fast_no_retry(self):
        set_global_watchdog(_StubWatchdog("down"))
        policy = self._policy(retries=5)
        calls = [0]

        def attempt():
            calls[0] += 1
            raise InjectedFault("wedged")

        with pytest.raises(InjectedFault):
            policy.run(attempt)
        assert calls[0] == 1  # never retried into the dead backend
        assert policy.record()["n_fast_failed"] == 1

    def test_flapping_backend_does_retry(self):
        set_global_watchdog(_StubWatchdog("flapping"))
        policy = self._policy(retries=1)
        calls = [0]

        def attempt():
            calls[0] += 1
            if calls[0] == 1:
                raise InjectedFault("flap gap")
            return "served"

        assert policy.run(attempt) == "served"
        assert calls[0] == 2

    def test_budget_exhausted_gives_up(self):
        w = ListWriter()
        policy = self._policy(retries=2, writer=w)

        def attempt():
            raise InjectedFault("persistent")

        with pytest.raises(InjectedFault):
            policy.run(attempt)
        assert policy.record()["n_gave_up"] == 1
        assert [r["action"] for r in w.records] == [
            "dispatch-retry", "dispatch-retry",
        ]

    def test_validation(self):
        with pytest.raises(ValueError):
            RetryPolicy(retries=-1)
        with pytest.raises(ValueError):
            RetryPolicy(backoff_factor=0.5)


# ---------------------------------------------------------------------------
# Degradation ladder
# ---------------------------------------------------------------------------


class TestLadder:
    def _ladder(self, writer=None, **kw):
        kw.setdefault("degraded_iters", 3)
        kw.setdefault("bucket_cap", 2)
        kw.setdefault("min_dwell_s", 0.0)
        return DegradationLadder(writer=writer, **kw)

    def test_pressure_steps_down_then_drains_back_up(self):
        w = ListWriter()
        ladder = self._ladder(writer=w)
        rungs = [ladder.observe(queue_fill=0.9) for _ in range(3)]
        assert rungs == [1, 2, 3]  # one rung per evaluation, down to shed
        assert ladder.rung_name() == "shed"
        rungs = [ladder.observe(queue_fill=0.0) for _ in range(3)]
        assert rungs == [2, 1, 0]  # fully REVERSIBLE
        assert ladder.rung() == NORMAL
        events = ladder.timeline()
        assert [e["direction"] for e in events] == (
            ["degrade"] * 3 + ["restore"] * 3
        )
        assert [e["rung"] for e in events] == [
            "capped_iters", "bucket_cap", "shed",
            "bucket_cap", "capped_iters", "normal",
        ]
        for e in events:
            assert e["kind"] == "serve" and e["event"] == "ladder"
            assert "backend_state" in e  # stamp_serve merged it
            assert schema.validate_record(e) == []
        rec = ladder.record()
        assert rec["ladder_degrades"] == 3 and rec["ladder_restores"] == 3

    def test_flapping_floors_at_capped_iters_never_sheds(self):
        ladder = self._ladder()
        # flapping with an EMPTY queue: degrade to capped_iters, no more
        for _ in range(5):
            rung = ladder.observe(queue_fill=0.0, backend_state="flapping")
        assert rung == CAPPED_ITERS
        assert ladder.rung_name() == "capped_iters"
        # the flap alone can never reach shed
        assert all(
            ladder.observe(queue_fill=0.3, backend_state="flapping") < SHED
            for _ in range(5)
        )
        # backend settles, queue empty -> full restore
        ladder.observe(queue_fill=0.0, backend_state="up")
        assert ladder.rung() == NORMAL

    def test_dwell_hysteresis_limits_transition_rate(self):
        clock = [0.0]
        ladder = self._ladder(min_dwell_s=10.0, clock=lambda: clock[0])
        assert ladder.observe(queue_fill=0.9) == 1
        assert ladder.observe(queue_fill=0.9) == 1  # dwell blocks
        clock[0] = 11.0
        assert ladder.observe(queue_fill=0.9) == 2

    def test_from_config_resolves_defaults(self):
        from glom_tpu.utils.config import GlomConfig, ServeConfig

        cfg = GlomConfig(dim=16, levels=3, image_size=8, patch_size=2)
        scfg = ServeConfig(max_batch=8)
        ladder = DegradationLadder.from_config(cfg, scfg)
        assert ladder.degraded_iters == cfg.default_iters // 2 == 3
        assert ladder.bucket_cap == 4
        scfg2 = ServeConfig(degraded_iters=2, degraded_max_batch=1)
        ladder2 = DegradationLadder.from_config(cfg, scfg2)
        assert ladder2.degraded_iters == 2 and ladder2.bucket_cap == 1

    def test_validation(self):
        with pytest.raises(ValueError):
            self._ladder(high_water=0.2, low_water=0.5)
        with pytest.raises(ValueError):
            self._ladder(degraded_iters=0)


class TestBatcherLadder:
    def test_shed_rung_sheds_new_admissions_with_the_why(self):
        from glom_tpu.serve.batcher import DynamicBatcher, LadderShedError

        w = ListWriter()
        ladder = DegradationLadder(
            degraded_iters=3, bucket_cap=2, min_dwell_s=0.0, writer=w
        )
        for _ in range(3):
            ladder.observe(queue_fill=1.0)
        assert ladder.rung() == SHED
        batcher = DynamicBatcher(
            _FakeEngine(), max_batch=2, queue_depth=4, writer=w,
            shed_when_down=False, ladder=ladder,
        )
        with pytest.raises(LadderShedError) as ei:
            batcher.submit(np.zeros((3, 8, 8), np.float32))
        assert ei.value.detail["rung"] == "shed"
        assert "queue_depth" in ei.value.detail
        shed = [r for r in w.records if r.get("event") == "shed"]
        assert shed and shed[0]["reason"] == "ladder-shed"
        assert shed[0]["rung"] == "shed"
        assert "queue_depth" in shed[0] and "queue_capacity" in shed[0]
        assert schema.validate_record(shed[0]) == []
        assert batcher.summary_record()["n_requests"] == 1
        batcher.stop(drain=False)

    def test_capped_iters_rung_dispatches_degraded(self):
        from glom_tpu.serve.batcher import DynamicBatcher

        w = ListWriter()
        # huge dwell: the forced rung cannot restore mid-test
        ladder = DegradationLadder(
            degraded_iters=3, bucket_cap=2, min_dwell_s=1e9, writer=w
        )
        ladder.observe(queue_fill=0.9)
        assert ladder.rung() == CAPPED_ITERS
        engine = _FakeEngine()
        batcher = DynamicBatcher(
            engine, max_batch=2, max_delay_ms=1.0, queue_depth=8,
            writer=w, shed_when_down=False, ladder=ladder,
        ).start()
        ticket = batcher.submit(np.zeros((3, 8, 8), np.float32))
        _, iters_run, _ = ticket.result(timeout=10.0)
        batcher.stop()
        assert iters_run == 3  # the degraded budget, not the full 6
        assert engine.calls[-1]["iters_override"] == 3
        disp = [r for r in w.records if r.get("event") == "dispatch"]
        assert disp[0]["rung"] == "capped_iters"
        assert disp[0]["iters_override"] == 3
        s = batcher.summary_record()
        assert s["n_degraded"] == 1 and s["ladder_rung"] == "capped_iters"
        assert schema.validate_record(s) == []

    def test_serve_config_ladder_auto_resolves(self):
        """ServeConfig(ladder=True) must never be silently two-mode: a
        batcher built without an explicit ladder resolves one from the
        engine's config (docs/RESILIENCE.md names this enable path)."""
        from glom_tpu.serve.batcher import DynamicBatcher
        from glom_tpu.utils.config import GlomConfig, ServeConfig

        engine = _FakeEngine()
        engine.cfg = GlomConfig(dim=16, levels=3, image_size=8, patch_size=2)
        engine.scfg = ServeConfig(ladder=True, max_batch=4, buckets=(1, 2, 4))
        batcher = DynamicBatcher(engine, shed_when_down=False)
        assert batcher.ladder is not None
        assert batcher.ladder.degraded_iters == 3  # default_iters // 2
        assert batcher.ladder.bucket_cap == 2
        batcher.stop(drain=False)
        # explicit instances and plain configs stay untouched
        assert DynamicBatcher(_FakeEngine(), shed_when_down=False).ladder is None

    def test_queue_full_shed_carries_depth(self):
        from glom_tpu.serve.batcher import DynamicBatcher, QueueFullError

        w = ListWriter()
        batcher = DynamicBatcher(
            _FakeEngine(), max_batch=4, queue_depth=1,
            shed_when_down=False, writer=w,
        )
        batcher.submit(np.zeros((3, 8, 8), np.float32))  # fills depth-1
        with pytest.raises(QueueFullError) as ei:
            batcher.submit(np.zeros((3, 8, 8), np.float32))
        detail = dict(ei.value.detail)
        # Since schema v6 the shed detail also carries the request's
        # minted trace_id (telemetry/tracectx.py) so callers can join
        # their own failure records to the shed leaf.
        assert isinstance(detail.pop("trace_id", None), str)
        assert detail == {
            "queue_depth": 1,
            "queue_capacity": 1,
            "continuations_queued": 0,
        }
        shed = [r for r in w.records if r.get("event") == "shed"]
        assert shed[0]["queue_depth"] == 1
        assert shed[0]["reason"] == "queue-full"
        assert shed[0]["trace_id"] == ei.value.detail["trace_id"]
        batcher.stop(drain=False)


# ---------------------------------------------------------------------------
# NaN storm + checkpoint faults
# ---------------------------------------------------------------------------


class TestDataAndCheckpointFaults:
    def test_nan_storm_poisons_exactly_the_scheduled_batches(self):
        plan = FaultPlan(seed=0)
        plan.register("nan-storm", at=(1,))
        clean = [np.ones((2, 2), np.float32) for _ in range(3)]
        out = list(nan_storm(iter(clean), plan))
        assert not np.isnan(out[0]).any()
        assert np.isnan(out[1]).any()
        assert not np.isnan(out[2]).any()
        # the source batches are never mutated in place
        assert not np.isnan(clean[1]).any()

    def test_dispatch_fault_hook_raises_on_schedule(self):
        plan = FaultPlan(seed=0)
        plan.register("engine-dispatch", at=(0,), fault="dispatch-error")
        hook = dispatch_fault(plan)
        with pytest.raises(InjectedFault):
            hook({"bucket": 4, "n_valid": 2, "attempt": 1})
        hook({"bucket": 4, "n_valid": 2, "attempt": 2})  # retry lands
        [event] = plan.events()
        assert event["fault"] == "dispatch-error"
        assert event["bucket"] == 4 and event["attempt"] == 1

    def test_truncate_newest_checkpoint_stamps_the_fault(self, tmp_path):
        from glom_tpu.utils.checkpoint import CheckpointManager

        w = ListWriter()
        mgr = CheckpointManager(str(tmp_path), async_save=False)
        state = {"a": np.arange(16, dtype=np.float32)}
        mgr.save(1, state)
        mgr.save(2, state)
        out = truncate_newest_checkpoint(tmp_path, writer=w)
        assert out is not None and out[0] == 2
        [rec] = w.records
        assert rec["kind"] == "fault" and rec["fault"] == "torn-checkpoint"
        assert rec["step"] == 2
        assert schema.validate_record(rec) == []
        mgr.close()

    def test_schema_v4_kinds_validate(self):
        fault = schema.stamp({"fault": "backend-flap", "site": "s"}, kind="fault")
        rec = schema.stamp({"action": "restart", "attempt": 1}, kind="recovery")
        assert schema.validate_record(fault) == []
        assert schema.validate_record(rec) == []
        assert schema.infer_kind({"fault": "x"}) == "fault"
        bad = schema.stamp({"note": "n"}, kind="fault")
        assert schema.validate_record(bad)  # missing required `fault`
