"""Full-forward parity with the NumPy oracle + every SURVEY §3.2 subtlety."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from glom_tpu.models import Glom, glom_forward, init_glom
from glom_tpu.models.core import contribution_divisor
from glom_tpu.utils.config import GlomConfig
from oracle_np import np_forward, np_local_mask

CFG = GlomConfig(dim=16, levels=3, image_size=8, patch_size=2)  # n=16, tiny


def params_to_np(params):
    def ffw(p):
        return {k: np.asarray(getattr(p, k), np.float64) for k in ("w1", "b1", "w2", "b2")}

    return {
        "token_w": np.asarray(params.token_embed.w, np.float64),
        "token_b": np.asarray(params.token_embed.b, np.float64),
        "pos_emb": np.asarray(params.pos_emb, np.float64),
        "init_levels": np.asarray(params.init_levels, np.float64),
        "bottom_up": ffw(params.bottom_up),
        "top_down": ffw(params.top_down),
    }


@pytest.fixture(scope="module")
def setup():
    params = init_glom(jax.random.PRNGKey(1), CFG)
    img = np.random.default_rng(2).normal(size=(2, 3, 8, 8))
    return params, params_to_np(params), img


class TestForwardParity:
    def test_default_forward(self, setup):
        params, np_params, img = setup
        got = glom_forward(params, jnp.asarray(img, jnp.float32), CFG)
        want = np_forward(np_params, img, levels_cfg=CFG.levels, patch_size=2)
        assert got.shape == (2, 16, 3, 16)
        np.testing.assert_allclose(np.asarray(got), want, rtol=2e-3, atol=2e-4)

    def test_default_iters_is_2L(self, setup):
        """Contract #1: default T = 2*levels, observable via return_all count."""
        params, _, img = setup
        all_states = glom_forward(
            params, jnp.asarray(img, jnp.float32), CFG, return_all=True
        )
        assert all_states.shape[0] == 2 * CFG.levels + 1  # T+1 incl. initial

    def test_return_all_includes_initial(self, setup):
        """Contract #6: state 0 is the broadcast init_levels."""
        params, _, img = setup
        all_states = glom_forward(
            params, jnp.asarray(img, jnp.float32), CFG, return_all=True
        )
        want0 = np.broadcast_to(
            np.asarray(params.init_levels)[None, None], all_states.shape[1:]
        )
        np.testing.assert_allclose(np.asarray(all_states[0]), want0, atol=1e-6)
        # and state 1 differs (the loop actually ran)
        assert not np.allclose(np.asarray(all_states[1]), want0)

    def test_explicit_iters(self, setup):
        params, np_params, img = setup
        got = glom_forward(params, jnp.asarray(img, jnp.float32), CFG, iters=4)
        want = np_forward(np_params, img, levels_cfg=CFG.levels, patch_size=2, iters=4)
        np.testing.assert_allclose(np.asarray(got), want, rtol=2e-3, atol=2e-4)

    def test_levels_carry_in(self, setup):
        """Contract #7: T iters from a provided state == 2x T/2 chained calls
        (the temporal/video recipe)."""
        params, _, img = setup
        jimg = jnp.asarray(img, jnp.float32)
        full = glom_forward(params, jimg, CFG, iters=4)
        half = glom_forward(params, jimg, CFG, iters=2)
        chained = glom_forward(params, jimg, CFG, iters=2, levels=half)
        np.testing.assert_allclose(
            np.asarray(chained), np.asarray(full), rtol=1e-4, atol=1e-5
        )

    def test_top_level_divisor_is_3(self):
        """Contract #5."""
        div = np.asarray(contribution_divisor(5))
        assert div.shape == (5, 1)
        assert (div[:-1] == 4.0).all() and div[-1] == 3.0

    def test_local_radius_forward_parity(self, setup):
        cfg = GlomConfig(
            dim=16, levels=3, image_size=8, patch_size=2, local_consensus_radius=1
        )
        params, np_params, img = setup
        got = glom_forward(params, jnp.asarray(img, jnp.float32), cfg, iters=3)
        want = np_forward(
            np_params,
            img,
            levels_cfg=3,
            patch_size=2,
            iters=3,
            local_mask=np_local_mask(4, 1),
        )
        np.testing.assert_allclose(np.asarray(got), want, rtol=2e-3, atol=2e-4)

    def test_consensus_self_forward_parity(self, setup):
        cfg = GlomConfig(dim=16, levels=3, image_size=8, patch_size=2, consensus_self=True)
        params, np_params, img = setup
        got = glom_forward(params, jnp.asarray(img, jnp.float32), cfg, iters=3)
        want = np_forward(
            np_params, img, levels_cfg=3, patch_size=2, iters=3, attend_self=True
        )
        np.testing.assert_allclose(np.asarray(got), want, rtol=2e-3, atol=2e-4)

    def test_remat_matches_plain(self, setup):
        params, _, img = setup
        jimg = jnp.asarray(img, jnp.float32)
        plain = glom_forward(params, jimg, CFG)
        remat = glom_forward(params, jimg, CFG, remat=True)
        np.testing.assert_allclose(np.asarray(remat), np.asarray(plain), atol=1e-6)

    def test_grad_flows(self, setup):
        """backward through all T scan iterations (the README training path)."""
        params, _, img = setup
        jimg = jnp.asarray(img, jnp.float32)

        def loss(p):
            return jnp.mean(glom_forward(p, jimg, CFG, remat=True) ** 2)

        g = jax.grad(loss)(params)
        flat = jax.tree_util.tree_leaves(g)
        assert all(np.isfinite(np.asarray(t)).all() for t in flat)
        assert any(np.abs(np.asarray(t)).max() > 0 for t in flat)


class TestGlomAPI:
    def test_reference_signature(self):
        """The reference constructor and forward call, verbatim."""
        model = Glom(dim=16, levels=3, image_size=8, patch_size=2)
        img = jnp.zeros((1, 3, 8, 8))
        out = model(img)
        assert out.shape == (1, 16, 3, 16)
        all_states = model(img, iters=5, return_all=True)
        assert all_states.shape == (6, 1, 16, 3, 16)
        cont = model(img, iters=2, levels=out)
        assert cont.shape == out.shape

    def test_backend_flag(self):
        Glom(dim=16, levels=2, image_size=8, patch_size=2, backend="tpu")
        with pytest.raises(ValueError):
            Glom(dim=16, levels=2, image_size=8, patch_size=2, backend="cuda")

    def test_jit_cache_reused(self):
        model = Glom(dim=16, levels=2, image_size=8, patch_size=2)
        img = jnp.zeros((1, 3, 8, 8))
        model(img)
        model(img)
        assert len(model._jitted) == 1

    def test_config_validation(self):
        with pytest.raises(ValueError):
            GlomConfig(image_size=10, patch_size=3)
        with pytest.raises(ValueError):
            GlomConfig(levels=1)

    def test_backend_tpu_selects_pallas_path(self):
        """backend='tpu' must reach the fused kernel path (VERDICT weak #4:
        round 1's preserved API only ever hit the slow path) and agree with
        the explicit slow path numerically."""
        model = Glom(dim=16, levels=3, image_size=8, patch_size=2, backend="tpu")
        assert model.use_pallas
        slow = Glom(
            dim=16, levels=3, image_size=8, patch_size=2, use_pallas=False,
            params=model.params,
        )
        assert not slow.use_pallas
        img = jnp.asarray(np.random.default_rng(0).normal(size=(1, 3, 8, 8)), jnp.float32)
        np.testing.assert_allclose(
            np.asarray(model(img)), np.asarray(slow(img)), rtol=1e-5, atol=1e-6
        )

    def test_mesh_kwarg_runs_sharded(self):
        """mesh= + sp_strategy= through the preserved API: same results as
        the single-device forward."""
        from glom_tpu.utils.config import MeshConfig

        base = Glom(dim=16, levels=3, image_size=8, patch_size=2, use_pallas=False)
        sharded = Glom(
            dim=16, levels=3, image_size=8, patch_size=2,
            mesh=MeshConfig(data=2, seq=2), sp_strategy="ring",
            params=base.params, use_pallas=False,
        )
        assert not sharded.use_pallas  # GSPMD path carries the sharding
        img = jnp.asarray(
            np.random.default_rng(1).normal(size=(2, 3, 8, 8)), jnp.float32
        )
        np.testing.assert_allclose(
            np.asarray(sharded(img)), np.asarray(base(img)), rtol=1e-5, atol=1e-6
        )

    def test_mesh_default_rides_manual_fused_path(self):
        """Round-2 VERDICT weak #5: `Glom(mesh=...)` must reach the fused
        path — the backend='tpu' default keeps use_pallas ON under a mesh
        and routes through the manual shard_map forward, matching the
        single-device forward on final levels, return_all stacks, and the
        temporal levels carry."""
        from glom_tpu.utils.config import MeshConfig

        base = Glom(dim=16, levels=3, image_size=8, patch_size=2, use_pallas=False)
        sharded = Glom(
            dim=16, levels=3, image_size=8, patch_size=2,
            mesh=MeshConfig(data=2, seq=2), sp_strategy="ring",
            params=base.params,
        )
        assert sharded.use_pallas  # the fused path survives the mesh
        img = jnp.asarray(
            np.random.default_rng(1).normal(size=(2, 3, 8, 8)), jnp.float32
        )
        np.testing.assert_allclose(
            np.asarray(sharded(img)), np.asarray(base(img)), rtol=1e-5, atol=1e-6
        )
        np.testing.assert_allclose(
            np.asarray(sharded(img, return_all=True)),
            np.asarray(base(img, return_all=True)),
            rtol=1e-5, atol=1e-6,
        )
        lv = base(img, iters=2)
        np.testing.assert_allclose(
            np.asarray(sharded(img, iters=3, levels=lv)),
            np.asarray(base(img, iters=3, levels=lv)),
            rtol=1e-5, atol=1e-6,
        )

    def test_mesh_tp_manual_forward_matches(self):
        """Hidden-TP mesh through the API: the manual Megatron psum in the
        inference forward too."""
        from glom_tpu.utils.config import MeshConfig

        base = Glom(dim=16, levels=3, image_size=8, patch_size=2, use_pallas=False)
        sharded = Glom(
            dim=16, levels=3, image_size=8, patch_size=2,
            mesh=MeshConfig(data=2, seq=2, model=2), sp_strategy="ring",
            params=base.params,
        )
        assert sharded.use_pallas
        img = jnp.asarray(
            np.random.default_rng(2).normal(size=(2, 3, 8, 8)), jnp.float32
        )
        np.testing.assert_allclose(
            np.asarray(sharded(img)), np.asarray(base(img)), rtol=1e-5, atol=1e-6
        )

    def test_mesh_without_standard_axes_warns(self):
        import jax as _jax
        from jax.sharding import Mesh

        mesh = Mesh(np.asarray(_jax.devices()[:2]).reshape(2), ("x",))
        with pytest.warns(UserWarning, match="axis names"):
            m = Glom(
                dim=16, levels=3, image_size=8, patch_size=2,
                mesh=mesh, use_pallas=True,
            )
        assert not m.use_pallas
