"""Host-thread race harness: seeded interleaving stress over the four
threaded subsystems (batcher submit/stop, watchdog flap, flight-dump-
during-emit) plus the runtime half of the lockset acceptance pair — a
deliberately-unlocked DynamicBatcher counter mutation demonstrably LOSES
updates under barrier-forced interleaving while the shipped class
conserves them exactly.

The static half lives in tests/test_analysis.py (the lockset checker over
tests/fixtures/racy_batcher.py). Everything here is host-only (fake
engine, injected probes/clocks) and deterministic where it matters: the
lost-update demonstration uses barriers, not sleeps. slow-marked per the
tier-1 budget; CI's lint job runs this module unfiltered.
"""

import random
import threading
import time

import numpy as np
import pytest

from glom_tpu.serve.batcher import DynamicBatcher, ShedError
from glom_tpu.serve.engine import ServeResult
from glom_tpu.telemetry import schema
from glom_tpu.telemetry.watchdog import BackendWatchdog
from glom_tpu.tracing.flight import FlightRecorder

pytestmark = pytest.mark.slow  # tier-1 keeps only the fast AST tests

IMG = np.zeros((3, 8, 8), np.float32)


class FakeEngine:
    """Engine-shaped stub: instant (or slightly delayed) zero-levels."""

    def __init__(self, buckets=(1, 2, 4), latency_s=0.0):
        self.buckets = buckets
        self.latency_s = latency_s

    def pick_bucket(self, n):
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(n)

    def infer(self, imgs, n_valid=None):
        if self.latency_s:
            time.sleep(self.latency_s)
        b = imgs.shape[0]
        return ServeResult(
            levels=np.zeros((b, 4, 3, 8), np.float32),
            iters_run=6,
            latency_s=self.latency_s,
            bucket=b,
            compiled=False,
        )


# ---------------------------------------------------------------------------
# submit/stop interleaving: no ticket is ever stranded
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("drain", [True, False])
def test_submit_stop_race_never_strands_a_ticket(drain):
    """8 submitter threads race a mid-traffic stop(): every ticket a
    caller ever got back must reach a terminal state (served or failed)
    — a hang here is the round-5 wedge this subsystem exists to kill."""
    rng = random.Random(20260803)
    for round_seed in range(3):
        batcher = DynamicBatcher(
            FakeEngine(latency_s=0.001),
            max_batch=4,
            max_delay_ms=1.0,
            queue_depth=16,
            shed_when_down=False,
        ).start()
        tickets, lock = [], threading.Lock()
        stop_evt = threading.Event()

        def submitter(seed):
            r = random.Random(seed)
            while not stop_evt.is_set():
                try:
                    t = batcher.submit(IMG)
                except ShedError:
                    continue
                with lock:
                    tickets.append(t)
                if r.random() < 0.2:
                    time.sleep(0.0005)

        threads = [
            threading.Thread(target=submitter, args=(rng.random(),))
            for _ in range(8)
        ]
        for t in threads:
            t.start()
        time.sleep(0.03)
        # stop() runs concurrently with live submitters for a beat (the
        # race under test), then the submitters quiesce so a draining
        # worker can actually reach an empty queue.
        stopper = threading.Thread(target=batcher.stop, kwargs={"drain": drain})
        stopper.start()
        time.sleep(0.01)
        stop_evt.set()
        for t in threads:
            t.join(timeout=5.0)
            assert not t.is_alive()
        stopper.join(timeout=90.0)
        assert not stopper.is_alive()
        # late submits against the stopped batcher must fail fast, not hang
        with pytest.raises(ShedError):
            while True:
                batcher.submit(IMG)
        n_served = n_failed = 0
        for t in tickets:
            try:
                t.result(timeout=5.0)
                n_served += 1
            except ShedError:
                n_failed += 1
        assert n_served + n_failed == len(tickets)
        if drain:
            # graceful stop serves everything already accepted
            assert n_served >= 1
        # counters stay conserved under the race (reads under lock).
        # A submit that raced the dying worker may have been admitted —
        # and even served — after its caller got ShedError, so the
        # batcher's view bounds ours; it must never be smaller, and
        # n_served <= n_submitted must hold unconditionally.
        s = batcher.summary_record()
        assert s["n_submitted"] >= len(tickets)
        assert n_served <= s["n_served"] <= s["n_submitted"]
        assert schema.validate_record(s) == []


# ---------------------------------------------------------------------------
# watchdog flap under concurrent probes and readers
# ---------------------------------------------------------------------------


def test_watchdog_flap_stress_timeline_stays_consistent():
    """Concurrent probe_once callers + record()/timeline() readers over a
    flapping backend: the transition chain must stay linked (each event's
    prev_state == the previous event's backend_state) and the counters
    reconciled — the lock discipline the lockset checker certifies
    statically, exercised dynamically."""
    counter = [0]
    count_lock = threading.Lock()

    def probe(timeout):
        with count_lock:
            counter[0] += 1
            n = counter[0]
        return 1 if (n // 5) % 2 == 0 else None  # flip every 5 probes

    wd = BackendWatchdog(
        probe=probe, flap_window_s=1e9, flap_threshold=3, heartbeat_s=0
    )
    errors = []

    def prober():
        try:
            for _ in range(40):
                state = wd.probe_once()
                assert state in schema.WATCHDOG_STATES
        except BaseException as e:  # pragma: no cover - failure evidence
            errors.append(e)

    def reader():
        try:
            for _ in range(200):
                rec = wd.record()
                assert rec["backend_state"] in schema.WATCHDOG_STATES
                tl = wd.timeline()
                for prev, nxt in zip(tl, tl[1:]):
                    assert nxt["prev_state"] == prev["backend_state"]
        except BaseException as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=prober) for _ in range(4)] + [
        threading.Thread(target=reader) for _ in range(2)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30.0)
        assert not t.is_alive()
    assert errors == []
    tl = wd.timeline()
    assert len(tl) >= 2  # the flip sequence produced real transitions
    for prev, nxt in zip(tl, tl[1:]):
        assert nxt["prev_state"] == prev["backend_state"]
    assert tl[-1]["transitions"] == len(tl)
    for event in tl:
        assert schema.validate_record(event) == []


# ---------------------------------------------------------------------------
# flight recorder: dumps racing the feed
# ---------------------------------------------------------------------------


def test_flight_dump_during_emit_stays_lintable(tmp_path):
    """Writer threads feed the ring while a dumper forces dumps: every
    dump file must lint clean against the schema and carry strictly
    increasing flight_seq — a torn dump (half-appended event, seq going
    backwards) is exactly what a postmortem artifact cannot be."""
    fr = FlightRecorder(str(tmp_path), capacity=32)
    stop = threading.Event()

    def writer(tid):
        i = 0
        while not stop.is_set():
            fr.observe(
                schema.stamp({"note": f"w{tid}-{i}"}, kind="note")
            )
            i += 1

    def dumper():
        while not stop.is_set():
            fr.dump("race-test")

    threads = [threading.Thread(target=writer, args=(t,)) for t in range(4)]
    threads.append(threading.Thread(target=dumper))
    for t in threads:
        t.start()
    time.sleep(0.2)
    stop.set()
    for t in threads:
        t.join(timeout=10.0)
        assert not t.is_alive()
    fr.dump("final")
    assert fr.dumps
    for path in fr.dumps:
        with open(path) as fh:
            lines = fh.read().splitlines()
        assert schema.lint_stream(lines) == []
        header = schema.iter_json_lines([lines[0]])
        assert next(iter(header))[1]["kind"] == "note"
        seqs = [
            rec["flight_seq"]
            for _, rec in schema.iter_json_lines(lines[1:])
        ]
        assert seqs == sorted(seqs)
        assert len(set(seqs)) == len(seqs)


# ---------------------------------------------------------------------------
# the lockset acceptance pair, runtime half: unlocked mutation loses
# updates; the shipped batcher conserves them
# ---------------------------------------------------------------------------

N_THREADS = 8
N_ROUNDS = 5


class RacyShedBatcher(DynamicBatcher):
    """DynamicBatcher with the shed counter's lock DELIBERATELY removed
    and barriers forcing the read/write interleaving — the runtime twin
    of tests/fixtures/racy_batcher.py's static fixture."""

    def __init__(self, *args, read_barrier=None, write_barrier=None, **kw):
        super().__init__(*args, **kw)
        self._read_barrier = read_barrier
        self._write_barrier = write_barrier

    def _shed(self, ticket, reason, **detail):
        n = self.n_shed  # unlocked read...
        self._read_barrier.wait()  # ...held stale by every thread
        self.n_shed = n + 1  # unlocked write: all but one increment lost
        self._write_barrier.wait()
        ticket._fail(ShedError(reason))


def _full_batcher(cls, **kw):
    """A never-started batcher whose queue is pre-filled: every submit
    sheds via the queue-full path, which is where _shed races."""
    b = cls(FakeEngine(), max_batch=4, queue_depth=1,
            shed_when_down=False, **kw)
    b.submit(IMG)  # fills the depth-1 queue (no worker to drain it)
    return b


def test_unlocked_shed_counter_loses_updates_deterministically():
    read_b = threading.Barrier(N_THREADS)
    write_b = threading.Barrier(N_THREADS)
    batcher = _full_batcher(
        RacyShedBatcher, read_barrier=read_b, write_barrier=write_b
    )

    def hammer():
        for _ in range(N_ROUNDS):
            with pytest.raises(ShedError):
                batcher.submit(IMG)

    threads = [threading.Thread(target=hammer) for _ in range(N_THREADS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30.0)
        assert not t.is_alive()
    # every round: N_THREADS read the same value, N_THREADS write value+1
    # — the unlocked read-modify-write keeps exactly ONE of the N_THREADS
    # increments per round. The harness detects the introduced race 100%
    # deterministically, not probabilistically.
    assert batcher.n_shed == N_ROUNDS
    assert batcher.n_shed < N_THREADS * N_ROUNDS


def test_shipped_batcher_conserves_shed_counts_under_the_same_load():
    batcher = _full_batcher(DynamicBatcher)

    def hammer():
        for _ in range(200):
            with pytest.raises(ShedError):
                batcher.submit(IMG)

    threads = [threading.Thread(target=hammer) for _ in range(N_THREADS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30.0)
        assert not t.is_alive()
    assert batcher.summary_record()["n_shed"] == N_THREADS * 200


def test_shipped_summary_record_races_worker_without_tearing():
    """summary_record() snapshots under the counter lock (the fix the
    lockset checker forced): hammer it while the worker serves and check
    internal consistency of every snapshot."""
    batcher = DynamicBatcher(
        FakeEngine(), max_batch=2, max_delay_ms=0.5, queue_depth=64,
        shed_when_down=False,
    ).start()
    stop = threading.Event()
    errors = []

    def summarizer():
        try:
            while not stop.is_set():
                s = batcher.summary_record()
                assert s["n_served"] <= s["n_submitted"]
                assert sum(s["iters_histogram"].values()) <= s["n_served"]
                assert schema.validate_record(s) == []
        except BaseException as e:  # pragma: no cover
            errors.append(e)

    reader = threading.Thread(target=summarizer)
    reader.start()
    tickets = []
    for _ in range(300):
        try:
            tickets.append(batcher.submit(IMG))
        except ShedError:
            time.sleep(0.001)
    batcher.stop(drain=True)
    stop.set()
    reader.join(timeout=10.0)
    assert not reader.is_alive()
    assert errors == []
    for t in tickets:
        t.result(timeout=5.0)  # drain=True: everything accepted is served
