"""Block-banded ragged consensus parity matrix + in-place pool aliasing
torture suite (ISSUE 16).

THE PARITY CONTRACT is per-row page spans: the banded route is BITWISE
the windowed gather on every row's span (valid tokens AND intra-row pad
slots) at every iteration count. Tokens in completely UNUSED trailing
pages sit outside the contract: row_len == 0 hard-masks every slot, so
their softmax is a uniform average over route-dependent clamped garbage
values — and they are semantically dead (the convergence witness masks
them, the batcher resolves only row slices, write-backs and straggler
carries are per-row spans). The Pallas kernel holds the fused-route
TOLERANCE contract instead (an online softmax reorders the reduction);
off-TPU the wrapper falls back to the jnp banded route, which keeps CPU
serving on the bitwise bar end to end.

The aliasing half tortures the write seam: donated in-place write-backs
gated by read pins, the loud copy-on-write fallback when a dispatch has
the buffer pinned, byte-moved accounting (aliased writes move pages,
CoW writes move the whole pool), refcounted shared-base isolation, and
pool conservation under churn with aliasing on.
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from glom_tpu.models.core import init_glom
from glom_tpu.serve.engine import InferenceEngine
from glom_tpu.serve.early_exit import (
    banded_ragged_consensus_attention,
    ragged_consensus_attention,
    ragged_window_bytes,
)
from glom_tpu.serve.paged_columns import PagedColumnPool, pages_for_tokens
from glom_tpu.utils.config import GlomConfig, ServeConfig

CFG = GlomConfig(dim=32, levels=3, image_size=16, patch_size=4)  # n=16
SCFG = ServeConfig(
    buckets=(1, 2, 4), max_batch=4, max_delay_ms=2.0,
    iters="auto", max_auto_iters=6, exit_threshold=0.0,
    page_pool_pages=32, page_tokens=4, ragged=True,
    dispatch_retries=0,
)
PT = 4


def _layout(counts, pt=PT, pages_sig=None):
    """Page-aligned (row_start, row_len, T, starts) for per-token maps —
    the host mirror of serve/early_exit.ragged_row_layout."""
    pages = [pages_for_tokens(c, pt) for c in counts]
    P = pages_sig if pages_sig is not None else sum(pages)
    T = P * pt
    row_start = np.zeros((T,), np.int32)
    row_len = np.zeros((T,), np.int32)
    starts = []
    off = 0
    for c, k in zip(counts, pages):
        s = off * pt
        starts.append(s)
        row_start[s:s + k * pt] = s
        row_len[s:s + k * pt] = c
        off += k
    return row_start, row_len, T, starts


def _spans(arr, counts, starts, pt=PT):
    """Each row's FULL page span (valid tokens + intra-row pads) — the
    unit the parity contract covers."""
    out = []
    for c, s in zip(counts, starts):
        out.append(np.asarray(arr)[s:s + pages_for_tokens(c, pt) * pt])
    return out


class TestBandedParityMatrix:
    COUNTS = [5, 3, 16, 1]  # mixed: intra-row pads on three rows

    def _levels(self, T, seed=7):
        rng = np.random.default_rng(seed)
        return jnp.asarray(
            rng.normal(size=(T, CFG.levels, CFG.dim)).astype(np.float32)
        )

    def test_attention_bitwise_per_row_span(self):
        """One attention application: banded == windowed bitwise on
        every row span, window == the largest row's page band."""
        row_start, row_len, T, starts = _layout(self.COUNTS)
        lv = self._levels(T)
        window = pages_for_tokens(max(self.COUNTS), PT) * PT
        rs, rl = jnp.asarray(row_start), jnp.asarray(row_len)
        win = ragged_consensus_attention(
            lv, row_start=rs, row_len=rl, window=window
        )
        band = banded_ragged_consensus_attention(
            lv, row_start=rs, row_len=rl, window=window, page_tokens=PT
        )
        for a, b in zip(
            _spans(win, self.COUNTS, starts),
            _spans(band, self.COUNTS, starts),
        ):
            np.testing.assert_array_equal(a, b)

    def test_engine_threshold0_bitwise_windowed_vs_banded(self):
        """Cross-route at the engine: a threshold-0 mixed dispatch lands
        on bitwise the same row spans under both attentions, at the same
        iteration count, for every iteration budget."""
        params = init_glom(jax.random.PRNGKey(0), CFG)
        ew = InferenceEngine(CFG, SCFG, params=params, name="w")
        eb = InferenceEngine(
            CFG,
            dataclasses.replace(SCFG, ragged_attention="banded"),
            params=params,
            name="b",
        )
        rng = np.random.default_rng(11)
        counts = [16, 4]
        row_start, row_len, T, starts = _layout(
            counts, pages_sig=ew.pick_pages(5)
        )
        flat = np.zeros((T, CFG.patch_dim), np.float32)
        for c, s in zip(counts, starts):
            flat[s:s + c] = rng.normal(size=(c, CFG.patch_dim))
        for budget in (1, 3, 6):
            rw = ew.infer_ragged(flat, counts, iters_override=budget)
            rb = eb.infer_ragged(flat, counts, iters_override=budget)
            assert rw.iters_run == rb.iters_run
            for a, b in zip(
                _spans(rw.levels, counts, starts),
                _spans(rb.levels, counts, starts),
            ):
                np.testing.assert_array_equal(a, b)

    def test_banded_full_res_row_bitwise_equals_dense_cold(self):
        """The banded route keeps the windowed route's cross-route lock:
        a full-resolution banded ragged row reproduces the dense
        engine's cold dispatch bitwise."""
        params = init_glom(jax.random.PRNGKey(0), CFG)
        eb = InferenceEngine(
            CFG,
            dataclasses.replace(SCFG, ragged_attention="banded"),
            params=params,
            name="b",
        )
        ed = InferenceEngine(
            CFG,
            dataclasses.replace(SCFG, ragged=False, page_pool_pages=0),
            params=params,
            name="d",
        )
        rng = np.random.default_rng(12)
        img = (100.0 * rng.normal(size=(3, 16, 16))).astype(np.float32)
        from glom_tpu.serve.batcher import _patchify_host

        row = _patchify_host(img, 4)
        T = eb.pick_pages(4) * PT
        flat = np.zeros((T, CFG.patch_dim), np.float32)
        flat[:16] = row
        ragged = eb.infer_ragged(flat, [16])
        dense = ed.infer(img[None], n_valid=1)
        assert ragged.iters_run == dense.iters_run
        np.testing.assert_array_equal(
            np.asarray(dense.levels[0]), np.asarray(ragged.levels)[0:16]
        )

    def test_pad_poisoning_invariance(self):
        """Garbage in intra-row pad slots and unused trailing pages must
        not move any row span — the banded mask is airtight."""
        row_start, row_len, T, starts = _layout(self.COUNTS, pages_sig=10)
        lv = np.asarray(self._levels(T))
        rs, rl = jnp.asarray(row_start), jnp.asarray(row_len)
        window = pages_for_tokens(max(self.COUNTS), PT) * PT
        clean = banded_ragged_consensus_attention(
            jnp.asarray(lv), row_start=rs, row_len=rl, window=window,
            page_tokens=PT,
        )
        dirty = lv.copy()
        valid = np.zeros((T,), bool)
        for c, s in zip(self.COUNTS, starts):
            valid[s:s + c] = True
        dirty[~valid] = 1e30  # poison pads AND unused trailing pages
        poisoned = banded_ragged_consensus_attention(
            jnp.asarray(dirty), row_start=rs, row_len=rl, window=window,
            page_tokens=PT,
        )
        for c, s in zip(self.COUNTS, starts):
            # VALID tokens only: intra-row pad slots were themselves
            # poisoned (their q changed), but no valid token may see it.
            np.testing.assert_array_equal(
                np.asarray(clean)[s:s + c], np.asarray(poisoned)[s:s + c]
            )

    def test_pallas_interpret_matches_jnp_banded(self):
        """The fused kernel's tolerance contract: interpret-mode Pallas
        vs the jnp banded reference (online softmax reorders the
        reduction — close, not bitwise)."""
        from glom_tpu.kernels import banded_ragged_consensus

        row_start, row_len, T, starts = _layout(self.COUNTS)
        lv = self._levels(T, seed=9)
        window = pages_for_tokens(max(self.COUNTS), PT) * PT
        rs, rl = jnp.asarray(row_start), jnp.asarray(row_len)
        ref = banded_ragged_consensus_attention(
            lv, row_start=rs, row_len=rl, window=window, page_tokens=PT
        )
        fused = banded_ragged_consensus(
            lv, row_start=rs, row_len=rl, window=window, page_tokens=PT,
            interpret=True,
        )
        for a, b in zip(
            _spans(ref, self.COUNTS, starts),
            _spans(fused, self.COUNTS, starts),
        ):
            np.testing.assert_allclose(a, b, rtol=2e-6, atol=2e-6)

    def test_window_bytes_banded_is_page_tokens_fold_smaller(self):
        """The number the --banded-ab gate prices: the banded working
        set is exactly page_tokens-fold below the windowed one."""
        w = ragged_window_bytes(64, 16, 3, 32, 4, PT, attention="windowed")
        b = ragged_window_bytes(64, 16, 3, 32, 4, PT, attention="banded")
        assert w == b * PT
        with pytest.raises(ValueError):
            ragged_window_bytes(64, 16, 3, 32, 4, PT, attention="dense")


class TestPoolAliasing:
    def _pool(self, **over):
        scfg = dataclasses.replace(SCFG, pool_aliasing=True, **over)
        return PagedColumnPool(CFG, scfg, name="t")

    def _row(self, n=16, seed=0):
        rng = np.random.default_rng(seed)
        return jnp.asarray(
            rng.normal(size=(n, CFG.levels, CFG.dim)).astype(np.float32)
        )

    def test_alias_write_bumps_epoch_and_moves_page_bytes(self):
        pool = self._pool()
        assert pool.write_back("sA", self._row(), 16)
        assert pool.epoch() == 1
        rec = pool.record()
        assert rec["alias"]["n_alias_writes"] == 1
        assert rec["alias"]["n_alias_fallbacks"] == 0
        assert rec["alias"]["alias_bytes_moved"] == 4 * pool.page_bytes
        assert rec["cow_bytes_moved"] == 0
        assert rec["alias"]["alias_rate"] == 1.0

    def test_pinned_read_forces_loud_cow_fallback(self):
        """The serialization seam itself: a dispatch holding a read pin
        forces the concurrent write-back onto copy-on-write (the pinned
        buffer stays valid), epoch does NOT advance (same logical
        contents, old identity preserved), and the fallback is stamped."""
        pool = self._pool()
        pinned = pool.acquire_read()
        assert pool.read_pins() == 1
        assert pool.write_back("sA", self._row(seed=1), 16)
        rec = pool.record()
        assert rec["alias"]["n_alias_fallbacks"] == 1
        assert rec["alias"]["n_alias_writes"] == 0
        assert pool.epoch() == 0
        assert rec["cow_bytes_moved"] == pool.pool_bytes
        # The pinned buffer survived the write — still all zeros.
        assert not np.asarray(pinned).any()
        pool.release_read()
        # Pin gone: the next write aliases again.
        assert pool.write_back("sA", self._row(seed=2), 16)
        assert pool.epoch() == 1
        assert pool.record()["alias"]["alias_rate"] == 0.5

    def test_read_pin_discipline_is_loud(self):
        pool = self._pool()
        with pytest.raises(RuntimeError, match="release_read"):
            pool.release_read()
        pool.release()
        with pytest.raises(RuntimeError, match="released"):
            pool.acquire_read()

    def test_aliasing_off_is_byte_for_byte_unchanged(self):
        """The acceptance lock: the same write/read sequence through an
        aliasing pool and a CoW pool lands on identical bytes; the CoW
        pool's record carries no alias block."""
        on = self._pool()
        off = PagedColumnPool(CFG, SCFG, name="t0")
        for seed, sid in ((3, "sA"), (4, "sB"), (5, "sA")):
            row = self._row(seed=seed)
            assert on.write_back(sid, row, 16)
            assert off.write_back(sid, row, 16)
        for sid in ("sA", "sB"):
            np.testing.assert_array_equal(
                on.read_block(sid), off.read_block(sid)
            )
        rec = off.record()
        assert "alias" not in rec
        assert rec["cow_bytes_moved"] == 3 * off.pool_bytes
        assert on.record()["cow_bytes_moved"] == 0

    def test_conservation_under_churn_with_aliasing(self):
        """The pool conservation invariant survives aliased churn with
        interleaved read pins (pins only steer writes onto the CoW
        fallback — they never leak pages or double-free)."""
        pool = self._pool()
        rng = np.random.default_rng(6)
        pins = 0
        for step in range(120):
            op = rng.integers(0, 4)
            sid = f"s{rng.integers(0, 6)}"
            if op == 0:
                pool.write_back(sid, self._row(seed=step), 16)
            elif op == 1:
                pool.free(sid)
            elif op == 2 and pins < 2:
                pool.acquire_read()
                pins += 1
            elif op == 3 and pins > 0:
                pool.release_read()
                pins -= 1
            rec = pool.record()
            assert (
                rec["pages_used"] + rec["pages_free"] == rec["pages_total"]
            )
        rec = pool.record()
        writes = (
            rec["alias"]["n_alias_writes"] + rec["alias"]["n_alias_fallbacks"]
        )
        assert writes == rec["n_writebacks"]
        assert (
            rec["alias"]["alias_bytes_moved"] + rec["cow_bytes_moved"]
            == rec["alias"]["n_alias_writes"] * 4 * pool.page_bytes
            + rec["alias"]["n_alias_fallbacks"] * pool.pool_bytes
        )

    def test_shared_base_refcount_isolation_under_aliasing(self):
        """Delta-mode shared bases stay isolated when writes alias: a
        second stream aliasing the same content-hashed base, then
        appending its own delta, must not move the first stream's
        reconstruction by a single bit."""
        pool = self._pool(
            delta_streaming=True, ragged=False, delta_page_atol=0.0
        )
        base_row = self._row(seed=7)
        h = "hash-base"
        assert pool.write_back_stream("sA", base_row, 16, content_hash=h)
        assert pool.write_back_stream("sB", base_row, 16, content_hash=h)
        assert pool.base_refs("sA") == 2  # shared, refcounted
        before_a = np.array(pool.read_block("sA"))
        # sB diverges: its delta pages are fresh allocations, scattered
        # in place (aliased) — never into the shared base's pages.
        drift = np.asarray(base_row).copy()
        drift[5] += 1.0
        assert pool.write_back_stream("sB", jnp.asarray(drift), 16)
        np.testing.assert_array_equal(pool.read_block("sA"), before_a)
        np.testing.assert_array_equal(
            pool.read_block("sB"),
            np.asarray(drift, dtype=np.asarray(before_a).dtype),
        )
        assert pool.record()["alias"]["n_alias_writes"] >= 2

    def test_alias_events_are_stamped(self):
        """page_alias / alias_fallback events ride the pool's writer
        with the engine stamp — the observability the A/B gate and
        `telemetry compare` read."""

        class Sink:
            def __init__(self):
                self.records = []

            def write(self, rec):
                self.records.append(rec)

        sink = Sink()
        scfg = dataclasses.replace(SCFG, pool_aliasing=True)
        pool = PagedColumnPool(CFG, scfg, writer=sink, name="e9")
        pool.write_back("sA", self._row(seed=8), 16)
        pinned = pool.acquire_read()
        pool.write_back("sA", self._row(seed=9), 16)
        pool.release_read()
        del pinned
        ev = [r.get("event") for r in sink.records]
        assert "page_alias" in ev and "alias_fallback" in ev
        alias = next(r for r in sink.records if r["event"] == "page_alias")
        assert alias["engine"] == "e9"
        assert alias["n_pages"] == 4 and alias["epoch"] == 1
        assert alias["bytes_moved"] == 4 * pool.page_bytes
        fb = next(r for r in sink.records if r["event"] == "alias_fallback")
        assert fb["read_pins"] == 1
        assert fb["bytes_moved"] == pool.pool_bytes
