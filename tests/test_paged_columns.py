"""Paged column memory: pool conservation, ragged bitwise parity, the
zero-transfer warm path, and session-affinity routing (ISSUE 11).

The parity locks are the contract the ragged route ships under:

  * threshold-0 ragged dispatch is BITWISE the per-row lone dispatches
    it replaced (the PR 8 fold-parity pattern on the page axis);
  * a full-resolution ragged row is BITWISE the dense engine's cold
    dispatch (same embed, same update ops, same reductions — the
    row-windowed consensus gather reproduces the dense attention
    layout exactly);
  * the paged warm path is BITWISE the host-levels0 warm path while
    moving ZERO levels0 bytes host->device (the acceptance counter).

Pool/cache tests are host-side accounting: pages_used + pages_free ==
pages_total through arbitrary alloc/free/evict/invalidate churn, pinned
blocks survive eviction pressure, and the TTL sweep reclaims dead
sessions' pages under pressure without a lookup ever touching the key.
"""

import dataclasses
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from glom_tpu.models.core import init_glom
from glom_tpu.ops.patch import patchify
from glom_tpu.serve.batcher import DynamicBatcher, _patchify_host
from glom_tpu.serve.column_cache import ColumnCache, PageHit
from glom_tpu.serve.engine import InferenceEngine
from glom_tpu.serve.paged_columns import (
    PagedColumnPool,
    page_state_bytes,
    pages_for_tokens,
    resolve_page_tokens,
)
from glom_tpu.utils.config import GlomConfig, ServeConfig

CFG = GlomConfig(dim=32, levels=3, image_size=16, patch_size=4)  # n=16
SCFG = ServeConfig(
    buckets=(1, 2, 4), max_batch=4, max_delay_ms=2.0,
    iters="auto", max_auto_iters=6, exit_threshold=0.0,
    page_pool_pages=32, page_tokens=4, ragged=True,
    dispatch_retries=0,
)


def _imgs(rng, n=1, hw=16):
    return (100.0 * rng.normal(size=(n, CFG.channels, hw, hw))).astype(
        np.float32
    )


def _flat(rows, pt=4, pages_sig=None):
    """Pack host-patchified rows page-aligned (the batcher's layout)."""
    counts = [r.shape[0] for r in rows]
    need = sum(pages_for_tokens(c, pt) for c in counts)
    P = pages_sig if pages_sig is not None else need
    flat = np.zeros((P * pt, rows[0].shape[1]), np.float32)
    off = 0
    for r, c in zip(rows, counts):
        flat[off:off + c] = r
        off += pages_for_tokens(c, pt) * pt
    return flat, counts


@pytest.fixture(scope="module")
def engine():
    return InferenceEngine(CFG, SCFG, key=jax.random.PRNGKey(0))


class TestPageTokens:
    def test_explicit_must_divide(self):
        with pytest.raises(ValueError, match="does not divide"):
            resolve_page_tokens(
                CFG, dataclasses.replace(SCFG, page_tokens=5)
            )

    def test_auto_resolves_quarter_row(self):
        # n=16 -> 4-token pages (four pages per full row); flagship
        # n=256 -> 64-token pages (the cap).
        assert resolve_page_tokens(
            CFG, dataclasses.replace(SCFG, page_tokens=0)
        ) == 4
        big = GlomConfig(dim=32, levels=3, image_size=224, patch_size=14)
        assert resolve_page_tokens(
            big, dataclasses.replace(SCFG, page_tokens=0)
        ) == 64

    def test_pages_for_tokens(self):
        assert pages_for_tokens(16, 4) == 4
        assert pages_for_tokens(9, 4) == 3
        assert pages_for_tokens(1, 4) == 1
        with pytest.raises(ValueError):
            pages_for_tokens(0, 4)


class TestPoolConservation:
    def _pool(self):
        return PagedColumnPool(CFG, SCFG, name="t")

    def _check(self, pool):
        rec = pool.record()
        assert rec["pages_used"] + rec["pages_free"] == rec["pages_total"]
        assert rec["bytes_in_use"] == rec["pages_used"] * rec["page_bytes"]

    def test_alloc_free_churn_conserves(self):
        pool = self._pool()
        rng = np.random.default_rng(3)
        live = set()
        for step in range(200):
            op = rng.integers(0, 3)
            sid = f"s{rng.integers(0, 12)}"
            if op == 0:
                n = int(rng.integers(1, 17))
                pages = pool.alloc(sid, n)
                if pages is not None:
                    live.add(sid)
                    assert len(pages) == pages_for_tokens(n, 4)
                    assert len(set(pages)) == len(pages)
            elif op == 1:
                pool.free(sid)
                live.discard(sid)
            else:
                self._check(pool)
        self._check(pool)
        rec = pool.record()
        assert rec["n_sessions"] == len(live)
        pool.free_all()
        self._check(pool)
        assert pool.record()["pages_used"] == 0

    def test_alloc_fails_loudly_when_full(self):
        pool = self._pool()
        for i in range(8):  # 8 x 4 pages = the whole 32-page pool
            assert pool.alloc(f"s{i}", 16) is not None
        assert pool.alloc("overflow", 16) is None
        assert pool.record()["n_alloc_fails"] == 1
        self._check(pool)
        pool.free("s3")
        assert pool.alloc("overflow", 16) is not None
        self._check(pool)

    def test_same_size_realloc_reuses_pages(self):
        pool = self._pool()
        first = pool.alloc("s", 9)
        again = pool.alloc("s", 9)
        assert first == again
        resized = pool.alloc("s", 16)
        assert len(resized) == 4
        self._check(pool)

    def test_defrag_compacts_and_preserves_contents(self):
        pool = self._pool()
        lv = {}
        for i in range(4):
            n = 8
            arr = np.random.default_rng(i).normal(
                size=(n, CFG.levels, CFG.dim)
            ).astype(np.float32)
            assert pool.write_back(f"s{i}", jnp.asarray(arr), n)
            lv[f"s{i}"] = arr
        pool.free("s0")
        pool.free("s2")
        moved = pool.defrag()
        assert moved > 0
        self._check(pool)
        used_pages = sorted(
            p for sid in ("s1", "s3") for p in pool.lookup(sid)[0]
        )
        assert used_pages == list(range(len(used_pages)))  # compacted low
        for sid in ("s1", "s3"):
            np.testing.assert_array_equal(pool.read_block(sid), lv[sid])

    def test_pin_protects_free_force_overrides(self):
        pool = self._pool()
        pool.alloc("s", 16)
        pool.lookup("s", pin=True)
        assert pool.is_pinned("s")
        # free() is the force path (invalidation): it drops even pinned.
        assert pool.free("s") == 4
        self._check(pool)

    def test_write_back_read_block_roundtrip(self):
        pool = self._pool()
        arr = np.random.default_rng(0).normal(
            size=(9, CFG.levels, CFG.dim)
        ).astype(np.float32)
        assert pool.write_back("s", jnp.asarray(arr), 9)
        np.testing.assert_array_equal(pool.read_block("s"), arr)
        assert pool.lookup("s")[1] == 9
        assert len(pool.lookup("s")[0]) == 3  # ceil(9/4) pages


class TestRaggedParity:
    def test_threshold0_mixed_bitwise_equals_lone_dispatches(self, engine):
        """THE ragged contract: one mixed dispatch == the per-row lone
        dispatches it replaced, bit for bit, at threshold 0."""
        rng = np.random.default_rng(7)
        big = _imgs(rng)[0]
        small = _imgs(rng, hw=8)[0]
        rows = [
            _patchify_host(big, 4),
            _patchify_host(small, 4),
        ]
        flat, counts = _flat(rows, pages_sig=engine.pick_pages(5))
        mixed = engine.infer_ragged(flat, counts)
        assert mixed.iters_run == 6  # threshold 0: the full budget
        lone_a = engine.infer_ragged(
            *_flat([rows[0]], pages_sig=engine.pick_pages(4))
        )
        lone_b = engine.infer_ragged(
            *_flat([rows[1]], pages_sig=engine.pick_pages(1))
        )
        m = np.asarray(mixed.levels)
        np.testing.assert_array_equal(m[0:16], np.asarray(lone_a.levels)[0:16])
        np.testing.assert_array_equal(
            m[16:20], np.asarray(lone_b.levels)[0:4]
        )

    def test_full_res_ragged_bitwise_equals_dense_cold(self, engine):
        """Cross-route: a full-resolution ragged row reproduces the
        dense engine's cold dispatch bitwise (same embed, same update
        ops, W == n so even the softmax axis length matches)."""
        rng = np.random.default_rng(8)
        img = _imgs(rng)[0]
        dense = engine.infer(img[None], n_valid=1)
        ragged = engine.infer_ragged(
            *_flat([_patchify_host(img, 4)], pages_sig=4)
        )
        np.testing.assert_array_equal(
            np.asarray(dense.levels[0]), np.asarray(ragged.levels)[0:16]
        )
        assert ragged.levels0_h2d_bytes == 0

    def test_pad_positions_never_vote(self, engine):
        """Garbage in the page-tail pad positions must not change any
        row's output: pads are masked out of attention, the witness,
        and the quorum."""
        rng = np.random.default_rng(9)
        small = _imgs(rng, hw=8)[0]
        flat, counts = _flat(
            [_patchify_host(small, 4)], pages_sig=engine.pick_pages(1)
        )
        clean = engine.infer_ragged(flat, counts)
        dirty = flat.copy()
        dirty[counts[0]:] = 1e6  # page tail: pad positions
        poisoned = engine.infer_ragged(dirty, counts)
        np.testing.assert_array_equal(
            np.asarray(clean.levels)[: counts[0]],
            np.asarray(poisoned.levels)[: counts[0]],
        )

    def test_host_patchify_matches_einops(self):
        rng = np.random.default_rng(10)
        img = _imgs(rng)[0]
        ref = np.asarray(patchify(jnp.asarray(img)[None], 4))[0]
        np.testing.assert_array_equal(_patchify_host(img, 4), ref)


class TestPagedWarmPath:
    def test_paged_bitwise_equals_host_warm_and_moves_zero_bytes(self):
        """The tentpole claim in one test: page-warm == host-warm
        bitwise, with levels0_h2d_bytes 0 vs > 0."""
        scfg = dataclasses.replace(SCFG, ragged=False)
        eng = InferenceEngine(CFG, scfg, key=jax.random.PRNGKey(1))
        rng = np.random.default_rng(11)
        imgs = _imgs(rng, n=2)
        cold = eng.infer(imgs, n_valid=2)
        assert cold.levels0_h2d_bytes == 0
        eng.pool.write_back("s", cold.levels[0], CFG.num_patches)
        pages = eng.pool.lookup("s")[0]
        prow = np.full((2, 4), -1, np.int32)
        prow[0] = pages
        paged = eng.infer(imgs, n_valid=2, page_rows=prow)
        lv0 = np.zeros((2, CFG.num_patches, CFG.levels, CFG.dim), np.float32)
        lv0[0] = np.asarray(cold.levels[0])
        lv0[1] = eng.cold_levels()
        host = eng.infer(imgs, n_valid=2, levels0=lv0)
        np.testing.assert_array_equal(
            np.asarray(paged.levels), np.asarray(host.levels)
        )
        assert paged.levels0_h2d_bytes == 0
        assert host.levels0_h2d_bytes == lv0.nbytes
        assert eng.levels0_h2d_bytes_total == lv0.nbytes
        # Cold rows of the paged dispatch are bitwise the plain cold
        # route (page_idx -1 takes the forward's own init).
        np.testing.assert_array_equal(
            np.asarray(paged.levels[1]), np.asarray(cold.levels[1])
        )


class TestPagesCache:
    def _setup(self, budget_pages=8, ttl=None):
        pool = PagedColumnPool(
            CFG, dataclasses.replace(SCFG, page_pool_pages=budget_pages),
            name="e0",
        )
        clock = [0.0]
        cache = ColumnCache(
            budget_pages * pool.page_bytes,
            pools={"e0": pool},
            ttl_s=ttl,
            clock=lambda: clock[0],
        )
        return pool, cache, clock

    def _state(self, n=16):
        return jnp.asarray(
            np.random.default_rng(0).normal(
                size=(n, CFG.levels, CFG.dim)
            ).astype(np.float32)
        )

    def test_store_lookup_returns_page_hit(self):
        pool, cache, _ = self._setup()
        assert cache.store("sA", self._state(), engine="e0", n_tokens=16)
        hit = cache.lookup("sA")
        assert isinstance(hit, PageHit)
        assert hit.engine == "e0" and hit.n_tokens == 16
        assert len(hit.pages) == 4
        assert cache.engine_of("sA") == "e0"
        assert pool.record()["pages_used"] == 4

    def test_lru_eviction_frees_pages(self):
        pool, cache, _ = self._setup(budget_pages=8)
        cache.store("sA", self._state(), engine="e0", n_tokens=16)
        cache.store("sB", self._state(), engine="e0", n_tokens=16)
        # Pool (and budget) hold exactly two: the third evicts LRU sA.
        cache.store("sC", self._state(), engine="e0", n_tokens=16)
        assert cache.lookup("sA") is None
        assert isinstance(cache.lookup("sC"), PageHit)
        assert pool.record()["pages_used"] == 8
        assert cache.n_evictions == 1

    def test_pinned_block_survives_eviction_pressure(self):
        pool, cache, _ = self._setup(budget_pages=8)
        cache.store("sA", self._state(), engine="e0", n_tokens=16)
        cache.store("sB", self._state(), engine="e0", n_tokens=16)
        hit = cache.lookup("sA", pin=True)  # in-flight dispatch
        assert isinstance(hit, PageHit)
        cache.store("sC", self._state(), engine="e0", n_tokens=16)
        # sA was LRU but pinned: sB pays instead.
        assert pool.holds("sA") and not pool.holds("sB")
        cache.unpin("sA")
        assert not pool.is_pinned("sA")

    def test_ttl_expiry_at_lookup_frees_pages(self):
        pool, cache, clock = self._setup(ttl=10.0)
        cache.store("sA", self._state(), engine="e0", n_tokens=16)
        clock[0] = 11.0
        assert cache.lookup("sA") is None
        assert cache.n_expirations == 1
        assert pool.record()["pages_used"] == 0

    def test_pressure_sweep_reclaims_expired_without_lookup(self):
        """The TTL-at-lookup-only leak (ISSUE 11 satellite): a dead
        session's pages stay pinned until someone touches the key —
        eviction pressure now sweeps expired entries FIRST, before any
        live LRU victim pays."""
        pool, cache, clock = self._setup(budget_pages=8, ttl=10.0)
        cache.store("dead", self._state(), engine="e0", n_tokens=16)
        clock[0] = 5.0
        cache.store("live", self._state(), engine="e0", n_tokens=16)
        clock[0] = 12.0  # "dead" expired, never looked up again
        cache.store("new", self._state(), engine="e0", n_tokens=16)
        # The sweep reclaimed "dead"; "live" survived the pressure.
        assert cache.n_expirations == 1 and cache.n_evictions == 0
        assert isinstance(cache.lookup("live"), PageHit)
        assert cache.lookup("dead") is None

    def test_invalidate_engine_frees_pool_pages(self):
        pool, cache, _ = self._setup()
        cache.store("sA", self._state(), engine="e0", n_tokens=16)
        assert cache.invalidate_engine("e0") == 1
        assert pool.record()["pages_used"] == 0
        assert cache.lookup("sA") is None

    def test_host_mode_pressure_sweep(self):
        """The sweep satellite applies to the PR 8 host-array cache too
        (same leak, same fix)."""
        clock = [0.0]
        entry = np.zeros((16, CFG.levels, CFG.dim), np.float32)
        cache = ColumnCache(
            2 * entry.nbytes, ttl_s=10.0, clock=lambda: clock[0]
        )
        cache.store("dead", entry, engine="e0")
        clock[0] = 5.0
        cache.store("live", entry, engine="e0")
        clock[0] = 12.0
        cache.store("new", entry, engine="e0")
        assert cache.n_expirations == 1 and cache.n_evictions == 0
        assert cache.lookup("live") is not None


@pytest.mark.slow
class TestRaggedBatcher:
    def _engines(self, n=1, **over):
        scfg = dataclasses.replace(SCFG, **over) if over else SCFG
        params = init_glom(jax.random.PRNGKey(0), CFG)
        return [
            InferenceEngine(CFG, scfg, params=params, name=f"e{i}")
            for i in range(n)
        ]

    def test_mixed_resolution_batch_resolves_correct_shapes(self):
        engines = self._engines()
        rng = np.random.default_rng(12)
        big = _imgs(rng)[0]
        small = _imgs(rng, hw=8)[0]
        with DynamicBatcher(engines=engines) as b:
            ta = b.submit(big)
            tb = b.submit(small)
            lv_a, _, _ = ta.result(timeout=120)
            lv_b, _, _ = tb.result(timeout=120)
            s = b.summary_record()
        assert lv_a.shape == (16, CFG.levels, CFG.dim)
        assert lv_b.shape == (4, CFG.levels, CFG.dim)
        assert s["n_served"] == 2
        assert s["pad_fraction_mean"] > 0  # page-tail round-up, stamped
        assert s["levels0_h2d_bytes"] == 0
        assert s["page_pools"]["e0"]["pages_total"] == 32

    def test_batcher_ragged_threshold0_bitwise_vs_lone(self):
        """Fold-parity through the REAL batcher: the rows of one ragged
        batcher dispatch equal the engine's lone ragged dispatches."""
        engines = self._engines()
        eng = engines[0]
        rng = np.random.default_rng(13)
        big = _imgs(rng)[0]
        small = _imgs(rng, hw=8)[0]
        b = DynamicBatcher(engines=engines)
        ta = b.submit(big)
        tb = b.submit(small)
        b.start()  # both queued before the worker runs: ONE dispatch
        lv_a, iters_a, _ = ta.result(timeout=120)
        lv_b, iters_b, _ = tb.result(timeout=120)
        b.stop()
        lone_a = eng.infer_ragged(
            *_flat([_patchify_host(big, 4)], pages_sig=eng.pick_pages(4))
        )
        lone_b = eng.infer_ragged(
            *_flat([_patchify_host(small, 4)], pages_sig=eng.pick_pages(1))
        )
        np.testing.assert_array_equal(
            np.asarray(lv_a), np.asarray(lone_a.levels)[0:16]
        )
        np.testing.assert_array_equal(
            np.asarray(lv_b), np.asarray(lone_b.levels)[0:4]
        )
        assert iters_a == lone_a.iters_run == 6  # threshold 0: budget

    def test_session_affinity_routes_to_page_holder(self):
        engines = self._engines(
            n=2, exit_threshold=1e-3, column_cache_bytes=1 << 20
        )
        rng = np.random.default_rng(14)
        base = _imgs(rng)[0]
        with DynamicBatcher(engines=engines) as b:
            b.submit(base, session_id="sA").result(timeout=120)
            holder = b.cache.engine_of("sA")
            assert holder in ("e0", "e1")
            frame2 = base + 0.05 * rng.normal(size=base.shape).astype(
                np.float32
            )
            _, iters2, _ = b.submit(frame2, session_id="sA").result(
                timeout=120
            )
            s = b.summary_record()
        assert s["n_affinity"] >= 1
        assert s["n_page_warm"] >= 1
        assert s["levels0_h2d_bytes"] == 0
        assert iters2 < 6  # warm start exited early

    def test_affinity_falls_back_on_engine_death(self):
        """Session-affinity routing falls back cleanly when the page
        holder dies: pages freed, stream re-served cold on the sibling,
        every ticket terminal."""
        fail = {"e0": False}

        def hook(ctx):
            if fail["e0"]:
                raise RuntimeError("injected engine fault")

        scfg = dataclasses.replace(
            SCFG, exit_threshold=1e-3, column_cache_bytes=1 << 20
        )
        params = init_glom(jax.random.PRNGKey(0), CFG)
        e0 = InferenceEngine(
            CFG, scfg, params=params, name="e0", fault_hook=hook
        )
        e1 = InferenceEngine(CFG, scfg, params=params, name="e1")
        rng = np.random.default_rng(15)
        base = _imgs(rng)[0]
        with DynamicBatcher(
            engines=[e0, e1], engine_fail_threshold=1
        ) as b:
            # Warm sA wherever it lands; force it onto e0 by serving
            # until e0 holds it (2 workers race; retry with new streams).
            sid = None
            for k in range(8):
                cand = f"s{k}"
                b.submit(base, session_id=cand).result(timeout=120)
                if b.cache.engine_of(cand) == "e0":
                    sid = cand
                    break
            assert sid is not None, "no stream landed on e0"
            fail["e0"] = True  # e0 now fails every dispatch
            frame2 = base + 0.05 * rng.normal(size=base.shape).astype(
                np.float32
            )
            lv, iters, _ = b.submit(frame2, session_id=sid).result(
                timeout=120
            )
            assert lv.shape[0] == CFG.num_patches
            s = b.summary_record()
        assert s["engines"]["e0"]["alive"] is False
        assert e0.pool.record()["pages_used"] == 0  # death freed pages
        # Every ticket terminal, nothing lost: conservation holds across
        # the failover (the re-served frame ran cold on the sibling).
        assert s["n_failed"] == 0
        assert s["n_requests"] == s["n_served"] + s["n_shed"] + s["n_failed"]


def test_ragged_continuations_need_auto_route():
    """Ragged COMPOSES with the continuation queue now (ISSUE 16) — but
    only on the auto route: a fixed iteration count has no witness, so
    there are no stragglers to re-enter."""
    ServeConfig(iters="auto", ragged=True, max_continuations=2)
    with pytest.raises(ValueError, match="auto"):
        ServeConfig(iters=4, ragged=True, max_continuations=2)


@pytest.mark.slow
class TestRaggedContinuation:
    def test_ragged_straggler_bitwise_parity_and_iter_conservation(self):
        """Ragged x continuation composition (ISSUE 16): a ragged
        straggler exited at the quorum re-enters the RAGGED route as a
        row carrying its mid-flight columns and remaining budget, and
        lands on BITWISE the same final columns, after the same TOTAL
        iteration count, as its lone ragged run to convergence (the
        dense two-tier correctness lock, on the page axis)."""
        rng = np.random.default_rng(21)
        # Seeded convergence disparity: the 10x rows settle by iter 10,
        # the 1x row needs 12 — so the 0.5 quorum exits the cold
        # dispatch with the 1x row mid-flight.
        easy = [
            (10.0 * rng.normal(size=(CFG.channels, 16, 16))).astype(
                np.float32
            )
            for _ in range(2)
        ]
        hard = rng.normal(size=(CFG.channels, 16, 16)).astype(np.float32)
        scfg = dataclasses.replace(
            SCFG, exit_threshold=1e-3, max_auto_iters=16,
            exit_quorum=0.5, max_continuations=3,
        )
        params = init_glom(jax.random.PRNGKey(0), CFG)
        eng = InferenceEngine(CFG, scfg, params=params, name="e0")
        b = DynamicBatcher(engines=[eng])
        tickets = [b.submit(easy[0]), b.submit(hard), b.submit(easy[1])]
        b.start()  # all queued before the worker runs: ONE cold dispatch
        outs = [t.result(timeout=300.0) for t in tickets]
        summary = b.summary_record()
        b.stop()
        assert summary["n_served"] == 3 and summary["n_failed"] == 0
        assert summary["n_continued"] >= 1  # the hard row re-entered
        # Reference: the hard row alone on the ragged route, run to its
        # own convergence in ONE dispatch (a quorum of one row is the
        # row itself).
        ref_eng = InferenceEngine(
            CFG,
            dataclasses.replace(
                scfg, exit_quorum=1.0, max_continuations=0
            ),
            params=params,
        )
        ref = ref_eng.infer_ragged(
            *_flat(
                [_patchify_host(hard, 4)], pages_sig=ref_eng.pick_pages(4)
            )
        )
        levels, total_iters, _ = outs[1]
        assert total_iters == ref.iters_run
        np.testing.assert_array_equal(
            levels, np.asarray(ref.levels)[0:16]
        )


def test_ragged_ladder_must_hold_a_full_row():
    """A ragged_pages ladder below one full-resolution row's page count
    would turn every full-size request into a dispatch-time failure
    that reads as an engine fault — rejected at construction."""
    scfg = dataclasses.replace(SCFG, ragged_pages=(2,))
    with pytest.raises(ValueError, match="full-resolution row"):
        InferenceEngine(CFG, scfg, key=jax.random.PRNGKey(0))


def test_mixed_pool_fleet_rejected():
    """Pages mode must cover the whole fleet: a pool-less engine next to
    pooled siblings would receive PageHits its host path cannot use —
    a loud constructor error, never a mid-traffic worker crash."""
    scfg = dataclasses.replace(
        SCFG, ragged=False, column_cache_bytes=1 << 20
    )
    pooled = InferenceEngine(CFG, scfg, key=jax.random.PRNGKey(0), name="e0")
    plain = InferenceEngine(
        CFG, dataclasses.replace(scfg, page_pool_pages=0),
        key=jax.random.PRNGKey(0), name="e1",
    )
    with pytest.raises(ValueError, match="no page pool"):
        DynamicBatcher(engines=[pooled, plain])


def test_page_state_bytes_live_form():
    assert page_state_bytes(CFG, SCFG, 4) == 4 * CFG.levels * CFG.dim * 4
    bf16 = dataclasses.replace(SCFG, compute_dtype="bfloat16")
    assert page_state_bytes(CFG, bf16, 4) == 4 * CFG.levels * CFG.dim * 2
