"""Serving subsystem (glom_tpu/serve, docs/SERVING.md): engine AOT warmup
and bucket discipline, dynamic-batching admission policy (host-side, fake
engine — no device), consensus early-exit correctness.

The two acceptance locks:
  * threshold=0.0 -> iters="auto" output is BITWISE-identical to the
    fixed-iters forward (both jitted: the exit test `delta < 0` can never
    fire, and the while_loop body is the same update_step as the scan's);
  * a converged input (a long-settled state fed back in) exits in fewer
    than max_iters iterations.
"""

import time
import types

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from glom_tpu.models import Glom
from glom_tpu.models.core import glom_forward, init_glom
from glom_tpu.serve.batcher import (
    BackendDownError,
    DynamicBatcher,
    QueueFullError,
    ShedError,
)
from glom_tpu.serve.early_exit import (
    glom_forward_auto,
    masked_level_agreement,
)
from glom_tpu.serve.engine import InferenceEngine, ServeResult
from glom_tpu.telemetry import schema
from glom_tpu.utils.config import GlomConfig, ServeConfig

CFG = GlomConfig(dim=16, levels=3, image_size=8, patch_size=2)  # n=16, tiny
SCFG = ServeConfig(buckets=(1, 2, 4), max_batch=4, max_delay_ms=5.0)


@pytest.fixture(scope="module")
def params():
    return init_glom(jax.random.PRNGKey(1), CFG)


@pytest.fixture(scope="module")
def img():
    return jnp.asarray(
        np.random.default_rng(2).normal(size=(2, 3, 8, 8)), jnp.float32
    )


class Sink:
    def __init__(self):
        self.records = []

    def write(self, rec):
        self.records.append(rec)


# ---------------------------------------------------------------------------
# early exit
# ---------------------------------------------------------------------------


class TestEarlyExit:
    def test_threshold_zero_is_bitwise_fixed_iters(self, params, img):
        """The acceptance lock: exit disabled -> exactly max_iters updates,
        output bitwise-equal to the scanned fixed-iters forward."""
        fixed = jax.jit(
            lambda p, x: glom_forward(p, x, CFG, iters=6)
        )(params, img)
        auto, iters_run, _ = jax.jit(
            lambda p, x: glom_forward_auto(
                p, x, CFG, max_iters=6, threshold=0.0
            )
        )(params, img)
        assert int(iters_run) == 6
        assert np.array_equal(np.asarray(fixed), np.asarray(auto))

    def test_converged_input_exits_early(self, params, img):
        """A long-settled state fed back as the carry has a near-zero
        agreement delta: the loop must exit before the full budget."""
        settled = glom_forward(params, img, CFG, iters=40)
        _, iters_run, _ = jax.jit(
            lambda p, x, lv: glom_forward_auto(
                p, x, CFG, max_iters=12, threshold=1e-3, levels=lv
            )
        )(params, img, settled)
        assert int(iters_run) < 12

    @pytest.mark.slow  # one more while_loop compile; CI serve job runs it
    def test_min_iters_floors_the_exit(self, params, img):
        # A threshold so large every delta passes: exit lands exactly at
        # the floor, never below it.
        _, iters_run, _ = jax.jit(
            lambda p, x: glom_forward_auto(
                p, x, CFG, max_iters=8, threshold=1e9, min_iters=3
            )
        )(params, img)
        assert int(iters_run) == 3

    def test_masked_agreement_matches_unmasked_when_all_valid(
        self, params, img
    ):
        from glom_tpu.telemetry.diagnostics import level_agreement

        lv = glom_forward(params, img, CFG, iters=4)
        full = np.asarray(level_agreement(lv))
        np.testing.assert_allclose(
            np.asarray(masked_level_agreement(lv, None)), full, atol=1e-6
        )
        np.testing.assert_allclose(
            np.asarray(
                masked_level_agreement(lv, jnp.ones(lv.shape[0], bool))
            ),
            full,
            atol=1e-6,
        )

    @pytest.mark.slow  # one more while_loop compile; CI serve job runs it
    def test_pad_rows_do_not_vote_on_the_witness(self, params, img):
        """The serving contract: the SAME two valid rows must exit after
        the SAME number of iterations whatever garbage occupies the pad
        rows — the mask keeps the witness to real requests."""
        pad_a = jnp.concatenate([img, jnp.zeros_like(img)], axis=0)
        pad_b = jnp.concatenate([img, 100.0 * jnp.ones_like(img)], axis=0)
        mask = jnp.asarray([True, True, False, False])
        fn = jax.jit(
            lambda p, x, m: glom_forward_auto(
                p, x, CFG, max_iters=8, threshold=1e-2, valid_mask=m
            )
        )
        out_a, n_a, _ = fn(params, pad_a, mask)
        out_b, n_b, _ = fn(params, pad_b, mask)
        assert int(n_a) == int(n_b)
        assert np.array_equal(np.asarray(out_a[:2]), np.asarray(out_b[:2]))

    def test_validation(self, params, img):
        with pytest.raises(ValueError, match="max_iters"):
            glom_forward_auto(params, img, CFG, max_iters=0)
        with pytest.raises(ValueError, match="min_iters"):
            glom_forward_auto(params, img, CFG, max_iters=4, min_iters=5)
        with pytest.raises(ValueError, match="threshold"):
            glom_forward_auto(params, img, CFG, max_iters=4, threshold=-1.0)


class TestGlomAutoIters:
    def test_auto_matches_fixed_with_threshold_zero(self, img):
        """iters='auto' on the preserved API: exit disabled reproduces the
        fixed-iters call bitwise (both memoized jitted programs)."""
        model = Glom(
            dim=16, levels=3, image_size=8, patch_size=2, backend="cpu",
            exit_threshold=0.0, auto_max_iters=4,
        )
        fixed = model(img, iters=4)
        auto = model(img, iters="auto")
        assert np.array_equal(np.asarray(fixed), np.asarray(auto))
        assert int(model.last_auto_iters) == 4

    @pytest.mark.slow  # extra jit variant; CI serve job runs it
    def test_auto_early_exit_reports_count(self, img):
        model = Glom(
            dim=16, levels=3, image_size=8, patch_size=2, backend="cpu",
            exit_threshold=1e9, auto_max_iters=8, auto_min_iters=2,
        )
        model(img, iters="auto")
        assert int(model.last_auto_iters) == 2

    def test_auto_rejects_return_all(self, img):
        model = Glom(
            dim=16, levels=3, image_size=8, patch_size=2, backend="cpu"
        )
        with pytest.raises(ValueError, match="return_all"):
            model(img, iters="auto", return_all=True)


# ---------------------------------------------------------------------------
# engine
# ---------------------------------------------------------------------------


class TestInferenceEngine:
    @pytest.fixture(scope="class")
    def engine(self, params):
        return InferenceEngine(CFG, SCFG, params=params)

    def test_pick_bucket(self, engine):
        assert engine.pick_bucket(1) == 1
        assert engine.pick_bucket(2) == 2
        assert engine.pick_bucket(3) == 4
        assert engine.pick_bucket(4) == 4
        with pytest.raises(ValueError, match="exceeds"):
            engine.pick_bucket(5)
        with pytest.raises(ValueError, match=">= 1"):
            engine.pick_bucket(0)

    def test_warmup_precompiles_every_bucket(self, engine):
        sink = Sink()
        engine.writer = sink
        times = engine.warmup()
        assert set(times) == {1, 2, 4}
        assert all(
            engine.signature(b) in engine._compiled for b in SCFG.buckets
        )
        warm = [r for r in sink.records if r.get("event") == "warmup"]
        assert {r["bucket"] for r in warm} == {1, 2, 4}
        for r in warm:
            assert r["kind"] == "serve"
            assert schema.validate_record(r) == [], r
        # Re-warmup is free: everything is already compiled.
        assert all(v == 0.0 for v in engine.warmup().values())

    def test_infer_shapes_and_fixed_iters_stamp(self, engine):
        imgs = np.random.default_rng(0).normal(size=(4, 3, 8, 8))
        res = engine.infer(imgs, n_valid=3)
        assert isinstance(res, ServeResult)
        assert res.levels.shape == (4, 16, 3, 16)
        assert res.iters_run == CFG.default_iters  # fixed route stamp
        assert res.bucket == 4 and res.latency_s > 0

    def test_pad_rows_never_reach_valid_outputs(self, engine, params):
        """Rows are independent through the forward: the valid rows of a
        padded bucket equal the same images served alone."""
        rng = np.random.default_rng(3)
        two = rng.normal(size=(2, 3, 8, 8)).astype(np.float32)
        padded = np.zeros((4, 3, 8, 8), np.float32)
        padded[:2] = two
        got = np.asarray(engine.infer(padded, n_valid=2).levels[:2])
        alone = np.asarray(engine.infer(two, n_valid=2).levels)
        np.testing.assert_allclose(got, alone, rtol=1e-5, atol=1e-6)

    def test_infer_rejects_non_bucket_shapes(self, engine):
        imgs = np.zeros((3, 3, 8, 8), np.float32)
        with pytest.raises(ValueError, match="bucket"):
            engine.infer(imgs)
        with pytest.raises(ValueError, match="n_valid"):
            engine.infer(np.zeros((2, 3, 8, 8), np.float32), n_valid=3)

    def test_stats_records_lint(self, engine):
        recs = engine.stats_records()
        assert recs, "warmup/infer must have produced per-bucket stats"
        for r in recs:
            assert r["kind"] == "serve"
            assert schema.validate_record(r) == [], r

    @pytest.mark.slow  # compiles its own auto-route engine; CI runs it
    def test_auto_route_engine_exits_early_on_converged_input(self, params):
        """End-to-end: an engine on the auto route serves a converged
        batch in fewer iterations than the budget, and the count lands on
        the result."""
        scfg = ServeConfig(
            buckets=(2,), max_batch=2, iters="auto",
            exit_threshold=0.25, min_iters=1, max_auto_iters=10,
        )
        eng = InferenceEngine(CFG, scfg, params=params)
        # A constant image collapses to one island almost immediately —
        # the cheapest converged input there is.
        imgs = np.ones((2, 3, 8, 8), np.float32)
        res = eng.infer(imgs)
        assert res.iters_run < 10


# ---------------------------------------------------------------------------
# batcher (host-side: fake engine, no device)
# ---------------------------------------------------------------------------


class FakeEngine:
    """Engine-shaped policy probe: records every dispatch, returns
    zero-levels instantly."""

    def __init__(self, buckets=(1, 2, 4), latency_s=0.0, fail=None):
        self.scfg = ServeConfig(
            buckets=buckets, max_batch=max(buckets), max_delay_ms=5.0,
            queue_depth=8,
        )
        self.latency_s = latency_s
        self.fail = fail
        self.calls = []

    def pick_bucket(self, n):
        for b in self.scfg.buckets:
            if n <= b:
                return b
        raise ValueError(f"n={n} exceeds the largest bucket")

    def infer(self, imgs, n_valid=None):
        if self.fail is not None:
            raise self.fail
        b = imgs.shape[0]
        self.calls.append((b, n_valid))
        if self.latency_s:
            time.sleep(self.latency_s)
        return ServeResult(
            levels=np.zeros((b, 16, 3, 16), np.float32),
            iters_run=6,
            latency_s=self.latency_s,
            bucket=b,
            compiled=False,
        )


class DownWatchdog:
    def record(self):
        return {"backend_state": "down", "backend_devices": None,
                "backend_transitions": 1}


IMG = np.zeros((3, 8, 8), np.float32)


class TestDynamicBatcher:
    def test_queue_bound_sheds_with_backpressure(self):
        eng = FakeEngine()
        sink = Sink()
        b = DynamicBatcher(eng, queue_depth=2, writer=sink)  # NOT started
        b.submit(IMG)
        b.submit(IMG)
        with pytest.raises(QueueFullError):
            b.submit(IMG)
        assert b.n_shed == 1
        shed = [r for r in sink.records if r.get("event") == "shed"]
        assert shed and shed[0]["reason"] == "queue-full"
        assert schema.validate_record(shed[0]) == []
        b.stop(drain=False)

    def test_full_batch_dispatches_at_max_batch(self):
        eng = FakeEngine(buckets=(1, 2, 4))
        b = DynamicBatcher(eng, max_batch=4, max_delay_ms=10_000.0)
        tickets = [b.submit(IMG) for _ in range(4)]
        b.start()
        for t in tickets:
            levels, iters_run, latency = t.result(timeout=10.0)
            assert levels.shape == (16, 3, 16) and iters_run == 6
        b.stop()
        # One dispatch, gathered to the full batch, no padding.
        assert eng.calls == [(4, 4)]

    def test_max_delay_flushes_a_partial_batch(self):
        """The latency floor: 2 waiting requests must not wait forever for
        2 more — the oldest request's age bounds the gather."""
        eng = FakeEngine(buckets=(1, 2, 4))
        with DynamicBatcher(eng, max_batch=4, max_delay_ms=30.0) as b:
            t1 = b.submit(IMG)
            t2 = b.submit(IMG)
            t1.result(timeout=10.0)
            t2.result(timeout=10.0)
        # Padded up to bucket 2 with both rows valid.
        assert eng.calls == [(2, 2)]

    def test_bucket_selection_pads_to_smallest_admitting(self):
        eng = FakeEngine(buckets=(1, 2, 4))
        with DynamicBatcher(eng, max_batch=3, max_delay_ms=10_000.0) as b:
            tickets = [b.submit(IMG) for _ in range(3)]
            for t in tickets:
                t.result(timeout=10.0)
        assert eng.calls == [(4, 3)]  # 3 valid rows ride the 4-bucket

    def test_shed_on_backend_down_fails_fast_with_error_record(self):
        from glom_tpu.telemetry.watchdog import set_global_watchdog

        eng = FakeEngine()
        sink = Sink()
        set_global_watchdog(DownWatchdog())
        try:
            b = DynamicBatcher(eng, writer=sink)
            t0 = time.perf_counter()
            with pytest.raises(BackendDownError):
                b.submit(IMG)
            assert time.perf_counter() - t0 < 1.0  # fast-fail, not a hang
        finally:
            set_global_watchdog(None)
        errs = [r for r in sink.records if r.get("kind") == "error"]
        assert errs and errs[0]["error"] == "backend-down"
        assert errs[0].get("value") is None  # UNMEASURED, never a zero
        assert schema.validate_record(errs[0]) == []
        assert not eng.calls  # nothing was dispatched into a dead backend

    def test_gathered_batch_sheds_when_backend_dies_before_dispatch(self):
        from glom_tpu.telemetry.watchdog import set_global_watchdog

        eng = FakeEngine()
        sink = Sink()
        b = DynamicBatcher(eng, writer=sink)  # not started: requests queue
        tickets = [b.submit(IMG), b.submit(IMG)]
        set_global_watchdog(DownWatchdog())
        try:
            b.start()
            for t in tickets:
                with pytest.raises(BackendDownError):
                    t.result(timeout=10.0)
        finally:
            set_global_watchdog(None)
            b.stop(drain=False)
        assert not eng.calls

    def test_dispatch_error_fails_only_that_batch(self):
        eng = FakeEngine(fail=RuntimeError("XLA boom"))
        sink = Sink()
        with DynamicBatcher(eng, max_batch=2, max_delay_ms=10.0,
                            writer=sink) as b:
            t = b.submit(IMG)
            with pytest.raises(RuntimeError, match="XLA boom"):
                t.result(timeout=10.0)
            # The worker survives: a later healthy dispatch still serves.
            eng.fail = None
            t2 = b.submit(IMG)
            t2.result(timeout=10.0)
        assert [r.get("event") for r in sink.records].count("dispatch_error") == 1

    def test_dispatch_records_and_summary_lint(self):
        eng = FakeEngine()
        sink = Sink()
        with DynamicBatcher(eng, max_batch=2, max_delay_ms=10.0,
                            writer=sink) as b:
            for t in [b.submit(IMG) for _ in range(4)]:
                t.result(timeout=10.0)
            summary = b.summary_record()
        for r in sink.records + [summary]:
            assert schema.validate_record(r) == [], r
        dispatches = [r for r in sink.records if r.get("event") == "dispatch"]
        assert dispatches
        for d in dispatches:
            assert 0.0 <= d["pad_fraction"] < 1.0
            assert d["iters_run"] == 6
        assert summary["n_served"] == 4
        assert summary["iters_histogram"] == {"6": 4}

    def test_span_rollups_cover_the_serve_phases(self):
        eng = FakeEngine()
        with DynamicBatcher(eng, max_batch=2, max_delay_ms=10.0) as b:
            for t in [b.submit(IMG) for _ in range(2)]:
                t.result(timeout=10.0)
            recs = b.span_records()
        names = {r["name"] for r in recs}
        assert "serve_enqueue" in names and "serve_dispatch" in names
        for r in recs:
            assert r["kind"] == "span"
            assert schema.validate_record(r) == [], r

    def test_ticket_timeout(self):
        eng = FakeEngine()
        b = DynamicBatcher(eng)  # never started: the ticket cannot resolve
        t = b.submit(IMG)
        with pytest.raises(TimeoutError):
            t.result(timeout=0.05)
        b.stop(drain=False)


class TestServeConfig:
    def test_bucket_validation(self):
        with pytest.raises(ValueError, match="ascending"):
            ServeConfig(buckets=(4, 2))
        with pytest.raises(ValueError, match="non-empty"):
            ServeConfig(buckets=())
        with pytest.raises(ValueError, match="max_batch"):
            ServeConfig(buckets=(1, 2), max_batch=4)
        with pytest.raises(ValueError, match="iters"):
            ServeConfig(iters="sometimes")
        with pytest.raises(ValueError, match="iters"):
            ServeConfig(iters=0)

    def test_presets_carry_serve_configs(self):
        from glom_tpu.utils.presets import get_preset

        assert get_preset("mnist").serve.buckets == (1, 2, 4, 8)
        flagship = get_preset("imagenet224-dp8").serve
        assert flagship.iters == "auto" and flagship.use_pallas


@pytest.mark.slow
class TestServeCli:
    def test_synthetic_run_emits_lintable_records(self, tmp_path):
        from glom_tpu.serve.cli import main
        from glom_tpu.telemetry.schema import lint_stream

        out = tmp_path / "serve.jsonl"
        rc = main([
            "--preset", "mnist", "--synthetic", "3",
            "--buckets", "1,2", "--max-batch", "2",
            "--iters", "auto", "--out", str(out),
        ])
        assert rc == 0
        lines = out.read_text().splitlines()
        assert lint_stream(lines) == []
        import json

        recs = [json.loads(l) for l in lines]
        responses = [
            r for r in recs
            if r.get("kind") == "serve" and r.get("event") == "response"
        ]
        assert len(responses) == 3 and all(r["ok"] for r in responses)
        assert any(r.get("event") == "summary" for r in recs)
        assert any(r.get("event") == "warmup" for r in recs)


# ---------------------------------------------------------------------------
# two-tier early exit (glom_forward_tiered + the continuation queue)
# ---------------------------------------------------------------------------


class TestTieredExit:
    def test_threshold_zero_is_bitwise_fixed_iters(self, params, img):
        """The PR 4 contract survives the per-row witness: at threshold 0
        no row can ever converge, the quorum never votes, exactly
        max_iters run, and the output is bitwise the fixed forward's."""
        from glom_tpu.serve.early_exit import glom_forward_tiered

        fixed = jax.jit(
            lambda p, x: glom_forward(p, x, CFG, iters=6)
        )(params, img)
        res = jax.jit(
            lambda p, x: glom_forward_tiered(
                p, x, CFG, max_iters=6, threshold=0.0
            )
        )(params, img)
        assert int(res.iters_run) == 6
        assert not np.asarray(res.row_converged).any()
        assert np.array_equal(np.asarray(fixed), np.asarray(res.levels))

    @pytest.mark.slow  # one more while_loop compile; CI serve job runs it
    def test_quorum_exits_before_all_rows_converge(self, params, img):
        """quorum=0.5 over two settled rows + two cold rows: the bucket
        exits once the settled half converges, with the cold rows
        reported unconverged — the straggler set the batcher re-buckets."""
        from glom_tpu.serve.early_exit import glom_forward_tiered

        settled = glom_forward(params, img, CFG, iters=40)
        lv0 = jnp.concatenate(
            [
                settled,
                jnp.broadcast_to(
                    jnp.asarray(params.init_levels)[None, None],
                    settled.shape,
                ).astype(settled.dtype),
            ],
            axis=0,
        )
        both = jnp.concatenate([img, img], axis=0)
        res = jax.jit(
            lambda p, x, lv: glom_forward_tiered(
                p, x, CFG, max_iters=12, threshold=1e-3, quorum=0.5,
                levels=lv,
            )
        )(params, both, lv0)
        conv = np.asarray(res.row_converged)
        assert int(res.iters_run) < 12
        assert conv[:2].all()          # the settled half carried the quorum
        assert not conv[2:].all()      # cold rows are the stragglers

    @pytest.mark.slow  # compiles its own warm engine route; CI runs it
    def test_warm_pad_rows_never_vote(self, params, img):
        """A continuation bucket's PAD rows carry arbitrary warm-state
        garbage; the masked witness must keep the exit identical whatever
        occupies them — the warm twin of the cold pad-row lock."""
        from glom_tpu.serve.early_exit import glom_forward_tiered

        settled = glom_forward(params, img, CFG, iters=40)
        pad_imgs = jnp.concatenate([img, jnp.zeros_like(img)], axis=0)
        mask = jnp.asarray([True, True, False, False])
        fn = jax.jit(
            lambda p, x, lv, m: glom_forward_tiered(
                p, x, CFG, max_iters=8, threshold=1e-2, levels=lv,
                valid_mask=m,
            )
        )
        lv_a = jnp.concatenate([settled, jnp.zeros_like(settled)], axis=0)
        lv_b = jnp.concatenate(
            [settled, 100.0 * jnp.ones_like(settled)], axis=0
        )
        res_a = fn(params, pad_imgs, lv_a, mask)
        res_b = fn(params, pad_imgs, lv_b, mask)
        assert int(res_a.iters_run) == int(res_b.iters_run)
        assert np.array_equal(
            np.asarray(res_a.levels[:2]), np.asarray(res_b.levels[:2])
        )

    @pytest.mark.slow  # several engine compiles; CI serve job runs it
    def test_continuation_bitwise_parity_and_iter_conservation(self, params):
        """THE two-tier correctness lock: a straggler exited at the quorum
        and continued from its warm state must land on BITWISE the same
        final columns, after the same TOTAL iteration count, as the same
        request run to convergence in one batch (threshold-0 discipline:
        row updates are batch-independent, the witness only ever decides
        when to stop)."""
        from glom_tpu.serve.engine import InferenceEngine

        rng = np.random.default_rng(0)
        easy = [
            rng.normal(size=(3, 8, 8)).astype(np.float32) for _ in range(2)
        ]
        hard = (100.0 * rng.normal(size=(3, 8, 8))).astype(np.float32)
        scfg = ServeConfig(
            buckets=(1, 2, 4), max_batch=4, max_delay_ms=100.0,
            iters="auto", exit_threshold=1e-3, max_auto_iters=16,
            exit_quorum=0.5, max_continuations=3,
        )
        eng = InferenceEngine(CFG, scfg, params=params)
        with DynamicBatcher(eng) as b:
            tickets = [
                b.submit(easy[0]), b.submit(hard), b.submit(easy[1]),
            ]
            outs = [t.result(timeout=120.0) for t in tickets]
            summary = b.summary_record()
        # Conservation across the re-bucketing: every ticket terminal,
        # each request resolved exactly once.
        assert summary["n_served"] == 3 and summary["n_failed"] == 0
        assert sum(summary["iters_histogram"].values()) == 3
        assert summary["n_continued"] >= 1  # the hard row re-bucketed
        # Reference: the hard request alone, to convergence, in ONE batch.
        ref_scfg = ServeConfig(
            buckets=(1, 2, 4), max_batch=4, iters="auto",
            exit_threshold=1e-3, max_auto_iters=16,
        )
        ref = InferenceEngine(CFG, ref_scfg, params=params).infer(
            hard[None], n_valid=1
        )
        levels, total_iters, _ = outs[1]
        assert total_iters == ref.iters_run
        assert np.array_equal(levels, np.asarray(ref.levels[0]))


class TieredFakeEngine:
    """Host-side two-tier policy probe: first (cold) dispatch reports the
    last `n_stragglers` valid rows unconverged; warm dispatches converge
    everyone. Records every call's kind."""

    def __init__(self, n_stragglers=1, buckets=(1, 2, 4), fail=None,
                 name="fake0"):
        self.scfg = ServeConfig(
            buckets=buckets, max_batch=max(buckets), max_delay_ms=5.0,
            queue_depth=8, iters="auto", max_auto_iters=12,
            exit_quorum=0.5, max_continuations=2, dispatch_retries=0,
        )
        self.iters_key = "auto"
        self.auto_budget = 12
        self.n_stragglers = n_stragglers
        self.fail = fail
        self.name = name
        self.calls = []

    def pick_bucket(self, n):
        for b in self.scfg.buckets:
            if n <= b:
                return b
        raise ValueError(f"n={n} exceeds the largest bucket")

    def infer(self, imgs, n_valid=None, levels0=None, auto_budget=None,
              **kw):
        if self.fail is not None:
            raise self.fail
        b = imgs.shape[0]
        warm = levels0 is not None
        self.calls.append(
            {"bucket": b, "n_valid": n_valid, "warm": warm,
             "auto_budget": auto_budget}
        )
        iters = 4 if not warm else (auto_budget or 8)
        conv = np.ones((b,), bool)
        if not warm:
            conv[max(0, n_valid - self.n_stragglers):n_valid] = False
        return ServeResult(
            levels=np.zeros((b, 16, 3, 16), np.float32),
            iters_run=iters,
            latency_s=0.0,
            bucket=b,
            compiled=False,
            row_converged=conv,
            row_iters=np.full((b,), iters, np.int32),
        )


class TestContinuationQueue:
    def test_straggler_rebuckets_and_tickets_conserve(self):
        """3 requests, 1 straggler: the straggler's ticket resolves after
        its warm continuation with the SUMMED executed iterations; the
        histograms split by tier and conservation holds."""
        eng = TieredFakeEngine(n_stragglers=1)
        sink = Sink()
        with DynamicBatcher(eng, max_batch=4, max_delay_ms=10.0,
                            writer=sink) as b:
            tickets = [b.submit(IMG) for _ in range(3)]
            outs = [t.result(timeout=10.0) for t in tickets]
            summary = b.summary_record()
        # Two fast rows resolved at tier 0 with 4 executed iters; the
        # straggler rode one warm hop: 4 + remaining (12 - 4 = 8) = 12.
        assert sorted(o[1] for o in outs) == [4, 4, 12]
        assert summary["n_served"] == 3 and summary["n_failed"] == 0
        assert summary["n_continued"] == 1
        assert summary["iters_histogram"] == {"4": 2, "12": 1}
        assert summary["iters_histogram_by_tier"] == {
            "0": {"4": 2}, "1": {"12": 1},
        }
        warm_calls = [c for c in eng.calls if c["warm"]]
        assert len(warm_calls) == 1
        assert warm_calls[0]["auto_budget"] == 8  # the REMAINING budget
        cont = [r for r in sink.records if r.get("event") == "continuation"]
        assert cont and cont[0]["n_stragglers"] == 1
        for r in sink.records + [summary]:
            assert schema.validate_record(r) == [], r

    def test_continuation_hops_are_bounded(self):
        """A row that never converges resolves once max_continuations is
        exhausted — two-tier must not orbit forever."""

        class NeverConverges(TieredFakeEngine):
            def infer(self, imgs, n_valid=None, levels0=None,
                      auto_budget=None, **kw):
                res = super().infer(
                    imgs, n_valid=n_valid, levels0=levels0,
                    auto_budget=auto_budget, **kw
                )
                conv = np.zeros((imgs.shape[0],), bool)
                return res._replace(row_converged=conv, iters_run=2)

        eng = NeverConverges()
        with DynamicBatcher(eng, max_batch=2, max_delay_ms=10.0) as b:
            t = b.submit(IMG)
            _, iters_run, _ = t.result(timeout=10.0)
            summary = b.summary_record()
        # initial + max_continuations hops, 2 iters each
        assert iters_run == 2 * (1 + eng.scfg.max_continuations)
        assert summary["n_served"] == 1
        assert summary["n_continued"] == eng.scfg.max_continuations


class RaggedTieredFakeEngine:
    """Host-side ragged x continuation policy probe (ISSUE 16): the cold
    ragged dispatch exits at the quorum after 3 iters with the LAST
    packed row unconverged; the continuation hop converges everyone in
    whatever budget it was handed. Records every call's shape/budget."""

    def __init__(self, name="rfake0"):
        self.scfg = ServeConfig(
            buckets=(1, 2, 4), max_batch=4, max_delay_ms=5.0,
            queue_depth=8, iters="auto", max_auto_iters=6,
            exit_quorum=0.5, max_continuations=2, ragged=True,
            page_tokens=4, dispatch_retries=0,
        )
        self.cfg = CFG  # n=16 tokens -> 4 pages of 4
        self.iters_key = "auto"
        self.auto_budget = 6
        self.pool = None
        self.ragged_page_buckets = (4, 8, 12, 16)
        self.name = name
        self.calls = []

    def pick_pages(self, n):
        for p in self.ragged_page_buckets:
            if n <= p:
                return p
        raise ValueError(f"{n} pages exceeds the ladder")

    def cold_levels(self):
        return np.zeros(
            (CFG.num_patches, CFG.levels, CFG.dim), np.float32
        )

    def infer_ragged(self, flat, counts, page_idx=None, levels0=None,
                     auto_budget=None, **kw):
        from glom_tpu.serve.engine import RaggedServeResult

        warm = levels0 is not None
        T = flat.shape[0]
        self.calls.append(
            {"pages": T // 4, "counts": list(counts), "warm": warm,
             "auto_budget": auto_budget}
        )
        iters = (auto_budget or 3) if warm else 3
        conv = np.ones((len(counts),), bool)
        if not warm:
            conv[-1] = False  # the last packed row straggles
        return RaggedServeResult(
            levels=np.zeros((T, CFG.levels, CFG.dim), np.float32),
            iters_run=iters, latency_s=0.0, pages=T // 4,
            compiled=False, row_converged=conv,
            row_iters=np.full((len(counts),), iters, np.int32),
        )


class TestRaggedContinuationQueue:
    def test_ragged_straggler_conserves_budget_3_plus_3(self):
        """THE ragged x continuation conservation lock (ISSUE 16): a
        ragged straggler's two hops total exactly the budget — 3 cold
        + 3 continuation == 6 — and the continuation dispatch re-enters
        the RAGGED route carrying the REMAINING budget."""
        eng = RaggedTieredFakeEngine()
        sink = Sink()
        b = DynamicBatcher(eng, max_batch=4, max_delay_ms=10.0,
                           writer=sink)
        tickets = [b.submit(IMG) for _ in range(3)]
        b.start()  # all queued before the worker runs: ONE cold dispatch
        outs = [t.result(timeout=10.0) for t in tickets]
        summary = b.summary_record()
        b.stop()
        assert sorted(o[1] for o in outs) == [3, 3, 6]
        assert summary["n_served"] == 3 and summary["n_failed"] == 0
        assert summary["n_continued"] == 1
        assert summary["iters_histogram"] == {"3": 2, "6": 1}
        assert summary["iters_histogram_by_tier"] == {
            "0": {"3": 2}, "1": {"6": 1},
        }
        # The warm hop re-entered RAGGED: one row repacked alone at its
        # own ladder rung, capped at the remaining budget (6 - 3).
        warm_calls = [c for c in eng.calls if c["warm"]]
        assert len(warm_calls) == 1
        assert warm_calls[0]["auto_budget"] == 3
        assert warm_calls[0]["counts"] == [16]
        assert warm_calls[0]["pages"] == 4
        cont = [r for r in sink.records if r.get("event") == "continuation"]
        assert cont and cont[0]["n_stragglers"] == 1
        assert cont[0]["ragged"] is True
        for r in sink.records + [summary]:
            assert schema.validate_record(r) == [], r


class _ChunkLadderEngine:
    """Bare ladder probe for _ragged_chunks: page math only, no device."""

    def __init__(self, buckets):
        self.ragged_page_buckets = buckets
        self.pool = None
        self.cfg = CFG
        self.scfg = ServeConfig(
            buckets=(1, 2, 4), max_batch=4, page_tokens=4
        )

    def pick_pages(self, n):
        for p in self.ragged_page_buckets:
            if n <= p:
                return p
        raise ValueError(f"{n} pages exceeds the ladder")


class TestRaggedChunkPadAwareness:
    """Pad-aware rung selection in _ragged_chunks (ISSUE 16): closing a
    chunk early must beat escalating onto the next ladder rung whenever
    the escalation's round-up pad exceeds the close-here pad."""

    @staticmethod
    def _rows(n, n_patches=4):
        return [types.SimpleNamespace(n_patches=n_patches) for _ in range(n)]

    @staticmethod
    def _pad_pages(engine, chunks):
        from glom_tpu.serve.paged_columns import pages_for_tokens

        pad = 0
        for chunk in chunks:
            pages = sum(pages_for_tokens(it.n_patches, 4) for it in chunk)
            pad += engine.pick_pages(pages) - pages
        return pad

    def test_fine_ladder_closes_early_for_zero_pad(self):
        """Five one-page rows on a (1,2,4,8) ladder: token round-up
        alone packs all five at rung 8 (pad 3); the pad-aware split
        closes chunks where escalation loses — zero pad total."""
        eng = _ChunkLadderEngine((1, 2, 4, 8))
        chunks = DynamicBatcher._ragged_chunks(None, eng, self._rows(5))
        assert [len(c) for c in chunks] == [2, 2, 1]
        assert self._pad_pages(eng, chunks) == 0

    def test_coarse_ladder_ties_pack_into_one_chunk(self):
        """The same five rows on the default-shaped coarse ladder: the
        escalation pad TIES the close-here pad (3 == 3), and ties must
        pack — one dispatch, the pre-pad-awareness behavior."""
        eng = _ChunkLadderEngine((4, 8, 12, 16))
        chunks = DynamicBatcher._ragged_chunks(None, eng, self._rows(5))
        assert [len(c) for c in chunks] == [5]
        assert self._pad_pages(eng, chunks) == 3

    def test_top_rung_overflow_still_splits(self):
        """Pad-awareness never overrides the hard cap: rows whose total
        exceeds the top signature split there regardless of pads."""
        eng = _ChunkLadderEngine((1, 2, 4))
        chunks = DynamicBatcher._ragged_chunks(
            None, eng, self._rows(3, n_patches=8)
        )
        assert [len(c) for c in chunks] == [2, 1]
        assert self._pad_pages(eng, chunks) == 0


class TestMultiEngineFanOut:
    def test_failover_redispatches_to_sibling_and_conserves(self):
        """A permanently failing engine's batches hand over to the
        sibling; the dead engine is marked, every ticket resolves, and
        conservation holds — the kill-serve chaos contract, host-side."""
        sink = Sink()
        bad = FakeEngine()
        bad.fail = RuntimeError("engine0 boom")
        bad.name = "bad"
        good = FakeEngine()
        good.name = "good"
        with DynamicBatcher(engines=[bad, good], max_batch=2,
                            max_delay_ms=10.0, writer=sink) as b:
            # PACED submissions until "bad" has demonstrably taken (and
            # failed) a batch: the fairness rotation hands the idle
            # worker the next request, so the failover path runs
            # deterministically — an all-at-once burst made ONE pickup
            # race decide whether it ran at all (this test was flaky
            # exactly that way).
            tickets = [b.submit(IMG)]
            deadline = time.monotonic() + 10.0
            while not any(
                r.get("event") == "engine_failover" for r in sink.records
            ):
                assert time.monotonic() < deadline, "bad never dispatched"
                time.sleep(0.02)
                tickets.append(b.submit(IMG))
            tickets += [b.submit(IMG) for _ in range(2)]
            outs = [t.result(timeout=10.0) for t in tickets]
            summary = b.summary_record()
        n = len(tickets)
        assert all(o[1] == 6 for o in outs)
        assert summary["n_served"] == n and summary["n_failed"] == 0
        assert summary["n_redispatched"] >= 1
        assert not summary["engines"]["bad"]["alive"]
        assert summary["engines"]["bad"]["dispatches"] == 0
        assert summary["engines"]["good"]["dispatches"] >= 1
        events = [r.get("event") for r in sink.records]
        assert "engine_failover" in events and "engine_dead" in events
        assert not bad.calls and good.calls

    def test_single_engine_dispatch_error_still_fails_fast(self):
        """With no sibling there is no failover: the batch fails fast
        exactly as before (the PR 4 contract unchanged)."""
        eng = FakeEngine(fail=RuntimeError("XLA boom"))
        with DynamicBatcher(eng, max_batch=2, max_delay_ms=10.0) as b:
            t = b.submit(IMG)
            with pytest.raises(RuntimeError, match="XLA boom"):
                t.result(timeout=10.0)

    def test_all_engines_dead_sheds_new_admissions(self):
        bad1 = FakeEngine(fail=RuntimeError("boom1"))
        bad1.name = "b1"
        bad2 = FakeEngine(fail=RuntimeError("boom2"))
        bad2.name = "b2"
        with DynamicBatcher(engines=[bad1, bad2], max_batch=1,
                            max_delay_ms=5.0, max_redispatch=1) as b:
            # Both engines can die before the later submits land — an
            # admission-time shed then IS the correct fast-fail (counted
            # below via conservation), so tolerate either ordering.
            tickets = []
            for _ in range(4):
                try:
                    tickets.append(b.submit(IMG))
                except ShedError:
                    pass
            for t in tickets:
                with pytest.raises(Exception):
                    t.result(timeout=10.0)
            # Both engines dead: admission now sheds fast, never strands.
            deadline = time.perf_counter() + 5.0
            while b._alive_engines() and time.perf_counter() < deadline:
                time.sleep(0.01)
            with pytest.raises(ShedError):
                b.submit(IMG)
        # Summary AFTER stop(): whatever could no longer resolve has been
        # failed, so conservation is exact.
        summary = b.summary_record()
        assert summary["n_served"] == 0
        total = (summary["n_failed"] + summary["n_shed"])
        assert total == summary["n_requests"]

    def test_explicit_ladder_rejected_with_multiple_engines(self):
        from glom_tpu.resilience.ladder import DegradationLadder

        ladder = DegradationLadder(degraded_iters=2, bucket_cap=1)
        with pytest.raises(ValueError, match="single engine"):
            DynamicBatcher(
                engines=[FakeEngine(), FakeEngine()], ladder=ladder
            )


class TestPickupFairness:
    """The ROADMAP fairness item (observed while building rejoin-serve):
    under slow paced traffic one worker could win EVERY 50ms-timeout
    first-get race for seconds — its loop re-entered get() microseconds
    after each dispatch while the sibling's expired wait re-queued behind
    it. The rotation fix: the last winner defers a small handicap on an
    idle queue (an already-waiting sibling is then first in the waiter
    list) and first-get timeouts carry deterministic per-engine jitter."""

    def test_paced_traffic_dispatches_on_both_workers(self):
        a, b = FakeEngine(), FakeEngine()
        a.name, b.name = "a", "b"
        with DynamicBatcher(engines=[a, b], max_batch=1,
                            max_delay_ms=1.0) as bat:
            for _ in range(12):
                t = bat.submit(IMG)
                t.result(timeout=10.0)
                # Paced WELL past the pickup handicap: each request is
                # resolved (and both workers idle-waiting again) before
                # the next arrives — exactly the traffic shape that
                # phase-locked before the rotation.
                time.sleep(0.012)
            summary = bat.summary_record()
        eng = summary["engines"]
        assert eng["a"]["dispatches"] > 0 and eng["b"]["dispatches"] > 0, (
            f"paced pickup phase-locked on one engine: {eng}"
        )
        assert summary["n_served"] == 12 and summary["n_failed"] == 0

    def test_first_get_timeouts_are_jittered_and_deterministic(self):
        engs = [FakeEngine() for _ in range(3)]
        for i, e in enumerate(engs):
            e.name = f"e{i}"
        bat = DynamicBatcher(engines=engs, max_batch=1)  # never started
        touts = [bat._first_get_timeout(f"e{i}") for i in range(3)]
        assert len(set(touts)) == 3, touts  # pairwise distinct
        assert all(0.05 <= t <= 0.07 for t in touts), touts
        assert touts == [bat._first_get_timeout(f"e{i}") for i in range(3)]
        bat.stop(drain=False)


class TestEngineRejoin:
    """Probation re-admit of a dead engine (ServeConfig.rejoin_threshold,
    docs/RESILIENCE.md): N consecutive successful health dispatches bring
    a recovered engine back behind the shared queue."""

    def _kill(self, b, bad, good, n=6):
        """Drive traffic until the bad engine is marked dead."""
        deadline = time.perf_counter() + 10.0
        while time.perf_counter() < deadline:
            try:
                b.submit(IMG).result(timeout=10.0)
            except Exception:
                pass
            with b._engine_lock:
                if not b._engine_state[bad.name]["alive"]:
                    return
        raise AssertionError("bad engine never died")

    def _await_rejoin(self, b, name, timeout=10.0):
        deadline = time.perf_counter() + timeout
        while time.perf_counter() < deadline:
            with b._engine_lock:
                st = dict(b._engine_state[name])
            if st["alive"]:
                return st
            time.sleep(0.01)
        raise AssertionError(f"{name} never rejoined")

    def test_probation_readmits_and_reserves(self):
        sink = Sink()
        bad = FakeEngine()
        bad.name = "bad"
        bad.fail = RuntimeError("boom")
        good = FakeEngine(latency_s=0.005)
        good.name = "good"
        with DynamicBatcher(
            engines=[bad, good], max_batch=1, max_delay_ms=5.0,
            writer=sink, rejoin_threshold=2, rejoin_interval_ms=15.0,
        ) as b:
            self._kill(b, bad, good)
            bad.fail = None  # the replica recovered
            st = self._await_rejoin(b, "bad")
            assert st["rejoins"] == 1 and not st["probation"]
            # The re-admitted engine serves real traffic again: keep the
            # good engine busy so the revived worker must pick up work.
            n_before = len(bad.calls)
            deadline = time.perf_counter() + 10.0
            while len(bad.calls) <= n_before + 2:
                b.submit(IMG).result(timeout=10.0)
                assert time.perf_counter() < deadline, "bad never re-served"
            summary = b.summary_record()
        events = [r.get("event") for r in sink.records]
        assert "engine_probation" in events and "engine_rejoin" in events
        rejoin = next(
            r for r in sink.records if r.get("event") == "engine_rejoin"
        )
        assert rejoin["engine"] == "bad"
        assert rejoin["health_dispatches"] == 2
        assert schema.validate_record(rejoin) == []
        assert summary["n_rejoined"] == 1
        assert summary["engines"]["bad"]["alive"]
        assert summary["engines"]["bad"]["dispatches"] >= 3
        assert summary["n_failed"] == 0  # failover covered the dead window

    def test_failed_probe_resets_the_consecutive_count(self):
        """A flapping engine must not rejoin on interleaved successes:
        the probation count restarts at every failed health dispatch."""
        flaky = FakeEngine()
        flaky.name = "flaky"
        flaky.fail = RuntimeError("boom")
        good = FakeEngine()
        good.name = "good"

        calls = {"n": 0}
        orig_infer = flaky.infer

        def infer(imgs, n_valid=None, **kw):
            calls["n"] += 1
            # Post-death probes: fail every second probe until probe 6 —
            # consecutive-success never reaches 3 before that.
            if flaky.fail is None and calls["n"] < 6 and calls["n"] % 2:
                raise RuntimeError("still flapping")
            return orig_infer(imgs, n_valid=n_valid)

        flaky.infer = infer
        with DynamicBatcher(
            engines=[flaky, good], max_batch=1, max_delay_ms=5.0,
            rejoin_threshold=3, rejoin_interval_ms=10.0,
        ) as b:
            TestEngineRejoin._kill(self, b, flaky, good)
            flaky.fail = None
            st = self._await_rejoin(b, "flaky")
            assert st["rejoins"] == 1
        # 3 consecutive successes require surviving past the flap window.
        assert calls["n"] >= 6

    def test_rejoin_disabled_keeps_death_terminal(self):
        bad = FakeEngine()
        bad.name = "bad"
        bad.fail = RuntimeError("boom")
        good = FakeEngine()
        good.name = "good"
        sink = Sink()
        with DynamicBatcher(
            engines=[bad, good], max_batch=1, max_delay_ms=5.0, writer=sink
        ) as b:
            self._kill(b, bad, good)
            bad.fail = None
            time.sleep(0.2)  # ample probation time, were there any
            with b._engine_lock:
                st = dict(b._engine_state["bad"])
        assert not st["alive"] and not st["probation"]
        assert "engine_probation" not in [
            r.get("event") for r in sink.records
        ]

    def test_stop_racing_rejoin_never_leaks_a_worker(self):
        """Review-caught race: a rejoin landing concurrently with stop()
        must either register its worker BEFORE stop()'s join snapshot
        (joined) or observe the stop flag and never spawn — across many
        seeds, no batcher thread survives stop() and a restart never
        yields duplicate workers."""
        import threading as th

        for i in range(15):
            bad = FakeEngine()
            bad.name = f"bad{i}"
            bad.fail = RuntimeError("boom")
            good = FakeEngine()
            good.name = f"good{i}"
            b = DynamicBatcher(
                engines=[bad, good], max_batch=1, max_delay_ms=5.0,
                rejoin_threshold=1, rejoin_interval_ms=1.0,
            )
            b.start()
            self._kill(b, bad, good)
            bad.fail = None  # rejoin becomes possible ...
            time.sleep(0.001 * (i % 4))  # ... racing the stop below
            b.stop()
            with b._counter_lock:
                assert b._threads == []
            mine = [
                t for t in th.enumerate()
                if t.name.endswith(f"-bad{i}") or t.name.endswith(f"-good{i}")
            ]
            deadline = time.perf_counter() + 2.0
            while any(t.is_alive() for t in mine):
                assert time.perf_counter() < deadline, (
                    f"leaked batcher thread(s) after stop(): "
                    f"{[t.name for t in mine if t.is_alive()]}"
                )
                time.sleep(0.01)
            with b._engine_lock:
                st = dict(b._engine_state[f"bad{i}"])
            assert not st["probation"]

    def test_stop_during_probation_exits_cleanly(self):
        bad = FakeEngine()
        bad.name = "bad"
        bad.fail = RuntimeError("boom")
        good = FakeEngine()
        good.name = "good"
        b = DynamicBatcher(
            engines=[bad, good], max_batch=1, max_delay_ms=5.0,
            rejoin_threshold=50, rejoin_interval_ms=10.0,
        )
        b.start()
        self._kill(b, bad, good)
        b.stop()  # probation still counting: must not block or leak
        with b._engine_lock:
            assert not b._engine_state["bad"]["probation"]


class TestReviewRegressions:
    def test_warm_hop_under_degraded_ladder_uses_fixed_budget(self):
        """A ladder that degrades to capped_iters BETWEEN a straggler's
        cold dispatch and its warm hop: the warm dispatch must ride the
        fixed degraded route (no auto_budget — the engine rejects the
        combination), resolving the ticket instead of failing it."""

        class StrictTiered(TieredFakeEngine):
            def infer(self, imgs, n_valid=None, levels0=None,
                      auto_budget=None, iters_override=None, **kw):
                if auto_budget is not None and iters_override is not None:
                    raise ValueError(
                        "auto_budget composes with the auto route only"
                    )
                if iters_override is not None:
                    b = imgs.shape[0]
                    self.calls.append(
                        {"bucket": b, "n_valid": n_valid,
                         "warm": levels0 is not None,
                         "iters_override": iters_override}
                    )
                    return ServeResult(
                        levels=np.zeros((b, 16, 3, 16), np.float32),
                        iters_run=iters_override, latency_s=0.0,
                        bucket=b, compiled=False,
                        row_converged=np.ones((b,), bool),
                        row_iters=np.full((b,), iters_override, np.int32),
                    )
                return super().infer(
                    imgs, n_valid=n_valid, levels0=levels0,
                    auto_budget=auto_budget, **kw
                )

        eng = StrictTiered(n_stragglers=1)

        class FlipLadder:
            """NORMAL until the first (cold) dispatch lands, then
            capped_iters — the degradation racing the continuation."""

            degraded_iters = 3
            bucket_cap = 4

            def rung(self):
                from glom_tpu.resilience.ladder import CAPPED_ITERS, NORMAL

                return CAPPED_ITERS if eng.calls else NORMAL

            def rung_name(self):
                from glom_tpu.resilience.ladder import RUNGS

                return RUNGS[self.rung()]

            def observe(self, **kw):
                return self.rung()

            def record(self):
                return {"ladder_rung": self.rung_name()}

        with DynamicBatcher(eng, max_batch=2, max_delay_ms=10.0,
                            ladder=FlipLadder()) as b:
            tickets = [b.submit(IMG), b.submit(IMG)]
            outs = [t.result(timeout=10.0) for t in tickets]
            summary = b.summary_record()
        assert summary["n_served"] == 2 and summary["n_failed"] == 0
        warm_calls = [c for c in eng.calls if c.get("warm")]
        assert warm_calls and warm_calls[0]["iters_override"] == 3

    def test_multi_engine_summary_nests_retry_records_per_engine(self):
        """Fan-out summaries must not let one engine's retry/ladder
        rollup overwrite a sibling's: they nest under engines[name]."""
        from glom_tpu.resilience.retry import RetryPolicy

        e0, e1 = FakeEngine(), FakeEngine()
        e0.name, e1.name = "e0", "e1"
        e0.retry = RetryPolicy(retries=1, site="e0-dispatch")
        e1.retry = RetryPolicy(retries=1, site="e1-dispatch")
        with DynamicBatcher(engines=[e0, e1], max_batch=1,
                            max_delay_ms=5.0) as b:
            for t in [b.submit(IMG) for _ in range(4)]:
                t.result(timeout=10.0)
            summary = b.summary_record()
        assert "retry_site" not in summary  # no flat (last-wins) merge
        sites = {
            name: st.get("retry", {}).get("retry_site")
            for name, st in summary["engines"].items()
        }
        assert set(sites.values()) <= {"e0-dispatch", "e1-dispatch", None}
        assert any(v for v in sites.values())
        assert schema.validate_record(summary) == []


class ColdTieredFakeEngine(TieredFakeEngine):
    """TieredFakeEngine + the cold_levels the mixed warm/cold fold path
    needs when a failover requeue mixes cold rows into a warm group."""

    def cold_levels(self):
        return np.zeros((16, 3, 16), np.float32)


class TestRequestTracing:
    """Schema v6 request-scoped tracing (telemetry/tracectx.py): every
    request is ONE causal tree, and per-request executed work CONSERVES
    exactly across continuation hops and engine failover — the
    end-to-end parity lock of the observability PR."""

    def test_dispatch_records_carry_row_aligned_trace_context(self):
        eng = FakeEngine()
        sink = Sink()
        with DynamicBatcher(eng, max_batch=2, max_delay_ms=10.0,
                            writer=sink) as b:
            tickets = [b.submit(IMG) for _ in range(2)]
            for t in tickets:
                t.result(timeout=10.0)
        (d,) = [r for r in sink.records if r.get("event") == "dispatch"]
        assert d["trace_ids"] == [t.trace_id for t in tickets]
        assert d["parent_spans"] == [t.span_id for t in tickets]
        assert isinstance(d["span_id"], str)
        resolves = [r for r in sink.records if r.get("event") == "resolve"]
        assert {r["trace_id"] for r in resolves} == {
            t.trace_id for t in tickets
        }
        for r in resolves:
            assert r["parent_span"] == d["span_id"]

    def test_shed_record_is_a_trace_leaf(self):
        eng = FakeEngine()
        sink = Sink()
        b = DynamicBatcher(eng, queue_depth=1, writer=sink)  # NOT started
        b.submit(IMG)
        with pytest.raises(QueueFullError) as ei:
            b.submit(IMG)
        b.stop(drain=False)
        (shed,) = [r for r in sink.records if r.get("event") == "shed"]
        assert shed["trace_id"] == ei.value.detail["trace_id"]
        assert isinstance(shed["span_id"], str)
        assert isinstance(shed["parent_span"], str)

    def test_trace_parity_lock_continuation_plus_failover(self):
        """THE end-to-end conservation lock: a request served through a
        straggler continuation AND an engine failover reconstructs as ONE
        trace tree whose summed per-hop executed iters and wall spans
        EXACTLY equal the ticket's resolved totals."""
        from glom_tpu.telemetry import tracectx

        bad = ColdTieredFakeEngine(n_stragglers=1, name="bad")
        bad.fail = RuntimeError("engine boom")
        good = ColdTieredFakeEngine(n_stragglers=1, name="good")
        sink = Sink()
        with DynamicBatcher(engines=[bad, good], max_batch=4,
                            max_delay_ms=10.0, writer=sink) as b:
            # PACED submissions (one per pickup) until the failing
            # engine has demonstrably taken a batch: the fairness
            # rotation hands the idle worker the next request, so "bad"
            # deterministically dispatches within a few requests — an
            # all-at-once burst would make ONE pickup race decide
            # whether the failover path runs at all (this test was
            # flaky exactly that way).
            tickets = [b.submit(IMG)]
            deadline = time.monotonic() + 10.0
            while not any(
                r.get("event") == "engine_failover" for r in sink.records
            ):
                assert time.monotonic() < deadline, "bad never dispatched"
                time.sleep(0.02)
                tickets.append(b.submit(IMG))
            # A couple more rides AFTER the failover so post-failover
            # serving (and its continuations) cross the trace too.
            tickets += [b.submit(IMG) for _ in range(2)]
            outs = [t.result(timeout=10.0) for t in tickets]
        recs = sink.records
        for r in recs:
            assert schema.validate_record(r) == [], r
        assert any(r.get("event") == "engine_failover" for r in recs)
        assert any(r.get("event") == "continuation" for r in recs)
        traces = tracectx.list_traces(recs)
        assert set(traces) == {t.trace_id for t in tickets}
        for ticket, (_, iters_run, _) in zip(tickets, outs):
            check = tracectx.conservation(recs, ticket.trace_id)
            assert check["ok"], check
            # The tree's totals ARE the ticket's resolved totals — and
            # the straggler's tree shows MORE than one hop.
            assert check["iters_total"] == iters_run
            assert check["hop_iters"] == iters_run
            assert check["dispatch_ms_total"] == ticket.dispatch_ms
            assert check["n_hops"] == ticket.hops + 1
            tree = tracectx.build_tree(recs, ticket.trace_id)
            assert tree["root"]["span_id"] == ticket.span_id
        straggler = [t for t in tickets if t.hops][0]
        assert tracectx.conservation(
            recs, straggler.trace_id)["n_hops"] >= 2
        # At least one tree carries the failover hop on its causal path.
        assert any(
            any(r.get("event") == "engine_failover"
                for r in tracectx.records_for(recs, t.trace_id))
            for t in tickets
        )

    def test_nested_retry_events_join_the_dispatch_span(self):
        """A retry recovery event emitted from UNDER the dispatch scope
        (engine RetryPolicy) lands in the same span node as its dispatch
        — context propagation with no signature threading."""
        from glom_tpu.resilience.retry import RetryPolicy
        from glom_tpu.telemetry import tracectx

        class FlakyEngine(FakeEngine):
            def __init__(self):
                super().__init__()
                self.tries = 0
                self.retry = None

            def infer(self, imgs, n_valid=None):
                self.tries += 1
                if self.tries == 1:
                    raise RuntimeError("transient")
                return super().infer(imgs, n_valid=n_valid)

        sink = Sink()
        eng = FlakyEngine()
        eng.retry = RetryPolicy(retries=2, backoff_s=0.0, writer=sink,
                                site="flaky-dispatch")

        class RetryingEngine:
            scfg = eng.scfg
            name = "flaky"

            def pick_bucket(self, n):
                return eng.pick_bucket(n)

            def infer(self, imgs, n_valid=None):
                return eng.retry.run(
                    lambda: eng.infer(imgs, n_valid=n_valid),
                    bucket=imgs.shape[0], n_valid=n_valid,
                )

        with DynamicBatcher(RetryingEngine(), max_batch=1,
                            max_delay_ms=5.0, writer=sink) as b:
            t = b.submit(IMG)
            t.result(timeout=10.0)
        retry = [r for r in sink.records
                 if r.get("action") == "dispatch-retry"]
        dispatch = [r for r in sink.records if r.get("event") == "dispatch"]
        assert retry and dispatch
        assert retry[0]["span_id"] == dispatch[0]["span_id"]
        assert retry[0]["trace_ids"] == [t.trace_id]
        tree = tracectx.build_tree(sink.records, t.trace_id)
        (node,) = tree["root"]["children"]
        actions = {r.get("action") for r in node["records"]}
        assert "dispatch-retry" in actions

    def test_ticket_exposes_served_totals(self):
        eng = TieredFakeEngine(n_stragglers=1)
        with DynamicBatcher(eng, max_batch=4, max_delay_ms=10.0) as b:
            tickets = [b.submit(IMG) for _ in range(3)]
            for t in tickets:
                t.result(timeout=10.0)
        by_hops = sorted(t.hops for t in tickets)
        assert by_hops == [0, 0, 1]
        for t in tickets:
            assert isinstance(t.dispatch_ms, float)
            assert isinstance(t.trace_id, str)
