"""Serving subsystem (glom_tpu/serve, docs/SERVING.md): engine AOT warmup
and bucket discipline, dynamic-batching admission policy (host-side, fake
engine — no device), consensus early-exit correctness.

The two acceptance locks:
  * threshold=0.0 -> iters="auto" output is BITWISE-identical to the
    fixed-iters forward (both jitted: the exit test `delta < 0` can never
    fire, and the while_loop body is the same update_step as the scan's);
  * a converged input (a long-settled state fed back in) exits in fewer
    than max_iters iterations.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from glom_tpu.models import Glom
from glom_tpu.models.core import glom_forward, init_glom
from glom_tpu.serve.batcher import (
    BackendDownError,
    DynamicBatcher,
    QueueFullError,
)
from glom_tpu.serve.early_exit import (
    glom_forward_auto,
    masked_level_agreement,
)
from glom_tpu.serve.engine import InferenceEngine, ServeResult
from glom_tpu.telemetry import schema
from glom_tpu.utils.config import GlomConfig, ServeConfig

CFG = GlomConfig(dim=16, levels=3, image_size=8, patch_size=2)  # n=16, tiny
SCFG = ServeConfig(buckets=(1, 2, 4), max_batch=4, max_delay_ms=5.0)


@pytest.fixture(scope="module")
def params():
    return init_glom(jax.random.PRNGKey(1), CFG)


@pytest.fixture(scope="module")
def img():
    return jnp.asarray(
        np.random.default_rng(2).normal(size=(2, 3, 8, 8)), jnp.float32
    )


class Sink:
    def __init__(self):
        self.records = []

    def write(self, rec):
        self.records.append(rec)


# ---------------------------------------------------------------------------
# early exit
# ---------------------------------------------------------------------------


class TestEarlyExit:
    def test_threshold_zero_is_bitwise_fixed_iters(self, params, img):
        """The acceptance lock: exit disabled -> exactly max_iters updates,
        output bitwise-equal to the scanned fixed-iters forward."""
        fixed = jax.jit(
            lambda p, x: glom_forward(p, x, CFG, iters=6)
        )(params, img)
        auto, iters_run, _ = jax.jit(
            lambda p, x: glom_forward_auto(
                p, x, CFG, max_iters=6, threshold=0.0
            )
        )(params, img)
        assert int(iters_run) == 6
        assert np.array_equal(np.asarray(fixed), np.asarray(auto))

    def test_converged_input_exits_early(self, params, img):
        """A long-settled state fed back as the carry has a near-zero
        agreement delta: the loop must exit before the full budget."""
        settled = glom_forward(params, img, CFG, iters=40)
        _, iters_run, _ = jax.jit(
            lambda p, x, lv: glom_forward_auto(
                p, x, CFG, max_iters=12, threshold=1e-3, levels=lv
            )
        )(params, img, settled)
        assert int(iters_run) < 12

    @pytest.mark.slow  # one more while_loop compile; CI serve job runs it
    def test_min_iters_floors_the_exit(self, params, img):
        # A threshold so large every delta passes: exit lands exactly at
        # the floor, never below it.
        _, iters_run, _ = jax.jit(
            lambda p, x: glom_forward_auto(
                p, x, CFG, max_iters=8, threshold=1e9, min_iters=3
            )
        )(params, img)
        assert int(iters_run) == 3

    def test_masked_agreement_matches_unmasked_when_all_valid(
        self, params, img
    ):
        from glom_tpu.telemetry.diagnostics import level_agreement

        lv = glom_forward(params, img, CFG, iters=4)
        full = np.asarray(level_agreement(lv))
        np.testing.assert_allclose(
            np.asarray(masked_level_agreement(lv, None)), full, atol=1e-6
        )
        np.testing.assert_allclose(
            np.asarray(
                masked_level_agreement(lv, jnp.ones(lv.shape[0], bool))
            ),
            full,
            atol=1e-6,
        )

    @pytest.mark.slow  # one more while_loop compile; CI serve job runs it
    def test_pad_rows_do_not_vote_on_the_witness(self, params, img):
        """The serving contract: the SAME two valid rows must exit after
        the SAME number of iterations whatever garbage occupies the pad
        rows — the mask keeps the witness to real requests."""
        pad_a = jnp.concatenate([img, jnp.zeros_like(img)], axis=0)
        pad_b = jnp.concatenate([img, 100.0 * jnp.ones_like(img)], axis=0)
        mask = jnp.asarray([True, True, False, False])
        fn = jax.jit(
            lambda p, x, m: glom_forward_auto(
                p, x, CFG, max_iters=8, threshold=1e-2, valid_mask=m
            )
        )
        out_a, n_a, _ = fn(params, pad_a, mask)
        out_b, n_b, _ = fn(params, pad_b, mask)
        assert int(n_a) == int(n_b)
        assert np.array_equal(np.asarray(out_a[:2]), np.asarray(out_b[:2]))

    def test_validation(self, params, img):
        with pytest.raises(ValueError, match="max_iters"):
            glom_forward_auto(params, img, CFG, max_iters=0)
        with pytest.raises(ValueError, match="min_iters"):
            glom_forward_auto(params, img, CFG, max_iters=4, min_iters=5)
        with pytest.raises(ValueError, match="threshold"):
            glom_forward_auto(params, img, CFG, max_iters=4, threshold=-1.0)


class TestGlomAutoIters:
    def test_auto_matches_fixed_with_threshold_zero(self, img):
        """iters='auto' on the preserved API: exit disabled reproduces the
        fixed-iters call bitwise (both memoized jitted programs)."""
        model = Glom(
            dim=16, levels=3, image_size=8, patch_size=2, backend="cpu",
            exit_threshold=0.0, auto_max_iters=4,
        )
        fixed = model(img, iters=4)
        auto = model(img, iters="auto")
        assert np.array_equal(np.asarray(fixed), np.asarray(auto))
        assert int(model.last_auto_iters) == 4

    @pytest.mark.slow  # extra jit variant; CI serve job runs it
    def test_auto_early_exit_reports_count(self, img):
        model = Glom(
            dim=16, levels=3, image_size=8, patch_size=2, backend="cpu",
            exit_threshold=1e9, auto_max_iters=8, auto_min_iters=2,
        )
        model(img, iters="auto")
        assert int(model.last_auto_iters) == 2

    def test_auto_rejects_return_all(self, img):
        model = Glom(
            dim=16, levels=3, image_size=8, patch_size=2, backend="cpu"
        )
        with pytest.raises(ValueError, match="return_all"):
            model(img, iters="auto", return_all=True)


# ---------------------------------------------------------------------------
# engine
# ---------------------------------------------------------------------------


class TestInferenceEngine:
    @pytest.fixture(scope="class")
    def engine(self, params):
        return InferenceEngine(CFG, SCFG, params=params)

    def test_pick_bucket(self, engine):
        assert engine.pick_bucket(1) == 1
        assert engine.pick_bucket(2) == 2
        assert engine.pick_bucket(3) == 4
        assert engine.pick_bucket(4) == 4
        with pytest.raises(ValueError, match="exceeds"):
            engine.pick_bucket(5)
        with pytest.raises(ValueError, match=">= 1"):
            engine.pick_bucket(0)

    def test_warmup_precompiles_every_bucket(self, engine):
        sink = Sink()
        engine.writer = sink
        times = engine.warmup()
        assert set(times) == {1, 2, 4}
        assert all(
            engine.signature(b) in engine._compiled for b in SCFG.buckets
        )
        warm = [r for r in sink.records if r.get("event") == "warmup"]
        assert {r["bucket"] for r in warm} == {1, 2, 4}
        for r in warm:
            assert r["kind"] == "serve"
            assert schema.validate_record(r) == [], r
        # Re-warmup is free: everything is already compiled.
        assert all(v == 0.0 for v in engine.warmup().values())

    def test_infer_shapes_and_fixed_iters_stamp(self, engine):
        imgs = np.random.default_rng(0).normal(size=(4, 3, 8, 8))
        res = engine.infer(imgs, n_valid=3)
        assert isinstance(res, ServeResult)
        assert res.levels.shape == (4, 16, 3, 16)
        assert res.iters_run == CFG.default_iters  # fixed route stamp
        assert res.bucket == 4 and res.latency_s > 0

    def test_pad_rows_never_reach_valid_outputs(self, engine, params):
        """Rows are independent through the forward: the valid rows of a
        padded bucket equal the same images served alone."""
        rng = np.random.default_rng(3)
        two = rng.normal(size=(2, 3, 8, 8)).astype(np.float32)
        padded = np.zeros((4, 3, 8, 8), np.float32)
        padded[:2] = two
        got = np.asarray(engine.infer(padded, n_valid=2).levels[:2])
        alone = np.asarray(engine.infer(two, n_valid=2).levels)
        np.testing.assert_allclose(got, alone, rtol=1e-5, atol=1e-6)

    def test_infer_rejects_non_bucket_shapes(self, engine):
        imgs = np.zeros((3, 3, 8, 8), np.float32)
        with pytest.raises(ValueError, match="bucket"):
            engine.infer(imgs)
        with pytest.raises(ValueError, match="n_valid"):
            engine.infer(np.zeros((2, 3, 8, 8), np.float32), n_valid=3)

    def test_stats_records_lint(self, engine):
        recs = engine.stats_records()
        assert recs, "warmup/infer must have produced per-bucket stats"
        for r in recs:
            assert r["kind"] == "serve"
            assert schema.validate_record(r) == [], r

    @pytest.mark.slow  # compiles its own auto-route engine; CI runs it
    def test_auto_route_engine_exits_early_on_converged_input(self, params):
        """End-to-end: an engine on the auto route serves a converged
        batch in fewer iterations than the budget, and the count lands on
        the result."""
        scfg = ServeConfig(
            buckets=(2,), max_batch=2, iters="auto",
            exit_threshold=0.25, min_iters=1, max_auto_iters=10,
        )
        eng = InferenceEngine(CFG, scfg, params=params)
        # A constant image collapses to one island almost immediately —
        # the cheapest converged input there is.
        imgs = np.ones((2, 3, 8, 8), np.float32)
        res = eng.infer(imgs)
        assert res.iters_run < 10


# ---------------------------------------------------------------------------
# batcher (host-side: fake engine, no device)
# ---------------------------------------------------------------------------


class FakeEngine:
    """Engine-shaped policy probe: records every dispatch, returns
    zero-levels instantly."""

    def __init__(self, buckets=(1, 2, 4), latency_s=0.0, fail=None):
        self.scfg = ServeConfig(
            buckets=buckets, max_batch=max(buckets), max_delay_ms=5.0,
            queue_depth=8,
        )
        self.latency_s = latency_s
        self.fail = fail
        self.calls = []

    def pick_bucket(self, n):
        for b in self.scfg.buckets:
            if n <= b:
                return b
        raise ValueError(f"n={n} exceeds the largest bucket")

    def infer(self, imgs, n_valid=None):
        if self.fail is not None:
            raise self.fail
        b = imgs.shape[0]
        self.calls.append((b, n_valid))
        if self.latency_s:
            time.sleep(self.latency_s)
        return ServeResult(
            levels=np.zeros((b, 16, 3, 16), np.float32),
            iters_run=6,
            latency_s=self.latency_s,
            bucket=b,
            compiled=False,
        )


class DownWatchdog:
    def record(self):
        return {"backend_state": "down", "backend_devices": None,
                "backend_transitions": 1}


IMG = np.zeros((3, 8, 8), np.float32)


class TestDynamicBatcher:
    def test_queue_bound_sheds_with_backpressure(self):
        eng = FakeEngine()
        sink = Sink()
        b = DynamicBatcher(eng, queue_depth=2, writer=sink)  # NOT started
        b.submit(IMG)
        b.submit(IMG)
        with pytest.raises(QueueFullError):
            b.submit(IMG)
        assert b.n_shed == 1
        shed = [r for r in sink.records if r.get("event") == "shed"]
        assert shed and shed[0]["reason"] == "queue-full"
        assert schema.validate_record(shed[0]) == []
        b.stop(drain=False)

    def test_full_batch_dispatches_at_max_batch(self):
        eng = FakeEngine(buckets=(1, 2, 4))
        b = DynamicBatcher(eng, max_batch=4, max_delay_ms=10_000.0)
        tickets = [b.submit(IMG) for _ in range(4)]
        b.start()
        for t in tickets:
            levels, iters_run, latency = t.result(timeout=10.0)
            assert levels.shape == (16, 3, 16) and iters_run == 6
        b.stop()
        # One dispatch, gathered to the full batch, no padding.
        assert eng.calls == [(4, 4)]

    def test_max_delay_flushes_a_partial_batch(self):
        """The latency floor: 2 waiting requests must not wait forever for
        2 more — the oldest request's age bounds the gather."""
        eng = FakeEngine(buckets=(1, 2, 4))
        with DynamicBatcher(eng, max_batch=4, max_delay_ms=30.0) as b:
            t1 = b.submit(IMG)
            t2 = b.submit(IMG)
            t1.result(timeout=10.0)
            t2.result(timeout=10.0)
        # Padded up to bucket 2 with both rows valid.
        assert eng.calls == [(2, 2)]

    def test_bucket_selection_pads_to_smallest_admitting(self):
        eng = FakeEngine(buckets=(1, 2, 4))
        with DynamicBatcher(eng, max_batch=3, max_delay_ms=10_000.0) as b:
            tickets = [b.submit(IMG) for _ in range(3)]
            for t in tickets:
                t.result(timeout=10.0)
        assert eng.calls == [(4, 3)]  # 3 valid rows ride the 4-bucket

    def test_shed_on_backend_down_fails_fast_with_error_record(self):
        from glom_tpu.telemetry.watchdog import set_global_watchdog

        eng = FakeEngine()
        sink = Sink()
        set_global_watchdog(DownWatchdog())
        try:
            b = DynamicBatcher(eng, writer=sink)
            t0 = time.perf_counter()
            with pytest.raises(BackendDownError):
                b.submit(IMG)
            assert time.perf_counter() - t0 < 1.0  # fast-fail, not a hang
        finally:
            set_global_watchdog(None)
        errs = [r for r in sink.records if r.get("kind") == "error"]
        assert errs and errs[0]["error"] == "backend-down"
        assert errs[0].get("value") is None  # UNMEASURED, never a zero
        assert schema.validate_record(errs[0]) == []
        assert not eng.calls  # nothing was dispatched into a dead backend

    def test_gathered_batch_sheds_when_backend_dies_before_dispatch(self):
        from glom_tpu.telemetry.watchdog import set_global_watchdog

        eng = FakeEngine()
        sink = Sink()
        b = DynamicBatcher(eng, writer=sink)  # not started: requests queue
        tickets = [b.submit(IMG), b.submit(IMG)]
        set_global_watchdog(DownWatchdog())
        try:
            b.start()
            for t in tickets:
                with pytest.raises(BackendDownError):
                    t.result(timeout=10.0)
        finally:
            set_global_watchdog(None)
            b.stop(drain=False)
        assert not eng.calls

    def test_dispatch_error_fails_only_that_batch(self):
        eng = FakeEngine(fail=RuntimeError("XLA boom"))
        sink = Sink()
        with DynamicBatcher(eng, max_batch=2, max_delay_ms=10.0,
                            writer=sink) as b:
            t = b.submit(IMG)
            with pytest.raises(RuntimeError, match="XLA boom"):
                t.result(timeout=10.0)
            # The worker survives: a later healthy dispatch still serves.
            eng.fail = None
            t2 = b.submit(IMG)
            t2.result(timeout=10.0)
        assert [r.get("event") for r in sink.records].count("dispatch_error") == 1

    def test_dispatch_records_and_summary_lint(self):
        eng = FakeEngine()
        sink = Sink()
        with DynamicBatcher(eng, max_batch=2, max_delay_ms=10.0,
                            writer=sink) as b:
            for t in [b.submit(IMG) for _ in range(4)]:
                t.result(timeout=10.0)
            summary = b.summary_record()
        for r in sink.records + [summary]:
            assert schema.validate_record(r) == [], r
        dispatches = [r for r in sink.records if r.get("event") == "dispatch"]
        assert dispatches
        for d in dispatches:
            assert 0.0 <= d["pad_fraction"] < 1.0
            assert d["iters_run"] == 6
        assert summary["n_served"] == 4
        assert summary["iters_histogram"] == {"6": 4}

    def test_span_rollups_cover_the_serve_phases(self):
        eng = FakeEngine()
        with DynamicBatcher(eng, max_batch=2, max_delay_ms=10.0) as b:
            for t in [b.submit(IMG) for _ in range(2)]:
                t.result(timeout=10.0)
            recs = b.span_records()
        names = {r["name"] for r in recs}
        assert "serve_enqueue" in names and "serve_dispatch" in names
        for r in recs:
            assert r["kind"] == "span"
            assert schema.validate_record(r) == [], r

    def test_ticket_timeout(self):
        eng = FakeEngine()
        b = DynamicBatcher(eng)  # never started: the ticket cannot resolve
        t = b.submit(IMG)
        with pytest.raises(TimeoutError):
            t.result(timeout=0.05)
        b.stop(drain=False)


class TestServeConfig:
    def test_bucket_validation(self):
        with pytest.raises(ValueError, match="ascending"):
            ServeConfig(buckets=(4, 2))
        with pytest.raises(ValueError, match="non-empty"):
            ServeConfig(buckets=())
        with pytest.raises(ValueError, match="max_batch"):
            ServeConfig(buckets=(1, 2), max_batch=4)
        with pytest.raises(ValueError, match="iters"):
            ServeConfig(iters="sometimes")
        with pytest.raises(ValueError, match="iters"):
            ServeConfig(iters=0)

    def test_presets_carry_serve_configs(self):
        from glom_tpu.utils.presets import get_preset

        assert get_preset("mnist").serve.buckets == (1, 2, 4, 8)
        flagship = get_preset("imagenet224-dp8").serve
        assert flagship.iters == "auto" and flagship.use_pallas


@pytest.mark.slow
class TestServeCli:
    def test_synthetic_run_emits_lintable_records(self, tmp_path):
        from glom_tpu.serve.cli import main
        from glom_tpu.telemetry.schema import lint_stream

        out = tmp_path / "serve.jsonl"
        rc = main([
            "--preset", "mnist", "--synthetic", "3",
            "--buckets", "1,2", "--max-batch", "2",
            "--iters", "auto", "--out", str(out),
        ])
        assert rc == 0
        lines = out.read_text().splitlines()
        assert lint_stream(lines) == []
        import json

        recs = [json.loads(l) for l in lines]
        responses = [
            r for r in recs
            if r.get("kind") == "serve" and r.get("event") == "response"
        ]
        assert len(responses) == 3 and all(r["ok"] for r in responses)
        assert any(r.get("event") == "summary" for r in recs)
        assert any(r.get("event") == "warmup" for r in recs)
