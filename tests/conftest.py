"""Test harness: force an 8-device virtual CPU platform BEFORE jax imports.

This is the JAX-native analog of a fake/mock distributed backend: every
pjit/shard_map/ring-collective test runs multi-device on CPU without TPU
hardware (SURVEY.md §4d). Must run before any test module imports jax.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
# PIN the 8-device virtual platform unconditionally — replacing any
# pre-existing xla_force_host_platform_device_count, not just appending
# when absent: an inherited =1 from the environment would silently turn
# every multi-device test into a skip/failure on a fresh checkout.
_flags = [
    f
    for f in os.environ.get("XLA_FLAGS", "").split()
    if "xla_force_host_platform_device_count" not in f
]
os.environ["XLA_FLAGS"] = " ".join(
    _flags + ["--xla_force_host_platform_device_count=8"]
)
# Determinism and precision: CPU tests compare against a float64 numpy oracle.
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

# A sitecustomize hook in this image may have pre-registered a TPU backend and
# overridden jax_platforms before conftest ran; force CPU at the config level.
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)
