"""Perfetto export (glom_tpu/telemetry/perfetto.py): span/flight JSONL ->
Chrome/Perfetto trace-event JSON. Pure host-side, no jax."""

import json


from glom_tpu.telemetry import schema
from glom_tpu.telemetry.perfetto import (
    convert_lines,
    main,
    to_trace_events,
)

FIXTURE = "tests/fixtures/bench_base.jsonl"


def span_rec(name, t_start, dur_s, depth=0, **extra):
    return schema.stamp(
        {"name": name, "t_start": t_start, "dur_s": dur_s, "depth": depth,
         **extra},
        kind="span",
    )


class TestToTraceEvents:
    def test_timed_spans_become_complete_events(self):
        evs = to_trace_events([
            span_rec("host_data_next", 100.0, 0.5),
            span_rec("host_step_dispatch", 100.5, 1.0, depth=1),
        ])
        assert [e["ph"] for e in evs] == ["X", "X"]
        first = evs[0]
        assert first["name"] == "host_data_next"
        assert first["ts"] == 0.0  # normalized to start at zero
        assert first["dur"] == 0.5e6  # microseconds
        assert evs[1]["ts"] == 0.5e6
        assert evs[1]["tid"] != first["tid"]  # depth separates tracks

    def test_rollup_spans_become_counters(self):
        rollup = schema.stamp(
            {"name": "serve_dispatch", "dur_s": 0.25, "count": 10},
            kind="span",
        )
        evs = to_trace_events([rollup])
        assert evs[0]["ph"] == "C"
        assert evs[0]["name"] == "phase:serve_dispatch"
        assert evs[0]["args"] == {"dur_s": 0.25}

    def test_watchdog_becomes_named_instant(self):
        wd = schema.stamp(
            {"t": 12.0, "event": "backend_transition", "prev_state": "up",
             "backend_state": "down", "backend_devices": None,
             "transitions": 2},
            kind="watchdog",
        )
        evs = to_trace_events([wd])
        assert evs[0]["ph"] == "i"
        assert evs[0]["name"] == "backend:down"

    def test_other_kinds_become_instants_sorted_by_ts(self):
        recs = [
            schema.stamp({"step": 10, "loss": 0.5, "wall_time": 2.0},
                         kind="train_step"),
            schema.stamp({"step": 5, "loss": 0.9, "wall_time": 1.0},
                         kind="train_step"),
            schema.stamp({"note": "hello"}, kind="note"),
        ]
        evs = to_trace_events(recs)
        assert len(evs) == 3
        assert evs == sorted(evs, key=lambda e: e["ts"])
        names = {e["name"] for e in evs}
        assert "step 10" in names and "step 5" in names

    def test_mixed_epoch_and_relative_clocks_normalize_separately(self):
        """A stream mixing epoch t_start spans with run-relative wall_time
        records must not render 50 years wide."""
        evs = to_trace_events([
            span_rec("a", 1.7e9, 0.1),  # epoch clock
            schema.stamp({"step": 1, "loss": 1.0, "wall_time": 3.0},
                         kind="train_step"),  # run-relative
        ])
        assert all(0 <= e["ts"] < 60e6 for e in evs), evs

    def test_clockless_records_keep_order(self):
        recs = [
            schema.stamp({"metric": f"m{i}", "value": 1.0, "unit": "x"},
                         kind="bench")
            for i in range(3)
        ]
        evs = to_trace_events(recs)
        assert [e["name"] for e in evs] == ["m0", "m1", "m2"]

    def test_empty_input(self):
        assert to_trace_events([]) == []


class TestConvertAndCli:
    def test_existing_fixture_converts(self):
        """The committed bench fixtures are a real artifact of record: the
        converter must map every row (incl. the UNMEASURED error row in
        bench_new) to a trace event."""
        with open(FIXTURE) as fh:
            trace = convert_lines(fh)
        assert trace["displayTimeUnit"] == "ms"
        assert len(trace["traceEvents"]) == 4  # 4 bench rows
        assert all(e["ph"] == "i" for e in trace["traceEvents"])
        with open("tests/fixtures/bench_new.jsonl") as fh:
            trace2 = convert_lines(fh)
        names = [e["name"] for e in trace2["traceEvents"]]
        assert any(n.startswith("error:") for n in names)
        # The whole object must be JSON-serializable (Perfetto loads it).
        json.dumps(trace2)

    def test_cli_writes_trace_file(self, tmp_path, capsys):
        src = tmp_path / "spans.jsonl"
        with open(src, "w") as fh:
            for i in range(3):
                fh.write(json.dumps(span_rec("phase", 10.0 + i, 0.5)) + "\n")
            fh.write("shell noise to be skipped\n")
        out = tmp_path / "trace.json"
        assert main([str(src), "-o", str(out)]) == 0
        trace = json.loads(out.read_text())
        assert len(trace["traceEvents"]) == 3
        assert trace["metadata"]["inputs"] == [str(src)]

    def test_cli_default_output_path(self, tmp_path):
        src = tmp_path / "flight.jsonl"
        src.write_text(json.dumps(span_rec("x", 1.0, 0.1)) + "\n")
        assert main([str(src)]) == 0
        assert (tmp_path / "flight.jsonl.perfetto.json").exists()

    def test_cli_fails_on_empty_input(self, tmp_path):
        src = tmp_path / "empty.log"
        src.write_text("no json here\n")
        assert main([str(src)]) == 1

    def test_cli_merges_multiple_inputs(self, tmp_path):
        a = tmp_path / "a.jsonl"
        b = tmp_path / "b.jsonl"
        a.write_text(json.dumps(span_rec("a", 1.0, 0.1)) + "\n")
        b.write_text(json.dumps(span_rec("b", 2.0, 0.1)) + "\n")
        out = tmp_path / "merged.json"
        assert main([str(a), str(b), "-o", str(out)]) == 0
        trace = json.loads(out.read_text())
        assert len(trace["traceEvents"]) == 2


class TestBarrierTracksAndFlows:
    """PR 10: barrier records render as per-host tracks with per-round
    flow arrows (propose -> commit -> saved -> complete), and v6
    trace-context serve records chain into per-request flows."""

    def barrier(self, phase, host, i, rnd="r1"):
        return schema.stamp(
            {"phase": phase, "round": rnd, "host": host, "step": 3,
             "wall_time_s": 1.7e9 + i},
            kind="barrier",
        )

    def test_barrier_records_land_on_per_host_tracks(self):
        recs = [
            self.barrier(p, h, i + h * 0.1)
            for i, p in enumerate(("propose", "commit", "saved", "complete"))
            for h in (0, 1)
        ]
        evs = to_trace_events(recs)
        instants = [e for e in evs if e["ph"] == "i"]
        assert {e["tid"] for e in instants} == {100, 101}
        meta = [e for e in evs if e["ph"] == "M"]
        assert {e["args"]["name"] for e in meta} == {
            "barrier host 0", "barrier host 1"
        }

    def test_barrier_round_chains_as_flow_arrows(self):
        recs = [
            self.barrier(p, 0, i)
            for i, p in enumerate(("propose", "commit", "saved", "complete"))
        ]
        evs = to_trace_events(recs)
        flows = [e for e in evs if e.get("cat") == "barrier"]
        assert [e["ph"] for e in flows] == ["s", "t", "t", "t"]
        assert {e["id"] for e in flows} == {"barrier:r1"}
        # Arrows follow time: the flow steps are ts-ordered.
        ts = [e["ts"] for e in flows]
        assert ts == sorted(ts)

    def test_hostless_barrier_falls_back_to_the_events_track(self):
        rec = schema.stamp(
            {"phase": "arrive", "round": "g0", "wall_time_s": 1.7e9},
            kind="barrier",
        )
        (ev,) = [e for e in to_trace_events([rec]) if e["ph"] == "i"]
        assert ev["tid"] == 90  # _TID_EVENTS

    def test_trace_context_serve_records_flow_link(self):
        recs = [
            schema.stamp(
                {"event": "dispatch", "engine": "e0", "latency_ms": 1.0,
                 "trace_ids": ["abc12345ff", "zzz"], "span_id": "d1",
                 "wall_time_s": 1.7e9 + 1},
                kind="serve",
            ),
            schema.stamp(
                {"event": "resolve", "iters_total": 6,
                 "trace_id": "abc12345ff", "wall_time_s": 1.7e9 + 2},
                kind="serve",
            ),
        ]
        evs = to_trace_events(recs)
        abc = [e for e in evs if e.get("id") == "trace:abc12345ff"]
        assert [e["ph"] for e in abc] == ["s", "f"]  # start -> finish
        assert abc[0]["name"] == "trace:abc12345"
        zzz = [e for e in evs if e.get("id") == "trace:zzz"]
        assert [e["ph"] for e in zzz] == ["s"]

    def test_untraced_serve_records_emit_no_flows(self):
        rec = schema.stamp(
            {"event": "dispatch", "engine": "e0", "trace_ids": None,
             "wall_time_s": 1.7e9},
            kind="serve",
        )
        assert not [e for e in to_trace_events([rec]) if "id" in e]

    def test_whole_trace_object_stays_serializable(self):
        recs = [
            self.barrier("propose", 0, 0),
            schema.stamp(
                {"event": "resolve", "trace_id": "t1", "iters_total": 4,
                 "wall_time_s": 1.7e9 + 5},
                kind="serve",
            ),
        ]
        json.dumps({"traceEvents": to_trace_events(recs)})

    def test_flow_finishes_exactly_once_across_resolve_and_response(self):
        """A traced CLI stream carries BOTH leaves per request (the
        batcher's resolve, then the CLI's response): the flow must emit
        one "s" and ONE "f" — a second finish on a terminated id is
        dropped by the importer."""
        mk = lambda ev, t: schema.stamp(
            {"event": ev, "trace_id": "abc", "latency_ms": 1.0,
             "wall_time_s": 1.7e9 + t},
            kind="serve",
        )
        evs = to_trace_events([
            schema.stamp(
                {"event": "dispatch", "trace_ids": ["abc"],
                 "latency_ms": 1.0, "wall_time_s": 1.7e9},
                kind="serve",
            ),
            mk("resolve", 1), mk("response", 2),
        ])
        flows = [e for e in evs if e.get("id") == "trace:abc"]
        assert [e["ph"] for e in flows] == ["s", "t", "f"] or \
            [e["ph"] for e in flows] == ["s", "f"], flows
        assert [e["ph"] for e in flows].count("f") == 1

    def test_flows_are_causal_under_the_batcher_emit_order(self):
        """The batcher stamps a hop's resolve leaf BEFORE the hop's own
        dispatch record, and the dispatch record's clock reads LATER —
        both stream order and raw ts order would start the flow at the
        leaf (never closing it) or close it early and drop the final
        hop. The flow must still read hop(s) -> leaf: "s" on the
        dispatch, "f" on the resolve, ts monotone."""
        resolve = schema.stamp(
            {"event": "resolve", "trace_id": "abc", "iters_total": 6,
             "latency_ms": 4.0, "wall_time": 5.995},
            kind="serve",
        )
        response = schema.stamp(
            {"event": "response", "trace_id": "abc", "ok": True,
             "latency_ms": 4.0, "wall_time": 5.995},
            kind="serve",
        )
        dispatch = schema.stamp(
            {"event": "dispatch", "trace_ids": ["abc"], "latency_ms": 4.0,
             "wall_time": 5.999},
            kind="serve",
        )
        # The real stream order: resolve, response, then the dispatch.
        evs = to_trace_events([resolve, response, dispatch])
        flows = [e for e in evs if e.get("id") == "trace:abc"]
        assert [e["ph"] for e in flows] == ["s", "f"], flows
        ts = [e["ts"] for e in flows]
        assert ts == sorted(ts), flows
        # The "s" sits on the dispatch hop's instant, not the leaf's.
        (disp,) = [e for e in evs if e.get("name") == "serve:dispatch"]
        assert flows[0]["ts"] == disp["ts"]

    def test_multi_hop_flow_keeps_every_hop_before_the_leaf(self):
        """A straggler's final hop is stamped after the resolve in
        stream order; it must still flow-link as a hop, not be dropped
        by an already-closed flow."""
        hop = lambda t: schema.stamp(
            {"event": "dispatch", "trace_ids": ["abc"], "latency_ms": 1.0,
             "wall_time": t},
            kind="serve",
        )
        resolve = schema.stamp(
            {"event": "resolve", "trace_id": "abc", "iters_total": 9,
             "latency_ms": 3.0, "wall_time": 7.0},
            kind="serve",
        )
        evs = to_trace_events([hop(1.0), hop(4.0), resolve, hop(7.1)])
        flows = [e for e in evs if e.get("id") == "trace:abc"]
        assert [e["ph"] for e in flows] == ["s", "t", "t", "f"], flows
        ts = [e["ts"] for e in flows]
        assert ts == sorted(ts), flows


class TestCapacityObservatoryTracks:
    """ISSUE 13: collective_time records render as per-(site, axis)
    counter tracks, capacity records as per-engine headroom counters,
    and dispatch records with a phase split as NESTED slices — one
    trace shows queue->pack->h2d->device->resolve end to end."""

    def test_collective_time_counter_per_site_axis(self):
        evs = to_trace_events([
            schema.stamp(
                {"site": "witness_cos_psum", "axis": "seq",
                 "collective": "psum", "wall_ms": 1.25, "wall_time": 1.0},
                kind="collective_time",
            ),
            schema.stamp(
                {"site": "zero_all_gather", "axis": "data",
                 "collective": "all_gather", "wall_ms": 2.5,
                 "wall_time": 2.0},
                kind="collective_time",
            ),
        ])
        counters = [e for e in evs if e["ph"] == "C"]
        assert {e["name"] for e in counters} == {
            "collective:witness_cos_psum@seq",
            "collective:zero_all_gather@data",
        }
        assert counters[0]["args"]["wall_ms"] == 1.25

    def test_capacity_headroom_counter_per_engine(self):
        evs = to_trace_events([
            schema.stamp(
                {"engine": "engine0", "headroom": 0.7, "wall_time": 1.0},
                kind="capacity",
            ),
        ])
        (c,) = [e for e in evs if e["ph"] == "C"]
        assert c["name"] == "headroom:engine0"
        assert c["args"]["headroom"] == 0.7

    def test_scale_events_render_global_instants_and_fleet_counter(self):
        """Elastic transitions (schema v8) are FULL-HEIGHT instants and
        every n_engines-carrying record samples the fleet counter —
        capacity following load, drawn."""
        evs = to_trace_events([
            schema.stamp(
                {"event": "scale_out_decision", "decision_id": 1,
                 "n_engines": 1, "wall_time": 1.0},
                kind="serve",
            ),
            schema.stamp(
                {"event": "scale_out", "decision_id": 1,
                 "engine": "engine1", "n_engines": 2, "spawn_ms": 900.0,
                 "wall_time": 2.0},
                kind="serve",
            ),
            schema.stamp(
                {"event": "drain_release", "decision_id": 2,
                 "engine": "engine1", "n_engines": 1, "wall_time": 3.0},
                kind="serve",
            ),
        ])
        instants = [
            e for e in evs if e["ph"] == "i" and e.get("s") == "g"
        ]
        assert {e["name"] for e in instants} == {
            "elastic:scale_out_decision", "elastic:scale_out",
            "elastic:drain_release",
        }
        fleet = [e for e in evs if e["ph"] == "C"
                 and e["name"] == "fleet:n_engines"]
        assert [e["args"]["n_engines"] for e in fleet] == [1.0, 2.0, 1.0]
        assert len({e["tid"] for e in fleet}) == 1

    def test_dispatch_phase_split_renders_nested_slices(self):
        rec = schema.stamp(
            {"event": "dispatch", "engine": "engine0", "bucket": 2,
             "n_valid": 2, "latency_ms": 10.0, "queue_wait_ms": 4.0,
             "pack_ms": 1.0, "h2d_ms": 0.5, "device_ms": 4.0,
             "resolve_ms": 0.5, "iters_run": 6, "trace_ids": None,
             "wall_time": 5.0},
            kind="serve",
        )
        evs = to_trace_events([rec])
        slices = [e for e in evs if e["ph"] == "X"]
        parent = [e for e in slices if e["name"].startswith("dispatch:")]
        phases = [e for e in slices if not e["name"].startswith("dispatch")]
        assert len(parent) == 1 and len(phases) == 5
        (p,) = parent
        assert p["dur"] == 10.0 * 1e3  # ms -> us
        assert [e["name"] for e in sorted(phases, key=lambda e: e["ts"])] \
            == ["queue_wait", "pack", "h2d", "device", "resolve"]
        # The phases tile the parent slice exactly.
        assert sum(e["dur"] for e in phases) == p["dur"]
        first = min(phases, key=lambda e: e["ts"])
        assert first["ts"] == p["ts"]
        # The dispatch instant (trace-flow anchor) still renders.
        assert any(
            e["ph"] == "i" and e["name"] == "serve:dispatch" for e in evs
        )

    def test_null_phases_render_no_slices(self):
        rec = schema.stamp(
            {"event": "dispatch", "engine": "engine0", "bucket": 2,
             "n_valid": 2, "latency_ms": 10.0, "queue_wait_ms": None,
             "pack_ms": None, "h2d_ms": None, "device_ms": None,
             "resolve_ms": None, "trace_ids": None, "wall_time": 5.0},
            kind="serve",
        )
        evs = to_trace_events([rec])
        assert [e for e in evs if e["ph"] == "X"] == []
