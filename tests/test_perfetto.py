"""Perfetto export (glom_tpu/telemetry/perfetto.py): span/flight JSONL ->
Chrome/Perfetto trace-event JSON. Pure host-side, no jax."""

import json


from glom_tpu.telemetry import schema
from glom_tpu.telemetry.perfetto import (
    convert_lines,
    main,
    to_trace_events,
)

FIXTURE = "tests/fixtures/bench_base.jsonl"


def span_rec(name, t_start, dur_s, depth=0, **extra):
    return schema.stamp(
        {"name": name, "t_start": t_start, "dur_s": dur_s, "depth": depth,
         **extra},
        kind="span",
    )


class TestToTraceEvents:
    def test_timed_spans_become_complete_events(self):
        evs = to_trace_events([
            span_rec("host_data_next", 100.0, 0.5),
            span_rec("host_step_dispatch", 100.5, 1.0, depth=1),
        ])
        assert [e["ph"] for e in evs] == ["X", "X"]
        first = evs[0]
        assert first["name"] == "host_data_next"
        assert first["ts"] == 0.0  # normalized to start at zero
        assert first["dur"] == 0.5e6  # microseconds
        assert evs[1]["ts"] == 0.5e6
        assert evs[1]["tid"] != first["tid"]  # depth separates tracks

    def test_rollup_spans_become_counters(self):
        rollup = schema.stamp(
            {"name": "serve_dispatch", "dur_s": 0.25, "count": 10},
            kind="span",
        )
        evs = to_trace_events([rollup])
        assert evs[0]["ph"] == "C"
        assert evs[0]["name"] == "phase:serve_dispatch"
        assert evs[0]["args"] == {"dur_s": 0.25}

    def test_watchdog_becomes_named_instant(self):
        wd = schema.stamp(
            {"t": 12.0, "event": "backend_transition", "prev_state": "up",
             "backend_state": "down", "backend_devices": None,
             "transitions": 2},
            kind="watchdog",
        )
        evs = to_trace_events([wd])
        assert evs[0]["ph"] == "i"
        assert evs[0]["name"] == "backend:down"

    def test_other_kinds_become_instants_sorted_by_ts(self):
        recs = [
            schema.stamp({"step": 10, "loss": 0.5, "wall_time": 2.0},
                         kind="train_step"),
            schema.stamp({"step": 5, "loss": 0.9, "wall_time": 1.0},
                         kind="train_step"),
            schema.stamp({"note": "hello"}, kind="note"),
        ]
        evs = to_trace_events(recs)
        assert len(evs) == 3
        assert evs == sorted(evs, key=lambda e: e["ts"])
        names = {e["name"] for e in evs}
        assert "step 10" in names and "step 5" in names

    def test_mixed_epoch_and_relative_clocks_normalize_separately(self):
        """A stream mixing epoch t_start spans with run-relative wall_time
        records must not render 50 years wide."""
        evs = to_trace_events([
            span_rec("a", 1.7e9, 0.1),  # epoch clock
            schema.stamp({"step": 1, "loss": 1.0, "wall_time": 3.0},
                         kind="train_step"),  # run-relative
        ])
        assert all(0 <= e["ts"] < 60e6 for e in evs), evs

    def test_clockless_records_keep_order(self):
        recs = [
            schema.stamp({"metric": f"m{i}", "value": 1.0, "unit": "x"},
                         kind="bench")
            for i in range(3)
        ]
        evs = to_trace_events(recs)
        assert [e["name"] for e in evs] == ["m0", "m1", "m2"]

    def test_empty_input(self):
        assert to_trace_events([]) == []


class TestConvertAndCli:
    def test_existing_fixture_converts(self):
        """The committed bench fixtures are a real artifact of record: the
        converter must map every row (incl. the UNMEASURED error row in
        bench_new) to a trace event."""
        with open(FIXTURE) as fh:
            trace = convert_lines(fh)
        assert trace["displayTimeUnit"] == "ms"
        assert len(trace["traceEvents"]) == 4  # 4 bench rows
        assert all(e["ph"] == "i" for e in trace["traceEvents"])
        with open("tests/fixtures/bench_new.jsonl") as fh:
            trace2 = convert_lines(fh)
        names = [e["name"] for e in trace2["traceEvents"]]
        assert any(n.startswith("error:") for n in names)
        # The whole object must be JSON-serializable (Perfetto loads it).
        json.dumps(trace2)

    def test_cli_writes_trace_file(self, tmp_path, capsys):
        src = tmp_path / "spans.jsonl"
        with open(src, "w") as fh:
            for i in range(3):
                fh.write(json.dumps(span_rec("phase", 10.0 + i, 0.5)) + "\n")
            fh.write("shell noise to be skipped\n")
        out = tmp_path / "trace.json"
        assert main([str(src), "-o", str(out)]) == 0
        trace = json.loads(out.read_text())
        assert len(trace["traceEvents"]) == 3
        assert trace["metadata"]["inputs"] == [str(src)]

    def test_cli_default_output_path(self, tmp_path):
        src = tmp_path / "flight.jsonl"
        src.write_text(json.dumps(span_rec("x", 1.0, 0.1)) + "\n")
        assert main([str(src)]) == 0
        assert (tmp_path / "flight.jsonl.perfetto.json").exists()

    def test_cli_fails_on_empty_input(self, tmp_path):
        src = tmp_path / "empty.log"
        src.write_text("no json here\n")
        assert main([str(src)]) == 1

    def test_cli_merges_multiple_inputs(self, tmp_path):
        a = tmp_path / "a.jsonl"
        b = tmp_path / "b.jsonl"
        a.write_text(json.dumps(span_rec("a", 1.0, 0.1)) + "\n")
        b.write_text(json.dumps(span_rec("b", 2.0, 0.1)) + "\n")
        out = tmp_path / "merged.json"
        assert main([str(a), str(b), "-o", str(out)]) == 0
        trace = json.loads(out.read_text())
        assert len(trace["traceEvents"]) == 2
