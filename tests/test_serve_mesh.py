"""Sharded serving (parallel/serve_mesh.py + the engine's mesh route) on
the 8-device virtual CPU mesh (tests/conftest.py pins XLA_FLAGS).

The acceptance locks:
  * data-sharded (8x1) threshold-0 auto route is BITWISE the
    single-device engine's output (same per-row program, different
    placement — the serving analog of tests/test_manual.py's parity);
  * the (data x seq) mesh with the decomposed witness matches to fp32
    reduction tolerance, and its while-loop witness collectives are
    counted (wire bytes on the signature's stats record);
  * bucket/mesh divisibility is validated loudly, never silently padded.

Every test here compiles shard_map programs — all slow-marked (the CI
serve job runs this module unfiltered; tier-1 keeps its budget).
"""

import jax
import numpy as np
import pytest

from glom_tpu.models.core import init_glom
from glom_tpu.serve.engine import InferenceEngine
from glom_tpu.utils.config import GlomConfig, ServeConfig

pytestmark = pytest.mark.slow

CFG = GlomConfig(dim=16, levels=3, image_size=8, patch_size=2)  # n=16


@pytest.fixture(scope="module")
def params():
    return init_glom(jax.random.PRNGKey(1), CFG)


@pytest.fixture(scope="module")
def imgs8():
    return np.random.default_rng(3).normal(size=(8, 3, 8, 8)).astype(
        np.float32
    )


def _pair(params, mesh_data, mesh_seq, **kw):
    base = dict(
        buckets=(8,), max_batch=8, iters="auto", exit_threshold=0.0,
        max_auto_iters=6,
    )
    base.update(kw)
    sharded = InferenceEngine(
        CFG,
        ServeConfig(**base, mesh_data=mesh_data, mesh_seq=mesh_seq),
        params=params,
    )
    single = InferenceEngine(CFG, ServeConfig(**base), params=params)
    return sharded, single


class TestShardedParity:
    def test_data_sharded_threshold_zero_is_bitwise_single_device(
        self, params, imgs8
    ):
        """8-way batch sharding, seq=1: the per-shard body is the exact
        single-device program per row — threshold-0 outputs must be
        BITWISE equal, including the pad-row discipline (n_valid=6)."""
        sharded, single = _pair(params, 8, 1)
        a = sharded.infer(imgs8, n_valid=6)
        b = single.infer(imgs8, n_valid=6)
        assert a.iters_run == b.iters_run == 6
        assert np.array_equal(np.asarray(a.levels), np.asarray(b.levels))
        assert np.array_equal(a.row_converged, b.row_converged)

    def test_data_seq_mesh_matches_single_device(self, params, imgs8):
        """(4 x 2): the seq-sharded band compute + decomposed witness
        reproduce the single-device route to fp32 reduction tolerance,
        and the early-exit trip counts agree at a live threshold."""
        sharded, single = _pair(
            params, 4, 2, exit_threshold=1e-3, max_auto_iters=12,
        )
        a = sharded.infer(imgs8)
        b = single.infer(imgs8)
        assert a.iters_run == b.iters_run
        np.testing.assert_allclose(
            np.asarray(a.levels), np.asarray(b.levels), rtol=1e-5,
            atol=1e-5,
        )
        assert np.array_equal(a.row_converged, b.row_converged)

    def test_fixed_route_sharded_matches_single_device(self, params, imgs8):
        sharded, single = _pair(params, 8, 1, iters=5)
        a = sharded.infer(imgs8)
        b = single.infer(imgs8)
        assert a.iters_run == b.iters_run == 5
        assert np.array_equal(np.asarray(a.levels), np.asarray(b.levels))
        assert a.row_converged.all()  # fixed route: converged by fiat

    def test_warm_continuation_route_compiles_and_matches(self, params, imgs8):
        """Warm (levels0-carrying) sharded signature: continuing a
        threshold-0 run for 3 more iterations equals one 6-iteration run
        bitwise — the sharded half of the continuation contract."""
        sharded3, _ = _pair(params, 8, 1, max_auto_iters=3)
        first = sharded3.infer(imgs8)
        cont = sharded3.infer(
            imgs8, levels0=np.asarray(first.levels), auto_budget=3,
        )
        sharded6, _ = _pair(params, 8, 1, max_auto_iters=6)
        full = sharded6.infer(imgs8)
        assert first.iters_run == 3 and cont.iters_run == 3
        assert np.array_equal(
            np.asarray(cont.levels), np.asarray(full.levels)
        )


class TestServeMeshPlumbing:
    def test_witness_collectives_are_counted(self, params, imgs8):
        """The sharded signatures' stats records carry the counted wire
        bytes from the lowering trace; a seq>1 mesh moves witness bytes
        every iteration, a data-only mesh just the quorum scalars."""
        sharded, _ = _pair(params, 4, 2, exit_threshold=1e-3)
        sharded.warmup()
        recs = [
            r for r in sharded.stats_records()
            if "comm_measured_bytes_per_step" in r
        ]
        assert recs and all(
            r["comm_measured_bytes_per_step"] > 0 for r in recs
        )

    def test_bucket_not_divisible_by_mesh_data_rejected(self):
        with pytest.raises(ValueError, match="divisible"):
            ServeConfig(buckets=(1, 2, 4), max_batch=4, mesh_data=4)

    def test_patches_not_divisible_by_mesh_seq_rejected(self, params):
        with pytest.raises(ValueError, match="mesh_seq"):
            InferenceEngine(
                CFG,
                ServeConfig(buckets=(8,), max_batch=8, mesh_seq=3),
                params=params,
            )

    def test_make_engine_meshes_partitions_devices(self):
        from glom_tpu.parallel.runtime import make_engine_meshes

        scfg = ServeConfig(buckets=(4,), max_batch=4, mesh_data=2,
                           mesh_seq=2)
        meshes = make_engine_meshes(scfg, 2)
        assert len(meshes) == 2
        d0 = set(meshes[0].devices.flat)
        d1 = set(meshes[1].devices.flat)
        assert len(d0) == len(d1) == 4 and not d0 & d1
        with pytest.raises(ValueError, match="replicas"):
            make_engine_meshes(scfg, 3)  # 8 devices, 4 per replica

    def test_replica_device_groups_validation(self):
        from glom_tpu.parallel.mesh import replica_device_groups

        devs = list(range(8))
        groups = replica_device_groups(devs, 4)
        assert groups == [[0, 1, 2, 3], [4, 5, 6, 7]]
        assert replica_device_groups(devs, 3) == [[0, 1, 2], [3, 4, 5]]
        with pytest.raises(ValueError, match=">= 1"):
            replica_device_groups(devs, 0)
        with pytest.raises(ValueError, match="cannot host"):
            replica_device_groups(devs[:2], 4)


class TestShardedBatcherRide:
    def test_two_tier_over_sharded_engine(self, params):
        """End to end on the mesh: heterogeneous traffic through the
        batcher over a sharded engine — stragglers re-bucket, tickets
        conserve, and the straggler's total matches its solo run."""
        from glom_tpu.serve.batcher import DynamicBatcher

        rng = np.random.default_rng(5)
        easy = [
            rng.normal(size=(3, 8, 8)).astype(np.float32) for _ in range(3)
        ]
        hard = (100.0 * rng.normal(size=(3, 8, 8))).astype(np.float32)
        scfg = ServeConfig(
            buckets=(4,), max_batch=4, max_delay_ms=100.0, iters="auto",
            exit_threshold=1e-3, max_auto_iters=16, exit_quorum=0.5,
            max_continuations=3, mesh_data=4,
        )
        eng = InferenceEngine(CFG, scfg, params=params)
        with DynamicBatcher(eng) as b:
            tickets = [
                b.submit(easy[0]), b.submit(hard), b.submit(easy[1]),
                b.submit(easy[2]),
            ]
            outs = [t.result(timeout=300.0) for t in tickets]
            summary = b.summary_record()
        assert summary["n_served"] == 4 and summary["n_failed"] == 0
        assert summary["n_continued"] >= 1
        # The two-tier win, measured: the easy quorum resolved in fewer
        # executed iters than the straggler's total.
        easy_iters = [outs[i][1] for i in (0, 2, 3)]
        assert max(easy_iters) < outs[1][1]


class TestNeededPagesGather:
    """The needed-pages-only sharded page exchange (ISSUE 12 satellite):
    the paged warm signature must be able to move ONLY the pages the
    dispatch references (a registered psum_scatter of bitcast integers)
    instead of all_gathering the whole pool, with the compile-trace
    counted bytes strictly below the whole-pool bound and the delivered
    pages BITWISE identical."""

    def _engine(self, params, mode, pool_pages=64):
        scfg = ServeConfig(
            buckets=(2, 8), max_batch=8, iters="auto", exit_threshold=0.0,
            max_auto_iters=4, mesh_data=2,
            page_pool_pages=pool_pages, page_tokens=4,
            column_cache_bytes=1 << 20, page_gather=mode,
            dispatch_retries=0,
        )
        return InferenceEngine(
            CFG, scfg, params=params, name=f"eng-{mode}"
        )

    def test_needed_bitwise_and_counted_bytes_below_pool_bound(
        self, params
    ):
        rng = np.random.default_rng(11)
        img = (100.0 * rng.normal(size=(2, 3, 8, 8))).astype(np.float32)
        outs, bytes_counted = {}, {}
        for mode in ("pool", "needed"):
            eng = self._engine(params, mode)
            lv = np.asarray(eng.infer(img, n_valid=2).levels)
            for i, sid in enumerate(("a", "b")):
                assert eng.pool.write_back(sid, lv[i], CFG.num_patches)
            prow = np.stack(
                [eng.pool.lookup("a")[0], eng.pool.lookup("b")[0]]
            ).astype(np.int32)
            res = eng.infer(img, n_valid=2, page_rows=prow)
            sig = eng.signature(2, warm="paged")
            outs[mode] = np.asarray(res.levels)
            bytes_counted[mode] = eng._comm[sig][
                "comm_measured_bytes_per_step"
            ]
        assert np.array_equal(outs["pool"], outs["needed"]), (
            "needed-pages exchange is not bitwise the whole-pool gather"
        )
        assert bytes_counted["needed"] < bytes_counted["pool"], (
            bytes_counted
        )

    def test_auto_picks_needed_for_big_pools(self, params):
        # A 64-page pool vs a 2-row dispatch: "auto" must take the
        # needed-pages route (counted bytes == the needed route's).
        rng = np.random.default_rng(12)
        img = (100.0 * rng.normal(size=(2, 3, 8, 8))).astype(np.float32)
        counted = {}
        for mode in ("auto", "needed", "pool"):
            eng = self._engine(params, mode)
            lv = np.asarray(eng.infer(img, n_valid=2).levels)
            assert eng.pool.write_back("s", lv[0], CFG.num_patches)
            prow = np.stack(
                [eng.pool.lookup("s")[0]] * 2
            ).astype(np.int32)
            eng.infer(img, n_valid=2, page_rows=prow)
            sig = eng.signature(2, warm="paged")
            counted[mode] = eng._comm[sig][
                "comm_measured_bytes_per_step"
            ]
        assert counted["auto"] == counted["needed"] < counted["pool"]
